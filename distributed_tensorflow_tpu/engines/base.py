"""Shared engine machinery: TrainState, loss, eval, batch placement.

Design: every engine is a single jitted SPMD program over a Mesh.  There is
no server process and no wire — where the reference moves pickled gradients
and weights over TCP every batch (reference client.py:85-90,
server.py:86-107), we move nothing off-device: XLA collectives combine
gradients/parameters across the mesh's ``data`` axis in-graph.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.parallel import collectives as coll
from distributed_tensorflow_tpu.parallel import compression
from distributed_tensorflow_tpu.parallel import mesh as meshlib
from distributed_tensorflow_tpu.parallel import overlap
from distributed_tensorflow_tpu.parallel import precision as precisionlib

PyTree = Any


@struct.dataclass
class TrainState:
    """Replaces the reference server's (model, optimizer) pair
    (reference server.py:148-155) as a pure value."""

    step: jax.Array
    params: PyTree
    opt_state: PyTree
    rng: jax.Array


def cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Sparse categorical crossentropy from logits — parity with the
    reference's loss (reference server.py:13-15, client.py:11-13)."""
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


def cross_entropy_onehot(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Cross-entropy via the one-hot contraction instead of a label gather.

    Same math as :func:`cross_entropy`; exists because XLA's SPMD
    partitioner CHECK-crashes (spmd_partitioner_util.cc device-group check)
    partitioning the take-along-axis GATHER over vocab-sharded logits inside
    a partial-manual shard_map region (composite engine + Megatron-TP GPT,
    whose tied head keeps logits vocab-sharded).  The one-hot form lowers to
    a reduction the partitioner handles; the extra FLOPs fuse into the loss
    reduction and are negligible next to the head matmul."""
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.sum(
        jax.nn.one_hot(labels, logits.shape[-1], dtype=logits.dtype) * logits,
        axis=-1)
    return lse - picked


def token_weights(mask: jax.Array, y: jax.Array) -> jax.Array:
    """Per-element eval weights: the pipeline yields one validity flag per
    ROW (B,), but LM labels are (B, L) per-token — broadcast the row mask
    over the label's trailing dims so `correct/loss/count` count tokens for
    LMs and examples for classifiers with one code path."""
    mask = mask.reshape(mask.shape + (1,) * (y.ndim - mask.ndim))
    return jnp.broadcast_to(mask, y.shape)


def make_loss_fn(apply_fn: Callable) -> Callable:
    def loss_fn(params, x, y, rng):
        logits = apply_fn({"params": params}, x, train=True, rngs={"dropout": rng})
        loss = cross_entropy(logits, y).mean()
        acc = (logits.argmax(-1) == y).mean()
        return loss, acc

    return loss_fn


def gspmd_grad_accum(grad_fn, params, x, y, rng, K: int, mesh=None,
                     batch_axes=meshlib.DATA_AXIS):
    """K-microbatch gradient accumulation under GSPMD (global jit
    semantics): reshape the batch to (K, B/K, ...), `lax.scan` the
    microbatches, accumulate gradients, divide by K once.

    ``grad_fn(params, xc, yc, rng_c) -> ((loss, aux), grads)`` — a
    ``value_and_grad(..., has_aux=True)`` of a per-chunk mean loss; ``aux``
    is any pytree of scalars, accumulated leaf-wise and K-averaged.  The
    returned gradient is then the global batch mean (mean of equal-chunk
    means), identical math to K=1 — the GSPMD counterpart of the sync
    engine's shard_map accumulation (engines/sync.py:68-111), but with no
    manual psum: 'data' stays a GSPMD axis, so each chunk's gradient is
    already globally reduced and the scan just sums K of them.  Activation
    memory drops ~K× (one microbatch's activations live at a time);
    gradient-accumulator memory is one extra param-sized buffer, sharded
    like the params themselves.

    Dropout draws an independent key per microbatch (fold_in on the chunk
    index), matching K separate steps.

    ``mesh``, when given, pins the microbatched inputs to
    ``P(None, batch_axes, ...)`` (K replicated, batch sharded —
    ``batch_axes`` defaults to 'data'; the expert engine passes its
    ('data','expert') combined batch axes).  Without the
    constraint the (B, ...) → (K, B/K, ...) reshape leaves the sharding
    of the new leading axis to propagation, and inside the scan body the
    partitioner can fail to move from its guess to what the embedding
    gather needs — an "Involuntary full rematerialization"
    (replicate-then-repartition) per microbatch on fsdp×tp BERT."""
    if x.shape[0] % K:
        raise ValueError(
            f"global batch {x.shape[0]} not divisible by grad_accum {K}")
    xm = x.reshape((K, x.shape[0] // K) + x.shape[1:])
    ym = y.reshape((K, y.shape[0] // K) + y.shape[1:])
    if mesh is not None:
        axes = batch_axes if isinstance(batch_axes, tuple) else (batch_axes,)
        n_batch = 1
        for a in axes:
            n_batch *= mesh.shape[a]
        # pin ONLY when each chunk's batch divides the batch-axes size:
        # forcing an uneven shard pads the per-device batch, and the padded
        # rows' embedding-gather cotangents scatter-add garbage into real
        # vocab rows (caught by test_tp_grad_accum_matches_k1 at K=4 on a
        # data=4 mesh — chunk batch 2).  When indivisible, sharding
        # propagation's own choice is left alone.
        if (x.shape[0] // K) % n_batch == 0:
            def pin(t):
                spec = P(None, batch_axes,
                         *([None] * (t.ndim - 2)))
                return jax.lax.with_sharding_constraint(
                    t, NamedSharding(mesh, spec))

            xm, ym = pin(xm), pin(ym)

    def micro(carry, chunk):
        g_acc, l_acc, a_acc, i = carry
        xc, yc = chunk
        (l, a), g = grad_fn(params, xc, yc, jax.random.fold_in(rng, i))
        return (jax.tree.map(jnp.add, g_acc, g),
                l_acc + l, jax.tree.map(jnp.add, a_acc, a), i + 1), None

    # aux may be any pytree of scalars (acc, or (task, acc, overflow) for
    # the MoE engine) — zeros come from an abstract eval, no FLOPs
    aux_shape = jax.eval_shape(
        lambda: grad_fn(params, xm[0], ym[0], rng)[0][1])
    aux_init = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), aux_shape)
    zero = jnp.zeros((), jnp.float32)
    init = (jax.tree.map(jnp.zeros_like, params), zero, aux_init,
            jnp.zeros((), jnp.int32))
    (g_sum, l_sum, a_sum, _), _ = jax.lax.scan(micro, init, (xm, ym))
    grads = jax.tree.map(lambda t: t / K, g_sum)
    return grads, l_sum / K, jax.tree.map(lambda t: t / K, a_sum)


def gspmd_value_and_grad(loss_fn, params, x, y, rng, K: int, mesh=None,
                         loss_scale=None):
    """(grads, loss, acc) of a GSPMD step — direct at K == 1, K-microbatch
    accumulated otherwise.  The shared step core of the jit engines
    (tensor_parallel, fsdp); ``loss_fn`` has the make_loss_fn signature.
    ``mesh`` pins microbatch shardings under accumulation (see
    gspmd_grad_accum).

    ``loss_scale`` is the GSPMD family's ONE loss-scaling hook
    (parallel/precision.py fp16-f32master): when given (a traced f32
    scalar read out of the step's opt_state), the DIFFERENTIATED value is
    ``loss × scale`` — fp16 backward intermediates stay in range — while
    the returned metric loss stays unscaled (it rides the aux);
    gradients come back SCALED and the master-weights wrapper unscales
    them.  ``None`` (every non-fp16 policy) compiles the exact unscaled
    program."""
    if loss_scale is not None:
        def scaled_fn(p, xc, yc, rng_c):
            loss, acc = loss_fn(p, xc, yc, rng_c)
            return loss * loss_scale, (loss, acc)

        grad_fn = jax.value_and_grad(scaled_fn, has_aux=True)
        if K == 1:
            (_, (loss, acc)), grads = grad_fn(params, x, y, rng)
            return grads, loss, acc
        grads, _scaled_sum, aux = gspmd_grad_accum(
            grad_fn, params, x, y, rng, K, mesh=mesh)
        loss, acc = aux
        return grads, loss, acc
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    if K == 1:
        (loss, acc), grads = grad_fn(params, x, y, rng)
        return grads, loss, acc
    return gspmd_grad_accum(grad_fn, params, x, y, rng, K, mesh=mesh)


class Engine:
    """Base: owns model, optimizer, mesh; subclasses build the step program."""

    axis = meshlib.DATA_AXIS
    # engines whose step threads the traced loss scale out of opt_state
    # into their loss (the fp16-f32master prerequisite) set this True; the
    # base constructor rejects a scaling policy on any engine that does
    # not — silently training UNscaled loss while the wrapper divides by
    # the scale would shrink the effective LR by the scale factor
    supports_loss_scaling = False

    def __init__(
        self,
        model,
        optimizer: optax.GradientTransformation | None = None,
        mesh=None,
        learning_rate: float = 1e-3,
        grad_compression: str | compression.GradCodec = "none",
        grad_bucket_mb: float = 0.0,
        precision: str | precisionlib.PrecisionPolicy = "f32",
    ):
        self.model = model
        self.tx = optimizer if optimizer is not None else optax.adam(learning_rate)
        self.mesh = mesh if mesh is not None else meshlib.create_mesh()
        self.n_devices = self.mesh.shape[self.axis]
        # mixed-precision policy (--precision; parallel/precision.py):
        # 'f32' (default) is a strict no-op — no cast, no wrap, the
        # compiled programs are byte-identical to the pre-policy ones.
        # Master policies wrap the optimizer HERE, before enable_health
        # chains its captures around the result, so health sees the raw
        # grads in and the final emitted updates out.
        self.precision = precisionlib.make_policy(precision)
        if self.precision.loss_scaling and not self.supports_loss_scaling:
            raise ValueError(
                f"precision '{self.precision.name}' needs dynamic loss "
                f"scaling, which {type(self).__name__} does not thread "
                f"into its loss — use a bf16 policy (bf16/bf16-f32master: "
                f"bfloat16 shares f32's exponent range, no scaling "
                f"needed), or train with a loss-scaling engine "
                f"(sync/allreduce/fsdp/tensor_parallel)")
        if self.precision.active:
            self.tx = self.precision.wrap_optimizer(self.tx)
        # cross-device gradient/parameter exchange codec (--grad-compression;
        # parallel/compression.py): 'none' compiles to the pre-codec program.
        # --grad-bucket-mb > 0 wraps it in the bucketed overlap codec
        # (parallel/overlap.py): size-targeted reverse-backward buckets
        # whose independent per-bucket collectives XLA's latency-hiding
        # scheduler can run behind the remaining backward compute; 0 (the
        # default) keeps the codec unwrapped — byte-identical programs.
        self.grad_codec = overlap.make_overlap_codec(grad_compression,
                                                     grad_bucket_mb)
        self._step_fn = None
        self._eval_fn = None
        self._many_step_fns: dict[int, Callable] = {}  # k → jitted scan drain
        self._init_shardings = None  # set by _init_partitioned_state
        # numeric-health layer (observability/health.py): None = off — no
        # optimizer wrap, no extra metrics, the compiled program is the
        # pre-health one.  enable_health() installs the capture transforms.
        self.health = None
        self._health_step_fn = None
        self._health_ema_val = None  # device (ema, count) loss-EMA carry
        self._precision_step_fn = None  # jitted scale-stats step (fp16)

    # ---------------------------------------------------------------- init
    def init_state(self, rng: jax.Array, sample_x: np.ndarray) -> TrainState:
        """Initialize replicated state (subclasses may re-layout).  The
        precision policy's storage cast happens HERE, before ``tx.init``:
        the optimizer (and a master policy's f32 copy) is built over the
        params the steps will actually train."""
        params = self.model.init(rng, jnp.asarray(sample_x[:1]), train=False)["params"]
        params = self.precision.cast_params(params)
        opt_state = self.tx.init(params)
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           opt_state=opt_state, rng=rng)
        # every process computed the same state (same rng); state_to_global
        # makes it one global replicated array on multi-process meshes
        return meshlib.state_to_global(state, meshlib.replicated(self.mesh))

    # ------------------------------------------------------------- batches
    def _place(self, arr, sharding, process_local: bool):
        """One batch-array placement: full-host copy or process-local rows."""
        if process_local:
            return meshlib.local_to_global(arr, sharding)
        return meshlib.host_to_global(arr, sharding)

    def shard_batch(self, x: np.ndarray, y: np.ndarray,
                    mask: np.ndarray | None = None,
                    process_local: bool = False):
        """Place a batch with its leading dim split over the data axis.

        ``process_local=False``: every process passes the same global batch
        (one host batch feeds all devices).  ``process_local=True``: each
        process passes its OWN rows (global_batch / process_count of them)
        from its input shard — the multi-host rendering of the reference's
        per-worker dataset sharding (reference initializer.py:44).
        """
        xs = self._place(x, meshlib.data_sharding(self.mesh, x.ndim),
                         process_local)
        ys = self._place(y, meshlib.data_sharding(self.mesh, y.ndim),
                         process_local)
        if mask is None:
            return xs, ys
        ms = self._place(mask, meshlib.data_sharding(self.mesh, mask.ndim),
                         process_local)
        return xs, ys, ms

    # -------------------------------------------------------------- health
    def enable_health(self, config=None):
        """Turn on the numeric-health layer (``--health on``): wraps the
        optimizer with the capture transforms of observability/health.py,
        so every subsequent step's metrics additionally carry
        ``grad_norm / param_norm / update_norm / update_ratio /
        nonfinite_count / loss_spike`` — computed on device, stacked
        through the many-step scan like any other metric.

        Must run BEFORE ``init_state``/the first step: the optimizer state
        tree gains its capture slots at ``tx.init``.  With health off
        (never called) nothing here touches the engine — the compiled
        program stays bitwise identical to the pre-health one."""
        from distributed_tensorflow_tpu.observability import health as hl

        if self.health is not None:
            return self.health
        if (self._step_fn is not None or self._many_step_fns
                or self._init_shardings is not None):
            raise RuntimeError(
                "enable_health() must run before the engine builds its "
                "step program or initializes state (the optimizer tree "
                "gains capture slots at tx.init)")
        self.health = config if config is not None else hl.HealthConfig()
        self.tx = hl.wrap_optimizer(self.tx, self.health)
        return self.health

    def _health_ema(self):
        from distributed_tensorflow_tpu.observability import health as hl

        if self._health_ema_val is None:
            self._health_ema_val = hl.ema_init()
        return self._health_ema_val

    def _check_health_state(self, state) -> None:
        """A state initialized BEFORE enable_health() carries no capture
        slots (the replicated engines' init_state sets none of the fields
        the enable-time guard can see) — fail at first step with the
        actionable message instead of an opaque optax tree-structure
        mismatch deep inside the jit."""
        from distributed_tensorflow_tpu.observability import health as hl

        hl.from_opt_state(state.opt_state)

    def _health_wrap(self, step):
        """``(state, ema, x, y) -> (state, ema, metrics ∪ health)``: run
        the engine's step, read the captured health scalars back out of
        the NEW opt_state, and score the loss against its running EMA —
        all inside the jit, so the health trajectory stacks through the
        scan exactly like loss/accuracy (k-invariant, flushed per chunk)."""
        from distributed_tensorflow_tpu.observability import health as hl

        cfg = self.health

        def stepped(state, ema, x, y):
            new_state, metrics = step(state, x, y)
            stats = hl.from_opt_state(new_state.opt_state)
            if "loss_scale" in metrics:
                # fp16 loss scaling: the grad capture sits BEFORE the
                # master-weights unscale, so its norm carries the scale —
                # divide it back out so grad_norm stays comparable across
                # precision policies (nan/inf divide through unchanged,
                # the anomaly signal survives).  The ENTERING state's
                # scale is the one the gradients were multiplied by;
                # metrics["loss_scale"] is post-update and differs on
                # every grow/backoff step
                entering = precisionlib.loss_scale_from(state.opt_state)
                stats["grad_norm"] = stats["grad_norm"] / entering
            if "loss" in metrics:
                spike, ema = hl.ema_spike(metrics["loss"], ema, cfg)
                stats["loss_spike"] = spike
            return new_state, ema, {**metrics, **stats}

        return stepped

    # ----------------------------------------------------------- precision
    def _precision_wrap(self, step):
        """``(state, x, y) -> (state, metrics ∪ {loss_scale, ls_skipped})``
        — read the dynamic-loss-scale bookkeeping back out of the NEW
        opt_state inside the jit, so skip accounting stacks through the
        scan exactly like loss/accuracy (k-invariant).  Installed only
        when the policy scales; every other policy compiles the engine's
        untouched step."""

        def stepped(state, x, y):
            new_state, metrics = step(state, x, y)
            stats = precisionlib.scale_stats_from(new_state.opt_state)
            return new_state, {**metrics, **stats}

        return stepped

    def _base_step(self):
        """The engine's step with the precision metrics wrap applied when
        the policy scales — the single composition point ``step`` and
        ``build_many_step`` share (the health wrap then goes OUTSIDE, so
        its anomaly policy sees the scaling stats too)."""
        if self._step_fn is None:
            self._step_fn = self._build_step()
        if self.precision.loss_scaling:
            return self._precision_wrap(self._step_fn)
        return self._step_fn

    # ---------------------------------------------------------------- step
    def step(self, state: TrainState, x, y):
        base = self._base_step()
        if self.health is None:
            if not self.precision.loss_scaling:
                return base(state, x, y)
            if self._precision_step_fn is None:
                self._precision_step_fn = jax.jit(base, donate_argnums=0)
            return self._precision_step_fn(state, x, y)
        if self._health_step_fn is None:
            self._check_health_state(state)
            # the outer jit inlines the engine's jitted step; the state is
            # donated as before (the two-scalar EMA carry is not worth
            # donation bookkeeping)
            self._health_step_fn = jax.jit(
                self._health_wrap(base), donate_argnums=0)
        state, ema, metrics = self._health_step_fn(
            state, self._health_ema(), x, y)
        self._health_ema_val = ema
        return state, metrics

    def _build_step(self):
        raise NotImplementedError

    # ------------------------------------------------------ multi-step drain
    def build_many_step(self, k: int):
        """One jitted program that runs ``k`` training steps as a
        ``lax.scan`` over ``k`` pre-staged device batches.

        Signature: ``many(state, xs_k, ys_k) -> (state, metrics)`` where
        ``xs_k``/``ys_k`` are length-``k`` tuples of batches already placed
        with this engine's input sharding (``shard_batch``), and each
        ``metrics`` leaf comes back stacked ``(k,)`` — the per-step
        trajectory, materializable with ONE host sync per call.  The tuples
        are stacked on-device inside the jit (no host-side concat), then the
        scan slices them back per step, so each slice keeps the batch
        sharding it was placed with.

        This is the steady-state fast path of ``Trainer.fit``
        (``steps_per_call``): the per-step Python dispatch + host round-trip
        that made the single-step loop swing 0.87→1.68× with zero code
        changes (BASELINE.md methodology) happens once per *chunk* instead
        of once per step.  The scan body is the engine's own donated
        ``train_step`` — identical math step for step.

        With the health layer on (``enable_health``) the signature gains
        the loss-EMA carry — ``many(state, ema, xs_k, ys_k) -> (state,
        ema, metrics)`` — and each ``metrics`` leaf includes the stacked
        per-step health stats; ``many_step`` threads the carry, so callers
        going through it see no difference.  Health OFF compiles the exact
        pre-health program below, untouched.
        """
        if k < 1:
            raise ValueError(f"steps_per_call must be >= 1, got {k}")
        # loss-scaling policies ride the same wrap here as in step():
        # the per-step loss_scale/ls_skipped stats stack through the scan
        step = self._base_step()

        if self.health is None:
            def many(state, xs_k, ys_k):
                def body(st, batch):
                    x, y = batch
                    return step(st, x, y)

                return jax.lax.scan(body, state,
                                    (jnp.stack(xs_k), jnp.stack(ys_k)))

            return jax.jit(many, donate_argnums=0)

        hstep = self._health_wrap(step)

        def many_health(state, ema, xs_k, ys_k):
            def body(carry, batch):
                st, e = carry
                x, y = batch
                st, e, m = hstep(st, e, x, y)
                return (st, e), m

            (state, ema), metrics = jax.lax.scan(
                body, (state, ema), (jnp.stack(xs_k), jnp.stack(ys_k)))
            return state, ema, metrics

        # state donated as in the health-off drain; the two-scalar EMA
        # carry is not worth donation bookkeeping
        return jax.jit(many_health, donate_argnums=0)

    def many_step(self, state: TrainState, xs_seq, ys_seq):
        """Run ``len(xs_seq)`` steps through the cached scanned drain
        (``build_many_step``); one compiled program per distinct chunk
        length.  Engines with a host-side per-step overflow watch (the MoE
        engines' ``overflow_monitor``, fed per step by their ``step()``
        overrides) get it fed here too, one still-lazy slice per step of
        the stacked metric — same window cadence as the single-step path."""
        k = len(xs_seq)
        fn = self._many_step_fns.get(k)
        if fn is None:
            if self.health is not None:
                self._check_health_state(state)
            fn = self.build_many_step(k)
            self._many_step_fns[k] = fn
        if self.health is None:
            state, metrics = fn(state, tuple(xs_seq), tuple(ys_seq))
        else:
            state, ema, metrics = fn(state, self._health_ema(),
                                     tuple(xs_seq), tuple(ys_seq))
            self._health_ema_val = ema
        monitor = getattr(self, "overflow_monitor", None)
        if monitor is not None and "overflow" in metrics:
            for i in range(k):
                monitor.observe(metrics["overflow"][i])
        return state, metrics

    # ----------------------------------------------------------- spec map
    def state_partition_specs(self, state: TrainState) -> PyTree:
        """Per-leaf ``PartitionSpec`` tree of this engine's state layout —
        the spec map elastic resharding restores a checkpoint under
        (elastic/reshard.py): a leaf loaded from a checkpoint written on a
        DIFFERENT mesh shape is re-placed as ``NamedSharding(self.mesh,
        spec)`` of its entry here.  Derived from the live leaf shardings
        of ``state`` (typically a fresh ``init_state`` template), so every
        engine's layout — replicated, fsdp-sharded, tensor-parallel, and
        a precision policy's master copies inside ``opt_state`` — is
        covered by the one base implementation; leaves without a
        ``NamedSharding`` (host scalars) map to replicated ``P()``."""
        def spec_of(leaf):
            sh = getattr(leaf, "sharding", None)
            if isinstance(sh, NamedSharding):
                return sh.spec
            return P()

        return jax.tree.map(spec_of, state)

    # ----------------------------------------------------------- telemetry
    def grad_collective_bytes_raw(self, state: TrainState) -> int:
        """UNCOMPRESSED bytes one gradient collective round moves (the
        data-axis allreduce of sync DP), from the REAL param leaf dtypes —
        gradients share the params' shapes and dtypes, so for the
        replicated-param engines this is the per-step payload (the same
        itemsize accounting bench_decode uses for its weight-streaming
        figure, not an assumed 4 B/param).  Engines whose state layout or
        collective cadence differs override this (async/gossip stack a
        leading per-device axis and sync every ``sync_every`` steps).
        0 when the state carries no param pytree."""
        params = getattr(state, "params", None)
        if params is None:
            return 0
        try:
            return int(sum(np.prod(a.shape) * a.dtype.itemsize
                           for a in jax.tree.leaves(params)))
        except Exception:  # exotic leaf without shape/dtype
            return 0

    def grad_collective_bytes(self, state: TrainState) -> int:
        """Wire bytes of one gradient collective round under this engine's
        ``grad_compression`` codec (bf16 halves the raw figure, int8
        quarters it plus one f32 scale per leaf; 'none' equals
        ``grad_collective_bytes_raw``).  On the explicit-collective
        engines (sync/async/gossip) this is what actually crosses ICI;
        on the GSPMD engines the collective is compiler-inserted and the
        codec is a quantize→dequantize roundtrip, so this is the codec's
        payload ACCOUNTING, not the executed transfer
        (parallel/compression.py module docstring).  Telemetry (the
        tracer's ``collective_profile`` event, the fit result, bench.py)
        reports BOTH figures so the compression win is visible."""
        params = getattr(state, "params", None)
        if params is None:
            return 0
        try:
            return self.grad_codec.wire_bytes(jax.tree.leaves(params))
        except Exception:  # exotic leaf without shape/dtype
            return 0

    def _bytes_per_device(self, tree) -> int:
        """Bytes of ``tree`` resident on ONE local device — real shard
        bytes for sharded leaves (FSDP/TP state counts its 1/n), full
        bytes for replicated/host leaves.  The first *addressable* device
        keeps the count real on every host of a multi-process mesh."""
        if tree is None:
            return 0
        dev = jax.local_devices()[0]
        total = 0
        for leaf in jax.tree.leaves(tree):
            shards = getattr(leaf, "addressable_shards", None)
            if shards is None:
                total += int(getattr(leaf, "nbytes", 0) or 0)
                continue
            for sh in shards:
                if sh.device == dev:
                    total += sh.data.nbytes
        return total

    def param_bytes_per_device(self, state: TrainState) -> int:
        """Per-device parameter bytes — THE storage number the precision
        policy halves (bf16 storage ≈ f32/2): reported in the fit result,
        run report and bench lines, gated lower-is-better by
        ``analyze diff``."""
        return self._bytes_per_device(getattr(state, "params", None))

    def opt_state_bytes_per_device(self, state: TrainState) -> int:
        """Per-device optimizer-state bytes.  Master policies GROW this
        (the f32 master lives here — the documented trade of
        bf16-f32master); the pure ``bf16`` policy halves it."""
        return self._bytes_per_device(getattr(state, "opt_state", None))

    def roofline_model(self):
        """Analytic cost model of this engine's model for ``--roofline``
        MFU attribution (observability/roofline.py), or None for model
        families the analytic accounting doesn't cover (CNN/MLP/BERT —
        their MFU then honestly reports None rather than a GPT formula
        applied to the wrong architecture).  Engines that microbatch
        (composite/expert_parallel ``grad_accum``) need no override:
        model FLOPs per optimizer step are grad-accum invariant."""
        from distributed_tensorflow_tpu.observability.roofline import (
            GPTCostModel)

        return GPTCostModel.from_model(self.model)

    # ---------------------------------------------------------------- eval
    def eval_params(self, state: TrainState) -> PyTree:
        """Parameters to evaluate with (replicated). Subclasses with
        per-device parameter copies override to average first."""
        return state.params

    def _build_eval_gspmd(self, logits_fn):
        """Masked eval under plain jit (GSPMD semantics: params keep their
        shardings, XLA gathers per layer).  Shared by the engines whose
        params must not be re-replicated wholesale (fsdp, pipeline); the
        base shard_map eval below is for replicated-param engines."""

        def eval_step(params, x, y, mask):
            logits = logits_fn(params, x)
            w = token_weights(mask, y)
            correct = ((logits.argmax(-1) == y) * w).sum()
            loss_sum = (cross_entropy(logits, y) * w).sum()
            return correct, loss_sum, w.sum()

        return jax.jit(eval_step)

    def _build_eval(self):
        apply_fn = self.model.apply
        axis = self.axis

        def device_eval(params, x, y, mask):
            logits = apply_fn({"params": params}, x, train=False)
            w = token_weights(mask, y)
            correct = coll.all_reduce_sum(
                ((logits.argmax(-1) == y) * w).sum(), axis)
            loss_sum = coll.all_reduce_sum((cross_entropy(logits, y) * w).sum(), axis)
            count = coll.all_reduce_sum(w.sum(), axis)
            return correct, loss_sum, count

        smapped = jax.shard_map(
            device_eval, mesh=self.mesh,
            in_specs=(P(), P(self.axis), P(self.axis), P(self.axis)),
            out_specs=(P(), P(), P()),
        )
        return jax.jit(smapped)

    def evaluate(self, state: TrainState, dataset, batch_size: int = 100) -> dict:
        """Full-test-set eval — parity with the reference's server-side eval on
        the unsharded test set (reference server.py:24-37, 179-180), not the
        per-shard eval of dist_keras (reference dist_keras.py:53)."""
        if self._eval_fn is None:
            self._eval_fn = self._build_eval()
        params = self.eval_params(state)
        bs = max(batch_size, self.n_devices)
        bs = (bs // self.n_devices) * self.n_devices
        tot_correct = tot_loss = tot_count = 0.0
        for bx, by, bm in dataset.batches(bs, shuffle=False):
            xs, ys, ms = self.shard_batch(bx, by, bm)
            c, l, n = self._eval_fn(params, xs, ys, ms)
            tot_correct += float(c)
            tot_loss += float(l)
            tot_count += float(n)
        return {
            "accuracy": tot_correct / max(tot_count, 1.0),
            "loss": tot_loss / max(tot_count, 1.0),
            "count": int(tot_count),
        }

    # ------------------------------------------------------------- helpers
    def _per_device_rng(self, state_rng: jax.Array, step: jax.Array) -> jax.Array:
        rng = jax.random.fold_in(state_rng, step)
        return jax.random.fold_in(rng, coll.axis_index(self.axis))

    def _init_partitioned_state(self, rng: jax.Array, sample_x,
                                init_model=None,
                                spec_fn=None) -> TrainState:
        """Sharded init for GSPMD engines: abstract-eval the init to read
        the model's `with_partitioning` annotations, then jit-init with
        those shardings so large params materialize already sharded (never
        replicated-then-resharded).  Unannotated params replicate.

        ``spec_fn`` overrides the annotation-derived specs: it receives the
        UNBOXED abstract state tree AND the annotation-derived spec tree,
        and returns a matching tree of `PartitionSpec`s (the FSDP engine
        merges data-axis sharding into the annotations this way).  The
        resolved shardings are kept on ``self._init_shardings`` for engines
        that pin step outputs.

        The returned state is UNBOXED (plain arrays, no `nn.Partitioned`
        wrappers): the annotations' only runtime job is done once the arrays
        carry their NamedShardings, and boxed leaves break under
        partial-manual shard_map — flax re-applies each box's spec via
        with_sharding_constraint at apply time, which crashes on
        DenseGeneral's pre-reshape kernels (rank-2 value, rank-3 spec).

        ``init_model`` optionally substitutes a structurally-identical module
        for tracing init (e.g. a dense-attention twin when the engine's model
        needs in-shard_map collectives that can't trace here).
        """
        import flax.linen as nn
        from jax.sharding import NamedSharding

        x = jnp.asarray(sample_x[:1])
        module = init_model if init_model is not None else self.model

        def boxed_init(rng):
            params = module.init(rng, x, train=False)["params"]
            # storage cast INSIDE the traced init (no-op for f32): the
            # abstract eval below then derives shardings for the FINAL
            # dtypes — low-precision params materialize already sharded,
            # and a master policy's f32 copy (created by tx.init via
            # jax.tree.map, so nn.Partitioned boxes survive) inherits the
            # same partition annotations as the params it mirrors
            params = self.precision.cast_params(params)
            opt_state = self.tx.init(params)
            return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                              opt_state=opt_state, rng=rng)

        def init_fn(rng):
            return nn.unbox(boxed_init(rng))

        abstract = jax.eval_shape(boxed_init, rng)
        if spec_fn is None:
            specs = nn.get_partition_spec(abstract)
        else:
            specs = spec_fn(nn.unbox(abstract),
                            nn.get_partition_spec(abstract))
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), specs,
            is_leaf=lambda s: isinstance(s, P))
        self._init_shardings = shardings
        return jax.jit(init_fn, out_shardings=shardings)(rng)
