"""Slot-based sharded KV cache: the device half of the serving engine.

Static batching idles the chip on every finished sequence — a batch of
requests decodes at the pace of its longest member, and admitting a new
request means restarting ``generate`` from scratch.  Continuous batching
(Orca/vLLM-style in-flight batching) fixes that by making the *batch slot*,
not the batch, the unit of scheduling: the KV cache is a fixed table of
``slots`` independent sequences, each with its own length, and ONE compiled
single-token decode step advances every active slot regardless of age.
Admission and eviction are per-slot edits between decode iterations — the
decode program never recompiles.

Device-side contract (everything else lives in serving/scheduler.py):

* the cache is a pytree of ``(slots, max_len, kv_heads, head_dim)`` leaves
  (models/gpt.py slot-decode mode — deliberately no scalar cursors, so
  every leaf shards the slot dim over the mesh's ``data`` axis and, for
  tensor-parallel models, the kv-head dim over ``model``;
  parallel/mesh.py ``kv_slot_sharding``);
* ``advance`` is the one jitted decode step: (tokens, lengths, active)
  vectors in, next tokens out, cache donated through;
* ``insert`` is a jitted prefill that feeds a new request's prompt through
  the SAME per-token decode math inside a ``lax.scan`` over the padded
  prompt, against only that slot's cache slice (batch 1), then writes the
  slice back — compiled once per padded length bucket (powers of two), so
  steady-state admission never triggers XLA.

Greedy slot decode is token-identical to the sequential ``generate``
sampler per request (tests/test_serving.py): prefill-at-position-t and
decode-at-cursor-t run the same dense cache attention with the same
length-driven validity mask.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.models.gpt import GPTLM
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def _bucket(n: int, floor: int, cap: int) -> int:
    """Smallest power-of-two ≥ max(n, floor), capped at ``cap`` — the
    padded prompt length, so prefill compiles once per bucket instead of
    once per prompt length."""
    b = max(int(floor), 1)
    while b < n:
        b *= 2
    return min(b, cap)


class SlotOverflow(RuntimeError):
    """An active slot was asked to write past its ``max_len`` capacity.

    The scheduler guards admission (prompt + max_new_tokens ≤ max_len), so
    reaching this means a bookkeeping bug, not a user error — the serving
    twin of the training path's sticky cache-overflow flag (models/gpt.py
    ADVICE r3: never silently clamp)."""


class SlotKVCache:
    """Fixed slot table + compiled prefill/decode programs for one GPTLM.

    ``model`` is the TRAINING-mode module (any attention impl); it is
    cloned into slot-decode mode exactly like ``generate`` clones into
    cursor-decode mode — dense cache attention, dropout off, Megatron TP
    layout kept when ``mesh`` has a 'model' axis and the model was
    partitioned.  ``params`` may be a TP engine's committed TrainState
    params (used in place) or host/single-device params (replicated).

    Host-side bookkeeping (`lengths`, `active`, `tokens`) lives on numpy:
    the scheduler owns admission/eviction and the decode step receives the
    vectors as arguments, so slot edits never touch device state except
    through the two compiled programs.
    """

    def __init__(self, model: GPTLM, params, slots: int, *,
                 mesh=None, greedy: bool = True, temperature: float = 1.0,
                 prefill_bucket: int = 8, rng=None, kv_dtype=None):
        if slots < 1:
            raise ValueError(f"slots must be positive, got {slots}")
        self.slots = int(slots)
        self.max_len = int(model.max_len)
        self.greedy = bool(greedy)
        self.temperature = float(temperature)
        self.prefill_bucket = int(prefill_bucket)
        self.mesh = mesh
        keep_tp = (mesh is not None and model.partition_model
                   and meshlib.MODEL_AXIS in mesh.axis_names)
        self.dm = model.clone(decode=True, decode_slots=True,
                              attention_impl="dense",
                              partition_model=keep_tp, dropout_rate=0.0)
        self._rng = rng if rng is not None else jax.random.key(0)

        # zero slot cache from an abstract init — zeros-from-shape IS the
        # init value (same argument as models/gpt.py `generate`)
        dummy = jnp.zeros((self.slots, 1), jnp.int32)
        shapes = jax.eval_shape(
            lambda: self.dm.init(jax.random.key(0), dummy, train=False,
                                 positions=dummy))["cache"]
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        if kv_dtype is not None:
            # --serve-kv-dtype: store the K/V table narrower than the
            # model computes (bf16 halves KV memory → double the slots per
            # chip).  The model's slot-scatter writes cast to the table's
            # dtype (models/gpt.py) and the attention read promotes back,
            # so the decode program stays the one compiled step.
            kv_dtype = jnp.dtype(kv_dtype)
            cache = jax.tree.map(
                lambda t: t.astype(kv_dtype)
                if jnp.issubdtype(t.dtype, jnp.floating) else t, cache)
        # the table's actual storage dtype (first float leaf — the K/V
        # buffers), surfaced in the serve report section
        self.kv_dtype = next(
            (str(leaf.dtype) for leaf in jax.tree.leaves(cache)
             if jnp.issubdtype(leaf.dtype, jnp.floating)), "float32")

        self._vec_sharding = None
        if mesh is not None:
            dp = mesh.shape.get(meshlib.DATA_AXIS, 1)
            if self.slots % dp:
                raise ValueError(
                    f"slots ({self.slots}) must divide by the mesh's data "
                    f"axis ({dp}): each data shard owns a contiguous slot "
                    f"block")
            cache = jax.tree.map(
                lambda t: jax.device_put(t, meshlib.kv_slot_sharding(
                    mesh, t.ndim, shard_heads=keep_tp)), cache)
            self._vec_sharding = meshlib.kv_slot_sharding(mesh, 1)
            # params committed to this mesh are used in place; anything
            # else replicates (the `generate(mesh=...)` placement rule)
            repl = NamedSharding(mesh, P())
            target = mesh.devices.tolist()

            def place(t):
                sh = getattr(t, "sharding", None)
                if isinstance(sh, NamedSharding) and (
                        sh.mesh is mesh
                        or sh.mesh.devices.tolist() == target):
                    return t
                return jax.device_put(t, repl)

            params = jax.tree.map(place, params)
        self.cache = cache
        self.params = params

        # host-side slot table
        self.lengths = np.zeros(self.slots, np.int32)
        self.active = np.zeros(self.slots, np.bool_)
        self.tokens = np.zeros(self.slots, np.int32)   # last token per slot

        self._step = self._build_step()
        self._prefills: dict[int, object] = {}

    # ------------------------------------------------------------- programs
    def _sample(self, logits, rng):
        """(B, V) logits → (B,) token ids; greedy or temperature draw —
        the ONE sampling definition shared by prefill and decode."""
        if self.greedy:
            return logits.argmax(-1)
        return jax.random.categorical(
            rng, logits / max(self.temperature, 1e-6))

    def _build_step(self):
        dm = self.dm

        def step(params, cache, tokens, lengths, active, rng):
            # write index = current length; inactive (free) slots scatter
            # garbage into their own rows only, which the next insert's
            # prefill overwrites — validity is length-driven, so stale
            # positions are never attended
            logits, upd = dm.apply(
                {"params": params, "cache": cache}, tokens[:, None],
                train=False, positions=lengths[:, None], mutable=["cache"])
            nxt = self._sample(logits[:, -1], rng).astype(tokens.dtype)
            return upd["cache"], jnp.where(active, nxt, tokens)

        return jax.jit(step, donate_argnums=1)

    def _prefill(self, lpad: int):
        """Compiled prefill-insert for one padded prompt length.

        Slices slot ``slot`` out of every cache leaf, scans the padded
        prompt through the single-token slot-decode step (batch 1,
        positions 0..lpad-1), writes the slice back, and samples the FIRST
        generated token from the logits at the last REAL prompt position.
        Steps past ``prompt_len`` write garbage K/V beyond the slot's
        length — invisible under the length mask and overwritten as
        decoding advances (the same argument that makes free-slot scatter
        writes safe).  The decode step is untouched: admission never
        recompiles it."""
        dm = self.dm

        def prefill(params, cache, slot, tokens, prompt_len, rng):
            sub = jax.tree.map(
                lambda t: lax.dynamic_slice_in_dim(t, slot, 1, 0), cache)

            def body(c, xs):
                tok, t = xs
                logits, upd = dm.apply(
                    {"params": params, "cache": c}, tok[None, None],
                    train=False, positions=t[None, None],
                    mutable=["cache"])
                return upd["cache"], logits[0, -1]

            sub, all_logits = lax.scan(
                body, sub, (tokens, jnp.arange(lpad, dtype=jnp.int32)))
            last = jnp.take(all_logits, prompt_len - 1, axis=0)
            first = self._sample(last[None, :], rng)[0]
            cache = jax.tree.map(
                lambda full, s: lax.dynamic_update_slice_in_dim(
                    full, s, slot, 0), cache, sub)
            return cache, first.astype(tokens.dtype)

        return jax.jit(prefill, donate_argnums=1)

    # ------------------------------------------------------------ slot API
    @property
    def free_slots(self) -> list[int]:
        return [i for i in range(self.slots) if not self.active[i]]

    def _put_vec(self, arr):
        arr = jnp.asarray(arr)
        if self._vec_sharding is not None:
            arr = jax.device_put(arr, self._vec_sharding)
        return arr

    def _put_repl(self, arr):
        """Replicated placement: the padded prompt is per-scan-step data,
        not a (slots,) vector — slot sharding would demand the padded
        length divide the data axis (it usually won't)."""
        arr = jnp.asarray(arr)
        if self.mesh is not None:
            arr = jax.device_put(arr, NamedSharding(self.mesh, P()))
        return arr

    def _next_rng(self):
        if self.greedy:
            return self._rng  # unused by the program; keep it static
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def insert(self, prompt, slot: int | None = None) -> tuple[int, int]:
        """Admit a prompt into a free slot (jitted prefill-insert).

        Returns ``(slot, first_token)`` — the first generated token is
        sampled by the prefill itself (its wall time IS the time-to-first-
        token), and the slot's length becomes ``len(prompt)``: the first
        decode step will write the returned token's K/V at that position.
        """
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        lp = int(prompt.shape[0])
        if lp < 1:
            raise ValueError("prompt must hold at least one token")
        if lp >= self.max_len:
            raise ValueError(
                f"prompt length {lp} leaves no room to generate within "
                f"max_len={self.max_len}")
        if slot is None:
            free = self.free_slots
            if not free:
                raise RuntimeError("no free slot — evict before inserting")
            slot = free[0]
        elif self.active[slot]:
            raise RuntimeError(f"slot {slot} is active — evict it first")
        lpad = _bucket(lp, self.prefill_bucket, self.max_len)
        padded = np.zeros(lpad, np.int32)
        padded[:lp] = prompt
        if lpad not in self._prefills:
            self._prefills[lpad] = self._prefill(lpad)
        fn = self._prefills[lpad]
        self.cache, first = fn(
            self.params, self.cache, jnp.int32(slot),
            self._put_repl(padded), jnp.int32(lp), self._next_rng())
        self.active[slot] = True
        self.lengths[slot] = lp
        self.tokens[slot] = first = int(first)
        return slot, first

    def advance(self) -> np.ndarray:
        """One decode iteration: every ACTIVE slot consumes its last token
        and emits the next one; lengths advance by one.  Returns the
        (slots,) token vector — inactive rows carry their stale token.
        The jitted step is compiled exactly once per cache shape."""
        live = self.lengths[self.active]
        if live.size and int(live.max()) >= self.max_len:
            raise SlotOverflow(
                f"active slot at length {int(live.max())} would write past "
                f"max_len={self.max_len}; the scheduler must bound "
                f"prompt + max_new_tokens at admission")
        self.cache, nxt = self._step(
            self.params, self.cache, self._put_vec(self.tokens),
            self._put_vec(self.lengths),
            self._put_vec(self.active), self._next_rng())
        nxt = np.asarray(nxt)
        self.lengths[self.active] += 1
        self.tokens = nxt.astype(np.int32)
        return nxt

    def evict(self, slot: int) -> None:
        """Free a slot.  Pure host bookkeeping: stale K/V stays in the
        buffer but is unreachable (validity is length-driven) and the next
        insert's prefill overwrites it from position 0."""
        if not self.active[slot]:
            raise RuntimeError(f"slot {slot} is not active")
        self.active[slot] = False
        self.lengths[slot] = 0
        self.tokens[slot] = 0

    def compiled_programs(self) -> dict[str, int]:
        """{decode_steps: 1, prefill_buckets: N} — the recompile-freedom
        invariant the tests pin down."""
        return {"decode_steps": 1, "prefill_buckets": len(self._prefills)}
