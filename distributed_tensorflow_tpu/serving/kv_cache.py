"""Slot-based sharded KV cache: the device half of the serving engine.

Static batching idles the chip on every finished sequence — a batch of
requests decodes at the pace of its longest member, and admitting a new
request means restarting ``generate`` from scratch.  Continuous batching
(Orca/vLLM-style in-flight batching) fixes that by making the *batch slot*,
not the batch, the unit of scheduling: the KV cache is a fixed table of
``slots`` independent sequences, each with its own length, and ONE compiled
single-token decode step advances every active slot regardless of age.
Admission and eviction are per-slot edits between decode iterations — the
decode program never recompiles.

Device-side contract (everything else lives in serving/scheduler.py):

* the cache is a pytree of ``(slots, max_len, kv_heads, head_dim)`` leaves
  (models/gpt.py slot-decode mode — deliberately no scalar cursors, so
  every leaf shards the slot dim over the mesh's ``data`` axis and, for
  tensor-parallel models, the kv-head dim over ``model``;
  parallel/mesh.py ``kv_slot_sharding``);
* ``advance`` is the one jitted decode step: (tokens, lengths, active)
  vectors in, next tokens out, cache donated through;
* ``insert`` is a jitted prefill that feeds a new request's prompt through
  the SAME per-token decode math inside a ``lax.scan`` over the padded
  prompt, against only that slot's cache slice (batch 1), then writes the
  slice back — compiled once per padded length bucket (powers of two), so
  steady-state admission never triggers XLA;
* ``begin_insert``/``prefill_chunk`` split that admission into fixed
  token-budget chunks (Sarathi-Serve, arXiv:2403.02310): each chunk resumes
  at the slot's fill position (the chunk program takes a traced ``start``,
  so ONE compile per power-of-two chunk-length bucket serves every resume
  point), and the scheduler interleaves at most one chunk per decode
  iteration — live slots keep emitting tokens while a long prompt fills;
* the optional **prefix pool** (vLLM PagedAttention's block-granular KV
  reuse, arXiv:2309.06180) caches block-aligned prompt-prefix KV keyed by
  the exact token bytes of the prefix: on admission the longest cached
  prefix is copied into the slot and prefill starts at the first uncached
  block, with hit/miss/evict accounting and bounded LRU eviction.

Greedy slot decode is token-identical to the sequential ``generate``
sampler per request (tests/test_serving.py): prefill-at-position-t and
decode-at-cursor-t run the same dense cache attention with the same
length-driven validity mask.  Chunked prefill is bitwise-identical to
monolithic prefill (each token's forward depends only on cache positions
below its own, all written by earlier chunks), and a prefix-cache hit is
bitwise-identical to recomputation (the pooled KV is a byte copy of what
the cold prefill would write).

Round 14 adds the two raw-decode-speed levers (ROADMAP item 3):
``verify_block``/``commit_block``/``rewind`` — the speculative-decode
device step (one batched program scores a (slots, k+1) token block;
acceptance/rollback is length bookkeeping alone, the same
validity-is-length-driven argument as chunk resume) — and
``kv_dtype='int8'`` — K/V stored int8 with one f32 max-abs scale per
written vector, the scale leaves riding the same sharded cache pytree
(``kv_bytes_per_slot`` is the capacity number; token parity vs the bf16
oracle is tolerance-based, the one serving feature with that caveat).
"""

from __future__ import annotations

import hashlib
import time
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.models.gpt import GPTLM
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def _bucket(n: int, floor: int, cap: int) -> int:
    """Smallest power-of-two ≥ max(n, floor), capped at ``cap`` — the
    padded prompt length, so prefill compiles once per bucket instead of
    once per prompt length."""
    b = max(int(floor), 1)
    while b < n:
        b *= 2
    return min(b, cap)


class SlotOverflow(RuntimeError):
    """An active slot was asked to write past its ``max_len`` capacity.

    The scheduler guards admission (prompt + max_new_tokens ≤ max_len), so
    reaching this means a bookkeeping bug, not a user error — the serving
    twin of the training path's sticky cache-overflow flag (models/gpt.py
    ADVICE r3: never silently clamp)."""


class SlotKVCache:
    """Fixed slot table + compiled prefill/decode programs for one GPTLM.

    ``model`` is the TRAINING-mode module (any attention impl); it is
    cloned into slot-decode mode exactly like ``generate`` clones into
    cursor-decode mode — dense cache attention, dropout off, Megatron TP
    layout kept when ``mesh`` has a 'model' axis and the model was
    partitioned.  ``params`` may be a TP engine's committed TrainState
    params (used in place) or host/single-device params (replicated).

    Host-side bookkeeping (`lengths`, `active`, `tokens`) lives on numpy:
    the scheduler owns admission/eviction and the decode step receives the
    vectors as arguments, so slot edits never touch device state except
    through the two compiled programs.
    """

    def __init__(self, model: GPTLM, params, slots: int, *,
                 mesh=None, greedy: bool = True, temperature: float = 1.0,
                 prefill_bucket: int = 8, rng=None, kv_dtype=None,
                 prefix_cache_blocks: int = 0, prefix_block: int = 16):
        if slots < 1:
            raise ValueError(f"slots must be positive, got {slots}")
        if prefix_cache_blocks < 0:
            raise ValueError(f"prefix_cache_blocks must be >= 0, got "
                             f"{prefix_cache_blocks}")
        if prefix_block < 1:
            raise ValueError(f"prefix_block must be positive, got "
                             f"{prefix_block}")
        self.slots = int(slots)
        self.max_len = int(model.max_len)
        self.greedy = bool(greedy)
        self.temperature = float(temperature)
        self.prefill_bucket = int(prefill_bucket)
        self.mesh = mesh
        # --serve-kv-dtype int8: the model stores K/V as int8 with one f32
        # max-abs scale per written vector (models/gpt.py kv_quant) — the
        # scale leaves ride the SAME cache pytree, so the slot dim shards
        # over 'data' exactly like the payload.  Quantize on write,
        # dequantize on the attention read; token parity vs the bf16
        # oracle is tolerance-based (greedy-token agreement), not bitwise.
        self.quantized = False
        if kv_dtype is not None:
            kv_dtype = jnp.dtype(kv_dtype)
            self.quantized = kv_dtype == jnp.dtype(jnp.int8)
        keep_tp = (mesh is not None and model.partition_model
                   and meshlib.MODEL_AXIS in mesh.axis_names)
        self.dm = model.clone(decode=True, decode_slots=True,
                              attention_impl="dense",
                              partition_model=keep_tp, dropout_rate=0.0,
                              kv_quant=self.quantized)
        self._rng = rng if rng is not None else jax.random.key(0)

        # zero slot cache from an abstract init — zeros-from-shape IS the
        # init value (same argument as models/gpt.py `generate`)
        dummy = jnp.zeros((self.slots, 1), jnp.int32)
        shapes = jax.eval_shape(
            lambda: self.dm.init(jax.random.key(0), dummy, train=False,
                                 positions=dummy))["cache"]
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        if kv_dtype is not None and not self.quantized:
            # --serve-kv-dtype bfloat16: store the K/V table narrower than
            # the model computes (bf16 halves KV memory → double the slots
            # per chip).  The model's slot-scatter writes cast to the
            # table's dtype (models/gpt.py) and the attention read
            # promotes back, so the decode program stays the one compiled
            # step.  (int8 needs no cast here — the kv_quant model
            # already initializes int8 payload + f32 scale leaves.)
            cache = jax.tree.map(
                lambda t: t.astype(kv_dtype)
                if jnp.issubdtype(t.dtype, jnp.floating) else t, cache)
        # the table's actual storage dtype, surfaced in the serve report
        # section (for int8 the first FLOAT leaf is a scale, so the name
        # is pinned explicitly; otherwise it is the K/V buffer dtype)
        self.kv_dtype = "int8" if self.quantized else next(
            (str(leaf.dtype) for leaf in jax.tree.leaves(cache)
             if jnp.issubdtype(leaf.dtype, jnp.floating)), "float32")

        self._vec_sharding = None
        self._blk_sharding = None
        if mesh is not None:
            dp = mesh.shape.get(meshlib.DATA_AXIS, 1)
            if self.slots % dp:
                raise ValueError(
                    f"slots ({self.slots}) must divide by the mesh's data "
                    f"axis ({dp}): each data shard owns a contiguous slot "
                    f"block")
            cache = jax.tree.map(
                lambda t: jax.device_put(t, meshlib.kv_slot_sharding(
                    mesh, t.ndim, shard_heads=keep_tp)), cache)
            self._vec_sharding = meshlib.kv_slot_sharding(mesh, 1)
            self._blk_sharding = meshlib.kv_slot_sharding(mesh, 2)
        self.cache = cache
        self.params = self._place_params(params)

        # host-side slot table.  ``reserved`` marks slots claimed by an
        # in-progress chunked admission (begin_insert): not free, but not
        # yet advanced by decode — lengths[] tracks the fill position.
        self.lengths = np.zeros(self.slots, np.int32)
        self.active = np.zeros(self.slots, np.bool_)
        self.reserved = np.zeros(self.slots, np.bool_)
        self.tokens = np.zeros(self.slots, np.int32)   # last token per slot
        self._pending: dict[int, dict] = {}            # slot → prefill state

        # block-aligned prefix pool (LRU over exact prefix-byte keys);
        # entries are the slot-slice KV of one block, stored at the table's
        # dtype so a hit writes back bitwise what the cold prefill wrote
        self.prefix_cache_blocks = int(prefix_cache_blocks)
        self.prefix_block = int(prefix_block)
        self._prefix_pool: OrderedDict[bytes, object] = OrderedDict()
        self.prefix_stats = {"hits": 0, "misses": 0, "evictions": 0,
                             "tokens_reused": 0, "inserted_blocks": 0}

        # prompt tokens actually fed through a prefill program (cached
        # prefix blocks are skipped, pad tokens not counted) — the
        # scheduler reads deltas of this for the prefill/decode token
        # split and the VirtualClock interference model
        self.prefill_tokens_computed = 0

        # host-observed seconds inside the compiled programs, per phase
        # (cumulative; the scheduler reads deltas per run) — the device
        # half of the per-request phase attribution: how much of a
        # window went to prefill programs vs decode steps
        self._phase_s = {"prefill_s": 0.0, "decode_s": 0.0}

        self._step = self._build_step()
        self._prefills: dict[int, object] = {}
        self._chunks: dict[int, object] = {}           # chunk-resume prefill
        self._verifies: dict[int, object] = {}         # speculative verify
        self._read_block = None                        # prefix-pool extract
        self._write_block = None                       # prefix-pool restore

    def _place_params(self, params):
        """Param placement rule (shared by __init__ and ``swap_params``):
        params committed to this table's mesh are used in place; anything
        else replicates (the `generate(mesh=...)` placement rule)."""
        if self.mesh is None:
            return params
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        target = mesh.devices.tolist()

        def place(t):
            sh = getattr(t, "sharding", None)
            if isinstance(sh, NamedSharding) and (
                    sh.mesh is mesh
                    or sh.mesh.devices.tolist() == target):
                return t
            return jax.device_put(t, repl)

        return jax.tree.map(place, params)

    def swap_params(self, params) -> None:
        """Zero-downtime weight hot-swap: replace the served params
        between compiled-program dispatches (serving/fleet.py drains a
        replica's in-flight slots first — KV written under the old params
        must never be decoded under the new ones).  The new tree must
        match the old one's structure/shapes/dtypes, so every compiled
        program (decode step, prefill buckets, chunk buckets, verify
        widths) stays a cache hit — a swap never recompiles."""
        old = jax.tree_util.tree_structure(self.params)
        new = jax.tree_util.tree_structure(params)
        if old != new:
            raise ValueError(
                "swap_params needs the same param tree structure as the "
                "served checkpoint (same model config) — a different "
                "architecture cannot hot-swap into live slots")
        mismatch = [
            f"{jax.tree_util.keystr(path)}: {a.shape}/{a.dtype} vs "
            f"{b.shape}/{b.dtype}"
            for (path, a), b in zip(
                jax.tree_util.tree_flatten_with_path(self.params)[0],
                jax.tree.leaves(params))
            if a.shape != b.shape or a.dtype != b.dtype]
        if mismatch:
            raise ValueError(
                f"swap_params shape/dtype mismatch (a swap must be a "
                f"compiled-program cache hit): {mismatch[:3]}")
        self.params = self._place_params(params)

    # ------------------------------------------------------------- programs
    def _sample(self, logits, rng):
        """(B, V) logits → (B,) token ids; greedy or temperature draw —
        the ONE sampling definition shared by prefill and decode."""
        if self.greedy:
            return logits.argmax(-1)
        return jax.random.categorical(
            rng, logits / max(self.temperature, 1e-6))

    def _build_step(self):
        dm = self.dm

        def step(params, cache, tokens, lengths, active, rng):
            # write index = current length; inactive (free) slots scatter
            # garbage into their own rows only, which the next insert's
            # prefill overwrites — validity is length-driven, so stale
            # positions are never attended
            logits, upd = dm.apply(
                {"params": params, "cache": cache}, tokens[:, None],
                train=False, positions=lengths[:, None], mutable=["cache"])
            nxt = self._sample(logits[:, -1], rng).astype(tokens.dtype)
            return upd["cache"], jnp.where(active, nxt, tokens)

        return jax.jit(step, donate_argnums=1)

    def _prefill(self, lpad: int):
        """Compiled prefill-insert for one padded prompt length.

        Slices slot ``slot`` out of every cache leaf, scans the padded
        prompt through the single-token slot-decode step (batch 1,
        positions 0..lpad-1), writes the slice back, and samples the FIRST
        generated token from the logits at the last REAL prompt position.
        Steps past ``prompt_len`` write garbage K/V beyond the slot's
        length — invisible under the length mask and overwritten as
        decoding advances (the same argument that makes free-slot scatter
        writes safe).  The decode step is untouched: admission never
        recompiles it."""
        dm = self.dm

        def prefill(params, cache, slot, tokens, prompt_len, rng):
            sub = jax.tree.map(
                lambda t: lax.dynamic_slice_in_dim(t, slot, 1, 0), cache)

            def body(c, xs):
                tok, t = xs
                logits, upd = dm.apply(
                    {"params": params, "cache": c}, tok[None, None],
                    train=False, positions=t[None, None],
                    mutable=["cache"])
                return upd["cache"], logits[0, -1]

            sub, all_logits = lax.scan(
                body, sub, (tokens, jnp.arange(lpad, dtype=jnp.int32)))
            last = jnp.take(all_logits, prompt_len - 1, axis=0)
            first = self._sample(last[None, :], rng)[0]
            cache = jax.tree.map(
                lambda full, s: lax.dynamic_update_slice_in_dim(
                    full, s, slot, 0), cache, sub)
            return cache, first.astype(tokens.dtype)

        return jax.jit(prefill, donate_argnums=1)

    def _chunk(self, lpad: int):
        """Compiled chunk-resumable prefill for one padded CHUNK length.

        Like ``_prefill`` but resumes at a traced ``start`` position
        (positions ``start .. start+lpad-1``), so one compile per
        power-of-two chunk bucket serves every resume point — a long
        prompt's admission becomes several short scans the scheduler can
        interleave with decode iterations.  ``n_valid`` is the chunk's
        real token count; the sampled token (logits at the last valid
        position) only matters on the FINAL chunk — it is the request's
        first generated token, exactly as in the monolithic prefill.
        Padding past ``n_valid`` writes garbage K/V that the next chunk
        (which starts at ``start+n_valid``) or decode overwrites, and
        out-of-range scatter rows are dropped — the same argument that
        makes monolithic pad writes safe."""
        dm = self.dm

        def chunk(params, cache, slot, tokens, start, n_valid, rng):
            sub = jax.tree.map(
                lambda t: lax.dynamic_slice_in_dim(t, slot, 1, 0), cache)

            def body(c, xs):
                tok, t = xs
                logits, upd = dm.apply(
                    {"params": params, "cache": c}, tok[None, None],
                    train=False, positions=t[None, None],
                    mutable=["cache"])
                return upd["cache"], logits[0, -1]

            sub, all_logits = lax.scan(
                body, sub,
                (tokens, start + jnp.arange(lpad, dtype=jnp.int32)))
            last = jnp.take(all_logits, n_valid - 1, axis=0)
            first = self._sample(last[None, :], rng)[0]
            cache = jax.tree.map(
                lambda full, s: lax.dynamic_update_slice_in_dim(
                    full, s, slot, 0), cache, sub)
            return cache, first.astype(tokens.dtype)

        return jax.jit(chunk, donate_argnums=1)

    def _verify(self, width: int):
        """Compiled speculative-verify step for one (slots, width) token
        block: per slot, ``width`` consecutive tokens (the committed
        pending token + width-1 draft proposals) enter at positions
        ``length .. length+width-1``; every position's K/V scatters into
        the cache and every position's logits take their greedy argmax in
        ONE batched slot-decode-style program (the models/gpt.py
        token-block contract — each query masked to positions ≤ its own).
        The host then ACCEPTS the longest draft prefix matching the
        argmaxes (``commit_block``); rejected positions stay in the
        buffer but are invalidated by length bookkeeping alone.  Greedy
        only: greedy acceptance is what makes speculative output bitwise
        identical to non-speculative decode."""
        dm = self.dm

        def verify(params, cache, block, lengths):
            positions = (lengths[:, None]
                         + jnp.arange(width, dtype=jnp.int32)[None, :])
            logits, upd = dm.apply(
                {"params": params, "cache": cache}, block,
                train=False, positions=positions, mutable=["cache"])
            return upd["cache"], logits.argmax(-1).astype(block.dtype)

        return jax.jit(verify, donate_argnums=1)

    def _block_ops(self):
        """Jitted prefix-pool block copy programs, compiled once each
        (slot/start are traced): ``read`` slices one block of a slot's KV
        out of every cache leaf; ``write`` scatters a pooled block back
        into a (possibly different) slot.  Cache leaves in slot-decode
        mode are (slots, max_len, kv_heads, head_dim) K/V buffers plus —
        under int8 storage — (slots, max_len, kv_heads) scale leaves, so
        the slices cover whatever trails the (slot, position) dims."""
        blk = self.prefix_block

        def read(cache, slot, start):
            return jax.tree.map(
                lambda t: lax.dynamic_slice(
                    t, (slot, start) + (0,) * (t.ndim - 2),
                    (1, blk) + t.shape[2:]), cache)

        def write(cache, entry, slot, start):
            return jax.tree.map(
                lambda t, e: lax.dynamic_update_slice(
                    t, e.astype(t.dtype),
                    (slot, start) + (0,) * (t.ndim - 2)),
                cache, entry)

        return jax.jit(read), jax.jit(write, donate_argnums=0)

    # ------------------------------------------------------------ slot API
    @property
    def free_slots(self) -> list[int]:
        return [i for i in range(self.slots)
                if not (self.active[i] or self.reserved[i])]

    def _put_vec(self, arr):
        arr = jnp.asarray(arr)
        if self._vec_sharding is not None:
            arr = jax.device_put(arr, self._vec_sharding)
        return arr

    def _put_repl(self, arr):
        """Replicated placement: the padded prompt is per-scan-step data,
        not a (slots,) vector — slot sharding would demand the padded
        length divide the data axis (it usually won't)."""
        arr = jnp.asarray(arr)
        if self.mesh is not None:
            arr = jax.device_put(arr, NamedSharding(self.mesh, P()))
        return arr

    def _next_rng(self):
        if self.greedy:
            return self._rng  # unused by the program; keep it static
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _claim_slot(self, prompt, slot: int | None) -> tuple[np.ndarray,
                                                             int, int]:
        """Shared admission validation: returns (prompt, lp, slot)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        lp = int(prompt.shape[0])
        if lp < 1:
            raise ValueError("prompt must hold at least one token")
        if lp >= self.max_len:
            raise ValueError(
                f"prompt length {lp} leaves no room to generate within "
                f"max_len={self.max_len}")
        if slot is None:
            free = self.free_slots
            if not free:
                raise RuntimeError("no free slot — evict before inserting")
            slot = free[0]
        elif self.active[slot] or self.reserved[slot]:
            raise RuntimeError(f"slot {slot} is active — evict it first")
        return prompt, lp, slot

    def insert(self, prompt, slot: int | None = None) -> tuple[int, int]:
        """Admit a prompt into a free slot (jitted prefill-insert).

        Returns ``(slot, first_token)`` — the first generated token is
        sampled by the prefill itself (its wall time IS the time-to-first-
        token), and the slot's length becomes ``len(prompt)``: the first
        decode step will write the returned token's K/V at that position.

        With the prefix pool enabled, admission routes through the
        chunk-resumable program (``begin_insert`` + one full-remainder
        ``prefill_chunk``) so prefill can start at the first uncached
        block; with the pool off, this is the byte-identical PR 7 path.
        """
        if self.prefix_cache_blocks:
            slot, _ = self.begin_insert(prompt, slot)
            try:
                first = self.prefill_chunk(slot)
            except BaseException:
                # the reservation is internal to this call — release
                # whichever state the slot reached so a failed admission
                # cannot leak it (a failure INSIDE the final chunk may
                # land after the slot already activated, e.g. in
                # _pool_prefix; aborting a no-longer-pending slot would
                # raise over the real error)
                if self.has_pending(slot):
                    self.abort_insert(slot)
                elif self.active[slot]:
                    self.evict(slot)
                raise
            assert first is not None  # uncapped chunk = whole remainder
            return slot, first
        prompt, lp, slot = self._claim_slot(prompt, slot)
        lpad = _bucket(lp, self.prefill_bucket, self.max_len)
        padded = np.zeros(lpad, np.int32)
        padded[:lp] = prompt
        if lpad not in self._prefills:
            self._prefills[lpad] = self._prefill(lpad)
        fn = self._prefills[lpad]
        t0 = time.perf_counter()
        self.cache, first = fn(
            self.params, self.cache, jnp.int32(slot),
            self._put_repl(padded), jnp.int32(lp), self._next_rng())
        self._phase_s["prefill_s"] += time.perf_counter() - t0
        self.prefill_tokens_computed += lp
        self.active[slot] = True
        self.lengths[slot] = lp
        self.tokens[slot] = first = int(first)
        return slot, first

    # ------------------------------------------- chunked (resumable) prefill
    def begin_insert(self, prompt,
                     slot: int | None = None) -> tuple[int, int]:
        """Claim a slot for a chunk-by-chunk admission; returns
        ``(slot, reused_tokens)``.

        The slot is RESERVED (not free, not decoded) until the final
        ``prefill_chunk`` activates it.  With the prefix pool enabled, the
        longest cached block-aligned prefix is copied into the slot here
        and ``reused_tokens`` positions are skipped — prefill resumes at
        the first uncached block.  At least the prompt's final token is
        always computed (its logits sample the first generated token)."""
        prompt, lp, slot = self._claim_slot(prompt, slot)
        reused = self._restore_prefix(prompt, lp, slot)
        self.reserved[slot] = True
        self.lengths[slot] = reused
        self._pending[slot] = {"prompt": prompt, "lp": lp, "filled": reused}
        return slot, reused

    def prefill_chunk(self, slot: int,
                      max_tokens: int | None = None) -> int | None:
        """Process the next ≤ ``max_tokens`` prompt tokens of a pending
        admission (one jitted chunk scan, compiled per power-of-two chunk
        bucket).  Returns the request's first generated token when this
        was the final chunk (the slot becomes active, exactly as after
        ``insert``), else None."""
        pend = self._pending.get(slot)
        if pend is None:
            raise RuntimeError(f"slot {slot} has no pending admission "
                               f"(begin_insert first)")
        filled, lp = pend["filled"], pend["lp"]
        n = lp - filled
        if max_tokens is not None:
            if max_tokens < 1:
                raise ValueError(
                    f"max_tokens must be positive, got {max_tokens}")
            n = min(n, int(max_tokens))
        final = filled + n == lp
        # chunk bucket floor is 1 (not prefill_bucket): budgets below the
        # admission floor must not round the chunk back up past the
        # scheduler's per-iteration token budget
        lpad = _bucket(n, 1, self.max_len)
        padded = np.zeros(lpad, np.int32)
        padded[:n] = pend["prompt"][filled:filled + n]
        if lpad not in self._chunks:
            self._chunks[lpad] = self._chunk(lpad)
        t0 = time.perf_counter()
        self.cache, first = self._chunks[lpad](
            self.params, self.cache, jnp.int32(slot),
            self._put_repl(padded), jnp.int32(filled), jnp.int32(n),
            self._next_rng())
        self._phase_s["prefill_s"] += time.perf_counter() - t0
        pend["filled"] = filled + n
        self.lengths[slot] = filled + n
        self.prefill_tokens_computed += n
        if not final:
            return None
        # materialize the token BEFORE flipping host state: a deferred
        # device error surfaces here while the slot is still pending, so
        # the caller's abort path sees a consistent table
        first = int(first)
        del self._pending[slot]
        self.reserved[slot] = False
        self.active[slot] = True
        self.lengths[slot] = lp
        self.tokens[slot] = first
        self._pool_prefix(pend["prompt"], lp, slot)
        return first

    def pending_tokens(self, slot: int) -> int:
        """Prompt tokens a pending admission still has to prefill."""
        pend = self._pending[slot]
        return pend["lp"] - pend["filled"]

    def has_pending(self, slot: int) -> bool:
        """Whether ``slot`` holds an in-progress (begin_insert) admission."""
        return slot in self._pending

    def abort_insert(self, slot: int) -> None:
        """Release a reserved slot whose admission will not complete (the
        scheduler's mid-run-failure cleanup path)."""
        if slot not in self._pending:
            raise RuntimeError(f"slot {slot} has no pending admission")
        del self._pending[slot]
        self.reserved[slot] = False
        self.lengths[slot] = 0

    # ------------------------------------------------------- prefix pool
    def _prefix_keys(self, prompt: np.ndarray, n_blocks: int):
        """Chained block keys: block b's key is SHA-256 of (block b-1's
        key ‖ block b's token bytes), so the 32-byte digest carries the
        FULL prefix identity — a block matches only when every block
        before it matched — at O(L) total work and constant key size
        (hashing the raw whole-prefix bytes per block would be O(L²)
        per admission and store megabytes of keys for long chains)."""
        blk = self.prefix_block
        keys, prev = [], b""
        for b in range(n_blocks):
            h = hashlib.sha256(prev)
            h.update(prompt[b * blk:(b + 1) * blk].tobytes())
            prev = h.digest()
            keys.append(prev)
        return keys

    def _restore_prefix(self, prompt: np.ndarray, lp: int,
                        slot: int) -> int:
        """Copy the longest cached block-aligned prefix into ``slot``;
        returns the number of reused token positions.  Reuse is capped at
        the blocks covering ``lp - 1`` tokens: the final prompt token is
        always recomputed so its logits can sample the first token."""
        if not self.prefix_cache_blocks:
            return 0
        blk = self.prefix_block
        usable = (lp - 1) // blk    # full blocks strictly before the tail
        insertable = lp // blk      # full blocks the prompt will pool
        keys = self._prefix_keys(prompt, usable)
        matched = 0
        for key in keys:
            if key not in self._prefix_pool:
                break
            matched += 1
        self.prefix_stats["hits"] += matched
        self.prefix_stats["misses"] += insertable - matched
        self.prefix_stats["tokens_reused"] += matched * blk
        if not matched:
            return 0
        if self._write_block is None:
            self._read_block, self._write_block = self._block_ops()
        for b, key in enumerate(keys[:matched]):
            self._prefix_pool.move_to_end(key)   # LRU touch
            self.cache = self._write_block(
                self.cache, self._prefix_pool[key], jnp.int32(slot),
                jnp.int32(b * blk))
        return matched * blk

    def _pool_prefix(self, prompt: np.ndarray, lp: int, slot: int) -> None:
        """After a completed prefill, pool every full block of the prompt
        not already cached (extracted from the slot's freshly-written KV),
        evicting least-recently-used entries past the pool bound."""
        if not self.prefix_cache_blocks:
            return
        blk = self.prefix_block
        if self._read_block is None:
            self._read_block, self._write_block = self._block_ops()
        for b, key in enumerate(self._prefix_keys(prompt, lp // blk)):
            if key in self._prefix_pool:
                self._prefix_pool.move_to_end(key)
                continue
            entry = self._read_block(
                self.cache, jnp.int32(slot), jnp.int32(b * blk))
            if self.mesh is not None:
                # pool entries replicate: a block extracted from one data
                # shard's slot row gets written into ANY slot later, so
                # leaving it pinned to the source shard would force XLA
                # into a resharding rematerialization on every hit
                repl = NamedSharding(self.mesh, P())
                entry = jax.tree.map(
                    lambda t: jax.device_put(t, repl), entry)
            self._prefix_pool[key] = entry
            self.prefix_stats["inserted_blocks"] += 1
        while len(self._prefix_pool) > self.prefix_cache_blocks:
            self._prefix_pool.popitem(last=False)
            self.prefix_stats["evictions"] += 1

    def prefix_cache_stats(self) -> dict | None:
        """Cumulative hit/miss/evict accounting (None when the pool is
        off).  ``hit_rate`` is block-level: reused blocks over reusable +
        pooled blocks."""
        if not self.prefix_cache_blocks:
            return None
        s = dict(self.prefix_stats)
        total = s["hits"] + s["misses"]
        s["cached_blocks"] = len(self._prefix_pool)
        s["hit_rate"] = s["hits"] / total if total else 0.0
        return s

    def reset_prefix_cache(self) -> None:
        """Drop pooled blocks and zero the accounting (bench windows call
        this so per-window hit rates are deterministic)."""
        self._prefix_pool.clear()
        for k in self.prefix_stats:
            self.prefix_stats[k] = 0

    def advance(self, only=None) -> np.ndarray:
        """One decode iteration: every ACTIVE slot consumes its last token
        and emits the next one; lengths advance by one.  Returns the
        (slots,) token vector — inactive rows carry their stale token.
        The jitted step is compiled exactly once per cache shape.

        ``only`` restricts the iteration to a (slots,) bool subset of the
        active slots (the speculative draft's catch-up step: after a
        fully-accepted round only those slots must consume one more
        committed token).  Excluded rows keep their token and length —
        their row still receives a scatter write at its current length,
        which is invisible (length-driven validity) and overwritten by
        that slot's next real write, the free-slot-scatter argument."""
        mask = self.active if only is None else np.asarray(only, np.bool_)
        live = self.lengths[mask]
        if live.size and int(live.max()) >= self.max_len:
            raise SlotOverflow(
                f"active slot at length {int(live.max())} would write past "
                f"max_len={self.max_len}; the scheduler must bound "
                f"prompt + max_new_tokens at admission")
        t0 = time.perf_counter()
        self.cache, nxt = self._step(
            self.params, self.cache, self._put_vec(self.tokens),
            self._put_vec(self.lengths),
            self._put_vec(mask), self._next_rng())
        nxt = np.asarray(nxt)
        self._phase_s["decode_s"] += time.perf_counter() - t0
        self.lengths[mask] += 1
        self.tokens = nxt.astype(np.int32)
        return nxt

    # ------------------------------------------------- speculative decode
    def verify_block(self, block) -> np.ndarray:
        """Score a (slots, width) token block in one batched step and
        return the (slots, width) per-position greedy argmax tokens.

        Per slot, ``block[s] = [pending_token, d_1, .., d_{width-1}]`` —
        the committed pending token followed by draft proposals; K/V for
        all ``width`` positions is written at ``length .. length+width-1``
        and the returned row ``g`` satisfies: ``g[j]`` is the target's
        greedy token after consuming ``block[s, :j+1]``.  Greedy
        acceptance (``commit_block``) then takes the longest prefix with
        ``d_{j+1} == g[j]`` plus the target's own next token — bitwise
        what non-speculative decode would have emitted.  Host bookkeeping
        (lengths/tokens) is NOT touched here: the scheduler owns
        acceptance, and rejected positions are rolled back by length
        bookkeeping alone (no KV rewrite)."""
        if not self.greedy:
            raise ValueError(
                "verify_block requires greedy sampling: the exact "
                "acceptance rule (accept while draft == target argmax) "
                "only exists for greedy decode")
        block = np.asarray(block, np.int32)
        if block.ndim != 2 or block.shape[0] != self.slots:
            raise ValueError(
                f"block must be (slots, width) = ({self.slots}, k+1), "
                f"got {block.shape}")
        width = int(block.shape[1])
        live = self.lengths[self.active]
        if live.size and int(live.max()) + width > self.max_len:
            raise SlotOverflow(
                f"verify width {width} at length {int(live.max())} would "
                f"write past max_len={self.max_len}; the scheduler must "
                f"cap the draft k by remaining slot capacity")
        if width not in self._verifies:
            self._verifies[width] = self._verify(width)
        blk = jnp.asarray(block)
        if self._blk_sharding is not None:
            blk = jax.device_put(blk, self._blk_sharding)
        t0 = time.perf_counter()
        self.cache, g = self._verifies[width](
            self.params, self.cache, blk, self._put_vec(self.lengths))
        g = np.asarray(g).astype(np.int32)
        self._phase_s["decode_s"] += time.perf_counter() - t0
        return g

    def commit_block(self, slot: int, n: int, last_token: int) -> None:
        """Commit ``n`` verified positions of the last ``verify_block``
        into ``slot``: lengths advance by ``n`` and ``last_token`` (the
        target's own token at the acceptance point) becomes the slot's
        pending token.  This IS the rollback path for rejected draft
        positions: the verify wrote K/V for the whole block, but validity
        is length-driven, so advancing by only the accepted count
        invalidates the rejected tail with no KV rewrite — the slot's
        next write simply lands over it."""
        if not self.active[slot]:
            raise RuntimeError(f"slot {slot} is not active")
        if n < 1:
            raise ValueError(f"commit_block needs n >= 1, got {n}")
        if int(self.lengths[slot]) + n > self.max_len:
            raise SlotOverflow(
                f"committing {n} positions at length "
                f"{int(self.lengths[slot])} exceeds max_len={self.max_len}")
        self.lengths[slot] += n
        self.tokens[slot] = int(last_token)

    def rewind(self, slot: int, length: int, token: int) -> None:
        """Rewind a slot's validity to ``length`` and set its pending
        token — the DRAFT table's resync after a verify round: positions
        past ``length`` were speculative writes, invalidated here by
        length bookkeeping alone.  A rewind can never extend validity."""
        if not self.active[slot]:
            raise RuntimeError(f"slot {slot} is not active")
        if length > int(self.lengths[slot]):
            raise ValueError(
                f"rewind cannot extend validity: slot {slot} is at "
                f"{int(self.lengths[slot])}, asked for {length}")
        self.lengths[slot] = int(length)
        self.tokens[slot] = int(token)

    def evict(self, slot: int) -> None:
        """Free a slot.  Pure host bookkeeping: stale K/V stays in the
        buffer but is unreachable (validity is length-driven) and the next
        insert's prefill overwrites it from position 0."""
        if not self.active[slot]:
            raise RuntimeError(f"slot {slot} is not active")
        self.active[slot] = False
        self.lengths[slot] = 0
        self.tokens[slot] = 0

    def phase_times(self) -> dict[str, float]:
        """Cumulative host-observed seconds inside the compiled prefill
        (monolithic + chunk) and decode programs — the device-side phase
        timestamps behind the scheduler's ``device_phase_s`` split.  Host-
        observed: each program's result is materialized before the next
        scheduling decision, so dispatch + device wait both land here."""
        return dict(self._phase_s)

    def kv_bytes_per_slot(self) -> int:
        """Stored KV-table bytes per serving slot: every cache leaf —
        K/V payload plus, under int8 storage, its f32 scale leaves —
        divided by the slot count.  THE capacity number behind
        ``--serve-kv-dtype``: bf16 halves f32; int8 halves bf16's payload
        again, plus a per-written-vector scale overhead of 4/head_dim
        (the serve section carries it as ``serve_kv_bytes_per_slot``,
        gated lower-is-better by `analyze diff`)."""
        total = sum(int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
                    for leaf in jax.tree.leaves(self.cache))
        return total // self.slots

    def compiled_programs(self) -> dict[str, int]:
        """The recompile-freedom invariant the tests pin down: one decode
        step, one prefill program per power-of-two bucket, one chunk
        program per power-of-two CHUNK bucket, at most the two prefix
        block-copy programs, and one speculative-verify program per block
        width actually used.  With chunking, the prefix pool and
        speculative decoding off, the chunk/block/verify counts are 0 and
        the compiled set is exactly PR 7's."""
        return {"decode_steps": 1,
                "prefill_buckets": len(self._prefills),
                "prefill_chunk_buckets": len(self._chunks),
                "prefix_block_ops": (0 if self._read_block is None else 2),
                "verify_widths": len(self._verifies)}
