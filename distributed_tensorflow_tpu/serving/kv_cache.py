"""Slot-based sharded KV cache: the device half of the serving engine.

Static batching idles the chip on every finished sequence — a batch of
requests decodes at the pace of its longest member, and admitting a new
request means restarting ``generate`` from scratch.  Continuous batching
(Orca/vLLM-style in-flight batching) fixes that by making the *batch slot*,
not the batch, the unit of scheduling: the KV cache is a fixed table of
``slots`` independent sequences, each with its own length, and ONE compiled
single-token decode step advances every active slot regardless of age.
Admission and eviction are per-slot edits between decode iterations — the
decode program never recompiles.

Device-side contract (everything else lives in serving/scheduler.py):

* the cache is a pytree of ``(slots, max_len, kv_heads, head_dim)`` leaves
  (models/gpt.py slot-decode mode — deliberately no scalar cursors, so
  every leaf shards the slot dim over the mesh's ``data`` axis and, for
  tensor-parallel models, the kv-head dim over ``model``;
  parallel/mesh.py ``kv_slot_sharding``);
* ``advance`` is the one jitted decode step: (tokens, lengths, active)
  vectors in, next tokens out, cache donated through;
* ``insert`` is a jitted prefill that feeds a new request's prompt through
  the SAME per-token decode math inside a ``lax.scan`` over the padded
  prompt, against only that slot's cache slice (batch 1), then writes the
  slice back — compiled once per padded length bucket (powers of two), so
  steady-state admission never triggers XLA;
* ``begin_insert``/``prefill_chunk`` split that admission into fixed
  token-budget chunks (Sarathi-Serve, arXiv:2403.02310): each chunk resumes
  at the slot's fill position (the chunk program takes a traced ``start``,
  so ONE compile per power-of-two chunk-length bucket serves every resume
  point), and the scheduler interleaves at most one chunk per decode
  iteration — live slots keep emitting tokens while a long prompt fills;
* the optional **prefix pool** (vLLM PagedAttention's block-granular KV
  reuse, arXiv:2309.06180) caches block-aligned prompt-prefix KV keyed by
  the exact token bytes of the prefix: on admission the longest cached
  prefix is copied into the slot and prefill starts at the first uncached
  block, with hit/miss/evict accounting and bounded LRU eviction.

Greedy slot decode is token-identical to the sequential ``generate``
sampler per request (tests/test_serving.py): prefill-at-position-t and
decode-at-cursor-t run the same dense cache attention with the same
length-driven validity mask.  Chunked prefill is bitwise-identical to
monolithic prefill (each token's forward depends only on cache positions
below its own, all written by earlier chunks), and a prefix-cache hit is
bitwise-identical to recomputation (the pooled KV is a byte copy of what
the cold prefill would write).

Round 14 adds the two raw-decode-speed levers (ROADMAP item 3):
``verify_block``/``commit_block``/``rewind`` — the speculative-decode
device step (one batched program scores a (slots, k+1) token block;
acceptance/rollback is length bookkeeping alone, the same
validity-is-length-driven argument as chunk resume) — and
``kv_dtype='int8'`` — K/V stored int8 with one f32 max-abs scale per
written vector, the scale leaves riding the same sharded cache pytree
(``kv_bytes_per_slot`` is the capacity number; token parity vs the bf16
oracle is tolerance-based, the one serving feature with that caveat).
"""

from __future__ import annotations

import hashlib
import time
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.models.gpt import GPTLM
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def _bucket(n: int, floor: int, cap: int) -> int:
    """Smallest power-of-two ≥ max(n, floor), capped at ``cap`` — the
    padded prompt length, so prefill compiles once per bucket instead of
    once per prompt length."""
    b = max(int(floor), 1)
    while b < n:
        b *= 2
    return min(b, cap)


class SlotOverflow(RuntimeError):
    """An active slot was asked to write past its ``max_len`` capacity.

    The scheduler guards admission (prompt + max_new_tokens ≤ max_len), so
    reaching this means a bookkeeping bug, not a user error — the serving
    twin of the training path's sticky cache-overflow flag (models/gpt.py
    ADVICE r3: never silently clamp)."""


class BlockPoolExhausted(RuntimeError):
    """The paged KV block pool has no free physical block for a required
    write.  Admission-level pool pressure is the SCHEDULER's problem
    (``PagedSlotKVCache.can_admit`` + block budgets defer admissions);
    reaching this mid-flight means the block accounting is broken — the
    paged twin of ``SlotOverflow``, not an overload signal."""


class SlotKVCache:
    """Fixed slot table + compiled prefill/decode programs for one GPTLM.

    ``model`` is the TRAINING-mode module (any attention impl); it is
    cloned into slot-decode mode exactly like ``generate`` clones into
    cursor-decode mode — dense cache attention, dropout off, Megatron TP
    layout kept when ``mesh`` has a 'model' axis and the model was
    partitioned.  ``params`` may be a TP engine's committed TrainState
    params (used in place) or host/single-device params (replicated).

    Host-side bookkeeping (`lengths`, `active`, `tokens`) lives on numpy:
    the scheduler owns admission/eviction and the decode step receives the
    vectors as arguments, so slot edits never touch device state except
    through the two compiled programs.
    """

    def __new__(cls, *args, kv_layout: str = "monolithic", **kwargs):
        # --serve-kv-layout dispatch: constructing a SlotKVCache with
        # kv_layout="paged" yields the paged subclass, so every call site
        # (harness, bench, fleet's build_replica_kvs **kv_kwargs
        # pass-through) selects the layout with one kwarg and no factory
        if cls is SlotKVCache and kv_layout == "paged":
            return super().__new__(PagedSlotKVCache)
        return super().__new__(cls)

    def __init__(self, model: GPTLM, params, slots: int, *,
                 mesh=None, greedy: bool = True, temperature: float = 1.0,
                 prefill_bucket: int = 8, rng=None, kv_dtype=None,
                 prefix_cache_blocks: int = 0, prefix_block: int = 16,
                 kv_layout: str = "monolithic", paged_blocks: int = 0,
                 paged_block: int = 0, paged_fused: bool = True,
                 ledger=None):
        if kv_layout not in ("monolithic", "paged"):
            raise ValueError(
                f"kv_layout must be 'monolithic' or 'paged', "
                f"got {kv_layout!r}")
        if paged_blocks or paged_block:
            raise ValueError(
                "paged_blocks/paged_block only apply to "
                "kv_layout='paged'")
        self.kv_layout = "monolithic"
        # --timeline's XLA memory/compile ledger: when attached, every
        # compiled program routes through ledger.jit (same program, AOT-
        # observed); None keeps the literal jax.jit path byte-identical
        self._ledger = ledger
        if slots < 1:
            raise ValueError(f"slots must be positive, got {slots}")
        if prefix_cache_blocks < 0:
            raise ValueError(f"prefix_cache_blocks must be >= 0, got "
                             f"{prefix_cache_blocks}")
        if prefix_block < 1:
            raise ValueError(f"prefix_block must be positive, got "
                             f"{prefix_block}")
        self.slots = int(slots)
        self.max_len = int(model.max_len)
        self.greedy = bool(greedy)
        self.temperature = float(temperature)
        self.prefill_bucket = int(prefill_bucket)
        self.mesh = mesh
        # --serve-kv-dtype int8: the model stores K/V as int8 with one f32
        # max-abs scale per written vector (models/gpt.py kv_quant) — the
        # scale leaves ride the SAME cache pytree, so the slot dim shards
        # over 'data' exactly like the payload.  Quantize on write,
        # dequantize on the attention read; token parity vs the bf16
        # oracle is tolerance-based (greedy-token agreement), not bitwise.
        self.quantized = False
        if kv_dtype is not None:
            kv_dtype = jnp.dtype(kv_dtype)
            self.quantized = kv_dtype == jnp.dtype(jnp.int8)
        keep_tp = (mesh is not None and model.partition_model
                   and meshlib.MODEL_AXIS in mesh.axis_names)
        self.dm = model.clone(decode=True, decode_slots=True,
                              attention_impl="dense",
                              partition_model=keep_tp, dropout_rate=0.0,
                              kv_quant=self.quantized)
        self._rng = rng if rng is not None else jax.random.key(0)

        # zero slot cache from an abstract init — zeros-from-shape IS the
        # init value (same argument as models/gpt.py `generate`)
        dummy = jnp.zeros((self.slots, 1), jnp.int32)
        shapes = jax.eval_shape(
            lambda: self.dm.init(jax.random.key(0), dummy, train=False,
                                 positions=dummy))["cache"]
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        if kv_dtype is not None and not self.quantized:
            # --serve-kv-dtype bfloat16: store the K/V table narrower than
            # the model computes (bf16 halves KV memory → double the slots
            # per chip).  The model's slot-scatter writes cast to the
            # table's dtype (models/gpt.py) and the attention read
            # promotes back, so the decode program stays the one compiled
            # step.  (int8 needs no cast here — the kv_quant model
            # already initializes int8 payload + f32 scale leaves.)
            cache = jax.tree.map(
                lambda t: t.astype(kv_dtype)
                if jnp.issubdtype(t.dtype, jnp.floating) else t, cache)
        # the table's actual storage dtype, surfaced in the serve report
        # section (for int8 the first FLOAT leaf is a scale, so the name
        # is pinned explicitly; otherwise it is the K/V buffer dtype)
        self.kv_dtype = "int8" if self.quantized else next(
            (str(leaf.dtype) for leaf in jax.tree.leaves(cache)
             if jnp.issubdtype(leaf.dtype, jnp.floating)), "float32")

        self._vec_sharding = None
        self._blk_sharding = None
        if mesh is not None:
            dp = mesh.shape.get(meshlib.DATA_AXIS, 1)
            if self.slots % dp:
                raise ValueError(
                    f"slots ({self.slots}) must divide by the mesh's data "
                    f"axis ({dp}): each data shard owns a contiguous slot "
                    f"block")
            cache = jax.tree.map(
                lambda t: jax.device_put(t, meshlib.kv_slot_sharding(
                    mesh, t.ndim, shard_heads=keep_tp)), cache)
            self._vec_sharding = meshlib.kv_slot_sharding(mesh, 1)
            self._blk_sharding = meshlib.kv_slot_sharding(mesh, 2)
        self.cache = cache
        self.params = self._place_params(params)

        # host-side slot table.  ``reserved`` marks slots claimed by an
        # in-progress chunked admission (begin_insert): not free, but not
        # yet advanced by decode — lengths[] tracks the fill position.
        self.lengths = np.zeros(self.slots, np.int32)
        self.active = np.zeros(self.slots, np.bool_)
        self.reserved = np.zeros(self.slots, np.bool_)
        self.tokens = np.zeros(self.slots, np.int32)   # last token per slot
        self._pending: dict[int, dict] = {}            # slot → prefill state
        self._init_multi_state()

        # block-aligned prefix pool (LRU over exact prefix-byte keys);
        # entries are the slot-slice KV of one block, stored at the table's
        # dtype so a hit writes back bitwise what the cold prefill wrote
        self.prefix_cache_blocks = int(prefix_cache_blocks)
        self.prefix_block = int(prefix_block)
        self._prefix_pool: OrderedDict[bytes, object] = OrderedDict()
        self.prefix_stats = {"hits": 0, "misses": 0, "evictions": 0,
                             "tokens_reused": 0, "inserted_blocks": 0}

        # prompt tokens actually fed through a prefill program (cached
        # prefix blocks are skipped, pad tokens not counted) — the
        # scheduler reads deltas of this for the prefill/decode token
        # split and the VirtualClock interference model
        self.prefill_tokens_computed = 0

        # host-observed seconds inside the compiled programs, per phase
        # (cumulative; the scheduler reads deltas per run) — the device
        # half of the per-request phase attribution: how much of a
        # window went to prefill programs vs decode steps
        self._phase_s = {"prefill_s": 0.0, "decode_s": 0.0}

        self._step = self._build_step()
        self._prefills: dict[int, object] = {}
        self._chunks: dict[int, object] = {}           # chunk-resume prefill
        self._verifies: dict[int, object] = {}         # speculative verify
        self._read_block = None                        # prefix-pool extract
        self._write_block = None                       # prefix-pool restore
        self._handoff_read = None                      # disagg KV handoff
        self._handoff_write = None

    def _init_multi_state(self) -> None:
        """Shared (monolithic + paged) init for the device-resident
        vector cache and the multi-step decode state.

        ``_dev_vecs`` is the value-keyed host→device cache behind
        ``_dev_cached``: slot vectors (tokens/lengths/mask) stay on
        device between iterations and re-upload only when the host VALUE
        changed — the explicit host-mirror sync point.  ``eos_tok`` /
        ``budget`` arm the fused program's in-device deactivation
        (-1 = no EOS, 0 = unlimited budget — the draft table's mode);
        ``halted`` mirrors slots the device stopped advancing that the
        scheduler has not yet evicted (occupancy ``active`` is separate);
        ``dispatch_count`` counts every compiled-program host call."""
        self.eos_tok = np.full(self.slots, -1, np.int32)
        self.budget = np.zeros(self.slots, np.int32)
        self.halted = np.zeros(self.slots, np.bool_)
        self.dispatch_count = 0
        self._dev_vecs: dict[str, tuple[np.ndarray, object]] = {}
        self._multis: dict[int, object] = {}    # k → fused decode program
        self._multi_state = None    # device carry after the last dispatch
        self._multi_snap = None     # host view at the last dispatch
        self._multi_pending: list[dict] = []    # in-flight rounds (FIFO)
        self._inflight = np.zeros(self.slots, np.int32)

    def _place_params(self, params):
        """Param placement rule (shared by __init__ and ``swap_params``):
        params committed to this table's mesh are used in place; anything
        else replicates (the `generate(mesh=...)` placement rule)."""
        if self.mesh is None:
            return params
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        target = mesh.devices.tolist()

        def place(t):
            sh = getattr(t, "sharding", None)
            if isinstance(sh, NamedSharding) and (
                    sh.mesh is mesh
                    or sh.mesh.devices.tolist() == target):
                return t
            return jax.device_put(t, repl)

        return jax.tree.map(place, params)

    def swap_params(self, params) -> None:
        """Zero-downtime weight hot-swap: replace the served params
        between compiled-program dispatches (serving/fleet.py drains a
        replica's in-flight slots first — KV written under the old params
        must never be decoded under the new ones).  The new tree must
        match the old one's structure/shapes/dtypes, so every compiled
        program (decode step, prefill buckets, chunk buckets, verify
        widths) stays a cache hit — a swap never recompiles."""
        old = jax.tree_util.tree_structure(self.params)
        new = jax.tree_util.tree_structure(params)
        if old != new:
            raise ValueError(
                "swap_params needs the same param tree structure as the "
                "served checkpoint (same model config) — a different "
                "architecture cannot hot-swap into live slots")
        mismatch = [
            f"{jax.tree_util.keystr(path)}: {a.shape}/{a.dtype} vs "
            f"{b.shape}/{b.dtype}"
            for (path, a), b in zip(
                jax.tree_util.tree_flatten_with_path(self.params)[0],
                jax.tree.leaves(params))
            if a.shape != b.shape or a.dtype != b.dtype]
        if mismatch:
            raise ValueError(
                f"swap_params shape/dtype mismatch (a swap must be a "
                f"compiled-program cache hit): {mismatch[:3]}")
        self.params = self._place_params(params)

    # ------------------------------------------------------------- programs
    def _jit(self, fn, name: str, **jit_kwargs):
        """``jax.jit`` or the ledger's observed jit — the ONE dispatch
        point deciding whether compiles are measured, and the ONE place
        every compiled-program host call is counted (``dispatch_count``,
        the denominator behind ``serve_dispatches``: the multi-step win
        is fewer of exactly these).  With no ledger the builtin runs
        underneath, so the flag-off compiled-program set is byte-
        identical (the parity pin — the counting closure is host Python,
        it compiles nothing)."""
        if self._ledger is None:
            compiled = jax.jit(fn, **jit_kwargs)
        else:
            compiled = self._ledger.jit(fn, name=name, **jit_kwargs)

        def dispatch(*args, **kwargs):
            self.dispatch_count += 1
            return compiled(*args, **kwargs)

        return dispatch

    def _sample(self, logits, rng):
        """(B, V) logits → (B,) token ids; greedy or temperature draw —
        the ONE sampling definition shared by prefill and decode."""
        if self.greedy:
            return logits.argmax(-1)
        return jax.random.categorical(
            rng, logits / max(self.temperature, 1e-6))

    def _build_step(self):
        dm = self.dm

        def step(params, cache, tokens, lengths, active, rng):
            # write index = current length; inactive (free) slots scatter
            # garbage into their own rows only, which the next insert's
            # prefill overwrites — validity is length-driven, so stale
            # positions are never attended.  The advanced token AND
            # length vectors are program outputs so the next iteration
            # can consume them on device (`_dev_learn`) instead of
            # re-uploading host mirrors.
            logits, upd = dm.apply(
                {"params": params, "cache": cache}, tokens[:, None],
                train=False, positions=lengths[:, None], mutable=["cache"])
            nxt = self._sample(logits[:, -1], rng).astype(tokens.dtype)
            return (upd["cache"], jnp.where(active, nxt, tokens),
                    jnp.where(active, lengths + 1, lengths))

        return self._jit(step, "kv_decode_step", donate_argnums=1)

    def _prefill(self, lpad: int):
        """Compiled prefill-insert for one padded prompt length.

        Slices slot ``slot`` out of every cache leaf, scans the padded
        prompt through the single-token slot-decode step (batch 1,
        positions 0..lpad-1), writes the slice back, and samples the FIRST
        generated token from the logits at the last REAL prompt position.
        Steps past ``prompt_len`` write garbage K/V beyond the slot's
        length — invisible under the length mask and overwritten as
        decoding advances (the same argument that makes free-slot scatter
        writes safe).  The decode step is untouched: admission never
        recompiles it."""
        dm = self.dm

        def prefill(params, cache, slot, tokens, prompt_len, rng):
            sub = jax.tree.map(
                lambda t: lax.dynamic_slice_in_dim(t, slot, 1, 0), cache)

            def body(c, xs):
                tok, t = xs
                logits, upd = dm.apply(
                    {"params": params, "cache": c}, tok[None, None],
                    train=False, positions=t[None, None],
                    mutable=["cache"])
                return upd["cache"], logits[0, -1]

            sub, all_logits = lax.scan(
                body, sub, (tokens, jnp.arange(lpad, dtype=jnp.int32)))
            last = jnp.take(all_logits, prompt_len - 1, axis=0)
            first = self._sample(last[None, :], rng)[0]
            cache = jax.tree.map(
                lambda full, s: lax.dynamic_update_slice_in_dim(
                    full, s, slot, 0), cache, sub)
            return cache, first.astype(tokens.dtype)

        return self._jit(prefill, f"kv_prefill_l{lpad}", donate_argnums=1)

    def _chunk(self, lpad: int):
        """Compiled chunk-resumable prefill for one padded CHUNK length.

        Like ``_prefill`` but resumes at a traced ``start`` position
        (positions ``start .. start+lpad-1``), so one compile per
        power-of-two chunk bucket serves every resume point — a long
        prompt's admission becomes several short scans the scheduler can
        interleave with decode iterations.  ``n_valid`` is the chunk's
        real token count; the sampled token (logits at the last valid
        position) only matters on the FINAL chunk — it is the request's
        first generated token, exactly as in the monolithic prefill.
        Padding past ``n_valid`` writes garbage K/V that the next chunk
        (which starts at ``start+n_valid``) or decode overwrites, and
        out-of-range scatter rows are dropped — the same argument that
        makes monolithic pad writes safe."""
        dm = self.dm

        def chunk(params, cache, slot, tokens, start, n_valid, rng):
            sub = jax.tree.map(
                lambda t: lax.dynamic_slice_in_dim(t, slot, 1, 0), cache)

            def body(c, xs):
                tok, t = xs
                logits, upd = dm.apply(
                    {"params": params, "cache": c}, tok[None, None],
                    train=False, positions=t[None, None],
                    mutable=["cache"])
                return upd["cache"], logits[0, -1]

            sub, all_logits = lax.scan(
                body, sub,
                (tokens, start + jnp.arange(lpad, dtype=jnp.int32)))
            last = jnp.take(all_logits, n_valid - 1, axis=0)
            first = self._sample(last[None, :], rng)[0]
            cache = jax.tree.map(
                lambda full, s: lax.dynamic_update_slice_in_dim(
                    full, s, slot, 0), cache, sub)
            return cache, first.astype(tokens.dtype)

        return self._jit(chunk, f"kv_prefill_chunk_l{lpad}",
                         donate_argnums=1)

    def _verify(self, width: int):
        """Compiled speculative-verify step for one (slots, width) token
        block: per slot, ``width`` consecutive tokens (the committed
        pending token + width-1 draft proposals) enter at positions
        ``length .. length+width-1``; every position's K/V scatters into
        the cache and every position's logits take their greedy argmax in
        ONE batched slot-decode-style program (the models/gpt.py
        token-block contract — each query masked to positions ≤ its own).
        The host then ACCEPTS the longest draft prefix matching the
        argmaxes (``commit_block``); rejected positions stay in the
        buffer but are invalidated by length bookkeeping alone.  Greedy
        only: greedy acceptance is what makes speculative output bitwise
        identical to non-speculative decode."""
        dm = self.dm

        def verify(params, cache, block, lengths):
            positions = (lengths[:, None]
                         + jnp.arange(width, dtype=jnp.int32)[None, :])
            logits, upd = dm.apply(
                {"params": params, "cache": cache}, block,
                train=False, positions=positions, mutable=["cache"])
            return upd["cache"], logits.argmax(-1).astype(block.dtype)

        return self._jit(verify, f"kv_verify_w{width}", donate_argnums=1)

    def _block_ops(self):
        """Jitted prefix-pool block copy programs, compiled once each
        (slot/start are traced): ``read`` slices one block of a slot's KV
        out of every cache leaf; ``write`` scatters a pooled block back
        into a (possibly different) slot.  Cache leaves in slot-decode
        mode are (slots, max_len, kv_heads, head_dim) K/V buffers plus —
        under int8 storage — (slots, max_len, kv_heads) scale leaves, so
        the slices cover whatever trails the (slot, position) dims."""
        blk = self.prefix_block

        def read(cache, slot, start):
            return jax.tree.map(
                lambda t: lax.dynamic_slice(
                    t, (slot, start) + (0,) * (t.ndim - 2),
                    (1, blk) + t.shape[2:]), cache)

        def write(cache, entry, slot, start):
            return jax.tree.map(
                lambda t, e: lax.dynamic_update_slice(
                    t, e.astype(t.dtype),
                    (slot, start) + (0,) * (t.ndim - 2)),
                cache, entry)

        return (self._jit(read, "kv_prefix_read_block"),
                self._jit(write, "kv_prefix_write_block", donate_argnums=0))

    def _handoff_block(self) -> int:
        """Block granularity of the handoff transfer format.  Prefers the
        prefix-pool block size (so a handoff payload is the same shape a
        pool entry would be) but falls back to one whole-row block when
        ``prefix_block`` does not divide ``max_len`` — a partial tail
        block would make ``dynamic_slice`` clamp its start and silently
        read shifted positions."""
        return (self.prefix_block if self.max_len % self.prefix_block == 0
                else self.max_len)

    def _handoff_ops(self):
        """Jitted handoff block copy programs (compiled once each;
        slot/start are traced) — the ``_block_ops`` machinery pointed at
        the disaggregated prefill→decode transfer: ``read`` slices one
        handoff block of a slot's KV out of every cache leaf, ``write``
        scatters a transferred block into the receiving table's slot.
        int8 scale leaves are cache leaves like any other, so they ride
        the same tree map and the restored KV is byte-exact."""
        hb = self._handoff_block()

        def read(cache, slot, start):
            return jax.tree.map(
                lambda t: lax.dynamic_slice(
                    t, (slot, start) + (0,) * (t.ndim - 2),
                    (1, hb) + t.shape[2:]), cache)

        def write(cache, entry, slot, start):
            return jax.tree.map(
                lambda t, e: lax.dynamic_update_slice(
                    t, e.astype(t.dtype),
                    (slot, start) + (0,) * (t.ndim - 2)),
                cache, entry)

        return (self._jit(read, "kv_handoff_read_block"),
                self._jit(write, "kv_handoff_write_block",
                          donate_argnums=0))

    # ------------------------------------------------------------ slot API
    @property
    def free_slots(self) -> list[int]:
        return [i for i in range(self.slots)
                if not (self.active[i] or self.reserved[i])]

    def _put_vec(self, arr):
        arr = jnp.asarray(arr)
        if self._vec_sharding is not None:
            arr = jax.device_put(arr, self._vec_sharding)
        return arr

    def _put_repl(self, arr):
        """Replicated placement: the padded prompt is per-scan-step data,
        not a (slots,) vector — slot sharding would demand the padded
        length divide the data axis (it usually won't)."""
        arr = jnp.asarray(arr)
        if self.mesh is not None:
            arr = jax.device_put(arr, NamedSharding(self.mesh, P()))
        return arr

    def _dev_cached(self, name: str, host, put=None):
        """Device copy of a host slot vector, re-uploaded only when the
        host VALUE changed since the copy was learned — the k=1 decode
        loop, the draft table and the fused multi-step dispatch all stop
        paying a per-iteration H2D upload for tokens/lengths/mask.  The
        cache is value-keyed, not identity-keyed: any host-side edit
        (admission, evict, commit_block, rewind) is caught by comparison
        at the next dispatch, which IS the explicit host→device sync
        point."""
        host = np.asarray(host)
        hit = self._dev_vecs.get(name)
        if hit is not None and hit[0].shape == host.shape \
                and np.array_equal(hit[0], host):
            return hit[1]
        dev = (self._put_vec if put is None else put)(host)
        self._dev_vecs[name] = (host.copy(), dev)
        return dev

    def _dev_learn(self, name: str, host, dev) -> None:
        """Adopt a program OUTPUT as the device copy for ``name``: the
        caller updated the host mirror to the same value, so the next
        ``_dev_cached`` hit costs zero uploads."""
        self._dev_vecs[name] = (np.asarray(host).copy(), dev)

    def _next_rng(self):
        if self.greedy:
            return self._rng  # unused by the program; keep it static
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _multi_rngs(self, k: int):
        """(k,)-stacked per-iteration rng keys for a fused dispatch.
        Greedy replicates the static key (the program never reads it);
        sampling advances the split chain exactly as k single ``advance``
        calls would — the parity requirement."""
        if self.greedy:
            return jnp.stack([self._rng] * k)
        return jnp.stack([self._next_rng() for _ in range(k)])

    def _claim_slot(self, prompt, slot: int | None) -> tuple[np.ndarray,
                                                             int, int]:
        """Shared admission validation: returns (prompt, lp, slot)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        lp = int(prompt.shape[0])
        if lp < 1:
            raise ValueError("prompt must hold at least one token")
        if lp >= self.max_len:
            raise ValueError(
                f"prompt length {lp} leaves no room to generate within "
                f"max_len={self.max_len}")
        if slot is None:
            free = self.free_slots
            if not free:
                raise RuntimeError("no free slot — evict before inserting")
            slot = free[0]
        elif self.active[slot] or self.reserved[slot]:
            raise RuntimeError(f"slot {slot} is active — evict it first")
        self._reset_multi_slot(slot)
        return prompt, lp, slot

    def _reset_multi_slot(self, slot: int) -> None:
        """Clear a slot's multi-step decode state at (re)claim and evict:
        no EOS armed, unlimited budget, not device-halted.  ``_inflight``
        is deliberately NOT cleared — it balances dispatch (+k on the
        dispatch mask) against drain (-k on the same mask), and a slot
        reclaimed while a round is still outstanding must keep its
        pending decrement (the count is a conservative upper bound on
        outstanding device writes, which is all coverage needs)."""
        self.eos_tok[slot] = -1
        self.budget[slot] = 0
        self.halted[slot] = False

    def set_decode_limits(self, slot: int, eos: int | None,
                          budget: int) -> None:
        """Arm in-device deactivation for ``slot``: the fused multi-step
        program stops advancing it once it emits ``eos`` (None = never)
        or exhausts ``budget`` further emissions (0 = unlimited — the
        draft table's mode).  Host-side bookkeeping only; the vectors
        ride the next dispatch as value-cached operands."""
        if budget < 0:
            raise ValueError(f"budget must be >= 0, got {budget}")
        self.eos_tok[slot] = -1 if eos is None else int(eos)
        self.budget[slot] = int(budget)
        self.halted[slot] = False

    def insert(self, prompt, slot: int | None = None) -> tuple[int, int]:
        """Admit a prompt into a free slot (jitted prefill-insert).

        Returns ``(slot, first_token)`` — the first generated token is
        sampled by the prefill itself (its wall time IS the time-to-first-
        token), and the slot's length becomes ``len(prompt)``: the first
        decode step will write the returned token's K/V at that position.

        With the prefix pool enabled, admission routes through the
        chunk-resumable program (``begin_insert`` + one full-remainder
        ``prefill_chunk``) so prefill can start at the first uncached
        block; with the pool off, this is the byte-identical PR 7 path.
        """
        if self.prefix_cache_blocks:
            slot, _ = self.begin_insert(prompt, slot)
            try:
                first = self.prefill_chunk(slot)
            except BaseException:
                # the reservation is internal to this call — release
                # whichever state the slot reached so a failed admission
                # cannot leak it (a failure INSIDE the final chunk may
                # land after the slot already activated, e.g. in
                # _pool_prefix; aborting a no-longer-pending slot would
                # raise over the real error)
                if self.has_pending(slot):
                    self.abort_insert(slot)
                elif self.active[slot]:
                    self.evict(slot)
                raise
            assert first is not None  # uncapped chunk = whole remainder
            return slot, first
        prompt, lp, slot = self._claim_slot(prompt, slot)
        lpad = _bucket(lp, self.prefill_bucket, self.max_len)
        padded = np.zeros(lpad, np.int32)
        padded[:lp] = prompt
        if lpad not in self._prefills:
            self._prefills[lpad] = self._prefill(lpad)
        fn = self._prefills[lpad]
        t0 = time.perf_counter()
        self.cache, first = fn(
            self.params, self.cache, jnp.int32(slot),
            self._put_repl(padded), jnp.int32(lp), self._next_rng())
        self._phase_s["prefill_s"] += time.perf_counter() - t0
        self.prefill_tokens_computed += lp
        self.active[slot] = True
        self.lengths[slot] = lp
        self.tokens[slot] = first = int(first)
        return slot, first

    # ------------------------------------------- chunked (resumable) prefill
    def begin_insert(self, prompt,
                     slot: int | None = None) -> tuple[int, int]:
        """Claim a slot for a chunk-by-chunk admission; returns
        ``(slot, reused_tokens)``.

        The slot is RESERVED (not free, not decoded) until the final
        ``prefill_chunk`` activates it.  With the prefix pool enabled, the
        longest cached block-aligned prefix is copied into the slot here
        and ``reused_tokens`` positions are skipped — prefill resumes at
        the first uncached block.  At least the prompt's final token is
        always computed (its logits sample the first generated token)."""
        prompt, lp, slot = self._claim_slot(prompt, slot)
        reused = self._restore_prefix(prompt, lp, slot)
        self.reserved[slot] = True
        self.lengths[slot] = reused
        self._pending[slot] = {"prompt": prompt, "lp": lp, "filled": reused}
        return slot, reused

    def prefill_chunk(self, slot: int,
                      max_tokens: int | None = None) -> int | None:
        """Process the next ≤ ``max_tokens`` prompt tokens of a pending
        admission (one jitted chunk scan, compiled per power-of-two chunk
        bucket).  Returns the request's first generated token when this
        was the final chunk (the slot becomes active, exactly as after
        ``insert``), else None."""
        pend = self._pending.get(slot)
        if pend is None:
            raise RuntimeError(f"slot {slot} has no pending admission "
                               f"(begin_insert first)")
        filled, lp = pend["filled"], pend["lp"]
        n = lp - filled
        if max_tokens is not None:
            if max_tokens < 1:
                raise ValueError(
                    f"max_tokens must be positive, got {max_tokens}")
            n = min(n, int(max_tokens))
        final = filled + n == lp
        # chunk bucket floor is 1 (not prefill_bucket): budgets below the
        # admission floor must not round the chunk back up past the
        # scheduler's per-iteration token budget
        lpad = _bucket(n, 1, self.max_len)
        padded = np.zeros(lpad, np.int32)
        padded[:n] = pend["prompt"][filled:filled + n]
        if lpad not in self._chunks:
            self._chunks[lpad] = self._chunk(lpad)
        t0 = time.perf_counter()
        self.cache, first = self._chunks[lpad](
            self.params, self.cache, jnp.int32(slot),
            self._put_repl(padded), jnp.int32(filled), jnp.int32(n),
            self._next_rng())
        self._phase_s["prefill_s"] += time.perf_counter() - t0
        pend["filled"] = filled + n
        self.lengths[slot] = filled + n
        self.prefill_tokens_computed += n
        if not final:
            return None
        # materialize the token BEFORE flipping host state: a deferred
        # device error surfaces here while the slot is still pending, so
        # the caller's abort path sees a consistent table
        first = int(first)
        del self._pending[slot]
        self.reserved[slot] = False
        self.active[slot] = True
        self.lengths[slot] = lp
        self.tokens[slot] = first
        self._pool_prefix(pend["prompt"], lp, slot)
        return first

    def pending_tokens(self, slot: int) -> int:
        """Prompt tokens a pending admission still has to prefill."""
        pend = self._pending[slot]
        return pend["lp"] - pend["filled"]

    def has_pending(self, slot: int) -> bool:
        """Whether ``slot`` holds an in-progress (begin_insert) admission."""
        return slot in self._pending

    def abort_insert(self, slot: int) -> None:
        """Release a reserved slot whose admission will not complete (the
        scheduler's mid-run-failure cleanup path)."""
        if slot not in self._pending:
            raise RuntimeError(f"slot {slot} has no pending admission")
        del self._pending[slot]
        self.reserved[slot] = False
        self.lengths[slot] = 0

    # ------------------------------------------------------- KV handoff
    def _claim_restore_slot(self, length: int, slot: int | None) -> int:
        """Shared restore-side validation (monolithic + paged): the
        restored sequence must leave room to decode, exactly insert's
        admission rule."""
        if not 1 <= length < self.max_len:
            raise ValueError(
                f"handoff length {length} must lie in [1, max_len="
                f"{self.max_len}) — a restored slot needs room to decode")
        if slot is None:
            free = self.free_slots
            if not free:
                raise RuntimeError(
                    "no free slot — evict before restoring a handoff")
            slot = free[0]
        elif self.active[slot] or self.reserved[slot]:
            raise RuntimeError(f"slot {slot} is active — evict it first")
        self._reset_multi_slot(slot)
        return slot

    def _check_handoff_payload(self, payload: dict, block: int) -> int:
        """Transfer-format compatibility gate: a payload restores only
        into a table with the same layout, block granularity, storage
        dtype and max_len — anything else would reinterpret bytes."""
        for key, want in (("layout", self.kv_layout),
                          ("block", block),
                          ("kv_dtype", self.kv_dtype),
                          ("max_len", self.max_len)):
            if payload.get(key) != want:
                raise ValueError(
                    f"handoff payload {key}={payload.get(key)!r} does not "
                    f"match the receiving table ({key}={want!r}): prefill "
                    f"and decode replicas must share the KV configuration")
        return int(payload["length"])

    def extract_handoff(self, slot: int) -> dict:
        """Serialize an active slot's KV state into a host-side transfer
        payload — the disaggregated-fleet handoff: a prefill replica
        extracts the finished prompt KV here and a decode replica
        ``restore_handoff``s it into its own table.

        The payload is a dict of plain host numpy trees (one per handoff
        block, sliced by the jitted ``_handoff_ops`` read program and
        ``device_get``; garbage positions past ``length`` in the final
        block travel along but are invisible — validity is length-driven
        on the receiving side too).  Under int8 storage the f32 scale
        leaves ride the same block trees, so restore is byte-exact and a
        greedy continuation on the decode replica is bitwise what the
        prefill replica would have produced.  The slot stays active:
        the caller evicts after a successful transfer."""
        if not self.active[slot]:
            raise RuntimeError(f"slot {slot} is not active")
        if self._handoff_read is None:
            self._handoff_read, self._handoff_write = self._handoff_ops()
        hb = self._handoff_block()
        length = int(self.lengths[slot])
        blocks = []
        for start in range(0, length, hb):
            entry = self._handoff_read(
                self.cache, jnp.int32(slot), jnp.int32(start))
            blocks.append(jax.device_get(entry))
        return {"layout": self.kv_layout, "block": hb, "length": length,
                "token": int(self.tokens[slot]),
                "kv_dtype": self.kv_dtype, "max_len": self.max_len,
                "blocks": blocks}

    def restore_handoff(self, payload: dict,
                        slot: int | None = None) -> tuple[int, int]:
        """Admit a transferred KV payload into a free slot; returns
        ``(slot, first_token)`` exactly like ``insert`` — the first
        generated token was already sampled by the prefill replica and
        travels in the payload, so the receiving scheduler delivers it
        without running any program.  The slot comes up active at the
        transferred length and the next ``advance`` continues the
        sequence bitwise (same storage dtype both sides)."""
        length = self._check_handoff_payload(payload, self._handoff_block())
        slot = self._claim_restore_slot(length, slot)
        if self._handoff_write is None:
            self._handoff_read, self._handoff_write = self._handoff_ops()
        hb = self._handoff_block()
        for b, entry in enumerate(payload["blocks"]):
            entry = jax.tree.map(self._put_repl, entry)
            self.cache = self._handoff_write(
                self.cache, entry, jnp.int32(slot), jnp.int32(b * hb))
        self.active[slot] = True
        self.lengths[slot] = length
        self.tokens[slot] = token = int(payload["token"])
        return slot, token

    # ------------------------------------------------------- prefix pool
    def _prefix_keys(self, prompt: np.ndarray, n_blocks: int):
        """Chained block keys: block b's key is SHA-256 of (block b-1's
        key ‖ block b's token bytes), so the 32-byte digest carries the
        FULL prefix identity — a block matches only when every block
        before it matched — at O(L) total work and constant key size
        (hashing the raw whole-prefix bytes per block would be O(L²)
        per admission and store megabytes of keys for long chains)."""
        blk = self.prefix_block
        keys, prev = [], b""
        for b in range(n_blocks):
            h = hashlib.sha256(prev)
            h.update(prompt[b * blk:(b + 1) * blk].tobytes())
            prev = h.digest()
            keys.append(prev)
        return keys

    def _restore_prefix(self, prompt: np.ndarray, lp: int,
                        slot: int) -> int:
        """Copy the longest cached block-aligned prefix into ``slot``;
        returns the number of reused token positions.  Reuse is capped at
        the blocks covering ``lp - 1`` tokens: the final prompt token is
        always recomputed so its logits can sample the first token."""
        if not self.prefix_cache_blocks:
            return 0
        blk = self.prefix_block
        usable = (lp - 1) // blk    # full blocks strictly before the tail
        insertable = lp // blk      # full blocks the prompt will pool
        keys = self._prefix_keys(prompt, usable)
        matched = 0
        for key in keys:
            if key not in self._prefix_pool:
                break
            matched += 1
        self.prefix_stats["hits"] += matched
        self.prefix_stats["misses"] += insertable - matched
        self.prefix_stats["tokens_reused"] += matched * blk
        if not matched:
            return 0
        if self._write_block is None:
            self._read_block, self._write_block = self._block_ops()
        for b, key in enumerate(keys[:matched]):
            self._prefix_pool.move_to_end(key)   # LRU touch
            self.cache = self._write_block(
                self.cache, self._prefix_pool[key], jnp.int32(slot),
                jnp.int32(b * blk))
        return matched * blk

    def _pool_prefix(self, prompt: np.ndarray, lp: int, slot: int) -> None:
        """After a completed prefill, pool every full block of the prompt
        not already cached (extracted from the slot's freshly-written KV),
        evicting least-recently-used entries past the pool bound."""
        if not self.prefix_cache_blocks:
            return
        blk = self.prefix_block
        if self._read_block is None:
            self._read_block, self._write_block = self._block_ops()
        for b, key in enumerate(self._prefix_keys(prompt, lp // blk)):
            if key in self._prefix_pool:
                self._prefix_pool.move_to_end(key)
                continue
            entry = self._read_block(
                self.cache, jnp.int32(slot), jnp.int32(b * blk))
            if self.mesh is not None:
                # pool entries replicate: a block extracted from one data
                # shard's slot row gets written into ANY slot later, so
                # leaving it pinned to the source shard would force XLA
                # into a resharding rematerialization on every hit
                repl = NamedSharding(self.mesh, P())
                entry = jax.tree.map(
                    lambda t: jax.device_put(t, repl), entry)
            self._prefix_pool[key] = entry
            self.prefix_stats["inserted_blocks"] += 1
        while len(self._prefix_pool) > self.prefix_cache_blocks:
            self._prefix_pool.popitem(last=False)
            self.prefix_stats["evictions"] += 1

    def prefix_cache_stats(self) -> dict | None:
        """Cumulative hit/miss/evict accounting (None when the pool is
        off).  ``hit_rate`` is block-level: reused blocks over reusable +
        pooled blocks."""
        if not self.prefix_cache_blocks:
            return None
        s = dict(self.prefix_stats)
        total = s["hits"] + s["misses"]
        s["cached_blocks"] = len(self._prefix_pool)
        s["hit_rate"] = s["hits"] / total if total else 0.0
        return s

    def reset_prefix_cache(self) -> None:
        """Drop pooled blocks and zero the accounting (bench windows call
        this so per-window hit rates are deterministic)."""
        self._prefix_pool.clear()
        for k in self.prefix_stats:
            self.prefix_stats[k] = 0

    def advance(self, only=None) -> np.ndarray:
        """One decode iteration: every ACTIVE slot consumes its last token
        and emits the next one; lengths advance by one.  Returns the
        (slots,) token vector — inactive rows carry their stale token.
        The jitted step is compiled exactly once per cache shape.

        ``only`` restricts the iteration to a (slots,) bool subset of the
        active slots (the speculative draft's catch-up step: after a
        fully-accepted round only those slots must consume one more
        committed token).  Excluded rows keep their token and length —
        their row still receives a scatter write at its current length,
        which is invisible (length-driven validity) and overwritten by
        that slot's next real write, the free-slot-scatter argument."""
        mask = self.active if only is None else np.asarray(only, np.bool_)
        live = self.lengths[mask]
        if live.size and int(live.max()) >= self.max_len:
            raise SlotOverflow(
                f"active slot at length {int(live.max())} would write past "
                f"max_len={self.max_len}; the scheduler must bound "
                f"prompt + max_new_tokens at admission")
        if self._multi_pending:
            raise RuntimeError(
                "a fused multi-step round is in flight — drain it before "
                "a single-step advance (host mirrors lag the device)")
        t0 = time.perf_counter()
        self.cache, d_nxt, d_len = self._step(
            self.params, self.cache,
            self._dev_cached("tokens", self.tokens),
            self._dev_cached("lengths", self.lengths),
            self._dev_cached("mask", mask), self._next_rng())
        nxt = np.asarray(d_nxt)
        self._phase_s["decode_s"] += time.perf_counter() - t0
        self.lengths[mask] += 1
        self.tokens = nxt.astype(np.int32)
        # the step's own outputs ARE the next iteration's inputs — learn
        # them so an uninterrupted decode loop uploads nothing
        self._dev_learn("tokens", self.tokens, d_nxt)
        self._dev_learn("lengths", self.lengths, d_len)
        return nxt

    # ------------------------------------------------- multi-step decode
    def _multi(self, k: int):
        """Fused k-iteration decode program (the serving twin of PR 1's
        ``build_many_step``): one ``lax.scan`` of k decode steps with
        token feedback, lengths, active mask and per-slot budgets carried
        ON DEVICE, plus in-device deactivation — a slot that emits its
        armed EOS token, exhausts its emission budget, or reaches max_len
        leaves the carried mask and contributes nothing to later
        iterations.  The prologue folds the host-edit merge in: per-slot
        ``edited`` flags select the freshly-uploaded host vectors over
        the device-carried ones, so scheduler edits between dispatches
        (admission, evict) need no separate merge program and no D2H
        wait.  Returns the final carry plus (k, slots) stacks of the
        emitted tokens, the active-at-entry mask per iteration (a
        contiguous True prefix per slot — deactivation only turns slots
        off) and the deactivated-at flags."""
        dm = self.dm
        max_len = self.max_len

        def multi(params, cache, d_tok, d_len, d_act, d_bud,
                  h_tok, h_len, h_act, h_bud, edited, eos, rngs):
            tokens = jnp.where(edited, h_tok, d_tok)
            lengths = jnp.where(edited, h_len, d_len)
            active = jnp.where(edited, h_act, d_act)
            budget = jnp.where(edited, h_bud, d_bud)

            def body(carry, rng):
                cache, tokens, lengths, active, budget = carry
                logits, upd = dm.apply(
                    {"params": params, "cache": cache}, tokens[:, None],
                    train=False, positions=lengths[:, None],
                    mutable=["cache"])
                nxt = self._sample(logits[:, -1],
                                   rng).astype(tokens.dtype)
                nxt = jnp.where(active, nxt, tokens)
                nlen = jnp.where(active, lengths + 1, lengths)
                nbud = jnp.where(active & (budget > 0),
                                 budget - 1, budget)
                done = active & ((nxt == eos)
                                 | ((budget > 0) & (nbud <= 0))
                                 | (nlen >= max_len))
                return ((upd["cache"], nxt, nlen, active & ~done, nbud),
                        (nxt, active, done))

            carry, (toks, acts, dones) = lax.scan(
                body, (cache, tokens, lengths, active, budget), rngs)
            return carry, toks, acts, dones

        return self._jit(multi, f"kv_decode_multi_k{k}", donate_argnums=1)

    def _multi_prepare(self, mask: np.ndarray, k: int) -> tuple:
        """Layout hook before a fused dispatch: extra program operands
        plus writability guarantees (the paged table overrides this to
        cover in-flight growth and snapshot the block table)."""
        return ()

    def dispatch_multi(self, k: int) -> dict:
        """Issue one fused k-iteration decode round WITHOUT materializing
        its results: the token/mask stacks start their D2H copy
        asynchronously and the device carry stays resident for the next
        round's prologue — the scheduler overlaps host work (admissions,
        chunk prefill, delivery of the previous round) with this round's
        device time, then ``drain_multi`` blocks only on the copy.
        Outstanding rounds drain strictly in dispatch order (FIFO).
        Slots the device already deactivated (``halted``) are excluded
        from the host mask; fresh host-side edits ride as ``edited``-
        selected uploads."""
        if k < 1:
            raise ValueError(f"multi-step k must be >= 1, got {k}")
        if k not in self._multis:
            self._multis[k] = self._multi(k)
        mask = self.active & ~self.halted
        extra = self._multi_prepare(mask, k)
        self._inflight[mask] += k
        h_tok = self.tokens.astype(np.int32)
        h_len = self.lengths.astype(np.int32)
        h_act = mask.astype(np.bool_)
        h_bud = self.budget.astype(np.int32)
        snap = self._multi_snap
        if self._multi_state is None or snap is None:
            # first dispatch: the host view is the only truth — the
            # device operands are the same upload, fully selected
            edited = np.ones(self.slots, np.bool_)
            d_tok = self._put_vec(h_tok)
            d_len = self._put_vec(h_len)
            d_act = self._put_vec(h_act)
            d_bud = self._put_vec(h_bud)
        else:
            # edited = host diverged from the host-view-at-last-dispatch
            # snapshot; drain applies round deltas to BOTH sides of this
            # comparison, so only genuine scheduler edits re-upload
            edited = ((h_tok != snap["tokens"])
                      | (h_len != snap["lengths"])
                      | (h_act != snap["mask"])
                      | (h_bud != snap["budget"]))
            d_tok, d_len, d_act, d_bud = self._multi_state
        t0 = time.perf_counter()
        carry, toks, acts, dones = self._multis[k](
            self.params, self.cache, d_tok, d_len, d_act, d_bud,
            self._put_vec(h_tok), self._put_vec(h_len),
            self._put_vec(h_act), self._put_vec(h_bud),
            self._put_vec(edited),
            self._dev_cached("eos", self.eos_tok),
            *extra, self._multi_rngs(k))
        self.cache = carry[0]
        self._multi_state = tuple(carry[1:])
        for arr in (toks, acts, dones):
            if hasattr(arr, "copy_to_host_async"):
                arr.copy_to_host_async()
        self._phase_s["decode_s"] += time.perf_counter() - t0
        self._multi_snap = {"tokens": h_tok.copy(), "lengths": h_len.copy(),
                            "mask": h_act.copy(), "budget": h_bud.copy()}
        handle = {"k": int(k), "mask": h_act.copy(),
                  "tok": toks, "act": acts, "done": dones}
        self._multi_pending.append(handle)
        return handle

    def drain_multi(self, handle: dict | None = None
                    ) -> tuple[np.ndarray, np.ndarray]:
        """Materialize the in-flight fused round and fold its deltas into
        the host mirrors: lengths advance by each slot's emitted count,
        ``tokens`` takes the last emission, deactivated slots set
        ``halted``.  The SAME deltas land on the dispatch snapshot, so
        the next dispatch's ``edited`` comparison sees only scheduler
        edits.  Returns ``(toks, acts)`` — (k, slots) stacks of tokens
        and the active-at-entry mask per iteration (``acts[:, s]`` is a
        contiguous True prefix: ``acts.sum(0)`` emissions, the last at
        row ``emitted-1``)."""
        if not self._multi_pending:
            raise RuntimeError("no fused round in flight")
        if handle is not None and handle is not self._multi_pending[0]:
            raise RuntimeError(
                "fused rounds drain in dispatch order — this handle is "
                "not the oldest outstanding round")
        handle = self._multi_pending.pop(0)
        k, mask = handle["k"], handle["mask"]
        t0 = time.perf_counter()
        toks = np.asarray(handle["tok"]).astype(np.int32)
        acts = np.asarray(handle["act"]).astype(np.bool_)
        dones = np.asarray(handle["done"]).astype(np.bool_)
        self._phase_s["decode_s"] += time.perf_counter() - t0
        self._inflight[mask] -= k
        emitted = acts.sum(axis=0).astype(np.int32)
        sel = emitted > 0
        done_any = dones.any(axis=0)
        snap = self._multi_snap
        # slots the scheduler touched mid-flight (evict + readmit) were
        # device-inactive the whole round — host-finish conditions ARE
        # the in-device deactivation conditions — so ``sel`` only covers
        # slots whose host state still describes this round's stream
        for host, view in ((self.lengths, snap["lengths"]),):
            host[sel] += emitted[sel]
            view[sel] += emitted[sel]
        last = toks[np.maximum(emitted - 1, 0), np.arange(self.slots)]
        self.tokens[sel] = last[sel]
        snap["tokens"][sel] = last[sel]
        bsel = sel & (self.budget > 0)
        self.budget[bsel] -= emitted[bsel]
        snap["budget"][bsel] -= emitted[bsel]
        self.halted |= done_any
        snap["mask"] &= ~done_any
        return toks, acts

    def advance_multi(self, k: int) -> tuple[np.ndarray, np.ndarray]:
        """Fused k decode iterations, synchronously: one host dispatch,
        one D2H materialization — ``dispatch_multi`` + ``drain_multi``
        back to back (the speculative draft's proposal loop and tests
        use this; the scheduler pipeline splits the two)."""
        self.dispatch_multi(k)
        return self.drain_multi()

    @property
    def pending_multi(self) -> int:
        """Outstanding (dispatched, undrained) fused rounds."""
        return len(self._multi_pending)

    def abandon_multi(self) -> None:
        """Drop every outstanding fused round without folding its tokens
        into the host mirrors (run()'s failure cleanup: the window's
        results are lost anyway, but evict() must not race a half-drained
        round's bookkeeping).  Rebalances ``_inflight`` for each dropped
        handle and resets the device carry — the next dispatch re-uploads
        from the host mirrors (edited = all-True)."""
        for handle in self._multi_pending:
            self._inflight[handle["mask"]] -= handle["k"]
        self._multi_pending.clear()
        self._multi_state = None
        self._multi_snap = None

    # ------------------------------------------------- speculative decode
    def verify_block(self, block) -> np.ndarray:
        """Score a (slots, width) token block in one batched step and
        return the (slots, width) per-position greedy argmax tokens.

        Per slot, ``block[s] = [pending_token, d_1, .., d_{width-1}]`` —
        the committed pending token followed by draft proposals; K/V for
        all ``width`` positions is written at ``length .. length+width-1``
        and the returned row ``g`` satisfies: ``g[j]`` is the target's
        greedy token after consuming ``block[s, :j+1]``.  Greedy
        acceptance (``commit_block``) then takes the longest prefix with
        ``d_{j+1} == g[j]`` plus the target's own next token — bitwise
        what non-speculative decode would have emitted.  Host bookkeeping
        (lengths/tokens) is NOT touched here: the scheduler owns
        acceptance, and rejected positions are rolled back by length
        bookkeeping alone (no KV rewrite)."""
        if not self.greedy:
            raise ValueError(
                "verify_block requires greedy sampling: the exact "
                "acceptance rule (accept while draft == target argmax) "
                "only exists for greedy decode")
        block = np.asarray(block, np.int32)
        if block.ndim != 2 or block.shape[0] != self.slots:
            raise ValueError(
                f"block must be (slots, width) = ({self.slots}, k+1), "
                f"got {block.shape}")
        width = int(block.shape[1])
        live = self.lengths[self.active]
        if live.size and int(live.max()) + width > self.max_len:
            raise SlotOverflow(
                f"verify width {width} at length {int(live.max())} would "
                f"write past max_len={self.max_len}; the scheduler must "
                f"cap the draft k by remaining slot capacity")
        if width not in self._verifies:
            self._verifies[width] = self._verify(width)
        blk = jnp.asarray(block)
        if self._blk_sharding is not None:
            blk = jax.device_put(blk, self._blk_sharding)
        t0 = time.perf_counter()
        self.cache, g = self._verifies[width](
            self.params, self.cache, blk,
            self._dev_cached("lengths", self.lengths))
        g = np.asarray(g).astype(np.int32)
        self._phase_s["decode_s"] += time.perf_counter() - t0
        return g

    def commit_block(self, slot: int, n: int, last_token: int) -> None:
        """Commit ``n`` verified positions of the last ``verify_block``
        into ``slot``: lengths advance by ``n`` and ``last_token`` (the
        target's own token at the acceptance point) becomes the slot's
        pending token.  This IS the rollback path for rejected draft
        positions: the verify wrote K/V for the whole block, but validity
        is length-driven, so advancing by only the accepted count
        invalidates the rejected tail with no KV rewrite — the slot's
        next write simply lands over it."""
        if not self.active[slot]:
            raise RuntimeError(f"slot {slot} is not active")
        if n < 1:
            raise ValueError(f"commit_block needs n >= 1, got {n}")
        if int(self.lengths[slot]) + n > self.max_len:
            raise SlotOverflow(
                f"committing {n} positions at length "
                f"{int(self.lengths[slot])} exceeds max_len={self.max_len}")
        self.lengths[slot] += n
        self.tokens[slot] = int(last_token)

    def rewind(self, slot: int, length: int, token: int) -> None:
        """Rewind a slot's validity to ``length`` and set its pending
        token — the DRAFT table's resync after a verify round: positions
        past ``length`` were speculative writes, invalidated here by
        length bookkeeping alone.  A rewind can never extend validity."""
        if not self.active[slot]:
            raise RuntimeError(f"slot {slot} is not active")
        if length > int(self.lengths[slot]):
            raise ValueError(
                f"rewind cannot extend validity: slot {slot} is at "
                f"{int(self.lengths[slot])}, asked for {length}")
        self.lengths[slot] = int(length)
        self.tokens[slot] = int(token)
        # a rewind shrinks validity below any max_len halt the fused
        # draft rounds may have recorded — the slot decodes again
        self.halted[slot] = False

    def evict(self, slot: int) -> None:
        """Free a slot.  Pure host bookkeeping: stale K/V stays in the
        buffer but is unreachable (validity is length-driven) and the next
        insert's prefill overwrites it from position 0."""
        if not self.active[slot]:
            raise RuntimeError(f"slot {slot} is not active")
        self.active[slot] = False
        self.lengths[slot] = 0
        self.tokens[slot] = 0
        self._reset_multi_slot(slot)

    def phase_times(self) -> dict[str, float]:
        """Cumulative host-observed seconds inside the compiled prefill
        (monolithic + chunk) and decode programs — the device-side phase
        timestamps behind the scheduler's ``device_phase_s`` split.  Host-
        observed: each program's result is materialized before the next
        scheduling decision, so dispatch + device wait both land here."""
        return dict(self._phase_s)

    def kv_bytes_per_slot(self) -> int:
        """Stored KV-table bytes per serving slot: every cache leaf —
        K/V payload plus, under int8 storage, its f32 scale leaves —
        divided by the slot count.  THE capacity number behind
        ``--serve-kv-dtype``: bf16 halves f32; int8 halves bf16's payload
        again, plus a per-written-vector scale overhead of 4/head_dim
        (the serve section carries it as ``serve_kv_bytes_per_slot``,
        gated lower-is-better by `analyze diff`)."""
        total = sum(int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
                    for leaf in jax.tree.leaves(self.cache))
        return total // self.slots

    def compiled_programs(self) -> dict[str, int]:
        """The recompile-freedom invariant the tests pin down: one decode
        step, one prefill program per power-of-two bucket, one chunk
        program per power-of-two CHUNK bucket, at most the two prefix
        block-copy programs, and one speculative-verify program per block
        width actually used.  With chunking, the prefix pool and
        speculative decoding off, the chunk/block/verify counts are 0 and
        the compiled set is exactly PR 7's."""
        out = {"decode_steps": 1,
               "prefill_buckets": len(self._prefills),
               "prefill_chunk_buckets": len(self._chunks),
               "prefix_block_ops": (0 if self._read_block is None else 2),
               "verify_widths": len(self._verifies),
               # one fused multi-step decode program per k actually
               # dispatched (--serve-multi-step) — 0 with the flag off:
               # the flag-off program set stays exactly the prior round's
               "decode_multi_widths": len(self._multis)}
        # the disaggregated handoff read/write pair appears only once a
        # handoff actually ran: with the feature off the compiled set —
        # keys included — is exactly the round-17 one (the flag-off
        # program-set parity pin)
        if self._handoff_read is not None:
            out["handoff_block_ops"] = 2
        return out

    def timeline_gauges(self) -> dict[str, float]:
        """Host-side gauge snapshot for the ``--timeline`` sampler: numpy
        sums over the slot table + dict lengths — NO device syncs (the
        cache leaves are touched only for shape/dtype metadata, cached
        after the first call).  ``kv_live_bytes`` is length-proportional
        stored bytes: tokens actually valid × stored bytes per token."""
        per_tok = getattr(self, "_tl_token_bytes", None)
        if per_tok is None:
            total = sum(int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
                        for leaf in jax.tree.leaves(self.cache))
            per_tok = self._tl_token_bytes = \
                total / (self.slots * self.max_len)
        live_tokens = int(self.lengths.sum())
        return {
            "kv_active_slots": int(self.active.sum()),
            "kv_reserved_slots": int(self.reserved.sum()),
            "kv_live_tokens": live_tokens,
            "kv_live_bytes": live_tokens * per_tok,
            "kv_prefix_pool_blocks": len(self._prefix_pool),
        }


class PagedSlotKVCache(SlotKVCache):
    """Paged KV layout (vLLM PagedAttention, arXiv:2309.06180): one
    physical block pool shared by every slot + host-owned per-slot block
    tables, selected by ``SlotKVCache(..., kv_layout="paged")``.

    What changes vs the monolithic table:

    * DEVICE: cache leaves are pools ``(num_blocks+1, block, kv_heads,
      head_dim)`` (+1 is a scratch block — see below) instead of
      ``(slots, max_len, ...)`` rows; the model's paged decode mode
      (models/gpt.py ``paged_blocks``) scatters each write through the
      block table and reads either fused (ops/paged_attention.py Pallas
      kernel — the decode/verify hot op) or by gather + dense (bitwise
      the monolithic math — the prefill scan).
    * HOST: block allocation, refcounts, and the block tables.  A
      prefix-pool hit is a POINTER WRITE — matched pool blocks are
      aliased into the slot's table with a refcount bump and zero KV
      bytes copied (counted in ``paged_stats``); the pool itself stores
      block IDS with a refcount pin, so each hot prefix exists exactly
      once in device memory.  The first write into a shared block
      triggers copy-on-write: one jitted block copy into a freshly
      allocated block, after which the writer owns its copy and the
      other sharers (and the pool) are untouched.
    * SAFETY: the pool carries one extra SCRATCH block (id
      ``num_blocks``); unmapped table entries point at it, and during
      decode/verify the device sees scratch-only table rows for
      non-participating slots — the monolithic layout's "garbage writes
      land in your own row" argument becomes "garbage writes land in
      scratch".  Out-of-range positions are dropped by construction
      (models/gpt.py routes them to an out-of-bounds offset, the scatter
      drop rule).
    * CAPACITY: ``kv_bytes_per_slot`` reports bytes actually backing
      live sequences — in-use pool blocks (payload + scales) + block
      tables, amortized over live slots (the BASELINE stored-bytes
      rule) — not ``slots × max_len``.  Admission is gated by
      ``can_admit`` (free blocks vs the request's worst-case block need
      plus committed-but-unallocated budgets of live slots); running the
      pool dry mid-flight raises ``BlockPoolExhausted``.

    Parity contract: prefill (gather path) is bitwise the monolithic
    prefill; fused decode/verify is tolerance-based (online-softmax
    reassociation — the int8 precedent).  ``paged_fused=False`` keeps
    even decode on the gather path (the parity oracle in paged clothes).
    """

    def __init__(self, model: GPTLM, params, slots: int, *,
                 mesh=None, greedy: bool = True, temperature: float = 1.0,
                 prefill_bucket: int = 8, rng=None, kv_dtype=None,
                 prefix_cache_blocks: int = 0, prefix_block: int = 16,
                 kv_layout: str = "paged", paged_blocks: int = 0,
                 paged_block: int = 0, paged_fused: bool = True,
                 ledger=None):
        if kv_layout != "paged":
            raise ValueError("PagedSlotKVCache is the kv_layout='paged' "
                             "implementation")
        self._ledger = ledger
        if slots < 1:
            raise ValueError(f"slots must be positive, got {slots}")
        if prefix_cache_blocks < 0:
            raise ValueError(f"prefix_cache_blocks must be >= 0, got "
                             f"{prefix_cache_blocks}")
        if prefix_block < 1:
            raise ValueError(f"prefix_block must be positive, got "
                             f"{prefix_block}")
        self.kv_layout = "paged"
        self.slots = int(slots)
        self.max_len = int(model.max_len)
        self.greedy = bool(greedy)
        self.temperature = float(temperature)
        self.prefill_bucket = int(prefill_bucket)
        self.mesh = mesh
        # ONE block granularity: aliasing a pooled prefix block into a
        # slot's table only works when the prefix pool and the physical
        # pool agree on the block size
        block = int(paged_block) if paged_block else int(prefix_block)
        if prefix_cache_blocks and paged_block \
                and int(paged_block) != int(prefix_block):
            raise ValueError(
                f"paged_block ({paged_block}) must equal prefix_block "
                f"({prefix_block}) when the prefix pool is on: pool hits "
                f"alias physical blocks by pointer")
        if block < 1:
            raise ValueError(f"paged_block must be positive, got {block}")
        if self.max_len % block:
            raise ValueError(
                f"paged_block={block} must divide max_len={self.max_len}")
        self.paged_block = block
        self.prefix_block = block
        self.max_blocks = self.max_len // block          # table width
        # default pool: every slot can grow to max_len (+1 block CoW
        # headroom per slot when aliasing is possible) and the prefix
        # pool can pin its full capacity — sized so the default NEVER
        # exhausts; smaller explicit pools rely on can_admit deferral
        cow_pad = 1 if prefix_cache_blocks else 0
        self.num_blocks = int(paged_blocks) if paged_blocks else (
            self.slots * (self.max_blocks + cow_pad)
            + int(prefix_cache_blocks))
        if self.num_blocks < self.max_blocks + cow_pad:
            raise ValueError(
                f"paged_blocks={self.num_blocks} cannot hold even one "
                f"full slot ({self.max_blocks} blocks + {cow_pad} CoW "
                f"headroom)")
        self._scratch = self.num_blocks  # physical id of the scratch block

        self.quantized = False
        if kv_dtype is not None:
            kv_dtype = jnp.dtype(kv_dtype)
            self.quantized = kv_dtype == jnp.dtype(jnp.int8)
        keep_tp = (mesh is not None and model.partition_model
                   and meshlib.MODEL_AXIS in mesh.axis_names)
        # fused clone for the decode/verify hot ops, gather clone for the
        # prefill scan (bitwise-monolithic math) — same params, same
        # cache variables, only the read path differs
        self.paged_fused = bool(paged_fused)
        self.dm = model.clone(decode=True, decode_slots=True,
                              attention_impl="dense",
                              partition_model=keep_tp, dropout_rate=0.0,
                              kv_quant=self.quantized,
                              paged_blocks=self.num_blocks + 1,
                              paged_block=block,
                              paged_fused=self.paged_fused)
        self.dm_gather = self.dm.clone(paged_fused=False)
        self._rng = rng if rng is not None else jax.random.key(0)

        dummy = jnp.zeros((self.slots, 1), jnp.int32)
        shapes = jax.eval_shape(
            lambda: self.dm.init(jax.random.key(0), dummy, train=False,
                                 positions=dummy))["cache"]
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        if kv_dtype is not None and not self.quantized:
            cache = jax.tree.map(
                lambda t: t.astype(kv_dtype)
                if jnp.issubdtype(t.dtype, jnp.floating) else t, cache)
        self.kv_dtype = "int8" if self.quantized else next(
            (str(leaf.dtype) for leaf in jax.tree.leaves(cache)
             if jnp.issubdtype(leaf.dtype, jnp.floating)), "float32")

        self._vec_sharding = None
        self._blk_sharding = None
        if mesh is not None:
            dp = mesh.shape.get(meshlib.DATA_AXIS, 1)
            if self.slots % dp:
                raise ValueError(
                    f"slots ({self.slots}) must divide by the mesh's data "
                    f"axis ({dp}): each data shard owns a contiguous slot "
                    f"block")
            # pool leaves REPLICATE: any slot (sharded over 'data') may
            # read/write any physical block, so a block-dim sharding
            # would turn every table-indirect access into a reshard
            repl = NamedSharding(mesh, P())
            cache = jax.tree.map(lambda t: jax.device_put(t, repl), cache)
            self._vec_sharding = meshlib.kv_slot_sharding(mesh, 1)
            self._blk_sharding = meshlib.kv_slot_sharding(mesh, 2)
        self.cache = cache
        self.params = self._place_params(params)

        # host slot table (identical to monolithic) ...
        self.lengths = np.zeros(self.slots, np.int32)
        self.active = np.zeros(self.slots, np.bool_)
        self.reserved = np.zeros(self.slots, np.bool_)
        self.tokens = np.zeros(self.slots, np.int32)
        self._pending: dict[int, dict] = {}
        self._init_multi_state()

        # ... plus the paged substrate: refcounted physical blocks, a
        # free list, per-slot logical→physical tables (host numpy; the
        # device sees a masked snapshot per program call)
        self._block_refs = np.zeros(self.num_blocks, np.int32)
        self._free_list = list(range(self.num_blocks))[::-1]  # pop() → 0,1,..
        self._slot_blocks: list[list[int]] = [[] for _ in range(self.slots)]
        self.block_tables_np = np.full(
            (self.slots, self.max_blocks), self._scratch, np.int32)
        # committed block budgets (can_admit's outstanding ledger):
        # worst-case blocks each live admission may still allocate
        self._slot_need = np.zeros(self.slots, np.int32)
        self._paged_counters = {"zero_copy_hits": 0, "zero_copy_blocks": 0,
                                "zero_copy_tokens": 0, "cow_copies": 0}

        # prefix pool: key → PHYSICAL BLOCK ID with a refcount pin (the
        # monolithic pool stores device byte copies; here the pool IS
        # the aliasing table — zero bytes stored twice)
        self.prefix_cache_blocks = int(prefix_cache_blocks)
        self._prefix_pool: OrderedDict[bytes, int] = OrderedDict()
        self.prefix_stats = {"hits": 0, "misses": 0, "evictions": 0,
                             "tokens_reused": 0, "inserted_blocks": 0}

        self.prefill_tokens_computed = 0
        self._phase_s = {"prefill_s": 0.0, "decode_s": 0.0}

        self._step = self._build_step()
        self._prefills: dict[int, object] = {}   # unused: paged admission
        self._chunks: dict[int, object] = {}     # always chunks
        self._verifies: dict[int, object] = {}
        self._read_block = None                  # monolithic pool programs
        self._write_block = None                 # never built under paged
        self._copy_block = None                  # CoW block copy (lazy)
        self._handoff_read = None                # disagg KV handoff (lazy)
        self._handoff_write = None

    # -------------------------------------------------- block bookkeeping
    @property
    def blocks_in_use(self) -> int:
        """Allocated physical blocks (scratch excluded)."""
        return self.num_blocks - len(self._free_list)

    def _alloc_block(self) -> int:
        if not self._free_list:
            raise BlockPoolExhausted(
                f"paged KV pool exhausted: all {self.num_blocks} blocks "
                f"in use — the scheduler's can_admit gate should have "
                f"deferred this admission (block budget accounting bug, "
                f"or the pool was sized below slots × max_len/block)")
        bid = self._free_list.pop()
        self._block_refs[bid] = 1
        return bid

    def _release_block(self, bid: int) -> None:
        self._block_refs[bid] -= 1
        if self._block_refs[bid] == 0:
            self._free_list.append(bid)

    def _release_slot_blocks(self, slot: int) -> None:
        for bid in self._slot_blocks[slot]:
            self._release_block(bid)
        self._slot_blocks[slot].clear()
        self.block_tables_np[slot, :] = self._scratch
        self._slot_need[slot] = 0

    def _build_copy(self):
        def copy(cache, src, dst):
            return jax.tree.map(
                lambda t: lax.dynamic_update_slice(
                    t, lax.dynamic_slice(
                        t, (src,) + (0,) * (t.ndim - 1),
                        (1,) + t.shape[1:]),
                    (dst,) + (0,) * (t.ndim - 1)), cache)

        return self._jit(copy, "kv_paged_cow_copy", donate_argnums=0)

    def _ensure_writable(self, slot: int, start: int, end: int) -> None:
        """Make positions ``[start, end)`` of ``slot`` safely writable:
        allocate missing blocks, copy-on-write shared ones.  A shared
        block (refcount > 1 — aliased from the prefix pool or pinned BY
        it) gets one jitted block copy into a fresh allocation; the
        slot's table then points at its private copy and every other
        sharer keeps reading the original."""
        if end <= start:
            return
        sb = self._slot_blocks[slot]
        blk = self.paged_block
        last = min((end - 1) // blk, self.max_blocks - 1)
        for j in range(start // blk, last + 1):
            while len(sb) <= j:      # extend coverage with fresh blocks
                bid = self._alloc_block()
                sb.append(bid)
                self.block_tables_np[slot, len(sb) - 1] = bid
            bid = sb[j]
            if self._block_refs[bid] > 1:   # shared → copy-on-write
                if self._copy_block is None:
                    self._copy_block = self._build_copy()
                new = self._alloc_block()
                self.cache = self._copy_block(
                    self.cache, jnp.int32(bid), jnp.int32(new))
                self._release_block(bid)
                sb[j] = new
                self.block_tables_np[slot, j] = new
                self._paged_counters["cow_copies"] += 1

    def _masked_bt(self, mask):
        """Device block-table snapshot with non-participating rows routed
        wholly to scratch — their garbage scatter writes can never land
        in a live (possibly shared) block.  Value-cached like the slot
        vectors: an unchanged table re-uploads nothing."""
        bt = np.where(np.asarray(mask, np.bool_)[:, None],
                      self.block_tables_np, np.int32(self._scratch))
        return self._dev_cached("bt", bt.astype(np.int32),
                                put=self._put_repl)

    # ------------------------------------------------- admission budgets
    def _block_need(self, total_len: int) -> int:
        need = -(-int(total_len) // self.paged_block)
        if self.prefix_cache_blocks:
            need += 1   # CoW headroom: a fully-aligned prefix hit
                        # recomputes its last token INTO a shared block
        return min(need, self.max_blocks + (1 if self.prefix_cache_blocks
                                            else 0))

    def can_admit(self, prompt_len: int, max_new_tokens: int) -> bool:
        """Block-exhaustion admission gate: free blocks (minus what live
        admissions may still claim under their registered budgets) must
        cover this request's worst-case need.  Conservative — aliasing
        only helps (aliased blocks never touch the free list)."""
        outstanding = sum(
            max(int(self._slot_need[s]) - len(self._slot_blocks[s]), 0)
            for s in range(self.slots) if self._slot_need[s])
        need = self._block_need(int(prompt_len) + int(max_new_tokens))
        return len(self._free_list) - outstanding >= need

    def note_admission(self, slot: int, total_len: int) -> None:
        """Register an admitted request's worst-case block budget (the
        scheduler calls this with prompt + max_new_tokens); cleared on
        evict/abort."""
        self._slot_need[slot] = self._block_need(total_len)

    # ------------------------------------------------------------ programs
    def _build_step(self):
        dm = self.dm

        def step(params, cache, tokens, lengths, active, bt, rng):
            logits, upd = dm.apply(
                {"params": params, "cache": cache}, tokens[:, None],
                train=False, positions=lengths[:, None],
                block_tables=bt, mutable=["cache"])
            nxt = self._sample(logits[:, -1], rng).astype(tokens.dtype)
            return (upd["cache"], jnp.where(active, nxt, tokens),
                    jnp.where(active, lengths + 1, lengths))

        return self._jit(step, "kv_paged_decode_step", donate_argnums=1)

    def _chunk(self, lpad: int):
        """Chunk-resumable prefill over the FULL pool (there is no
        per-slot cache slice to extract — the slot's identity is its
        block table row): same scan/positions/sampling contract as the
        monolithic ``_chunk``, gather read path (bitwise-monolithic
        math over the gathered table)."""
        dm = self.dm_gather

        def chunk(params, cache, bt_row, tokens, start, n_valid, rng):
            def body(c, xs):
                tok, t = xs
                logits, upd = dm.apply(
                    {"params": params, "cache": c}, tok[None, None],
                    train=False, positions=t[None, None],
                    block_tables=bt_row, mutable=["cache"])
                return upd["cache"], logits[0, -1]

            cache, all_logits = lax.scan(
                body, cache,
                (tokens, start + jnp.arange(lpad, dtype=jnp.int32)))
            last = jnp.take(all_logits, n_valid - 1, axis=0)
            first = self._sample(last[None, :], rng)[0]
            return cache, first.astype(tokens.dtype)

        return self._jit(chunk, f"kv_paged_prefill_chunk_l{lpad}",
                         donate_argnums=1)

    def _verify(self, width: int):
        dm = self.dm

        def verify(params, cache, block, lengths, bt):
            positions = (lengths[:, None]
                         + jnp.arange(width, dtype=jnp.int32)[None, :])
            logits, upd = dm.apply(
                {"params": params, "cache": cache}, block,
                train=False, positions=positions, block_tables=bt,
                mutable=["cache"])
            return upd["cache"], logits.argmax(-1).astype(block.dtype)

        return self._jit(verify, f"kv_paged_verify_w{width}",
                         donate_argnums=1)

    # ------------------------------------------------------------ slot API
    def insert(self, prompt, slot: int | None = None) -> tuple[int, int]:
        """Paged admission ALWAYS routes through the chunk-resumable
        program (begin_insert + one uncapped chunk): there is no
        slice-out monolithic prefill over a shared pool, and chunked
        admission is the path whose writes go through
        ``_ensure_writable`` (allocation + CoW)."""
        slot, _ = self.begin_insert(prompt, slot)
        try:
            first = self.prefill_chunk(slot)
        except BaseException:
            if self.has_pending(slot):
                self.abort_insert(slot)
            elif self.active[slot]:
                self.evict(slot)
            raise
        assert first is not None
        return slot, first

    def prefill_chunk(self, slot: int,
                      max_tokens: int | None = None) -> int | None:
        pend = self._pending.get(slot)
        if pend is None:
            raise RuntimeError(f"slot {slot} has no pending admission "
                               f"(begin_insert first)")
        filled, lp = pend["filled"], pend["lp"]
        n = lp - filled
        if max_tokens is not None:
            if max_tokens < 1:
                raise ValueError(
                    f"max_tokens must be positive, got {max_tokens}")
            n = min(n, int(max_tokens))
        final = filled + n == lp
        # allocation + CoW BEFORE the program runs: the scan's writes
        # must only ever land in private (or scratch) blocks
        self._ensure_writable(slot, filled, filled + n)
        lpad = _bucket(n, 1, self.max_len)
        padded = np.zeros(lpad, np.int32)
        padded[:n] = pend["prompt"][filled:filled + n]
        if lpad not in self._chunks:
            self._chunks[lpad] = self._chunk(lpad)
        bt_row = self._put_repl(
            self.block_tables_np[slot:slot + 1].astype(np.int32))
        t0 = time.perf_counter()
        self.cache, first = self._chunks[lpad](
            self.params, self.cache, bt_row,
            self._put_repl(padded), jnp.int32(filled), jnp.int32(n),
            self._next_rng())
        self._phase_s["prefill_s"] += time.perf_counter() - t0
        pend["filled"] = filled + n
        self.lengths[slot] = filled + n
        self.prefill_tokens_computed += n
        if not final:
            return None
        first = int(first)
        del self._pending[slot]
        self.reserved[slot] = False
        self.active[slot] = True
        self.lengths[slot] = lp
        self.tokens[slot] = first
        self._pool_prefix(pend["prompt"], lp, slot)
        return first

    def abort_insert(self, slot: int) -> None:
        super().abort_insert(slot)
        self._release_slot_blocks(slot)

    def evict(self, slot: int) -> None:
        if not self.active[slot]:
            raise RuntimeError(f"slot {slot} is not active")
        self._release_slot_blocks(slot)
        self.active[slot] = False
        self.lengths[slot] = 0
        self.tokens[slot] = 0
        self._reset_multi_slot(slot)

    # ------------------------------------------------------- KV handoff
    def _handoff_block(self) -> int:
        """Paged handoff granularity IS the physical block: the transfer
        format serializes whole pool blocks by id, so block size and
        table block size agree by construction."""
        return self.paged_block

    def _handoff_ops(self):
        """Physical-block handoff programs (``_build_copy``'s slicing
        aimed across tables instead of within one): ``read`` slices one
        physical block out of every pool leaf, ``write`` scatters a
        transferred block into a freshly-allocated block of the
        receiving pool.  Block ids are traced — one compile each."""
        def read(cache, bid):
            return jax.tree.map(
                lambda t: lax.dynamic_slice(
                    t, (bid,) + (0,) * (t.ndim - 1),
                    (1,) + t.shape[1:]), cache)

        def write(cache, entry, bid):
            return jax.tree.map(
                lambda t, e: lax.dynamic_update_slice(
                    t, e.astype(t.dtype), (bid,) + (0,) * (t.ndim - 1)),
                cache, entry)

        return (self._jit(read, "kv_handoff_read_block"),
                self._jit(write, "kv_handoff_write_block",
                          donate_argnums=0))

    def extract_handoff(self, slot: int) -> dict:
        """Paged extract: serialize the physical blocks backing the
        slot's first ``ceil(length/block)`` table entries (aliased
        prefix blocks serialize like private ones — the payload is
        self-contained, the receiving pool shares nothing with ours)."""
        if not self.active[slot]:
            raise RuntimeError(f"slot {slot} is not active")
        if self._handoff_read is None:
            self._handoff_read, self._handoff_write = self._handoff_ops()
        length = int(self.lengths[slot])
        blk = self.paged_block
        n = -(-length // blk)
        sb = self._slot_blocks[slot]
        if len(sb) < n:
            raise RuntimeError(
                f"slot {slot} block table covers {len(sb)} blocks but "
                f"length {length} needs {n} — block bookkeeping bug")
        blocks = []
        for bid in sb[:n]:
            entry = self._handoff_read(self.cache, jnp.int32(bid))
            blocks.append(jax.device_get(entry))
        return {"layout": "paged", "block": blk, "length": length,
                "token": int(self.tokens[slot]),
                "kv_dtype": self.kv_dtype, "max_len": self.max_len,
                "blocks": blocks}

    def restore_handoff(self, payload: dict,
                        slot: int | None = None) -> tuple[int, int]:
        """Paged restore: allocate the covering blocks, scatter the
        payload in, point the slot's table at them.  Failure anywhere —
        pool exhausted mid-allocation, a device error mid-write —
        releases every block this restore claimed before re-raising, so
        a failed handoff admission cannot leak pool blocks."""
        blk = self.paged_block
        length = self._check_handoff_payload(payload, blk)
        n = -(-length // blk)
        if len(payload["blocks"]) != n:
            raise ValueError(
                f"handoff payload carries {len(payload['blocks'])} blocks "
                f"but length {length} needs {n}")
        slot = self._claim_restore_slot(length, slot)
        if self._handoff_write is None:
            self._handoff_read, self._handoff_write = self._handoff_ops()
        sb = self._slot_blocks[slot]
        try:
            for j, entry in enumerate(payload["blocks"]):
                bid = self._alloc_block()
                sb.append(bid)
                self.block_tables_np[slot, j] = bid
                entry = jax.tree.map(self._put_repl, entry)
                self.cache = self._handoff_write(
                    self.cache, entry, jnp.int32(bid))
        except BaseException:
            # slot is still inactive — releasing its blocks restores the
            # pool exactly (refcounts were 1: nothing aliased a block
            # that never finished arriving)
            self._release_slot_blocks(slot)
            raise
        self.active[slot] = True
        self.lengths[slot] = length
        self.tokens[slot] = token = int(payload["token"])
        return slot, token

    # ------------------------------------------------------- prefix pool
    def _restore_prefix(self, prompt: np.ndarray, lp: int,
                        slot: int) -> int:
        """The zero-copy hit: matched pool blocks are aliased into the
        slot's block table (pointer writes + refcount bumps) — no device
        traffic at all.  Reuse covers FULL blocks including the one
        holding the prompt's final token (unlike the monolithic
        ``(lp-1)//blk`` cap): the final token is still recomputed (reuse
        is capped at ``lp - 1`` positions), and its write into the
        shared last block is what exercises copy-on-write."""
        if not self.prefix_cache_blocks:
            return 0
        blk = self.prefix_block
        usable = lp // blk
        keys = self._prefix_keys(prompt, usable)
        matched = 0
        for key in keys:
            if key not in self._prefix_pool:
                break
            matched += 1
        reused = min(matched * blk, lp - 1)
        self.prefix_stats["hits"] += matched
        self.prefix_stats["misses"] += usable - matched
        self.prefix_stats["tokens_reused"] += reused
        if not matched:
            return 0
        sb = self._slot_blocks[slot]
        for b, key in enumerate(keys[:matched]):
            self._prefix_pool.move_to_end(key)   # LRU touch
            bid = self._prefix_pool[key]
            self._block_refs[bid] += 1
            sb.append(bid)
            self.block_tables_np[slot, b] = bid
        self._paged_counters["zero_copy_hits"] += 1
        self._paged_counters["zero_copy_blocks"] += matched
        self._paged_counters["zero_copy_tokens"] += reused
        return reused

    def _pool_prefix(self, prompt: np.ndarray, lp: int, slot: int) -> None:
        """Pool = pin: every full prompt block not already pooled gets a
        refcount pin on the slot's OWN physical block (no extraction, no
        copy — the pool and the slot share the block until eviction
        drops the slot's reference)."""
        if not self.prefix_cache_blocks:
            return
        blk = self.prefix_block
        sb = self._slot_blocks[slot]
        for b, key in enumerate(self._prefix_keys(prompt, lp // blk)):
            if key in self._prefix_pool:
                self._prefix_pool.move_to_end(key)
                continue
            bid = sb[b]
            self._block_refs[bid] += 1          # the pool's pin
            self._prefix_pool[key] = bid
            self.prefix_stats["inserted_blocks"] += 1
        while len(self._prefix_pool) > self.prefix_cache_blocks:
            _, bid = self._prefix_pool.popitem(last=False)
            self._release_block(bid)
            self.prefix_stats["evictions"] += 1

    def reset_prefix_cache(self) -> None:
        while self._prefix_pool:
            _, bid = self._prefix_pool.popitem(last=False)
            self._release_block(bid)
        for k in self.prefix_stats:
            self.prefix_stats[k] = 0
        for k in self._paged_counters:
            self._paged_counters[k] = 0

    # ------------------------------------------------------------- decode
    def advance(self, only=None) -> np.ndarray:
        mask = self.active if only is None else np.asarray(only, np.bool_)
        live = self.lengths[mask]
        if live.size and int(live.max()) >= self.max_len:
            raise SlotOverflow(
                f"active slot at length {int(live.max())} would write past "
                f"max_len={self.max_len}; the scheduler must bound "
                f"prompt + max_new_tokens at admission")
        if self._multi_pending:
            raise RuntimeError(
                "a fused multi-step round is in flight — drain it before "
                "a single-step advance (host mirrors lag the device)")
        for slot in np.flatnonzero(mask):
            pos = int(self.lengths[slot])
            self._ensure_writable(int(slot), pos, pos + 1)
        t0 = time.perf_counter()
        self.cache, d_nxt, d_len = self._step(
            self.params, self.cache,
            self._dev_cached("tokens", self.tokens),
            self._dev_cached("lengths", self.lengths),
            self._dev_cached("mask", mask), self._masked_bt(mask),
            self._next_rng())
        nxt = np.asarray(d_nxt)
        self._phase_s["decode_s"] += time.perf_counter() - t0
        self.lengths[mask] += 1
        self.tokens = nxt.astype(np.int32)
        self._dev_learn("tokens", self.tokens, d_nxt)
        self._dev_learn("lengths", self.lengths, d_len)
        return nxt

    # ------------------------------------------------- multi-step decode
    def _multi(self, k: int):
        """Paged fused k-iteration decode: the monolithic scan with the
        masked block-table operand threaded through every step.  The
        table is a DISPATCH-TIME snapshot: `_multi_prepare` pre-extends
        each slot's coverage for all in-flight growth, and a slot the
        device deactivates keeps scattering at its frozen length — into
        its own covered block (overwritten before any read: validity is
        length-driven) or past the snapshot's coverage, which routes to
        the scratch block."""
        dm = self.dm
        max_len = self.max_len

        def multi(params, cache, d_tok, d_len, d_act, d_bud,
                  h_tok, h_len, h_act, h_bud, edited, eos, bt, rngs):
            tokens = jnp.where(edited, h_tok, d_tok)
            lengths = jnp.where(edited, h_len, d_len)
            active = jnp.where(edited, h_act, d_act)
            budget = jnp.where(edited, h_bud, d_bud)

            def body(carry, rng):
                cache, tokens, lengths, active, budget = carry
                logits, upd = dm.apply(
                    {"params": params, "cache": cache}, tokens[:, None],
                    train=False, positions=lengths[:, None],
                    block_tables=bt, mutable=["cache"])
                nxt = self._sample(logits[:, -1],
                                   rng).astype(tokens.dtype)
                nxt = jnp.where(active, nxt, tokens)
                nlen = jnp.where(active, lengths + 1, lengths)
                nbud = jnp.where(active & (budget > 0),
                                 budget - 1, budget)
                done = active & ((nxt == eos)
                                 | ((budget > 0) & (nbud <= 0))
                                 | (nlen >= max_len))
                return ((upd["cache"], nxt, nlen, active & ~done, nbud),
                        (nxt, active, done))

            carry, (toks, acts, dones) = lax.scan(
                body, (cache, tokens, lengths, active, budget), rngs)
            return carry, toks, acts, dones

        return self._jit(multi, f"kv_paged_decode_multi_k{k}",
                         donate_argnums=1)

    def _multi_prepare(self, mask: np.ndarray, k: int) -> tuple:
        """Cover every masked slot's worst-case in-flight growth —
        already-dispatched undrained rounds (``_inflight``) plus this
        round's k — so no fused write can land outside the slot's own
        blocks, then snapshot the masked block table as the program's
        extra operand."""
        for slot in np.flatnonzero(mask):
            start = int(self.lengths[slot])
            end = min(start + int(self._inflight[slot]) + k, self.max_len)
            self._ensure_writable(int(slot), start, end)
        return (self._masked_bt(mask),)

    def verify_block(self, block) -> np.ndarray:
        if not self.greedy:
            raise ValueError(
                "verify_block requires greedy sampling: the exact "
                "acceptance rule (accept while draft == target argmax) "
                "only exists for greedy decode")
        block = np.asarray(block, np.int32)
        if block.ndim != 2 or block.shape[0] != self.slots:
            raise ValueError(
                f"block must be (slots, width) = ({self.slots}, k+1), "
                f"got {block.shape}")
        width = int(block.shape[1])
        live = self.lengths[self.active]
        if live.size and int(live.max()) + width > self.max_len:
            raise SlotOverflow(
                f"verify width {width} at length {int(live.max())} would "
                f"write past max_len={self.max_len}; the scheduler must "
                f"cap the draft k by remaining slot capacity")
        for slot in np.flatnonzero(self.active):
            pos = int(self.lengths[slot])
            self._ensure_writable(int(slot), pos, pos + width)
        if width not in self._verifies:
            self._verifies[width] = self._verify(width)
        blk = jnp.asarray(block)
        if self._blk_sharding is not None:
            blk = jax.device_put(blk, self._blk_sharding)
        t0 = time.perf_counter()
        self.cache, g = self._verifies[width](
            self.params, self.cache, blk,
            self._dev_cached("lengths", self.lengths),
            self._masked_bt(self.active))
        g = np.asarray(g).astype(np.int32)
        self._phase_s["decode_s"] += time.perf_counter() - t0
        return g

    # --------------------------------------------------------- accounting
    def kv_bytes_per_slot(self) -> int:
        """HONEST paged capacity (the BASELINE stored-bytes rule): bytes
        actually backing live sequences — allocated pool blocks (K/V
        payload + int8 scales) plus the block tables — amortized over
        live (active or reserved) slots.  With nothing live this is the
        pool-warmth floor: whatever the prefix pool still pins, plus the
        tables.  The monolithic ``slots × max_len`` formula would claim
        capacity the pool never allocated."""
        per_block = sum(
            (int(leaf.size) // leaf.shape[0])
            * jnp.dtype(leaf.dtype).itemsize
            for leaf in jax.tree.leaves(self.cache))
        table_bytes = self.block_tables_np.nbytes
        live = int(self.active.sum()) + int(self.reserved.sum())
        return (self.blocks_in_use * per_block
                + table_bytes) // max(live, 1)

    def paged_stats(self) -> dict:
        """Pool utilization + the zero-copy/CoW ledger (cumulative; the
        scheduler reads counter deltas per run)."""
        return {"num_blocks": self.num_blocks,
                "block": self.paged_block,
                "blocks_in_use": self.blocks_in_use,
                "utilization": self.blocks_in_use / self.num_blocks,
                **dict(self._paged_counters)}

    def compiled_programs(self) -> dict[str, int]:
        """Paged program inventory: ONE decode step, one chunk program
        per bucket (admission always chunks — there is no monolithic
        slice-out prefill over a shared pool), no prefix block-copy
        programs (hits are pointer writes), one verify program per
        width, plus at most one CoW block copy."""
        out = super().compiled_programs()
        out["paged_block_copies"] = 0 if self._copy_block is None else 1
        return out

    def timeline_gauges(self) -> dict[str, float]:
        """Paged gauge snapshot: the base slot-table gauges plus pool
        occupancy/refcounts, all host numpy — no device syncs.  Under
        paging ``kv_live_bytes`` is block-backed: allocated blocks ×
        stored bytes per block (aliased blocks counted once, exactly the
        zero-copy saving the pool exists for)."""
        per_block = getattr(self, "_tl_block_bytes", None)
        if per_block is None:
            per_block = self._tl_block_bytes = sum(
                (int(leaf.size) // leaf.shape[0])
                * jnp.dtype(leaf.dtype).itemsize
                for leaf in jax.tree.leaves(self.cache))
        live_tokens = int(self.lengths.sum())
        return {
            "kv_active_slots": int(self.active.sum()),
            "kv_reserved_slots": int(self.reserved.sum()),
            "kv_live_tokens": live_tokens,
            "kv_live_bytes": self.blocks_in_use * per_block,
            "kv_prefix_pool_blocks": len(self._prefix_pool),
            "kv_blocks_in_use": self.blocks_in_use,
            "kv_pool_refcount_total": int(self._block_refs.sum()),
            "kv_free_blocks": len(self._free_list),
        }
