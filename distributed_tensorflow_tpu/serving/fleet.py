"""Fault-tolerant serving fleet: supervised replicas with no-loss failover.

PRs 7–13 built one excellent single-replica batcher; "millions of users"
(ROADMAP item 2) means a *fleet*, and the difference between a benchmark
and a service is what happens when a replica dies mid-decode.  This module
is that difference, with robustness as the headline contract:

* :class:`ReplicaSet` runs N ``ContinuousBatcher`` replicas — each with
  its own ``SlotKVCache`` — behind a least-loaded front-end router.  In
  wall-clock mode every replica serves on its own thread; with a
  ``VirtualClock`` the supervisor drives replicas deterministically in id
  order, so chaos tests are exact, repeatable schedules (the Varuna
  lesson, arXiv:2111.04007: preemption tolerance must be a first-class,
  testable design axis).

* The :class:`RequestJournal` records every request's replica assignment
  and every token actually delivered.  When a replica fails — an
  exception out of its run loop, a watchdog stall, or an injected fault —
  its queued AND in-flight requests are requeued to surviving replicas
  with bounded retry + backoff, and the journal's **assignment fence**
  makes delivery exactly-once: an emission is accepted only from the
  request's CURRENT replica, so a stalled zombie waking up after failover
  cannot re-emit (fenced emissions are counted, never delivered).  A
  retried request resumes by re-prefilling prompt + already-emitted
  prefix (greedy decode makes the continuation exact — the vLLM
  iteration-level substrate, arXiv:2309.06180: the retry re-enters the
  continuous-batching loop of the survivor, it does not restart a batch),
  and its TTFT stays charged from the ORIGINAL arrival, the PR 7/11
  accounting discipline.

* :class:`FaultInjector` is the seeded test substrate (the serving twin
  of ``HealthConfig.inject_nan_at``): crash-at-site-k (decode iteration,
  prefill chunk, or between verify and commit), stall-for-s (caught by
  the supervisor's watchdog), and nonfinite-logits corruption — modeled
  as an out-of-range sampled token id, detected by the fleet's cheap
  per-token host check before anything reaches the journal.

* **Graceful drain + zero-downtime weight hot-swap**: each replica
  carries a ``LeaseManager`` (the PR 9 ``should_stop`` contract) whose
  programmatic ``trigger`` drains it — stop admitting, finish in-flight —
  after which ``SlotKVCache.swap_params`` installs the new weights
  between compiled-program dispatches (a swap never recompiles).  Swaps
  run replica-by-replica, so the fleet never drops below N−1 admitting
  replicas, and ``swap_generations`` counts completed fleet-wide swaps.

* Fleet accounting: per-replica ``MetricsRegistry`` histograms merge via
  PR 11's ``merge`` (built for exactly this aggregation), and the run
  summary carries a ``serve_fleet`` section — replicas, failovers,
  retries, requeued_requests, duplicate_emissions (== 0 is the
  exactly-once claim, measured not assumed), swap_generations, and
  per-replica + merged goodput — plus the two gated headline keys
  ``serve_failover_recovery_p95_s`` and ``serve_duplicate_emissions``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from typing import Any, Callable, Iterable

import numpy as np

from distributed_tensorflow_tpu.elastic.lease import LeaseManager
from distributed_tensorflow_tpu.observability.metrics import (
    MetricsRegistry, exact_percentile)
from distributed_tensorflow_tpu.observability.trace import NULL_TRACER
from distributed_tensorflow_tpu.serving.kv_cache import SlotKVCache
from distributed_tensorflow_tpu.serving.scheduler import (
    ContinuousBatcher, Request, RequestQueue, RequestResult, VirtualClock,
    WallClock)


class InjectedFault(RuntimeError):
    """A FaultInjector fired: the replica's run loop dies here exactly the
    way an un-injected bug would — the supervisor must not special-case
    it (the whole point of injection is exercising the real path)."""


class CorruptionDetected(RuntimeError):
    """The fleet's cheap per-token host check rejected an emission (token
    id out of [0, vocab) — what nonfinite logits degrade sampling into).
    Raised BEFORE the journal records anything, so a corrupt token is
    never delivered; the replica fails over like any other death."""


# ------------------------------------------------------------ fault specs

_FAULT_KINDS = ("crash", "stall", "nanlogits")
_FAULT_SITES = ("decode", "prefill", "verify", "handoff")


@dataclasses.dataclass
class FaultSpec:
    """One seeded fault: ``kind`` at the ``at``-th ``site`` event on
    ``replica`` (1-based count of decode iterations / prefill programs /
    verify steps on that replica), or Bernoulli per event with ``prob``
    under the injector's seed.  ``stall_s`` is the stall duration."""

    kind: str
    replica: int
    site: str = "decode"
    at: int = 0
    prob: float = 0.0
    stall_s: float = 0.0

    def __post_init__(self):
        if self.kind not in _FAULT_KINDS:
            raise ValueError(f"fault kind must be one of {_FAULT_KINDS}, "
                             f"got '{self.kind}'")
        if self.site not in _FAULT_SITES:
            raise ValueError(f"fault site must be one of {_FAULT_SITES}, "
                             f"got '{self.site}'")
        if self.replica < 0:
            raise ValueError(f"fault replica must be >= 0, "
                             f"got {self.replica}")
        if (self.at <= 0) == (self.prob <= 0.0):
            raise ValueError(
                "a fault needs exactly one trigger: at=K (the K-th site "
                "event) or prob=P (seeded Bernoulli per event); got "
                f"at={self.at}, prob={self.prob}")
        if self.kind == "stall" and self.stall_s <= 0:
            raise ValueError("stall faults need stall_s > 0")
        if self.site != "decode" and self.kind != "crash":
            raise ValueError(
                f"site '{self.site}' supports crash only (stall/nanlogits "
                f"model decode-path failures)")


class FaultInjector:
    """Seeded fault injection over a replica's SlotKVCache programs.

    ``spec`` is a list of :class:`FaultSpec` or the CLI string grammar
    (``--serve-fault-spec``)::

        kind:key=val,key=val[;kind:...]

    e.g. ``crash:replica=0,iter=3`` (crash replica 0's 3rd decode
    iteration — a speculative verify round counts as one iteration, so
    spec-decoding replicas are killable too),
    ``crash:replica=1,prefill=2`` (during its 2nd prefill
    program — the kill-during-prefill-chunk case),
    ``crash:replica=0,verify=1`` (AFTER the verify step computed, BEFORE
    any commit — the kill-between-verify-and-commit case),
    ``crash:replica=0,handoff=1`` (a disaggregated prefill replica killed
    between prefill completion and decode admission — inside the KV
    extract, before the payload leaves the replica),
    ``stall:replica=1,iter=2,stall_s=0.5``, ``nanlogits:replica=0,iter=4``,
    ``crash:replica=0,prob=0.05`` (seeded Bernoulli per iteration).

    ``arm(replica_id, kv)`` wraps the instance's ``advance`` /
    ``insert``+``prefill_chunk`` / ``verify_block`` methods; every firing
    is recorded in ``fired`` with its site count.  One-shot per spec.
    """

    def __init__(self, spec: str | Iterable[FaultSpec], seed: int = 0):
        self.specs = (self.parse(spec) if isinstance(spec, str)
                      else list(spec))
        self._rng = np.random.default_rng(seed)
        self.seed = int(seed)
        self.fired: list[dict[str, Any]] = []
        self._done: set[int] = set()   # indices of one-shot specs fired

    @staticmethod
    def parse(spec: str) -> list[FaultSpec]:
        """CLI grammar → FaultSpec list (raises ValueError on any typo —
        the harness validates this pre-train, like every other serve
        flag)."""
        out: list[FaultSpec] = []
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            kind, colon, body = part.partition(":")
            kind = kind.strip()
            if not colon or kind not in _FAULT_KINDS:
                raise ValueError(
                    f"--serve-fault-spec entries are 'kind:key=val,...' "
                    f"with kind in {_FAULT_KINDS}; got '{part}'")
            kw: dict[str, Any] = {"kind": kind, "replica": -1}
            for item in body.split(","):
                item = item.strip()
                if not item:
                    continue
                key, eq, val = item.partition("=")
                key = key.strip()
                val = val.strip()
                if not eq:
                    raise ValueError(
                        f"--serve-fault-spec items must be key=val, got "
                        f"'{item}'")
                try:
                    if key == "replica":
                        kw["replica"] = int(val)
                    elif key == "iter":
                        kw["site"], kw["at"] = "decode", int(val)
                    elif key == "prefill":
                        kw["site"], kw["at"] = "prefill", int(val)
                    elif key == "verify":
                        kw["site"], kw["at"] = "verify", int(val)
                    elif key == "handoff":
                        kw["site"], kw["at"] = "handoff", int(val)
                    elif key == "prob":
                        kw["prob"] = float(val)
                    elif key == "stall_s":
                        kw["stall_s"] = float(val)
                    else:
                        raise ValueError(
                            f"unknown --serve-fault-spec key '{key}' "
                            f"(replica/iter/prefill/verify/handoff/prob/"
                            f"stall_s)")
                except ValueError as e:
                    if "fault-spec" in str(e):
                        raise
                    raise ValueError(
                        f"--serve-fault-spec value for '{key}' must be "
                        f"numeric, got '{val}'") from None
            if kw["replica"] < 0:
                raise ValueError(
                    f"--serve-fault-spec entry '{part}' needs replica=N")
            out.append(FaultSpec(**kw))
        if not out:
            raise ValueError("--serve-fault-spec parsed to no faults")
        return out

    # ------------------------------------------------------------- arming
    def _check(self, replica: int, site: str, count: int) -> FaultSpec | None:
        """The fault (if any) firing at this site event; one-shot specs
        fire at most once, prob specs draw from the injector's seeded rng
        (one draw per matching event — deterministic given the seed and
        the event schedule)."""
        for i, s in enumerate(self.specs):
            if s.replica != replica or s.site != site or i in self._done:
                continue
            hit = (count == s.at) if s.at else \
                (float(self._rng.random()) < s.prob)
            if hit:
                self._done.add(i)
                self.fired.append({"kind": s.kind, "replica": replica,
                                   "site": site, "count": count,
                                   "stall_s": s.stall_s or None})
                return s
        return None

    def arm(self, replica_id: int, kv: SlotKVCache) -> None:
        """Wrap this table's device-program entry points.  Instance-level
        wrappers: the class and every other table stay untouched."""
        if not any(s.replica == replica_id for s in self.specs):
            return
        counts = {"decode": 0, "prefill": 0, "verify": 0, "handoff": 0}
        injector = self

        orig_advance = kv.advance
        orig_insert = kv.insert
        orig_chunk = kv.prefill_chunk
        orig_verify = kv.verify_block
        orig_extract = kv.extract_handoff

        def advance(only=None):
            if only is None:   # draft catch-up steps are not iterations
                counts["decode"] += 1
                s = injector._check(replica_id, "decode", counts["decode"])
                if s is not None:
                    if s.kind == "crash":
                        raise InjectedFault(
                            f"injected crash: replica {replica_id} decode "
                            f"iteration {counts['decode']}")
                    if s.kind == "stall":
                        time.sleep(s.stall_s)
                    elif s.kind == "nanlogits":
                        toks = orig_advance(only)
                        bad = np.asarray(toks).copy()
                        # what NaN logits degrade argmax sampling into: an
                        # id no vocabulary contains — the fleet's host
                        # check rejects it before delivery
                        bad[:] = -1
                        return bad
            return orig_advance(only)

        def _prefill_gate():
            counts["prefill"] += 1
            s = injector._check(replica_id, "prefill", counts["prefill"])
            if s is not None:
                raise InjectedFault(
                    f"injected crash: replica {replica_id} prefill "
                    f"program {counts['prefill']}")

        def insert(prompt, slot=None):
            _prefill_gate()
            return orig_insert(prompt, slot)

        def prefill_chunk(slot, max_tokens=None):
            _prefill_gate()
            return orig_chunk(slot, max_tokens)

        def verify_block(block):
            # a speculative round's verify IS the target decode iteration
            # (draft-k → verify-1): decode-site faults count and fire
            # here too, or a spec-decoding replica would be unkillable
            # by `iter=K`
            counts["decode"] += 1
            s = injector._check(replica_id, "decode", counts["decode"])
            corrupt = False
            if s is not None:
                if s.kind == "crash":
                    raise InjectedFault(
                        f"injected crash: replica {replica_id} decode "
                        f"iteration {counts['decode']} (verify round)")
                if s.kind == "stall":
                    time.sleep(s.stall_s)
                corrupt = s.kind == "nanlogits"
            g = orig_verify(block)
            counts["verify"] += 1
            sv = injector._check(replica_id, "verify", counts["verify"])
            if sv is not None:
                # AFTER the verify program ran, BEFORE any commit_block:
                # the kill-between-verify-and-commit window — nothing of
                # this round may survive into the emitted stream
                raise InjectedFault(
                    f"injected crash: replica {replica_id} between verify "
                    f"{counts['verify']} and commit")
            if corrupt:
                g = np.asarray(g).copy()
                g[:] = -1
            return g

        def extract_handoff(slot):
            # fires BEFORE the KV leaves the replica: prefill is complete,
            # decode admission has not happened — the batcher's handoff
            # guard evicts the slot, so the crash must not leak blocks
            counts["handoff"] += 1
            s = injector._check(replica_id, "handoff", counts["handoff"])
            if s is not None:
                raise InjectedFault(
                    f"injected crash: replica {replica_id} handoff "
                    f"{counts['handoff']} (between prefill completion and "
                    f"decode admission)")
            return orig_extract(slot)

        kv.advance = advance
        kv.insert = insert
        kv.prefill_chunk = prefill_chunk
        kv.verify_block = verify_block
        kv.extract_handoff = extract_handoff


# --------------------------------------------------------------- journal

@dataclasses.dataclass
class _Entry:
    """One offered request's journal record (journal lock held for every
    mutation)."""

    req: Request
    status: str = "pending"   # pending | done | shed | lost | unserved
    replica: int | None = None
    attempts: int = 0
    phase: str = "prefill"    # disagg role the request currently sits in:
    #                           "prefill" until its KV is handed off, then
    #                           "decode"; a requeue flips it back (resume
    #                           re-prefills).  Homogeneous fleets never
    #                           leave "prefill".
    emitted: list[int] = dataclasses.field(default_factory=list)
    emit_t: list[float] = dataclasses.field(default_factory=list)
    assigned_t: float = 0.0
    first_assigned_t: float | None = None
    failed_at: float | None = None   # set at its replica's failure, until
    #                                  the first post-requeue emission
    completed_by: int | None = None
    finish_t: float | None = None


class RequestJournal:
    """Assignment + emission ledger: the exactly-once substrate.

    Every token delivery flows through :meth:`emit`, which accepts an
    emission only from the request's CURRENT replica assignment (the
    fence): after failover, a zombie replica's late emissions are counted
    (``fenced_emissions``) and dropped, never delivered.  A request
    completes when its emitted stream reaches ``max_new_tokens`` (or its
    EOS) — the same rule the batchers apply — so journal state and
    replica state cannot disagree about doneness.

    ``duplicate_emissions`` counts deliveries that would repeat an
    already-delivered position; the fence makes this structurally zero,
    and the counter measures it instead of assuming it (the chaos
    acceptance gate).
    """

    def __init__(self, requests: Iterable[Request]):
        self._lock = threading.RLock()
        self.entries: dict[int, _Entry] = {}
        self.load: dict[int, int] = {}    # replica -> live assigned count
        self.fenced_emissions = 0
        self.duplicate_emissions = 0
        self.done_count = 0               # O(1) completion counter (the
        #                                   swap-threshold check runs on
        #                                   every completion — a counts()
        #                                   scan there would be O(n²))
        self.requeues = 0                 # re-assignments (retries)
        self.requeued_rids: set[int] = set()
        self.recovery_s: list[float] = []
        for req in requests:
            if req.rid in self.entries:
                raise ValueError(f"duplicate rid {req.rid} in workload")
            self.entries[req.rid] = _Entry(req=req)

    # ------------------------------------------------------------ routing
    def assign(self, rid: int, replica: int, t: float,
               retry: bool = False, transfer: bool = False) -> None:
        """``transfer`` moves a live assignment between replicas without
        consuming retry budget — a KV handoff (prefill → decode) or an
        autoscale rebalance is a routing event, not a failure."""
        with self._lock:
            e = self.entries[rid]
            if e.replica is not None:
                self.load[e.replica] = self.load.get(e.replica, 1) - 1
            e.replica = replica
            if not transfer:
                e.attempts += 1
                e.phase = "prefill"   # fresh/retried work re-prefills
            e.assigned_t = t
            if e.first_assigned_t is None:
                e.first_assigned_t = t
            self.load[replica] = self.load.get(replica, 0) + 1
            if retry:
                self.requeues += 1
                self.requeued_rids.add(rid)

    def set_phase(self, rid: int, phase: str) -> None:
        with self._lock:
            self.entries[rid].phase = phase

    def least_loaded(self, replicas: Iterable[int]) -> int:
        """Front-end routing: the serving replica with the fewest live
        assignments (ties → lowest id, so routing is deterministic)."""
        with self._lock:
            return min(replicas,
                       key=lambda r: (self.load.get(r, 0), r))

    # ----------------------------------------------------------- emission
    def emit(self, rid: int, replica: int, token: int,
             t: float) -> tuple[bool, bool, float | None]:
        """Record one token delivery; returns ``(accepted, completed_now,
        recovery_s)``.  ``accepted`` False = fenced (stale assignment or
        already-terminal request) — the caller must NOT deliver."""
        with self._lock:
            e = self.entries.get(rid)
            if e is None:
                self.fenced_emissions += 1
                return False, False, None
            if e.status != "pending" or e.replica != replica:
                self.fenced_emissions += 1
                return False, False, None
            if len(e.emitted) >= e.req.max_new_tokens:
                # structurally unreachable (completion flips status); a
                # hit here is a real double-delivery — measured, not
                # assumed away
                self.duplicate_emissions += 1
                return False, False, None
            e.emitted.append(int(token))
            e.emit_t.append(float(t))
            recovery = None
            if e.failed_at is not None:
                recovery = float(t) - e.failed_at
                self.recovery_s.append(recovery)
                e.failed_at = None
            done = (len(e.emitted) >= e.req.max_new_tokens
                    or (e.req.eos_id is not None
                        and int(token) == e.req.eos_id))
            if done:
                e.status = "done"
                e.completed_by = replica
                e.finish_t = float(t)
                self.done_count += 1
                self.load[replica] = self.load.get(replica, 1) - 1
            return True, done, recovery

    # ----------------------------------------------------------- failover
    def pending_for(self, replica: int) -> list[int]:
        with self._lock:
            return sorted(rid for rid, e in self.entries.items()
                          if e.status == "pending" and e.replica == replica)

    def mark_failed(self, rids: Iterable[int], t: float) -> None:
        """Atomically fence a dead replica's requests: the assignment is
        CLEARED here (under the journal lock), so a zombie emission
        racing the failover — after the supervisor decided to fail over
        but before the requeue lands — is already stale.  Without this,
        such an emission would record a near-zero bogus recovery sample
        and could complete the stream mid-handoff."""
        with self._lock:
            for rid in rids:
                e = self.entries[rid]
                if e.status != "pending":
                    continue
                if e.failed_at is None:
                    e.failed_at = float(t)
                if e.replica is not None:
                    self.load[e.replica] = self.load.get(e.replica, 1) - 1
                    e.replica = None

    def retry_request(self, rid: int) -> Request | None:
        """The resume request for a failed-over rid: original prompt +
        already-emitted prefix re-prefilled, remaining budget only —
        greedy decode makes the continuation exactly what the dead
        replica would have produced.  None when the stream is already
        complete (crash after the last emission: nothing to resume)."""
        with self._lock:
            e = self.entries[rid]
            if e.status != "pending":
                return None   # completed/terminal while failing over
            remaining = e.req.max_new_tokens - len(e.emitted)
            if remaining <= 0:
                # crash landed after the last delivery: the stream is
                # complete, attributed to the replica that finished it
                e.status = "done"
                e.completed_by = e.replica
                e.finish_t = e.emit_t[-1] if e.emit_t else None
                self.done_count += 1
                if e.replica is not None:
                    self.load[e.replica] = self.load.get(e.replica, 1) - 1
                return None
            prompt = np.concatenate([
                np.asarray(e.req.prompt, np.int32).reshape(-1),
                np.asarray(e.emitted, np.int32)])
            return Request(rid=rid, prompt=prompt,
                           max_new_tokens=remaining,
                           arrival_s=e.req.arrival_s,
                           eos_id=e.req.eos_id)

    def finalize(self, rid: int, status: str) -> None:
        """Terminal non-completion states: shed / lost / unserved."""
        with self._lock:
            e = self.entries[rid]
            if e.status == "pending":
                e.status = status
                if e.replica is not None:
                    self.load[e.replica] = self.load.get(e.replica, 1) - 1

    def finalize_if_assigned(self, rid: int, replica: int,
                             status: str) -> None:
        """Fenced finalize: only the request's CURRENT replica may
        terminal-ize it (a zombie's shed report must not kill a request
        a survivor now owns — same fence as emission)."""
        with self._lock:
            e = self.entries.get(rid)
            if e is not None and e.status == "pending" \
                    and e.replica == replica:
                e.status = status
                self.load[replica] = self.load.get(replica, 1) - 1

    # ----------------------------------------------------------- summary
    def all_terminal(self) -> bool:
        with self._lock:
            return all(e.status != "pending"
                       for e in self.entries.values())

    def counts(self) -> dict[str, int]:
        with self._lock:
            c = {"done": 0, "shed": 0, "lost": 0, "unserved": 0,
                 "pending": 0}
            for e in self.entries.values():
                c[e.status] += 1
            return c

    def role_counts(self) -> dict[str, dict[str, int]]:
        """Terminal status counts partitioned by the phase each request
        ENDED in.  Phase is single-valued, so the two partitions sum to
        ``counts()`` exactly — a dropped handoff flips the request back
        to "prefill" and it is counted once, there; it cannot
        double-count or vanish."""
        with self._lock:
            out = {p: {"done": 0, "shed": 0, "lost": 0, "unserved": 0,
                       "pending": 0} for p in ("prefill", "decode")}
            for e in self.entries.values():
                out[e.phase][e.status] += 1
            return out

    def results(self) -> list[RequestResult]:
        """Fleet-level per-request results from the journal's emission
        timeline: TTFT from the ORIGINAL arrival (retries do not reset
        the clock — the PR 7/11 accounting discipline), ITL gaps from
        consecutive delivery times (a failover's recovery gap lands in
        the retried request's own ITL tail, where its reader felt it)."""
        with self._lock:
            out = []
            for rid in sorted(self.entries):
                e = self.entries[rid]
                if e.status != "done" or not e.emit_t:
                    continue
                lp = int(np.asarray(e.req.prompt).reshape(-1).shape[0])
                r = RequestResult(
                    rid=rid, prompt_len=lp, tokens=list(e.emitted),
                    arrival_s=e.req.arrival_s,
                    admitted_s=(e.first_assigned_t
                                if e.first_assigned_t is not None
                                else e.req.arrival_s),
                    first_token_s=e.emit_t[0],
                    finished_s=e.emit_t[-1],
                    itl_s=[b - a for a, b in zip(e.emit_t, e.emit_t[1:])],
                    queue_wait_s=max(
                        (e.first_assigned_t or e.req.arrival_s)
                        - e.req.arrival_s, 0.0),
                    prefill_s=max(e.emit_t[0]
                                  - (e.first_assigned_t
                                     or e.req.arrival_s), 0.0))
                out.append(r)
            return out


# ---------------------------------------------------------- shared clock

class _SharedClock:
    """One fleet-wide clock behind every replica's batcher: ``start`` is
    idempotent (each ``ContinuousBatcher.run`` calls it; only the first
    may zero the timeline) and virtual mutations are serialized — the
    fleet timeline is shared state, per-replica restarts must not rewind
    it."""

    def __init__(self, base):
        self._base = base
        self._lock = threading.Lock()
        self._started = False
        self.poll_slice_s = getattr(base, "poll_slice_s", float("inf"))

    def start(self) -> None:
        with self._lock:
            if not self._started:
                self._base.start()
                self._started = True

    def now(self) -> float:
        return self._base.now()

    def on_decode_iteration(self) -> None:
        with self._lock:
            self._base.on_decode_iteration()

    def on_prefill(self, tokens: int) -> None:
        with self._lock:
            self._base.on_prefill(tokens)

    def wait_until(self, t: float) -> None:
        self._base.wait_until(t)


class _FleetQueue(RequestQueue):
    """RequestQueue whose mutations are lock-guarded, so the supervisor
    can requeue a failed replica's requests INTO a survivor's live run —
    the retry re-enters the continuous-batching loop between decode
    iterations instead of waiting for the survivor's batch to drain."""

    def __init__(self, requests=()):
        super().__init__(requests)
        self._qlock = threading.RLock()

    def push(self, request):
        with self._qlock:
            super().push(request)

    def __len__(self):
        with self._qlock:
            return super().__len__()

    def next_arrival(self):
        with self._qlock:
            return super().next_arrival()

    def pop_ready(self, now):
        with self._qlock:
            return super().pop_ready(now)

    def depth(self, now=None):
        with self._qlock:
            return super().depth(now)

    def shed_ready(self, now, keep):
        with self._qlock:
            return super().shed_ready(now, keep)

    def drain(self) -> list[Request]:
        with self._qlock:
            items, self._items = list(self._items), []
            return items


# ---------------------------------------------------------------- replica

class _Replica:
    """Supervisor-side record of one batcher replica."""

    def __init__(self, rid: int, kv: SlotKVCache,
                 registry: MetricsRegistry):
        self.id = rid
        self.kv = kv
        self.batcher: ContinuousBatcher | None = None  # set by ReplicaSet
        self.registry = registry
        self.lease = LeaseManager(signals=())   # trigger()-driven only
        self.queue = _FleetQueue()
        self.state = "serving"                  # serving | dormant | failed
        self.generation = 0                     # weight-swap count
        self.busy = False
        self.completed = 0
        self.role: str | None = None            # prefill | decode | None
        self.serve_start: float | None = None   # replica_seconds interval
        self.idle_since: float | None = None    # autoscale scale-down timer
        self.failure: str | None = None
        self.last_progress = time.monotonic()
        self.work = threading.Event()
        self.stop = threading.Event()
        self.thread: threading.Thread | None = None


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Queue-driven replica-count policy (``--serve-autoscale MIN:MAX``).

    Scale-up fires when the fleet's ARRIVED backlog per admitting
    replica crosses ``high_watermark`` — queue depth is the leading
    overload signal (the PR 11 finding: depth p95 climbs before goodput
    falls), so capacity is added before the knee, not after shed rate
    proves it arrived too late.  Scale-down retires one replica with no
    arrived work after ``idle_s`` of continuous idleness, transferring
    its not-yet-arrived assignments to the survivors.  ``cooldown_s``
    spaces consecutive scaling actions so one burst cannot thrash the
    fleet, and ``slice_s`` bounds each replica's serving slice so the
    supervisor gets a decision point at least that often in fleet time
    (without it a sequential replica would serve its whole queue —
    including idle gaps — before the policy could react).
    """

    min_replicas: int = 1
    max_replicas: int = 0          # 0 = every replica in the set
    high_watermark: float = 4.0    # arrived backlog per admitting replica
    idle_s: float = 2.0            # continuous idleness before scale-down
    cooldown_s: float = 1.0        # min spacing between scaling actions
    slice_s: float = 4.0           # max serving slice between decisions

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"autoscale min_replicas must be >= 1, "
                f"got {self.min_replicas}")
        if self.max_replicas and self.max_replicas < self.min_replicas:
            raise ValueError(
                f"autoscale max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})")
        if self.high_watermark <= 0:
            raise ValueError(
                f"autoscale high_watermark must be > 0, "
                f"got {self.high_watermark}")
        if self.idle_s < 0 or self.cooldown_s < 0 or self.slice_s <= 0:
            raise ValueError(
                "autoscale idle_s/cooldown_s must be >= 0 and "
                "slice_s > 0")

    @staticmethod
    def parse(spec: str) -> "AutoscalePolicy":
        """``--serve-autoscale MIN:MAX`` grammar (e.g. ``1:4``)."""
        lo, colon, hi = spec.partition(":")
        try:
            if not colon:
                raise TypeError
            lo_i, hi_i = int(lo), int(hi)
        except (TypeError, ValueError):
            raise ValueError(
                f"--serve-autoscale must be MIN:MAX (e.g. 1:4), "
                f"got '{spec}'") from None
        return AutoscalePolicy(min_replicas=lo_i, max_replicas=hi_i)


class ReplicaSet:
    """N-replica serving fleet supervisor (module docstring).

    ``kvs`` is one ``SlotKVCache`` per replica (each replica owns its
    table; params may share device buffers).  ``clock`` is fleet-wide:
    ``WallClock`` (default) serves every replica on its own thread;
    ``VirtualClock`` drives replicas sequentially in id order —
    deterministic chaos schedules (``threaded`` overrides the default).

    ``fault_injector`` arms seeded faults on the matching replicas'
    tables before serving.  ``watchdog_timeout_s`` (threaded mode) fails
    over a replica whose scheduler loop made no heartbeat for that long
    while busy — the heartbeat ticks at every loop iteration and idle
    poll slice (``_replica_should_stop``), so a replica idling toward a
    future arrival is NOT a stall; one wedged inside a device program
    is.  The zombie is fenced, not killed: its late emissions are
    rejected by the journal.  The watchdog still cannot tell a stall
    from a first-program XLA compile (the host blocks inside the same
    call), so set the timeout above worst-case compile time or warm the
    tables before serving (``bench.py --serve`` warms; the harness's
    post-train window compiles in its first requests).

    ``retry_limit`` bounds per-request failover attempts (assignments
    beyond the first), with ``retry_backoff_s`` exponential arrival
    backoff; an exhausted request is terminal ``lost`` and counts into
    ``unserved_requests`` (conservation stays exact).

    Round 18 (all default-off — the defaults are class-, program- and
    summary-key-identical to the homogeneous fleet):

    - ``roles`` disaggregates the fleet (one ``"prefill"``/``"decode"``
      entry per replica): prefill replicas run admission + chunked
      prefill only and hand the finished KV to a decode replica as a
      serialized block payload (``SlotKVCache.extract_handoff``), taking
      ``handoff_s`` of simulated transfer time that lands inside the
      request's TTFT; decode replicas never share an iteration with a
      long prompt.  Retries re-prefill, so they route to the prefill
      side.
    - ``routing="affinity"`` keys fresh requests on the chained SHA-256
      digest of their first prefix block and lands shared-prefix
      traffic where that block is already resident (falling back to
      least-loaded for unkeyed prompts and retries).
    - ``autoscale`` (an :class:`AutoscalePolicy` or ``"MIN:MAX"``)
      drives the serving-replica count from arrived queue depth;
      replicas above the floor start dormant and ``replica_seconds``
      (integral of serving time) lands in the summary.
    - ``parallel_lanes`` (VirtualClock, sequential driver) gives each
      replica its own virtual-time lane so N replicas genuinely overlap
      in fleet time — cross-replica events (handoffs, retries) carry
      absolute stamps and the receiving lane jumps forward, never back.
      Fleet elapsed time is then the max over lanes.
    """

    def __init__(self, kvs: list[SlotKVCache], *, tracer=NULL_TRACER,
                 clock=None, threaded: bool | None = None,
                 prefill_chunk: int = 0, queue_cap: int = 0, slo=None,
                 draft_kvs: list[SlotKVCache] | None = None,
                 draft_k: int = 4, retry_limit: int = 2,
                 retry_backoff_s: float = 0.0,
                 watchdog_timeout_s: float = 0.0,
                 fault_injector: FaultInjector | None = None,
                 timeline=None,
                 roles: list[str] | None = None,
                 routing: str = "least-loaded",
                 autoscale: AutoscalePolicy | str | None = None,
                 handoff_s: float = 0.0,
                 parallel_lanes: bool = False,
                 roofline=None, multi_step: int | None = None):
        if not kvs:
            raise ValueError("ReplicaSet needs at least one SlotKVCache")
        if draft_kvs is not None and len(draft_kvs) != len(kvs):
            raise ValueError(
                f"draft_kvs must pair replicas 1:1 ({len(draft_kvs)} "
                f"drafts vs {len(kvs)} replicas)")
        if retry_limit < 0:
            raise ValueError(f"retry_limit must be >= 0, got {retry_limit}")
        if routing not in ("least-loaded", "affinity"):
            raise ValueError(
                f"routing must be 'least-loaded' or 'affinity', "
                f"got '{routing}'")
        if roles is not None:
            roles = [str(r) for r in roles]
            if len(roles) != len(kvs):
                raise ValueError(
                    f"roles must pair replicas 1:1 ({len(roles)} roles "
                    f"vs {len(kvs)} replicas)")
            bad = sorted(set(roles) - {"prefill", "decode"})
            if bad:
                raise ValueError(
                    f"roles must be 'prefill' or 'decode', got {bad}")
            if "prefill" not in roles or "decode" not in roles:
                raise ValueError(
                    "a disaggregated fleet needs at least one prefill "
                    "AND one decode replica")
            if draft_kvs is not None:
                raise ValueError(
                    "speculative decoding is not supported in a "
                    "disaggregated fleet (draft KV state does not ride "
                    "the handoff payload)")
        if isinstance(autoscale, str):
            autoscale = AutoscalePolicy.parse(autoscale)
        if autoscale is not None:
            # with roles the policy drives each role pool independently
            # (the MIN:MAX range is clamped per group — see _role_range);
            # homogeneous fleets keep the exact round-18 validation
            n_max = autoscale.max_replicas or len(kvs)
            if roles is None and \
                    not autoscale.min_replicas <= n_max <= len(kvs):
                raise ValueError(
                    f"autoscale range {autoscale.min_replicas}:{n_max} "
                    f"must fit in the {len(kvs)}-replica set")
        if handoff_s < 0:
            raise ValueError(f"handoff_s must be >= 0, got {handoff_s}")
        self.tracer = tracer
        base_clock = clock if clock is not None else WallClock()
        self.clock = _SharedClock(base_clock)
        if threaded is None:
            threaded = not isinstance(base_clock, VirtualClock)
        self.threaded = bool(threaded)
        if parallel_lanes:
            if not isinstance(base_clock, VirtualClock):
                raise ValueError(
                    "parallel_lanes needs a VirtualClock base (wall time "
                    "already overlaps replicas via threads)")
            if self.threaded:
                raise ValueError(
                    "parallel_lanes is a sequential-driver feature "
                    "(threaded=False)")
        self.roles = roles
        self.routing = routing
        self.autoscale = autoscale
        if multi_step is not None and int(multi_step) < 1:
            raise ValueError(
                f"multi_step must be >= 1, got {multi_step}")
        self.multi_step = None if multi_step is None else int(multi_step)
        self.handoff_s = float(handoff_s)
        self.parallel_lanes = bool(parallel_lanes)
        self.slo = slo
        self.retry_limit = int(retry_limit)
        self.retry_backoff_s = float(retry_backoff_s)
        self.watchdog_timeout_s = float(watchdog_timeout_s)
        self.fault_injector = fault_injector
        # --timeline: ONE shared sampler; per-replica series are keyed by
        # replica id (batchers tag their own series, the coordinator
        # samples fleet-level load/admitting/backlog gauges).  Concurrent
        # replica threads write DISTINCT series keys, so the host-side
        # ring writes never contend on one buffer.
        self.timeline = timeline
        # --roofline: ONE Roofline (device peaks + the analytic cost model
        # for the replicas' shared model) handed to every batcher; each
        # tallies its own host-side phase counters, and _summary sums
        # them across replicas flag-gated (key-set parity when off)
        self.roofline = roofline
        self.vocab = int(kvs[0].dm.vocab_size)
        self.draft_kvs = draft_kvs
        self._affinity_block = int(getattr(kvs[0], "prefix_block", 0) or 0)
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._lanes: dict[int, _SharedClock] = {}
        self.replicas: list[_Replica] = []
        for i, kv in enumerate(kvs):
            registry = MetricsRegistry()
            replica = _Replica(i, kv, registry)
            role = None if roles is None else roles[i]
            replica.role = role
            rclock = self.clock
            if parallel_lanes:
                # each replica advances its own virtual lane; all lanes
                # share the epoch (start() zeroes them together in run())
                rclock = _SharedClock(VirtualClock(
                    tick=base_clock.tick,
                    prefill_token_tick=base_clock.prefill_token_tick))
                self._lanes[i] = rclock
            replica.batcher = ContinuousBatcher(
                kv, tracer=tracer, clock=rclock, mode="continuous",
                # decode replicas restore handed-off KV instead of
                # prefilling, and never shed (a handoff is admitted work)
                prefill_chunk=(0 if role == "decode" else prefill_chunk),
                metrics=registry,
                queue_cap=(0 if role == "decode" else queue_cap),
                should_stop=(lambda iters, r=replica:
                             self._replica_should_stop(r, iters)),
                draft_kv=(draft_kvs[i] if draft_kvs is not None else None),
                draft_k=draft_k, timeline=timeline, timeline_tag=i,
                role=role, roofline=roofline, multi_step=multi_step,
                handoff_out=(self._handoff_hook(replica)
                             if role == "prefill" else None))
            self.replicas.append(replica)
            if fault_injector is not None:
                fault_injector.arm(i, kv)
        # swap state survives _reset_run_state: schedule_swap may be
        # called BEFORE run(), and generations accumulate across windows
        self.swap_generations = 0
        self._swap: dict[str, Any] | None = None
        self._draining = 0
        # fleet-level ledgers, reset per run()
        self._reset_run_state()

    # ------------------------------------------------------------- state
    def _reset_run_state(self) -> None:
        self.journal: RequestJournal | None = None
        self.min_admitting_replicas: int | None = None
        self._failovers: list[dict[str, Any]] = []
        self._watchdog_stalls = 0
        self._preempted: str | None = None
        self._on_token: Callable[[int, int], None] | None = None
        self._sums: dict[str, float] = {}
        self._spec_sums: dict[str, int] = {}
        self._prefix_sums: dict[str, int] = {}
        self._paged_sums: dict[str, int] = {}   # zero-copy/CoW across replicas
        self._phase_sums: dict[str, float] = {}
        # --roofline ledgers (identically empty flag-off): fleet totals of
        # the batchers' analytic counters + the same split per replica id
        self._rf_sums: dict[str, float] = {}
        self._rf_replica: dict[int, dict[str, float]] = {}
        self._shed_count = 0
        self._run_summaries = 0
        # round-18 per-run ledgers (all identically zero/empty flag-off)
        self._affinity: dict[bytes, int] = {}
        self._handoffs_initiated = 0
        self._handoffs_delivered = 0
        self._handoffs_dropped = 0
        self._replica_seconds = 0.0
        # per-role serving-time split (round 20): keys are replica roles
        # (None for a homogeneous fleet) — sums to _replica_seconds
        self._role_seconds: dict[str | None, float] = {}
        self._scale_ups = 0
        self._scale_downs = 0
        self._scale_events: list[dict[str, Any]] = []
        # per-role cooldown clocks (round 20): with roles each pool
        # scales on its own queue-watermark signal and cooldown — one
        # pool's action never starves the other's (homogeneous fleets
        # use the single None key, exactly the round-18 behavior)
        self._last_scale_t: dict[str | None, float] = {}
        self._slice_end: dict[int, float] = {}
        self._t_start = 0.0
        self._run_live = False

    def _serving(self) -> list[_Replica]:
        return [r for r in self.replicas if r.state == "serving"]

    def _clock_for(self, replica: _Replica):
        """The clock a replica's events are stamped with: its own lane
        under ``parallel_lanes``, the shared fleet clock otherwise."""
        return self._lanes.get(replica.id, self.clock)

    def _fleet_now(self) -> float:
        """Fleet time: max over replica lanes (a lane only ever jumps
        forward, so the max is monotone), or the shared clock."""
        if self._lanes:
            return max(lane.now() for lane in self._lanes.values())
        return self.clock.now()

    def _note_admitting(self) -> None:
        """Track the fleet's minimum admitting-replica count (serving and
        not draining) — the zero-downtime claim is measured, not assumed."""
        admitting = len(self._serving()) - self._draining
        if (self.min_admitting_replicas is None
                or admitting < self.min_admitting_replicas):
            self.min_admitting_replicas = admitting

    def _sample_timeline(self) -> None:
        """Fleet-level --timeline gauges, sampled by the run coordinator
        at its existing poll boundary: per-replica live load (a killed
        replica's lane drops to zero — the failover counter cliff the
        e2e test asserts), admitting-replica count, and the journal's
        retry backlog.  Pure host reads; None = off."""
        tl = self.timeline
        if tl is None or self.journal is None:
            return
        for r in self.replicas:
            load = (self.journal.load.get(r.id, 0)
                    if r.state == "serving" else 0)
            tl.sample("replica_load", load, replica=r.id)
        counts = self.journal.counts()
        gauges = {
            "admitting_replicas": len(self._serving()) - self._draining,
            "journal_pending": counts.get("pending", 0),
            "journal_retries": self.journal.requeues,
        }
        if self.roles is not None:
            # per-role load: where the fleet's live assignments sit —
            # the disaggregation dashboards' headline gauge pair
            for role in ("prefill", "decode"):
                gauges[f"{role}_load"] = sum(
                    self.journal.load.get(r.id, 0)
                    for r in self.replicas
                    if r.role == role and r.state == "serving")
        if self.autoscale is not None:
            gauges["serving_replicas"] = len(self._serving())
        tl.sample_many(gauges, group="fleet")

    def _replica_should_stop(self, replica: _Replica,
                             iters: int) -> str | None:
        """The per-replica drain hook — and the watchdog's heartbeat:
        the batcher consults it at every scheduler-loop iteration AND
        every idle poll slice, so a replica legitimately idling toward a
        future arrival keeps ticking while one wedged inside a device
        program (or an injected stall) freezes — exactly the distinction
        `busy` alone cannot make."""
        replica.last_progress = time.monotonic()
        reason = replica.lease.should_stop(iters)
        if reason is not None:
            return reason
        if self.autoscale is not None:
            end = self._slice_end.get(replica.id)
            if end is not None and self._clock_for(replica).now() >= end:
                # bounded serving slice: drain in-flight work and hand
                # control back so the autoscaler gets a decision point
                return "autoscale_slice"
        return None

    # ------------------------------------------------------------ routing
    def _route_candidates(self) -> list[_Replica]:
        """Replicas a fresh (or retried) request may land on: the whole
        serving set — or, disaggregated, the prefill side only (a resume
        re-prefills, so retries go there too)."""
        serving = self._serving()
        if self.roles is None:
            return serving
        return [r for r in serving if r.role == "prefill"]

    def _affinity_key(self, prompt) -> bytes | None:
        """The chained SHA-256 digest of the prompt's FIRST prefix block
        — the same key the prefix pool stores for that block, so routing
        on it lands a request where its shared prefix is already warm.
        None for prompts shorter than one block (nothing shareable to
        key on)."""
        blk = self._affinity_block
        p = np.asarray(prompt, np.int32).reshape(-1)
        if blk <= 0 or p.shape[0] < blk:
            return None
        h = hashlib.sha256(b"")
        h.update(p[:blk].tobytes())
        return h.digest()

    def _route(self, req: Request, retry: bool = False,
               from_replica: int | None = None,
               reason: str | None = None,
               at: float | None = None) -> bool:
        """Assign ``req`` among the route candidates — prefix-affinity
        first when enabled (a fresh request with a keyable first block
        follows earlier traffic with the same block), least-loaded
        otherwise; False when no replica can take it (the caller marks
        it lost)."""
        candidates = self._route_candidates()
        if not candidates:
            return False
        target = None
        if self.routing == "affinity" and not retry:
            key = self._affinity_key(req.prompt)
            if key is not None:
                by_id = {r.id: r for r in candidates}
                known = self._affinity.get(key)
                if known is not None and known in by_id:
                    target = by_id[known]
                else:
                    target = self.replicas[self.journal.least_loaded(
                        list(by_id))]
                    self._affinity[key] = target.id
        if target is None:
            target = self.replicas[self.journal.least_loaded(
                [r.id for r in candidates])]
        now = self.clock.now() if at is None else float(at)
        self.journal.assign(req.rid, target.id, now, retry=retry)
        if retry:
            entry = self.journal.entries[req.rid]
            backoff = (self.retry_backoff_s
                       * (2 ** max(entry.attempts - 2, 0)))
            req = dataclasses.replace(
                req, arrival_s=max(req.arrival_s, now + backoff))
            self.tracer.event(
                "requeue", rid=req.rid, from_replica=from_replica,
                to_replica=target.id, attempt=entry.attempts,
                arrival_s=entry.req.arrival_s, reason=reason,
                emitted=len(entry.emitted))
            self.tracer.counter("requeued_requests")
        target.queue.push(req)
        target.work.set()
        return True

    # ----------------------------------------------------------- emission
    def _emit_hook(self, replica: _Replica):
        def hook(rid: int, token: int) -> None:
            tok = int(token)
            if tok < 0 or tok >= self.vocab:
                # the cheap host check: two comparisons per token.  An id
                # outside the vocabulary is what nonfinite logits degrade
                # sampling into — fail the replica BEFORE delivery.
                raise CorruptionDetected(
                    f"replica {replica.id} emitted token id {tok} outside "
                    f"[0, {self.vocab}) for rid {rid} — nonfinite-logits "
                    f"corruption")
            accepted, done, _recovery = self.journal.emit(
                rid, replica.id, tok, self._clock_for(replica).now())
            replica.last_progress = time.monotonic()
            if not accepted:
                return   # fenced: counted by the journal, never delivered
            if self._on_token is not None:
                self._on_token(rid, tok)
            if done:
                replica.completed += 1
                with self._cond:
                    self._maybe_start_swap()
                    self._cond.notify_all()
        return hook

    # ----------------------------------------------------------- failover
    def _on_replica_failure(self, replica: _Replica, exc: BaseException,
                            kind: str | None = None) -> None:
        with self._lock:
            if replica.state == "failed":
                return   # watchdog + exception can race; first wins
            replica.state = "failed"
            replica.failure = f"{type(exc).__name__}: {exc}"
            self._note_admitting()
            now = self._clock_for(replica).now()
            if replica.serve_start is not None:
                self._replica_seconds += max(now - replica.serve_start, 0.0)
                replica.serve_start = None
            kind = kind or (
                "injected" if isinstance(exc, InjectedFault) else
                "corruption" if isinstance(exc, CorruptionDetected) else
                "crash")
            pending = self.journal.pending_for(replica.id)
            # fence first (a zombie's next emission must already be
            # stale), then requeue
            self.journal.mark_failed(pending, now)
            self.tracer.event("replica_failure", replica=replica.id,
                              kind=kind, error=replica.failure,
                              requests=len(pending))
            self.tracer.counter("replica_failures")
            self._failovers.append({
                "replica": replica.id, "kind": kind,
                "error": replica.failure, "t": now,
                "requeued": len(pending)})
            # a failed replica scheduled for a swap must not wedge the
            # rotation
            if self._swap is not None and self._swap.get("active") \
                    == replica.id:
                self._advance_swap()
            # queued-but-unadmitted requests still sit in its queue; the
            # journal assignment is the routing truth either way
            replica.queue.drain()
            for rid in pending:
                self._requeue(rid, replica.id,
                              reason=f"replica_failure:{kind}", at=now)
            self._cond.notify_all()

    def _requeue(self, rid: int, from_replica: int, reason: str,
                 at: float | None = None) -> None:
        entry = self.journal.entries[rid]
        retries_used = max(entry.attempts - 1, 0)
        if retries_used >= self.retry_limit:
            self.journal.finalize(rid, "lost")
            self.tracer.event("retry_exhausted", rid=rid,
                              attempts=entry.attempts,
                              limit=self.retry_limit)
            return
        req = self.journal.retry_request(rid)
        if req is None:
            return   # stream already complete — nothing to resume
        if not self._route(req, retry=True, from_replica=from_replica,
                           reason=reason, at=at):
            self.journal.finalize(rid, "lost")
            self.tracer.event("retry_exhausted", rid=rid,
                              attempts=entry.attempts,
                              limit=self.retry_limit,
                              error="no surviving replica")

    # ---------------------------------------------------------- handoff
    def _handoff_hook(self, replica: _Replica):
        """The prefill batcher's ``handoff_out`` callback (runs inline in
        the prefill replica's serving loop, right after the slot was
        extracted and evicted)."""
        def hook(req: Request, payload: dict[str, Any]) -> None:
            self._deliver_handoff(replica, req, payload)
        return hook

    def _deliver_handoff(self, src: _Replica, req: Request,
                         payload: dict[str, Any]) -> None:
        """Route a finished prefill's serialized KV to a decode replica.

        The payload rides the fleet queue inside the request
        (``Request.handoff``); the decode batcher restores it into a
        slot instead of prefilling.  Transfer takes ``handoff_s`` of
        fleet time, charged inside the request's TTFT (arrival →
        first-token, the PR 7 discipline).  With no decode replica
        serving, the handoff is DROPPED and the request re-enters the
        retry path (re-prefill on a surviving prefill replica) — the
        ledger identity ``initiated == delivered + dropped`` and the
        journal's single-phase accounting keep a dropped handoff from
        double-counting or vanishing."""
        with self._lock:
            self._handoffs_initiated += 1
            src_t = self._clock_for(src).now()
            decode = [r for r in self._serving() if r.role == "decode"]
            if not decode:
                self._handoffs_dropped += 1
                self.tracer.event("handoff_dropped", rid=req.rid,
                                  from_replica=src.id)
                self.tracer.counter("handoffs_dropped")
                # fence first (same discipline as failover), then retry
                self.journal.mark_failed([req.rid], src_t)
                self._requeue(req.rid, src.id, reason="handoff_no_decode",
                              at=src_t)
                return
            target = self.replicas[self.journal.least_loaded(
                [r.id for r in decode])]
            arrive = src_t + self.handoff_s
            # a transfer, not a retry: no attempt consumed, phase flips
            self.journal.assign(req.rid, target.id, arrive, transfer=True)
            self.journal.set_phase(req.rid, "decode")
            self._handoffs_delivered += 1
            hreq = dataclasses.replace(
                req, handoff=payload,
                arrival_s=max(req.arrival_s, arrive))
            self.tracer.event("kv_handoff", rid=req.rid,
                              from_replica=src.id, to_replica=target.id,
                              blocks=len(payload["blocks"]),
                              length=int(payload["length"]))
            self.tracer.counter("handoffs_delivered")
            target.queue.push(hreq)
            target.work.set()
            self._cond.notify_all()

    # ---------------------------------------------------------- autoscale
    def _autoscale_tick(self) -> None:
        """One scaling decision, evaluated at the run coordinator's poll
        boundary (threaded) or between sequential rounds.  At most one
        action per cooldown window: scale-up wakes ONE dormant replica
        when arrived backlog per admitting replica crosses the high
        watermark; scale-down retires ONE replica that held no arrived
        work for ``idle_s``.  Also re-arms every serving replica's
        bounded serving slice."""
        pol = self.autoscale
        if pol is None or self.journal is None:
            return
        with self._lock:
            serving = self._serving()
            if not serving:
                return
            now = self._fleet_now()
            for r in serving:
                self._slice_end[r.id] = (self._clock_for(r).now()
                                         + pol.slice_s)
            # the decision runs PER ROLE GROUP (round 20): a disaggregated
            # fleet's prefill and decode pools see different backlogs —
            # prefill queues hold routed arrivals, decode queues hold
            # handed-off streams — so each pool scales on its own
            # watermark signal, range, and cooldown.  A homogeneous fleet
            # has the single group None: exactly the round-18 decision.
            for role in self._role_groups():
                self._autoscale_tick_role(role, now)

    def _role_groups(self) -> list[str | None]:
        return ([None] if self.roles is None
                else sorted(set(self.roles)))

    def _role_range(self, role: str | None) -> tuple[int, int]:
        """The policy's MIN:MAX clamped to the role group's size (a 1:4
        policy over a 1P:3D split drives prefill at 1:1 and decode at
        1:3); at least one replica per group always serves — a pool
        scaled to zero could never observe the backlog that should wake
        it."""
        pol = self.autoscale
        group = [r for r in self.replicas if r.role == role]
        n_max = min(pol.max_replicas or len(group), len(group))
        n_min = max(min(pol.min_replicas, n_max), 1)
        return n_min, n_max

    def _autoscale_tick_role(self, role: str | None, now: float) -> None:
        pol = self.autoscale
        serving = [r for r in self._serving() if r.role == role]
        if not serving:
            return
        n_min, n_max = self._role_range(role)
        admitting = max(len(serving) - self._draining, 1)
        backlog = sum(r.queue.depth(now) for r in serving)
        # idle bookkeeping runs every tick (cooldown only gates the
        # actions, not the timers)
        idle = []
        for r in serving:
            if (r.queue.depth(now) == 0 and not r.busy
                    and not (self._swap is not None
                             and self._swap.get("active") == r.id)):
                if r.idle_since is None:
                    r.idle_since = now
                idle.append(r)
            else:
                r.idle_since = None
        last = self._last_scale_t.get(role)
        if last is not None and now - last < pol.cooldown_s:
            return
        if (backlog > pol.high_watermark * admitting
                and len(serving) < n_max):
            dormant = [r for r in self.replicas
                       if r.state == "dormant" and r.role == role]
            if dormant:
                self._scale_up(dormant[0], now, backlog)
                return
        if len(serving) > n_min:
            for r in reversed(idle):   # highest id retires first
                if now - r.idle_since >= pol.idle_s:
                    self._scale_down(r, now)
                    return

    def _scale_up(self, replica: _Replica, now: float,
                  backlog: int) -> None:
        """Wake a dormant replica and rebalance queued work over the
        grown fleet (routing happened upfront — without the rebalance
        the new replica would idle to the end of the trace)."""
        replica.state = "serving"
        replica.idle_since = None
        replica.serve_start = now
        self._scale_ups += 1
        self._last_scale_t[replica.role] = now
        event = {"action": "up", "replica": replica.id, "t": now,
                 "backlog": int(backlog), "serving": len(self._serving())}
        if self.roles is not None:
            event["role"] = replica.role
        self._scale_events.append(event)
        self.tracer.event("scale_up", replica=replica.id,
                          backlog=int(backlog),
                          serving=len(self._serving()))
        self.tracer.counter("scale_ups")
        # rebalance strictly WITHIN the role group: a woken decode
        # replica must never receive un-prefilled arrivals (and vice
        # versa) — role partitions are a routing invariant
        moved: list[Request] = []
        group = [r for r in self._serving() if r.role == replica.role]
        for r in group:
            if r.id != replica.id:
                moved.extend(r.queue.drain())
        serving_ids = [r.id for r in group]
        for req in sorted(moved, key=lambda q: (q.arrival_s, q.rid)):
            target = self.replicas[self.journal.least_loaded(serving_ids)]
            self.journal.assign(req.rid, target.id, now, transfer=True)
            target.queue.push(req)
            target.work.set()
        if self.threaded and self._run_live:
            self._start_worker(replica)

    def _scale_down(self, replica: _Replica, now: float) -> None:
        """Retire one idle serving replica; its not-yet-arrived
        assignments transfer to the survivors (a transfer, not a retry —
        no attempt consumed)."""
        replica.state = "dormant"
        replica.idle_since = None
        if replica.serve_start is not None:
            span = max(now - replica.serve_start, 0.0)
            self._replica_seconds += span
            self._role_seconds[replica.role] = (
                self._role_seconds.get(replica.role, 0.0) + span)
            replica.serve_start = None
        self._scale_downs += 1
        self._last_scale_t[replica.role] = now
        event = {"action": "down", "replica": replica.id, "t": now,
                 "serving": len(self._serving())}
        if self.roles is not None:
            event["role"] = replica.role
        self._scale_events.append(event)
        self.tracer.event("scale_down", replica=replica.id,
                          serving=len(self._serving()))
        self.tracer.counter("scale_downs")
        replica.work.set()   # the worker observes dormant and exits
        leftovers = replica.queue.drain()
        serving_ids = [r.id for r in self._serving()
                       if r.role == replica.role]
        for req in sorted(leftovers, key=lambda q: (q.arrival_s, q.rid)):
            if not serving_ids:
                self.journal.finalize(req.rid, "lost")
                continue
            target = self.replicas[self.journal.least_loaded(serving_ids)]
            self.journal.assign(req.rid, target.id, now, transfer=True)
            target.queue.push(req)
            target.work.set()

    # ---------------------------------------------------------- hot swap
    def schedule_swap(self, params, draft_params=None, *,
                      after_completions: int = 0) -> None:
        """Schedule a zero-downtime weight hot-swap: once
        ``after_completions`` requests have completed fleet-wide (0 =
        immediately), replicas drain and swap one at a time — the fleet
        never drops below N−1 admitting replicas.  Call before or during
        ``run``; ``swap_generations`` increments when every serving
        replica carries the new weights."""
        with self._lock:
            if self._swap is not None:
                raise RuntimeError("a weight swap is already in flight")
            self._swap = {"params": params, "draft_params": draft_params,
                          "after": int(after_completions),
                          "queue": None, "active": None}
            self._maybe_start_swap()

    def _maybe_start_swap(self) -> None:
        sw = self._swap
        if sw is None or sw["queue"] is not None or self.journal is None:
            return
        if self.journal.done_count < sw["after"]:
            return
        sw["queue"] = [r.id for r in self._serving()]
        self._advance_swap()

    def _advance_swap(self) -> None:
        sw = self._swap
        if sw is None:
            return
        if sw["active"] is not None:
            self._draining -= 1
            sw["active"] = None
        while sw["queue"]:
            rid = sw["queue"].pop(0)
            replica = self.replicas[rid]
            if replica.state != "serving":
                continue
            sw["active"] = rid
            self._draining += 1
            self._note_admitting()
            replica.lease.trigger("weight_swap")
            replica.work.set()
            return
        # rotation complete: one whole fleet generation
        self.swap_generations += 1
        self.tracer.event("weight_swap_generation",
                          generation=self.swap_generations)
        self._swap = None
        self._cond.notify_all()

    def _finish_pending_swap(self) -> None:
        """Complete a STARTED swap rotation once serving work is done:
        every remaining replica is idle, so each turn installs the new
        weights with nothing in flight.  A trigger can land exactly as
        the active replica's run loop empties — the run then exits
        without the drain marker, and without this sweep the rotation
        would stall one replica short of a generation."""
        for _ in range(len(self.replicas) + 1):
            with self._lock:
                sw = self._swap
                if sw is None or sw.get("queue") is None \
                        or sw.get("active") is None:
                    return
                active = self.replicas[sw["active"]]
            self._perform_swap(active)

    def _perform_swap(self, replica: _Replica) -> None:
        """The drained replica installs the new weights between compiled-
        program dispatches and resumes serving on the same lease."""
        with self._lock:
            sw = self._swap
            if sw is None or sw["active"] != replica.id:
                return
            replica.kv.swap_params(sw["params"])
            if self.draft_kvs is not None and sw["draft_params"] is not None:
                self.draft_kvs[replica.id].swap_params(sw["draft_params"])
            replica.lease.reset_trigger()
            replica.generation += 1
            self.tracer.event("weight_swap", replica=replica.id,
                              generation=replica.generation)
            self._advance_swap()
            replica.work.set()

    # --------------------------------------------------------- the loop
    def _serve_once(self, replica: _Replica) -> None:
        """One batcher run over the replica's queue; failures fail over,
        a weight_swap drain performs the swap and leaves the leftover
        queue for the next run."""
        replica.busy = True
        replica.last_progress = time.monotonic()
        try:
            summary = replica.batcher.run(
                replica.queue, on_token=self._emit_hook(replica))
        except BaseException as e:  # noqa: BLE001 — any death fails over
            replica.busy = False
            self._on_replica_failure(replica, e)
            return
        replica.busy = False
        if replica.state == "failed":
            # a fenced zombie's late summary is not fleet truth: the
            # watchdog already failed this replica over mid-run, its
            # requests were requeued, and absorbing would double-count
            # the ledgers — worse, its shed_rids would finalize requests
            # a survivor now owns, truncating their streams
            return
        self._absorb(replica, summary)
        if summary.get("preempted") == "weight_swap":
            self._perform_swap(replica)
        elif summary.get("preempted") == "autoscale_slice":
            # benign: the slice expired; dis-arm it so the next run is
            # not preempted on entry (the next tick re-arms)
            self._slice_end.pop(replica.id, None)
        with self._cond:
            self._cond.notify_all()

    def _absorb(self, replica: _Replica, s: dict[str, Any]) -> None:
        """Fold one successful run summary into the fleet ledgers (a run
        that died contributes nothing here; the journal still has every
        delivered token)."""
        with self._lock:
            self._run_summaries += 1
            for k in ("decode_iterations", "prefills", "prefill_chunks",
                      "prefill_tokens", "decode_tokens", "idle_polls"):
                self._sums[k] = self._sums.get(k, 0) + (s.get(k) or 0)
            spec = s.get("speculative")
            if spec:
                for k in ("proposed_tokens", "accepted_tokens",
                          "rejected_tokens", "draft_iterations",
                          "draft_catchup_steps"):
                    self._spec_sums[k] = (self._spec_sums.get(k, 0)
                                          + spec.get(k, 0))
            pc = s.get("prefix_cache")
            if pc:
                for k, v in pc.items():
                    if isinstance(v, int):
                        self._prefix_sums[k] = (self._prefix_sums.get(k, 0)
                                                + v)
            pg = s.get("paged")
            if pg:
                # counter deltas sum across replicas; pool-state keys
                # (blocks_in_use/utilization) are read live at summary
                # time from the replica kvs instead
                for k in ("zero_copy_hits", "zero_copy_blocks",
                          "zero_copy_tokens", "cow_copies",
                          "block_deferrals"):
                    self._paged_sums[k] = (self._paged_sums.get(k, 0)
                                           + pg.get(k, 0))
            for k, v in (s.get("device_phase_s") or {}).items():
                self._phase_sums[k] = self._phase_sums.get(k, 0.0) + v
            # multi-step dispatch ledger (keys absent flag-off): host
            # dispatches and host-gap seconds sum across replica windows
            if "serve_dispatches" in s:
                self._sums["serve_dispatches"] = (
                    self._sums.get("serve_dispatches", 0)
                    + (s.get("serve_dispatches") or 0))
                self._sums["serve_host_gap_s"] = (
                    self._sums.get("serve_host_gap_s", 0.0)
                    + (s.get("serve_host_gap_s") or 0.0))
            rf = s.get("roofline")
            if rf:
                per = self._rf_replica.setdefault(replica.id, {})
                for k in ("prefill_model_flops", "decode_model_flops",
                          "decode_must_read_bytes", "prefill_s",
                          "decode_s"):
                    v = float(rf.get(k) or 0.0)
                    self._rf_sums[k] = self._rf_sums.get(k, 0.0) + v
                    per[k] = per.get(k, 0.0) + v
            self._shed_count += s.get("shed_requests") or 0
            for rid in s.get("shed_rids") or ():
                self.journal.finalize_if_assigned(rid, replica.id, "shed")

    # sequential (deterministic) driver -------------------------------
    def _run_sequential(self, should_stop) -> None:
        while True:
            if should_stop is not None and self._preempted is None:
                reason = should_stop(0)
                if reason:
                    self._preempted = reason
                    break
            progressed = False
            self._sample_timeline()
            self._autoscale_tick()
            for replica in self.replicas:
                if replica.state != "serving":
                    continue
                if self._swap is not None \
                        and self._swap.get("active") == replica.id \
                        and not len(replica.queue):
                    # idle replica's swap turn: nothing in flight to drain
                    self._perform_swap(replica)
                if len(replica.queue):
                    progressed = True
                    self._serve_once(replica)
            if self.journal.all_terminal():
                break
            if not progressed:
                # no serving replica holds work but entries are pending —
                # every assignment points at a corpse (requeue already
                # exhausted or raced); terminal-ize so conservation holds
                for rid, e in self.journal.entries.items():
                    if e.status == "pending":
                        self.journal.finalize(rid, "lost")
                break

    # threaded driver --------------------------------------------------
    def _worker(self, replica: _Replica) -> None:
        while True:
            if replica.state != "serving":
                return
            if self._preempted is not None:
                # fleet drain: the current run already finished in-flight
                # (its lease was triggered); do not restart over the
                # leftover queue — those are the drain's unserved
                return
            with self._lock:
                if self._swap is not None \
                        and self._swap.get("active") == replica.id \
                        and not len(replica.queue) and not replica.busy:
                    pass_swap = True
                else:
                    pass_swap = False
            if pass_swap:
                self._perform_swap(replica)
                continue
            if replica.stop.is_set():
                return
            if not len(replica.queue):
                replica.work.wait(0.02)
                replica.work.clear()
                continue
            self._serve_once(replica)

    def _watchdog(self) -> None:
        timeout = self.watchdog_timeout_s
        while not self._wd_stop.wait(timeout / 4):
            for replica in self._serving():
                if replica.busy and (time.monotonic()
                                     - replica.last_progress) > timeout:
                    self._watchdog_stalls += 1
                    # fence + requeue; the zombie thread keeps running
                    # until it wakes, at which point its lease drains it
                    # and its emissions are already stale
                    replica.lease.trigger("watchdog_stall")
                    self._on_replica_failure(
                        replica,
                        TimeoutError(f"no progress for >{timeout}s"),
                        kind="watchdog_stall")

    # ----------------------------------------------------------- run
    def run(self, requests: Iterable[Request],
            on_token: Callable[[int, int], None] | None = None,
            should_stop: Callable[[int], str | None] | None = None,
            ) -> dict[str, Any]:
        """Serve every offered request to terminal state across the
        fleet; returns the fleet summary (serve-section compatible, plus
        ``serve_fleet``)."""
        requests = sorted(requests, key=lambda r: (r.arrival_s, r.rid))
        self._reset_run_state()
        for replica in self.replicas:
            # a previous run's shutdown left stop set; surviving replicas
            # serve again (failed ones stay dead — state is the gate)
            replica.stop.clear()
            replica.work.clear()
            # fresh per-run histograms: this run's summary must describe
            # THIS window (the ContinuousBatcher per-run-registry
            # convention) — the batcher merges its per-run records into
            # whatever registry it holds, so swap in a new one per run
            replica.registry = MetricsRegistry()
            replica.batcher.metrics = replica.registry
        self.journal = RequestJournal(requests)
        self._on_token = on_token
        offered = len(requests)
        if self.autoscale is not None:
            # start at the floor, PER ROLE GROUP; the rest of the set
            # sleeps until queue pressure wakes it (failed replicas stay
            # dead).  Homogeneous fleets have one group (None) and keep
            # the exact round-18 floor.
            for role in self._role_groups():
                n_min, _ = self._role_range(role)
                live = [r for r in self.replicas
                        if r.state != "failed" and r.role == role]
                for idx, replica in enumerate(live):
                    replica.state = ("serving" if idx < n_min
                                     else "dormant")
        self.min_admitting_replicas = len(self._serving())
        if self.slo is not None:
            self.slo.reset()
        self.clock.start()
        for lane in self._lanes.values():
            lane.start()   # every lane shares the run epoch
        t_start = self._t_start = self._fleet_now()
        for replica in self.replicas:
            replica.idle_since = None
            replica.serve_start = (t_start if replica.state == "serving"
                                   else None)
        for req in requests:
            if not self._route(req):
                self.journal.finalize(req.rid, "lost")
        with self._lock:
            self._maybe_start_swap()   # after_completions == 0 case
            self._autoscale_tick()     # arm the first serving slices
        if self.threaded:
            self._run_live = True
            self._wd_stop = threading.Event()
            wd = None
            if self.watchdog_timeout_s > 0:
                wd = threading.Thread(target=self._watchdog, daemon=True)
                wd.start()
            for replica in self._serving():
                self._start_worker(replica)
            try:
                with self._cond:
                    while not self.journal.all_terminal():
                        if should_stop is not None \
                                and self._preempted is None:
                            reason = should_stop(0)
                            if reason:
                                self._preempted = reason
                                for replica in self._serving():
                                    replica.lease.trigger(reason)
                                    replica.work.set()
                        if self._preempted is not None and not any(
                                r.busy for r in self.replicas):
                            break
                        if not self._serving():
                            break
                        self._sample_timeline()
                        self._autoscale_tick()
                        self._cond.wait(0.05)
            finally:
                self._run_live = False
                self._wd_stop.set()
                for replica in self.replicas:
                    replica.stop.set()
                    replica.work.set()
                for replica in self.replicas:
                    if replica.thread is not None:
                        # a stalled zombie may be asleep inside an
                        # injected fault; it is fenced and daemonized —
                        # do not hang the fleet on it
                        replica.thread.join(timeout=1.0)
                if wd is not None:
                    wd.join(timeout=1.0)
        else:
            self._run_sequential(should_stop)
        if self._preempted is None:
            self._finish_pending_swap()
        # terminal sweep: anything still pending (fleet drain, stop with
        # no survivors) is unserved — conservation stays exact
        for rid, e in list(self.journal.entries.items()):
            if e.status == "pending":
                self.journal.finalize(rid, "unserved")
        if self._preempted:
            self.tracer.event("serve_preempted", reason=self._preempted,
                              completed=self.journal.counts()["done"],
                              unserved=self.journal.counts()["unserved"])
        self._sample_timeline()   # final state (post-failover cliffs)
        elapsed = self._fleet_now() - t_start
        return self._summary(offered, elapsed)

    def _start_worker(self, replica: _Replica) -> None:
        replica.thread = threading.Thread(
            target=self._worker, args=(replica,), daemon=True)
        replica.thread.start()

    def close(self, timeout_s: float = 10.0) -> None:
        """Join worker threads left behind by ``run`` (a fenced zombie —
        e.g. a stalled replica sleeping through its watchdog failover —
        keeps running until it wakes; its emissions are already rejected,
        but a clean shutdown should wait it out rather than let the
        interpreter tear down under a live XLA dispatch)."""
        deadline = time.monotonic() + timeout_s
        for replica in self.replicas:
            replica.stop.set()
            replica.work.set()
        for replica in self.replicas:
            t = replica.thread
            if t is not None and t.is_alive():
                t.join(timeout=max(deadline - time.monotonic(), 0.0))

    # ----------------------------------------------------------- summary
    def _summary(self, offered: int, elapsed: float) -> dict[str, Any]:
        journal = self.journal
        results = journal.results()
        counts = journal.counts()
        tracer_stats = self.tracer.stats() or {}
        ttfts = [r.ttft_s for r in results]
        itls = [g for r in results for g in r.itl_s]
        tokens = sum(len(e.emitted) for e in journal.entries.values()
                     if e.emitted)
        # merged per-replica histograms: the PR 11 aggregation substrate —
        # windows → runs → FLEET, by bucket-count addition, no resampling
        merged = MetricsRegistry()
        for replica in self.replicas:
            merged.merge(replica.registry)
        # fleet-level goodput: every completed request judged on its
        # journal timeline (TTFT from original arrival), per replica and
        # merged — a retried request counts ONCE, for the replica that
        # finished it
        per_replica = []
        slo = self.slo
        fleet_good = 0
        for replica in self.replicas:
            done = [r for r in results
                    if journal.entries[r.rid].completed_by == replica.id]
            good = None
            if slo is not None:
                good = sum(
                    1 for r in done
                    if r.ttft_s <= slo.ttft_s
                    and ((exact_percentile(r.itl_s, slo.quantile)
                          or 0.0) <= slo.itl_s))
                fleet_good += good
            per_replica.append({
                "replica": replica.id,
                "state": replica.state,
                "failure": replica.failure,
                "completed": len(done),
                "tokens": sum(len(r.tokens) for r in done),
                "generation": replica.generation,
                "goodput_requests_per_sec": (
                    good / elapsed
                    if good is not None and elapsed > 0 else None),
            })
        slo_sec = None
        if slo is not None:
            slo.reset()
            for r in results:
                slo.observe(r.ttft_s, r.itl_s)
            slo.shed(counts["shed"])
            slo_sec = slo.summary(elapsed)
        recovery = list(journal.recovery_s)
        unserved = counts["lost"] + counts["unserved"]
        depth_hwm = max((r.queue.depth_high_watermark
                         for r in self.replicas), default=0)
        prefix_sec = None
        hit_rate = None
        if self._prefix_sums:
            prefix_sec = dict(self._prefix_sums)
            asked = prefix_sec.get("hits", 0) + prefix_sec.get("misses", 0)
            hit_rate = prefix_sec["hits"] / asked if asked else 0.0
        spec_sec = None
        accept_rate = None
        if self.draft_kvs is not None:
            spec_sec = dict(self._spec_sums)
            proposed = spec_sec.get("proposed_tokens", 0)
            accept_rate = (spec_sec.get("accepted_tokens", 0) / proposed
                           if proposed else None)
        # fleet paged accounting: counters summed across replica windows,
        # pool state (blocks in use / utilization) summed/averaged over
        # the CURRENT replica pools
        paged_sec = zero_copy_rate = None
        paged_kvs = [r.kv for r in self.replicas
                     if hasattr(r.kv, "paged_stats")]
        if paged_kvs:
            states = [kv.paged_stats() for kv in paged_kvs]
            paged_sec = dict(self._paged_sums)
            paged_sec["num_blocks"] = sum(s["num_blocks"] for s in states)
            paged_sec["block"] = states[0]["block"]
            paged_sec["blocks_in_use"] = sum(s["blocks_in_use"]
                                             for s in states)
            paged_sec["utilization"] = (paged_sec["blocks_in_use"]
                                        / paged_sec["num_blocks"])
            asked = (self._prefix_sums.get("hits", 0)
                     + self._prefix_sums.get("misses", 0))
            if self._prefix_sums:
                zero_copy_rate = (
                    paged_sec.get("zero_copy_blocks", 0) / asked
                    if asked else 0.0)
        qw = merged.histogram("queue_wait")
        qd = merged.histogram("queue_depth")
        prefill_tokens = int(self._sums.get("prefill_tokens", 0))
        decode_tokens = int(self._sums.get("decode_tokens", 0))
        summary = {
            "mode": "fleet",
            "replicas": len(self.replicas),
            "requests": len(results),
            "completed": counts["done"],
            "serve_kv_dtype": self.replicas[0].kv.kv_dtype,
            "serve_kv_bytes_per_slot":
                self.replicas[0].kv.kv_bytes_per_slot(),
            "serve_kv_layout": getattr(self.replicas[0].kv, "kv_layout",
                                       "monolithic"),
            "serve_kv_blocks_in_use": (paged_sec["blocks_in_use"]
                                       if paged_sec else None),
            "serve_kv_block_utilization": (paged_sec["utilization"]
                                           if paged_sec else None),
            "serve_prefix_zero_copy_hit_rate": zero_copy_rate,
            "serve_kv_block_deferrals": int(self._paged_sums.get(
                "block_deferrals", 0)),
            "paged": paged_sec,
            "serve_accept_rate": accept_rate,
            "speculative": spec_sec,
            "decode_iterations": int(self._sums.get(
                "decode_iterations", 0)),
            "prefills": int(self._sums.get("prefills", 0)),
            "prefill_chunk": max(r.batcher.prefill_chunk
                                 for r in self.replicas),
            "prefill_chunks": int(self._sums.get("prefill_chunks", 0)),
            "prefill_tokens": prefill_tokens,
            "decode_tokens": decode_tokens,
            "idle_polls": int(self._sums.get("idle_polls", 0)),
            "tokens_generated": tokens,
            "elapsed_s": elapsed,
            "serve_requests_per_sec": (counts["done"] / elapsed
                                       if elapsed > 0 else None),
            "serve_tokens_per_sec": (tokens / elapsed
                                     if elapsed > 0 else None),
            "serve_prefill_tokens_per_sec": (prefill_tokens / elapsed
                                             if elapsed > 0 else None),
            "serve_decode_tokens_per_sec": (decode_tokens / elapsed
                                            if elapsed > 0 else None),
            "serve_prefix_cache_hit_rate": hit_rate,
            "prefix_cache": prefix_sec,
            "serve_ttft_p50_s": exact_percentile(ttfts, 0.50),
            "serve_ttft_p95_s": exact_percentile(ttfts, 0.95),
            "serve_ttft_p99_s": exact_percentile(ttfts, 0.99),
            "serve_itl_p50_s": exact_percentile(itls, 0.50),
            "serve_itl_p95_s": exact_percentile(itls, 0.95),
            "serve_itl_p99_s": exact_percentile(itls, 0.99),
            # attempt-level queue waits from the merged replica histograms
            # (each admission's claim wait on ITS replica's clock — the
            # fleet-level TTFT above is the original-arrival number)
            "serve_queue_wait_p50_s": qw.quantile(0.50),
            "serve_queue_wait_p95_s": qw.quantile(0.95),
            "serve_queue_wait_p99_s": qw.quantile(0.99),
            "queue_depth_p95": qd.quantile(0.95),
            "queue_depth_high_watermark": depth_hwm,
            "queue_cap": max(r.batcher.queue_cap for r in self.replicas),
            "offered": offered,
            "admitted": counts["done"],
            "shed_requests": counts["shed"],
            "unserved_requests": unserved,
            "serve_shed_rate": (counts["shed"] / offered
                                if offered else 0.0),
            "preempted": self._preempted,
            "serve_goodput_under_slo": (
                (slo_sec or {}).get("goodput_requests_per_sec")
                if slo_sec else None),
            "slo": slo_sec,
            "histograms": merged.snapshot(),
            "device_phase_s": dict(self._phase_sums),
            # fleet robustness headline keys (gated by `analyze diff`):
            # recovery time = replica-failure detection → the failed-over
            # request's first post-requeue delivery; duplicates == 0 is
            # the measured exactly-once claim
            "serve_failover_recovery_p95_s": exact_percentile(
                recovery, 0.95),
            "serve_duplicate_emissions": journal.duplicate_emissions,
            "serve_fleet": {
                "replicas": len(self.replicas),
                "serving_replicas": len(self._serving()),
                "failed_replicas": [r.id for r in self.replicas
                                    if r.state == "failed"],
                "failovers": len(self._failovers),
                "failover_events": self._failovers[:32],
                "retries": journal.requeues,
                "requeued_requests": len(journal.requeued_rids),
                "lost_requests": counts["lost"],
                "duplicate_emissions": journal.duplicate_emissions,
                "fenced_emissions": journal.fenced_emissions,
                "watchdog_stalls": self._watchdog_stalls,
                "faults_injected": (list(self.fault_injector.fired)
                                    if self.fault_injector is not None
                                    else []),
                "swap_generations": self.swap_generations,
                "min_admitting_replicas": self.min_admitting_replicas,
                "failover_recovery_s": recovery[:128],
                "failover_recovery_p95_s": exact_percentile(
                    recovery, 0.95),
                "per_replica": per_replica,
                "merged_goodput_under_slo": (
                    fleet_good / elapsed
                    if slo is not None and elapsed > 0 else None),
                # telemetry self-accounting (the fleet shares ONE tracer
                # across replica workers): sink drop counter + span-
                # bookkeeping overhead, both gated lower-is-better — a
                # fleet that drops trace records under load is flying a
                # partial instrument panel
                "sink_dropped": tracer_stats.get("dropped", 0),
                "sink_written": tracer_stats.get("written", 0),
                "trace_overhead_s": tracer_stats.get("overhead_s", 0.0),
            },
            "results": results,
        }
        if self.timeline is not None:
            # timeline-derived fleet keys only when sampling is on — the
            # flag-off key set stays byte-identical (parity pin)
            summary["queue_depth_auc"] = sum(
                filter(None, (self.timeline.stat("queue_depth", "auc",
                                                 replica=r.id)
                              for r in self.replicas))) or None
            summary["kv_blocks_in_use_p95"] = max(
                filter(lambda v: v is not None,
                       (self.timeline.stat("kv_blocks_in_use", "p95",
                                           replica=r.id)
                        for r in self.replicas)), default=None)
            summary["timeline_overhead_s"] = self.timeline.overhead_s
        if self.roofline is not None:
            # --roofline fleet keys only when attached (flag-off parity
            # pin).  Totals are the replica batchers' analytic counters
            # summed; the achieved rate divides total model work by total
            # per-replica device seconds, so the MFU/MBU headline is the
            # MEAN utilization of a serving replica — each replica runs
            # on the roofline's n_devices.  Unknown device kind → None.
            rf = self.roofline
            pre_s = self._rf_sums.get("prefill_s", 0.0)
            dec_s = self._rf_sums.get("decode_s", 0.0)
            pre_fps = (self._rf_sums.get("prefill_model_flops", 0.0)
                       / pre_s if pre_s > 0 else None)
            dec_fps = (self._rf_sums.get("decode_model_flops", 0.0)
                       / dec_s if dec_s > 0 else None)
            dec_bps = (self._rf_sums.get("decode_must_read_bytes", 0.0)
                       / dec_s if dec_s > 0 else None)
            summary["serve_prefill_mfu"] = rf.mfu(pre_fps)
            summary["serve_decode_mbu"] = rf.mbu(dec_bps)
            summary["roofline"] = {
                "prefill_model_flops": self._rf_sums.get(
                    "prefill_model_flops", 0.0),
                "decode_model_flops": self._rf_sums.get(
                    "decode_model_flops", 0.0),
                "decode_must_read_bytes": self._rf_sums.get(
                    "decode_must_read_bytes", 0.0),
                "prefill_s": pre_s,
                "decode_s": dec_s,
                "prefill_achieved_flops_per_sec": pre_fps,
                "decode_achieved_flops_per_sec": dec_fps,
                "decode_achieved_bytes_per_sec": dec_bps,
                "prefill_mfu": rf.mfu(pre_fps),
                "decode_mfu": rf.mfu(dec_fps),
                "decode_mbu": rf.mbu(dec_bps),
                "per_replica": [
                    {"replica": rid, **counters}
                    for rid, counters in sorted(
                        self._rf_replica.items())],
                "device": rf.describe(),
            }
        # ---- round-18 keys, each gated on its feature so the flag-off
        # summary key set stays byte-identical to round 17 (parity pin)
        if (self.roles is not None or self.autoscale is not None
                or self.parallel_lanes):
            end = self._t_start + elapsed
            summary["serve_replica_seconds"] = self._replica_seconds + sum(
                max(end - r.serve_start, 0.0) for r in self.replicas
                if r.serve_start is not None)
            if self.roles is not None:
                # per-role split (round 20): the capacity bill behind a
                # disaggregated + autoscaled fleet — which POOL the
                # replica-seconds went to; the two keys sum to
                # serve_replica_seconds exactly
                for role in self._role_groups():
                    summary[f"serve_replica_seconds_{role}"] = (
                        self._role_seconds.get(role, 0.0) + sum(
                            max(end - r.serve_start, 0.0)
                            for r in self.replicas
                            if r.role == role
                            and r.serve_start is not None))
        if self.parallel_lanes:
            summary["serve_parallel_lanes"] = True
        if self.routing != "least-loaded":
            summary["serve_routing"] = self.routing
            # the fleet-wide hit rate under THIS router, on this trace —
            # the number `analyze diff` gates against a least-loaded
            # baseline window of the same seeded trace
            summary["serve_fleet_prefix_hit_rate"] = hit_rate
        if self.roles is not None:
            role_counts = journal.role_counts()
            # per-role conservation: phase is single-valued, so the two
            # partitions sum to the fleet identity admitted+shed+unserved
            # == offered exactly — a dropped handoff flips its request
            # back to the prefill partition, counted once, never twice
            summary["serve_disagg"] = {
                "prefill_replicas": sum(1 for r in self.replicas
                                        if r.role == "prefill"),
                "decode_replicas": sum(1 for r in self.replicas
                                       if r.role == "decode"),
                "handoff_s": self.handoff_s,
                "handoffs_initiated": self._handoffs_initiated,
                "handoffs_delivered": self._handoffs_delivered,
                "handoffs_dropped": self._handoffs_dropped,
                "per_role": role_counts,
            }
        if self.autoscale is not None:
            pol = self.autoscale
            summary["autoscale"] = {
                "min_replicas": pol.min_replicas,
                "max_replicas": pol.max_replicas or len(self.replicas),
                "high_watermark": pol.high_watermark,
                "scale_ups": self._scale_ups,
                "scale_downs": self._scale_downs,
                "events": self._scale_events[:64],
                "serving_replicas_final": len(self._serving()),
            }
            if self.roles is not None:
                # the clamped per-pool ranges the tick actually drives
                summary["autoscale"]["per_role"] = {
                    role: {"min_replicas": rng[0], "max_replicas": rng[1],
                           "serving_final": sum(
                               1 for r in self._serving()
                               if r.role == role)}
                    for role in self._role_groups()
                    for rng in (self._role_range(role),)}
        if self.multi_step is not None:
            # multi-step keys ride ONLY flag-on (the flag-off fleet
            # summary key set stays byte-identical to round 19): total
            # host dispatches and host-gap seconds across every replica
            # window, same vocabulary as the single-batcher summary
            summary["serve_multi_step"] = self.multi_step
            summary["serve_dispatches"] = int(
                self._sums.get("serve_dispatches", 0))
            summary["serve_host_gap_s"] = float(
                self._sums.get("serve_host_gap_s", 0.0))
            if self.roofline is not None:
                summary["roofline"]["dispatches"] = \
                    summary["serve_dispatches"]
                summary["roofline"]["host_gap_s"] = \
                    summary["serve_host_gap_s"]
        return summary


# re-exported convenience: a fleet built from one (model, params) pair
def build_replica_kvs(model, params, n_replicas: int, slots: int,
                      **kv_kwargs) -> list[SlotKVCache]:
    """N independent slot tables over shared params (replicated params
    share device buffers; each replica owns its KV memory).  n == 0 is
    legal and returns [] — callers extending an already-built first
    table pass n_replicas - 1."""
    if n_replicas < 0:
        raise ValueError(f"n_replicas must be >= 0, got {n_replicas}")
    return [SlotKVCache(model, params, slots, **kv_kwargs)
            for _ in range(n_replicas)]


__all__ = [
    "AutoscalePolicy",
    "CorruptionDetected",
    "FaultInjector",
    "FaultSpec",
    "InjectedFault",
    "ReplicaSet",
    "RequestJournal",
    "build_replica_kvs",
]
