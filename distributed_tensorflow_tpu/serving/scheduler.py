"""Continuous batcher: the request-scheduling half of the serving engine.

The loop is the Orca/vLLM iteration-level scheduler shape: between decode
iterations it (a) admits arrived requests into free KV slots (jitted
prefill-insert, never recompiling the decode step), (b) runs ONE decode
iteration over the whole slot table, and (c) evicts finished slots so the
next arrivals claim them mid-flight.  ``mode='static'`` degrades the same
loop to the restart-per-batch ``generate`` baseline — admission only when
the table is empty — so continuous-vs-static comparisons share every line
of device code and the decode-iteration counter is directly comparable.

The request queue rebuilds the claim discipline of the unwired native
batch pipeline (native/batcher.py): one consumer claims the queue for a
run and releases it deterministically on exit, so two schedulers can never
interleave admissions from the same queue (the _EpochIterator busy-claim
contract, rebuilt in Python because requests arrive one at a time rather
than as a C++ epoch cursor).

Latency accounting follows the MLPerf inference convention (Mattson et
al., arXiv:1910.01500 — latency percentiles as machine-checked numbers):
TTFT is arrival→first-token (queue wait INCLUDED — an admitted-late
request is a slow request), ITL is the gap between consecutive token
deliveries, and both report p50/p95 over the whole run.  Every request
emits ``request``/``prefill``/``decode`` trace spans through the existing
observability stack, so `analyze spans` and the Perfetto export read
serving timelines with no new machinery.

Clocks are injectable: ``WallClock`` (real time; idle waits sleep until
the next arrival — the open-loop bench) or ``VirtualClock`` (time = decode
iterations; deterministic staggered-arrival tests).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Any, Callable, Iterable

import numpy as np

from distributed_tensorflow_tpu.observability.trace import NULL_TRACER
from distributed_tensorflow_tpu.serving.kv_cache import SlotKVCache


# ------------------------------------------------------------------ clocks

class WallClock:
    """Real time: arrivals are seconds since ``start()``; idle waits sleep."""

    def __init__(self):
        self._t0 = None

    def start(self) -> None:
        self._t0 = time.monotonic()

    def now(self) -> float:
        return time.monotonic() - self._t0

    def on_decode_iteration(self) -> None:
        pass  # real time advances itself

    def wait_until(self, t: float) -> None:
        delta = t - self.now()
        if delta > 0:
            time.sleep(delta)


class VirtualClock:
    """Deterministic time: one decode iteration = ``tick`` time units.

    Arrival times are then expressed in decode iterations, which makes
    "request arrives mid-decode" an exact, repeatable event — the
    staggered-arrival acceptance tests run on this clock."""

    def __init__(self, tick: float = 1.0):
        self.t = 0.0
        self.tick = float(tick)

    def start(self) -> None:
        self.t = 0.0

    def now(self) -> float:
        return self.t

    def on_decode_iteration(self) -> None:
        self.t += self.tick

    def wait_until(self, t: float) -> None:
        self.t = max(self.t, t)


# ----------------------------------------------------------------- request

@dataclasses.dataclass
class Request:
    """One serving request of the open-loop arrival process."""

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    arrival_s: float = 0.0
    eos_id: int | None = None


class RequestQueue:
    """Arrival-ordered queue with the native batcher's busy-claim contract
    (native/batcher.py: one consumer owns the cursor; release is
    deterministic, not GC-time).  ``claim()`` returns a context manager —
    a second concurrent scheduler on the same queue raises instead of
    silently interleaving admissions."""

    def __init__(self, requests: Iterable[Request] = ()):
        self._items: list[Request] = sorted(
            requests, key=lambda r: (r.arrival_s, r.rid))
        self.busy = False

    def push(self, request: Request) -> None:
        self._items.append(request)
        self._items.sort(key=lambda r: (r.arrival_s, r.rid))

    def __len__(self) -> int:
        return len(self._items)

    def next_arrival(self) -> float | None:
        return self._items[0].arrival_s if self._items else None

    def pop_ready(self, now: float) -> Request | None:
        if self._items and self._items[0].arrival_s <= now:
            return self._items.pop(0)
        return None

    @contextlib.contextmanager
    def claim(self):
        if self.busy:
            raise RuntimeError(
                "RequestQueue is busy: another scheduler run owns it "
                "(the native/batcher.py single-consumer claim contract)")
        self.busy = True
        try:
            yield self
        finally:
            self.busy = False


@dataclasses.dataclass
class RequestResult:
    """Per-request outcome + latency timeline (clock units)."""

    rid: int
    prompt_len: int
    tokens: list[int]
    arrival_s: float
    admitted_s: float
    first_token_s: float
    finished_s: float = 0.0
    itl_s: list[float] = dataclasses.field(default_factory=list)

    @property
    def ttft_s(self) -> float:
        return self.first_token_s - self.arrival_s


class _Live:
    """Host bookkeeping for one in-flight slot."""

    def __init__(self, req: Request, result: RequestResult,
                 req_span, dec_span, last_t: float):
        self.req = req
        self.result = result
        self.req_span = req_span     # entered context managers, exited on
        self.dec_span = dec_span     # finish (per-request span contract)
        self.last_t = last_t


def _percentile(vals: list[float], q: float) -> float | None:
    """Linear-interpolated percentile (stdlib-only math so the summary is
    recomputable anywhere the JSONL lands)."""
    if not vals:
        return None
    s = sorted(vals)
    if len(s) == 1:
        return s[0]
    pos = (len(s) - 1) * q
    lo = int(pos)
    hi = min(lo + 1, len(s) - 1)
    return s[lo] + (s[hi] - s[lo]) * (pos - lo)


# --------------------------------------------------------------- batcher

class ContinuousBatcher:
    """In-flight request scheduler over a SlotKVCache (module docstring).

    ``mode='continuous'`` admits between decode iterations (the tentpole
    path); ``mode='static'`` only admits into an EMPTY slot table — the
    restart-per-batch ``generate`` baseline, measured with the same
    counters so the comparison is apples-to-apples.
    """

    def __init__(self, kv: SlotKVCache, *, tracer=NULL_TRACER,
                 clock=None, mode: str = "continuous"):
        if mode not in ("continuous", "static"):
            raise ValueError(f"mode must be continuous|static, got {mode}")
        self.kv = kv
        self.tracer = tracer
        self.clock = clock if clock is not None else WallClock()
        self.mode = mode

    # ------------------------------------------------------------ admission
    def _admit(self, req: Request, live: dict[int, _Live]) -> int:
        kv, tracer = self.kv, self.tracer
        lp = int(np.asarray(req.prompt).reshape(-1).shape[0])
        if lp + req.max_new_tokens > kv.max_len:
            raise ValueError(
                f"request {req.rid}: prompt ({lp}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds the slot capacity "
                f"max_len={kv.max_len}")
        if req.max_new_tokens < 1:
            raise ValueError(
                f"request {req.rid}: max_new_tokens must be positive")
        req_span = tracer.span("request", rid=req.rid, prompt_len=lp,
                               max_new_tokens=req.max_new_tokens)
        req_span.__enter__()
        with tracer.span("prefill", rid=req.rid, prompt_len=lp):
            slot, first = kv.insert(req.prompt)
        now = self.clock.now()
        result = RequestResult(
            rid=req.rid, prompt_len=lp, tokens=[first],
            arrival_s=req.arrival_s, admitted_s=now, first_token_s=now)
        dec_span = tracer.span("decode", rid=req.rid, slot=slot)
        dec_span.__enter__()
        live[slot] = _Live(req, result, req_span, dec_span, now)
        if self._finished(live[slot]):
            # max_new_tokens == 1 (or instant EOS): the prefill's token was
            # the whole continuation — finish without a decode iteration
            self._finish(slot, live)
        return first

    def _finished(self, lv: _Live) -> bool:
        if len(lv.result.tokens) >= lv.req.max_new_tokens:
            return True
        eos = lv.req.eos_id
        return eos is not None and lv.result.tokens[-1] == eos

    def _finish(self, slot: int, live: dict[int, _Live]) -> None:
        lv = live.pop(slot)
        lv.result.finished_s = self.clock.now()
        lv.dec_span.__exit__(None, None, None)
        lv.req_span.__exit__(None, None, None)
        self.kv.evict(slot)
        self._results.append(lv.result)

    # ------------------------------------------------------------- the loop
    def _serve(self, queue: RequestQueue, live: dict[int, _Live],
               on_token: Callable[[int, int], None] | None,
               ) -> tuple[int, int]:
        """The iteration loop under run()'s claim + cleanup guard; returns
        (decode_iterations, prefills)."""
        kv, tracer, clock = self.kv, self.tracer, self.clock
        decode_iterations = 0
        prefills = 0
        while len(queue) or live:
            # admission between decode iterations: continuous mode
            # fills any free slot from the arrived queue; static mode
            # waits for the whole table to drain first
            can_admit = self.mode == "continuous" or not live
            while can_admit and kv.free_slots:
                req = queue.pop_ready(clock.now())
                if req is None:
                    break
                first = self._admit(req, live)
                prefills += 1
                if on_token is not None:
                    on_token(req.rid, first)  # the prefill's own token
            if not live:
                nxt = queue.next_arrival()
                if nxt is None:
                    break
                clock.wait_until(nxt)  # idle: jump/sleep to the arrival
                continue
            with tracer.span("decode_step", active=len(live)):
                toks = kv.advance()
            decode_iterations += 1
            clock.on_decode_iteration()
            now = clock.now()
            for slot in sorted(live):
                lv = live[slot]
                tok = int(toks[slot])
                lv.result.tokens.append(tok)
                lv.result.itl_s.append(now - lv.last_t)
                lv.last_t = now
                if on_token is not None:
                    on_token(lv.req.rid, tok)
                if self._finished(lv):
                    self._finish(slot, live)
        return decode_iterations, prefills

    def run(self, requests: Iterable[Request] | RequestQueue,
            on_token: Callable[[int, int], None] | None = None,
            ) -> dict[str, Any]:
        """Serve every request to completion; returns the summary dict
        (per-request results under ``results``).  ``on_token(rid, token)``
        is the streaming hook — called at each token's host delivery."""
        queue = (requests if isinstance(requests, RequestQueue)
                 else RequestQueue(requests))
        self._results: list[RequestResult] = []
        live: dict[int, _Live] = {}
        with queue.claim():
            self.clock.start()
            t_start = self.clock.now()
            try:
                decode_iterations, prefills = self._serve(queue, live,
                                                          on_token)
            except BaseException:
                # a failed window must not poison the slot table — bench
                # windows share ONE SlotKVCache, and a leaked active slot
                # shrinks every later window's capacity (zero free slots
                # + zero live = a busy-spin).  Free the in-flight slots
                # and close their spans so the records written so far
                # survive into the partial-results artifact.
                for slot in sorted(live):
                    lv = live.pop(slot)
                    lv.dec_span.__exit__(None, None, None)
                    lv.req_span.__exit__(None, None, None)
                    self.kv.evict(slot)
                raise
            elapsed = self.clock.now() - t_start
        results = sorted(self._results, key=lambda r: r.rid)
        ttfts = [r.ttft_s for r in results]
        itls = [g for r in results for g in r.itl_s]
        tokens = sum(len(r.tokens) for r in results)
        return {
            "mode": self.mode,
            "requests": len(results),
            "completed": len(results),
            # KV-table storage dtype (SlotKVCache kv_dtype — the --serve-
            # kv-dtype memory knob); rides into the serve report section
            "serve_kv_dtype": getattr(self.kv, "kv_dtype", None),
            "decode_iterations": decode_iterations,
            "prefills": prefills,
            "tokens_generated": tokens,
            "elapsed_s": elapsed,
            "serve_requests_per_sec": (len(results) / elapsed
                                       if elapsed > 0 else None),
            "serve_tokens_per_sec": (tokens / elapsed
                                     if elapsed > 0 else None),
            "serve_ttft_p50_s": _percentile(ttfts, 0.50),
            "serve_ttft_p95_s": _percentile(ttfts, 0.95),
            "serve_itl_p50_s": _percentile(itls, 0.50),
            "serve_itl_p95_s": _percentile(itls, 0.95),
            "results": results,
        }
