"""Continuous batcher: the request-scheduling half of the serving engine.

The loop is the Orca/vLLM iteration-level scheduler shape: between decode
iterations it (a) admits arrived requests into free KV slots (jitted
prefill-insert, never recompiling the decode step), (b) runs ONE decode
iteration over the whole slot table, and (c) evicts finished slots so the
next arrivals claim them mid-flight.  ``mode='static'`` degrades the same
loop to the restart-per-batch ``generate`` baseline — admission only when
the table is empty — so continuous-vs-static comparisons share every line
of device code and the decode-iteration counter is directly comparable.

``prefill_chunk > 0`` enables Sarathi-Serve-style chunked prefill
(arXiv:2403.02310): admission only CLAIMS the slot; the prompt then fills
in ≤budget-token chunks, at most one chunk per loop iteration, so a long
prompt's prefill is spread across decode iterations instead of stalling
every live slot in one gap.  The first token is still sampled by the
final chunk — TTFT keeps its arrival→first-token meaning, queue wait and
chunk wait both included.  The summary splits throughput into
``serve_prefill_tokens_per_sec`` / ``serve_decode_tokens_per_sec`` and,
when the SlotKVCache's prefix pool is on, carries the run's block-level
``serve_prefix_cache_hit_rate`` with the hit/miss/evict ledger.

The request queue rebuilds the claim discipline of the unwired native
batch pipeline (native/batcher.py): one consumer claims the queue for a
run and releases it deterministically on exit, so two schedulers can never
interleave admissions from the same queue (the _EpochIterator busy-claim
contract, rebuilt in Python because requests arrive one at a time rather
than as a C++ epoch cursor).

Latency accounting follows the MLPerf inference convention (Mattson et
al., arXiv:1910.01500 — latency percentiles as machine-checked numbers):
TTFT is arrival→first-token (queue wait INCLUDED — an admitted-late
request is a slow request), ITL is the gap between consecutive token
deliveries, and both report p50/p95/p99 over the whole run.  Every request
emits ``request``/``prefill``/``decode`` trace spans through the existing
observability stack, so `analyze spans` and the Perfetto export read
serving timelines with no new machinery.

Round 13 makes the batcher service-grade observable — all host-side, so
the compiled program set and the greedy tokens stay byte-identical:

* **per-phase attribution**: each request's queue wait (arrival→claim),
  prefill (claim→first token, chunk wait included) and decode gaps land
  in a streaming log-bucketed histogram registry
  (observability/metrics.py — O(1) record, online p50/p95/p99,
  mergeable across windows) AND as attrs on the ``request`` span, which
  is what ``analyze serve`` renders as a per-request waterfall;
* **goodput under SLO**: an attached ``SLOMonitor``
  (observability/slo.py) judges every completed request against TTFT +
  ITL targets and the summary carries ``serve_goodput_under_slo`` —
  requests/sec that met BOTH, the MLPerf/Sarathi-Serve headline;
* **bounded-admission overload mode** (``queue_cap > 0``): arrived
  backlog past the cap is shed with exact 429 accounting
  (``shed_requests``/``serve_shed_rate``, a structured ``overload``
  trace event per rejection, admitted + shed + unserved == offered), so
  an overloaded batcher degrades to bounded queue wait instead of
  unbounded TTFT;
* **lease drain** (``should_stop``): the PR 9 preemption hook — a
  SIGTERM'd serve window stops admitting, finishes in-flight requests
  and flushes a consistent partial summary (``preempted`` names why).

Round 14 attacks the decode step itself — **greedy-exact speculative
decoding** (``draft_kv``/``draft_k``; Leviathan et al., arXiv:2211.17192):
a draft model's own SlotKVCache runs in slot lockstep (admitted/evicted
with the target), each iteration becomes draft-k → verify-1 (k draft
steps propose, ONE batched target step scores all k+1 positions), and
greedy acceptance — accept while draft token == target argmax, then take
the target's token — makes the emitted stream **bitwise identical** to
non-speculative decode; speculation changes iteration counts, never
tokens.  Rollback of rejected positions is pure length bookkeeping on
both tables (no KV rewrite).  ``serve_accept_rate`` + the
proposed/accepted/rejected ledger (exact conservation) ride the summary;
``serve_tokens_per_sec`` stays emitted-tokens-only, and ITL gaps are
attributed per emitted token (a round's batch-mates land at gap 0), so
the SLO math stays honest.

Clocks are injectable: ``WallClock`` (real time; idle waits sleep until
the next arrival — the open-loop bench) or ``VirtualClock`` (time = decode
iterations; deterministic staggered-arrival tests).
"""

from __future__ import annotations

import bisect
import contextlib
import dataclasses
import time
from typing import Any, Callable, Iterable

import numpy as np

from distributed_tensorflow_tpu.observability.metrics import (
    MetricsRegistry, exact_percentile)
from distributed_tensorflow_tpu.observability.trace import NULL_TRACER
from distributed_tensorflow_tpu.serving.kv_cache import (
    SlotKVCache, SlotOverflow)


# ------------------------------------------------------------------ clocks

class WallClock:
    """Real time: arrivals are seconds since ``start()``; idle waits sleep.

    ``poll_slice_s`` bounds each idle sleep: the batcher re-checks the
    queue between slices (a concurrent producer's earlier arrival is
    noticed within one slice) instead of either spinning or oversleeping.
    """

    def __init__(self, poll_slice_s: float = 0.05):
        self._t0 = None
        self.poll_slice_s = float(poll_slice_s)

    def start(self) -> None:
        self._t0 = time.monotonic()

    def now(self) -> float:
        return time.monotonic() - self._t0

    def on_decode_iteration(self) -> None:
        pass  # real time advances itself

    def on_prefill(self, tokens: int) -> None:
        pass  # real time advances itself

    def wait_until(self, t: float) -> None:
        delta = t - self.now()
        if delta > 0:
            time.sleep(delta)


class VirtualClock:
    """Deterministic time: one decode iteration = ``tick`` time units.

    Arrival times are then expressed in decode iterations, which makes
    "request arrives mid-decode" an exact, repeatable event — the
    staggered-arrival acceptance tests run on this clock.

    ``prefill_token_tick`` is the interference cost model: each prefilled
    prompt token advances time by this much (default 0 — prefill is free,
    the PR 7 accounting).  With it set, a monolithic admission of an
    L-token prompt stalls every live slot by ``L × prefill_token_tick``
    in one gap, while chunked prefill bounds the per-iteration stall to
    ``budget × prefill_token_tick`` — the chunked-prefill acceptance
    tests measure exactly that, deterministically."""

    poll_slice_s = float("inf")   # virtual idle waits jump, never slice

    def __init__(self, tick: float = 1.0, prefill_token_tick: float = 0.0):
        self.t = 0.0
        self.tick = float(tick)
        self.prefill_token_tick = float(prefill_token_tick)

    def start(self) -> None:
        self.t = 0.0

    def now(self) -> float:
        return self.t

    def on_decode_iteration(self) -> None:
        self.t += self.tick

    def on_prefill(self, tokens: int) -> None:
        self.t += tokens * self.prefill_token_tick

    def wait_until(self, t: float) -> None:
        self.t = max(self.t, t)


# ----------------------------------------------------------------- request

@dataclasses.dataclass
class Request:
    """One serving request of the open-loop arrival process."""

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    arrival_s: float = 0.0
    eos_id: int | None = None
    # disaggregated fleet: a serialized KV payload (SlotKVCache
    # extract_handoff) attached by a prefill replica's handoff hook —
    # the decode replica admits by restoring it instead of prefilling.
    # None everywhere outside the disaggregated path.
    handoff: dict | None = None


class RequestQueue:
    """Arrival-ordered queue with the native batcher's busy-claim contract
    (native/batcher.py: one consumer owns the cursor; release is
    deterministic, not GC-time).  ``claim()`` returns a context manager —
    a second concurrent scheduler on the same queue performs a BOUNDED
    busy-claim (short doubling backoff sleeps, never a hot spin, attempt
    count pinned by tests) and raises once the bound is exhausted instead
    of silently interleaving admissions."""

    def __init__(self, requests: Iterable[Request] = ()):
        self._items: list[Request] = sorted(
            requests, key=lambda r: (r.arrival_s, r.rid))
        self.busy = False
        self.claim_attempts = 0   # attempts of the LAST claim() call
        # deepest ARRIVED backlog ever observed via depth(now) — the
        # queue-pressure number that used to be invisible until TTFT
        # blew up
        self.depth_high_watermark = 0

    def push(self, request: Request) -> None:
        self._items.append(request)
        self._items.sort(key=lambda r: (r.arrival_s, r.rid))

    def __len__(self) -> int:
        return len(self._items)

    def next_arrival(self) -> float | None:
        return self._items[0].arrival_s if self._items else None

    def pop_ready(self, now: float) -> Request | None:
        if self._items and self._items[0].arrival_s <= now:
            return self._items.pop(0)
        return None

    def depth(self, now: float | None = None) -> int:
        """Queue depth: all queued requests when ``now`` is None, else
        only those already ARRIVED by ``now`` (the admission backlog —
        the number bounded-admission caps).  ``now``-based reads update
        ``depth_high_watermark``.  O(log n): the batcher calls this every
        decode iteration, and a linear scan would make the host loop
        quadratic in the backlog exactly when overloaded."""
        if now is None:
            return len(self._items)
        d = bisect.bisect_right(self._items, now,
                                key=lambda r: r.arrival_s)
        if d > self.depth_high_watermark:
            self.depth_high_watermark = d
        return d

    def shed_ready(self, now: float, keep: int) -> list[Request]:
        """Bounded admission: remove and return every ARRIVED request
        beyond the oldest ``keep`` (the 429 path — newest arrivals shed
        first, FIFO preserved for the survivors)."""
        ready = self.depth(now)
        n_shed = ready - max(int(keep), 0)
        if n_shed <= 0:
            return []
        shed = self._items[ready - n_shed:ready]
        del self._items[ready - n_shed:ready]
        return shed

    @contextlib.contextmanager
    def claim(self, max_attempts: int = 8, backoff_s: float = 0.005):
        """Claim the queue for one scheduler run.

        A busy queue is retried ``max_attempts`` times with a short
        doubling sleep between attempts (bounded host cost — the claim
        loop can never spin a core), then raises.  ``claim_attempts``
        records how many attempts the call made, so tests pin the bound.
        """
        delay = float(backoff_s)
        self.claim_attempts = 0
        while True:
            self.claim_attempts += 1
            if not self.busy:
                break
            if self.claim_attempts >= max_attempts:
                raise RuntimeError(
                    "RequestQueue is busy: another scheduler run owns it "
                    "(the native/batcher.py single-consumer claim "
                    f"contract; gave up after {self.claim_attempts} "
                    f"bounded claim attempts)")
            time.sleep(delay)
            delay = min(delay * 2, 0.1)
        self.busy = True
        try:
            yield self
        finally:
            self.busy = False


@dataclasses.dataclass
class RequestResult:
    """Per-request outcome + latency timeline (clock units).

    Phase attribution: ``queue_wait_s`` is arrival → slot claim,
    ``prefill_s`` is claim → first token (chunk wait included), and the
    decode phase is the ``itl_s`` gap list — the three sum (with the
    decode gaps) to the request's total latency, and each phase also
    lands in the batcher's histogram registry and on the ``request``
    trace span."""

    rid: int
    prompt_len: int
    tokens: list[int]
    arrival_s: float
    admitted_s: float
    first_token_s: float
    finished_s: float = 0.0
    itl_s: list[float] = dataclasses.field(default_factory=list)
    queue_wait_s: float = 0.0
    prefill_s: float = 0.0
    slo_met: bool | None = None   # None: no SLOMonitor attached
    # speculative-decode accounting (zero when no draft is attached):
    # draft tokens proposed for / accepted by this request's slot —
    # conservation holds exactly: accepted + rejected == proposed
    proposed_tokens: int = 0
    accepted_tokens: int = 0

    @property
    def ttft_s(self) -> float:
        return self.first_token_s - self.arrival_s

    @property
    def decode_s(self) -> float:
        return self.finished_s - self.first_token_s


class _Live:
    """Host bookkeeping for one in-flight slot."""

    def __init__(self, req: Request, result: RequestResult,
                 req_span, dec_span, last_t: float, req_attrs=None):
        self.req = req
        self.result = result
        self.req_span = req_span     # entered context managers, exited on
        self.dec_span = dec_span     # finish (per-request span contract)
        self.req_attrs = req_attrs if req_attrs is not None else {}
        self.last_t = last_t


# stdlib-only linear-interpolated percentile (shared with the histogram
# module so the stored-sample path and the exactness tests use literally
# the same function)
_percentile = exact_percentile


# --------------------------------------------------------------- batcher

class ContinuousBatcher:
    """In-flight request scheduler over a SlotKVCache (module docstring).

    ``mode='continuous'`` admits between decode iterations (the tentpole
    path); ``mode='static'`` only admits into an EMPTY slot table — the
    restart-per-batch ``generate`` baseline, measured with the same
    counters so the comparison is apples-to-apples.
    """

    def __init__(self, kv: SlotKVCache, *, tracer=NULL_TRACER,
                 clock=None, mode: str = "continuous",
                 prefill_chunk: int = 0, metrics=None, slo=None,
                 queue_cap: int = 0, should_stop=None,
                 draft_kv: SlotKVCache | None = None, draft_k: int = 4,
                 timeline=None, timeline_tag: int | None = None,
                 role: str | None = None, handoff_out=None,
                 roofline=None, multi_step: int | None = None):
        if mode not in ("continuous", "static"):
            raise ValueError(f"mode must be continuous|static, got {mode}")
        if prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0 (0 = monolithic prefill), "
                f"got {prefill_chunk}")
        if queue_cap < 0:
            raise ValueError(
                f"queue_cap must be >= 0 (0 = unbounded admission), got "
                f"{queue_cap}")
        if draft_kv is not None:
            # speculative decoding (--serve-draft-config/--serve-draft-k):
            # a small draft model proposes k tokens per live slot between
            # target iterations, the target scores all k+1 positions in
            # one batched verify step, and greedy acceptance keeps the
            # emitted stream bitwise identical to non-speculative decode.
            # The draft runs its own SlotKVCache in slot lockstep —
            # admitted/evicted with the target, resynced by length
            # bookkeeping after every round.
            if draft_k < 1:
                raise ValueError(
                    f"draft_k must be >= 1 (draft tokens proposed per "
                    f"verify round), got {draft_k}")
            if not (kv.greedy and draft_kv.greedy):
                raise ValueError(
                    "speculative decoding requires greedy sampling on "
                    "both the target and the draft: the exact acceptance "
                    "rule only exists for greedy decode")
            if draft_kv.slots != kv.slots:
                raise ValueError(
                    f"draft slot table ({draft_kv.slots}) must match the "
                    f"target's ({kv.slots}): slots run in lockstep")
            if draft_kv.max_len < kv.max_len:
                raise ValueError(
                    f"draft max_len ({draft_kv.max_len}) must cover the "
                    f"target's ({kv.max_len}): the draft mirrors every "
                    f"slot position")
        # disaggregated fleet roles (--serve-disaggregate): a 'prefill'
        # batcher runs admission + (chunked) prefill only and hands each
        # finished slot's KV to `handoff_out(req, payload)` instead of
        # decoding; a 'decode' batcher admits handoff-carrying requests
        # by restoring the payload.  role=None is the homogeneous batcher,
        # byte-identical to round 17.
        if role not in (None, "prefill", "decode"):
            raise ValueError(
                f"role must be None|prefill|decode, got {role!r}")
        if (role == "prefill") != (handoff_out is not None):
            raise ValueError(
                "role='prefill' and handoff_out go together: the prefill "
                "batcher needs a delivery hook for finished KV, and only "
                "a prefill batcher may have one")
        if role == "prefill" and draft_kv is not None:
            raise ValueError(
                "speculative decoding cannot ride a prefill-role batcher: "
                "it never decodes — attach the draft to decode replicas")
        self.role = role
        self.handoff_out = handoff_out
        self.draft_kv = draft_kv
        self.draft_k = int(draft_k)
        self.kv = kv
        self.tracer = tracer
        self.clock = clock if clock is not None else WallClock()
        self.mode = mode
        # per-iteration prompt-token budget (Sarathi-Serve chunked
        # prefill): 0 = admission prefills the whole prompt in one program
        # (the PR 7 path); >0 = at most one ≤prefill_chunk-token chunk
        # rides each decode iteration, so live slots keep emitting tokens
        # while a long prompt fills
        self.prefill_chunk = int(prefill_chunk)
        # observability hooks — ALL host-side, so the compiled program set
        # and the greedy tokens are byte-identical with them on or off:
        # `metrics` is an external MetricsRegistry the per-run histograms
        # merge into (windows → runs → fleet), `slo` an SLOMonitor
        # (goodput-under-SLO per window), `queue_cap` the bounded-
        # admission overload mode (>0: arrived backlog past the cap is
        # shed with 429 accounting instead of queuing unboundedly), and
        # `should_stop` the lease-drain hook (reason string → stop
        # admitting, finish in-flight, flush accounting)
        self.metrics = metrics
        self.slo = slo
        self.queue_cap = int(queue_cap)
        self.should_stop = should_stop
        # `timeline` (--timeline) is the same discipline: a throttled
        # host-side gauge sampler fed at the existing per-iteration
        # boundary; `timeline_tag` is the fleet's replica id, keying
        # per-replica series lanes.  None = sampling fully off.
        self.timeline = timeline
        self.timeline_tag = timeline_tag
        # `roofline` (--roofline) follows the same host-side discipline: a
        # Roofline carrying the analytic GPTCostModel for THIS kv's model.
        # The batcher tallies model FLOPs and must-read bytes per phase in
        # plain Python counters at boundaries that already exist — zero
        # device syncs, zero new programs — and the summary gains
        # serve_prefill_mfu / serve_decode_mbu plus a roofline section
        # ONLY when it is attached (flag-off key-set parity pin).  The
        # draft model's work is deliberately NOT counted: MFU/MBU describe
        # the TARGET model's efficiency, and crediting draft flops would
        # let a wasteful draft inflate the headline (BASELINE.md).
        self.roofline = roofline
        self._rf_cost = (roofline.cost if roofline is not None else None)
        # --serve-multi-step k: fuse k decode iterations per host
        # dispatch (SlotKVCache.dispatch_multi/drain_multi) and pipeline
        # round i+1's dispatch ahead of round i's materialization —
        # bounded admission staleness (a new arrival waits at most one
        # k-iteration round) for k× fewer host round-trips.  None = the
        # legacy per-iteration loop, byte-identical to round 19 (the
        # flag-off parity pin; k=1 runs the pipeline at legacy fusion).
        # With a draft attached the outer loop stays legacy (verify
        # rounds need host acceptance each iteration) but the draft's
        # proposal loop fuses through the same program.
        if multi_step is not None and int(multi_step) < 1:
            raise ValueError(
                f"multi_step must be >= 1 fused decode iterations per "
                f"dispatch, got {multi_step}")
        self.multi_step = None if multi_step is None else int(multi_step)
        self.idle_polls = 0

    # ------------------------------------------------------------ admission
    def _check_capacity(self, req: Request) -> int:
        lp = int(np.asarray(req.prompt).reshape(-1).shape[0])
        if lp + req.max_new_tokens > self.kv.max_len:
            raise ValueError(
                f"request {req.rid}: prompt ({lp}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds the slot capacity "
                f"max_len={self.kv.max_len}")
        if req.max_new_tokens < 1:
            raise ValueError(
                f"request {req.rid}: max_new_tokens must be positive")
        return lp

    def _admit(self, req: Request, live: dict[int, _Live]) -> int | None:
        kv, tracer = self.kv, self.tracer
        lp = self._check_capacity(req)
        t_claim = self.clock.now()
        req_span = tracer.span("request", rid=req.rid, prompt_len=lp,
                               max_new_tokens=req.max_new_tokens)
        req_attrs = req_span.__enter__() or {}
        if req.handoff is not None:
            # disaggregated decode-side admission: the prompt KV arrives
            # serialized from a prefill replica — restore it instead of
            # prefilling.  The first token was already sampled by the
            # prefill replica's final chunk and rides the payload; no
            # prefill program runs here, so a long prompt can never
            # share this replica's iteration with live decodes.
            if self.role != "decode":
                raise ValueError(
                    f"request {req.rid} carries a KV handoff but this "
                    f"batcher's role is {self.role!r} — only decode-role "
                    f"batchers admit handoffs")
            with tracer.span("kv_handoff_restore", rid=req.rid,
                             length=int(req.handoff["length"])):
                slot, first = kv.restore_handoff(req.handoff)
            self._handoffs_in += 1
        else:
            before = kv.prefill_tokens_computed
            with tracer.span("prefill", rid=req.rid, prompt_len=lp):
                slot, first = kv.insert(req.prompt)
            self.clock.on_prefill(kv.prefill_tokens_computed - before)
            if self._rf_cost is not None:
                # credit only positions actually computed: a prefix-cache
                # hit of r tokens leaves positions r..lp, whose new-token
                # attention still spans the cached context (the start
                # offset) — plus one LM head read sampling the first token
                done = kv.prefill_tokens_computed - before
                self._rf_prefill_flops += (
                    self._rf_cost.prefill_chunk_flops(done, lp - done)
                    + self._rf_cost.lm_head_flops)
        if hasattr(kv, "note_admission"):
            # register the paged block budget (prompt + decode growth) so
            # can_admit's outstanding ledger covers this slot's worst case
            kv.note_admission(slot, lp + req.max_new_tokens)
        if self.handoff_out is not None:
            # prefill role: the finished slot's KV leaves for a decode
            # replica — no local decode, no local token delivery (the
            # decode replica emits the payload's first token, so TTFT is
            # still charged arrival→first-token INCLUDING the handoff)
            self._handoff(req, slot, req_span, req_attrs)
            return None
        now = self.clock.now()
        result = RequestResult(
            rid=req.rid, prompt_len=lp, tokens=[first],
            arrival_s=req.arrival_s, admitted_s=now, first_token_s=now,
            queue_wait_s=t_claim - req.arrival_s,
            prefill_s=now - t_claim)
        dec_span = tracer.span("decode", rid=req.rid, slot=slot)
        dec_span.__enter__()
        live[slot] = _Live(req, result, req_span, dec_span, now, req_attrs)
        self._arm_multi(slot, live[slot])
        self._draft_admit(req.prompt, slot, first)
        if self._finished(live[slot]):
            # max_new_tokens == 1 (or instant EOS): the prefill's token was
            # the whole continuation — finish without a decode iteration
            self._finish(slot, live)
        return first

    def _begin_admit(self, req: Request, pending: dict[int, dict]) -> None:
        """Chunked admission: claim the slot (longest cached prefix copied
        in) and queue the prompt for chunk-by-chunk prefill — the first
        token is sampled by the FINAL chunk (``_promote``), so TTFT keeps
        the arrival→first-token meaning, queue AND chunk wait included."""
        kv, tracer = self.kv, self.tracer
        lp = self._check_capacity(req)
        t_claim = self.clock.now()
        req_span = tracer.span("request", rid=req.rid, prompt_len=lp,
                               max_new_tokens=req.max_new_tokens)
        req_attrs = req_span.__enter__() or {}
        slot, reused = kv.begin_insert(req.prompt)
        if hasattr(kv, "note_admission"):
            kv.note_admission(slot, lp + req.max_new_tokens)
        pending[slot] = {"req": req, "span": req_span, "lp": lp,
                         "admitted_s": t_claim, "reused": reused,
                         "attrs": req_attrs,
                         "queue_wait_s": t_claim - req.arrival_s}

    def _promote(self, slot: int, pend: dict, first: int,
                 live: dict[int, _Live]) -> bool:
        """Final chunk done: the slot joins the decode table — or, on a
        prefill-role batcher, leaves for a decode replica (returns False:
        the caller must not deliver the first token locally)."""
        req = pend["req"]
        if self.handoff_out is not None:
            self._handoff(req, slot, pend["span"], pend["attrs"])
            return False
        now = self.clock.now()
        result = RequestResult(
            rid=req.rid, prompt_len=pend["lp"], tokens=[first],
            arrival_s=req.arrival_s, admitted_s=pend["admitted_s"],
            first_token_s=now,
            queue_wait_s=pend["queue_wait_s"],
            prefill_s=now - pend["admitted_s"])
        dec_span = self.tracer.span("decode", rid=req.rid, slot=slot)
        dec_span.__enter__()
        live[slot] = _Live(req, result, pend["span"], dec_span, now,
                           pend["attrs"])
        self._arm_multi(slot, live[slot])
        self._draft_admit(req.prompt, slot, first)
        if self._finished(live[slot]):
            self._finish(slot, live)
        return True

    def _arm_multi(self, slot: int, lv: _Live) -> None:
        """Arm the kv's in-device deactivation for a freshly-live slot
        (multi-step mode only — the flag-off path never touches the
        vectors): the fused rounds stop a slot the moment it emits the
        request's EOS or exhausts its remaining token budget, so later
        fused iterations cannot decode past the stream's end.  The
        budget counts emissions still owed AFTER the prefill's first
        token; a request finished by that first token never dispatches
        (``_finished`` → ``_finish`` evicts it immediately)."""
        if self.multi_step is None:
            return
        remaining = lv.req.max_new_tokens - len(lv.result.tokens)
        self.kv.set_decode_limits(slot, lv.req.eos_id, max(remaining, 0))

    def _handoff(self, req: Request, slot: int, span, attrs) -> None:
        """Prefill-role completion: serialize the finished slot's KV
        (SlotKVCache.extract_handoff — the jitted block read programs +
        device_get), free the slot, and deliver (req, payload) to the
        fleet's handoff hook.  The evict-before-raise guard is the
        no-KV-leak fence the chaos tests pin: at this point the slot is
        visible to NEITHER run()'s live nor its pending cleanup, so a
        fault injected into the extract (or a real device error) must
        release the slot — under paging, its blocks and refcounts —
        right here, before the failure surfaces to the supervisor."""
        kv = self.kv
        try:
            with self.tracer.span("kv_handoff", rid=req.rid, slot=slot,
                                  length=int(kv.lengths[slot])):
                payload = kv.extract_handoff(slot)
        except BaseException:
            kv.evict(slot)
            span.__exit__(None, None, None)
            raise
        kv.evict(slot)
        self._handoffs_out += 1
        attrs.update(handed_off=True,
                     handoff_blocks=len(payload["blocks"]))
        span.__exit__(None, None, None)
        self.handoff_out(req, payload)

    def _draft_admit(self, prompt, slot: int, first: int) -> None:
        """Speculative decode: admit the same prompt into the draft
        table's SAME slot (slot lockstep).  The draft's prefill samples
        its own first token, which is DISCARDED — the committed pending
        token is the target's, so the draft's first proposal next round
        continues the real stream.  The draft prefill is monolithic and
        unpooled by design: the chunked-prefill stall bound covers the
        TARGET's programs, and this per-admission cost is draft-sized —
        the reason production drafts are small (MIGRATING round 14)."""
        if self.draft_kv is None:
            return
        self.draft_kv.insert(prompt, slot=slot)
        self.draft_kv.tokens[slot] = int(first)

    def _finished(self, lv: _Live) -> bool:
        if len(lv.result.tokens) >= lv.req.max_new_tokens:
            return True
        eos = lv.req.eos_id
        return eos is not None and lv.result.tokens[-1] == eos

    def _finish(self, slot: int, live: dict[int, _Live]) -> None:
        lv = live.pop(slot)
        r = lv.result
        r.finished_s = self.clock.now()
        # phase attribution: histogram observations (online percentiles,
        # mergeable across windows) + the same numbers as attrs on the
        # request span record, so `analyze serve` can render the
        # queue→prefill→decode waterfall from the trace alone
        reg = self._registry
        reg.record("ttft", r.ttft_s)
        reg.record("queue_wait", r.queue_wait_s)
        reg.record("prefill", r.prefill_s)
        for gap in r.itl_s:
            reg.record("itl", gap)
        if self.slo is not None:
            r.slo_met = self.slo.observe(r.ttft_s, r.itl_s)
        lv.req_attrs.update(
            queue_wait_s=r.queue_wait_s, prefill_s=r.prefill_s,
            decode_s=r.decode_s, ttft_s=r.ttft_s, tokens=len(r.tokens),
            **({} if r.slo_met is None else {"slo_met": r.slo_met}))
        lv.dec_span.__exit__(None, None, None)
        lv.req_span.__exit__(None, None, None)
        self.kv.evict(slot)
        if self.draft_kv is not None and self.draft_kv.active[slot]:
            self.draft_kv.evict(slot)
        self._results.append(lv.result)

    def _shed(self, req: Request, depth: int) -> None:
        """Bounded-admission rejection (the 429 path): exact accounting —
        a structured ``overload`` trace event + counter, the SLO monitor's
        shed ledger (shed is offered load, never goodput), and a bounded
        record list for the summary."""
        self._shed_count += 1
        if len(self._shed_rids) < 128:   # bounded: accounting, not a log
            self._shed_rids.append(req.rid)
        self.tracer.event("overload", rid=req.rid, queue_depth=depth,
                          queue_cap=self.queue_cap,
                          arrival_s=req.arrival_s)
        self.tracer.counter("shed_requests")
        if self.slo is not None:
            self.slo.shed()

    def _check_preempt(self, iters: int, queue: RequestQueue) -> bool:
        """Consult the lease-drain hook once (sticky): the first reason it
        returns stops admission and emits the structured drain event."""
        if self.should_stop is not None and self._preempted is None:
            reason = self.should_stop(iters)
            if reason:
                self._preempted = reason
                self.tracer.event("serve_preempted", reason=reason,
                                  completed=len(self._results),
                                  unserved=len(queue))
        return self._preempted is not None

    def _idle_wait(self, queue: RequestQueue, target: float,
                   iters: int) -> None:
        """Wait for the next arrival in bounded poll slices (the clock's
        ``poll_slice_s``): each slice re-reads the queue head, so a
        concurrent producer's earlier push is noticed within one slice and
        an idle batcher costs a counted, bounded number of wakeups — never
        a hot spin.  Each slice also consults the lease-drain hook: a
        preemption notice landing in a long idle gap must drain within
        one slice, not after the next arrival (typical grace periods are
        ~30 s — shorter than a sparse workload's gaps)."""
        clock = self.clock
        slice_s = getattr(clock, "poll_slice_s", float("inf"))
        while True:
            now = clock.now()
            nxt = queue.next_arrival()
            if nxt is None or now >= nxt:
                return
            if self._check_preempt(iters, queue):
                return   # the loop top turns this into the drain/break
            self.idle_polls += 1
            clock.wait_until(min(nxt, now + slice_s))

    # ------------------------------------------------------------- the loop
    def _serve(self, queue: RequestQueue, live: dict[int, _Live],
               pending: dict[int, dict],
               on_token: Callable[[int, int], None] | None,
               ) -> tuple[int, int, int]:
        """The iteration loop under run()'s claim + cleanup guard; returns
        (decode_iterations, prefills, prefill_chunks).

        With ``multi_step`` armed (and no draft / non-prefill role) the
        loop is replaced by the pipelined ``_serve_multi`` — same
        admission/shed/observe/chunk passes at the same per-iteration
        boundaries, but decode runs as fused k-step rounds with one
        round always in flight."""
        if (self.multi_step is not None and self.draft_kv is None
                and self.role != "prefill"):
            return self._serve_multi(queue, live, pending, on_token)
        clock = self.clock
        decode_iterations = 0
        prefills = 0
        chunks = 0
        while len(queue) or live or pending:
            # lease drain (should_stop hook, the PR 9 contract): a
            # preemption notice stops admission — in-flight slots finish,
            # claimed (pending) admissions complete, the rest of the
            # queue is left unserved and accounted — so a SIGTERM'd serve
            # window flushes a consistent partial summary instead of
            # dying mid-table
            self._check_preempt(decode_iterations, queue)
            if self._preempted is not None and not (live or pending):
                break
            prefills += self._admission_pass(queue, live, pending, on_token)
            self._shed_pass(queue)
            self._observe_pass(queue, live, pending)
            dc, dp = self._chunk_pass(live, pending, on_token)
            chunks += dc
            prefills += dp
            if not live:
                if pending:
                    continue   # keep chunking: nothing to decode yet
                nxt = queue.next_arrival()
                if nxt is None:
                    break
                self._idle_wait(queue, nxt,  # bounded-slice sleep/jump
                                decode_iterations)
                continue
            emitted = self._decode_round(live)
            decode_iterations += 1
            clock.on_decode_iteration()
            now = clock.now()
            for slot in sorted(live):
                lv = live[slot]
                for j, tok in enumerate(emitted[slot]):
                    lv.result.tokens.append(tok)
                    # ITL attribution per EMITTED token (the SLO math
                    # stays honest): a verify round delivers its accepted
                    # tokens at one host instant, so the first token of
                    # the round carries the inter-round gap and its
                    # batch-mates arrive at gap 0 — the gaps still sum to
                    # the request's decode wall time
                    lv.result.itl_s.append((now - lv.last_t) if j == 0
                                           else 0.0)
                    lv.last_t = now
                    self._decode_tokens += 1
                    if on_token is not None:
                        on_token(lv.req.rid, tok)
                    if self._finished(lv):
                        self._finish(slot, live)
                        break
        return decode_iterations, prefills, chunks

    # ------------------------------------------- shared per-iteration passes
    def _admission_pass(self, queue: RequestQueue, live: dict[int, _Live],
                        pending: dict[int, dict],
                        on_token: Callable[[int, int], None] | None) -> int:
        """Admission between decode iterations → prefill count delta:
        continuous mode fills any free slot from the arrived queue;
        static mode waits for the whole table to drain first."""
        kv, clock = self.kv, self.clock
        prefills = 0
        can_admit = (self._preempted is None
                     and (self.mode == "continuous"
                          or not (live or pending)))
        while can_admit and kv.free_slots:
            req = queue.pop_ready(clock.now())
            if req is None:
                break
            # paged block-exhaustion gate: a free SLOT is not enough
            # when the kv is a block pool — the request's worst-case
            # block need (prompt + max_new_tokens, plus live slots'
            # committed budgets) must fit the free list.  Deferral
            # pushes the request back (FIFO by arrival is preserved:
            # the queue re-sorts) until decode completions release
            # blocks.  With NOTHING in flight the pool is as free as
            # it will ever get, so deferring would busy-spin — admit
            # and let BlockPoolExhausted surface the impossible
            # configuration instead.
            if (hasattr(kv, "can_admit") and (live or pending)
                    and not kv.can_admit(
                        int(np.asarray(req.prompt).reshape(-1)
                            .shape[0]),
                        req.max_new_tokens)):
                queue.push(req)
                self._block_deferrals += 1
                break
            if self.prefill_chunk:
                self._begin_admit(req, pending)
            else:
                first = self._admit(req, live)
                prefills += 1
                if first is not None and on_token is not None:
                    on_token(req.rid, first)  # the prefill's own token
        return prefills

    def _shed_pass(self, queue: RequestQueue) -> None:
        """Bounded admission (overload mode): whatever arrived beyond the
        queue-depth cap after this round's admissions is shed with 429
        accounting — queue wait stays bounded by construction instead of
        growing with offered load."""
        if self.queue_cap and self._preempted is None:
            now = self.clock.now()
            # depth BEFORE shedding: the overload events must record
            # the backlog that triggered them (post-shed depth is
            # always == queue_cap — zero information)
            depth = queue.depth(now)
            for req in queue.shed_ready(now, self.queue_cap):
                self._shed(req, depth)

    def _observe_pass(self, queue: RequestQueue, live: dict[int, _Live],
                      pending: dict[int, dict]) -> None:
        """Queue-pressure attribution: the arrived backlog, per iteration,
        into the histogram the summary's queue_depth_p95 reads (+ the
        queue's own high watermark), and the --timeline sample batch at
        the same boundary."""
        clock = self.clock
        self._registry.record("queue_depth", queue.depth(clock.now()))
        if self.timeline is not None:
            # --timeline sampling at the SAME boundary: queue/slot/
            # prefill pressure plus the kv's host-counter gauges, one
            # throttled batch per iteration — no device syncs, no new
            # keys or programs with the flag off
            self.timeline.sample_many(
                {"queue_depth": queue.depth(clock.now()),
                 "active_slots": len(live),
                 "prefill_pending": len(pending),
                 **self.kv.timeline_gauges()},
                replica=self.timeline_tag, group="batcher")

    def _chunk_pass(self, live: dict[int, _Live], pending: dict[int, dict],
                    on_token: Callable[[int, int], None] | None,
                    ) -> tuple[int, int]:
        """At most ONE ≤budget-token chunk rides each iteration → (chunk,
        prefill) count deltas: the decode stall a filling prompt can
        inflict is bounded by the chunk budget, whatever the prompt
        length."""
        if not pending:
            return 0, 0
        kv, tracer, clock = self.kv, self.tracer, self.clock
        chunks = 0
        prefills = 0
        slot = next(iter(pending))    # FIFO admission order
        pend = pending[slot]
        n = min(kv.pending_tokens(slot), self.prefill_chunk)
        start = int(kv.lengths[slot])
        with tracer.span("prefill_chunk", rid=pend["req"].rid,
                         slot=slot, tokens=n, start=start):
            first = kv.prefill_chunk(slot, self.prefill_chunk)
        chunks += 1
        clock.on_prefill(n)
        if self._rf_cost is not None:
            # n new positions attending over `start` cached ones;
            # the LM head runs once, on the FINAL chunk's sample
            self._rf_prefill_flops += \
                self._rf_cost.prefill_chunk_flops(n, start)
            if first is not None:
                self._rf_prefill_flops += self._rf_cost.lm_head_flops
        if first is not None:
            pending.pop(slot)
            prefills += 1
            if self._promote(slot, pend, first, live) \
                    and on_token is not None:
                on_token(pend["req"].rid, first)
        return chunks, prefills

    # ------------------------------------------------- multi-step pipeline
    def _serve_multi(self, queue: RequestQueue, live: dict[int, _Live],
                     pending: dict[int, dict],
                     on_token: Callable[[int, int], None] | None,
                     ) -> tuple[int, int, int]:
        """The --serve-multi-step iteration loop: each pipeline iteration
        runs the same admission/shed/observe/chunk passes as the legacy
        loop, DISPATCHES the next fused k-step round, and only then
        DRAINS the previous round's token stack — so the device is
        already decoding round i+1 while the host materializes round i's
        tokens and runs scheduling (``copy_to_host_async`` at dispatch,
        the blocking ``np.asarray`` at drain).  Exactly one round is in
        flight at a time: admissions observed between a dispatch and its
        drain take effect on the NEXT round (the fused program's
        host-edit prologue folds them in), bounding admission staleness
        at k fused iterations.  Greedy streams are bitwise identical to
        k=1: the in-device EOS/budget deactivation mirrors
        ``_finished``'s stop conditions exactly, and per-token delivery
        replays the stack level by level with the same clock/ITL
        attribution the legacy loop uses per iteration."""
        kv, tracer = self.kv, self.tracer
        k = self.multi_step
        decode_iterations = 0
        prefills = 0
        chunks = 0
        inflight: tuple[dict, np.ndarray] | None = None
        while len(queue) or live or pending or inflight is not None:
            self._check_preempt(decode_iterations, queue)
            if self._preempted is not None \
                    and not (live or pending or inflight is not None):
                break
            prefills += self._admission_pass(queue, live, pending, on_token)
            self._shed_pass(queue)
            self._observe_pass(queue, live, pending)
            dc, dp = self._chunk_pass(live, pending, on_token)
            chunks += dc
            prefills += dp
            handle = pre = None
            # slots halted ON DEVICE (EOS/budget hit mid-round) never
            # re-dispatch; if every live slot is halted there is nothing
            # to decode — they all finish at this round's drain
            if live and any(not kv.halted[s] for s in live):
                pre = kv.lengths.copy()
                with tracer.span("decode_dispatch", active=len(live), k=k):
                    handle = kv.dispatch_multi(k)
            if inflight is not None:
                h, pre_prev = inflight
                inflight = None
                toks, acts = kv.drain_multi(h)
                decode_iterations += self._deliver_multi(
                    live, toks, acts, pre_prev, on_token)
                # a live slot still halted after delivery hit the
                # device-side stop conditions without ``_finished``
                # agreeing — only possible when the table ran out of
                # room (length == max_len) before the request's budget
                for slot in sorted(live):
                    if kv.halted[slot]:
                        raise SlotOverflow(
                            f"slot {slot} reached max_len={kv.max_len} "
                            f"mid-round with "
                            f"{live[slot].req.max_new_tokens} tokens "
                            "requested — admission must bound "
                            "prompt+max_new_tokens to max_len")
            if handle is not None:
                inflight = (handle, pre)
                continue
            if live or pending:
                continue
            nxt = queue.next_arrival()
            if nxt is None:
                break
            self._idle_wait(queue, nxt,  # bounded-slice sleep/jump
                            decode_iterations)
        return decode_iterations, prefills, chunks

    def _deliver_multi(self, live: dict[int, _Live], toks: np.ndarray,
                       acts: np.ndarray, pre: np.ndarray,
                       on_token: Callable[[int, int], None] | None) -> int:
        """Replay a drained (k, slots) stack level by level as if each
        level were one legacy decode iteration → iterations delivered.
        Every non-empty level advances the clock once and stamps each of
        its tokens with ``now - last_t`` — under VirtualClock this is
        bitwise the k=1 ITL attribution; under WallClock the first level
        of the round carries the real inter-round gap.  Levels where
        every slot was already deactivated (EOS'd mid-round) deliver
        nothing and don't count as iterations."""
        kv, clock = self.kv, self.clock
        iterations = 0
        for j in range(acts.shape[0]):
            if not acts[j].any():
                continue
            if self._rf_cost is not None:
                # context at level j is the dispatch-time length + j
                # committed fused steps — same per-token cost the legacy
                # loop would have tallied at that iteration
                contexts = [int(pre[s]) + j for s in sorted(live)
                            if acts[j, s]]
                if contexts:
                    self._rf_decode_flops += sum(
                        self._rf_cost.decode_flops_per_token(L)
                        for L in contexts)
                    self._rf_decode_bytes += \
                        self._rf_cost.decode_step_bytes(contexts)
            clock.on_decode_iteration()
            now = clock.now()
            iterations += 1
            for slot in sorted(np.flatnonzero(acts[j])):
                slot = int(slot)
                if slot not in live:
                    continue
                lv = live[slot]
                tok = int(toks[j, slot])
                lv.result.tokens.append(tok)
                lv.result.itl_s.append(now - lv.last_t)
                lv.last_t = now
                self._decode_tokens += 1
                if on_token is not None:
                    on_token(lv.req.rid, tok)
                if self._finished(lv):
                    self._finish(slot, live)
        return iterations

    # ------------------------------------------------- speculative decode
    def _decode_round(self, live: dict[int, _Live]) -> dict[int, list[int]]:
        """One target decode iteration → per-slot emitted tokens.

        Without a draft (or when speculation cannot help this round) this
        is the single-token ``advance`` emitting exactly one token per
        live slot — the compiled program and the tokens are byte-identical
        to the draft-off batcher.  With a draft, the round becomes
        draft-k → verify-1 (``_spec_round``): up to ``draft_k + 1``
        tokens per slot from ONE target iteration."""
        kv = self.kv
        k_eff = self._spec_k(live) if self.draft_kv is not None else 0
        if k_eff < 1:
            if self._rf_cost is not None:
                contexts = [int(kv.lengths[s]) for s in sorted(live)]
                self._rf_decode_flops += sum(
                    self._rf_cost.decode_flops_per_token(L)
                    for L in contexts)
                self._rf_decode_bytes += \
                    self._rf_cost.decode_step_bytes(contexts)
            with self.tracer.span("decode_step", active=len(live)):
                toks = kv.advance()
            return {slot: [int(toks[slot])] for slot in live}
        return self._spec_round(live, k_eff)

    def _spec_k(self, live: dict[int, _Live]) -> int:
        """Per-round draft budget: ``draft_k`` capped by the table's
        remaining write capacity (all k+1 verify positions must fit EVERY
        live slot — SlotOverflow is a bookkeeping bug, never a tuning
        knob) and by the longest remaining request budget (proposing past
        every slot's finish line is pure draft waste; one round can emit
        at most k+1 tokens, so k = longest-remaining − 1 suffices)."""
        kv = self.kv
        cap = min(kv.max_len - int(kv.lengths[s]) for s in live) - 1
        needed = max(lv.req.max_new_tokens - len(lv.result.tokens)
                     for lv in live.values()) - 1
        return min(self.draft_k, cap, needed)

    def _spec_round(self, live: dict[int, _Live],
                    k_eff: int) -> dict[int, list[int]]:
        """Draft-k → verify-1.  The draft autoregressively proposes
        ``k_eff`` tokens for every live slot (k_eff single-token draft
        iterations over the whole table), the target scores all k_eff+1
        positions in ONE batched verify step, and each slot accepts the
        longest draft prefix matching the target argmaxes plus the
        target's own next token — exactly the tokens non-speculative
        greedy decode would have emitted, bitwise.  Draft resync is pure
        length bookkeeping (``rewind`` — rejected positions are never
        rewritten); only a FULLY-accepted slot needs one masked catch-up
        draft step, because its last proposal was never consumed by the
        draft itself."""
        kv, draft, tracer = self.kv, self.draft_kv, self.tracer
        slots = sorted(live)
        base = {s: int(kv.lengths[s]) for s in slots}
        if self._rf_cost is not None:
            # TARGET verify flops only (the draft's work is never
            # credited — see __init__); bytes are the one verify step's
            # param + live-KV reads, identical to a width-1 decode: the
            # verify width widens activations, not weight/KV traffic
            self._rf_decode_flops += sum(
                self._rf_cost.verify_flops(base[s], k_eff + 1)
                for s in slots)
            self._rf_decode_bytes += self._rf_cost.decode_step_bytes(
                [base[s] for s in slots])
        block = np.zeros((kv.slots, k_eff + 1), np.int32)
        block[:, 0] = kv.tokens
        with tracer.span("draft_propose", active=len(live), k=k_eff):
            if self.multi_step is not None and k_eff > 1:
                # --serve-multi-step: the draft's k_eff proposal loop IS
                # a fused multi-round (budget 0 = unlimited, no EOS — the
                # draft never self-deactivates; _spec_k already bounds
                # k_eff to the table's capacity), one dispatch instead of
                # k_eff.  Token-identical to the loop below: same program
                # body under lax.scan, same greedy feedback.
                stack, _ = draft.advance_multi(k_eff)
                block[:, 1:] = stack.T
                self._draft_iterations += k_eff
            else:
                for j in range(k_eff):
                    block[:, j + 1] = draft.advance()
                    self._draft_iterations += 1
        with tracer.span("decode_step", active=len(live),
                         verify_width=k_eff + 1):
            g = kv.verify_block(block)
        emitted: dict[int, list[int]] = {}
        full = np.zeros(kv.slots, np.bool_)
        for s in slots:
            a = 0
            while a < k_eff and block[s, a + 1] == g[s, a]:
                a += 1
            emitted[s] = [int(t) for t in g[s, :a + 1]]
            lv = live[s]
            lv.result.proposed_tokens += k_eff
            lv.result.accepted_tokens += a
            self._proposed += k_eff
            self._accepted += a
            kv.commit_block(s, a + 1, int(g[s, a]))
            if a < k_eff:
                # rejected tail: rollback by length bookkeeping alone —
                # draft positions base..base+a already hold the committed
                # tokens' K/V (they were consumed during proposing)
                draft.rewind(s, base[s] + a + 1, int(g[s, a]))
            else:
                full[s] = True
        if full.any():
            # fully-accepted slots: the draft emitted its k-th proposal
            # without ever consuming it, so its cache is one committed
            # token short — one masked draft step writes it (the draft's
            # pending token IS that proposal), then the pending token is
            # overridden with the target's bonus token
            draft.advance(only=full)
            self._draft_catchup += 1
            for s in slots:
                if full[s]:
                    draft.tokens[s] = emitted[s][-1]
        return emitted

    def run(self, requests: Iterable[Request] | RequestQueue,
            on_token: Callable[[int, int], None] | None = None,
            ) -> dict[str, Any]:
        """Serve every request to completion; returns the summary dict
        (per-request results under ``results``).  ``on_token(rid, token)``
        is the streaming hook — called at each token's host delivery."""
        queue = (requests if isinstance(requests, RequestQueue)
                 else RequestQueue(requests))
        offered = len(queue)
        self._results: list[RequestResult] = []
        self._decode_tokens = 0
        self.idle_polls = 0
        # fresh per-run registry (the summary's histograms describe THIS
        # window); an external self.metrics registry accumulates the
        # merged per-window histograms across windows/replicas
        self._registry = MetricsRegistry()
        self._shed_count = 0
        self._shed_rids: list[int] = []
        self._block_deferrals = 0   # paged pool admission deferrals
        self._preempted: str | None = None
        # speculative-decode ledger (zeros when no draft is attached):
        # conservation is exact — accepted + rejected == proposed
        self._proposed = 0
        self._accepted = 0
        self._draft_iterations = 0
        self._draft_catchup = 0
        # disaggregated handoff ledger (stays zero with role=None)
        self._handoffs_out = 0
        self._handoffs_in = 0
        # roofline tallies (stay zero with roofline=None): analytic model
        # FLOPs per phase + the bytes decode MUST read (params + live KV)
        self._rf_prefill_flops = 0.0
        self._rf_decode_flops = 0.0
        self._rf_decode_bytes = 0.0
        if self.slo is not None:
            self.slo.reset()   # one monitor measures one window
        live: dict[int, _Live] = {}
        pending: dict[int, dict] = {}
        prefix_before = self.kv.prefix_cache_stats()
        prefill_before = self.kv.prefill_tokens_computed
        phases_before = self.kv.phase_times()
        # paged-pool counter snapshot (zero-copy/CoW are cumulative on the
        # kv — bench windows share one pool — so the summary reports
        # deltas over THIS run, like the prefix-pool ledger above)
        paged_before = (self.kv.paged_stats()
                        if hasattr(self.kv, "paged_stats") else None)
        # host-dispatch ledger (multi-step accounting): compiled-program
        # host calls as a delta over this run, and the REAL wall clock —
        # clock.now() may be virtual, but the host gap the multi-step
        # pipeline exists to shrink is wall time outside the device
        disp_before = self.kv.dispatch_count + (
            self.draft_kv.dispatch_count if self.draft_kv is not None else 0)
        with queue.claim():
            self.clock.start()
            t_start = self.clock.now()
            wall0 = time.perf_counter()
            try:
                decode_iterations, prefills, chunks = self._serve(
                    queue, live, pending, on_token)
            except BaseException:
                # a torn fused round first: host mirrors lag the device
                # while a round is in flight, and evict() below edits
                # those mirrors — drop the outstanding handles (their
                # tokens are lost with the window) before touching slots
                self.kv.abandon_multi()
                if self.draft_kv is not None:
                    self.draft_kv.abandon_multi()
                # a failed window must not poison the slot table — bench
                # windows share ONE SlotKVCache, and a leaked active slot
                # shrinks every later window's capacity (zero free slots
                # + zero live = a busy-spin).  Free the in-flight slots
                # (decoding AND mid-prefill) and close their spans so the
                # records written so far survive into the partial-results
                # artifact.
                for slot in sorted(live):
                    lv = live.pop(slot)
                    lv.dec_span.__exit__(None, None, None)
                    lv.req_span.__exit__(None, None, None)
                    self.kv.evict(slot)
                    if (self.draft_kv is not None
                            and self.draft_kv.active[slot]):
                        self.draft_kv.evict(slot)
                for slot in sorted(pending):
                    pend = pending.pop(slot)
                    pend["span"].__exit__(None, None, None)
                    # a failure between the FINAL chunk and promotion
                    # leaves the slot pending HERE but already active in
                    # the kv (its kv-side pending entry is gone) —
                    # release whichever state it reached; aborting an
                    # activated slot would raise over the original error
                    if self.kv.has_pending(slot):
                        self.kv.abort_insert(slot)
                    elif self.kv.active[slot]:
                        self.kv.evict(slot)
                raise
            wall_elapsed = time.perf_counter() - wall0
            elapsed = self.clock.now() - t_start
        results = sorted(self._results, key=lambda r: r.rid)
        ttfts = [r.ttft_s for r in results]
        itls = [g for r in results for g in r.itl_s]
        queue_waits = [r.queue_wait_s for r in results]
        tokens = sum(len(r.tokens) for r in results)
        # overload/drain conservation ledger: every offered request is
        # admitted (and completed — run() drains), shed, or left unserved
        # by a lease drain; admitted + shed + unserved == offered exactly
        admitted = len(results)
        unserved = len(queue)
        slo_sec = (self.slo.summary(elapsed) if self.slo is not None
                   else None)
        if self.metrics is not None:
            self.metrics.merge(self._registry)
        depth_hist = self._registry.histogram("queue_depth")
        phases_after = self.kv.phase_times()
        # prefill/decode token split + prefix-pool accounting, as deltas
        # over this run (bench windows share one SlotKVCache)
        prefill_tokens = self.kv.prefill_tokens_computed - prefill_before
        prefix_after = self.kv.prefix_cache_stats()
        prefix_sec = hit_rate = None
        if prefix_after is not None:
            prefix_sec = {
                k: prefix_after[k] - (prefix_before or {}).get(k, 0)
                for k in ("hits", "misses", "evictions", "tokens_reused",
                          "inserted_blocks")}
            prefix_sec["cached_blocks"] = prefix_after["cached_blocks"]
            asked = prefix_sec["hits"] + prefix_sec["misses"]
            hit_rate = prefix_sec["hits"] / asked if asked else 0.0
        # paged-pool accounting: utilization is CURRENT pool state
        # (blocks still backing live/pinned data), the zero-copy/CoW
        # ledger is the delta over this run.  zero-copy hit rate = aliased
        # blocks over blocks asked of the prefix pool — the fraction of
        # reusable prefix KV shared by POINTER instead of copied.
        paged_sec = zero_copy_rate = None
        if paged_before is not None:
            paged_after = self.kv.paged_stats()
            paged_sec = {
                k: paged_after[k] - paged_before.get(k, 0)
                for k in ("zero_copy_hits", "zero_copy_blocks",
                          "zero_copy_tokens", "cow_copies")}
            paged_sec["num_blocks"] = paged_after["num_blocks"]
            paged_sec["block"] = paged_after["block"]
            paged_sec["blocks_in_use"] = paged_after["blocks_in_use"]
            paged_sec["utilization"] = paged_after["utilization"]
            paged_sec["block_deferrals"] = self._block_deferrals
            if prefix_sec is not None:
                asked = prefix_sec["hits"] + prefix_sec["misses"]
                zero_copy_rate = (paged_sec["zero_copy_blocks"] / asked
                                  if asked else 0.0)
        summary = {
            "mode": self.mode,
            "requests": len(results),
            "completed": len(results),
            # KV-table storage dtype (SlotKVCache kv_dtype — the --serve-
            # kv-dtype memory knob) + the stored bytes behind it, per
            # slot (gated lower-is-better by `analyze diff`: the
            # capacity-per-chip number int8/bf16 storage exists to
            # shrink); both ride into the serve report section
            "serve_kv_dtype": getattr(self.kv, "kv_dtype", None),
            "serve_kv_bytes_per_slot": self.kv.kv_bytes_per_slot(),
            # --serve-kv-layout: paged pool accounting (None/0 under
            # monolithic — the keys are always present so `analyze diff`
            # gates them when both runs page).  blocks_in_use is gated
            # lower (fewer physical blocks for the same streams = the
            # aliasing working), zero-copy rate higher.
            "serve_kv_layout": getattr(self.kv, "kv_layout", "monolithic"),
            "serve_kv_blocks_in_use": (paged_sec["blocks_in_use"]
                                       if paged_sec else None),
            "serve_kv_block_utilization": (paged_sec["utilization"]
                                           if paged_sec else None),
            "serve_prefix_zero_copy_hit_rate": zero_copy_rate,
            "serve_kv_block_deferrals": self._block_deferrals,
            "paged": paged_sec,
            # speculative decoding (draft-k → verify-1): accept rate over
            # THIS run's proposals (None: no draft attached — the key is
            # always present so `analyze diff` gates it when both runs
            # speculate) + the full ledger.  tokens_per_sec counts
            # EMITTED tokens only (BASELINE.md accounting rule); accept
            # rate is workload- and draft-dependent.
            "serve_accept_rate": (self._accepted / self._proposed
                                  if self._proposed else None),
            "speculative": (None if self.draft_kv is None else {
                "draft_k": self.draft_k,
                "proposed_tokens": self._proposed,
                "accepted_tokens": self._accepted,
                "rejected_tokens": self._proposed - self._accepted,
                "draft_iterations": self._draft_iterations,
                "draft_catchup_steps": self._draft_catchup,
                "draft_kv_dtype": self.draft_kv.kv_dtype,
            }),
            "decode_iterations": decode_iterations,
            "prefills": prefills,
            "prefill_chunk": self.prefill_chunk,
            "prefill_chunks": chunks,
            "prefill_tokens": prefill_tokens,
            "decode_tokens": self._decode_tokens,
            "idle_polls": self.idle_polls,
            "tokens_generated": tokens,
            "elapsed_s": elapsed,
            "serve_requests_per_sec": (len(results) / elapsed
                                       if elapsed > 0 else None),
            "serve_tokens_per_sec": (tokens / elapsed
                                     if elapsed > 0 else None),
            # the split the chunked-prefill trade is tuned by: prompt
            # tokens prefilled vs tokens decoded, per wall/virtual second
            "serve_prefill_tokens_per_sec": (prefill_tokens / elapsed
                                             if elapsed > 0 else None),
            "serve_decode_tokens_per_sec": (self._decode_tokens / elapsed
                                            if elapsed > 0 else None),
            # block-level prefix-pool hit rate for THIS run (None: pool
            # off) + the hit/miss/evict ledger behind it
            "serve_prefix_cache_hit_rate": hit_rate,
            "prefix_cache": prefix_sec,
            "serve_ttft_p50_s": _percentile(ttfts, 0.50),
            "serve_ttft_p95_s": _percentile(ttfts, 0.95),
            "serve_ttft_p99_s": _percentile(ttfts, 0.99),
            "serve_itl_p50_s": _percentile(itls, 0.50),
            "serve_itl_p95_s": _percentile(itls, 0.95),
            "serve_itl_p99_s": _percentile(itls, 0.99),
            # queue-pressure attribution (stored-sample path, like the
            # TTFT/ITL percentiles above; the histogram copies ride the
            # `histograms` section below and are asserted within one
            # bucket width of these)
            "serve_queue_wait_p50_s": _percentile(queue_waits, 0.50),
            "serve_queue_wait_p95_s": _percentile(queue_waits, 0.95),
            "serve_queue_wait_p99_s": _percentile(queue_waits, 0.99),
            "queue_depth_p95": depth_hist.quantile(0.95),
            "queue_depth_high_watermark": queue.depth_high_watermark,
            # bounded-admission overload accounting (exact conservation:
            # admitted + shed + unserved == offered)
            "queue_cap": self.queue_cap,
            "offered": offered,
            "admitted": admitted,
            "shed_requests": self._shed_count,
            "shed_rids": list(self._shed_rids),
            "unserved_requests": unserved,
            "serve_shed_rate": (self._shed_count / offered
                                if offered else 0.0),
            # lease drain: the should_stop reason when this window was
            # preempted mid-run (None = ran to completion) — the partial
            # accounting above is still exact
            "preempted": self._preempted,
            # goodput under the SLO (requests/sec meeting BOTH targets;
            # None when no SLOMonitor is attached) + the monitor's section
            "serve_goodput_under_slo": (
                slo_sec.get("goodput_requests_per_sec")
                if slo_sec else None),
            "slo": slo_sec,
            # online log-bucketed histograms of the per-phase attribution
            # (queue_wait / prefill / ttft / itl / queue_depth): p50/95/99
            # within one bucket's relative width of the stored-sample
            # percentiles, mergeable across windows via `metrics=`
            "histograms": self._registry.snapshot(),
            # host-observed seconds inside the kv's compiled programs,
            # as deltas over this run (SlotKVCache.phase_times)
            "device_phase_s": {
                k: phases_after[k] - phases_before.get(k, 0.0)
                for k in phases_after},
            "results": results,
        }
        if self.role is not None:
            # disaggregated-role keys ride the summary ONLY when a role
            # is assigned: the role=None key set stays byte-identical to
            # round 17 (the flag-off summary-key parity pin)
            summary["serve_role"] = self.role
            summary["handoffs_out"] = self._handoffs_out
            summary["handoffs_in"] = self._handoffs_in
        if self.timeline is not None:
            # timeline-derived keys ride the summary ONLY when sampling is
            # on: the flag-off key set stays byte-identical (parity pin)
            tag = self.timeline_tag
            summary["queue_depth_auc"] = self.timeline.stat(
                "queue_depth", "auc", replica=tag)
            summary["kv_blocks_in_use_p95"] = self.timeline.stat(
                "kv_blocks_in_use", "p95", replica=tag)
            summary["timeline_overhead_s"] = self.timeline.overhead_s
        if self.roofline is not None:
            # --roofline keys ride ONLY when a Roofline is attached: the
            # flag-off key set stays byte-identical to round 18 (parity
            # pin).  Achieved rates divide the analytic tallies by the
            # kv's own per-phase device seconds; on an unknown device
            # kind mfu()/mbu() return None — never a fabricated peak.
            rf = self.roofline
            dphase = summary["device_phase_s"]
            pre_s = dphase.get("prefill_s", 0.0)
            dec_s = dphase.get("decode_s", 0.0)
            pre_fps = (self._rf_prefill_flops / pre_s
                       if pre_s > 0 else None)
            dec_fps = (self._rf_decode_flops / dec_s
                       if dec_s > 0 else None)
            dec_bps = (self._rf_decode_bytes / dec_s
                       if dec_s > 0 else None)
            summary["serve_prefill_mfu"] = rf.mfu(pre_fps)
            summary["serve_decode_mbu"] = rf.mbu(dec_bps)
            summary["roofline"] = {
                # analytic model work (BASELINE.md: model flops, never
                # rematerialization; must-read bytes, never bytes moved)
                "prefill_model_flops": self._rf_prefill_flops,
                "decode_model_flops": self._rf_decode_flops,
                "decode_must_read_bytes": self._rf_decode_bytes,
                "prefill_s": pre_s,
                "decode_s": dec_s,
                "prefill_achieved_flops_per_sec": pre_fps,
                "decode_achieved_flops_per_sec": dec_fps,
                "decode_achieved_bytes_per_sec": dec_bps,
                "prefill_mfu": rf.mfu(pre_fps),
                "decode_mfu": rf.mfu(dec_fps),
                "decode_mbu": rf.mbu(dec_bps),
                "device": rf.describe(),
            }
        if self.multi_step is not None:
            # multi-step keys ride ONLY when the flag is set: the
            # flag-off summary key set stays byte-identical to round 19
            # (parity pin).  serve_dispatches counts compiled-program
            # host calls (every jitted entry: prefill, decode, fused
            # rounds, verify — the denominator the k× win divides);
            # serve_host_gap_s is REAL wall time minus host-observed
            # device seconds — Python scheduling + D2H sync + H2D upload,
            # exactly what fusing k iterations amortizes (gated
            # lower-is-better by `analyze diff`).
            dphase = summary["device_phase_s"]
            dispatches = (self.kv.dispatch_count
                          + (self.draft_kv.dispatch_count
                             if self.draft_kv is not None else 0)
                          - disp_before)
            summary["serve_multi_step"] = self.multi_step
            summary["serve_dispatches"] = dispatches
            summary["serve_host_gap_s"] = max(
                wall_elapsed - dphase.get("prefill_s", 0.0)
                - dphase.get("decode_s", 0.0), 0.0)
            if self.roofline is not None:
                summary["roofline"]["dispatches"] = dispatches
                summary["roofline"]["host_gap_s"] = \
                    summary["serve_host_gap_s"]
        return summary
