"""Continuous-batching inference serving (ROADMAP item 1).

Three layers:

* ``kv_cache.SlotKVCache`` — the device half: a fixed slot table of KV
  buffers sharded over the training mesh, one compiled single-token decode
  step for the whole table, and a compiled per-bucket prefill-insert so
  admission never recompiles decoding.  Chunk-resumable prefill
  (``begin_insert``/``prefill_chunk``) splits an admission into fixed
  token-budget chunks, and the optional block-granular prefix pool
  (``prefix_cache_blocks``) reuses cached shared-prompt KV with LRU
  eviction and hit/miss accounting.  ``kv_layout="paged"`` swaps the
  per-slot rows for ``PagedSlotKVCache``'s refcounted physical block
  pool (vLLM PagedAttention): prefix-pool hits alias blocks by pointer
  (zero KV bytes copied), first write into a shared block copies on
  write, decode/verify read through the block table in one fused Pallas
  kernel (``ops/paged_attention.py``, in-kernel int8 dequant), and pool
  pressure defers admission (``can_admit``) or raises
  ``BlockPoolExhausted``.
* ``scheduler.ContinuousBatcher`` — the host half: an iteration-level
  request scheduler (admit between decode steps, evict finished slots,
  with ``prefill_chunk`` at most one prompt chunk interleaved per decode
  iteration — Sarathi-Serve stall bounding) with MLPerf-style TTFT/ITL
  percentile accounting, a prefill/decode token split, and per-request
  trace spans through the existing observability stack.
* ``fleet.ReplicaSet`` — the fault-tolerance layer: N batcher replicas
  behind a least-loaded router, a request journal with an exactly-once
  emission fence, no-loss failover with bounded retry (resume
  re-prefills prompt + emitted prefix, greedy-exact), seeded fault
  injection (``FaultInjector``), and graceful drain + zero-downtime
  weight hot-swap (``SlotKVCache.swap_params``) that never drops the
  fleet below N−1 admitting replicas.  Round 18 makes the fleet
  heterogeneous, all default-off: ``roles`` disaggregates prefill from
  decode with a serialized KV handoff
  (``SlotKVCache.extract_handoff``/``restore_handoff``), so decode
  replicas never share an iteration with a long prompt;
  ``routing="affinity"`` lands shared-prefix traffic where its first
  prefix block is already warm; ``autoscale`` (``AutoscalePolicy``)
  drives the serving-replica count from arrived queue depth with
  ``serve_replica_seconds`` as the efficiency ledger; and
  ``parallel_lanes`` gives each replica its own virtual-time lane so
  fleet time overlaps replicas deterministically.

``bench.py --serve`` drives an open-loop arrival process through both and
reports requests/sec/chip + latency percentiles; the harness's ``--serve``
flag runs a post-training serving window whose summary lands in the run
report, gated by ``analyze diff`` exactly like the training metrics.
"""

from distributed_tensorflow_tpu.serving.fleet import (  # noqa: F401
    AutoscalePolicy, CorruptionDetected, FaultInjector, FaultSpec,
    InjectedFault, ReplicaSet, RequestJournal, build_replica_kvs)
from distributed_tensorflow_tpu.serving.kv_cache import (  # noqa: F401
    BlockPoolExhausted, PagedSlotKVCache, SlotKVCache, SlotOverflow)
from distributed_tensorflow_tpu.serving.scheduler import (  # noqa: F401
    ContinuousBatcher, Request, RequestQueue, RequestResult, VirtualClock,
    WallClock)
