#!/usr/bin/env python
"""Launcher — drop-in role of the reference's initializer.py.

Like the reference (reference README.md:12), users plug in their own
``model_fn`` / ``dataset_fn`` by editing this file; unlike the reference
they are passed explicitly into the CLI (no fork-inherited globals,
SURVEY.md §2.4(5)).  Leave them as None to use --model/--dataset.

Examples:
  python initializer.py -m tpu_pod --dataset mnist --model cnn -n 8 -b 32
  python initializer.py -m c -cs sync -n 4 -b 32      # PS-sync semantics
  python initializer.py -m c -cs async -n 4 -b 32     # local-SGD async
  python initializer.py -m d -ds keras -n 4 -b 32     # allreduce
  python initializer.py -m d -ds custom -n 4 -d 2     # gossip ring, degree 2
  python initializer.py -m t --model bert_tiny --dataset glue_synth -sp 4
  python initializer.py -m t --model moe -ep 4 --num-experts 8
  python initializer.py -m t -tp 4 --dtype bf16       # Megatron TP + bf16
"""

from distributed_tensorflow_tpu.cli import main

# --- user plug-in point (reference README.md:12) ---------------------------
# Edit these like the reference's initializer.py model_fn/dataset_fn.
# model_fn() -> flax.linen.Module with __call__(x, train: bool) -> logits
# dataset_fn(batch_size, type='train'|'test', shard=False, index=0,
#            buffer_size=10000, reshape=True, n_shards=1) -> data.Dataset
#
# Example:
#   def model_fn():
#       from distributed_tensorflow_tpu.models.mlp import MLP
#       return MLP(num_classes=10, hidden=512)
#
#   from distributed_tensorflow_tpu.data import make_dataset_fn
#   dataset_fn = make_dataset_fn("mnist")

model_fn = None
dataset_fn = None
# ---------------------------------------------------------------------------

if __name__ == "__main__":
    main(model_fn=model_fn, dataset_fn=dataset_fn)
