#!/usr/bin/env python
"""Launcher — drop-in role of the reference's initializer.py.

Like the reference (reference README.md:12), users may plug in their own
``model_fn`` / ``dataset_fn`` below; unlike the reference these are passed
explicitly (no fork-inherited globals, SURVEY.md §2.4(5)).

Examples:
  python initializer.py -m tpu_pod --dataset mnist --model mlp -n 8 -b 32
  python initializer.py -m c -cs sync -n 4 -b 32      # PS-sync semantics
  python initializer.py -m c -cs async -n 4 -b 32     # local-SGD async
  python initializer.py -m d -ds keras -n 4 -b 32     # allreduce
  python initializer.py -m d -ds custom -n 4 -d 2     # gossip ring, degree 2
"""

from distributed_tensorflow_tpu.cli import main

# --- user plug-in point (optional) -----------------------------------------
# def model_fn():
#     import flax.linen as nn
#     from distributed_tensorflow_tpu.models.mlp import MLP
#     return MLP(num_classes=10)
#
# def dataset_fn(batch_size, type="train", shard=False, index=0,
#                buffer_size=10000, reshape=True, n_shards=1):
#     from distributed_tensorflow_tpu.data import make_dataset_fn
#     return make_dataset_fn("mnist")(batch_size, type, shard, index,
#                                     buffer_size, reshape, n_shards)
# ---------------------------------------------------------------------------

if __name__ == "__main__":
    main()
