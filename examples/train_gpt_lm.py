#!/usr/bin/env python
"""Causal-LM pretraining: GPT decoder on the synthetic Markov-chain corpus.

The decoder family composes with every parallel mode; this example shows the
two most useful single-knob renderings — plain DP with the Pallas causal
flash kernel, and long-context ring-attention sequence parallelism (pass
``--seq-parallel 4``).  No reference counterpart (SURVEY.md §2.2: no
language models anywhere).

  JAX_PLATFORM_NAME=cpu JAX_PLATFORMS="" \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/train_gpt_lm.py [--seq-parallel 4]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import jax

from distributed_tensorflow_tpu.data.loaders import load_lm_dataset
from distributed_tensorflow_tpu.engines import (
    SeqParallelEngine, SyncEngine, Trainer)
from distributed_tensorflow_tpu.models import create_model
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def main(seq_parallel: int = 1) -> None:
    train = load_lm_dataset(seq_len=64, vocab_size=128)
    test = load_lm_dataset(seq_len=64, vocab_size=128, split="test")

    total = jax.device_count()
    if seq_parallel > 1:
        dp = total // seq_parallel
        mesh = meshlib.create_mesh(total, shape=(dp, seq_parallel),
                                   axis_names=("data", "seq"))
        model = create_model("gpt", num_classes=train.num_classes,
                             hidden=64, layers=2, heads=4, ffn=128,
                             max_len=64, attention_impl="ring_flash")
        engine = SeqParallelEngine(model, mesh=mesh, learning_rate=3e-3)
    else:
        dp = total
        mesh = meshlib.create_mesh(total)
        # 'flash' = the Pallas causal kernel (interpret mode off-TPU)
        model = create_model("gpt", num_classes=train.num_classes,
                             hidden=64, layers=2, heads=4, ffn=128,
                             max_len=64, attention_impl="flash")
        engine = SyncEngine(model, mesh=mesh, learning_rate=3e-3)

    trainer = Trainer(None, engine=engine)
    fit = trainer.fit(train, epochs=2, batch_size=8 * dp, log_every=20)
    ev = trainer.evaluate(test, batch_size=64)
    print(f"steps={fit['steps']}  elapsed={fit['elapsed']:.1f}s  "
          f"token-accuracy={ev['accuracy']:.3f}  perplexity-proxy "
          f"loss={ev['loss']:.3f}")


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--seq-parallel", type=int, default=1)
    main(p.parse_args().seq_parallel)
