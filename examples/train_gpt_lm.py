#!/usr/bin/env python
"""Causal-LM pretraining: GPT decoder on the synthetic Markov-chain corpus.

The decoder family composes with every parallel mode; this example shows the
two most useful single-knob renderings — plain DP with the Pallas causal
flash kernel, and long-context ring-attention sequence parallelism (pass
``--seq-parallel 4``).  No reference counterpart (SURVEY.md §2.2: no
language models anywhere).

  JAX_PLATFORM_NAME=cpu JAX_PLATFORMS="" \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/train_gpt_lm.py [--seq-parallel 4]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import jax

from distributed_tensorflow_tpu.data.loaders import load_lm_dataset
from distributed_tensorflow_tpu.engines import (
    SeqParallelEngine, SyncEngine, Trainer)
from distributed_tensorflow_tpu.models import create_model
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def main(seq_parallel: int = 1) -> None:
    train = load_lm_dataset(seq_len=64, vocab_size=128)
    test = load_lm_dataset(seq_len=64, vocab_size=128, split="test")

    total = jax.device_count()
    if seq_parallel > 1:
        dp = total // seq_parallel
        mesh = meshlib.create_mesh(total, shape=(dp, seq_parallel),
                                   axis_names=("data", "seq"))
        model = create_model("gpt", num_classes=train.num_classes,
                             hidden=64, layers=2, heads=4, ffn=128,
                             max_len=64, attention_impl="ring_flash")
        engine = SeqParallelEngine(model, mesh=mesh, learning_rate=3e-3)
    else:
        dp = total
        mesh = meshlib.create_mesh(total)
        # the Pallas causal kernel on TPU; dense on CPU (interpret-mode
        # Pallas is orders of magnitude slower than XLA there — right for
        # correctness tests, wrong for a demo)
        impl = "flash" if jax.default_backend() == "tpu" else "dense"
        model = create_model("gpt", num_classes=train.num_classes,
                             hidden=64, layers=2, heads=4, ffn=128,
                             max_len=64, attention_impl=impl)
        engine = SyncEngine(model, mesh=mesh, learning_rate=3e-3)

    trainer = Trainer(None, engine=engine)
    fit = trainer.fit(train, epochs=1, batch_size=8 * dp, log_every=20)
    ev = trainer.evaluate(test, batch_size=64)
    print(f"steps={fit['steps']}  elapsed={fit['elapsed']:.1f}s  "
          f"token-accuracy={ev['accuracy']:.3f}  perplexity-proxy "
          f"loss={ev['loss']:.3f}")

    # sample a continuation with the KV cache (greedy): the trained chain
    # model should keep producing plausible transitions
    from distributed_tensorflow_tpu.models.gpt import generate

    params = jax.device_get(engine.eval_params(trainer.state))
    cont = generate(model, params, test.x[:2, :16], max_new_tokens=16,
                    greedy=True)
    print("prompt :", test.x[0, :16].tolist())
    print("sampled:", cont[0].tolist())


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--seq-parallel", type=int, default=1)
    main(p.parse_args().seq_parallel)
