#!/usr/bin/env python
"""Data-parallel MNIST training via the library API.

The library rendering of the reference's default workload (reference
initializer.py:12-21 MLP + MNIST): sync DP over every local device, full
test-set eval.  Runs on real TPUs or the fake CPU mesh:

  JAX_PLATFORM_NAME=cpu JAX_PLATFORMS="" \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/train_mnist_dp.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

from distributed_tensorflow_tpu.data.loaders import load_dataset
from distributed_tensorflow_tpu.engines import Trainer
from distributed_tensorflow_tpu.models import create_model
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def main() -> None:
    mesh = meshlib.create_mesh()
    n = mesh.devices.size
    print(f"mesh: {n} devices on axis '{meshlib.DATA_AXIS}'")

    model = create_model("cnn", num_classes=10)
    train = load_dataset("mnist", split="train")
    test = load_dataset("mnist", split="test")

    trainer = Trainer(model, mesh=mesh, learning_rate=1e-3)
    fit = trainer.fit(train, epochs=1, batch_size=64 * n, log_every=50)
    ev = trainer.evaluate(test)
    print(f"steps={fit['steps']}  {fit['examples_per_sec']:.0f} ex/s  "
          f"accuracy={ev['accuracy']:.4f}")


if __name__ == "__main__":
    main()
