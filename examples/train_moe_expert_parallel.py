#!/usr/bin/env python
"""Mixture-of-Experts training with expert parallelism.

Experts shard over an 'expert' mesh axis (GShard/Switch dense-dispatch,
models/moe.py); XLA lowers the dispatch einsums to all-to-alls over ICI.
No reference counterpart (SURVEY.md §2.2: no MoE anywhere).

  JAX_PLATFORM_NAME=cpu JAX_PLATFORMS="" \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/train_moe_expert_parallel.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import jax

from distributed_tensorflow_tpu.data.loaders import load_dataset
from distributed_tensorflow_tpu.engines import ExpertParallelEngine
from distributed_tensorflow_tpu.models import create_model
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def main(expert_parallel: int = 4, num_experts: int = 8) -> None:
    total = jax.device_count()
    dp = total // expert_parallel
    mesh = meshlib.create_mesh(
        total, shape=(dp, expert_parallel),
        axis_names=(meshlib.DATA_AXIS, meshlib.EXPERT_AXIS))
    print(f"mesh: data={dp} x expert={expert_parallel}, "
          f"{num_experts} experts ({num_experts // expert_parallel}/device)")

    train = load_dataset("mnist", split="train")
    test = load_dataset("mnist", split="test")
    model = create_model("moe", num_classes=train.num_classes,
                         num_experts=num_experts, partition_experts=True)

    eng = ExpertParallelEngine(model, mesh=mesh, learning_rate=1e-3)
    state = eng.init_state(jax.random.key(0), train.x[:total])
    for step, (bx, by, _) in enumerate(
            train.batches(16 * total, shuffle=True, drop_remainder=True)):
        state, m = eng.step(state, *eng.shard_batch(bx, by))
        if step % 20 == 0:
            print(f"step {step}  task-loss {float(m['loss']):.4f}  "
                  f"total {float(m['total_loss']):.4f}")
    ev = eng.evaluate(state, test)
    print(f"accuracy={ev['accuracy']:.4f}")


if __name__ == "__main__":
    main()
