#!/usr/bin/env python
"""Long-context BERT-tiny fine-tune with ring-attention sequence parallelism.

Shards the sequence dimension over a 'seq' mesh axis: each device holds a
slice of every sequence, and attention runs as a blockwise ppermute ring
(parallel/ring_attention.py) so the full sequence never materializes on one
device.  No reference counterpart (SURVEY.md §2.2: no attention anywhere).

  JAX_PLATFORM_NAME=cpu JAX_PLATFORMS="" \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/train_bert_seq_parallel.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import jax

from distributed_tensorflow_tpu.data.loaders import load_text_dataset
from distributed_tensorflow_tpu.engines import SeqParallelEngine
from distributed_tensorflow_tpu.models import create_model
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def main(seq_parallel: int = 4) -> None:
    total = jax.device_count()
    dp = total // seq_parallel
    mesh = meshlib.create_mesh(
        total, shape=(dp, seq_parallel),
        axis_names=(meshlib.DATA_AXIS, meshlib.SEQ_AXIS))
    print(f"mesh: data={dp} x seq={seq_parallel}")

    train = load_text_dataset("glue_synth", split="train", seq_len=128)
    test = load_text_dataset("glue_synth", split="test", seq_len=128)
    model = create_model("bert_tiny", num_classes=train.num_classes,
                         attention_impl="ring")

    eng = SeqParallelEngine(model, mesh=mesh, learning_rate=3e-4)
    state = eng.init_state(jax.random.key(0), train.x[:dp])
    for epoch in range(1):
        for step, (bx, by, _) in enumerate(
                train.batches(8 * dp, shuffle=True, epoch=epoch,
                              drop_remainder=True)):
            state, m = eng.step(state, *eng.shard_batch(bx, by))
            if step % 50 == 0:
                print(f"step {step}  loss {float(m['loss']):.4f}")
    ev = eng.evaluate(state, test)
    print(f"accuracy={ev['accuracy']:.4f}")


if __name__ == "__main__":
    main()
