#!/usr/bin/env python
"""MoE pipeline training: dp×pp×ep on one 3-D mesh.

A GPT decoder split over pipeline stages (GPipe collective schedule,
engines/pipeline.py) whose stage blocks carry routed MoE FFNs — the
experts shard over an 'expert' GSPMD auto axis while the pipe ppermute
ring stays manual, so stage activations ride ICI between stages AND
expert dispatch rides ICI within them.  No reference counterpart
(SURVEY.md §2.2: no pipeline, no MoE anywhere).

  JAX_PLATFORM_NAME=cpu JAX_PLATFORMS="" \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/train_moe_pipeline.py

CLI spelling of the same run:
  python initializer.py -m t -pp 2 -ep 2 --model gpt --dataset lm_synth \
      --num-experts 4
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import jax
import numpy as np

from distributed_tensorflow_tpu.data.loaders import load_lm_dataset
from distributed_tensorflow_tpu.engines.pipeline import PipelineEngine
from distributed_tensorflow_tpu.models.gpt import gpt_pipeline_stages
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def main(pipeline_parallel: int = 2, expert_parallel: int = 2,
         num_experts: int = 4) -> None:
    total = jax.device_count()
    dp = total // (pipeline_parallel * expert_parallel)
    mesh = meshlib.create_mesh(
        total, shape=(dp, pipeline_parallel, expert_parallel),
        axis_names=(meshlib.DATA_AXIS, meshlib.PIPE_AXIS,
                    meshlib.EXPERT_AXIS))
    print(f"mesh: data={dp} x pipe={pipeline_parallel} x "
          f"expert={expert_parallel}; {num_experts} experts "
          f"({num_experts // expert_parallel}/expert-device), "
          f"{pipeline_parallel} stages")

    # small synthetic corpus: the demo is the composition, not the corpus
    train = load_lm_dataset(seq_len=32, vocab_size=256, n_train=512)
    eng = PipelineEngine(
        microbatches=4, mesh=mesh, learning_rate=1e-3,
        stages=gpt_pipeline_stages(
            vocab_size=train.num_classes, hidden=64, heads=4, ffn=128,
            max_len=32, moe_experts=num_experts, partition_experts=True))

    state = eng.init_state(jax.random.key(0), train.x[:dp])
    batch = 8 * dp
    for step, (bx, by, _) in enumerate(
            train.batches(batch, shuffle=True, drop_remainder=True)):
        state, m = eng.step(state, *eng.shard_batch(bx, by))
        if step % 20 == 0:
            print(f"step {step}  loss {float(m['loss']):.4f}  "
                  f"overflow {float(m['overflow']):.3f}")
    ev = eng.evaluate(state, train)
    print(f"final train accuracy={ev['accuracy']:.4f}  "
          f"perplexity={float(np.exp(ev['loss'])):.2f}")


if __name__ == "__main__":
    main()
