"""Test harness: fake 8-device CPU mesh.

The SPMD analogue of the reference's fake cluster (fork + loopback TCP,
reference initializer.py:134-145): we expose 8 XLA host-platform devices so
every multi-device code path runs on CPU.  The environment may preload jax
(sitecustomize) before this module runs, so we switch platform via
``jax.config`` — valid as long as no backend has been initialized yet.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from distributed_tensorflow_tpu.parallel import mesh as meshlib

    return meshlib.create_mesh(8)


@pytest.fixture(scope="session")
def devices():
    return jax.devices()
