"""Test harness: fake 8-device CPU mesh.

The SPMD analogue of the reference's fake cluster (fork + loopback TCP,
reference initializer.py:134-145): we expose 8 XLA host-platform devices so
every multi-device code path runs on CPU.  The environment may preload jax
(sitecustomize) before this module runs, so we switch platform via
``jax.config`` — valid as long as no backend has been initialized yet.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _force_cpu_mesh  # noqa: E402

_force_cpu_mesh(8)

import jax  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from distributed_tensorflow_tpu.parallel import mesh as meshlib

    return meshlib.create_mesh(8)


@pytest.fixture(scope="session")
def devices():
    return jax.devices()
