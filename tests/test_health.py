"""Numeric-health layer + offline trace analyzer (ISSUE 4).

Covers the acceptance contracts: health stats computed on device inside
the jitted scan and stacked like metrics (k=8 on-disk stream bitwise equal
to k=1), ``--health off`` leaving the program untouched (health ON must
not perturb the trajectory either — the captures are pass-through), the
seeded-NaN injection caught AT its step by ``on_anomaly='halt'`` with a
structured ``anomaly`` event naming the offending stat (where the loss-only
nan_guard catches it a log-cadence later), and the analyzer round-trip:
trace JSONL → Chrome-trace JSON with one complete event per span, plus the
run-vs-run diff exiting nonzero iff a metric regresses beyond threshold.

Engine-layer machinery runs through the pure-jit ``JitEngine`` (any
container); the shard_map engines get a health smoke wherever the engine
layer itself runs.
"""

import json
import math
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

sys.path.insert(0, os.path.dirname(__file__))
from test_steady_state import JitEngine, _tiny_ds  # noqa: E402

from distributed_tensorflow_tpu.engines.allreduce import Trainer  # noqa: E402
from distributed_tensorflow_tpu.observability import analyze  # noqa: E402
from distributed_tensorflow_tpu.observability import Tracer, build_run_report  # noqa: E402
from distributed_tensorflow_tpu.observability import health as hl  # noqa: E402
from distributed_tensorflow_tpu.utils.failure import (  # noqa: E402
    AnomalyDetected, TrainingDiverged)
from distributed_tensorflow_tpu.utils.metrics import MetricsLogger  # noqa: E402

needs_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="shard_map engine layer needs a newer jax than this container")


# ------------------------------------------------------------ capture units

def test_global_norm_and_nonfinite_count():
    tree = {"a": jnp.full((2, 3), 2.0), "b": jnp.ones((4,))}
    assert float(hl.global_norm(tree)) == pytest.approx(math.sqrt(4 * 6 + 4))
    assert float(hl.nonfinite_leaf_count(tree)) == 0
    bad = {"a": jnp.array([1.0, jnp.nan]), "b": jnp.array([jnp.inf]),
           "c": jnp.array([1, 2], jnp.int32)}  # int leaves can't be nonfinite
    assert float(hl.nonfinite_leaf_count(bad)) == 2
    assert float(hl.global_norm({})) == 0.0


def test_wrapped_optimizer_captures_stats():
    """The optax capture chain records grad/param/update norms and the
    ratio WITHOUT changing the updates (pass-through)."""
    params = {"w": jnp.ones((4, 3)), "b": jnp.zeros((3,))}
    grads = {"w": jnp.full((4, 3), 2.0), "b": jnp.ones((3,))}
    plain = optax.sgd(0.1)
    wrapped = hl.wrap_optimizer(optax.sgd(0.1), hl.HealthConfig())
    u0, _ = plain.update(grads, plain.init(params), params)
    u1, st = wrapped.update(grads, wrapped.init(params), params)
    for a, b in zip(jax.tree.leaves(u0), jax.tree.leaves(u1)):
        np.testing.assert_array_equal(a, b)  # captures observe, not perturb
    stats = {k: float(v) for k, v in hl.from_opt_state(st).items()}
    gn = math.sqrt(4 * 4 * 3 + 3)
    pn = math.sqrt(12)
    assert stats["grad_norm"] == pytest.approx(gn, rel=1e-6)
    assert stats["param_norm"] == pytest.approx(pn, rel=1e-6)
    assert stats["update_norm"] == pytest.approx(0.1 * gn, rel=1e-6)
    assert stats["update_ratio"] == pytest.approx(0.1 * gn / pn, rel=1e-6)
    assert stats["nonfinite_count"] == 0


def test_injection_hook_poisons_exactly_one_step():
    params = {"w": jnp.ones((2,))}
    tx = hl.wrap_optimizer(optax.sgd(0.1),
                           hl.HealthConfig(inject_nan_at=2))
    st = tx.init(params)
    grads = {"w": jnp.full((2,), 3.0)}
    _, st = tx.update(grads, st, params)
    s1 = hl.from_opt_state(st)
    assert math.isfinite(float(s1["grad_norm"]))
    _, st = tx.update(grads, st, params)
    s2 = hl.from_opt_state(st)
    assert not math.isfinite(float(s2["grad_norm"]))  # the poisoned step
    assert float(s2["nonfinite_count"]) > 0


def test_from_opt_state_without_captures_is_loud():
    tx = optax.sgd(0.1)
    with pytest.raises(ValueError, match="enable_health"):
        hl.from_opt_state(tx.init({"w": jnp.ones((2,))}))


def test_detect_anomalies_policy():
    cfg = hl.HealthConfig()
    assert hl.detect_anomalies(
        {"loss": 1.0, "grad_norm": 2.0, "update_ratio": 0.1,
         "loss_spike": 1.1, "nonfinite_count": 0.0}, cfg) == []
    stats = [a["stat"] for a in hl.detect_anomalies(
        {"loss": float("nan"), "nonfinite_count": 3.0,
         "update_ratio": 2.0, "loss_spike": 99.0}, cfg)]
    assert stats == ["nonfinite_count", "loss", "update_ratio", "loss_spike"]
    # threshold checks only fire on finite values (NaN comparisons are
    # silently False); the non-finite check is what reports them
    assert [a["stat"] for a in hl.detect_anomalies(
        {"grad_norm": float("inf")}, cfg)] == ["grad_norm"]
    ceil = hl.HealthConfig(max_grad_norm=10.0)
    assert [a["stat"] for a in hl.detect_anomalies(
        {"grad_norm": 11.0}, ceil)] == ["grad_norm"]


# --------------------------------------------------- engine hook (pure jit)

def test_engine_step_metrics_carry_health_and_real_grad_norm():
    """The base hook merges the health stats into the step metrics, and
    grad_norm is the TRUE global gradient norm (cross-checked against a
    hand computation of the same loss)."""
    from distributed_tensorflow_tpu.engines.base import cross_entropy

    eng = JitEngine()
    eng.enable_health()
    ds = _tiny_ds()
    state = eng.init_state(jax.random.key(0), ds.x[:8])
    params0 = jax.device_get(state.params)
    xs, ys = eng.shard_batch(ds.x[:16], ds.y[:16])
    state, m = eng.step(state, xs, ys)
    assert set(hl.HEALTH_KEYS) <= set(m.keys())
    assert float(m["loss_spike"]) == 1.0  # first step scores 1 by definition

    def loss_fn(p):
        logits = eng.model.apply({"params": p}, jnp.asarray(ds.x[:16]))
        return cross_entropy(logits, jnp.asarray(ds.y[:16])).mean()

    grads = jax.grad(loss_fn)(params0)
    assert float(m["grad_norm"]) == pytest.approx(
        float(hl.global_norm(grads)), rel=1e-5)
    # SGD: ‖Δp‖ = lr·‖g‖ (JitEngine uses optax.sgd(0.1))
    assert float(m["update_norm"]) == pytest.approx(
        0.1 * float(m["grad_norm"]), rel=1e-5)
    state, m2 = eng.step(state, xs, ys)
    assert math.isfinite(float(m2["loss_spike"]))


def test_enable_health_after_step_build_is_rejected():
    eng = JitEngine()
    ds = _tiny_ds()
    state = eng.init_state(jax.random.key(0), ds.x[:8])
    xs, ys = eng.shard_batch(ds.x[:16], ds.y[:16])
    eng.step(state, xs, ys)
    with pytest.raises(RuntimeError, match="before"):
        eng.enable_health()


def test_enable_health_after_init_state_fails_actionably():
    """The replicated engines' init_state sets none of the fields the
    enable-time guard can see — a state initialized pre-enable must fail
    at the first step with the actionable message, not an opaque optax
    tree mismatch inside the jit."""
    eng = JitEngine()
    ds = _tiny_ds()
    state = eng.init_state(jax.random.key(0), ds.x[:8])  # pre-enable
    eng.enable_health()
    xs, ys = eng.shard_batch(ds.x[:16], ds.y[:16])
    with pytest.raises(ValueError, match="enable_health"):
        eng.step(state, xs, ys)
    with pytest.raises(ValueError, match="enable_health"):
        eng.many_step(state, [xs], [ys])


def _run_fit(k, health=True, inject=None, on_anomaly="warn", path=None,
             tracer=None, **fit_kw):
    eng = JitEngine()
    if health:
        eng.enable_health(hl.HealthConfig(inject_nan_at=inject))
    tr = Trainer(None, engine=eng, seed=0)
    ml = MetricsLogger(path, log_every=1)
    r = tr.fit(_tiny_ds(), epochs=2, batch_size=16, log_every=0,
               steps_per_call=k, metrics_logger=ml, max_steps=13,
               on_anomaly=on_anomaly, tracer=tracer, **fit_kw)
    ml.close()
    return r, ml.records, jax.device_get(tr.state.params)


def test_health_on_does_not_perturb_trajectory():
    """Health ON must observe, not perturb: identical per-step loss and
    bitwise-identical final params vs health OFF on the same seed (the
    capture transforms are pass-through; `--health off` trivially keeps
    the pre-health program — nothing is wrapped)."""
    r_on, recs_on, p_on = _run_fit(8, health=True)
    r_off, recs_off, p_off = _run_fit(8, health=False)
    assert [m["loss"] for m in recs_on] == [m["loss"] for m in recs_off]
    for a, b in zip(jax.tree.leaves(p_on), jax.tree.leaves(p_off)):
        np.testing.assert_array_equal(a, b)
    assert "health" in r_on and "health" not in r_off
    assert r_on["health"]["anomalies"] == 0
    assert r_on["health"]["first_anomaly_step"] is None
    assert r_on["health"]["max_update_ratio"] > 0


def test_health_stream_parity_k8_vs_k1_on_disk(tmp_path):
    """Acceptance: with health ON, the k=8 on-disk health stream equals
    k=1's — every per-step health stat, bitwise, same discipline as the
    PR 2 metrics parity."""
    r1, _, p1 = _run_fit(1, path=tmp_path / "k1.jsonl")
    r8, _, p8 = _run_fit(8, path=tmp_path / "k8.jsonl")
    assert r8["steps_per_call"] == 8  # health never downshifts
    load = lambda p: [json.loads(l)  # noqa: E731
                      for l in p.read_text().splitlines()]
    recs1, recs8 = load(tmp_path / "k1.jsonl"), load(tmp_path / "k8.jsonl")
    assert len(recs1) == len(recs8) == 13
    keys = ("step", "loss", "accuracy") + hl.HEALTH_KEYS
    traj = lambda recs: [tuple(m[kk] for kk in keys)  # noqa: E731
                         for m in recs]
    assert traj(recs1) == traj(recs8)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p8)):
        np.testing.assert_array_equal(a, b)


def test_anomaly_halt_catches_injection_at_its_step(tmp_path):
    """Acceptance: grads scaled by inf at step 5 → on_anomaly='halt'
    raises AT step 5 with a structured `anomaly` trace event naming the
    offending stat, and the step's metrics record reached the sink first."""
    trace = tmp_path / "t.jsonl"
    with Tracer(path=trace, run_id="r-halt") as tracer:
        with pytest.raises(AnomalyDetected, match="step 5"):
            _run_fit(8, inject=5, on_anomaly="halt",
                     path=tmp_path / "m.jsonl", tracer=tracer)
    events = [json.loads(l) for l in trace.read_text().splitlines()]
    anomalies = [e for e in events if e.get("name") == "anomaly"]
    assert anomalies and anomalies[0]["step"] == 5
    assert anomalies[0]["stat"] in hl.HEALTH_KEYS + ("loss",)
    assert anomalies[0]["policy"] == "halt"
    recs = [json.loads(l)
            for l in (tmp_path / "m.jsonl").read_text().splitlines()]
    assert recs[-1]["step"] == 5  # the diverging step's record is on disk
    assert not math.isfinite(recs[-1]["grad_norm"])


def test_old_nan_guard_catches_a_cadence_later():
    """The contrast the tentpole exists for: the same blow-up under the
    loss-only nan_guard (health off) is invisible until a logging cadence
    materializes the loss — here the END of the 13-step run, 8 steps after
    the fault; the health policy (previous test) halts at step 5."""
    class BlowsUpAtStep5(JitEngine):
        """Grads scale by inf once state.step reaches 4 (0-based), i.e.
        the 5th optimizer update — a health-off rendering of the
        inject_nan_at hook."""

        def _build_step(self):
            import optax as _optax

            tx, apply_fn = self.tx, self.model.apply

            def train_step(state, x, y):
                from distributed_tensorflow_tpu.engines.base import (
                    cross_entropy)

                def loss_fn(p):
                    logits = apply_fn({"params": p}, x)
                    loss = cross_entropy(logits, y).mean()
                    return loss, (logits.argmax(-1) == y).mean()

                (loss, acc), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(state.params)
                scale = jnp.where(state.step == 4, jnp.inf, 1.0)
                grads = jax.tree.map(lambda g: g * scale, grads)
                updates, opt_state = tx.update(grads, state.opt_state,
                                               state.params)
                params = _optax.apply_updates(state.params, updates)
                return state.replace(step=state.step + 1, params=params,
                                     opt_state=opt_state), \
                    {"loss": loss, "accuracy": acc}

            return jax.jit(train_step, donate_argnums=0)

    eng = BlowsUpAtStep5()
    tr = Trainer(None, engine=eng, seed=0)
    # log_every=0 and no metrics logger: the only nan_guard check left is
    # the final-metrics one — the divergence surfaces at step 13, not 5
    with pytest.raises(TrainingDiverged, match="step 13"):
        tr.fit(_tiny_ds(), epochs=2, batch_size=16, log_every=0,
               steps_per_call=8, max_steps=13)


def test_warn_keeps_divergence_fatal_under_nan_guard_default():
    """Adding --health must never silently downgrade a NaN'd run from
    abort to train-to-completion: under on_anomaly='warn' with the
    nan_guard default, a 'nonfinite' anomaly is still fatal — and now
    step-exact, where the legacy guard waited for a log cadence."""
    with pytest.raises(AnomalyDetected, match="step 5"):
        _run_fit(8, inject=5, on_anomaly="warn")  # nan_guard defaults True


def test_anomaly_warn_completes_and_reports(tmp_path):
    """Observe-only mode (warn + nan_guard off): the run completes and
    the health summary records every anomalous step."""
    trace = tmp_path / "t.jsonl"
    with Tracer(path=trace) as tracer:
        r, recs, _ = _run_fit(8, inject=5, on_anomaly="warn",
                              nan_guard=False, tracer=tracer)
    h = r["health"]
    assert r["steps"] == 13  # observe-only records, never stops
    assert h["first_anomaly_step"] == 5
    assert h["anomaly_steps"][0] == 5 and 13 in h["anomaly_steps"]
    assert h["anomalies"] >= len(h["anomaly_steps"])
    events = [json.loads(l) for l in trace.read_text().splitlines()]
    assert any(e.get("name") == "anomaly" and e.get("step") == 5
               for e in events)


def test_fit_rejects_unknown_anomaly_policy():
    eng = JitEngine()
    eng.enable_health()
    tr = Trainer(None, engine=eng, seed=0)
    with pytest.raises(ValueError, match="on_anomaly"):
        tr.fit(_tiny_ds(), epochs=1, batch_size=16, on_anomaly="explode")


def test_run_report_carries_health_section():
    r, _, _ = _run_fit(8)
    report = build_run_report(r)
    assert report["health"] == r["health"]
    assert build_run_report({"elapsed": 1.0, "steps": 1})["health"] is None


# ---------------------------------------------- shard_map engine smoke

@needs_shard_map
def test_sync_engine_health_smoke(mesh8):
    """The shared base hook covers the real engine layer: one SyncEngine
    step on the 8-device mesh carries finite health stats."""
    from distributed_tensorflow_tpu.data.loaders import load_dataset
    from distributed_tensorflow_tpu.engines import SyncEngine
    from distributed_tensorflow_tpu.models import create_model

    ds = load_dataset("mnist", split="train")
    eng = SyncEngine(create_model("mlp", num_classes=ds.num_classes),
                     mesh=mesh8)
    eng.enable_health()
    state = eng.init_state(jax.random.key(0), ds.x[:8])
    xs, ys = eng.shard_batch(ds.x[:64], ds.y[:64])
    state, m = eng.step(state, xs, ys)
    floats = {k: float(v) for k, v in m.items()}
    assert set(hl.HEALTH_KEYS) <= set(floats)
    assert floats["nonfinite_count"] == 0
    assert floats["grad_norm"] > 0 and floats["update_ratio"] > 0
    assert hl.detect_anomalies(floats, eng.health) == []


# ------------------------------------------------------- analyzer (offline)

def _instrumented_run(tmp_path):
    trace = tmp_path / "trace.jsonl"
    metrics = tmp_path / "metrics.jsonl"
    with Tracer(path=trace, run_id="r-an") as tracer:
        r, _, _ = _run_fit(8, path=metrics, tracer=tracer)
        report = build_run_report(r, tracer=tracer)
    return trace, metrics, r, report


def test_chrome_export_round_trip(tmp_path):
    """Acceptance: a real run's trace JSONL exports to Chrome-trace JSON
    that json.loads with one complete ('X') event per span record."""
    trace, _, _, _ = _instrumented_run(tmp_path)
    out = tmp_path / "chrome.json"
    assert analyze.main(["export", str(trace), "-o", str(out)]) == 0
    ct = json.load(open(out))
    assert "traceEvents" in ct and ct["traceEvents"]
    recs = analyze.read_jsonl(trace)
    n_spans = sum(1 for r in recs if r.get("event") == "span")
    xs = [e for e in ct["traceEvents"] if e.get("ph") == "X"]
    assert len(xs) == n_spans > 0
    for e in xs:
        assert {"name", "ts", "dur", "pid", "tid"} <= set(e)
    # instants + counters made it too, and the timeline is ts-ordered
    assert any(e["ph"] == "C" for e in ct["traceEvents"])
    ts = [e["ts"] for e in ct["traceEvents"] if e["ph"] != "M"]
    assert ts == sorted(ts)


def test_trace_summary_spans_and_stalls(tmp_path):
    trace, _, _, _ = _instrumented_run(tmp_path)
    summ = analyze.trace_summary(analyze.read_jsonl(trace))
    assert summ["spans"]["compile"]["count"] == 1
    assert summ["spans"]["materialize"]["count"] >= 1
    assert summ["wall_s"] > 0
    assert summ["stalls"]["anomaly_events"] == 0
    assert summ["stalls"]["gauges"] >= 1


def test_health_timeline_from_metrics(tmp_path):
    _, metrics, r, _ = _instrumented_run(tmp_path)
    ht = analyze.health_timeline(analyze.read_jsonl(metrics))
    assert ht["steps"] == 13
    assert ht["first_anomaly_step"] is None
    assert ht["max_update_ratio"] == pytest.approx(
        r["health"]["max_update_ratio"])
    # and with a poisoned run the first anomaly step is recoverable
    bad = tmp_path / "bad.jsonl"
    _run_fit(8, inject=5, on_anomaly="warn", nan_guard=False, path=bad)
    ht2 = analyze.health_timeline(analyze.read_jsonl(bad))
    assert ht2["first_anomaly_step"] == 5
    assert ht2["nonfinite_steps"] >= 1


def test_diff_exits_nonzero_iff_regression(tmp_path):
    """Acceptance: self-diff reports zero regressions (exit 0); a metric
    past the threshold exits nonzero; within-threshold drift does not."""
    _, _, _, report = _instrumented_run(tmp_path)
    a = tmp_path / "a.json"
    a.write_text(json.dumps(report))
    assert analyze.main(["diff", str(a), str(a)]) == 0
    worse = dict(report)
    worse["step_time_p50_s"] = (report["step_time_p50_s"] or 0.01) * 2
    b = tmp_path / "b.json"
    b.write_text(json.dumps(worse))
    assert analyze.main(["diff", str(a), str(b)]) == 1
    assert analyze.main(["diff", str(b), str(a)]) == 0  # improvement
    drift = dict(report)
    drift["step_time_p50_s"] = (report["step_time_p50_s"] or 0.01) * 1.05
    c = tmp_path / "c.json"
    c.write_text(json.dumps(drift))
    assert analyze.main(["diff", str(a), str(c), "--threshold", "0.1"]) == 0
    assert analyze.main(["diff", str(a), str(c), "--threshold", "0.01"]) == 1


def test_diff_bench_lines_and_higher_better(tmp_path):
    base = {"metric": "mnist", "value": 100.0, "step_time_p50": 0.01,
            "prefetch_starvation": 0}
    slow = {"metric": "mnist", "value": 70.0, "step_time_p50": 0.01,
            "prefetch_starvation": 0}
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(base))
    b.write_text(json.dumps(slow))
    res = analyze.diff_reports(analyze.load_report(a),
                               analyze.load_report(b))
    assert [r["metric"] for r in res["regressions"]] == ["value"]
    assert analyze.main(["diff", str(a), str(b)]) == 1


def test_diff_value_direction_and_metric_mismatch(tmp_path):
    """A time-valued bench metric's headline `value` is lower-is-better
    (a 2x attention-step-ms increase is a regression, not an
    improvement), and diffing two DIFFERENT bench metrics compares
    nothing and exits 2 — never a silent 'no regression'."""
    fast = {"metric": "attention_fwd_bwd_step_ms", "value": 10.0}
    slow = {"metric": "attention_fwd_bwd_step_ms", "value": 20.0}
    res = analyze.diff_reports(fast, slow)
    assert [r["metric"] for r in res["regressions"]] == ["value"]
    assert analyze.diff_reports(slow, fast)["regressions"] == []
    # rate-valued metrics keep higher-is-better
    res2 = analyze.diff_reports({"metric": "x_examples_per_sec",
                                 "unit": "examples/sec", "value": 100.0},
                                {"metric": "x_examples_per_sec",
                                 "unit": "examples/sec", "value": 50.0})
    assert [r["metric"] for r in res2["regressions"]] == ["value"]
    mism = analyze.diff_reports({"metric": "a", "value": 1.0},
                                {"metric": "b", "value": 99.0})
    assert mism["compared"] == 0 and mism["metric_mismatch"]
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps({"metric": "a", "value": 1.0}))
    b.write_text(json.dumps({"metric": "b", "value": 99.0}))
    assert analyze.main(["diff", str(a), str(b)]) == 2


def test_health_timeline_counts_threshold_crossings():
    """Threshold anomalies (finite values past the ceilings) must not
    vanish from the offline timeline — first_anomaly_step covers them,
    not only non-finites."""
    recs = [{"step": 1, "update_ratio": 0.1, "loss_spike": 1.0,
             "nonfinite_count": 0},
            {"step": 2, "update_ratio": 3.0, "loss_spike": 1.0,
             "nonfinite_count": 0},
            {"step": 3, "update_ratio": 0.1, "loss_spike": 50.0,
             "nonfinite_count": 0}]
    ht = analyze.health_timeline(recs)
    assert ht["first_anomaly_step"] == 2
    assert ht["threshold_steps"] == 2
    assert ht["nonfinite_steps"] == 0
    # custom ceilings mirror a customized HealthConfig
    loose = analyze.health_timeline(recs, max_update_ratio=5.0,
                                    loss_spike_factor=100.0)
    assert loose["first_anomaly_step"] is None


def test_chrome_export_keeps_event_value_arg():
    recs = [{"event": "event", "name": "anomaly", "t": 1.0, "step": 5,
             "stat": "update_ratio", "value": 12.3, "limit": 1.0,
             "process": 0, "pid": 42, "run": "r", "host": "h"},
            {"event": "gauge", "name": "prefetch_depth", "t": 2.0,
             "value": 2, "process": 0, "pid": 42}]
    ct = analyze.to_chrome_trace(recs)
    instant = next(e for e in ct["traceEvents"] if e["ph"] == "i")
    assert instant["args"]["value"] == 12.3  # the offending stat value
    counter = next(e for e in ct["traceEvents"] if e["ph"] == "C")
    assert counter["args"] == {"prefetch_depth": 2}


def test_chrome_export_of_anomalous_run_is_strict_json():
    """The traces most worth opening carry inf/NaN anomaly values —
    json.dumps would render bare Infinity tokens that Perfetto's strict
    JSON.parse rejects, so they must export as strings."""
    recs = [{"event": "event", "name": "anomaly", "t": 1.0, "step": 5,
             "stat": "grad_norm", "value": float("inf"), "limit": None,
             "process": 0, "pid": 1},
            {"event": "span", "name": "chunk_dispatch", "t": 2.0,
             "dur_s": 0.1, "bad": float("nan"), "process": 0, "pid": 1}]
    text = json.dumps(analyze.to_chrome_trace(recs))
    parsed = json.loads(text, parse_constant=lambda s: pytest.fail(
        f"non-strict JSON token {s!r} in Chrome export"))
    instant = next(e for e in parsed["traceEvents"] if e["ph"] == "i")
    assert instant["args"]["value"] == "inf"


def test_diff_nothing_compared_exits_2(tmp_path):
    """Diffing artifacts that share no known metric keys (e.g. two trace
    files by mistake) must not exit 0 — nothing was checked."""
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps({"event": "span", "name": "eval", "t": 1.0}))
    b.write_text(json.dumps({"event": "span", "name": "eval", "t": 2.0}))
    assert analyze.main(["diff", str(a), str(b)]) == 2


def test_health_timeline_ignores_trace_records():
    """Trace spans carry a 'step' attr (checkpoint/eval) but are not
    health steps — only metric records (no 'event' envelope) count."""
    recs = [{"step": 1, "loss": 1.0, "nonfinite_count": 0},
            {"event": "span", "name": "checkpoint", "t": 1.0, "step": 400},
            {"event": "gauge", "name": "prefetch_depth", "t": 2.0,
             "value": 2}]
    assert analyze.health_timeline(recs)["steps"] == 1


def test_diff_summary_with_nested_run_report(tmp_path):
    summary = {"engine": "sync", "examples_per_sec": 1000.0,
               "run_report": {"step_time_p50_s": 0.01,
                              "health": {"anomalies": 0}}}
    worse = {"engine": "sync", "examples_per_sec": 1000.0,
             "run_report": {"step_time_p50_s": 0.05,
                            "health": {"anomalies": 3}}}
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(summary))
    b.write_text(json.dumps(worse))
    res = analyze.diff_reports(analyze.load_report(a),
                               analyze.load_report(b))
    assert {r["metric"] for r in res["regressions"]} == {
        "step_time_p50_s", "health_anomalies"}


def test_load_report_takes_last_jsonl_object(tmp_path):
    p = tmp_path / "results.jsonl"
    p.write_text('{"event": "start"}\n{"value": 5.0, "metric": "m"}\n')
    assert analyze.load_report(p)["value"] == 5.0
    torn = tmp_path / "torn.jsonl"
    torn.write_text("not json at all\n")
    with pytest.raises(ValueError, match="no parsable"):
        analyze.load_report(torn)


def test_read_jsonl_rejects_torn_lines(tmp_path):
    p = tmp_path / "t.jsonl"
    p.write_text('{"a": 1}\n{"b": ')
    with pytest.raises(ValueError, match="unparsable"):
        analyze.read_jsonl(p)
