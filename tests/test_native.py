"""Native (C++) runtime components: build, wire framing, batch pipeline.

The wire tests check byte-compatibility BOTH directions against the pure
Python framing (which matches the reference's network.py:4-28 format); the
pipeline tests check batch-for-batch identity with the Python input path.
"""

import socket
import threading

import numpy as np
import pytest

from distributed_tensorflow_tpu import native
from distributed_tensorflow_tpu.data.pipeline import iter_batches
from distributed_tensorflow_tpu.utils import wire

pytestmark = pytest.mark.skipif(
    not native.is_available(), reason="native toolchain unavailable")


# ------------------------------------------------------------------ build
def test_build_is_cached():
    p1 = native.build()
    p2 = native.build()
    assert p1 == p2 and p1.exists()


# ------------------------------------------------------------------- wire
def _blocking_socketpair():
    a, b = socket.socketpair()
    a.settimeout(None)
    b.settimeout(None)
    return a, b


def test_native_frame_roundtrip():
    a, b = _blocking_socketpair()
    try:
        for payload in (b"", b"x", b"hello world" * 100, bytes(range(256)) * 64):
            wire.send_bytes(a, payload)
            assert wire.recv_bytes(b) == payload
    finally:
        a.close()
        b.close()


def test_native_interop_with_python_framing():
    """Native writer ↔ Python reader and vice versa (same bytes on the wire
    as the reference's 4-byte big-endian framing)."""
    lib = native.load()
    a, b = _blocking_socketpair()
    try:
        # native write → python read
        assert lib.dtw_send_frame(a.fileno(), b"ping", 4) == 0
        header = wire.recvall(b, 4)
        assert header == (4).to_bytes(4, "big")
        assert wire.recvall(b, 4) == b"ping"
        # python write → native read
        import ctypes

        b.sendall((3).to_bytes(4, "big") + b"abc")
        buf = ctypes.create_string_buffer(16)
        assert lib.dtw_recv_frame(a.fileno(), buf, 16) == 3
        assert buf.raw[:3] == b"abc"
    finally:
        a.close()
        b.close()


def test_native_recv_on_close_returns_none():
    a, b = _blocking_socketpair()
    a.close()
    try:
        assert wire.recv_bytes(b) is None
    finally:
        b.close()


def test_native_listen_connect_accept():
    lib = native.load()
    lfd = lib.dtw_listen(0)
    assert lfd >= 0
    port = lib.dtw_port(lfd)
    assert port > 0
    results = {}

    def server():
        cfd = lib.dtw_accept(lfd)
        import ctypes

        buf = ctypes.create_string_buffer(64)
        n = lib.dtw_recv_frame(cfd, buf, 64)
        results["msg"] = buf.raw[:n]
        lib.dtw_send_frame(cfd, b"ack", 3)
        lib.dtw_close(cfd)

    t = threading.Thread(target=server)
    t.start()
    fd = lib.dtw_connect(b"127.0.0.1", port)
    assert fd >= 0
    assert lib.dtw_send_frame(fd, b"syn", 3) == 0
    import ctypes

    buf = ctypes.create_string_buffer(8)
    assert lib.dtw_recv_frame(fd, buf, 8) == 3
    assert buf.raw[:3] == b"ack"
    t.join(timeout=5)
    lib.dtw_close(fd)
    lib.dtw_close(lfd)
    assert results["msg"] == b"syn"


# --------------------------------------------------------- race detection
def test_pipeline_under_thread_sanitizer():
    """TSAN over the producer/worker-pool/consumer concurrency (the race
    detection the reference lacks outright, SURVEY.md §5)."""
    import subprocess

    binary = native.build_race_test()
    if binary is None:
        pytest.skip("TSAN unavailable")
    proc = subprocess.run([str(binary)], capture_output=True, text=True,
                          timeout=120)
    assert "WARNING: ThreadSanitizer" not in proc.stderr, proc.stderr[:4000]
    assert proc.returncode == 0, (proc.returncode, proc.stderr[:2000])
    assert "tsan-driver-ok" in proc.stdout


# --------------------------------------------------------------- pipeline
def _ref_batches(x, y, bs, **kw):
    return list(iter_batches(x, y, bs, **kw))


def _native_batches(x, y, bs, **kw):
    from distributed_tensorflow_tpu.native.batcher import NativeBatcher

    nb = NativeBatcher(x, y, bs)
    try:
        return list(nb.epoch(**kw))
    finally:
        nb.close()


@pytest.mark.parametrize("n,bs", [(64, 16), (100, 32), (10, 32), (96, 32)])
def test_pipeline_matches_python(n, bs):
    rng = np.random.default_rng(7)
    x = rng.random((n, 5, 3), np.float32)
    y = rng.integers(0, 10, n).astype(np.int32)
    for shuffle in (True, False):
        for epoch in (0, 1, 3):
            ref = _ref_batches(x, y, bs, shuffle=shuffle, seed=11, epoch=epoch)
            got = _native_batches(x, y, bs, shuffle=shuffle, seed=11, epoch=epoch)
            assert len(ref) == len(got)
            for (rx, ry, rm), (gx, gy, gm) in zip(ref, got):
                np.testing.assert_array_equal(rx, gx)
                np.testing.assert_array_equal(ry, gy)
                np.testing.assert_array_equal(rm, gm)


def test_pipeline_drop_remainder():
    x = np.arange(100, dtype=np.float32).reshape(100, 1)
    y = np.arange(100, dtype=np.int32)
    got = _native_batches(x, y, 32, shuffle=False, drop_remainder=True)
    assert len(got) == 3
    assert all(m.all() for _, _, m in got)


def test_pipeline_epoch_restart_and_reuse():
    """Abandoning an epoch mid-way then restarting must not deadlock."""
    from distributed_tensorflow_tpu.native.batcher import NativeBatcher

    x = np.arange(256, dtype=np.float32).reshape(64, 4)
    y = np.arange(64, dtype=np.int32)
    nb = NativeBatcher(x, y, 8, prefetch_depth=2)
    it = nb.epoch(shuffle=True, seed=1, epoch=0)
    next(it)  # consume one batch, abandon the rest while producer is staged
    with pytest.raises(RuntimeError):
        nb.epoch()  # handle is busy while the first iterator is live
    it.close()  # releases the handle
    full = list(nb.epoch(shuffle=True, seed=1, epoch=1))
    ref = _ref_batches(x, y, 8, shuffle=True, seed=1, epoch=1)
    assert len(full) == len(ref)
    for (rx, ry, rm), (gx, gy, gm) in zip(ref, full):
        np.testing.assert_array_equal(rx, gx)
        np.testing.assert_array_equal(ry, gy)
    nb.close()


def test_dataset_concurrent_iterators_independent():
    """Two live Dataset.batches() iterators must not corrupt each other
    (each gets its own native pipeline when the cached one is busy)."""
    from distributed_tensorflow_tpu.data.loaders import Dataset

    x = np.arange(4 * 64, dtype=np.float32).reshape(64, 4)
    y = np.arange(64, dtype=np.int32)
    ds = Dataset(x=x, y=y, num_classes=10)
    it1 = ds.batches(8, shuffle=True, seed=5, epoch=0, native=True)
    it2 = ds.batches(8, shuffle=True, seed=5, epoch=1, native=True)
    got1, got2 = [], []
    for a, b in zip(it1, it2):  # interleave consumption
        got1.append(a)
        got2.append(b)
    ref1 = _ref_batches(x, y, 8, shuffle=True, seed=5, epoch=0)
    ref2 = _ref_batches(x, y, 8, shuffle=True, seed=5, epoch=1)
    for ref, got in ((ref1, got1), (ref2, got2)):
        assert len(ref) == len(got)
        for (rx, ry, rm), (gx, gy, gm) in zip(ref, got):
            np.testing.assert_array_equal(rx, gx)
            np.testing.assert_array_equal(ry, gy)


def test_dataset_batches_native_parity():
    """Dataset.batches native vs forced-Python paths agree."""
    from distributed_tensorflow_tpu.data.loaders import load_dataset

    ds = load_dataset("synthetic", split="test")
    a = list(ds.batches(33, shuffle=True, seed=3, epoch=2, native=True))
    b = list(ds.batches(33, shuffle=True, seed=3, epoch=2, native=False))
    assert len(a) == len(b)
    for (ax, ay, am), (bx, by, bm) in zip(a, b):
        np.testing.assert_array_equal(ax, bx)
        np.testing.assert_array_equal(ay, by)
        np.testing.assert_array_equal(am, bm)
