"""Observability layer: async JSONL sink, trace spans, run report.

Covers the ISSUE 2 contracts: crash-durable metric sinks (whole JSON
lines even after SIGKILL, schema_version on every record), the bounded
queue's drop counter, the span timeline's envelope (monotonic clock,
run/host/process ids), the end-of-run report's fields, and the harness
wiring that emits the report through the CLI with telemetry enabled at
``steps_per_call > 1``.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import jax
import numpy as np
import pytest

from distributed_tensorflow_tpu.observability import (
    NULL_TRACER, SCHEMA_VERSION, AsyncJsonlSink, Tracer, build_run_report)
from distributed_tensorflow_tpu.utils.metrics import MetricsLogger, StepTimer

# ------------------------------------------------------------ AsyncJsonlSink


def test_sink_writes_whole_schema_stamped_lines(tmp_path):
    path = tmp_path / "sink.jsonl"
    with AsyncJsonlSink(path) as sink:
        for i in range(50):
            assert sink.write({"step": i, "loss": 0.1 * i})
        sink.flush()
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert [rec["step"] for rec in lines] == list(range(50))  # order kept
    assert all(rec["schema_version"] == SCHEMA_VERSION for rec in lines)
    assert sink.stats() == {"written": 50, "dropped": 0}


def test_sink_bounded_queue_drops_and_counts(tmp_path):
    # start=False keeps the writer thread off so the queue fills
    # deterministically; close() then drains synchronously
    sink = AsyncJsonlSink(tmp_path / "s.jsonl", maxsize=4, start=False)
    results = [sink.write({"i": i}) for i in range(10)]
    assert results == [True] * 4 + [False] * 6
    assert sink.dropped == 6
    sink.close()
    lines = (tmp_path / "s.jsonl").read_text().splitlines()
    assert len(lines) == 4  # the accepted records survive, in order
    assert [json.loads(line)["i"] for line in lines] == [0, 1, 2, 3]
    assert sink.write({"i": 99}) is False  # closed sink drops, not crashes


def test_sink_close_is_idempotent(tmp_path):
    sink = AsyncJsonlSink(tmp_path / "s.jsonl")
    sink.write({"a": 1})
    sink.close()
    sink.close()
    assert json.loads((tmp_path / "s.jsonl").read_text())["a"] == 1


_KILLED_WRITER = """
import sys, time
from distributed_tensorflow_tpu.utils.metrics import MetricsLogger
ml = MetricsLogger(sys.argv[1], log_every=1)
step = 0
while True:  # parent SIGKILLs us mid-stream
    step += 1
    ml.log(step, loss=1.0 / step, accuracy=0.5)
    if step == 5:
        print("GOING", flush=True)  # parent waits for real records first
"""


def test_killed_run_leaves_only_whole_json_lines(tmp_path):
    """Satellite: crash durability — a SIGKILLed run's metrics file holds
    only complete JSON lines (each with schema_version), never a torn
    record."""
    path = tmp_path / "metrics.jsonl"
    proc = subprocess.Popen(
        [sys.executable, "-c", _KILLED_WRITER, str(path)],
        stdout=subprocess.PIPE, text=True,
        cwd=str(Path(__file__).resolve().parents[1]))
    try:
        assert proc.stdout.readline().strip() == "GOING"
        # let the writer thread put real bytes on disk mid-write-storm
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if path.exists() and path.stat().st_size > 2000:
                break
            time.sleep(0.02)
        os.kill(proc.pid, signal.SIGKILL)
    finally:
        proc.wait(timeout=10)
    data = path.read_text()
    assert len(data) > 0
    recs = [json.loads(line) for line in data.splitlines()]  # ALL parse
    assert len(recs) >= 5
    assert all(rec["schema_version"] == SCHEMA_VERSION for rec in recs)
    assert [rec["step"] for rec in recs] == list(range(1, len(recs) + 1))


# ------------------------------------------------------------------- tracer


def test_tracer_span_timeline_envelope(tmp_path):
    path = tmp_path / "trace.jsonl"
    with Tracer(path=path, run_id="r-test", process_index=3) as tracer:
        with tracer.span("compile", steps=8):
            time.sleep(0.01)
        with tracer.span("chunk_dispatch", steps=8):
            pass
        tracer.gauge("prefetch_depth", 2, starvation=0)
        tracer.counter("drops", 4)
        tracer.event("collective_profile", grad_allreduce_bytes=123)
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    assert all(rec["run"] == "r-test" and rec["process"] == 3
               and rec["host"] and rec["pid"] for rec in recs)
    spans = [rec for rec in recs if rec["event"] == "span"]
    assert [s["name"] for s in spans] == ["compile", "chunk_dispatch"]
    assert spans[0]["dur_s"] >= 0.01 and spans[0]["steps"] == 8
    # monotonic clock: the timeline orders within the run
    ts = [rec["t"] for rec in recs]
    assert ts == sorted(ts)
    gauge = next(rec for rec in recs if rec["event"] == "gauge")
    assert gauge["name"] == "prefetch_depth" and gauge["value"] == 2
    counter = next(rec for rec in recs if rec["event"] == "counter")
    assert counter["total"] == 4


def test_tracer_aggregates_without_file_sink():
    tracer = Tracer(path=None)
    for _ in range(3):
        with tracer.span("materialize"):
            pass
    summary = tracer.span_summary()
    assert summary["materialize"]["count"] == 3
    assert summary["materialize"]["total_s"] >= \
        summary["materialize"]["max_s"] > 0
    assert tracer.overhead_s >= 0
    tracer.close()


def test_null_tracer_is_inert():
    with NULL_TRACER.span("anything", x=1):
        pass
    NULL_TRACER.gauge("g", 1)
    NULL_TRACER.event("e")
    NULL_TRACER.counter("c")
    assert NULL_TRACER.span_summary() == {}
    assert not NULL_TRACER.enabled


# round 20 fast-lane repair: xprof-window e2e rides the slow lane
@pytest.mark.slow
def test_profile_wraps_xprof_window_in_span(tmp_path):
    from distributed_tensorflow_tpu.utils.metrics import profile

    tracer = Tracer(path=None)
    try:
        with profile(tmp_path / "xprof", tracer=tracer):
            jax.block_until_ready(jax.numpy.ones((4,)) * 2)
    except Exception:
        pytest.skip("jax profiler unavailable on this backend")
    assert tracer.span_summary()["xprof"]["count"] == 1


# --------------------------------------------------------------- run report


def _fit_result():
    st = StepTimer()
    st.compile_steps = 8
    st.times = [0.5] * 8 + [0.01] * 24
    return {
        "elapsed": 4.3, "steps": 32, "steps_per_call": 8,
        "chunk_sizes": [8], "prefetch_depth": 2,
        "prefetch_starvation": 1, "prefetch_fill_wait_s": 0.2,
        "step_time": st.summary(),
    }


def test_run_report_fields(tmp_path):
    from distributed_tensorflow_tpu.utils.failure import Watchdog

    ml = MetricsLogger(tmp_path / "m.jsonl", log_every=1)
    for i in range(1, 33):
        ml.log(i, loss=1.0 / i)
    ml.close()
    tracer = Tracer(path=None)
    with tracer.span("chunk_dispatch", steps=8):
        pass
    wd = Watchdog(timeout=1.0, poll_interval=0.01)
    wd.rescale(8)
    wd.beat()
    report = build_run_report(_fit_result(), watchdog=wd,
                              metrics_logger=ml, tracer=tracer)
    wd.close()
    assert report["schema_version"] == SCHEMA_VERSION
    # steady-state percentiles split from the compile-smeared first chunk
    assert report["compile_s"] == pytest.approx(4.0)
    assert report["step_time_p50_s"] == pytest.approx(0.01)
    assert report["step_time_p95_s"] == pytest.approx(0.01)
    assert report["chunk_sizes"] == [8]
    assert report["watchdog"] == {"beats": 1, "stall_episodes": 0,
                                  "timeout_s": 8.0}
    assert report["prefetch"] == {"depth": 2, "starvation": 1,
                                  "fill_wait_s": 0.2}
    assert report["metrics_sink"]["records"] == 32
    assert report["metrics_sink"]["dropped"] == 0
    assert report["spans"]["chunk_dispatch"]["count"] == 1
    # the telemetry budget is measured and self-reported
    assert report["telemetry_overhead_s"] >= 0
    assert 0 <= report["telemetry_overhead_frac"] < 0.05


def test_run_report_none_for_absent_subsystems():
    report = build_run_report({"elapsed": 0.0, "steps": 0})
    assert report["watchdog"] is None
    assert report["metrics_sink"] is None
    assert report["prefetch"] is None
    assert report["spans"] is None
    assert report["trace"] is None
    assert report["health"] is None
    assert report["telemetry_overhead_frac"] is None


def test_run_report_zero_elapsed_is_not_none():
    """Satellite: a measured 0.0-elapsed run is a real observation — the
    old `elapsed or None` collapsed it into 'never reported'."""
    report = build_run_report({"elapsed": 0.0, "steps": 0})
    assert report["elapsed_s"] == 0.0
    assert build_run_report({"steps": 0})["elapsed_s"] is None


def test_run_report_enabled_idle_tracer_is_not_none(tmp_path):
    """Satellite: an ENABLED tracer always reports a trace dict —
    file-backed-but-idle shows integer zeros-or-counts, aggregate-only
    shows None written/dropped; only a DISABLED tracer reports None."""
    agg = Tracer(path=None)
    report = build_run_report(_fit_result(), tracer=agg)
    assert report["trace"] == {"written": None, "dropped": None}
    agg.close()
    with Tracer(path=tmp_path / "t.jsonl") as filed:
        filed._sink.flush()
        report = build_run_report(_fit_result(), tracer=filed)
    assert isinstance(report["trace"]["written"], int)
    assert report["trace"]["dropped"] == 0


def test_run_report_single_chunk_run_has_no_steady_percentiles():
    """Satellite: a run that never left its compile-smeared first chunk
    has NO steady state — percentiles report None, compile_s the whole
    prefix — rather than smearing compile into 'steady' numbers."""
    st = StepTimer()
    st.compile_steps = 8
    st.times = [0.5] * 8  # one chunk, all compile-smeared
    report = build_run_report(
        {"elapsed": 4.0, "steps": 8, "step_time": st.summary()})
    assert report["compile_s"] == pytest.approx(4.0)
    assert report["step_time_p50_s"] is None
    assert report["step_time_p95_s"] is None
    assert report["step_time_mean_s"] is None


def test_run_report_without_step_time():
    report = build_run_report({"elapsed": 1.0, "steps": 0})
    assert report["compile_s"] is None
    assert report["step_time_p50_s"] is None


# --------------------------------------------------- harness / CLI end-to-end


def test_cli_run_report_with_telemetry_at_k8(tmp_path):
    """End-to-end through the harness: metrics + trace enabled, explicit
    steps_per_call=8 — the run keeps its chunking, the summary carries the
    run report, and both JSONL artifacts land on disk.

    Subprocess (like the other CLI tests): the harness initializes a jax
    backend, which must not leak into this process's fake 8-CPU mesh."""
    repo = Path(__file__).resolve().parents[1]
    metrics = tmp_path / "metrics.jsonl"
    trace = tmp_path / "trace.jsonl"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "distributed_tensorflow_tpu.cli",
         "--dataset", "synthetic", "--model", "mlp", "-n", "1",
         "-b", "32", "--log-every", "4", "--steps-per-call", "8",
         "--watchdog-timeout", "30", "--health", "on",
         "--metrics-path", str(metrics), "--trace", str(trace)],
        capture_output=True, text=True, timeout=300, env=env, cwd=str(repo))
    if proc.returncode != 0 and "shard_map" in (proc.stderr or ""):
        pytest.skip("engine layer needs jax.shard_map")
    assert proc.returncode == 0, proc.stderr[-2000:]
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["steps_per_call"] == 8  # telemetry did not downshift
    report = summary["run_report"]
    assert report["steps"] == summary["steps"]
    assert report["metrics_sink"]["dropped"] == 0
    assert report["watchdog"]["beats"] >= 1
    assert report["watchdog"]["timeout_s"] == pytest.approx(240.0)
    assert report["telemetry_overhead_s"] >= 0
    # --health on: the report carries the health section and the metric
    # records carry the on-device health trajectory (ISSUE 4)
    assert report["health"]["anomalies"] == 0
    assert report["health"]["max_update_ratio"] > 0
    # both artifacts are whole-line JSONL with the schema stamp
    recs = [json.loads(line) for line in metrics.read_text().splitlines()]
    assert recs and all(r["schema_version"] == SCHEMA_VERSION for r in recs)
    assert all("grad_norm" in r and "update_ratio" in r for r in recs)
    spans = [json.loads(line) for line in trace.read_text().splitlines()]
    assert any(s.get("name") == "compile" for s in spans)
    assert any(s.get("name") == "eval" for s in spans)
    # the run_report event also reached the sink-readable timeline
    assert summary["run_report"]["spans"]


def test_overhead_bounded_jit_engine():
    """Telemetry-on vs telemetry-off through the pure-jit engine: the
    measured overhead the report carries must be a small fraction of the
    run, and the two configurations must produce identical trajectories
    (telemetry must observe, not perturb)."""
    sys.path.insert(0, os.path.dirname(__file__))
    from test_steady_state import JitEngine, _tiny_ds

    from distributed_tensorflow_tpu.engines.allreduce import Trainer

    def run(telemetry, tmpdir=None):
        eng = JitEngine()
        tr = Trainer(None, engine=eng, seed=0)
        kw = {}
        ml = tracer = None
        if telemetry:
            ml = MetricsLogger(None, log_every=1)
            tracer = Tracer(path=None)
            kw = dict(metrics_logger=ml, tracer=tracer)
        r = tr.fit(_tiny_ds(), epochs=2, batch_size=16, log_every=0,
                   steps_per_call=8, max_steps=13, **kw)
        report = build_run_report(r, metrics_logger=ml, tracer=tracer)
        return r, report, jax.device_get(tr.state.params)

    r_off, rep_off, p_off = run(False)
    r_on, rep_on, p_on = run(True)
    for a, b in zip(jax.tree.leaves(p_off), jax.tree.leaves(p_on)):
        np.testing.assert_array_equal(a, b)  # observed ≠ perturbed
    assert rep_on["telemetry_overhead_s"] < max(0.05 * r_on["elapsed"], 0.05)
    assert rep_off["telemetry_overhead_s"] == 0.0
