"""CLI integration tests: the full reference-compatible flag surface driving
real training on the fake mesh (the analogue of the reference's only
"test" — an end-to-end run, SURVEY.md §4)."""

import json

import pytest

from distributed_tensorflow_tpu.cli import build_parser, main, select_engine, str2bool


def test_str2bool_parity():
    # reference initializer.py:59-67
    for v in ("yes", "true", "t", "y", "1"):
        assert str2bool(v) is True
    for v in ("no", "false", "f", "n", "0"):
        assert str2bool(v) is False
    with pytest.raises(Exception):
        str2bool("maybe")


@pytest.mark.parametrize("argv,engine", [
    (["-m", "c", "-cs", "sync"], "sync"),
    (["-m", "centralized", "-cs", "async"], "async"),
    (["-m", "d", "-ds", "keras"], "allreduce"),
    (["-m", "d", "-ds", "graph"], "gossip"),
    (["-m", "decentralized", "-ds", "custom"], "gossip"),
    (["-m", "tpu_pod"], "sync"),
    (["-m", "t"], "sync"),
])
def test_mode_dispatch(argv, engine):
    args = build_parser().parse_args(argv)
    assert select_engine(args) == engine


def test_reference_flag_surface_accepted():
    # every reference flag parses (reference initializer.py:72-114)
    args = build_parser().parse_args(
        ["-m", "c", "-cs", "sync", "-ds", "keras", "-n", "4", "-b", "32",
         "-ti", "0", "-ca", "y"])
    assert args.number_nodes == 4 and args.batch_size == 32
    assert args.cpu_affinity is True


@pytest.mark.parametrize("argv", [
    ["-m", "tpu_pod", "-n", "8", "-b", "8"],
    ["-m", "c", "-cs", "async", "-n", "8", "-b", "8", "--sync-every", "4"],
    ["-m", "d", "-ds", "custom", "-n", "8", "-b", "8", "-d", "2"],
])
@pytest.mark.slow
def test_cli_end_to_end(tmp_path, capsys, argv):
    out = tmp_path / "events.jsonl"
    summary = main(argv + ["--dataset", "synthetic", "--model", "mlp",
                           "--result-path", str(out), "--log-every", "0",
                           "-e", "1"])
    assert summary["n_devices"] == 8
    assert summary["steps"] > 0
    assert 0.0 <= summary["test_accuracy"] <= 1.0
    # stdout carries the one-line JSON summary
    printed = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert printed["steps"] == summary["steps"]
    # JSONL sink got the reference event triple + summary
    events = [json.loads(l)["event"] for l in out.read_text().splitlines()]
    assert events[:2] == ["start", "done"]
    assert "results" in events and "summary" in events


def test_cli_supervisor_channel():
    """--supervisor wires a CLI run to an external reference-style harness:
    the listener must observe exactly the reference event triple
    ['start', ['done', elapsed], ['results', accuracy]]
    (reference server.py:121-124, 182-187; VERDICT r1 missing #1)."""
    from distributed_tensorflow_tpu.utils.supervisor import SupervisorListener

    listener = SupervisorListener()
    summary = main(["-m", "tpu_pod", "-n", "8", "-b", "8",
                    "--dataset", "synthetic", "--model", "mlp",
                    "--log-every", "0", "-e", "1",
                    "--supervisor", f"127.0.0.1:{listener.port}"])
    listener.close()  # joins the serve thread (sink closed inside main)
    assert listener.messages[0] == "start"
    done = listener.messages[1]
    assert done[0] == "done" and done[1] == pytest.approx(
        summary["elapsed_s"], rel=1e-6)
    assert listener.messages[2] == ["results", summary["test_accuracy"]]


def test_tt_and_sa_must_come_together():
    """'-tt worker' without '-sa' must error, not silently run single-process
    (unlike the reference's role dispatch on task_type alone)."""
    with pytest.raises(SystemExit):
        main(["-tt", "worker"])
    with pytest.raises(SystemExit):
        main(["-sa", "127.0.0.1:9999"])


def test_dtype_handling_for_plugin_and_registered_models():
    import flax.linen as nn

    from distributed_tensorflow_tpu import models as modellib
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, _resolve_model)

    class NoDtype(nn.Module):
        num_classes: int = 10

        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(self.num_classes)(x.reshape((x.shape[0], -1)))

    @modellib.register("nodtype_test_mlp")
    def _factory(num_classes=10, **kw):
        return NoDtype(num_classes=num_classes, **kw)

    # registered model lacking a dtype field works at the f32 default ...
    m = _resolve_model(ExperimentConfig(model="nodtype_test_mlp"), 10)
    assert isinstance(m, NoDtype)
    # ... and fails loudly (not TypeError) when bf16 is requested
    with pytest.raises(ValueError, match="dtype"):
        _resolve_model(
            ExperimentConfig(model="nodtype_test_mlp", dtype="bf16"), 10)
    # plug-in model_fn owns its dtype: --dtype warns instead of silently
    # doing nothing
    with pytest.warns(UserWarning, match="dtype"):
        m = _resolve_model(
            ExperimentConfig(model_fn=lambda: NoDtype(), dtype="bf16"), 10)
    assert isinstance(m, NoDtype)


def test_steps_to_accuracy_step_granularity():
    from distributed_tensorflow_tpu.utils.harness import ExperimentConfig, steps_to_accuracy

    cfg = ExperimentConfig(engine="sync", model="mlp", dataset="synthetic",
                           n_devices=8, batch_size=16, learning_rate=5e-3)
    r = steps_to_accuracy(cfg, target=0.9, max_steps=300, eval_every=8)
    assert r["reached"], r
    assert r["steps"] % 8 == 0  # eval cadence honored
    assert r["steps"] < 300
    # resolution is MEASURED (gap between the crossing eval and the one
    # before), labeled synthetic, and routed through the one Trainer loop
    assert r["step_resolution"] <= 8
    assert r["synthetic"] is True


def test_steps_to_accuracy_max_steps_final_eval():
    """Hitting max_steps must still report a real (final-step) accuracy,
    never a stale or never-computed one (review r3 finding)."""
    from distributed_tensorflow_tpu.utils.harness import ExperimentConfig, steps_to_accuracy

    cfg = ExperimentConfig(engine="sync", model="mlp", dataset="synthetic",
                           n_devices=8, batch_size=16)
    r = steps_to_accuracy(cfg, target=1.01, max_steps=7, eval_every=50)
    assert not r["reached"]
    assert r["steps"] == 7
    assert r["accuracy"] > 0.0  # the cap-step eval ran


def test_cli_user_plugin_model_and_dataset_fn():
    """The reference's 'edit model_fn/dataset_fn in initializer.py' contract
    (reference README.md:12): plug-ins override --model/--dataset."""
    from distributed_tensorflow_tpu.data import make_dataset_fn
    from distributed_tensorflow_tpu.models.mlp import MLP

    built = {}

    def model_fn():
        built["model"] = True
        return MLP(num_classes=10, hidden=16)

    summary = main(
        ["-m", "tpu_pod", "-n", "8", "-b", "8", "--log-every", "0",
         "--model", "ignored_because_plugin", "--dataset", "synthetic"],
        model_fn=model_fn, dataset_fn=make_dataset_fn("synthetic"))
    assert built.get("model")
    assert summary["steps"] > 0
    assert summary["test_accuracy"] > 0.5


@pytest.mark.slow
def test_model_arg_passthrough():
    """--model-arg KEY=VALUE reaches the model constructor (a 3-layer
    hidden-48 GPT has a distinct param tree)."""
    import math

    from distributed_tensorflow_tpu.cli import main, parse_model_args
    from distributed_tensorflow_tpu.data.loaders import load_lm_dataset

    assert parse_model_args(["hidden=48", "tie_embeddings=false",
                             "positional=rope"]) == {
        "hidden": 48, "tie_embeddings": False, "positional": "rope"}
    import pytest as _pytest
    with _pytest.raises(Exception, match="KEY=VALUE"):
        parse_model_args(["hidden"])

    def lm_fn(batch_size, type="train", **kw):
        return load_lm_dataset(seq_len=16, vocab_size=64, n_train=64,
                               n_test=32, split=type)

    summary = main(["-m", "t", "-n", "8", "-b", "4", "--model", "gpt",
                    "--dataset", "lm_synth", "--model-arg", "hidden=48",
                    "--model-arg", "layers=1", "--log-every", "0"],
                   dataset_fn=lm_fn)
    assert math.isfinite(summary["test_loss"])


def test_model_arg_typo_fails_loudly():
    """A typo'd --model-arg key must error, not silently train the
    default-size model (the dtype-probe fallback once dropped all kwargs)."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    with pytest.raises(TypeError):
        run(ExperimentConfig(engine="sync", model="gpt", dataset="lm_synth",
                             n_devices=8, model_args={"hiden": 256}))


def test_model_arg_rejected_under_pipeline():
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    with pytest.raises(ValueError, match="pipeline-hidden"):
        run(ExperimentConfig(engine="sync", model="gpt", dataset="lm_synth",
                             n_devices=8, pipeline_parallel=2,
                             model_args={"hidden": 64}))


def test_model_arg_reserved_key_rejected_cleanly():
    """--model-arg keys owned by dedicated flags (num_experts under EP,
    dtype anywhere) must raise the clean reserved-key ValueError, not a raw
    'got multiple values' TypeError (ADVICE r3)."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    with pytest.raises(ValueError, match="reserved"):
        run(ExperimentConfig(engine="sync", model="moe",
                             dataset="synthetic", n_devices=8,
                             expert_parallel=4, num_experts=4,
                             model_args={"num_experts": 8}))
    with pytest.raises(ValueError, match="reserved"):
        run(ExperimentConfig(engine="sync", model="gpt", dataset="lm_synth",
                             n_devices=8, model_args={"dtype": "float16"}))
    with pytest.raises(ValueError, match="reserved"):
        run(ExperimentConfig(engine="sync", model="gpt",
                             dataset="lm_synth", n_devices=8,
                             seq_parallel=2,
                             model_args={"attention_impl": "ulysses"}))


@pytest.mark.slow
def test_package_import_honors_platform_env():
    """The package __init__ re-asserts JAX_PLATFORMS/JAX_PLATFORM_NAME over
    config state a preloaded plugin may have forced (the sitecustomize
    hang: importing jax alone leaves the forced platform in place; every
    framework entry path imports this package before touching devices).
    Precedence matches JAX's own: non-empty JAX_PLATFORMS wins, the
    deprecated JAX_PLATFORM_NAME is the fallback."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    script = (
        "import jax\n"
        # simulate a sitecustomize-style forced platform before import
        "jax.config.update('jax_platforms', 'bogus_accel,cpu')\n"
        "import distributed_tensorflow_tpu\n"
        "print('PLATFORMS=' + str(jax.config.jax_platforms))\n"
    )
    for env_extra, want in (
            ({"JAX_PLATFORMS": "cpu", "JAX_PLATFORM_NAME": "tpu"}, "cpu"),
            ({"JAX_PLATFORMS": "", "JAX_PLATFORM_NAME": "cpu"}, "cpu"),
            # jax lowercases JAX_PLATFORM_NAME itself; the hook must too
            ({"JAX_PLATFORMS": "", "JAX_PLATFORM_NAME": "CPU"}, "cpu"),
            # neither set: the forced value must be left alone (no-op)
            ({"JAX_PLATFORMS": "", "JAX_PLATFORM_NAME": ""},
             "bogus_accel,cpu"),
    ):
        env = {k: v for k, v in os.environ.items()
               if k not in ("JAX_PLATFORMS", "JAX_PLATFORM_NAME")}
        env.update({k: v for k, v in env_extra.items() if v})
        env["PYTHONPATH"] = str(repo) + os.pathsep + env.get("PYTHONPATH", "")
        out = subprocess.run([sys.executable, "-c", script], env=env,
                             capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr[-2000:]
        assert f"PLATFORMS={want}" in out.stdout, (env_extra, out.stdout)
