"""CLI integration tests: the full reference-compatible flag surface driving
real training on the fake mesh (the analogue of the reference's only
"test" — an end-to-end run, SURVEY.md §4)."""

import json

import pytest

from distributed_tensorflow_tpu.cli import build_parser, main, select_engine, str2bool


def test_str2bool_parity():
    # reference initializer.py:59-67
    for v in ("yes", "true", "t", "y", "1"):
        assert str2bool(v) is True
    for v in ("no", "false", "f", "n", "0"):
        assert str2bool(v) is False
    with pytest.raises(Exception):
        str2bool("maybe")


@pytest.mark.parametrize("argv,engine", [
    (["-m", "c", "-cs", "sync"], "sync"),
    (["-m", "centralized", "-cs", "async"], "async"),
    (["-m", "d", "-ds", "keras"], "allreduce"),
    (["-m", "d", "-ds", "graph"], "gossip"),
    (["-m", "decentralized", "-ds", "custom"], "gossip"),
    (["-m", "tpu_pod"], "sync"),
    (["-m", "t"], "sync"),
])
def test_mode_dispatch(argv, engine):
    args = build_parser().parse_args(argv)
    assert select_engine(args) == engine


def test_reference_flag_surface_accepted():
    # every reference flag parses (reference initializer.py:72-114)
    args = build_parser().parse_args(
        ["-m", "c", "-cs", "sync", "-ds", "keras", "-n", "4", "-b", "32",
         "-ti", "0", "-ca", "y"])
    assert args.number_nodes == 4 and args.batch_size == 32
    assert args.cpu_affinity is True


@pytest.mark.parametrize("argv", [
    ["-m", "tpu_pod", "-n", "8", "-b", "8"],
    ["-m", "c", "-cs", "async", "-n", "8", "-b", "8", "--sync-every", "4"],
    ["-m", "d", "-ds", "custom", "-n", "8", "-b", "8", "-d", "2"],
])
def test_cli_end_to_end(tmp_path, capsys, argv):
    out = tmp_path / "events.jsonl"
    summary = main(argv + ["--dataset", "synthetic", "--model", "mlp",
                           "--result-path", str(out), "--log-every", "0",
                           "-e", "1"])
    assert summary["n_devices"] == 8
    assert summary["steps"] > 0
    assert 0.0 <= summary["test_accuracy"] <= 1.0
    # stdout carries the one-line JSON summary
    printed = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert printed["steps"] == summary["steps"]
    # JSONL sink got the reference event triple + summary
    events = [json.loads(l)["event"] for l in out.read_text().splitlines()]
    assert events[:2] == ["start", "done"]
    assert "results" in events and "summary" in events


def test_steps_to_accuracy_step_granularity():
    from distributed_tensorflow_tpu.utils.harness import ExperimentConfig, steps_to_accuracy

    cfg = ExperimentConfig(engine="sync", model="mlp", dataset="synthetic",
                           n_devices=8, batch_size=16, learning_rate=5e-3)
    r = steps_to_accuracy(cfg, target=0.9, max_steps=300, eval_every=8)
    assert r["reached"], r
    assert r["steps"] % 8 == 0  # eval cadence honored
    assert r["steps"] < 300


def test_cli_user_plugin_model_and_dataset_fn():
    """The reference's 'edit model_fn/dataset_fn in initializer.py' contract
    (reference README.md:12): plug-ins override --model/--dataset."""
    from distributed_tensorflow_tpu.data import make_dataset_fn
    from distributed_tensorflow_tpu.models.mlp import MLP

    built = {}

    def model_fn():
        built["model"] = True
        return MLP(num_classes=10, hidden=16)

    summary = main(
        ["-m", "tpu_pod", "-n", "8", "-b", "8", "--log-every", "0",
         "--model", "ignored_because_plugin", "--dataset", "synthetic"],
        model_fn=model_fn, dataset_fn=make_dataset_fn("synthetic"))
    assert built.get("model")
    assert summary["steps"] > 0
    assert summary["test_accuracy"] > 0.5
