"""Async checkpointing (ISSUE 5): checkpoint + eval I/O off the training
critical path.

Contracts covered, per the issue's checklist:

* async-vs-sync bitwise equality — a state saved under both disciplines
  restores tree-equal (params / opt_state / step / rng);
* atomicity under an injected writer crash — no visible ``step_N``
  directory is ever half-written (the crash leaves only ``tmp_step_N``,
  swept on the next manager start), and the error re-raises on the
  training thread at the next save / wait / close;
* in-flight-save backpressure — a second save WAITS on the previous
  write (bounding host memory to one extra TrainState), never drops;
* the acceptance bound — with a deliberately slowed writer, the training
  thread's ``checkpoint_wait_s`` under async mode is < 25% of the same
  run's synchronous save time, while the restored states stay tree-equal;
* failure-path cleanup — a fit that raises mid-run leaves no background
  writer in flight and no half-buffered JSONL records.

Everything here runs on any jax (the Trainer paths go through the
pure-jit ``JitEngine``; harness runs use the GSPMD fsdp engine — neither
needs ``jax.shard_map``).
"""

import dataclasses
import json
import time

import jax
import numpy as np
import pytest

from test_steady_state import JitEngine, _tiny_ds  # noqa: E402

from distributed_tensorflow_tpu.engines.allreduce import Trainer
from distributed_tensorflow_tpu.utils.checkpoint import (
    AsyncCheckpointError, AsyncCheckpointManager, CheckpointManager)


def _as_np(v):
    if hasattr(v, "dtype") and jax.dtypes.issubdtype(
            v.dtype, jax.dtypes.prng_key):
        v = jax.random.key_data(v)
    return np.asarray(jax.device_get(v))


def assert_states_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(_as_np(x), _as_np(y))


def _trained_state(n_steps=2, seed=0):
    eng = JitEngine()
    ds = _tiny_ds(128)
    state = eng.init_state(jax.random.key(seed), ds.x[:8])
    xs, ys = eng.shard_batch(ds.x[:32], ds.y[:32])
    for _ in range(n_steps):
        state, _ = eng.step(state, xs, ys)
    jax.block_until_ready(state)
    return eng, ds, state


def _template(seed=0):
    eng = JitEngine()
    ds = _tiny_ds(128)
    return eng.init_state(jax.random.key(seed), ds.x[:8])


class SlowWriter(AsyncCheckpointManager):
    """Writer-delay test shim: every background write sleeps ``delay``
    first; write number ``crash_at`` (1-based) instead leaves a partial
    ``tmp_step_N`` behind and raises — a fault-injected mid-write crash.
    ``fake=True`` replaces the real Orbax write with a marker directory:
    the write cost becomes EXACTLY ``delay`` (the real write's duration
    jitters with GIL contention from the training thread), which is what
    the timing-ratio acceptance test needs to be deterministic."""

    def __init__(self, *args, delay=0.0, crash_at=None, fake=False, **kw):
        self.delay = delay
        self.crash_at = crash_at
        self.fake = fake
        self.writes = 0
        super().__init__(*args, **kw)

    def _write(self, step, host_state, extra=None):
        self.writes += 1
        time.sleep(self.delay)
        if self.crash_at is not None and self.writes == self.crash_at:
            tmp = self.directory / f"tmp_step_{step}"
            tmp.mkdir(exist_ok=True)
            (tmp / "partial.bin").write_text("torn")
            raise RuntimeError("injected writer crash")
        if self.fake:
            (self.directory / f"step_{step}").mkdir(exist_ok=True)
            return
        super()._write(step, host_state, extra)


class SlowSyncWriter(CheckpointManager):
    """The synchronous counterpart of :class:`SlowWriter` — same write
    delay, paid on the training thread (the acceptance comparison's
    baseline)."""

    def __init__(self, *args, delay=0.0, fake=False, **kw):
        self.delay = delay
        self.fake = fake
        super().__init__(*args, **kw)

    def _write(self, step, host_state, extra=None):
        time.sleep(self.delay)
        if self.fake:
            (self.directory / f"step_{step}").mkdir(exist_ok=True)
            return
        super()._write(step, host_state, extra)


class _SlowBatchDataset:
    """Wraps a Dataset so every produced batch costs ``sleep_s`` of host
    time — simulated between-checkpoint compute for the writer to overlap."""

    def __init__(self, ds, sleep_s):
        self._ds = ds
        self.sleep_s = sleep_s

    def __getattr__(self, name):
        return getattr(self._ds, name)

    def __len__(self):
        return len(self._ds)

    def batches(self, *args, **kw):
        for b in self._ds.batches(*args, **kw):
            time.sleep(self.sleep_s)
            yield b


# ------------------------------------------------------- manager semantics

def test_async_sync_checkpoints_bitwise_equal(tmp_path):
    """The same state saved under both disciplines restores tree-equal —
    the async snapshot/transfer/write chain loses nothing."""
    eng, ds, state = _trained_state()
    sync_mgr = CheckpointManager(tmp_path / "sync")
    async_mgr = AsyncCheckpointManager(tmp_path / "async")
    sync_mgr.save(state)
    async_mgr.save(state)
    async_mgr.wait()
    assert sync_mgr.steps() == async_mgr.steps() == [2]

    a = async_mgr.restore(_template())
    s = sync_mgr.restore(_template())
    assert_states_equal(a, s)
    assert_states_equal(a, state)
    async_mgr.close()


def test_async_save_survives_donated_buffers(tmp_path):
    """The snapshot decouples the save from the live buffers: training
    steps dispatched IMMEDIATELY after save (donating the state the
    writer is still reading) must not corrupt the checkpoint."""
    eng, ds, state = _trained_state()
    expect = jax.device_get(jax.tree.map(lambda x: x, state.params))
    mgr = SlowWriter(tmp_path / "c", delay=0.2)
    mgr.save(state)
    xs, ys = eng.shard_batch(ds.x[:32], ds.y[:32])
    for _ in range(3):  # donates/overwrites the saved buffers mid-write
        state, _ = eng.step(state, xs, ys)
    jax.block_until_ready(state)
    mgr.wait()
    restored = mgr.restore(_template(), step=2)
    jax.tree.map(lambda e, r: np.testing.assert_array_equal(e, _as_np(r)),
                 expect, restored.params)
    mgr.close()


def test_backpressure_second_save_waits_never_drops(tmp_path):
    """At most one save in flight: save N+1 blocks until write N lands —
    both checkpoints exist afterwards, and the blocked time is visible in
    wait_s."""
    _eng, _ds, state = _trained_state()
    mgr = SlowWriter(tmp_path / "c", delay=0.3, max_to_keep=10)
    t0 = time.perf_counter()
    mgr.save(state, step=1)
    first_save_s = time.perf_counter() - t0
    assert first_save_s < 0.25  # did NOT pay the write on this thread
    t0 = time.perf_counter()
    mgr.save(state, step=2)  # must wait out write #1 (~0.3s)
    assert time.perf_counter() - t0 > 0.2
    assert 1 in mgr.steps()  # write #1 landed before save #2 proceeded
    mgr.wait()
    assert mgr.steps() == [1, 2]  # never dropped
    assert mgr.saves == 2
    assert mgr.wait_s > 0.2
    # wall time the trainer stood blocked on a write is charged to wait_s
    # ONLY — overlapped_s keeps just the genuinely concurrent share
    # (here: nearly everything was blocked, so overlap stays small)
    assert mgr.overlapped_s < 0.3
    mgr.close()


def test_writer_crash_leaves_no_visible_partial(tmp_path):
    """Fault injection: a mid-write crash must leave only ``tmp_step_N``
    (invisible to steps()/restore), re-raise on the training thread at
    the next checkpoint, and be swept by the next manager start."""
    _eng, _ds, state = _trained_state()
    mgr = SlowWriter(tmp_path / "c", delay=0.0, crash_at=1)
    mgr.save(state, step=5)
    mgr._idle.wait()  # let the writer fail without consuming the error
    assert mgr.steps() == []                       # nothing visible
    assert (mgr.directory / "tmp_step_5").exists()  # only the torn tmp
    with pytest.raises(AsyncCheckpointError, match="injected writer crash"):
        mgr.save(state, step=6)  # the NEXT checkpoint surfaces the error
    mgr.wait()  # save 6 was never enqueued (the raise aborted it)
    assert mgr.steps() == []
    mgr.close()

    fresh = AsyncCheckpointManager(tmp_path / "c")  # next start sweeps tmp
    assert not (fresh.directory / "tmp_step_5").exists()
    assert fresh.latest_step() is None
    fresh.close()


def test_writer_error_reraises_at_close(tmp_path):
    _eng, _ds, state = _trained_state()
    mgr = SlowWriter(tmp_path / "c", crash_at=1)
    mgr.save(state, step=1)
    with pytest.raises(AsyncCheckpointError):
        mgr.close()
    # reraise=False (the exception-path cleanup contract) must swallow
    mgr2 = SlowWriter(tmp_path / "c2", crash_at=1)
    mgr2.save(state, step=1)
    mgr2.close(reraise=False)


def test_sync_write_is_atomic_too(tmp_path):
    """The tmp-fsync-rename discipline is shared: a synchronous write that
    crashes leaves only the tmp directory."""
    _eng, _ds, state = _trained_state()

    class CrashingSync(CheckpointManager):
        def _write(self, step, host_state, extra=None):
            tmp = self.directory / f"tmp_step_{step}"
            tmp.mkdir(exist_ok=True)
            (tmp / "partial.bin").write_text("torn")
            raise RuntimeError("boom")

    mgr = CrashingSync(tmp_path / "c")
    with pytest.raises(RuntimeError, match="boom"):
        mgr.save(state, step=3)
    assert mgr.steps() == []
    assert (mgr.directory / "tmp_step_3").exists()


def test_restore_drains_pending_write(tmp_path):
    """The resume barrier: restore blocks on an in-flight write, so it
    always reads the newest complete checkpoint."""
    _eng, _ds, state = _trained_state()
    mgr = SlowWriter(tmp_path / "c", delay=0.3)
    mgr.save(state, step=1)  # still writing when restore is called
    restored = mgr.restore(_template())  # waits, then reads step_1
    assert_states_equal(restored, state)
    mgr.close()


# ------------------------------------------------------- trainer wiring

def test_fit_async_spans_and_result_keys(tmp_path):
    """Async fit: ckpt_snapshot (training thread) + ckpt_write (writer
    thread) spans land in the trace, and the result carries the
    blocked/overlapped split the run report reads."""
    from distributed_tensorflow_tpu.observability import (
        Tracer, build_run_report)
    from distributed_tensorflow_tpu.observability.analyze import trace_summary

    mgr = AsyncCheckpointManager(tmp_path / "c", max_to_keep=10)
    tr = Trainer(None, engine=JitEngine(), seed=0)
    trace = tmp_path / "t.jsonl"
    tracer = Tracer(path=trace)
    r = tr.fit(_tiny_ds(256), epochs=1, batch_size=16, log_every=0,
               steps_per_call=4, checkpoint_manager=mgr,
               checkpoint_every=4, max_steps=12, tracer=tracer)
    mgr.close()
    tracer.close()
    assert r["checkpoint_async"] is True
    assert r["checkpoint_wait_s"] >= 0.0
    assert r["checkpoint_overlapped_s"] >= 0.0
    assert {4, 8, 12} <= set(mgr.steps())
    report = build_run_report(r)
    assert report["checkpoint_wait_s"] == r["checkpoint_wait_s"]
    assert report["checkpoint_overlapped_s"] == r["checkpoint_overlapped_s"]
    assert report["checkpoint_async"] is True

    recs = [json.loads(l) for l in trace.read_text().splitlines()]
    names = {x["name"] for x in recs if x.get("event") == "span"}
    assert "ckpt_snapshot" in names and "ckpt_write" in names
    assert "checkpoint" not in names  # the blocking span is the sync one
    summary = trace_summary(recs)
    assert summary["stalls"]["checkpoint_overlapped_s"] > 0.0
    assert summary["stalls"]["checkpoint_blocked_s"] >= 0.0


def test_fit_sync_keeps_checkpoint_span(tmp_path):
    from distributed_tensorflow_tpu.observability import Tracer

    mgr = CheckpointManager(tmp_path / "c", max_to_keep=10)
    tr = Trainer(None, engine=JitEngine(), seed=0)
    trace = tmp_path / "t.jsonl"
    tracer = Tracer(path=trace)
    r = tr.fit(_tiny_ds(256), epochs=1, batch_size=16, log_every=0,
               steps_per_call=4, checkpoint_manager=mgr,
               checkpoint_every=4, max_steps=8, tracer=tracer)
    tracer.close()
    assert r["checkpoint_async"] is False
    assert r["checkpoint_overlapped_s"] == 0.0
    assert r["checkpoint_wait_s"] > 0.0
    names = {x["name"] for x in
             (json.loads(l) for l in trace.read_text().splitlines())
             if x.get("event") == "span"}
    assert "checkpoint" in names
    assert "ckpt_snapshot" not in names and "ckpt_write" not in names


def test_fit_async_trajectory_matches_sync(tmp_path):
    """Same seed, both disciplines: final params and every checkpoint are
    bitwise identical — async changes WHEN the write happens, never what
    is written or trained."""
    results = {}
    for name, mgr in (
            ("sync", CheckpointManager(tmp_path / "s", max_to_keep=10)),
            ("async", AsyncCheckpointManager(tmp_path / "a", max_to_keep=10))):
        tr = Trainer(None, engine=JitEngine(), seed=0)
        tr.fit(_tiny_ds(256), epochs=1, batch_size=16, log_every=0,
               checkpoint_manager=mgr, checkpoint_every=4, max_steps=12)
        mgr.close()
        results[name] = (tr.state, mgr)
    assert_states_equal(results["sync"][0], results["async"][0])
    s_mgr, a_mgr = results["sync"][1], results["async"][1]
    assert s_mgr.steps() == a_mgr.steps()
    for step in s_mgr.steps():
        assert_states_equal(s_mgr.restore(_template(), step=step),
                            a_mgr.restore(_template(), step=step))


# round 20 fast-lane repair: wall-clock acceptance race (~12s) rides
# the slow lane; the unit-level wait accounting stays fast
@pytest.mark.slow
def test_acceptance_async_wait_under_quarter_of_sync(tmp_path):
    """ISSUE 5 acceptance: with a deliberately slowed writer,
    ``checkpoint_wait_s`` under async mode is < 25% of the same run's
    synchronous save time.  The write is a pure ``delay`` sleep
    (``fake=True``) so the ratio is deterministic — the tree-equality
    half of the acceptance (restored async state == synchronous
    checkpoint, bitwise, through real Orbax writes) is
    ``test_fit_async_trajectory_matches_sync`` above."""
    delay, gap, steps = 0.3, 0.45, 8
    # more batches than steps: the prefetcher (depth 2) must keep paying
    # the per-batch gap through the LAST save too — an exhausted source
    # would hand out its final staged batches gap-free and the tail saves
    # would block on the still-running previous write
    ds = _SlowBatchDataset(_tiny_ds(16 * (steps + 4)), gap)
    # warm the snapshot's on-device-copy compile outside the timed runs
    # (one-time cost, not steady-state blocked time)
    from distributed_tensorflow_tpu.utils import checkpoint as ckpt_mod

    jax.block_until_ready(ckpt_mod._snapshot(_template()))
    waits = {}
    for name, mgr in (
            ("sync", SlowSyncWriter(tmp_path / "s", delay=delay,
                                    fake=True, max_to_keep=20)),
            ("async", SlowWriter(tmp_path / "a", delay=delay,
                                 fake=True, max_to_keep=20))):
        tr = Trainer(None, engine=JitEngine(), seed=0)
        r = tr.fit(ds, epochs=1, batch_size=16, log_every=0,
                   checkpoint_manager=mgr, checkpoint_every=1,
                   max_steps=steps)
        mgr.close()
        assert r["steps"] == steps
        assert mgr.steps() == list(range(1, steps + 1))  # none dropped
        waits[name] = r["checkpoint_wait_s"]
        if name == "async":
            # the gaps genuinely hid several full writes behind training
            # (discounted accounting: blocked time never counts as overlap)
            assert r["checkpoint_overlapped_s"] > delay, r
    # every between-checkpoint gap (0.45s of host batch time) exceeds the
    # write (0.3s), so the async run's only irreducible blocked time is
    # the end-of-fit drain of the final save — expected ratio ~1/steps,
    # asserted at the issue's 25% bound
    assert waits["sync"] > steps * delay * 0.9  # sanity: sync paid all
    assert waits["async"] < 0.25 * waits["sync"], waits


def test_fit_failure_drains_writer_and_flushes_sinks(tmp_path):
    """Satellite: a fit that raises mid-run must leave no write in flight
    and no buffered JSONL records — the failure-path cleanup runs before
    the error propagates, without masking it."""
    from distributed_tensorflow_tpu.observability import Tracer
    from distributed_tensorflow_tpu.utils.metrics import MetricsLogger

    mgr = SlowWriter(tmp_path / "c", delay=0.3, max_to_keep=10)
    ml = MetricsLogger(tmp_path / "m.jsonl", log_every=1)
    tracer = Tracer(path=tmp_path / "t.jsonl")
    tr = Trainer(None, engine=JitEngine(), seed=0)

    def boom(msg):
        raise RuntimeError("mid-run failure")

    with pytest.raises(RuntimeError, match="mid-run failure"):
        # log_fn fires at step 2 with a save from step 1 still in flight
        tr.fit(_tiny_ds(256), epochs=1, batch_size=16, log_every=2,
               log_fn=boom, checkpoint_manager=mgr, checkpoint_every=1,
               metrics_logger=ml, tracer=tracer, max_steps=6)
    assert mgr._idle.is_set()  # writer drained before the raise escaped
    # the flushed streams are whole-line parsable, records present
    recs = [json.loads(l)
            for l in (tmp_path / "m.jsonl").read_text().splitlines()]
    # record_step logs BEFORE the heartbeat that raised, so the failing
    # step's own record reaches the (flushed) sink — [1, 2], whole lines
    assert [r["step"] for r in recs] == [1, 2]
    for line in (tmp_path / "t.jsonl").read_text().splitlines():
        json.loads(line)
    ml.close()
    tracer.close()
    mgr.close()


def test_fit_failure_cleanup_does_not_mask_error(tmp_path):
    """A writer crash pending at failure-cleanup time must not replace
    the fit's own error (the drain runs reraise=False)."""
    mgr = SlowWriter(tmp_path / "c", delay=0.05, crash_at=1, max_to_keep=10)
    tr = Trainer(None, engine=JitEngine(), seed=0)

    def boom(msg):
        raise RuntimeError("the real failure")

    with pytest.raises(RuntimeError, match="the real failure"):
        tr.fit(_tiny_ds(256), epochs=1, batch_size=16, log_every=2,
               log_fn=boom, checkpoint_manager=mgr, checkpoint_every=1,
               max_steps=6)
    mgr.close(reraise=False)


# ------------------------------------------------------- harness / CLI

def test_cli_async_checkpoint_flag_parses():
    from distributed_tensorflow_tpu.cli import build_parser

    p = build_parser()
    assert p.parse_args([]).async_checkpoint == "on"  # default on
    assert p.parse_args(["--async-checkpoint", "off"]).async_checkpoint \
        == "off"
    with pytest.raises(SystemExit):
        p.parse_args(["--async-checkpoint", "maybe"])


# round 20 fast-lane repair: heaviest harness e2e in the suite (~22s:
# two full runs + resume); rides the slow lane
@pytest.mark.slow
def test_harness_async_checkpoint_resume_roundtrip(tmp_path):
    """`--checkpoint-every` + `--resume` under the async default (fsdp
    engine — GSPMD, runs on any jax): the resumed run continues the
    original step numbering, and the run report carries the wait split."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    common = dict(engine="fsdp", model="mlp", dataset="synthetic",
                  n_devices=8, batch_size=8, epochs=1, log_every=0,
                  checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2)
    first = run(ExperimentConfig(**common))
    mgr = CheckpointManager(common["checkpoint_dir"])
    assert mgr.latest_step() == first["steps"]
    report = first["run_report"]
    assert report["checkpoint_async"] is True
    assert report["checkpoint_wait_s"] >= 0.0
    assert report["checkpoint_overlapped_s"] >= 0.0
    second = run(ExperimentConfig(**common, resume=True))
    assert np.isfinite(second["test_loss"])
    assert mgr.latest_step() == 2 * first["steps"]


# round 20 fast-lane repair: harness e2e flag-off variant
@pytest.mark.slow
def test_harness_async_checkpoint_off_is_sync(tmp_path):
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    summary = run(ExperimentConfig(
        engine="fsdp", model="mlp", dataset="synthetic", n_devices=8,
        batch_size=8, epochs=1, log_every=0, async_checkpoint=False,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2))
    report = summary["run_report"]
    assert report["checkpoint_async"] is False
    assert report["checkpoint_overlapped_s"] == 0.0
    assert CheckpointManager(str(tmp_path / "ck")).latest_step() \
        == summary["steps"]


def test_analyze_diff_compares_checkpoint_wait(tmp_path):
    """`analyze diff` gates on checkpoint_wait_s (lower-better): a slower
    candidate regresses, a faster one improves."""
    from distributed_tensorflow_tpu.observability.analyze import diff_reports

    base = {"checkpoint_wait_s": 1.0}
    worse = diff_reports(base, {"checkpoint_wait_s": 2.0})
    assert [r["metric"] for r in worse["regressions"]] \
        == ["checkpoint_wait_s"]
    better = diff_reports(base, {"checkpoint_wait_s": 0.1})
    assert [r["metric"] for r in better["improvements"]] \
        == ["checkpoint_wait_s"]
