"""Failure detection: divergence guard, stall watchdog, crash recovery."""

import dataclasses
import time

import numpy as np
import pytest

from distributed_tensorflow_tpu.utils.failure import (
    StallDetected, TrainingDiverged, Watchdog, check_finite,
    run_with_recovery)
from distributed_tensorflow_tpu.utils.harness import ExperimentConfig


# ------------------------------------------------------------ check_finite
def test_check_finite_passes():
    check_finite({"loss": 0.5, "accuracy": 1.0})


@pytest.mark.parametrize("bad", [float("nan"), float("inf"), float("-inf")])
def test_check_finite_raises(bad):
    with pytest.raises(TrainingDiverged, match="loss"):
        check_finite({"loss": bad}, step=7)


# ---------------------------------------------------------------- watchdog
def test_watchdog_quiet_while_beating():
    with Watchdog(timeout=0.3, poll_interval=0.05) as wd:
        for _ in range(8):
            time.sleep(0.05)
            wd.beat()
            wd.check()
        assert not wd.stalled


def test_watchdog_detects_stall():
    fired = []
    with Watchdog(timeout=0.15, poll_interval=0.03,
                  on_stall=fired.append) as wd:
        wd.beat()  # arm (the clock starts at the first beat)
        time.sleep(0.4)  # then no beats
        assert wd.stalled
        assert fired and fired[0] > 0.15
        with pytest.raises(StallDetected):
            wd.check()


def test_watchdog_unarmed_before_first_beat():
    """No false stall during the first-step XLA compile window."""
    with Watchdog(timeout=0.1, poll_interval=0.02) as wd:
        time.sleep(0.3)  # 'compiling': no beats yet
        assert not wd.stalled
        wd.check()  # does not raise


def test_watchdog_rearms_after_recovery():
    """A transient pause that recovers must not poison later checks."""
    with Watchdog(timeout=0.12, poll_interval=0.02) as wd:
        wd.beat()
        time.sleep(0.3)  # stall episode fires
        assert wd.stalled
        wd.beat()       # progress resumes
        time.sleep(0.06)
        assert not wd.stalled  # monitor re-armed
        wd.check()      # recovered episode never raises
        assert wd.stall_episodes == 1


def test_trainer_raises_on_nan(mesh8):
    """A diverged loss surfaces as TrainingDiverged from fit()."""
    import flax.linen as nn
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.data.loaders import Dataset
    from distributed_tensorflow_tpu.engines.allreduce import Trainer

    class NaNModel(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            h = nn.Dense(10)(x.reshape((x.shape[0], -1)))
            return h / 0.0  # NaN/inf logits from step one

    x = np.random.default_rng(0).random((64, 4), np.float32)
    y = (np.arange(64) % 10).astype(np.int32)
    ds = Dataset(x=x, y=y, num_classes=10)
    tr = Trainer(NaNModel(), mesh=None)
    with pytest.raises(TrainingDiverged):
        tr.fit(ds, epochs=1, batch_size=16, log_every=1, log_fn=lambda s: None)


# ---------------------------------------------------------------- recovery
def test_run_with_recovery_requires_checkpoint_dir():
    cfg = ExperimentConfig()
    with pytest.raises(ValueError, match="checkpoint_dir"):
        run_with_recovery(cfg, max_restarts=1, run_fn=lambda c: {})


def test_run_with_recovery_restarts_with_resume(tmp_path):
    cfg = ExperimentConfig(checkpoint_dir=str(tmp_path))
    calls = []

    def flaky_run(config):
        calls.append((config.resume, config.elastic_restore))
        if len(calls) < 3:
            raise RuntimeError(f"crash {len(calls)}")
        return {"ok": True}

    restarts = []
    out = run_with_recovery(cfg, max_restarts=2, run_fn=flaky_run,
                            on_restart=lambda n, e: restarts.append(str(e)))
    assert out == {"ok": True, "restarts": 2}
    # the restart is the ELASTIC resume (resharding + data state), not a
    # cold restore: both flags flip on after the first crash
    assert calls == [(False, False), (True, True), (True, True)]
    assert restarts == ["crash 1", "crash 2"]


def test_run_with_recovery_exhausts_restarts(tmp_path):
    cfg = ExperimentConfig(checkpoint_dir=str(tmp_path))

    def always_crash(config):
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        run_with_recovery(cfg, max_restarts=2, run_fn=always_crash)


def test_run_with_recovery_no_retry_on_divergence(tmp_path):
    cfg = ExperimentConfig(checkpoint_dir=str(tmp_path))
    calls = []

    def diverge(config):
        calls.append(1)
        raise TrainingDiverged("loss is nan")

    with pytest.raises(TrainingDiverged):
        run_with_recovery(cfg, max_restarts=5, run_fn=diverge)
    assert len(calls) == 1  # restarting into the same NaN is not recovery


def test_harness_run_dispatches_recovery():
    """max_restarts on the config is honored by harness.run itself
    (programmatic path, not just the CLI)."""
    from distributed_tensorflow_tpu.utils import harness

    cfg = ExperimentConfig(max_restarts=1)  # no checkpoint_dir
    with pytest.raises(ValueError, match="checkpoint_dir"):
        harness.run(cfg)


@pytest.mark.slow
def test_recovery_end_to_end_resumes_from_checkpoint(tmp_path):
    """Crash mid-training → run_with_recovery resumes from the checkpoint
    and the final step count continues (not restarts) the original run."""
    from distributed_tensorflow_tpu.utils import harness

    cfg = ExperimentConfig(
        engine="sync", model="mlp", dataset="synthetic", n_devices=8,
        batch_size=8, epochs=2, log_every=0,
        checkpoint_dir=str(tmp_path), checkpoint_every=10)

    crashed = {"done": False}
    real_run = harness.run

    def crash_once(config):
        if not crashed["done"]:
            crashed["done"] = True
            # run a short real training to write checkpoints, then "crash"
            short = dataclasses.replace(config, epochs=1)
            real_run(short)
            raise RuntimeError("injected crash after epoch 1")
        return real_run(config)

    out = run_with_recovery(cfg, max_restarts=1, run_fn=crash_once)
    assert out["restarts"] == 1
    # resumed run trained on top of the checkpoint: steps continue
    assert out["steps"] > 0
    assert out["test_accuracy"] > 0.5
