"""Multi-host (multi-process) validation over jax.distributed.

The reference's multi-machine mode launches one role per machine with
``-tt server|worker -ti I -sa ADDR`` (reference initializer.py:147-155) over
hand-rolled TCP.  The TPU-native equivalent: every host runs the SAME SPMD
program after ``jax.distributed.initialize`` (parallel/mesh.py
multihost_initialize); XLA owns cross-host tensor traffic.

These tests spawn REAL separate processes (the SPMD analogue of separate
machines), each exposing 2 CPU devices, so the 2-process job trains over a
4-device global mesh with cross-process collectives.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _proc_env(local_devices: int = 2) -> dict:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORM_NAME": "cpu",
        "JAX_PLATFORMS": "",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={local_devices}",
        "PYTHONPATH": str(REPO),
    })
    return env


COLLECTIVE_SCRIPT = r"""
import sys
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.parallel import mesh as meshlib

coord, pid = sys.argv[1], int(sys.argv[2])
meshlib.multihost_initialize(coordinator_address=coord, num_processes=2,
                             process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 4, jax.device_count()  # 2 procs x 2 local

mesh = meshlib.create_mesh(4)
ones = jnp.ones((), jnp.float32)

def allreduce(x):
    return jax.lax.psum(x, meshlib.DATA_AXIS)

total = jax.jit(jax.shard_map(allreduce, mesh=mesh, in_specs=P(),
                              out_specs=P()))(ones)
assert float(total) == 4.0, float(total)
print("MULTIHOST_COLLECTIVE_OK", float(total))
"""


TRAIN_SCRIPT = r"""
import sys
import jax
import numpy as np

from distributed_tensorflow_tpu.parallel import mesh as meshlib

coord, pid = sys.argv[1], int(sys.argv[2])
meshlib.multihost_initialize(coordinator_address=coord, num_processes=2,
                             process_id=pid)

from distributed_tensorflow_tpu.engines import SyncEngine
from distributed_tensorflow_tpu.models import create_model

mesh = meshlib.create_mesh(jax.device_count())
model = create_model("mlp", num_classes=10, hidden=16)
eng = SyncEngine(model, mesh=mesh, learning_rate=1e-2)

# identical host data on every process (same seed) — device_put places each
# process's addressable shard of the global batch
rnd = np.random.default_rng(0)
x = rnd.random((16, 28, 28, 1), np.float32)
y = (np.arange(16) % 10).astype(np.int32)
state = eng.init_state(jax.random.key(0), x)
xs, ys = eng.shard_batch(x, y)
state, first = eng.step(state, xs, ys)
for _ in range(10):
    state, m = eng.step(state, xs, ys)
jax.block_until_ready(state)
l0, l1 = float(first["loss"]), float(m["loss"])
assert l1 < l0, (l0, l1)

# async engine: per-device stacked state placement across processes
from distributed_tensorflow_tpu.engines import AsyncLocalEngine

aeng = AsyncLocalEngine(model, mesh=mesh, learning_rate=1e-2, sync_every=2)
astate = aeng.init_state(jax.random.key(1), x)
astate, am = aeng.step(astate, *aeng.shard_batch(x, y))
jax.block_until_ready(astate)
assert float(am["loss"]) > 0.0
print("MULTIHOST_TRAIN_OK", l0, l1)
"""


SHARDED_INPUT_SCRIPT = r"""
import sys
import numpy as np
import jax

from distributed_tensorflow_tpu.parallel import mesh as meshlib

coord, pid = sys.argv[1], int(sys.argv[2])
meshlib.multihost_initialize(coordinator_address=coord, num_processes=2,
                             process_id=pid)

from distributed_tensorflow_tpu.engines import SyncEngine, Trainer
from distributed_tensorflow_tpu.models import create_model
from distributed_tensorflow_tpu.utils.harness import ExperimentConfig, _load_data

# harness shards the TRAIN split by process; eval stays full
cfg = ExperimentConfig(dataset="synthetic", batch_size=8)
train, test = _load_data(cfg)
assert train.process_shard == (jax.process_index(), 2), train.process_shard
full = 8192  # loaders' synthetic train size
assert len(train) == full // 2, len(train)   # each process holds ~1/P
assert len(test) == 2048, len(test)          # eval unsharded

mesh = meshlib.create_mesh(jax.device_count())
model = create_model("mlp", num_classes=10, hidden=16, dropout_rate=0.0)

# parity: one sync step from process-local rows == one step on the same
# examples fed as a full global batch (sync DP depends on the SET of
# examples, and shard p's first rows are x[p::2][:lb] — union x[:bs])
import optax
bs, lb = 16, 8
eng_a = SyncEngine(model, optimizer=optax.sgd(0.5), mesh=mesh)
sa = eng_a.init_state(jax.random.key(0), train.x[:1])
xs, ys = eng_a.shard_batch(train.x[:lb], train.y[:lb], process_local=True)
sa, ma = eng_a.step(sa, xs, ys)

from distributed_tensorflow_tpu.data.loaders import load_dataset
full_ds = load_dataset("synthetic", split="train")
eng_b = SyncEngine(model, optimizer=optax.sgd(0.5), mesh=mesh)
sb = eng_b.init_state(jax.random.key(0), full_ds.x[:1])
xs, ys = eng_b.shard_batch(full_ds.x[:bs], full_ds.y[:bs])
sb, mb = eng_b.step(sb, xs, ys)

la, lbb = float(ma["loss"]), float(mb["loss"])
assert abs(la - lbb) < 1e-5, (la, lbb)
for a, b in zip(jax.tree.leaves(jax.device_get(sa.params)),
                jax.tree.leaves(jax.device_get(sb.params))):
    np.testing.assert_allclose(a, b, atol=1e-5)

# end-to-end: the Trainer consumes the sharded dataset (local batches,
# process_local placement, global example accounting)
tr = Trainer(None, engine=SyncEngine(model, mesh=mesh, learning_rate=1e-2))
fit = tr.fit(train, epochs=1, batch_size=bs, log_every=0)
assert fit["steps"] == (full // 2) // lb, fit["steps"]
assert fit["examples"] == fit["steps"] * bs, fit["examples"]  # global count
print("MULTIHOST_SHARDED_INPUT_OK", la)
"""


def _run_two_procs(script: str, timeout: int = 180):
    coord = f"127.0.0.1:{_free_port()}"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, coord, str(pid)],
            env=_proc_env(), cwd=str(REPO),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


@pytest.mark.slow
def test_multihost_psum_across_processes():
    outs = _run_two_procs(COLLECTIVE_SCRIPT)
    for rc, out, err in outs:
        assert rc == 0, err[-3000:]
        assert "MULTIHOST_COLLECTIVE_OK 4.0" in out


@pytest.mark.slow
def test_multihost_sync_training_step():
    outs = _run_two_procs(TRAIN_SCRIPT)
    for rc, out, err in outs:
        assert rc == 0, err[-3000:]
        assert "MULTIHOST_TRAIN_OK" in out


@pytest.mark.slow
def test_multihost_sharded_input():
    """Each process materializes ~1/P of the train split and global batches
    assemble from local rows, with step-for-step sync parity vs the full-
    batch path (VERDICT r2 task 7: the reference's per-worker `.shard`,
    reference initializer.py:44, honored for real on multi-host)."""
    outs = _run_two_procs(SHARDED_INPUT_SCRIPT)
    for rc, out, err in outs:
        assert rc == 0, err[-3000:]
        assert "MULTIHOST_SHARDED_INPUT_OK" in out


@pytest.mark.slow
def test_multihost_cli_roles(tmp_path):
    """The reference's -tt/-ti/-sa surface drives a 2-process run end-to-end
    (reference initializer.py:147-155 required manual per-role launches of
    server and each worker — same UX here, but both roles run the same SPMD
    training program)."""
    coord = f"127.0.0.1:{_free_port()}"
    args = ["-m", "tpu_pod", "-b", "8", "--dataset", "synthetic",
            "--model", "mlp", "--log-every", "0", "--num-processes", "2",
            "-sa", coord]
    cmds = [
        [sys.executable, "initializer.py", *args, "-tt", "server"],
        [sys.executable, "initializer.py", *args, "-tt", "worker", "-ti", "0"],
    ]
    procs = [subprocess.Popen(c, env=_proc_env(), cwd=str(REPO),
                              stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                              text=True) for c in cmds]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, err[-3000:]
        summary = json.loads(out.strip().splitlines()[-1])
        assert summary["n_devices"] == 4  # 2 procs x 2 local cpu devices
        assert summary["test_accuracy"] > 0.5


PIPELINE_SCRIPT = r"""
import sys
import jax
import numpy as np

from distributed_tensorflow_tpu.parallel import mesh as meshlib

coord, pid = sys.argv[1], int(sys.argv[2])
meshlib.multihost_initialize(coordinator_address=coord, num_processes=2,
                             process_id=pid)

import optax

from distributed_tensorflow_tpu.engines.base import cross_entropy
from distributed_tensorflow_tpu.engines.pipeline import PipelineEngine

# 'pipe' as the MAJOR mesh dim over 2 processes x 2 local devices puts
# consecutive pipeline stages on DIFFERENT processes (stage 0 = process
# 0's devices, stage 1 = process 1's), so the schedule's ppermute ring
# crosses the process boundary every tick — the multi-host rendering of
# cross-machine stage hand-off.  The engine looks axes up by name, so
# the mesh-dim order is free.
mesh = meshlib.create_mesh(
    jax.device_count(), shape=(2, 2),
    axis_names=(meshlib.PIPE_AXIS, meshlib.DATA_AXIS))
procs = {d.process_index for d in mesh.devices[:, 0]}  # one pipe column
assert len(procs) == 2, procs  # stage hop really crosses processes

# lr=0 keeps params unchanged through the step, so the post-step gather
# below feeds the oracle the same params the schedule used
eng = PipelineEngine(num_classes=10, hidden=16, microbatches=2, mesh=mesh,
                     optimizer=optax.sgd(0.0))
rnd = np.random.default_rng(0)
x = rnd.random((8, 28, 28, 1), np.float32)
y = (np.arange(8) % 10).astype(np.int32)
state = eng.init_state(jax.random.key(0), x)
state, m = eng.step(state, *eng.shard_batch(x, y))
jax.block_until_ready(state)

# loss parity vs the sequential oracle still holds across hosts; params
# are globally sharded, so gather a host-local copy for the oracle
import jax.numpy as jnp
from jax.experimental import multihost_utils

params = multihost_utils.process_allgather(state.params, tiled=True)
logits = eng._sequential_logits(jax.device_get(params), x)
ref = float(cross_entropy(jnp.asarray(logits), jnp.asarray(y)).mean())
print("MULTIHOST_PIPELINE_OK", float(m["loss"]), ref)
assert abs(float(m["loss"]) - ref) < 1e-4, (float(m["loss"]), ref)
"""


@pytest.mark.slow
def test_multihost_pipeline_ring_across_processes():
    """The GPipe ppermute ring crosses a REAL process boundary: with
    'pipe' as the MAJOR mesh dim over 2 processes (pipe=2 major, data=2
    minor — the ordering is load-bearing; data-major would keep each
    stage pair within one process), consecutive stages land on different
    processes and stage activations hop hosts every tick.  Loss must
    still match the sequential oracle."""
    outs = _run_two_procs(PIPELINE_SCRIPT)
    for rc, out, err in outs:
        assert rc == 0, err[-3000:]
        assert "MULTIHOST_PIPELINE_OK" in out
