"""Time-series telemetry (ISSUE 17): GaugeSeries ring-buffer laws
(bound, merge≡record-all, serialization round-trip), the throttled
Timeline sampler, the sampler-off parity pin at the batcher level, the
fleet's per-replica series surviving a seeded kill, and the offline
reconstruction path (`analyze timeline` + Perfetto counter lanes) from
the emitted trace alone.  Host-side throughout — no shard_map.
"""

import json
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.models.gpt import GPTLM
from distributed_tensorflow_tpu.observability.analyze import (
    _TIMELINE_PID_BASE, render_timeline_text, timeline_series,
    timeline_summary, to_chrome_trace)
from distributed_tensorflow_tpu.observability.timeline import (
    GaugeSeries, Timeline, sparkline, split_series_key)
from distributed_tensorflow_tpu.observability.trace import Tracer
from distributed_tensorflow_tpu.serving import (
    ContinuousBatcher, FaultInjector, ReplicaSet, Request, SlotKVCache,
    VirtualClock, build_replica_kvs)


def tiny_gpt(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("hidden", 32)
    kw.setdefault("layers", 1)
    kw.setdefault("heads", 2)
    kw.setdefault("ffn", 64)
    kw.setdefault("max_len", 48)
    kw.setdefault("dropout_rate", 0.0)
    return GPTLM(**kw)


@pytest.fixture(scope="module")
def model_params():
    model = tiny_gpt()
    x = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 8)),
                    jnp.int32)
    params = model.init(jax.random.key(0), x, train=False)["params"]
    return model, params


def _requests(n=6, seed=3, max_new=8, spread=0.5):
    rng = np.random.default_rng(seed)
    return [Request(rid=i,
                    prompt=rng.integers(0, 64, 6 + i % 4).astype(np.int32),
                    max_new_tokens=max_new, arrival_s=float(i) * spread)
            for i in range(n)]


# ------------------------------------------------------------ ring buffer


def test_ring_bound_and_exact_totals():
    """The ring retains the most recent `capacity` samples while the
    exact totals (count/sum/min/max) cover EVERY sample ever recorded —
    the window never lies about the extremes."""
    g = GaugeSeries(capacity=8)
    vals = [float(v) for v in range(100)]
    for i, v in enumerate(vals):
        g.record(v, t_mono=float(i), wall=float(i))
    assert g.values() == vals[-8:]
    assert g.count == 100 and g.dropped == 92
    assert g.sum == sum(vals)
    assert g.vmin == 0.0 and g.vmax == 99.0
    s = g.summary()
    assert s["retained"] == 8 and s["dropped"] == 92
    assert s["mean"] == pytest.approx(sum(vals) / 100)
    assert s["max"] == 99.0 and s["min"] == 0.0   # pre-drop extremes live
    assert s["last"] == 99.0


def test_ring_capacity_validation():
    with pytest.raises(ValueError):
        GaugeSeries(capacity=0)
    with pytest.raises(ValueError):
        Timeline(interval_s=-1.0)


def test_merge_equals_record_all():
    """THE merge law: a.merge(b) holds exactly what one series recording
    both sample streams in time order would hold — retained window,
    totals, extremes — including when the union overflows the ring."""
    rng = np.random.default_rng(7)
    for na, nb, cap in ((5, 5, 32), (40, 25, 32), (3, 60, 16)):
        ta = sorted(rng.uniform(0, 100, na))
        tb = sorted(rng.uniform(0, 100, nb))
        a = GaugeSeries(capacity=cap)
        b = GaugeSeries(capacity=cap)
        ref = GaugeSeries(capacity=cap)
        for t in ta:
            a.record(t * 2.0, t_mono=t, wall=t)
        for t in tb:
            b.record(-t, t_mono=t, wall=t)
        for t, v in sorted([(t, t * 2.0) for t in ta]
                           + [(t, -t) for t in tb]):
            ref.record(v, t_mono=t, wall=t)
        a.merge(b)
        assert a.samples() == ref.samples()
        assert a.count == ref.count
        assert a.sum == pytest.approx(ref.sum)
        assert a.vmin == ref.vmin and a.vmax == ref.vmax
        assert a.summary() == pytest.approx(ref.summary())


def test_serialization_round_trip():
    """to_dict → JSON → from_dict reproduces samples, totals, and every
    summary stat — including a ring that has dropped samples."""
    g = GaugeSeries(capacity=4)
    for i in range(9):
        g.record(float(i * i), t_mono=float(i), wall=100.0 + i)
    d = json.loads(json.dumps(g.to_dict()))
    h = GaugeSeries.from_dict(d)
    assert h.samples() == g.samples()
    assert h.count == g.count and h.sum == g.sum
    assert h.vmin == g.vmin and h.vmax == g.vmax
    assert h.summary() == g.summary()
    # Timeline round-trip carries every series + the overhead ledger
    tl = Timeline(interval_s=0.0, capacity=4)
    tl.sample_many({"a": 1.0, "b": 2.0})
    tl2 = Timeline.from_dict(json.loads(json.dumps(tl.to_dict())))
    assert tl2.names() == tl.names()
    assert tl2.summary() == tl.summary()


def test_auc_trapezoid():
    g = GaugeSeries()
    assert g.auc() is None
    g.record(2.0, t_mono=0.0, wall=0.0)
    assert g.auc() is None          # one sample spans no time
    g.record(4.0, t_mono=1.0, wall=1.0)
    g.record(0.0, t_mono=3.0, wall=3.0)
    # (2+4)/2*1 + (4+0)/2*2
    assert g.auc() == pytest.approx(7.0)


def test_sparkline_shapes():
    assert sparkline([]) == ""
    assert sparkline([5.0, 5.0, 5.0]) == "▁▁▁"
    s = sparkline(list(range(200)), width=60)
    assert len(s) == 60
    assert s[0] == "▁" and s[-1] == "█"
    assert len(sparkline([1.0, 9.0])) == 2


def test_split_series_key():
    assert split_series_key("queue_depth") == ("queue_depth", None)
    assert split_series_key("replica_load@r3") == ("replica_load", 3)
    assert split_series_key("odd@rx") == ("odd@rx", None)


# --------------------------------------------------------------- sampler


def test_timeline_throttle_interval():
    """One recorded sample per interval per throttle group; the skip
    path records nothing; interval 0 records at every boundary."""
    t = [0.0]
    tl = Timeline(interval_s=1.0, clock=lambda: t[0])
    assert tl.sample("g", 1.0) is True
    t[0] = 0.5
    assert tl.sample("g", 2.0) is False
    t[0] = 1.0
    assert tl.sample("g", 3.0) is True
    assert tl.series("g").values() == [1.0, 3.0]
    # distinct groups throttle independently
    assert tl.sample("h", 9.0) is True
    every = Timeline(interval_s=0.0, clock=lambda: t[0])
    for _ in range(5):
        assert every.sample("g", 1.0) is True
    assert every.series("g").count == 5
    assert tl.overhead_s >= 0.0


def test_timeline_merge_and_stat():
    a = Timeline(interval_s=0.0)
    b = Timeline(interval_s=0.0)
    a.sample("q", 1.0, replica=0)
    b.sample("q", 5.0, replica=1)
    b.sample("q", 3.0, replica=0)
    a.merge(b)
    assert a.names() == ["q@r0", "q@r1"]
    assert a.stat("q", "max", replica=0) == 3.0
    assert a.stat("q", "max", replica=1) == 5.0
    assert a.stat("missing", "max") is None


def test_emit_reconstruction_lossless(tmp_path):
    """emit() → trace file → analyze's timeline_series reproduces the
    retained window AND the exact totals even when the ring dropped
    samples — the counter-cliff forensics work from the file alone."""
    path = tmp_path / "trace.jsonl"
    tl = Timeline(interval_s=0.0, capacity=4)
    for i in range(11):
        tl.series("load", replica=1).record(float(i), t_mono=float(i),
                                            wall=float(i))
    with Tracer(path=path, annotate=False) as tr:
        tl.emit(tr)
    records = [json.loads(l) for l in path.read_text().splitlines()]
    series = timeline_series(records)
    g = series["load@r1"]
    assert g.values() == [7.0, 8.0, 9.0, 10.0]
    assert g.count == 11 and g.dropped == 7
    assert g.vmin == 0.0 and g.vmax == 10.0 and g.sum == sum(range(11))
    summ = timeline_summary(records)
    assert summ["series"]["load@r1"]["max"] == 10.0
    text = render_timeline_text(records)
    assert "load@r1" in text and "+7 dropped" in text
    assert render_timeline_text([]).startswith("(no timeline_series")


# ------------------------------------------------- batcher parity (off/on)


def test_batcher_sampler_off_parity(model_params):
    """The PR 11 parity pin at the batcher level: with the sampler OFF
    the token streams, compiled-program inventory, and summary key set
    are byte-identical to pre-timeline; flag ON adds EXACTLY the three
    timeline keys and changes no token."""
    model, params = model_params
    kv_off = SlotKVCache(model, params, slots=2)
    off = ContinuousBatcher(kv_off, clock=VirtualClock()).run(_requests())
    kv_on = SlotKVCache(model, params, slots=2)
    tl = Timeline(interval_s=0.0)
    on = ContinuousBatcher(kv_on, clock=VirtualClock(),
                           timeline=tl).run(_requests())
    for a, b in zip(off["results"], on["results"]):
        assert a.tokens == b.tokens, (a.rid, a.tokens, b.tokens)
    assert set(kv_on.compiled_programs()) == set(kv_off.compiled_programs())
    extra = set(on) - set(off)
    assert extra == {"queue_depth_auc", "kv_blocks_in_use_p95",
                     "timeline_overhead_s"}, extra
    assert set(off) - set(on) == set()
    assert on["queue_depth_auc"] is not None
    assert on["timeline_overhead_s"] == tl.overhead_s
    # the batcher sampled at decode boundaries: queue/slot/kv gauges live
    assert {"queue_depth", "active_slots", "prefill_pending"} <= \
        set(tl.names())


def test_batcher_timeline_overhead_budget(model_params):
    """Self-measured sampler cost stays under 1% of the run's wall time
    (the budget BASELINE.md states is measured, not assumed)."""
    import time
    model, params = model_params
    tl = Timeline(interval_s=0.0)
    t0 = time.perf_counter()
    ContinuousBatcher(SlotKVCache(model, params, slots=2),
                      clock=VirtualClock(), timeline=tl).run(_requests())
    elapsed = time.perf_counter() - t0
    assert tl.overhead_s < 0.01 * elapsed, (tl.overhead_s, elapsed)


# --------------------------------------------------- fleet kill → cliff


def test_fleet_per_replica_series_survive_kill(model_params, tmp_path):
    """A seeded kill of replica 0 leaves its per-replica lanes IN the
    emitted trace with the counter cliff visible: replica 0's load lane
    exists and ends at zero, the admitting-replicas gauge steps 2 → 1,
    and the survivor's lane keeps sampling."""
    model, params = model_params
    path = tmp_path / "fleet_trace.jsonl"
    tl = Timeline(interval_s=0.0)
    inj = FaultInjector("crash:replica=0,iter=3", seed=0)
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                    clock=VirtualClock(), fault_injector=inj,
                    timeline=tl)
    s = rs.run(_requests())
    assert s["serve_fleet"]["failovers"] == 1
    assert s["completed"] == s["offered"] == 6
    with Tracer(path=path, annotate=False) as tr:
        tl.emit(tr)
    records = [json.loads(l) for l in path.read_text().splitlines()]
    series = timeline_series(records)
    # both replica lanes present (batcher gauges key by replica tag too)
    assert "replica_load@r0" in series and "replica_load@r1" in series
    assert "queue_depth@r0" in series and "queue_depth@r1" in series
    # the cliff: replica 0 stops serving → its load lane ends at 0 while
    # the fleet-level admitting count steps down to exactly 1
    assert series["replica_load@r0"].values()[-1] == 0.0
    adm = series["admitting_replicas"]
    assert adm.vmax == 2.0 and adm.vmin == 1.0 and adm.values()[-1] == 1.0
    # the journal charged the requeue
    assert series["journal_retries"].vmax >= 1.0
    # fleet summary carries the flag-on keys (folded across replicas)
    assert s["timeline_overhead_s"] == tl.overhead_s
    assert "queue_depth_auc" in s


def test_chrome_counter_lanes(tmp_path):
    """Perfetto export: per-replica timeline series render as counter
    tracks on synthetic per-replica pids with process_name metadata —
    replica lanes separate in the UI."""
    path = tmp_path / "trace.jsonl"
    tl = Timeline(interval_s=0.0)
    tl.sample_many({"queue_depth": 3.0}, replica=0)
    tl.sample_many({"queue_depth": 1.0}, replica=1)
    tl.sample_many({"admitting_replicas": 2.0})
    with Tracer(path=path, annotate=False) as tr:
        tl.emit(tr)
    records = [json.loads(l) for l in path.read_text().splitlines()]
    events = to_chrome_trace(records)["traceEvents"]
    counters = [e for e in events if e["ph"] == "C"]
    assert counters, "no counter events"
    pids = {e["pid"] for e in counters}
    assert _TIMELINE_PID_BASE in pids and _TIMELINE_PID_BASE + 1 in pids
    metas = {e["args"]["name"] for e in events
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert "replica 0 (timeline)" in metas
    assert "replica 1 (timeline)" in metas
    # the fleet-level (replica-less) series stays on the host pid
    host = [e for e in counters if e["name"] == "admitting_replicas"]
    assert host and host[0]["pid"] not in (
        _TIMELINE_PID_BASE, _TIMELINE_PID_BASE + 1)
