"""Flash-attention Pallas kernel vs the dense oracle.

The oracle is `parallel.ring_attention.dense_attention` (itself validated
against plain softmax math in test_ring_attention.py).  Kernels run in
Pallas interpret mode on the CPU fake mesh — same code path the TPU
compiles (SURVEY.md §4: unit tests on the fake mesh are the analogue of the
reference's fork-based fake cluster).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.ops import flash_attention
from distributed_tensorflow_tpu.parallel.ring_attention import dense_attention


def _qkv(key, b, l, h, d, lk=None):
    kq, kk, kv = jax.random.split(key, 3)
    lk = lk or l
    q = jax.random.normal(kq, (b, l, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, lk, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, lk, h, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_matches_dense_single_block(causal):
    q, k, v = _qkv(jax.random.key(0), 2, 16, 2, 8)
    out = flash_attention(q, k, v, causal=causal)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_matches_dense_multi_block(causal):
    # L=64 with 16-wide blocks → 4×4 grid exercises the online-softmax merge
    q, k, v = _qkv(jax.random.key(1), 2, 64, 2, 8)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_padding_non_divisible_lengths():
    # L=50 not divisible by 16 → kernel pads internally and slices back
    q, k, v = _qkv(jax.random.key(2), 1, 50, 2, 8)
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_kv_mask():
    q, k, v = _qkv(jax.random.key(3), 2, 32, 2, 8)
    mask = (jax.random.uniform(jax.random.key(4), (2, 32)) > 0.3)
    mask = mask.at[:, 0].set(True)  # keep ≥1 valid key per row
    out = flash_attention(q, k, v, kv_mask=mask.astype(jnp.float32),
                          block_q=16, block_k=16)
    ref = dense_attention(q, k, v, kv_mask=mask.astype(jnp.float32))
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_cross_attention_lengths():
    q, k, v = _qkv(jax.random.key(5), 1, 32, 2, 8, lk=48)
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_dense(causal):
    q, k, v = _qkv(jax.random.key(6), 2, 32, 2, 8)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
        return jnp.sum(jnp.sin(o))  # non-trivial upstream gradient

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(dense_attention(q, k, v, causal=causal)))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.slow
def test_gradients_with_mask_and_padding():
    q, k, v = _qkv(jax.random.key(7), 1, 40, 2, 8)
    mask = jnp.ones((1, 40)).at[:, 33:].set(0.0)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, kv_mask=mask, block_q=16, block_k=16)
        return jnp.sum(o * o)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, kv_mask=mask) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


def test_jit_and_vmap_compose():
    q, k, v = _qkv(jax.random.key(8), 2, 32, 2, 8)
    jitted = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, block_q=16, block_k=16, interpret=True))
    np.testing.assert_allclose(jitted(q, k, v), dense_attention(q, k, v),
                               atol=1e-5, rtol=1e-5)


def test_bfloat16_inputs():
    q, k, v = _qkv(jax.random.key(9), 1, 32, 2, 8)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(qb, kb, vb, block_q=16, block_k=16)
    ref = dense_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(out.astype(jnp.float32), ref,
                               atol=3e-2, rtol=3e-2)


def test_block_sizes_validated_against_vmem():
    """Oversized blocks fail fast with a clear ValueError instead of an
    opaque Mosaic allocation error (VERDICT r2 weak #8)."""
    import jax.numpy as jnp
    import pytest

    from distributed_tensorflow_tpu.ops import flash_attention

    q = jnp.ones((1, 1 << 16, 1, 256), jnp.float32)
    with pytest.raises(ValueError, match="VMEM"):
        # interpret=False: exercise the kernel path's validation (the
        # check fires before any pallas_call is built)
        flash_attention(q, q, q, block_q=1 << 16, block_k=1 << 16,
                        interpret=False)
