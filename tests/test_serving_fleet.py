"""Fault-tolerant serving fleet (ISSUE 15): ReplicaSet supervision with
journaled no-loss failover, seeded fault injection, the exactly-once
emission fence, graceful drain + zero-downtime weight hot-swap, fleet
accounting, and the analyze/harness/CLI surfaces.  Everything here runs on
this container — the fleet is host Python over the GSPMD slot tables, no
shard_map anywhere.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.elastic.lease import LeaseManager
from distributed_tensorflow_tpu.models.gpt import GPTLM
from distributed_tensorflow_tpu.serving import (
    ContinuousBatcher, FaultInjector, FaultSpec, ReplicaSet, Request,
    SlotKVCache, VirtualClock, build_replica_kvs)
from distributed_tensorflow_tpu.serving.fleet import (
    InjectedFault, RequestJournal)


def tiny_gpt(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("hidden", 32)
    kw.setdefault("layers", 1)
    kw.setdefault("heads", 2)
    kw.setdefault("ffn", 64)
    kw.setdefault("max_len", 48)
    kw.setdefault("dropout_rate", 0.0)
    return GPTLM(**kw)


@pytest.fixture(scope="module")
def model_params():
    model = tiny_gpt()
    x = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 8)),
                    jnp.int32)
    params = model.init(jax.random.key(0), x, train=False)["params"]
    return model, params


def _requests(n=6, seed=3, max_new=8, spread=0.5):
    rng = np.random.default_rng(seed)
    return [Request(rid=i,
                    prompt=rng.integers(0, 64, 6 + i % 4).astype(np.int32),
                    max_new_tokens=max_new, arrival_s=float(i) * spread)
            for i in range(n)]


@pytest.fixture(scope="module")
def oracle_tokens(model_params):
    """Per-request greedy streams from a single-replica batcher — THE
    bitwise reference every fleet schedule must reproduce (greedy decode
    is a pure function of (params, prompt), whatever the batching)."""
    model, params = model_params
    s = ContinuousBatcher(SlotKVCache(model, params, slots=2),
                          clock=VirtualClock()).run(_requests())
    return {r.rid: r.tokens for r in s["results"]}


def _check_parity(summary, oracle, n=6):
    assert summary["completed"] == n, summary["serve_fleet"]
    assert summary["serve_duplicate_emissions"] == 0
    got = {r.rid: r.tokens for r in summary["results"]}
    for rid, toks in oracle.items():
        assert got[rid] == toks, (rid, got[rid], toks)
    assert (summary["admitted"] + summary["shed_requests"]
            + summary["unserved_requests"]) == summary["offered"]


# ------------------------------------------------------------------ lease


def test_lease_trigger_programmatic():
    """trigger() flips the drain flag without a signal; the first reason
    is sticky until reset_trigger; a real preemption signal survives the
    reset (the process is still going away)."""
    lease = LeaseManager(signals=())
    assert lease.should_stop(0) is None
    lease.trigger("weight_swap")
    lease.trigger("later")              # first reason wins
    assert lease.should_stop(0) == "weight_swap"
    assert lease.report()["triggered"] == "weight_swap"
    lease.reset_trigger()
    assert lease.should_stop(0) is None
    with pytest.raises(ValueError, match="reason"):
        lease.trigger("")
    # a delivered SIGNAL is not cleared by reset_trigger
    lease.preempt_signal = 15
    lease.reset_trigger()
    assert lease.should_stop(0) == "signal:SIGTERM"


def test_lease_trigger_thread_safe():
    """Concurrent triggers settle on exactly one reason."""
    lease = LeaseManager(signals=())
    reasons = [f"r{i}" for i in range(16)]
    threads = [threading.Thread(target=lease.trigger, args=(r,))
               for r in reasons]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert lease.should_stop(0) in reasons


def test_lease_off_main_thread_degrade():
    """install() from a non-main thread degrades gracefully: no handler
    is armed (Python restricts signal.signal to the main thread), the
    step budget AND the programmatic trigger still work, and report()
    records that no handler was installed."""
    lease = LeaseManager(max_steps_per_lease=3)
    out = {}

    def worker():
        out["self"] = lease.install()
        out["installed"] = lease.installed

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert out["self"] is lease
    assert out["installed"] is False
    assert lease.report()["signal_handler_installed"] is False
    assert lease.should_stop(2) is None
    assert lease.should_stop(3) == "max_steps_per_lease:3"
    lease.trigger("drain")
    assert lease.should_stop(0) == "drain"
    lease.uninstall()   # no-op, must not raise


# ---------------------------------------------------------- fault injector


def test_fault_spec_parse_grammar():
    specs = FaultInjector.parse(
        "crash:replica=0,iter=3;stall:replica=1,iter=2,stall_s=0.5;"
        "nanlogits:replica=0,iter=4;crash:replica=1,prefill=2;"
        "crash:replica=0,verify=1;crash:replica=1,prob=0.1")
    kinds = [(s.kind, s.site) for s in specs]
    assert kinds == [("crash", "decode"), ("stall", "decode"),
                     ("nanlogits", "decode"), ("crash", "prefill"),
                     ("crash", "verify"), ("crash", "decode")]
    assert specs[0].at == 3 and specs[1].stall_s == 0.5
    assert specs[5].prob == 0.1 and specs[5].at == 0


def test_fault_spec_parse_rejects():
    for bad in ("boom:replica=0,iter=1",       # unknown kind
                "crash:iter=1",                # missing replica
                "crash:replica=0",             # no trigger
                "crash:replica=0,iter=1,prob=0.5",  # two triggers
                "crash:replica=0,wat=1",       # unknown key
                "crash:replica=0,iter=x",      # non-numeric
                "stall:replica=0,iter=1",      # stall without stall_s
                "nanlogits:replica=0,prefill=1",  # non-crash off-decode
                ""):
        with pytest.raises(ValueError):
            FaultInjector.parse(bad)
    with pytest.raises(ValueError, match="crash only"):
        FaultSpec(kind="stall", replica=0, site="verify", at=1,
                  stall_s=1.0)


def test_fault_injector_seeded_prob(model_params):
    """prob triggers draw from the injector's seeded rng: the same seed
    fires at the same site event, a different seed may not — determinism
    is what makes a chaos schedule a regression test."""
    model, params = model_params

    def fire_events(seed):
        inj = FaultInjector("crash:replica=0,prob=0.3", seed=seed)
        kv = SlotKVCache(model, params, slots=1)
        inj.arm(0, kv)
        kv.insert(np.arange(4, dtype=np.int32))
        fired_at = None
        for i in range(40):
            try:
                kv.advance()
            except InjectedFault:
                fired_at = i
                break
        return fired_at

    assert fire_events(7) == fire_events(7)


def test_fault_injector_one_shot(model_params):
    """An at=K spec fires exactly once: the recovered replica-path (or a
    later window over the same armed table) does not re-crash."""
    model, params = model_params
    inj = FaultInjector("crash:replica=0,iter=2", seed=0)
    kv = SlotKVCache(model, params, slots=1)
    inj.arm(0, kv)
    kv.insert(np.arange(4, dtype=np.int32))
    kv.advance()
    with pytest.raises(InjectedFault):
        kv.advance()
    assert len(inj.fired) == 1
    for _ in range(3):
        kv.advance()   # no re-fire
    assert len(inj.fired) == 1


# ---------------------------------------------------------------- journal


def test_journal_fence_exactly_once():
    """The assignment fence: emissions from a stale replica are counted
    and dropped; the current assignment's emissions deliver; a complete
    stream auto-finishes; delivered duplicates stay structurally zero."""
    reqs = [Request(rid=0, prompt=np.arange(4, dtype=np.int32),
                    max_new_tokens=3)]
    j = RequestJournal(reqs)
    j.assign(0, replica=0, t=0.0)
    assert j.emit(0, 0, 11, 1.0) == (True, False, None)
    # failover: replica 0 dies, rid 0 moves to replica 1
    j.mark_failed([0], t=2.0)
    j.assign(0, replica=1, t=2.0, retry=True)
    # zombie replica 0 wakes and keeps emitting: fenced, never delivered
    assert j.emit(0, 0, 12, 3.0)[0] is False
    assert j.fenced_emissions == 1
    accepted, done, recovery = j.emit(0, 1, 12, 4.0)
    assert accepted and not done
    assert recovery == pytest.approx(2.0)   # failure t=2 → first emit t=4
    accepted, done, _ = j.emit(0, 1, 13, 5.0)
    assert accepted and done                # 3 tokens == max_new
    # post-completion emissions (from anyone) are fenced
    assert j.emit(0, 1, 14, 6.0)[0] is False
    assert j.duplicate_emissions == 0
    e = j.entries[0]
    assert e.emitted == [11, 12, 13]
    assert e.completed_by == 1 and e.status == "done"
    assert j.requeues == 1 and j.requeued_rids == {0}


def test_journal_retry_request_resumes_prefix():
    """The retry request re-prefills prompt + emitted prefix with only
    the remaining budget — and a crash AFTER the last emission resumes
    nothing (the stream is already complete)."""
    reqs = [Request(rid=0, prompt=np.arange(4, dtype=np.int32),
                    max_new_tokens=3, eos_id=None),
            Request(rid=1, prompt=np.arange(4, dtype=np.int32),
                    max_new_tokens=2)]
    j = RequestJournal(reqs)
    j.assign(0, 0, 0.0)
    j.assign(1, 0, 0.0)
    j.emit(0, 0, 50, 1.0)
    retry = j.retry_request(0)
    assert retry.max_new_tokens == 2
    assert retry.prompt.tolist() == [0, 1, 2, 3, 50]
    assert retry.arrival_s == 0.0           # ORIGINAL arrival
    # rid 1: both tokens emitted → done via auto-complete; nothing to
    # resume even if a crash raced the finish bookkeeping
    j.emit(1, 0, 7, 1.0)
    j.emit(1, 0, 8, 2.0)
    assert j.retry_request(1) is None
    assert j.entries[1].status == "done"


def test_journal_least_loaded_routing():
    reqs = [Request(rid=i, prompt=np.arange(4, dtype=np.int32),
                    max_new_tokens=2) for i in range(5)]
    j = RequestJournal(reqs)
    picks = []
    for rid in range(5):
        r = j.least_loaded([0, 1])
        picks.append(r)
        j.assign(rid, r, 0.0)
    assert picks == [0, 1, 0, 1, 0]   # ties break to the lower id


# -------------------------------------------------- THE chaos acceptance


def test_chaos_kill_one_of_two_replicas_bitwise(model_params,
                                                oracle_tokens):
    """THE acceptance claim: on a seeded VirtualClock trace, killing 1 of
    2 replicas mid-run loses zero requests, duplicates zero emissions,
    and every result is bitwise equal to the unkilled single-replica
    oracle."""
    model, params = model_params
    inj = FaultInjector("crash:replica=0,iter=3", seed=0)
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                    clock=VirtualClock(), fault_injector=inj)
    s = rs.run(_requests())
    _check_parity(s, oracle_tokens)
    fl = s["serve_fleet"]
    assert fl["failovers"] == 1
    assert fl["failed_replicas"] == [0]
    assert fl["requeued_requests"] >= 1
    assert fl["retries"] == fl["requeued_requests"]
    assert fl["duplicate_emissions"] == 0
    assert inj.fired and inj.fired[0]["site"] == "decode"
    assert fl["faults_injected"] == inj.fired
    # failover recovery is measured for requests that had emitted tokens
    # before the crash (only those have a stalled reader to recover)
    if any(e["requeued"] for e in fl["failover_events"]):
        assert s["serve_failover_recovery_p95_s"] is None or \
            s["serve_failover_recovery_p95_s"] >= 0


def test_chaos_retry_ttft_charged_from_original_arrival(model_params):
    """A failed-over request's TTFT spans original arrival → first
    delivery on the SURVIVOR when the crash predates its first token:
    the retry never resets the clock (PR 7/11 accounting)."""
    model, params = model_params
    # one request, arrival 0; replica 0 crashes during ITS prefill, so
    # the first token is only ever delivered by replica 1 — after the
    # failover round-trip
    inj = FaultInjector("crash:replica=0,prefill=1", seed=0)
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 1),
                    clock=VirtualClock(), fault_injector=inj)
    s = rs.run([Request(rid=0, prompt=np.arange(5, dtype=np.int32),
                        max_new_tokens=4, arrival_s=0.0)])
    assert s["completed"] == 1
    r = s["results"][0]
    assert r.arrival_s == 0.0
    assert r.ttft_s == r.first_token_s - 0.0


def test_chaos_kill_during_prefill_chunk(model_params, oracle_tokens):
    """Kill-during-prefill-chunk (chunked prefill composed): the requeued
    request's emitted stream stays bitwise equal to the unkilled oracle —
    a dead mid-prefill admission re-prefills from scratch on the
    survivor."""
    model, params = model_params
    # chunking itself never changes tokens (PR 10 pin) — so the chunked
    # fleet is held to the same oracle
    inj = FaultInjector("crash:replica=0,prefill=2", seed=0)
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                    clock=VirtualClock(), prefill_chunk=3,
                    fault_injector=inj)
    s = rs.run(_requests())
    _check_parity(s, oracle_tokens)
    assert s["serve_fleet"]["failovers"] == 1
    assert inj.fired[0]["site"] == "prefill"


# round 20 fast-lane repair: the heaviest chaos sites ride the slow
# lane — four cheaper chaos-site tests stay fast in this suite
@pytest.mark.slow
def test_chaos_kill_between_verify_and_commit(model_params,
                                              oracle_tokens):
    """Kill-between-verify-and-commit (speculative decoding composed):
    the verify round's proposals die with the replica — nothing of the
    uncommitted block reaches the journal, and the requeued requests'
    streams stay bitwise equal to the non-speculative oracle."""
    model, params = model_params
    inj = FaultInjector("crash:replica=0,verify=2", seed=0)
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                    clock=VirtualClock(),
                    draft_kvs=build_replica_kvs(model, params, 2, 2),
                    draft_k=3, fault_injector=inj)
    s = rs.run(_requests())
    _check_parity(s, oracle_tokens)
    assert s["serve_fleet"]["failovers"] == 1
    assert inj.fired[0]["site"] == "verify"
    # self-draft: every surviving verify round accepts everything
    assert s["serve_accept_rate"] == 1.0
    led = s["speculative"]
    assert led["accepted_tokens"] + led["rejected_tokens"] \
        == led["proposed_tokens"]


# round 20 fast-lane repair: chaos × spec-decode composition variant
@pytest.mark.slow
def test_chaos_decode_site_kill_fires_under_spec_decode(model_params,
                                                        oracle_tokens):
    """`iter=K` must be able to kill a SPECULATIVE replica: its target
    iterations are verify rounds, not single-token advances — the
    injector counts them as decode iterations (a spec-decoding fleet
    was otherwise unkillable by the decode site)."""
    model, params = model_params
    inj = FaultInjector("crash:replica=0,iter=2", seed=0)
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                    clock=VirtualClock(),
                    draft_kvs=build_replica_kvs(model, params, 2, 2),
                    draft_k=2, fault_injector=inj)
    s = rs.run(_requests())
    _check_parity(s, oracle_tokens)
    assert s["serve_fleet"]["failovers"] == 1
    assert inj.fired and inj.fired[0]["site"] == "decode"


def test_chaos_nanlogits_detected_never_delivered(model_params,
                                                  oracle_tokens):
    """Nonfinite-logits corruption: the injector degrades the sampled
    token vector to out-of-range ids; the fleet's cheap host check fails
    the replica BEFORE anything reaches the journal — delivered streams
    stay bitwise clean."""
    model, params = model_params
    vocab = 64
    inj = FaultInjector("nanlogits:replica=0,iter=2", seed=0)
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                    clock=VirtualClock(), fault_injector=inj)
    s = rs.run(_requests())
    _check_parity(s, oracle_tokens)
    fl = s["serve_fleet"]
    assert fl["failovers"] == 1
    assert fl["failover_events"][0]["kind"] == "corruption"
    for r in s["results"]:
        assert all(0 <= t < vocab for t in r.tokens)


def test_chaos_threaded_wall_clock(model_params, oracle_tokens):
    """The same kill under real threads + WallClock: exactly-once and
    bitwise parity are schedule-independent claims."""
    model, params = model_params
    reqs = _requests()
    for r in reqs:
        r.arrival_s = 0.0
    inj = FaultInjector("crash:replica=0,iter=3", seed=0)
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                    fault_injector=inj)
    try:
        s = rs.run(reqs)
    finally:
        rs.close()
    _check_parity(s, oracle_tokens)
    assert s["serve_fleet"]["failovers"] == 1


# round 20 fast-lane repair: test_zombie_late_summary_not_absorbed
# keeps the fast zombie-fencing representative
@pytest.mark.slow
def test_stall_watchdog_fences_zombie(model_params, oracle_tokens):
    """A stalled replica is failed over by the supervisor's watchdog and
    FENCED, not killed: when the zombie wakes and keeps emitting, the
    journal rejects its stale emissions — zero duplicates delivered, all
    requests complete on the survivor, streams bitwise clean."""
    model, params = model_params
    reqs = _requests()
    for r in reqs:
        r.arrival_s = 0.0
    kvs = build_replica_kvs(model, params, 2, 2)
    for kv in kvs:
        # warm every program OUTSIDE the watchdog window: the watchdog
        # cannot tell a stall from a first-program XLA compile
        for plen in (6, 7, 8, 9):
            slot, _ = kv.insert(np.arange(plen, dtype=np.int32) % 64)
            kv.advance()
            kv.evict(slot)
    inj = FaultInjector("stall:replica=0,iter=2,stall_s=1.5", seed=0)
    rs = ReplicaSet(kvs, watchdog_timeout_s=0.3, fault_injector=inj)
    try:
        s = rs.run(reqs)
    finally:
        rs.close(timeout_s=15.0)
    _check_parity(s, oracle_tokens)
    fl = s["serve_fleet"]
    assert fl["watchdog_stalls"] >= 1
    assert fl["failover_events"][0]["kind"] == "watchdog_stall"
    # the zombie woke AFTER failover and its live slots kept decoding:
    # those emissions must have been fenced (close() waited it out)
    assert rs.journal.fenced_emissions > 0
    assert rs.journal.duplicate_emissions == 0


def test_retry_exhaustion_is_lost_not_hung(model_params):
    """Bounded retry: when every replica dies, pending requests go
    terminal `lost` (counted into unserved_requests) instead of hanging
    the fleet — conservation stays exact."""
    model, params = model_params
    inj = FaultInjector("crash:replica=0,iter=2;crash:replica=1,iter=2",
                        seed=0)
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                    clock=VirtualClock(), retry_limit=1,
                    fault_injector=inj)
    s = rs.run(_requests())
    fl = s["serve_fleet"]
    assert fl["failed_replicas"] == [0, 1]
    assert s["unserved_requests"] > 0
    assert fl["lost_requests"] == s["unserved_requests"]
    assert (s["admitted"] + s["shed_requests"]
            + s["unserved_requests"]) == s["offered"] == 6
    assert s["serve_duplicate_emissions"] == 0


# --------------------------------------------------------------- hot swap


def test_hot_swap_zero_downtime(model_params, oracle_tokens):
    """The hot-swap acceptance: all in-flight requests complete across
    the swap, swap_generations >= 1, and the fleet never dropped below
    N-1 admitting replicas (same params re-installed → tokens bitwise
    unchanged)."""
    model, params = model_params
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                    clock=VirtualClock())
    rs.schedule_swap(params, after_completions=2)
    s = rs.run(_requests())
    _check_parity(s, oracle_tokens)
    fl = s["serve_fleet"]
    assert fl["swap_generations"] == 1
    assert rs.swap_generations == 1
    assert fl["min_admitting_replicas"] >= 1   # never below N-1 of 2
    assert all(pr["generation"] == 1 for pr in fl["per_replica"])


def test_hot_swap_installs_new_params(model_params):
    """A swap really installs the new weights: requests admitted after
    the swap decode under the swapped params (different streams), while
    requests that finished before it used the old ones.  One replica —
    the drain interrupts its run mid-window, the swap lands while the
    later arrivals are still queued, and serving resumes on the same
    lease with the new weights."""
    model, params = model_params
    new_params = jax.tree.map(lambda t: t * 0.5, params)
    # two phases: rids 0-1 complete pre-swap, rids 2-3 arrive after
    reqs = [Request(rid=i, prompt=np.arange(5, dtype=np.int32),
                    max_new_tokens=6,
                    arrival_s=0.0 if i < 2 else 50.0)
            for i in range(4)]
    rs = ReplicaSet(build_replica_kvs(model, params, 1, 2),
                    clock=VirtualClock())
    rs.schedule_swap(new_params, after_completions=2)
    s = rs.run(reqs)
    assert s["completed"] == 4
    assert rs.swap_generations == 1
    toks = {r.rid: r.tokens for r in s["results"]}
    old = ContinuousBatcher(SlotKVCache(model, params, slots=1),
                            clock=VirtualClock()).run(
        [Request(rid=0, prompt=np.arange(5, dtype=np.int32),
                 max_new_tokens=6)])["results"][0].tokens
    new = ContinuousBatcher(SlotKVCache(model, new_params, slots=1),
                            clock=VirtualClock()).run(
        [Request(rid=0, prompt=np.arange(5, dtype=np.int32),
                 max_new_tokens=6)])["results"][0].tokens
    assert toks[0] == old and toks[1] == old
    assert toks[2] == new and toks[3] == new
    assert old != new   # the perturbation must actually matter


def test_swap_params_validation(model_params):
    """swap_params must be a compiled-program cache hit: a different
    tree structure or leaf shape is rejected, the table untouched."""
    model, params = model_params
    kv = SlotKVCache(model, params, slots=1)
    other = tiny_gpt(hidden=16, ffn=32)
    x = jnp.zeros((1, 4), jnp.int32)
    other_params = other.init(jax.random.key(0), x, train=False)["params"]
    with pytest.raises(ValueError):
        kv.swap_params(other_params)
    flat = jax.tree.leaves(params)
    assert jax.tree.leaves(kv.params)[0].shape == flat[0].shape
    kv.swap_params(jax.tree.map(lambda t: t, params))   # same-shape OK


# ------------------------------------------------------- fleet accounting


def test_fleet_merged_histograms_and_goodput(model_params):
    """Per-replica MetricsRegistry histograms merge into fleet totals
    (the PR 11 merge, applied to its designed purpose): the merged ttft
    count equals completed requests, and the serve_fleet section carries
    per-replica + merged goodput under the SLO."""
    from distributed_tensorflow_tpu.observability import SLOMonitor

    model, params = model_params
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                    clock=VirtualClock(),
                    slo=SLOMonitor(1e9, 1e9))   # everything is goodput
    s = rs.run(_requests())
    assert s["completed"] == 6
    assert s["histograms"]["ttft"]["count"] == 6
    fl = s["serve_fleet"]
    assert s["slo"]["good_requests"] == 6
    assert s["serve_goodput_under_slo"] > 0
    per = {pr["replica"]: pr for pr in fl["per_replica"]}
    assert sum(pr["completed"] for pr in per.values()) == 6
    assert fl["merged_goodput_under_slo"] == pytest.approx(
        sum(pr["goodput_requests_per_sec"] or 0 for pr in per.values()))
    # both replicas actually served (least-loaded routing spreads a
    # staggered trace)
    assert all(pr["completed"] > 0 for pr in per.values())


def test_fleet_serve_section_and_flatten(model_params):
    """The fleet summary rides serve_section/load_report unchanged: the
    per-chip keys derive, serve_fleet survives, and the new gate keys
    flatten to the top level for `analyze diff`."""
    import json

    from distributed_tensorflow_tpu.observability import serve_section
    from distributed_tensorflow_tpu.observability.analyze import (
        _DIFF_METRICS, load_report)

    model, params = model_params
    inj = FaultInjector("crash:replica=0,iter=3", seed=0)
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                    clock=VirtualClock(), fault_injector=inj)
    sec = serve_section(rs.run(_requests()), 8)
    assert "results" not in sec
    assert sec["serve_requests_per_sec_per_chip"] == pytest.approx(
        sec["serve_requests_per_sec"] / 8)
    assert sec["serve_fleet"]["failovers"] == 1
    json.dumps(sec)   # the section must stay JSON
    directions = dict(_DIFF_METRICS)
    assert directions["serve_failover_recovery_p95_s"] == "lower"
    assert directions["serve_duplicate_emissions"] == "lower"
    flat = load_report_from_dict({"serve": sec}, load_report)
    assert flat["serve_duplicate_emissions"] == 0
    assert flat["serve_failover_recovery_p95_s"] is not None


def load_report_from_dict(obj, load_report):
    import json
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump(obj, f)
        path = f.name
    return load_report(path)


def test_waterfall_requeue_rows(model_params, tmp_path):
    """analyze serve renders failover: the retried request's new span
    segment carries its attempt number + original arrival, the requeue
    hops ride the output, and the text renderer draws them."""
    from distributed_tensorflow_tpu.observability import Tracer
    from distributed_tensorflow_tpu.observability.analyze import (
        read_jsonl, render_waterfall_text, serve_waterfall)

    model, params = model_params
    trace = tmp_path / "fleet_trace.jsonl"
    tracer = Tracer(path=str(trace))
    inj = FaultInjector("crash:replica=0,iter=3", seed=0)
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                    clock=VirtualClock(), tracer=tracer,
                    fault_injector=inj)
    s = rs.run(_requests())
    tracer.close()
    wf = serve_waterfall(read_jsonl(str(trace)))
    assert wf["requeue_n"] == s["serve_fleet"]["retries"] > 0
    hops = {q["rid"] for q in wf["requeues"]}
    retried_rows = [r for r in wf["requests"] if r["attempt"] > 1]
    assert retried_rows, wf["requests"]
    for row in retried_rows:
        assert row["rid"] in hops
        # keyed to the ORIGINAL arrival (the retry accounting rule)
        assert row["original_arrival_s"] == pytest.approx(
            row["rid"] * 0.5)
    text = render_waterfall_text(wf)
    assert ">" in text and "requeue r0→r1" in text
    assert "retry#2" in text
    # every hop records where the stream stood when it moved
    for q in wf["requeues"]:
        assert q["emitted"] >= 0 and q["reason"]


# ------------------------------------------------------- harness surface


def _lm_fn(batch_size, type="train", **kw):
    from distributed_tensorflow_tpu.data.loaders import load_lm_dataset

    return load_lm_dataset(seq_len=16, vocab_size=64, n_train=64,
                           n_test=32, split=type)


def test_harness_fleet_e2e_fsdp():
    """--serve-replicas 2 + --serve-fault-spec through the harness: the
    serve section carries serve_fleet + the gate keys, every request
    completes exactly once, and the exit policy flag is clean."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    summary = run(ExperimentConfig(
        engine="fsdp", model="gpt", dataset="lm_synth", dataset_fn=_lm_fn,
        n_devices=8, batch_size=4, log_every=0,
        model_args={"hidden": 32, "layers": 1, "heads": 2, "ffn": 64,
                    "max_len": 32},
        serve_requests=8, serve_slots=2, serve_max_new=4,
        serve_prompt_len=4, serve_replicas=2,
        serve_fault_spec="crash:replica=0,iter=2"))
    sec = summary["serve"]
    assert sec == summary["run_report"]["serve"]
    assert sec["mode"] == "fleet"
    assert sec["replicas"] == 2
    assert sec["completed"] == 8
    assert sec["serve_duplicate_emissions"] == 0
    assert sec["serve_fleet"]["failovers"] == 1
    assert sec["serve_fleet"]["faults_injected"]
    assert summary["serve_exit_policy"] == 0
    assert sec["serve_requests_per_sec_per_chip"] > 0
    assert sec["serve_goodput_under_slo_per_chip"] is not None


@pytest.mark.slow    # round 20 fast-lane repair: the e2e
# representative is test_harness_fleet_e2e_fsdp
def test_harness_fleet_hot_swap_e2e_fsdp():
    """--serve-hot-swap: the drill drains + swaps replica-by-replica —
    swap_generations >= 1, never below N-1 admitting, clean policy."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    summary = run(ExperimentConfig(
        engine="fsdp", model="gpt", dataset="lm_synth", dataset_fn=_lm_fn,
        n_devices=8, batch_size=4, log_every=0,
        model_args={"hidden": 32, "layers": 1, "heads": 2, "ffn": 64,
                    "max_len": 32},
        serve_requests=8, serve_slots=2, serve_max_new=4,
        serve_prompt_len=4, serve_replicas=2, serve_hot_swap=True))
    sec = summary["serve"]
    fl = sec["serve_fleet"]
    assert sec["completed"] == 8
    assert fl["swap_generations"] >= 1
    assert fl["min_admitting_replicas"] >= 1
    assert summary["serve_exit_policy"] == 0


@pytest.mark.slow    # round 20 fast-lane repair (see above)
def test_harness_degraded_window_flags_exit_policy(tmp_path):
    """A serve window that loses requests (single replica, killed, no
    survivor to fail over to) must surface it: serve_exit_policy = 1 and
    a structured serve_warning event in the result stream — CI gates on
    the flag instead of excavating the summary."""
    import json

    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    result_path = tmp_path / "results.jsonl"
    summary = run(ExperimentConfig(
        engine="fsdp", model="gpt", dataset="lm_synth", dataset_fn=_lm_fn,
        n_devices=8, batch_size=4, log_every=0,
        model_args={"hidden": 32, "layers": 1, "heads": 2, "ffn": 64,
                    "max_len": 32},
        result_path=str(result_path),
        serve_requests=6, serve_slots=2, serve_max_new=4,
        serve_prompt_len=4, serve_replicas=1,
        serve_fault_spec="crash:replica=0,iter=2"))
    sec = summary["serve"]
    assert sec["unserved_requests"] > 0
    assert summary["serve_exit_policy"] == 1
    events = [json.loads(line) for line in
              result_path.read_text().splitlines()]
    warnings = [e for e in events if e["event"] == "serve_warning"]
    assert warnings and any("unserved" in r for r in
                            warnings[0]["reasons"])
    # conservation still exact on the degraded window
    assert (sec["admitted"] + sec["shed_requests"]
            + sec["unserved_requests"]) == sec["offered"] == 6


def test_harness_fleet_validation_pre_train():
    """Bad fleet flags fail BEFORE training, like every other serve
    flag."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    base = dict(engine="fsdp", model="gpt", dataset="lm_synth",
                dataset_fn=_lm_fn, n_devices=8, batch_size=4,
                log_every=0,
                model_args={"hidden": 32, "layers": 1, "heads": 2,
                            "ffn": 64, "max_len": 32},
                serve_requests=4, serve_slots=2, serve_max_new=4,
                serve_prompt_len=4)
    with pytest.raises(ValueError, match="serve-replicas"):
        run(ExperimentConfig(**base, serve_replicas=0))
    with pytest.raises(ValueError, match="fault-spec"):
        run(ExperimentConfig(**base, serve_fault_spec="boom:replica=0"))
    with pytest.raises(ValueError, match="replica 3"):
        run(ExperimentConfig(**base, serve_replicas=2,
                             serve_fault_spec="crash:replica=3,iter=1"))
    with pytest.raises(ValueError, match="serve-watchdog"):
        run(ExperimentConfig(**base, serve_watchdog_s=-1.0))


def test_cli_fleet_flags_parse():
    from distributed_tensorflow_tpu.cli import build_parser

    args = build_parser().parse_args(
        ["--serve", "8", "--serve-replicas", "2",
         "--serve-fault-spec", "crash:replica=0,iter=3",
         "--serve-hot-swap", "--serve-watchdog", "5.5"])
    assert args.serve_replicas == 2
    assert args.serve_fault_spec == "crash:replica=0,iter=3"
    assert args.serve_hot_swap is True
    assert args.serve_watchdog == 5.5


def test_zombie_late_summary_not_absorbed(model_params):
    """A watchdog-failed replica's run eventually returns — its late
    summary must NOT fold into the fleet ledgers, and its shed report
    must not terminal-ize a request a survivor now owns (the same fence
    as emission, applied to accounting)."""
    model, params = model_params
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                    clock=VirtualClock())
    rs.run(_requests())
    r0 = rs.replicas[0]
    rs.journal = RequestJournal([
        Request(rid=0, prompt=np.arange(4, dtype=np.int32),
                max_new_tokens=4)])
    rs.journal.assign(0, 1, 0.0)   # the SURVIVOR owns rid 0 now
    r0.state = "failed"
    fake = {"shed_rids": [0], "shed_requests": 1,
            "decode_iterations": 99, "preempted": None}
    r0.batcher.run = lambda queue, on_token=None: fake
    before = dict(rs._sums)
    rs._serve_once(r0)
    assert rs._sums == before, "zombie summary was absorbed"
    assert rs.journal.entries[0].status == "pending"
    # the fenced finalize itself: the dead replica's shed claim is a
    # no-op on a request assigned elsewhere
    rs.journal.finalize_if_assigned(0, 0, "shed")
    assert rs.journal.entries[0].status == "pending"
    rs.journal.finalize_if_assigned(0, 1, "shed")
    assert rs.journal.entries[0].status == "shed"


def test_waterfall_attempts_not_fooled_by_multi_window(model_params,
                                                       tmp_path):
    """Bench traces hold several windows reusing rids 0..n−1: same-rid
    rows from LATER windows are not retries — attempt numbering anchors
    on requeue hops, not bare rid repetition."""
    from distributed_tensorflow_tpu.observability import Tracer
    from distributed_tensorflow_tpu.observability.analyze import (
        read_jsonl, serve_waterfall)

    model, params = model_params
    trace = tmp_path / "two_windows.jsonl"
    tracer = Tracer(path=str(trace))
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                    clock=VirtualClock(), tracer=tracer)
    rs.run(_requests())
    rs.run(_requests())    # same rids, second window, zero failovers
    tracer.close()
    wf = serve_waterfall(read_jsonl(str(trace)))
    assert wf["requests_n"] == 12 and wf["requeue_n"] == 0
    assert all(r["attempt"] == 1 for r in wf["requests"]), \
        [r for r in wf["requests"] if r["attempt"] > 1]


# round 20 fast-lane repair: reuse variant of the fleet run path the
# fast e2e test already drives once
@pytest.mark.slow
def test_replica_set_run_reuse(model_params, oracle_tokens):
    """A ReplicaSet serves window after window (the bench shape): the
    second run()'s journal is fresh, surviving replicas serve again, and
    parity holds both times — including under real threads, where the
    first run's shutdown left stop events set."""
    model, params = model_params
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                    clock=VirtualClock())
    for _ in range(2):
        s = rs.run(_requests())
        _check_parity(s, oracle_tokens)
    rs2 = ReplicaSet(build_replica_kvs(model, params, 2, 2))
    try:
        for _ in range(2):
            reqs = _requests()
            for r in reqs:
                r.arrival_s = 0.0
            s = rs2.run(reqs)
            _check_parity(s, oracle_tokens)
    finally:
        rs2.close()


def test_replica_set_validation(model_params):
    model, params = model_params
    with pytest.raises(ValueError, match="at least one"):
        ReplicaSet([])
    kvs = build_replica_kvs(model, params, 2, 2)
    with pytest.raises(ValueError, match="1:1"):
        ReplicaSet(kvs, draft_kvs=build_replica_kvs(model, params, 1, 2))
    with pytest.raises(ValueError, match="retry_limit"):
        ReplicaSet(kvs, retry_limit=-1)
    with pytest.raises(RuntimeError, match="already in flight"):
        rs = ReplicaSet(kvs, clock=VirtualClock())
        rs.schedule_swap(params)
        rs.schedule_swap(params)
