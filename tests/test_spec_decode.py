"""Raw decode speed (ISSUE 14): greedy-exact speculative decoding +
int8-quantized KV cache.

Speculative decoding (draft-k → verify-1) must be BITWISE invisible in
the token stream: greedy acceptance emits exactly the tokens
non-speculative decode would have, whatever the draft proposes — the
draft only changes how many target iterations it takes.  The int8 table
is tolerance-based instead: greedy-token AGREEMENT with the bf16/f32
oracle on the test workload, plus the memory claim
(``serve_kv_bytes_per_slot``).  Everything here runs on this container —
plain GSPMD jit + host Python, like tests/test_serving.py.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.models.gpt import GPTLM, generate
from distributed_tensorflow_tpu.observability import SLOMonitor
from distributed_tensorflow_tpu.serving import (
    ContinuousBatcher, Request, SlotKVCache, SlotOverflow, VirtualClock)


def tiny_gpt(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("hidden", 32)
    kw.setdefault("layers", 2)
    kw.setdefault("heads", 2)
    kw.setdefault("ffn", 64)
    kw.setdefault("max_len", 32)
    kw.setdefault("dropout_rate", 0.0)
    return GPTLM(**kw)


@pytest.fixture(scope="module")
def model_params():
    model = tiny_gpt()
    x = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 8)),
                    jnp.int32)
    params = model.init(jax.random.key(0), x, train=False)["params"]
    return model, params


@pytest.fixture(scope="module")
def draft_params():
    """A DIFFERENT (smaller, independently seeded) draft: proposals
    disagree with the target often, exercising rejection/rollback."""
    model = tiny_gpt(hidden=16, layers=1, ffn=32)
    x = jnp.asarray(np.random.default_rng(1).integers(0, 64, (2, 8)),
                    jnp.int32)
    params = model.init(jax.random.key(7), x, train=False)["params"]
    return model, params


def _prompts(n, seed=0, lo=3, hi=9):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 64, int(rng.integers(lo, hi))).astype(np.int32)
            for _ in range(n)]


def _oracle(model, params, prompt, n_new):
    return np.asarray(generate(model, params, prompt[None, :], n_new,
                               greedy=True))[0]


def _staggered(prompts, news, arrivals):
    return [Request(rid=i, prompt=p, max_new_tokens=news[i],
                    arrival_s=arrivals[i]) for i, p in enumerate(prompts)]


# ------------------------------------------------------ kv-cache verify units


def test_verify_block_matches_sequential_argmaxes(model_params):
    """The verify program's core contract: feeding the committed pending
    token + the ORACLE's own continuation returns exactly the oracle's
    next tokens at every position — the (slots, k+1) batched step scores
    like k+1 sequential single-token steps, bitwise."""
    model, params = model_params
    kv = SlotKVCache(model, params, slots=2)
    p = _prompts(1, seed=3, lo=5, hi=6)[0]
    orc = _oracle(model, params, p, 6)
    slot, first = kv.insert(p)
    assert first == orc[0]
    block = np.zeros((2, 4), np.int32)
    block[slot] = orc[:4]                   # pending + 3 correct "drafts"
    g = kv.verify_block(block)
    np.testing.assert_array_equal(g[slot], orc[1:5])
    # committing all 4 then decoding continues the oracle stream
    kv.commit_block(slot, 4, int(g[slot, 3]))
    assert int(kv.advance()[slot]) == orc[5]


def test_verify_rollback_is_length_bookkeeping_only(model_params):
    """Rejected draft positions are invalidated by LENGTH bookkeeping
    alone — no KV rewrite: after a verify whose tail is junk, committing
    only the accepted prefix leaves the (stale) buffer contents in place,
    and the continuation still matches the oracle because validity is
    length-driven."""
    model, params = model_params
    kv = SlotKVCache(model, params, slots=1)
    p = _prompts(1, seed=5, lo=4, hi=5)[0]
    orc = _oracle(model, params, p, 6)
    slot, first = kv.insert(p)
    base = int(kv.lengths[slot])
    # pending + 1 correct draft + 2 JUNK drafts
    block = np.asarray([[orc[0], orc[1], (orc[2] + 1) % 64,
                         (orc[3] + 5) % 64]], np.int32)
    g = kv.verify_block(block)
    assert int(g[0, 0]) == orc[1]           # target argmax after pending
    stale = jax.tree.map(lambda t: np.asarray(t), kv.cache)
    # accept a=1 draft token + the target's own token at the mismatch
    kv.commit_block(slot, 2, int(g[0, 1]))
    assert int(kv.lengths[slot]) == base + 2
    assert int(kv.tokens[slot]) == orc[2]   # g[1] conditioned on orc[:2]
    # rollback touched NO device buffer — byte-identical cache
    for a, b in zip(jax.tree.leaves(stale),
                    jax.tree.leaves(jax.tree.map(
                        lambda t: np.asarray(t), kv.cache))):
        np.testing.assert_array_equal(a, b)
    # the rejected junk at positions base+2.. is invisible: decode
    # continues the oracle stream right over it
    got = [int(kv.advance()[slot]) for _ in range(3)]
    np.testing.assert_array_equal(got, orc[3:6])


def test_rewind_guards(model_params):
    model, params = model_params
    kv = SlotKVCache(model, params, slots=1)
    p = _prompts(1, seed=6)[0]
    slot, _ = kv.insert(p)
    with pytest.raises(ValueError, match="extend"):
        kv.rewind(slot, int(kv.lengths[slot]) + 1, 0)
    kv.rewind(slot, int(kv.lengths[slot]) - 1, 3)
    assert int(kv.tokens[slot]) == 3
    kv.evict(slot)
    with pytest.raises(RuntimeError, match="not active"):
        kv.rewind(slot, 0, 0)
    with pytest.raises(RuntimeError, match="not active"):
        kv.commit_block(slot, 1, 0)


def test_verify_block_guards(model_params):
    model, params = model_params
    kv = SlotKVCache(model, params, slots=2)
    with pytest.raises(ValueError, match="slots, width"):
        kv.verify_block(np.zeros((3, 2), np.int32))
    kv_t = SlotKVCache(model, params, slots=2, greedy=False)
    with pytest.raises(ValueError, match="greedy"):
        kv_t.verify_block(np.zeros((2, 2), np.int32))
    # capacity: a near-full slot rejects an over-wide block
    kv.insert(np.zeros(model.max_len - 2, np.int32))
    with pytest.raises(SlotOverflow, match="verify width"):
        kv.verify_block(np.zeros((2, 3), np.int32))


def test_masked_advance_only_moves_masked_slots(model_params):
    """advance(only=mask) — the draft catch-up step — advances exactly
    the masked slots' lengths/tokens; unmasked active slots keep both,
    and their streams stay oracle-exact afterwards."""
    model, params = model_params
    kv = SlotKVCache(model, params, slots=2)
    ps = _prompts(2, seed=8)
    s0, f0 = kv.insert(ps[0])
    s1, f1 = kv.insert(ps[1])
    len1, tok1 = int(kv.lengths[s1]), int(kv.tokens[s1])
    mask = np.zeros(2, np.bool_)
    mask[s0] = True
    toks = kv.advance(only=mask)
    assert int(kv.lengths[s0]) == len(ps[0]) + 1
    assert int(kv.lengths[s1]) == len1           # untouched
    assert int(kv.tokens[s1]) == tok1
    got0 = [f0, int(toks[s0])]
    # both slots keep decoding correctly after the partial step
    full = kv.advance()
    got0.append(int(full[s0]))
    got1 = [f1, int(full[s1])]
    np.testing.assert_array_equal(_oracle(model, params, ps[0], 3), got0)
    np.testing.assert_array_equal(_oracle(model, params, ps[1], 2), got1)


# ------------------------------------------------------- scheduler (tentpole)


def test_spec_decode_bitwise_and_fewer_iterations(model_params):
    """THE acceptance claim: on the staggered-arrival test workload,
    speculative decode (draft = the target itself, the deterministic
    always-accept configuration) emits BITWISE-identical greedy tokens
    to the non-speculative run and completes in STRICTLY fewer decode
    iterations (program-relative count, BASELINE prefill-accounting
    rule: both runs admit identically)."""
    model, params = model_params
    prompts = _prompts(5, seed=4)
    news = [6, 3, 8, 2, 5]
    arrivals = [0.0, 0.0, 1.0, 4.0, 6.0]

    kv0 = SlotKVCache(model, params, slots=2)
    base = ContinuousBatcher(kv0, clock=VirtualClock()).run(
        _staggered(prompts, news, arrivals))
    kv = SlotKVCache(model, params, slots=2)
    spec = ContinuousBatcher(
        kv, clock=VirtualClock(),
        draft_kv=SlotKVCache(model, params, slots=2), draft_k=3).run(
        _staggered(prompts, news, arrivals))

    assert spec["completed"] == base["completed"] == 5
    for i, p in enumerate(prompts):
        orc = _oracle(model, params, p, news[i])
        np.testing.assert_array_equal(
            orc, np.asarray(spec["results"][i].tokens), str(i))
        np.testing.assert_array_equal(
            np.asarray(base["results"][i].tokens),
            np.asarray(spec["results"][i].tokens), str(i))
    assert spec["decode_iterations"] < base["decode_iterations"], \
        (spec["decode_iterations"], base["decode_iterations"])
    assert spec["serve_accept_rate"] == 1.0   # draft == target, greedy
    assert base["serve_accept_rate"] is None
    assert kv.free_slots == [0, 1]


# round 20 fast-lane repair: robustness variant —
# test_spec_decode_bitwise_and_fewer_iterations keeps the fast core pin
@pytest.mark.slow
def test_spec_decode_random_draft_still_bitwise(model_params,
                                                draft_params):
    """Parity holds for ANY draft: a small independently-initialized
    draft proposes mostly-rejected tokens, yet the emitted stream is
    bitwise the oracle's — rejection costs only iterations."""
    model, params = model_params
    dmodel, dparams = draft_params
    prompts = _prompts(5, seed=4)
    news = [6, 3, 8, 2, 5]
    arrivals = [0.0, 0.0, 1.0, 4.0, 6.0]
    res = ContinuousBatcher(
        SlotKVCache(model, params, slots=2), clock=VirtualClock(),
        draft_kv=SlotKVCache(dmodel, dparams, slots=2), draft_k=2).run(
        _staggered(prompts, news, arrivals))
    for i, p in enumerate(prompts):
        np.testing.assert_array_equal(
            _oracle(model, params, p, news[i]),
            np.asarray(res["results"][i].tokens), str(i))
    assert 0.0 <= res["serve_accept_rate"] <= 1.0


def test_accept_accounting_conservation(model_params, draft_params):
    """accepted + rejected == proposed, exactly — per request AND in the
    run ledger; tokens/sec still counts emitted tokens only."""
    model, params = model_params
    dmodel, dparams = draft_params
    prompts = _prompts(4, seed=9)
    res = ContinuousBatcher(
        SlotKVCache(model, params, slots=2), clock=VirtualClock(),
        draft_kv=SlotKVCache(dmodel, dparams, slots=2), draft_k=3).run(
        [Request(rid=i, prompt=p, max_new_tokens=5, arrival_s=0.0)
         for i, p in enumerate(prompts)])
    spec = res["speculative"]
    assert spec["proposed_tokens"] > 0
    assert (spec["accepted_tokens"] + spec["rejected_tokens"]
            == spec["proposed_tokens"])
    assert spec["proposed_tokens"] == sum(
        r.proposed_tokens for r in res["results"])
    assert spec["accepted_tokens"] == sum(
        r.accepted_tokens for r in res["results"])
    assert res["serve_accept_rate"] == pytest.approx(
        spec["accepted_tokens"] / spec["proposed_tokens"])
    # emitted-token accounting unchanged: every request got exactly its
    # budget, and the rate divides emitted tokens by elapsed
    assert res["tokens_generated"] == 4 * 5
    assert res["serve_tokens_per_sec"] == pytest.approx(
        res["tokens_generated"] / res["elapsed_s"])
    assert spec["draft_iterations"] > 0


# round 20 fast-lane repair: four-feature composition variant
@pytest.mark.slow
def test_spec_composes_with_chunk_prefix_cap_slo(model_params):
    """Spec decode under the WHOLE round-10/13 surface at once — chunked
    prefill, prefix pool, bounded admission, SLO monitor: completed
    requests are oracle-exact, shed conservation stays exact, the pool
    reports hits."""
    model, params = model_params
    rng = np.random.default_rng(11)
    shared = rng.integers(0, 64, 8).astype(np.int32)
    prompts = [np.concatenate([shared,
                               rng.integers(0, 64, 4).astype(np.int32)])
               for _ in range(6)]
    kv = SlotKVCache(model, params, slots=2, prefix_cache_blocks=16,
                     prefix_block=4)
    res = ContinuousBatcher(
        kv, clock=VirtualClock(), prefill_chunk=4,
        slo=SLOMonitor(100.0, 100.0), queue_cap=3,
        draft_kv=SlotKVCache(model, params, slots=2), draft_k=2).run(
        [Request(rid=i, prompt=p, max_new_tokens=4, arrival_s=float(i))
         for i, p in enumerate(prompts)])
    assert (res["admitted"] + res["shed_requests"]
            + res["unserved_requests"]) == res["offered"] == 6
    assert res["serve_prefix_cache_hit_rate"] > 0
    assert res["prefill_chunks"] > 0
    assert res["serve_goodput_under_slo"] is not None
    served = {r.rid: r for r in res["results"]}
    for rid, r in served.items():
        np.testing.assert_array_equal(
            _oracle(model, params, prompts[rid], 4),
            np.asarray(r.tokens), str(rid))
    assert kv.free_slots == [0, 1]


def test_spec_decode_respects_eos(model_params):
    """An EOS landing mid-verify-block truncates the stream exactly
    where non-speculative decode would stop."""
    model, params = model_params
    p = _prompts(1, seed=12)[0]
    orc = _oracle(model, params, p, 8)
    eos = int(orc[3])                       # stop after the 4th token

    def run(draft):
        return ContinuousBatcher(
            SlotKVCache(model, params, slots=1), clock=VirtualClock(),
            draft_kv=draft, draft_k=4).run(
            [Request(rid=0, prompt=p, max_new_tokens=8, arrival_s=0.0,
                     eos_id=eos)])

    spec = run(SlotKVCache(model, params, slots=1))
    base = ContinuousBatcher(
        SlotKVCache(model, params, slots=1), clock=VirtualClock()).run(
        [Request(rid=0, prompt=p, max_new_tokens=8, arrival_s=0.0,
                 eos_id=eos)])
    np.testing.assert_array_equal(np.asarray(base["results"][0].tokens),
                                  np.asarray(spec["results"][0].tokens))
    assert spec["results"][0].tokens[-1] == eos
    assert len(spec["results"][0].tokens) == 4


def test_spec_itl_per_emitted_token(model_params):
    """ITL gaps are attributed per EMITTED token: a verify round's batch
    delivers at one instant — first token of the round carries the gap,
    batch-mates land at 0 — so the gaps still sum to decode wall time
    (the SLO math stays honest)."""
    model, params = model_params
    p = _prompts(1, seed=13)[0]
    res = ContinuousBatcher(
        SlotKVCache(model, params, slots=1), clock=VirtualClock(),
        draft_kv=SlotKVCache(model, params, slots=1), draft_k=3).run(
        [Request(rid=0, prompt=p, max_new_tokens=8, arrival_s=0.0)])
    r = res["results"][0]
    assert len(r.itl_s) == len(r.tokens) - 1
    assert sum(r.itl_s) == pytest.approx(r.decode_s)
    assert 0.0 in r.itl_s                   # some tokens were batch-mates


def test_flags_off_parity_pin(model_params):
    """With spec decode (and every other serving flag) OFF, the compiled
    program set and the serve-section vocabulary are the PR 11 ones:
    verify family empty, draft section None, accept rate None — and the
    tokens are the oracle's (the byte-identity pin for round 14)."""
    model, params = model_params
    prompts = _prompts(3, seed=4)
    kv = SlotKVCache(model, params, slots=2)
    res = ContinuousBatcher(kv, clock=VirtualClock()).run(
        [Request(rid=i, prompt=p, max_new_tokens=4, arrival_s=0.0)
         for i, p in enumerate(prompts)])
    assert kv.compiled_programs()["verify_widths"] == 0
    assert kv.compiled_programs()["prefill_chunk_buckets"] == 0
    assert kv.compiled_programs()["prefix_block_ops"] == 0
    assert res["serve_accept_rate"] is None
    assert res["speculative"] is None
    assert res["serve_kv_dtype"] == "float32"
    assert res["serve_kv_bytes_per_slot"] == kv.kv_bytes_per_slot()
    for i, p in enumerate(prompts):
        np.testing.assert_array_equal(
            _oracle(model, params, p, 4),
            np.asarray(res["results"][i].tokens), str(i))


def test_draft_validation(model_params):
    model, params = model_params
    kv = SlotKVCache(model, params, slots=2)
    with pytest.raises(ValueError, match="draft_k"):
        ContinuousBatcher(kv, draft_kv=SlotKVCache(model, params, 2),
                          draft_k=0)
    with pytest.raises(ValueError, match="match the"):
        ContinuousBatcher(kv, draft_kv=SlotKVCache(model, params, 4))
    with pytest.raises(ValueError, match="greedy"):
        ContinuousBatcher(
            kv, draft_kv=SlotKVCache(model, params, 2, greedy=False))


def test_spec_failure_cleanup_frees_draft_slots(model_params):
    """The mid-run-failure guard extends to the draft table: both tables
    come back empty and serve the next window."""
    model, params = model_params
    kv = SlotKVCache(model, params, slots=2)
    draft = SlotKVCache(model, params, slots=2)
    calls = [0]

    def boom(rid, tok):
        calls[0] += 1
        if calls[0] >= 3:
            raise RuntimeError("sink died")

    reqs = [Request(rid=i, prompt=p, max_new_tokens=4, arrival_s=0.0)
            for i, p in enumerate(_prompts(2, seed=7))]
    with pytest.raises(RuntimeError, match="sink died"):
        ContinuousBatcher(kv, clock=VirtualClock(), draft_kv=draft,
                          draft_k=2).run(reqs, on_token=boom)
    assert kv.free_slots == [0, 1]
    assert draft.free_slots == [0, 1]
    res = ContinuousBatcher(kv, clock=VirtualClock(), draft_kv=draft,
                            draft_k=2).run(reqs)
    assert res["completed"] == 2


# ------------------------------------------------------------- int8 KV cache


def test_int8_kv_bytes_and_capacity(model_params):
    """The memory claim: the int8 payload is exactly half of bf16's (a
    quarter of f32's); with the per-written-vector f32 scales included,
    serve_kv_bytes_per_slot lands at (1 + 4/head_dim)/2 of bf16 — and
    DOUBLING the slots at int8 costs no more than (1 + 8/head_dim)× the
    bf16 table, the doubled-capacity check."""
    model, params = model_params
    head_dim = model.hidden // model.heads
    kv8 = SlotKVCache(model, params, slots=4, kv_dtype="int8")
    kv16 = SlotKVCache(model, params, slots=4, kv_dtype=jnp.bfloat16)
    kv32 = SlotKVCache(model, params, slots=4)
    assert kv8.kv_dtype == "int8" and kv8.quantized

    def payload(kv):
        return sum(leaf.size * jnp.dtype(leaf.dtype).itemsize
                   for leaf in jax.tree.leaves(kv.cache)
                   if jnp.dtype(leaf.dtype) == jnp.int8
                   or jnp.issubdtype(leaf.dtype, jnp.floating))

    int8_payload = sum(leaf.size for leaf in jax.tree.leaves(kv8.cache)
                       if jnp.dtype(leaf.dtype) == jnp.int8)
    assert int8_payload * 2 == payload(kv16)
    assert int8_payload * 4 == payload(kv32)
    b8, b16 = kv8.kv_bytes_per_slot(), kv16.kv_bytes_per_slot()
    assert b8 == pytest.approx(b16 * (1 + 4 / head_dim) / 2)
    # doubled slots at int8 vs the bf16 table: within the scale overhead
    kv8x2 = SlotKVCache(model, params, slots=8, kv_dtype="int8")
    assert (kv8x2.kv_bytes_per_slot() * 8
            <= kv16.kv_bytes_per_slot() * 4 * (1 + 8 / head_dim))


def test_int8_kv_matches_oracle_greedy(model_params):
    """The tolerance-based acceptance: int8 storage agrees with the
    full-precision oracle's greedy tokens on the serving test workload,
    through staggered-age slots."""
    model, params = model_params
    kv = SlotKVCache(model, params, slots=4, kv_dtype="int8")
    prompts = _prompts(3, seed=11)
    firsts = {}

    def collect(toks):
        for _, (slot, got) in firsts.items():
            got.append(int(toks[slot]))

    for i, p in enumerate(prompts):
        slot, first = kv.insert(p)
        firsts[i] = (slot, [first])
        collect(kv.advance())
    for _ in range(3):
        collect(kv.advance())
    for i, p in enumerate(prompts):
        n = len(firsts[i][1])
        np.testing.assert_array_equal(_oracle(model, params, p, n),
                                      np.asarray(firsts[i][1]), str(i))


def test_int8_kv_full_scheduler_workload(model_params):
    """int8 through the batcher on the staggered workload: greedy tokens
    agree with the f32 run, the summary carries dtype + bytes."""
    model, params = model_params
    prompts = _prompts(5, seed=4)
    news = [6, 3, 8, 2, 5]
    arrivals = [0.0, 0.0, 1.0, 4.0, 6.0]

    def run(dtype):
        return ContinuousBatcher(
            SlotKVCache(model, params, slots=2, kv_dtype=dtype),
            clock=VirtualClock()).run(
            _staggered(prompts, news, arrivals))

    res8, res32 = run("int8"), run(None)
    assert res8["serve_kv_dtype"] == "int8"
    assert (res8["serve_kv_bytes_per_slot"]
            < res32["serve_kv_bytes_per_slot"])
    for i in range(len(prompts)):
        np.testing.assert_array_equal(
            np.asarray(res32["results"][i].tokens),
            np.asarray(res8["results"][i].tokens), str(i))


# round 20 fast-lane repair: int8 composition variant —
# test_int8_kv_matches_oracle_greedy keeps the fast int8 pin
@pytest.mark.slow
def test_int8_kv_composes_with_chunk_and_prefix(model_params):
    """Chunked prefill + the prefix pool over an int8 table: pooled
    blocks byte-copy the int8 payload AND its scale leaves (the 3-dim
    block-op path), so a hit reproduces the cold prefill exactly."""
    model, params = model_params
    rng = np.random.default_rng(14)
    shared = rng.integers(0, 64, 8).astype(np.int32)
    prompts = [np.concatenate([shared,
                               rng.integers(0, 64, 4).astype(np.int32)])
               for _ in range(4)]

    def run(dtype, blocks):
        kv = SlotKVCache(model, params, slots=2, kv_dtype=dtype,
                         prefix_cache_blocks=blocks, prefix_block=4)
        res = ContinuousBatcher(kv, clock=VirtualClock(),
                                prefill_chunk=3).run(
            [Request(rid=i, prompt=p, max_new_tokens=4,
                     arrival_s=float(i)) for i, p in enumerate(prompts)])
        return res

    hot = run("int8", 16)
    cold = run("int8", 0)
    oracle = run(None, 0)
    assert hot["serve_prefix_cache_hit_rate"] > 0
    for i in range(len(prompts)):
        t_hot = np.asarray(hot["results"][i].tokens)
        np.testing.assert_array_equal(
            np.asarray(cold["results"][i].tokens), t_hot, str(i))
        np.testing.assert_array_equal(
            np.asarray(oracle["results"][i].tokens), t_hot, str(i))


def test_int8_kv_on_mesh(model_params, mesh8):
    """The int8 table's payload AND scale leaves shard the slot dim over
    'data' (the scale leaf is 3-dim — kv_slot_sharding generalizes), and
    sharded decode agrees with the oracle."""
    from distributed_tensorflow_tpu.parallel import mesh as meshlib

    model, params = model_params
    kv = SlotKVCache(model, params, slots=8, mesh=mesh8, kv_dtype="int8")
    for leaf in jax.tree.leaves(kv.cache):
        assert leaf.sharding.spec[0] == meshlib.DATA_AXIS
    p = _prompts(1, seed=15)[0]
    slot, first = kv.insert(p)
    got = [first] + [int(kv.advance()[slot]) for _ in range(3)]
    np.testing.assert_array_equal(_oracle(model, params, p, 4), got)


# round 20 fast-lane repair: spec × int8 composition variant
@pytest.mark.slow
def test_spec_decode_over_int8_table(model_params):
    """Both round-14 flags at once: the draft speculates over an int8
    target table — the verify is exact AGAINST THAT TABLE's decode, so
    spec-on tokens equal spec-off tokens on the same int8 table (the
    spec-parity discipline survives quantization)."""
    model, params = model_params
    prompts = _prompts(4, seed=16)

    def run(draft):
        return ContinuousBatcher(
            SlotKVCache(model, params, slots=2, kv_dtype="int8"),
            clock=VirtualClock(), draft_kv=draft, draft_k=2).run(
            [Request(rid=i, prompt=p, max_new_tokens=5, arrival_s=0.0)
             for i, p in enumerate(prompts)])

    spec = run(SlotKVCache(model, params, slots=2))
    base = ContinuousBatcher(
        SlotKVCache(model, params, slots=2, kv_dtype="int8"),
        clock=VirtualClock()).run(
        [Request(rid=i, prompt=p, max_new_tokens=5, arrival_s=0.0)
         for i, p in enumerate(prompts)])
    for i in range(len(prompts)):
        np.testing.assert_array_equal(
            np.asarray(base["results"][i].tokens),
            np.asarray(spec["results"][i].tokens), str(i))
    assert spec["serve_accept_rate"] is not None


# ----------------------------------------------------- observability / gates


def test_analyze_diff_round14_directions():
    """serve_accept_rate gates higher-is-better, serve_kv_bytes_per_slot
    lower — a rate drop and a footprint growth are both regressions."""
    from distributed_tensorflow_tpu.observability.analyze import (
        diff_reports)

    base = {"serve_accept_rate": 0.8, "serve_kv_bytes_per_slot": 1000.0,
            "serve_tokens_per_sec": 50.0}
    worse = {"serve_accept_rate": 0.4, "serve_kv_bytes_per_slot": 2000.0,
             "serve_tokens_per_sec": 20.0}
    d = diff_reports(base, worse, threshold=0.1)
    assert {r["metric"] for r in d["regressions"]} == {
        "serve_accept_rate", "serve_kv_bytes_per_slot",
        "serve_tokens_per_sec"}
    better = diff_reports(worse, base, threshold=0.1)
    assert not better["regressions"]
    assert {r["metric"] for r in better["improvements"]} == {
        "serve_accept_rate", "serve_kv_bytes_per_slot",
        "serve_tokens_per_sec"}


def test_value_direction_round14_pins():
    """_value_direction pins (the `sec_per` substring bug class): the
    tokens/sec family stays higher-better, byte-valued headlines gate
    lower-better."""
    from distributed_tensorflow_tpu.observability.analyze import (
        _value_direction)

    assert _value_direction(
        {"metric": "gpt_serve_tokens_per_sec", "unit": "tokens/sec"}) \
        == "higher"
    assert _value_direction(
        {"metric": "serve_kv_bytes_per_slot", "unit": "bytes/slot"}) \
        == "lower"
    assert _value_direction(
        {"metric": "gpt_lm_decode_bytes_per_token",
         "unit": "bytes/token"}) == "lower"
    # the round-7 rate pins must survive the 'byte' substring addition
    assert _value_direction(
        {"metric": "gpt_serve_requests_per_sec_per_chip",
         "unit": "requests/sec/chip"}) == "higher"


def test_load_report_flattens_round14_keys(tmp_path):
    from distributed_tensorflow_tpu.observability.analyze import (
        diff_reports, load_report)

    summary = {"steps": 2, "run_report": {
        "serve": {"serve_accept_rate": 0.9,
                  "serve_kv_bytes_per_slot": 4096}}}
    p = tmp_path / "summary.json"
    p.write_text(json.dumps(summary))
    flat = load_report(p)
    assert flat["serve_accept_rate"] == 0.9
    assert flat["serve_kv_bytes_per_slot"] == 4096
    worse = dict(flat, serve_accept_rate=0.2)
    d = diff_reports(flat, worse)
    assert [r["metric"] for r in d["regressions"]] == \
        ["serve_accept_rate"]


# ----------------------------------------------------------- harness + bench


def _lm_fn(batch_size, type="train", **kw):
    from distributed_tensorflow_tpu.data.loaders import load_lm_dataset

    return load_lm_dataset(seq_len=16, vocab_size=64, n_train=64,
                           n_test=32, split=type)


def test_harness_spec_decode_e2e():
    """--serve-draft-config self --serve-draft-k through the harness:
    the serve section carries accept rate 1 (draft == target) and the
    speculative ledger, in summary AND run report."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    summary = run(ExperimentConfig(
        engine="fsdp", model="gpt", dataset="lm_synth",
        dataset_fn=_lm_fn, n_devices=8, batch_size=4, log_every=0,
        model_args={"hidden": 32, "layers": 1, "heads": 2, "ffn": 64,
                    "max_len": 32},
        serve_requests=6, serve_slots=8, serve_max_new=6,
        serve_prompt_len=4, serve_draft_config="self", serve_draft_k=2))
    sec = summary["serve"]
    assert sec == summary["run_report"]["serve"]
    assert sec["completed"] == 6
    assert sec["serve_accept_rate"] == 1.0
    spec = sec["speculative"]
    assert spec["draft_k"] == 2
    assert (spec["accepted_tokens"] + spec["rejected_tokens"]
            == spec["proposed_tokens"])


@pytest.mark.slow    # round 20 fast-lane repair: the e2e
# representative is test_harness_spec_decode_e2e
def test_harness_spec_decode_sized_draft_e2e():
    """A size-spec draft ('hidden=16,layers=1'): fresh-initialized from
    the seed, runs the same window — accept rate is whatever it is, but
    the window completes and the ledger conserves."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    summary = run(ExperimentConfig(
        engine="fsdp", model="gpt", dataset="lm_synth",
        dataset_fn=_lm_fn, n_devices=8, batch_size=4, log_every=0,
        model_args={"hidden": 32, "layers": 1, "heads": 2, "ffn": 64,
                    "max_len": 32},
        serve_requests=4, serve_slots=8, serve_max_new=4,
        serve_prompt_len=4, serve_draft_config="hidden=16,layers=1",
        serve_draft_k=2))
    sec = summary["serve"]
    assert sec["completed"] == 4
    spec = sec["speculative"]
    assert (spec["accepted_tokens"] + spec["rejected_tokens"]
            == spec["proposed_tokens"])
    assert 0.0 <= sec["serve_accept_rate"] <= 1.0


@pytest.mark.slow    # round 20 fast-lane repair (see above)
def test_harness_int8_kv_e2e():
    """--serve-kv-dtype int8 through the harness: dtype + bytes in the
    serve section, at 2× the slots of the bf16 run (the capacity
    check)."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    base = dict(
        engine="fsdp", model="gpt", dataset="lm_synth",
        dataset_fn=_lm_fn, n_devices=8, batch_size=4, log_every=0,
        model_args={"hidden": 32, "layers": 1, "heads": 2, "ffn": 64,
                    "max_len": 32},
        serve_requests=4, serve_max_new=4, serve_prompt_len=4)
    s8 = run(ExperimentConfig(**base, serve_slots=16,
                              serve_kv_dtype="int8"))
    s16 = run(ExperimentConfig(**base, serve_slots=8,
                               serve_kv_dtype="bfloat16"))
    sec8, sec16 = s8["serve"], s16["serve"]
    assert sec8["serve_kv_dtype"] == "int8"
    assert sec16["serve_kv_dtype"] == "bfloat16"
    assert sec8["completed"] == sec16["completed"] == 4
    # int8 at DOUBLE the slots fits in (about) the bf16 table's bytes:
    # payload exactly half, plus the per-vector scale overhead
    head_dim = 32 // 2
    assert (sec8["serve_kv_bytes_per_slot"] * 16
            <= sec16["serve_kv_bytes_per_slot"] * 8 * (1 + 8 / head_dim))


def test_harness_round14_flag_validation():
    """Bad draft/kv-dtype flags fail BEFORE training (the --serve
    contract), with the draft-spec parser's message."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, parse_draft_config, run)

    base = dict(engine="fsdp", model="gpt", dataset="lm_synth",
                n_devices=8, serve_requests=2,
                model_args={"hidden": 32, "layers": 1, "heads": 2,
                            "ffn": 64})
    with pytest.raises(ValueError, match="serve-draft-k"):
        run(ExperimentConfig(**base, serve_draft_k=0))
    with pytest.raises(ValueError, match="key=int"):
        run(ExperimentConfig(**base, serve_draft_config="hidden:16"))
    with pytest.raises(ValueError, match="serve-kv-dtype"):
        run(ExperimentConfig(**base, serve_kv_dtype="int4"))
    # parser unit: 'self' → None, sizes parse, junk raises
    assert parse_draft_config("self") is None
    assert parse_draft_config("hidden=16, layers=1") == {
        "hidden": 16, "layers": 1}
    with pytest.raises(ValueError, match="vocab/max_len"):
        parse_draft_config("vocab_size=8")
    with pytest.raises(ValueError, match="int"):
        parse_draft_config("hidden=big")


@pytest.mark.slow
def test_bench_serve_smoke_int8_and_draft():
    """`bench.py --serve` with BENCH_SERVE_KV_DTYPE=int8 + a self draft:
    one parsable JSON line carrying serve_kv_dtype /
    serve_kv_bytes_per_slot, the same-trace model-dtype baseline with
    the bytes ratio + greedy agreement, and the speculative ledger."""
    repo = Path(__file__).resolve().parents[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               BENCH_SERVE_HIDDEN="32", BENCH_SERVE_LAYERS="1",
               BENCH_SERVE_HEADS="2", BENCH_SERVE_FFN="64",
               BENCH_SERVE_VOCAB="64", BENCH_SERVE_PROMPT_LEN="6",
               BENCH_SERVE_MAX_NEW="6", BENCH_SERVE_SLOTS="2",
               BENCH_SERVE_REQUESTS="4", BENCH_SERVE_RATE="5",
               BENCH_SERVE_REPEATS="1",
               BENCH_SERVE_PREFILL_CHUNK="2",
               BENCH_SERVE_PREFIX_CACHE="8",
               BENCH_SERVE_PREFIX_BLOCK="2",
               BENCH_SERVE_SHARED_PREFIX="4",
               BENCH_SERVE_LONG_EVERY="2",
               BENCH_SERVE_KV_DTYPE="int8",
               BENCH_SERVE_DRAFT="self", BENCH_SERVE_DRAFT_K="2")
    proc = subprocess.run(
        [sys.executable, str(repo / "bench.py"), "--serve", "--no-probe"],
        capture_output=True, text=True, timeout=540, env=env,
        cwd=str(repo))
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["metric"] == "gpt_serve_requests_per_sec_per_chip"
    if payload.get("skipped"):
        assert payload["value"] is None and payload["error"]
        return
    assert payload["serve_kv_dtype"] == "int8"
    assert payload["serve_kv_bytes_per_slot"] > 0
    assert payload["config"]["kv_dtype"] == "int8"
    assert payload["config"]["draft"] == "self"
    spec = payload["speculative"]
    assert (spec["accepted_tokens"] + spec["rejected_tokens"]
            == spec["proposed_tokens"])
    # draft == target → acceptance is near-total; not asserted exactly
    # 1.0 because the target verifies over the INT8 table while the
    # draft proposes from its full-precision view (tolerance-based)
    assert payload["serve_accept_rate"] > 0
    cmp_line = payload["kv_baseline"]
    assert cmp_line is not None
    assert cmp_line["kv_dtype"] == "bfloat16"
    # int8 payload + scales vs the bf16 table on the SAME trace: the
    # bytes must shrink, and the greedy streams must agree (head_dim 16
    # → ratio (1 + 4/16)/2 = 0.625)
    assert cmp_line["kv_bytes_ratio"] == pytest.approx(0.625, rel=1e-3)
    assert cmp_line["greedy_token_match"] == 1.0
