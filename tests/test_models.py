"""Model registry + forward-shape tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.models import create_model, get_model_fn


@pytest.mark.parametrize("name,shape", [
    ("mlp", (2, 28, 28, 1)),
    ("cnn", (2, 28, 28, 1)),
    ("cnn", (2, 28, 28)),   # no-channel input path
])
@pytest.mark.slow
def test_forward_shapes(name, shape):
    model = create_model(name, num_classes=10)
    x = jnp.ones(shape)
    params = model.init(jax.random.key(0), x, train=False)["params"]
    logits = model.apply({"params": params}, x, train=False)
    assert logits.shape == (shape[0], 10)
    assert logits.dtype == jnp.float32


def test_dropout_train_vs_eval():
    model = create_model("mlp")
    x = jnp.ones((4, 28, 28, 1))
    params = model.init(jax.random.key(0), x, train=False)["params"]
    e1 = model.apply({"params": params}, x, train=False)
    e2 = model.apply({"params": params}, x, train=False)
    np.testing.assert_array_equal(e1, e2)  # eval is deterministic
    t1 = model.apply({"params": params}, x, train=True,
                     rngs={"dropout": jax.random.key(1)})
    t2 = model.apply({"params": params}, x, train=True,
                     rngs={"dropout": jax.random.key(2)})
    assert not np.array_equal(t1, t2)  # dropout active in train


def test_model_fn_contract():
    # reference-style zero-arg model_fn (reference initializer.py:12)
    fn = get_model_fn("mlp", num_classes=7)
    m = fn()
    assert m.num_classes == 7


def test_unknown_model():
    with pytest.raises(KeyError):
        create_model("transformer_xxl")


@pytest.mark.slow
def test_resnet20_forward():
    model = create_model("resnet20", num_classes=10)
    x = jnp.ones((2, 32, 32, 3))
    params = model.init(jax.random.key(0), x, train=False)["params"]
    logits = model.apply({"params": params}, x, train=False)
    assert logits.shape == (2, 10)


@pytest.mark.slow
def test_bert_tiny_forward():
    model = create_model("bert_tiny", num_classes=2, vocab_size=100, max_len=32)
    ids = jnp.array(np.random.default_rng(0).integers(1, 100, (2, 16)))
    params = model.init(jax.random.key(0), ids, train=False)["params"]
    logits = model.apply({"params": params}, ids, train=False)
    assert logits.shape == (2, 2)
    # padding must not change unpadded positions' logits meaningfully
    padded = jnp.concatenate([ids, jnp.zeros((2, 4), jnp.int32)], axis=1)
    lp = model.apply({"params": params}, padded, train=False)
    np.testing.assert_allclose(logits, lp, atol=1e-4)


def test_bf16_mixed_precision():
    """dtype='bfloat16' computes in bf16 but keeps f32 params and f32 logits
    (mixed precision: MXU-rate matmuls, full-precision optimizer math)."""
    from distributed_tensorflow_tpu.models import resolve_dtype

    assert resolve_dtype("bf16") == jnp.bfloat16
    assert resolve_dtype(jnp.float32) == jnp.float32
    with pytest.raises(KeyError):
        resolve_dtype("int4")

    model = create_model("cnn", num_classes=10, dtype="bfloat16")
    x = jnp.ones((2, 28, 28, 1))
    params = model.init(jax.random.key(0), x, train=False)["params"]
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(params))
    logits = model.apply({"params": params}, x, train=False)
    assert logits.dtype == jnp.float32

    f32 = create_model("cnn", num_classes=10)
    ref = f32.apply({"params": params}, x, train=False)
    np.testing.assert_allclose(logits, ref, atol=0.15)  # bf16 has ~8 mantissa bits


def test_bf16_training_learns(mesh8):
    """A bf16 sync-DP step must still optimize (grads flow through casts)."""
    from distributed_tensorflow_tpu.engines import SyncEngine

    model = create_model("mlp", num_classes=10, dtype="bfloat16", hidden=32)
    eng = SyncEngine(model, mesh=mesh8, learning_rate=1e-2)
    rng = np.random.default_rng(0)
    x = rng.random((64, 28, 28, 1), np.float32)
    y = (np.arange(64) % 10).astype(np.int32)
    state = eng.init_state(jax.random.key(0), x)
    xs, ys = eng.shard_batch(x, y)
    state, first = eng.step(state, xs, ys)  # step donates its input state
    for _ in range(30):
        state, m = eng.step(state, xs, ys)
    assert float(m["loss"]) < float(first["loss"])
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(state.params))


@pytest.mark.slow
def test_bert_flash_matches_dense():
    """attention_impl='flash' (Pallas kernel) must agree with 'dense'."""
    kw = dict(num_classes=2, vocab_size=100, max_len=32)
    dense = create_model("bert_tiny", attention_impl="dense", **kw)
    flash = create_model("bert_tiny", attention_impl="flash", **kw)
    ids = jnp.array(np.random.default_rng(1).integers(1, 100, (2, 32)))
    params = dense.init(jax.random.key(0), ids, train=False)["params"]
    ld = dense.apply({"params": params}, ids, train=False)
    lf = flash.apply({"params": params}, ids, train=False)
    np.testing.assert_allclose(ld, lf, atol=1e-4, rtol=1e-4)


# round 20 fast-lane repair: remat parity pays two BERT grad compiles
# (~13s); rides the slow lane
@pytest.mark.slow
def test_bert_remat_param_and_grad_parity():
    """Model-level remat on BERT is a scheduling change only: identical
    param tree (paths AND values — nn.remat must not perturb the flax
    scope names or init RNG streams) and identical grads."""
    import optax

    kw = dict(num_classes=2, vocab_size=100, max_len=32, dropout_rate=0.0)
    ids = jnp.array(np.random.default_rng(2).integers(1, 100, (4, 16)))
    labels = jnp.array([0, 1, 0, 1])
    out = {}
    for remat in (False, True):
        model = create_model("bert_tiny", remat=remat, **kw)
        params = model.init(jax.random.key(0), ids, train=False)["params"]

        def loss_fn(p):
            logits = model.apply({"params": p}, ids, train=False)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        out[remat] = (float(loss), jax.device_get(params),
                      jax.device_get(grads))
    # identical tree structure (same param paths) ...
    assert (jax.tree_util.tree_structure(out[False][1])
            == jax.tree_util.tree_structure(out[True][1]))
    # ... identical values and grads
    assert out[False][0] == pytest.approx(out[True][0], abs=1e-6)
    jax.tree.map(np.testing.assert_array_equal, out[False][1], out[True][1])
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5),
        out[False][2], out[True][2])
