"""Tests for the L3 data layer (loaders + pipeline)."""

import numpy as np
import pytest

from distributed_tensorflow_tpu.data import make_dataset_fn
from distributed_tensorflow_tpu.data.loaders import load_dataset, synthetic_classification
from distributed_tensorflow_tpu.data.pipeline import iter_batches, steps_per_epoch


def test_synthetic_deterministic():
    a = load_dataset("synthetic")
    b = load_dataset("synthetic")
    np.testing.assert_array_equal(a.x, b.x)
    np.testing.assert_array_equal(a.y, b.y)


def test_synthetic_train_test_same_task():
    # same prototypes, different samples (the train/test-prototype-mismatch
    # failure mode would make accuracy targets meaningless)
    xtr, ytr = synthetic_classification((4, 4), 3, 64, seed=7, split="train")
    xte, yte = synthetic_classification((4, 4), 3, 64, seed=7, split="test")
    assert not np.array_equal(xtr, xte)
    # class-0 means should be close across splits (same prototype)
    m_tr = xtr[ytr == 0].mean(axis=0)
    m_te = xte[yte == 0].mean(axis=0)
    assert np.abs(m_tr - m_te).mean() < 0.2


def test_reshape_flag():
    # reference initializer.py:28-35 — reshape adds the channel dim
    a = load_dataset("mnist", reshape=True)
    b = load_dataset("mnist", reshape=False)
    assert a.x.shape[1:] == (28, 28, 1)
    assert b.x.shape[1:] == (28, 28)


def test_shard_round_robin():
    # tf.data .shard(n, i) semantics: every n-th example (reference initializer.py:44)
    ds = load_dataset("synthetic")
    s = ds.shard(4, 1)
    np.testing.assert_array_equal(s.x, ds.x[1::4])


def test_dataset_fn_signature_parity():
    fn = make_dataset_fn("synthetic")
    full = load_dataset("synthetic", split="test")
    ds = fn(32, type="test", shard=True, index=2, n_shards=4)
    assert ds.batch_size == 32
    assert len(ds) == len(full.x[2::4])
    np.testing.assert_array_equal(ds.x, full.x[2::4])


def test_iter_batches_shuffles_examples_not_batches():
    x = np.arange(100).reshape(100, 1).astype(np.float32)
    y = np.arange(100).astype(np.int32)
    b0 = [by for _, by, _ in iter_batches(x, y, 10, seed=1, epoch=0)]
    # example-level shuffle: a batch should not be a contiguous range
    assert any(np.max(np.diff(np.sort(b))) > 1 for b in b0)
    # per-epoch reshuffle differs
    b1 = [by for _, by, _ in iter_batches(x, y, 10, seed=1, epoch=1)]
    assert not all(np.array_equal(a, b) for a, b in zip(b0, b1))
    # deterministic given (seed, epoch)
    b0b = [by for _, by, _ in iter_batches(x, y, 10, seed=1, epoch=0)]
    assert all(np.array_equal(a, b) for a, b in zip(b0, b0b))


def test_iter_batches_padding_mask():
    x = np.ones((25, 2), np.float32)
    y = np.zeros(25, np.int32)
    batches = list(iter_batches(x, y, 10, shuffle=False))
    assert len(batches) == 3
    bx, by, mask = batches[-1]
    assert bx.shape == (10, 2)
    assert mask.sum() == 5  # 5 real rows, 5 padded
    assert steps_per_epoch(25, 10) == 3
    assert steps_per_epoch(25, 10, drop_remainder=True) == 2


def test_unknown_dataset():
    with pytest.raises(KeyError):
        load_dataset("imagenet")


def test_lm_bin_corpus_loader(tmp_path, monkeypatch):
    """A local <name>.bin (flat uint16 token ids) is memmapped and windowed
    into next-token pairs; last 10% is the test split."""
    from distributed_tensorflow_tpu.data.loaders import load_lm_dataset

    tokens = (np.arange(1000) % 97).astype(np.uint16)
    (tmp_path / "mycorpus.bin").write_bytes(tokens.tobytes())
    monkeypatch.setenv("DTF_TPU_DATA_DIR", str(tmp_path))

    tr = load_lm_dataset("mycorpus", split="train", seq_len=32)
    te = load_lm_dataset("mycorpus", split="test", seq_len=32)
    assert not tr.synthetic and not te.synthetic
    assert tr.num_classes == 97
    assert tr.x.shape == (28, 32)        # floor((900-1)/32) windows
    assert te.x.shape[1] == 32
    # next-token alignment inside every window
    np.testing.assert_array_equal(tr.x[:, 1:], tr.y[:, :-1])
    np.testing.assert_array_equal(
        tr.x.reshape(-1)[1:], tr.y.reshape(-1)[:-1])
    # splits come from disjoint regions of the stream
    assert tr.x.max() <= 96 and te.x.min() >= 0
    assert not np.array_equal(tr.x[: len(te.x)], te.x)

    # absent file still falls back to the synthetic chain
    missing = load_lm_dataset("nosuch", split="train", seq_len=16,
                              vocab_size=32, n_train=64)
    assert missing.synthetic and missing.num_classes == 32


def test_lm_bin_corpus_too_small_region_rejected(tmp_path, monkeypatch):
    """A split region smaller than one window must error, not read out of
    bounds (test) or leak held-out tokens (train)."""
    from distributed_tensorflow_tpu.data.loaders import load_lm_dataset

    tokens = (np.arange(1000) % 50).astype(np.uint16)
    (tmp_path / "small.bin").write_bytes(tokens.tobytes())
    monkeypatch.setenv("DTF_TPU_DATA_DIR", str(tmp_path))
    # test region = 100 tokens < 128 + 1
    with pytest.raises(ValueError, match="seq_len"):
        load_lm_dataset("small", split="test", seq_len=128)
    # explicit vocab_size skips the full-file max scan and wins
    tr = load_lm_dataset("small", split="train", seq_len=32, vocab_size=64)
    assert tr.num_classes == 64


def test_lm_bin_explicit_vocab_undercoverage_rejected(tmp_path, monkeypatch):
    """An explicit vocab_size smaller than the corpus's max token id must
    raise (naming the offending id), not silently clamp in nn.Embed and the
    CE label gather (ADVICE r3)."""
    from distributed_tensorflow_tpu.data.loaders import load_lm_dataset

    tokens = (np.arange(1000) % 97).astype(np.uint16)
    (tmp_path / "wide.bin").write_bytes(tokens.tobytes())
    monkeypatch.setenv("DTF_TPU_DATA_DIR", str(tmp_path))
    with pytest.raises(ValueError, match="96"):
        load_lm_dataset("wide", split="train", seq_len=32, vocab_size=50)
    # a covering explicit vocab still wins over the derived one
    tr = load_lm_dataset("wide", split="train", seq_len=32, vocab_size=128)
    assert tr.num_classes == 128
