"""_honor_platform_env: env-var precedence, case handling, and the
already-initialized-backend warning (ADVICE.md round-5 lows #1/#2).

``jax.config.update`` is monkeypatched to a recorder so these tests assert
the exact value the hook would apply without disturbing the live test
backend.
"""

import warnings

import jax
import pytest

import distributed_tensorflow_tpu as dtf


@pytest.fixture()
def recorded_update(monkeypatch):
    calls = {}
    monkeypatch.setattr(jax.config, "update",
                        lambda key, value: calls.__setitem__(key, value))
    return calls


def test_jax_platforms_passes_through_verbatim(monkeypatch, recorded_update):
    # jax_platforms entries are case-sensitive plugin-name lookups: a
    # registered non-lowercase PJRT plugin name must survive the re-assert
    monkeypatch.setenv("JAX_PLATFORMS", "MyPlugin,cpu")
    monkeypatch.setenv("JAX_PLATFORM_NAME", "CPU")  # loses to JAX_PLATFORMS
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # backend state is irrelevant here
        dtf._honor_platform_env()
    assert recorded_update["jax_platforms"] == "MyPlugin,cpu"


def test_platform_name_fallback_is_lowercased(monkeypatch, recorded_update):
    # jax itself lowercases JAX_PLATFORM_NAME (xla_bridge) — the fallback
    # must match, so JAX_PLATFORM_NAME=CPU selects cpu instead of erroring
    monkeypatch.setenv("JAX_PLATFORMS", "")
    monkeypatch.setenv("JAX_PLATFORM_NAME", "CPU")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        dtf._honor_platform_env()
    assert recorded_update["jax_platforms"] == "cpu"


def test_noop_without_env_vars(monkeypatch, recorded_update):
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.delenv("JAX_PLATFORM_NAME", raising=False)
    dtf._honor_platform_env()
    assert recorded_update == {}


def test_warns_when_initialized_backend_conflicts(monkeypatch,
                                                  recorded_update):
    jax.devices()  # make sure a (cpu) backend is initialized in-process
    monkeypatch.setenv("JAX_PLATFORMS", "NotThisBackend")
    monkeypatch.delenv("JAX_PLATFORM_NAME", raising=False)
    with pytest.warns(RuntimeWarning, match="already initialized"):
        dtf._honor_platform_env()
    assert recorded_update["jax_platforms"] == "NotThisBackend"


def test_no_warning_when_env_matches_live_backend(monkeypatch,
                                                  recorded_update):
    # the conftest backend IS cpu: re-asserting cpu changes nothing and
    # must stay silent (the warning is for the conflicting-embedder case)
    jax.devices()
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv("JAX_PLATFORM_NAME", raising=False)
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        dtf._honor_platform_env()
    assert recorded_update["jax_platforms"] == "cpu"
