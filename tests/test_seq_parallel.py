"""Sequence-parallel engine tests: math equivalence vs single-device dense,
and end-to-end BERT-tiny convergence on the synthetic text task."""

import jax
import numpy as np
import pytest

from distributed_tensorflow_tpu.data.loaders import load_text_dataset
from distributed_tensorflow_tpu.engines import SeqParallelEngine, SyncEngine, Trainer
from distributed_tensorflow_tpu.models import create_model
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def tiny_bert(attention_impl="ring", heads=2):
    return create_model(
        "bert_tiny", num_classes=2, vocab_size=128, hidden=32, layers=1,
        heads=heads, ffn=64, max_len=64, dropout_rate=0.0,
        attention_impl=attention_impl)


@pytest.fixture(scope="module")
def text_data():
    tr = load_text_dataset(seq_len=32, vocab_size=128, n_train=512, n_test=256)
    te = load_text_dataset(seq_len=32, vocab_size=128, n_train=512, n_test=256,
                           split="test")
    return tr, te


def seq_mesh(dp, sp):
    return meshlib.create_mesh(dp * sp, shape=(dp, sp),
                               axis_names=("data", "seq"))


@pytest.mark.slow
def test_seq_parallel_matches_single_device(text_data):
    """(data=2, seq=4) ring-attention training must reproduce single-device
    dense-attention training step-for-step (same global batch, no dropout).

    SGD optimizer: it is linear in the gradient, so fp32 noise stays fp32
    noise.  (Adam would amplify ~1e-8 noise on mathematically-zero grads —
    e.g. key biases, which softmax shift-invariance cancels — to lr-scale
    param diffs.)"""
    import optax

    tr, _ = text_data
    x, y = tr.x[:32], tr.y[:32]

    # oracle: 1 device, dense attention
    eng1 = SyncEngine(tiny_bert("dense"), optimizer=optax.sgd(0.1),
                      mesh=meshlib.create_mesh(1))
    s1 = eng1.init_state(jax.random.key(0), x)
    for _ in range(2):
        xs, ys = eng1.shard_batch(x, y)
        s1, m1 = eng1.step(s1, xs, ys)

    # 8 devices, 2-way data × 4-way seq, ring attention
    eng8 = SeqParallelEngine(tiny_bert("ring"), optimizer=optax.sgd(0.1),
                             mesh=seq_mesh(2, 4))
    s8 = eng8.init_state(jax.random.key(0), x)
    for _ in range(2):
        xs, ys = eng8.shard_batch(x, y)
        s8, m8 = eng8.step(s8, xs, ys)

    for a, b in zip(jax.tree.leaves(jax.device_get(s1.params)),
                    jax.tree.leaves(jax.device_get(s8.params))):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3)
    assert float(m1["loss"]) == pytest.approx(float(m8["loss"]), abs=1e-4)


@pytest.mark.slow
@pytest.mark.parametrize("impl", ["ulysses", "ulysses_flash"])
def test_seq_parallel_ulysses_matches_single_device(text_data, impl):
    """Both Ulysses local-math variants (XLA dense / Pallas flash kernel)
    must reproduce single-device dense training — the flash variant also
    exercises the all-gathered pad mask through the kernel's kv_mask."""
    import optax

    tr, _ = text_data
    x, y = tr.x[:16], tr.y[:16]

    eng1 = SyncEngine(tiny_bert("dense", heads=4), optimizer=optax.sgd(0.1),
                      mesh=meshlib.create_mesh(1))
    s1 = eng1.init_state(jax.random.key(0), x)
    xs, ys = eng1.shard_batch(x, y)
    s1, m1 = eng1.step(s1, xs, ys)

    eng8 = SeqParallelEngine(tiny_bert(impl, heads=4),
                             optimizer=optax.sgd(0.1), mesh=seq_mesh(2, 4))
    s8 = eng8.init_state(jax.random.key(0), x)
    xs, ys = eng8.shard_batch(x, y)
    s8, m8 = eng8.step(s8, xs, ys)

    for a, b in zip(jax.tree.leaves(jax.device_get(s1.params)),
                    jax.tree.leaves(jax.device_get(s8.params))):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3)
    assert float(m1["loss"]) == pytest.approx(float(m8["loss"]), abs=1e-4)


@pytest.mark.slow
def test_bert_ring_converges(text_data):
    tr, te = text_data
    eng = SeqParallelEngine(tiny_bert("ring"), mesh=seq_mesh(2, 4),
                            learning_rate=3e-3)
    t = Trainer(None, engine=eng)
    t.fit(tr, epochs=2, batch_size=32, log_every=0)
    acc = t.evaluate(te, batch_size=64)["accuracy"]
    assert acc > 0.85, acc


@pytest.mark.slow
def test_seq_parallel_eval_full_test_set(text_data):
    _, te = text_data
    eng = SeqParallelEngine(tiny_bert("ring"), mesh=seq_mesh(2, 4))
    state = eng.init_state(jax.random.key(0), te.x[:8])
    ev = eng.evaluate(state, te, batch_size=48)
    assert ev["count"] == len(te)


def test_mesh_axis_validation():
    with pytest.raises(ValueError):
        SeqParallelEngine(tiny_bert(), mesh=meshlib.create_mesh(8))
    with pytest.raises(ValueError):
        SeqParallelEngine(tiny_bert(), mesh=None)


@pytest.mark.slow
def test_seq_parallel_ring_flash_matches_single_device(text_data):
    """ring_flash (ring schedule + flash local math, VERDICT r2 task 5)
    must reproduce single-device dense training like plain ring does —
    this exercises the custom_vjp ring backward through a real model."""
    import optax

    tr, _ = text_data
    x, y = tr.x[:32], tr.y[:32]

    eng1 = SyncEngine(tiny_bert("dense"), optimizer=optax.sgd(0.1),
                      mesh=meshlib.create_mesh(1))
    s1 = eng1.init_state(jax.random.key(0), x)
    for _ in range(2):
        xs, ys = eng1.shard_batch(x, y)
        s1, m1 = eng1.step(s1, xs, ys)

    eng8 = SeqParallelEngine(tiny_bert("ring_flash"), optimizer=optax.sgd(0.1),
                             mesh=seq_mesh(2, 4))
    s8 = eng8.init_state(jax.random.key(0), x)
    for _ in range(2):
        xs, ys = eng8.shard_batch(x, y)
        s8, m8 = eng8.step(s8, xs, ys)

    for a, b in zip(jax.tree.leaves(jax.device_get(s1.params)),
                    jax.tree.leaves(jax.device_get(s8.params))):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3)
    assert float(m1["loss"]) == pytest.approx(float(m8["loss"]), abs=1e-4)


@pytest.mark.slow
def test_flash_attention_via_harness_dp_path(text_data):
    """--attention flash at seq_parallel == 1 (VERDICT r2 task 2: the CLI
    must be able to reach the Pallas kernel end-to-end)."""
    from distributed_tensorflow_tpu.data.loaders import load_text_dataset
    from distributed_tensorflow_tpu.utils.harness import ExperimentConfig, run

    def dataset_fn(batch_size, type="train", **kw):
        return load_text_dataset(seq_len=32, vocab_size=128, n_train=128,
                                 n_test=64, split=type)

    summary = run(ExperimentConfig(
        engine="sync", model="bert_tiny", dataset="glue_synth",
        attention_impl="flash", n_devices=8, batch_size=8, epochs=1,
        log_every=0, dataset_fn=dataset_fn))
    assert summary["engine"] == "sync"
    assert np.isfinite(summary["test_loss"])


def test_flash_attention_rejected_with_seq_parallel():
    from distributed_tensorflow_tpu.utils.harness import ExperimentConfig, run

    with pytest.raises(ValueError, match="ring_flash"):
        run(ExperimentConfig(model="bert_tiny", dataset="glue_synth",
                             attention_impl="flash", seq_parallel=4,
                             n_devices=8))


def test_seq_parallel_grad_accum_parity(text_data):
    """grad_accum=2 under dp×sp is pure scheduling: mean-of-chunk-means
    equals the full-batch mean (no dropout, SGD), so loss and params match
    the K=1 step."""
    import optax

    tr, _ = text_data
    x, y = tr.x[:16], tr.y[:16]
    out = {}
    for K in (1, 2):
        eng = SeqParallelEngine(tiny_bert("ring"), optimizer=optax.sgd(0.1),
                                mesh=seq_mesh(2, 4), grad_accum=K)
        st = eng.init_state(jax.random.key(0), x)
        st, m = eng.step(st, *eng.shard_batch(x, y))
        out[K] = (float(m["loss"]), jax.device_get(st.params))
    assert out[1][0] == pytest.approx(out[2][0], abs=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5),
        out[1][1], out[2][1])


def test_seq_parallel_grad_accum_validates():
    import optax

    with pytest.raises(ValueError, match="grad_accum"):
        SeqParallelEngine(tiny_bert("ring"), optimizer=optax.sgd(0.1),
                          mesh=seq_mesh(2, 4), grad_accum=0)
