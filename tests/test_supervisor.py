"""Supervisor channel + wire framing tests (reference network.py / port-4000
protocol parity, SURVEY.md §2.3)."""

import socket
import threading

from distributed_tensorflow_tpu.utils import wire
from distributed_tensorflow_tpu.utils.supervisor import ResultSink, SupervisorListener


def test_wire_roundtrip():
    a, b = socket.socketpair()
    wire.send_msg(a, {"k": [1, 2, 3]})
    assert wire.recv_msg(b) == {"k": [1, 2, 3]}
    # length-prefix framing survives split delivery
    wire.send_msg(a, "x" * 70000)
    assert wire.recv_msg(b) == "x" * 70000
    a.close()
    assert wire.recv_msg(b) is None  # closed → None (reference network.py:12-13)
    b.close()


def test_wire_pickle_compat():
    # reference-style pickle payloads decode when explicitly allowed
    a, b = socket.socketpair()
    wire.send_msg(a, ["done", 1.5], use_pickle=True)
    assert wire.recv_msg(b, allow_pickle=True) == ["done", 1.5]
    a.close(); b.close()


def test_result_sink_event_triple(tmp_path):
    # the reference's exact supervisor sequence: start, done(elapsed),
    # results(accuracy) — server.py:121-124, 182-187
    listener = SupervisorListener()
    sink = ResultSink(tmp_path / "r.jsonl", supervisor_address="127.0.0.1",
                      supervisor_port=listener.port)
    sink.start()
    sink.done(12.5)
    sink.results(0.97)
    sink.close()
    listener._thread.join(timeout=2)
    assert listener.messages == ["start", ["done", 12.5], ["results", 0.97]]
    assert [e["event"] for e in sink.events] == ["start", "done", "results"]
    listener.close()
