"""Tensor-parallel engine tests: params actually sharded, math matches
single-device, end-to-end convergence."""

import jax
import numpy as np
import optax
import pytest

from distributed_tensorflow_tpu.data.loaders import Dataset, synthetic_classification
from distributed_tensorflow_tpu.engines import SyncEngine, Trainer
from distributed_tensorflow_tpu.engines.tensor_parallel import (
    TensorParallelEngine, TPMLP)
from distributed_tensorflow_tpu.models import create_model
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def tp_mesh(dp=2, tp=4):
    return meshlib.create_mesh(dp * tp, shape=(dp, tp),
                               axis_names=("data", "model"))


def tiny_data(n=512, split="train"):
    x, y = synthetic_classification((8, 8), 4, n, seed=5, split=split)
    return Dataset(x=x, y=y, num_classes=4, name="tiny", synthetic=True)


def test_params_are_model_sharded():
    eng = TensorParallelEngine(TPMLP(num_classes=4, hidden=64),
                               mesh=tp_mesh(2, 4))
    state = eng.init_state(jax.random.key(0), tiny_data().x[:8])
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    spec_by_name = {jax.tree_util.keystr(p): l.sharding.spec for p, l in flat}
    # column-parallel kernel sharded on output dim, row-parallel on input dim
    assert any("model" in str(s) for s in spec_by_name.values()), spec_by_name
    col = [s for n, s in spec_by_name.items() if "Dense_0']['kernel" in n][0]
    row = [s for n, s in spec_by_name.items() if "Dense_1']['kernel" in n][0]
    assert col == ("model",) or col[-1] == "model" or "model" in tuple(col)
    assert "model" in tuple(row) or row[0] == "model"


@pytest.mark.slow
def test_tp_matches_single_device():
    """(data=2, model=4) must equal 1-device training (SGD, no dropout)."""
    train = tiny_data()
    x, y = train.x[:64], train.y[:64]

    def model(**kw):
        return TPMLP(num_classes=4, hidden=64, dropout_rate=0.0, **kw)

    eng1 = TensorParallelEngine(model(), optimizer=optax.sgd(0.5),
                                mesh=tp_mesh(1, 1))
    s1 = eng1.init_state(jax.random.key(0), x)
    eng8 = TensorParallelEngine(model(), optimizer=optax.sgd(0.5),
                                mesh=tp_mesh(2, 4))
    s8 = eng8.init_state(jax.random.key(0), x)

    for _ in range(3):
        xs1, ys1 = eng1.shard_batch(x, y)
        s1, m1 = eng1.step(s1, xs1, ys1)
        xs8, ys8 = eng8.shard_batch(x, y)
        s8, m8 = eng8.step(s8, xs8, ys8)

    for a, b in zip(jax.tree.leaves(jax.device_get(s1.params)),
                    jax.tree.leaves(jax.device_get(s8.params))):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)
    assert float(m1["loss"]) == pytest.approx(float(m8["loss"]), abs=1e-5)


def test_tp_trains_and_evaluates():
    train, test = tiny_data(), tiny_data(128, "test")
    eng = TensorParallelEngine(TPMLP(num_classes=4, hidden=64),
                               mesh=tp_mesh(2, 4), learning_rate=5e-3)
    tr = Trainer(None, engine=eng)
    tr.fit(train, epochs=4, batch_size=64, log_every=0)
    ev = tr.evaluate(test)
    assert ev["count"] == len(test)
    assert ev["accuracy"] > 0.9, ev


def test_tp_mesh_validation():
    with pytest.raises(ValueError):
        TensorParallelEngine(TPMLP(), mesh=meshlib.create_mesh(8))


def tiny_tp_bert(tp=True):
    return create_model(
        "bert_tiny", num_classes=2, vocab_size=128, hidden=32, layers=2,
        heads=2, ffn=64, max_len=32, dropout_rate=0.0, partition_model=tp)


@pytest.mark.slow
def test_tp_bert_matches_single_device():
    """BERT with Megatron partition_model annotations: (data=2, model=4)
    must equal 1-device training (VERDICT r1 #3 acceptance)."""
    rnd = np.random.default_rng(3)
    x = rnd.integers(1, 128, (32, 16)).astype(np.int32)
    y = (np.arange(32) % 2).astype(np.int32)

    eng1 = TensorParallelEngine(tiny_tp_bert(), optimizer=optax.sgd(0.1),
                                mesh=tp_mesh(1, 1))
    s1 = eng1.init_state(jax.random.key(0), x)
    eng8 = TensorParallelEngine(tiny_tp_bert(), optimizer=optax.sgd(0.1),
                                mesh=tp_mesh(2, 4))
    s8 = eng8.init_state(jax.random.key(0), x)

    for _ in range(2):
        s1, m1 = eng1.step(s1, *eng1.shard_batch(x, y))
        s8, m8 = eng8.step(s8, *eng8.shard_batch(x, y))

    for a, b in zip(jax.tree.leaves(jax.device_get(s1.params)),
                    jax.tree.leaves(jax.device_get(s8.params))):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3)
    assert float(m1["loss"]) == pytest.approx(float(m8["loss"]), abs=1e-5)


@pytest.mark.slow
def test_tp_bert_params_sharded():
    eng = TensorParallelEngine(tiny_tp_bert(), mesh=tp_mesh(2, 4))
    x = np.ones((8, 16), np.int32)
    state = eng.init_state(jax.random.key(0), x)
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    sharded = [jax.tree_util.keystr(p) for p, l in flat
               if "model" in str(l.sharding.spec)]
    # QKV col-parallel, attention out row-parallel, FFN both, vocab embed
    for want in ("query", "key", "value", "out", "Dense_0", "Dense_1",
                 "Embed_0"):
        assert any(want in n for n in sharded), (want, sharded)


@pytest.mark.slow
def test_tp_bert_harness_run():
    """`--model bert_tiny -tp 4` accepted by the harness (whitelist dropped)."""
    from distributed_tensorflow_tpu.data.loaders import load_text_dataset
    from distributed_tensorflow_tpu.utils.harness import ExperimentConfig, run

    def dataset_fn(batch_size, type="train", **kw):
        return load_text_dataset(seq_len=16, vocab_size=128, n_train=128,
                                 n_test=64, split=type)

    summary = run(ExperimentConfig(
        engine="sync", model="bert_tiny", dataset="glue_synth",
        n_devices=8, tensor_parallel=4, batch_size=16, epochs=1, log_every=0,
        model_fn=lambda: tiny_tp_bert(), dataset_fn=dataset_fn))
    assert summary["engine"] == "tensor_parallel"
    assert summary["tensor_parallel"] == 4
    assert np.isfinite(summary["test_loss"])


@pytest.mark.slow
def test_tp_grad_accum_matches_k1(mesh8):
    """GSPMD gradient accumulation under TP: K=4 must reproduce K=1's SGD
    update exactly (mean of equal-chunk means == global mean)."""
    import optax

    from distributed_tensorflow_tpu.models import create_model

    mesh = meshlib.create_mesh(
        8, shape=(4, 2), axis_names=(meshlib.DATA_AXIS, meshlib.MODEL_AXIS))
    rnd = np.random.default_rng(7)
    x = rnd.integers(0, 64, (8, 16)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    outs = []
    for K in (1, 4):
        model = create_model("gpt", num_classes=64, hidden=32, layers=1,
                             heads=2, ffn=64, max_len=16, dropout_rate=0.0,
                             partition_model=True)
        eng = TensorParallelEngine(model, mesh=mesh,
                                   optimizer=optax.sgd(0.1), grad_accum=K)
        state = eng.init_state(jax.random.key(1), x)
        state, m = eng.step(state, *eng.shard_batch(x, y))
        outs.append((float(m["loss"]), jax.device_get(state.params)))
    assert abs(outs[0][0] - outs[1][0]) < 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5, rtol=1e-4),
        outs[0][1], outs[1][1])
