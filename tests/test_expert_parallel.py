"""MoE model + expert-parallel engine on the fake 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.engines.expert_parallel import ExpertParallelEngine
from distributed_tensorflow_tpu.models import create_model
from distributed_tensorflow_tpu.models.moe import MoELayer
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def _ep_mesh(dp=2, ep=4):
    return meshlib.create_mesh(dp * ep, shape=(dp, ep),
                               axis_names=(meshlib.DATA_AXIS,
                                           meshlib.EXPERT_AXIS))


def test_moe_forward_shape():
    model = create_model("moe", num_classes=10, num_experts=4,
                         embed_dim=32, expert_hidden=64)
    x = jnp.ones((16, 28, 28, 1))
    params = model.init(jax.random.key(0), x, train=False)["params"]
    logits = model.apply({"params": params}, x, train=False)
    assert logits.shape == (16, 10)
    assert logits.dtype == jnp.float32


def test_moe_layer_routing_capacity():
    """Every kept token lands in exactly one (expert, slot); over-capacity
    tokens are dropped (zero output row), never double-booked."""
    layer = MoELayer(num_experts=4, hidden=16, capacity_factor=1.0)
    x = jax.random.normal(jax.random.key(1), (32, 8))
    params = layer.init(jax.random.key(0), x)["params"]

    # re-derive the dispatch tensor exactly as the layer builds it
    probs = jax.nn.softmax(x @ params["gate"], axis=-1)
    top1 = jnp.argmax(probs, axis=-1)
    mask = jax.nn.one_hot(top1, 4)
    capacity = 8  # 1.0 * 32 / 4
    position = (jnp.cumsum(mask, axis=0) - 1.0) * mask
    keep = mask * (position < capacity)
    dispatch = keep[:, :, None] * jax.nn.one_hot(
        position.astype(jnp.int32), capacity)
    # ≤ 1 slot per token; ≤ 1 token per slot
    assert float(dispatch.sum(axis=(1, 2)).max()) <= 1.0
    assert float(dispatch.sum(axis=0).max()) <= 1.0
    # all tokens within capacity for their expert are kept
    per_expert = mask.sum(axis=0)
    expected_kept = float(jnp.minimum(per_expert, capacity).sum())
    assert float(dispatch.sum()) == pytest.approx(expected_kept)


def test_moe_aux_loss_sown():
    model = create_model("moe", num_classes=10, num_experts=4, depth=2,
                         embed_dim=16, expert_hidden=16)
    x = jnp.ones((8, 28, 28, 1))
    variables = model.init(jax.random.key(0), x, train=False)
    _, col = model.apply({"params": variables["params"]}, x, train=False,
                         mutable=["intermediates"])
    from distributed_tensorflow_tpu.engines.expert_parallel import _collect

    aux = _collect(col["intermediates"], "aux_loss")
    assert len(aux) == 2  # one per MoE layer
    for a in aux:
        assert float(jnp.squeeze(jnp.asarray(a))) >= 1.0  # lower bound at uniform
    # the other per-layer diagnostics ride alongside
    assert len(_collect(col["intermediates"], "z_loss")) == 2
    assert len(_collect(col["intermediates"], "overflow")) == 2


def test_expert_parallel_trains(mesh8):
    mesh = _ep_mesh(dp=2, ep=4)
    model = create_model("moe", num_classes=10, num_experts=8,
                         embed_dim=32, expert_hidden=32,
                         partition_experts=True)
    eng = ExpertParallelEngine(model, mesh=mesh, learning_rate=5e-3)
    rng = np.random.default_rng(0)
    x = rng.random((64, 28, 28, 1), np.float32)
    y = (np.arange(64) % 10).astype(np.int32)
    state = eng.init_state(jax.random.key(0), x)

    # expert weights actually sharded over the expert axis
    w1 = state.params["MoELayer_0"]["w1"]
    spec = w1.sharding.spec
    assert spec[0] == meshlib.EXPERT_AXIS

    xs, ys = eng.shard_batch(x, y)
    state, first = eng.step(state, xs, ys)
    for _ in range(40):
        state, m = eng.step(state, xs, ys)
    assert float(m["loss"]) < float(first["loss"])


def test_expert_parallel_eval_matches_replicated_forward():
    """EP-sharded eval must agree with an unsharded single-device forward."""
    mesh = _ep_mesh(dp=2, ep=4)
    model = create_model("moe", num_classes=10, num_experts=8,
                         embed_dim=16, expert_hidden=16,
                         partition_experts=True)
    eng = ExpertParallelEngine(model, mesh=mesh)
    rng = np.random.default_rng(1)
    x = rng.random((32, 28, 28, 1), np.float32)
    y = (np.arange(32) % 10).astype(np.int32)
    state = eng.init_state(jax.random.key(0), x)

    from distributed_tensorflow_tpu.data.loaders import Dataset

    ds = Dataset(x=x, y=y, num_classes=10)
    ev = eng.evaluate(state, ds, batch_size=16)

    params = jax.tree.map(
        lambda p: np.asarray(p.value if hasattr(p, "value") else p),
        state.params, is_leaf=lambda p: hasattr(p, "value"))
    logits = model.apply({"params": params}, jnp.asarray(x), train=False)
    ref_acc = float((logits.argmax(-1) == y).mean())
    assert ev["accuracy"] == pytest.approx(ref_acc, abs=1e-6)
    assert ev["count"] == 32


def test_expert_parallel_rejects_wrong_mesh(mesh8):
    model = create_model("moe", num_classes=10)
    with pytest.raises(ValueError):
        ExpertParallelEngine(model, mesh=mesh8)


@pytest.mark.slow
def test_harness_expert_parallel_cli():
    from distributed_tensorflow_tpu.cli import main

    summary = main([
        "-m", "tpu_pod", "-n", "8", "-b", "8", "-ep", "4",
        "--num-experts", "8", "--model", "moe", "--dataset", "synthetic",
        "--log-every", "0",
    ])
    assert summary["engine"] == "expert_parallel"
    assert summary["expert_parallel"] == 4
    assert summary["n_devices"] == 8
    assert summary["test_accuracy"] > 0.5  # synthetic task is easy


# ----------------------------------------------------------- top-2 routing


def test_moe_top2_gates_sum_to_one_and_respect_capacity():
    """Top-2 (GShard): each token's two gates renormalize to 1 across its
    chosen experts; dispatch stays one-hot per (expert, slot); top-1
    assignments claim capacity slots before any top-2 assignment."""
    # capacity_factor=4 → capacity == tokens: max possible per-expert load
    # (a token contributes each expert at most once), so zero drops are
    # GUARANTEED regardless of how unbalanced the fresh router is
    layer = MoELayer(num_experts=4, hidden=16, capacity_factor=4.0,
                     router_top_k=2)
    x = jax.random.normal(jax.random.key(2), (32, 8))
    params = layer.init(jax.random.key(0), x)["params"]
    _, col = layer.apply({"params": params}, x, mutable=["intermediates"])

    probs = jax.nn.softmax(x @ params["gate"], axis=-1)
    mask1 = jax.nn.one_hot(jnp.argmax(probs, axis=-1), 4)
    mask2 = jax.nn.one_hot(jnp.argmax(probs * (1 - mask1), axis=-1), 4)
    p1 = (probs * mask1).sum(-1)
    p2 = (probs * mask2).sum(-1)
    np.testing.assert_allclose(p1 / (p1 + p2) + p2 / (p1 + p2),
                               np.ones(32), atol=1e-6)
    # ample capacity: nothing dropped, overflow reports 0
    assert float(col["intermediates"]["overflow"][0]) == pytest.approx(0.0)


def test_moe_overflow_metric_reports_drops():
    """Tiny capacity must show up as a nonzero overflow fraction — the
    observable for router collapse (VERDICT r2 weak #7: drops were silent)."""
    layer = MoELayer(num_experts=4, hidden=16, capacity_factor=0.25,
                     router_top_k=1)
    x = jax.random.normal(jax.random.key(3), (64, 8))
    params = layer.init(jax.random.key(0), x)["params"]
    _, col = layer.apply({"params": params}, x, mutable=["intermediates"])
    assert float(col["intermediates"]["overflow"][0]) > 0.1


def test_expert_parallel_top2_trains_and_reports_overflow(mesh8):
    """End-to-end: top-2 + router z-loss through the EP engine on the fake
    mesh; metrics carry the overflow diagnostic."""
    mesh = _ep_mesh()
    model = create_model("moe", num_classes=4, num_experts=4, embed_dim=16,
                         expert_hidden=32, router_top_k=2,
                         partition_experts=True)
    eng = ExpertParallelEngine(model, mesh=mesh, learning_rate=5e-3,
                               router_z_weight=1e-3)
    rnd = np.random.default_rng(0)
    x = rnd.random((32, 28, 28, 1), np.float32)
    y = (np.arange(32) % 4).astype(np.int32)
    state = eng.init_state(jax.random.key(0), x)
    losses = []
    for _ in range(30):
        state, m = eng.step(state, *eng.shard_batch(x, y))
        losses.append(float(m["loss"]))
    assert "overflow" in m and 0.0 <= float(m["overflow"]) <= 1.0
    assert losses[-1] < losses[0], losses[::10]


def test_harness_router_flags():
    from distributed_tensorflow_tpu.cli import build_parser

    args = build_parser().parse_args(
        ["-ep", "4", "--model", "moe", "--router-top-k", "2",
         "--router-z-weight", "1e-3"])
    assert args.router_top_k == 2
    assert args.router_z_weight == pytest.approx(1e-3)


# ------------------------------------------------------------- ep × tp


def test_expert_tp_trains_with_2d_sharded_experts():
    """dp×ep×tp: experts shard over 'expert', each expert's FFN Megatron-
    split over 'model' — both visible in the weight sharding spec — and
    training still converges."""
    mesh = meshlib.create_mesh(
        8, shape=(2, 2, 2),
        axis_names=(meshlib.DATA_AXIS, meshlib.EXPERT_AXIS,
                    meshlib.MODEL_AXIS))
    model = create_model("moe", num_classes=10, num_experts=4,
                         embed_dim=32, expert_hidden=32,
                         partition_experts=True, partition_model=True)
    eng = ExpertParallelEngine(model, mesh=mesh, learning_rate=5e-3)
    rng = np.random.default_rng(0)
    x = rng.random((32, 28, 28, 1), np.float32)
    y = (np.arange(32) % 10).astype(np.int32)
    state = eng.init_state(jax.random.key(0), x)

    w1 = state.params["MoELayer_0"]["w1"]
    w2 = state.params["MoELayer_0"]["w2"]
    assert w1.sharding.spec[0] == meshlib.EXPERT_AXIS
    assert w1.sharding.spec[2] == meshlib.MODEL_AXIS  # column-parallel
    assert w2.sharding.spec[1] == meshlib.MODEL_AXIS  # row-parallel

    xs, ys = eng.shard_batch(x, y)
    state, first = eng.step(state, xs, ys)
    for _ in range(40):
        state, m = eng.step(state, xs, ys)
    assert float(m["loss"]) < float(first["loss"])


def test_moe_partition_model_requires_experts():
    layer = MoELayer(num_experts=4, hidden=16, partition_model=True,
                     partition_experts=False)
    x = jax.random.normal(jax.random.key(0), (8, 8))
    with pytest.raises(ValueError, match="partition_experts"):
        layer.init(jax.random.key(0), x)


@pytest.mark.slow
def test_harness_expert_tp_cli():
    from distributed_tensorflow_tpu.cli import main

    summary = main([
        "-m", "tpu_pod", "-n", "8", "-b", "8", "-ep", "2", "-tp", "2",
        "--num-experts", "4", "--model", "moe", "--dataset", "synthetic",
        "--log-every", "0",
    ])
    assert summary["engine"] == "expert_tp[dp*ep*tp]"
    assert summary["n_devices"] == 8
    assert summary["test_accuracy"] > 0.5


# --------------------------------------------------- overflow watch (loud)


def test_overflow_monitor_warns_once_per_episode():
    """Sustained high overflow warns exactly once, re-arming only after the
    window mean recovers below threshold (VERDICT r3 #10)."""
    from distributed_tensorflow_tpu.engines.expert_parallel import (
        _OverflowMonitor)

    mon = _OverflowMonitor(threshold=0.25, window=5)
    with pytest.warns(UserWarning, match="capacity_factor"):
        for _ in range(5):
            mon.observe(0.9)
    assert mon.warning_count == 1
    assert mon.last_window_mean == pytest.approx(0.9)
    # still high: no second warning while un-armed
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        for _ in range(5):
            mon.observe(0.8)
    assert mon.warning_count == 1
    # recovery re-arms ...
    with _w.catch_warnings():
        _w.simplefilter("error")
        for _ in range(5):
            mon.observe(0.0)
    # ... so a new collapse warns again
    with pytest.warns(UserWarning):
        for _ in range(5):
            mon.observe(0.8)
    assert mon.warning_count == 2


def test_collapsed_router_warns_through_engine():
    """A capacity_factor starving the experts must surface as the loud
    warning within a few steps, and the monitor's report carries the
    summary fields."""
    moe = create_model("moe", num_classes=10, num_experts=4, embed_dim=16,
                       expert_hidden=16, partition_experts=True,
                       capacity_factor=0.05)
    eng = ExpertParallelEngine(moe, mesh=_ep_mesh(), overflow_window=3)
    rnd = np.random.default_rng(0)
    x = rnd.random((16, 28, 28, 1), np.float32)
    y = (np.arange(16) % 10).astype(np.int32)
    state = eng.init_state(jax.random.key(0), x)
    with pytest.warns(UserWarning, match="overflow"):
        for _ in range(3):
            state, m = eng.step(state, *eng.shard_batch(x, y))
    rep = eng.overflow_monitor.report()
    assert rep["expert_overflow_warnings"] >= 1
    assert rep["expert_overflow_window_mean"] > 0.25


# round 20 fast-lane repair: grad-accum parity variant —
# test_expert_parallel_grad_accum_trains keeps the fast representative
@pytest.mark.slow
def test_expert_parallel_grad_accum_parity(mesh8):
    """grad_accum=2 with no capacity pressure (capacity_factor=num_experts
    → zero drops) and aux_weight=0 is pure scheduling: task grads are
    linear in the batch, so the K=2 step matches K=1.  (With aux losses or
    tight capacity the per-chunk routing statistics legitimately differ —
    that is microbatched MoE semantics, not an accumulation bug.)"""
    import optax

    rng = np.random.default_rng(0)
    x = rng.random((16, 28, 28, 1), np.float32)
    y = (np.arange(16) % 10).astype(np.int32)
    mesh = _ep_mesh(2, 4)
    out = {}
    for K in (1, 2):
        model = create_model("moe", num_classes=10, num_experts=4,
                             embed_dim=16, expert_hidden=16,
                             capacity_factor=4.0, dropout_rate=0.0,
                             partition_experts=True)
        eng = ExpertParallelEngine(model, optimizer=optax.sgd(0.1),
                                   mesh=mesh, aux_weight=0.0,
                                   router_z_weight=0.0, grad_accum=K)
        st = eng.init_state(jax.random.key(0), x)
        st, m = eng.step(st, *eng.shard_batch(x, y))
        out[K] = (float(m["loss"]), float(m["overflow"]),
                  jax.device_get(st.params))
    assert out[1][1] == 0.0 and out[2][1] == 0.0  # no drops by construction
    assert out[1][0] == pytest.approx(out[2][0], abs=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5),
        out[1][2], out[2][2])


def test_expert_parallel_grad_accum_trains(mesh8):
    """Accumulated MoE training with the real aux losses still learns."""
    rng = np.random.default_rng(1)
    x = rng.random((32, 28, 28, 1), np.float32)
    y = (np.arange(32) % 10).astype(np.int32)
    model = create_model("moe", num_classes=10, num_experts=4,
                         embed_dim=16, expert_hidden=32,
                         partition_experts=True)
    eng = ExpertParallelEngine(model, mesh=_ep_mesh(2, 4), learning_rate=1e-2,
                               grad_accum=2)
    st = eng.init_state(jax.random.key(0), x)
    xs, ys = eng.shard_batch(x, y)
    st, first = eng.step(st, xs, ys)
    for _ in range(20):
        st, m = eng.step(st, xs, ys)
    assert float(m["loss"]) < float(first["loss"])


def test_moe_grouped_routing_matches_ungrouped_when_dropfree():
    """GShard G×S grouped routing (group_size) is a cost optimization, not
    a math change, when capacity never binds: with capacity_factor =
    num_experts (zero drops) the grouped forward must equal the one-group
    forward token-for-token."""
    layer1 = MoELayer(num_experts=4, hidden=16, capacity_factor=4.0)
    layerg = MoELayer(num_experts=4, hidden=16, capacity_factor=4.0,
                      group_size=8)
    x = jax.random.normal(jax.random.key(3), (32, 8))
    params = layer1.init(jax.random.key(0), x)["params"]
    y1 = layer1.apply({"params": params}, x)
    yg = layerg.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(yg),
                               atol=1e-5, rtol=1e-5)


def test_moe_grouped_routing_capacity_is_per_group():
    """Capacity binds per group of S tokens (k·cf·S/E), so a group whose
    tokens all route to one expert drops everything past its own slots —
    even if other groups' slots are idle."""
    layer = MoELayer(num_experts=2, hidden=8, capacity_factor=1.0,
                     group_size=4)  # capacity = 1·1.0·4/2 = 2 per group
    # strictly positive features so a [+50, -50] gate row routes EVERY
    # token to expert 0 (with sign-mixed x the forcing would be
    # sign-of-sum dependent)
    x = jax.random.uniform(jax.random.key(5), (8, 4), minval=0.5,
                           maxval=1.0)
    params = layer.init(jax.random.key(0), x)["params"]
    forced = {"gate": jnp.asarray([[50.0, -50.0]] * 4),
              "w1": params["w1"], "w2": params["w2"]}
    _, col = layer.apply({"params": forced}, x,
                         mutable=["intermediates"])
    # 8 assignments, 2 groups × 2 slots kept → overflow = 1 - 4/8 = 0.5
    overflow = float(col["intermediates"]["overflow"][0])
    assert overflow == pytest.approx(0.5)


def test_moe_group_size_selection():
    """Group picking is static and floor-guarded: ≤target → one group;
    power-of-two divisor in [256, 1024] when available; tiny divisors
    (tokens with small 2-adic valuation) fall back to one group rather
    than tiny token-dropping groups."""
    from distributed_tensorflow_tpu.models.moe import _moe_group_size

    assert _moe_group_size(1024) is None      # fits one group
    assert _moe_group_size(8192) == 1024
    assert _moe_group_size(4096) == 1024
    assert _moe_group_size(1536) == 512       # 1536 = 3·512
    assert _moe_group_size(2000) is None      # best divisor 16 < floor
    assert _moe_group_size(1025) is None      # odd
