"""MoE model + expert-parallel engine on the fake 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.engines.expert_parallel import ExpertParallelEngine
from distributed_tensorflow_tpu.models import create_model
from distributed_tensorflow_tpu.models.moe import MoELayer
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def _ep_mesh(dp=2, ep=4):
    return meshlib.create_mesh(dp * ep, shape=(dp, ep),
                               axis_names=(meshlib.DATA_AXIS,
                                           meshlib.EXPERT_AXIS))


def test_moe_forward_shape():
    model = create_model("moe", num_classes=10, num_experts=4,
                         embed_dim=32, expert_hidden=64)
    x = jnp.ones((16, 28, 28, 1))
    params = model.init(jax.random.key(0), x, train=False)["params"]
    logits = model.apply({"params": params}, x, train=False)
    assert logits.shape == (16, 10)
    assert logits.dtype == jnp.float32


def test_moe_layer_routing_capacity():
    """Every kept token lands in exactly one (expert, slot); over-capacity
    tokens are dropped (zero output row), never double-booked."""
    layer = MoELayer(num_experts=4, hidden=16, capacity_factor=1.0)
    x = jax.random.normal(jax.random.key(1), (32, 8))
    params = layer.init(jax.random.key(0), x)["params"]

    # re-derive the dispatch tensor exactly as the layer builds it
    probs = jax.nn.softmax(x @ params["gate"], axis=-1)
    top1 = jnp.argmax(probs, axis=-1)
    mask = jax.nn.one_hot(top1, 4)
    capacity = 8  # 1.0 * 32 / 4
    position = (jnp.cumsum(mask, axis=0) - 1.0) * mask
    keep = mask * (position < capacity)
    dispatch = keep[:, :, None] * jax.nn.one_hot(
        position.astype(jnp.int32), capacity)
    # ≤ 1 slot per token; ≤ 1 token per slot
    assert float(dispatch.sum(axis=(1, 2)).max()) <= 1.0
    assert float(dispatch.sum(axis=0).max()) <= 1.0
    # all tokens within capacity for their expert are kept
    per_expert = mask.sum(axis=0)
    expected_kept = float(jnp.minimum(per_expert, capacity).sum())
    assert float(dispatch.sum()) == pytest.approx(expected_kept)


def test_moe_aux_loss_sown():
    model = create_model("moe", num_classes=10, num_experts=4, depth=2,
                         embed_dim=16, expert_hidden=16)
    x = jnp.ones((8, 28, 28, 1))
    variables = model.init(jax.random.key(0), x, train=False)
    _, col = model.apply({"params": variables["params"]}, x, train=False,
                         mutable=["intermediates"])
    aux = jax.tree.leaves(col["intermediates"])
    assert len(aux) == 2  # one per MoE layer
    for a in aux:
        assert float(a) >= 1.0  # Switch aux loss lower bound at uniform


def test_expert_parallel_trains(mesh8):
    mesh = _ep_mesh(dp=2, ep=4)
    model = create_model("moe", num_classes=10, num_experts=8,
                         embed_dim=32, expert_hidden=32,
                         partition_experts=True)
    eng = ExpertParallelEngine(model, mesh=mesh, learning_rate=5e-3)
    rng = np.random.default_rng(0)
    x = rng.random((64, 28, 28, 1), np.float32)
    y = (np.arange(64) % 10).astype(np.int32)
    state = eng.init_state(jax.random.key(0), x)

    # expert weights actually sharded over the expert axis
    w1 = state.params["MoELayer_0"]["w1"]
    spec = w1.sharding.spec
    assert spec[0] == meshlib.EXPERT_AXIS

    xs, ys = eng.shard_batch(x, y)
    state, first = eng.step(state, xs, ys)
    for _ in range(40):
        state, m = eng.step(state, xs, ys)
    assert float(m["loss"]) < float(first["loss"])


def test_expert_parallel_eval_matches_replicated_forward():
    """EP-sharded eval must agree with an unsharded single-device forward."""
    mesh = _ep_mesh(dp=2, ep=4)
    model = create_model("moe", num_classes=10, num_experts=8,
                         embed_dim=16, expert_hidden=16,
                         partition_experts=True)
    eng = ExpertParallelEngine(model, mesh=mesh)
    rng = np.random.default_rng(1)
    x = rng.random((32, 28, 28, 1), np.float32)
    y = (np.arange(32) % 10).astype(np.int32)
    state = eng.init_state(jax.random.key(0), x)

    from distributed_tensorflow_tpu.data.loaders import Dataset

    ds = Dataset(x=x, y=y, num_classes=10)
    ev = eng.evaluate(state, ds, batch_size=16)

    params = jax.tree.map(
        lambda p: np.asarray(p.value if hasattr(p, "value") else p),
        state.params, is_leaf=lambda p: hasattr(p, "value"))
    logits = model.apply({"params": params}, jnp.asarray(x), train=False)
    ref_acc = float((logits.argmax(-1) == y).mean())
    assert ev["accuracy"] == pytest.approx(ref_acc, abs=1e-6)
    assert ev["count"] == 32


def test_expert_parallel_rejects_wrong_mesh(mesh8):
    model = create_model("moe", num_classes=10)
    with pytest.raises(ValueError):
        ExpertParallelEngine(model, mesh=mesh8)


def test_harness_expert_parallel_cli():
    from distributed_tensorflow_tpu.cli import main

    summary = main([
        "-m", "tpu_pod", "-n", "8", "-b", "8", "-ep", "4",
        "--num-experts", "8", "--model", "moe", "--dataset", "synthetic",
        "--log-every", "0",
    ])
    assert summary["engine"] == "expert_parallel"
    assert summary["expert_parallel"] == 4
    assert summary["n_devices"] == 8
    assert summary["test_accuracy"] > 0.5  # synthetic task is easy
