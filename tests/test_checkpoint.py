"""Checkpoint/resume + metrics tests (capabilities the reference lacks —
SURVEY.md §5 rows 'Checkpoint / resume' and 'Metrics / logging')."""

import json

import jax
import numpy as np
import pytest

from distributed_tensorflow_tpu.data.loaders import Dataset, synthetic_classification
from distributed_tensorflow_tpu.engines import AsyncLocalEngine, SyncEngine, Trainer
from distributed_tensorflow_tpu.models import create_model
from distributed_tensorflow_tpu.utils.checkpoint import CheckpointManager
from distributed_tensorflow_tpu.utils.metrics import MetricsLogger, StepTimer


def tiny_data(n=256, split="train"):
    x, y = synthetic_classification((8, 8), 4, n, seed=3, split=split)
    return Dataset(x=x, y=y, num_classes=4, name="tiny", synthetic=True)


def tiny_model():
    return create_model("mlp", num_classes=4, hidden=32)


def assert_states_equal(a, b):
    def as_np(x):
        if hasattr(x, "dtype") and jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key):
            x = jax.random.key_data(x)
        return np.asarray(jax.device_get(x))

    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(as_np(x), as_np(y))


def test_save_restore_roundtrip(mesh8, tmp_path):
    train = tiny_data()
    eng = SyncEngine(tiny_model(), mesh=mesh8)
    state = eng.init_state(jax.random.key(0), train.x[:8])
    xs, ys = eng.shard_batch(train.x[:64], train.y[:64])
    state, _ = eng.step(state, xs, ys)
    jax.block_until_ready(state)

    mgr = CheckpointManager(tmp_path / "ckpt")
    mgr.save(state)
    assert mgr.latest_step() == 1

    template = eng.init_state(jax.random.key(0), train.x[:8])
    restored = mgr.restore(template)
    assert_states_equal(state, restored)
    # restored state is usable for further steps
    restored, m = eng.step(restored, xs, ys)
    assert float(m["loss"]) > 0


def test_restore_preserves_training_trajectory(mesh8, tmp_path):
    """Train 2 steps → checkpoint → 2 more; vs restore-at-2 → 2 more.
    Final params must be identical (exact resume)."""
    train = tiny_data()
    x, y = train.x[:64], train.y[:64]

    eng = SyncEngine(tiny_model(), mesh=mesh8)
    state = eng.init_state(jax.random.key(0), x)
    xs, ys = eng.shard_batch(x, y)
    for _ in range(2):
        state, _ = eng.step(state, xs, ys)
    jax.block_until_ready(state)
    mgr = CheckpointManager(tmp_path / "c")
    mgr.save(state)
    for _ in range(2):
        state, _ = eng.step(state, xs, ys)

    resumed = mgr.restore(eng.init_state(jax.random.key(0), x))
    for _ in range(2):
        resumed, _ = eng.step(resumed, xs, ys)
    assert_states_equal(state, resumed)


def test_checkpoint_per_device_state(mesh8, tmp_path):
    """Async engine state is stacked per-device and sharded — must survive
    the round trip with per-device values intact."""
    train = tiny_data()
    eng = AsyncLocalEngine(tiny_model(), mesh=mesh8, sync_every=100)
    state = eng.init_state(jax.random.key(0), train.x[:8])
    xs, ys = eng.shard_batch(train.x[:64], train.y[:64])
    state, _ = eng.step(state, xs, ys)  # devices diverge (no sync yet)
    jax.block_until_ready(state)

    mgr = CheckpointManager(tmp_path / "c")
    mgr.save(state, step=1)
    restored = mgr.restore(eng.init_state(jax.random.key(0), train.x[:8]), step=1)
    assert_states_equal(state, restored)
    leaf = jax.device_get(jax.tree.leaves(restored.params)[0])
    assert np.abs(leaf - leaf.mean(axis=0, keepdims=True)).max() > 1e-7


def test_retention(mesh8, tmp_path):
    train = tiny_data()
    eng = SyncEngine(tiny_model(), mesh=mesh8)
    state = eng.init_state(jax.random.key(0), train.x[:8])
    mgr = CheckpointManager(tmp_path / "c", max_to_keep=2)
    for s in (1, 2, 3, 4):
        mgr.save(state, step=s)
    assert mgr.steps() == [3, 4]


def test_trainer_checkpoint_integration(mesh8, tmp_path):
    train = tiny_data()
    mgr = CheckpointManager(tmp_path / "c")
    tr = Trainer(tiny_model(), mesh=mesh8)
    tr.fit(train, epochs=1, batch_size=64, log_every=0,
           checkpoint_manager=mgr, checkpoint_every=2)
    steps = len(train) // 64
    assert mgr.latest_step() == steps  # final checkpoint present


def test_metrics_logger(tmp_path):
    path = tmp_path / "m.jsonl"
    ml = MetricsLogger(path, log_every=2)
    for s in range(1, 7):
        ml.log(s, loss=1.0 / s)
    ml.close()  # drain the async sink (flush-on-close contract)
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    assert [r["step"] for r in recs] == [2, 4, 6]
    assert recs[0]["loss"] == pytest.approx(0.5)
    assert all(r["schema_version"] == 1 for r in recs)


def test_step_timer():
    t = StepTimer()
    for _ in range(5):
        with t:
            pass
    s = t.summary()
    assert s["steps"] == 5
    assert s["total_s"] >= 0
    assert "p90_s" in s and "first_step_s" in s


def test_checkpoint_cross_remat_restore(mesh8, tmp_path):
    """A checkpoint saved WITHOUT --remat must restore into a --remat model
    (and keep training identically): the remat flag only changes the
    gradient schedule, so the param-tree paths must match exactly.  Guards
    the nn.remat scope-rename regression (models/gpt.py GPTLM.remat)."""
    import optax

    from distributed_tensorflow_tpu.models import create_model
    from distributed_tensorflow_tpu.parallel import mesh as meshlib

    kw = dict(num_classes=64, hidden=32, layers=2, heads=2, ffn=64,
              max_len=64, dropout_rate=0.0)
    rng = np.random.default_rng(0)
    x = rng.integers(1, 64, (16, 16)).astype(np.int32)
    y = np.roll(x, -1, axis=1)

    plain = SyncEngine(create_model("gpt", remat=False, **kw),
                       optimizer=optax.sgd(0.1), mesh=mesh8)
    state = plain.init_state(jax.random.key(0), x)
    xs, ys = plain.shard_batch(x, y)
    state, _ = plain.step(state, xs, ys)
    jax.block_until_ready(state)
    mgr = CheckpointManager(tmp_path / "x")
    mgr.save(state)

    rem = SyncEngine(create_model("gpt", remat=True, **kw),
                     optimizer=optax.sgd(0.1), mesh=mesh8)
    template = rem.init_state(jax.random.key(0), x)
    restored = mgr.restore(template)   # raises if param paths diverge
    assert_states_equal(state, restored)

    # both continue from the restored point with matching trajectories
    # (allclose, not exact: remat's backward recompute fuses differently,
    # so params drift at the ~1e-10 float-reassociation level)
    state, m0 = plain.step(state, xs, ys)
    restored, m1 = rem.step(restored, xs, ys)
    assert float(m0["loss"]) == pytest.approx(float(m1["loss"]), abs=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=1e-6, rtol=1e-5),
        jax.device_get(state.params), jax.device_get(restored.params))


def test_checkpoint_ep_sp_composite_roundtrip(tmp_path):
    """ep×sp composite state (GSPMD expert-sharded MoE params inside a
    manual-seq engine) survives an orbax save/restore and keeps training
    identically — the MoE/composite counterpart of the sync roundtrip."""
    import optax

    from distributed_tensorflow_tpu.engines.composite import CompositeEngine
    from distributed_tensorflow_tpu.models import create_model
    from distributed_tensorflow_tpu.parallel import mesh as meshlib

    rng = np.random.default_rng(7)
    x = rng.integers(0, 64, (8, 32)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    mesh = meshlib.create_mesh(
        8, shape=(2, 2, 2),
        axis_names=(meshlib.DATA_AXIS, meshlib.EXPERT_AXIS,
                    meshlib.SEQ_AXIS))

    def build():
        m = create_model("gpt", num_classes=64, hidden=32, layers=1,
                         heads=2, ffn=64, max_len=64, dropout_rate=0.0,
                         attention_impl="ring", moe_experts=4,
                         partition_experts=True)
        return CompositeEngine(m, optimizer=optax.sgd(0.1), mesh=mesh)

    eng = build()
    state = eng.init_state(jax.random.key(0), x)
    xs, ys = eng.shard_batch(x, y)
    state, _ = eng.step(state, xs, ys)
    jax.block_until_ready(state)
    mgr = CheckpointManager(tmp_path / "ep_sp")
    mgr.save(state)

    fresh = build()
    restored = mgr.restore(fresh.init_state(jax.random.key(1), x))
    assert_states_equal(state, restored)
    state, m0 = eng.step(state, xs, ys)
    restored, m1 = fresh.step(restored, xs, ys)
    assert float(m0["loss"]) == pytest.approx(float(m1["loss"]), abs=1e-6)
    assert_states_equal(state, restored)


@pytest.mark.slow
def test_checkpoint_pipeline_roundtrip(tmp_path):
    """Pipe-stacked TrainState (params P('pipe'), per-stage optimizer
    moments) roundtrips through Orbax: restored values identical, restored
    arrays keep the pipe sharding of the template, and training continues
    bit-identically from the restored state — the pipeline engines need no
    special-casing in the checkpoint layer."""
    import optax

    from distributed_tensorflow_tpu.engines.pipeline import PipelineEngine
    from distributed_tensorflow_tpu.parallel import mesh as meshlib

    rng = np.random.default_rng(5)
    x = rng.random((8, 28, 28, 1), np.float32)
    y = (np.arange(8) % 10).astype(np.int32)
    mesh = meshlib.create_mesh(
        8, shape=(2, 4), axis_names=(meshlib.DATA_AXIS, meshlib.PIPE_AXIS))

    def build():
        return PipelineEngine(num_classes=10, hidden=24, microbatches=2,
                              mesh=mesh, optimizer=optax.adam(1e-3))

    eng = build()
    state = eng.init_state(jax.random.key(0), x)
    xs, ys = eng.shard_batch(x, y)
    state, _ = eng.step(state, xs, ys)
    jax.block_until_ready(state)
    mgr = CheckpointManager(tmp_path / "pipe")
    mgr.save(state)

    fresh = build()
    restored = mgr.restore(fresh.init_state(jax.random.key(1), x))
    assert_states_equal(state, restored)
    spec = restored.params["blocks"]["Dense_0"]["kernel"].sharding.spec
    assert spec[0] == meshlib.PIPE_AXIS
    state, m0 = eng.step(state, xs, ys)
    restored, m1 = fresh.step(restored, xs, ys)
    assert float(m0["loss"]) == pytest.approx(float(m1["loss"]), abs=1e-6)
    assert_states_equal(state, restored)


@pytest.mark.slow
def test_pipeline_checkpoint_resume_through_harness(tmp_path):
    """`-pp 2 --checkpoint-dir D` then `--resume`: the harness run restores
    the pipe-stacked state and continues the step numbering."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    common = dict(engine="sync", model="mlp", dataset="synthetic",
                  n_devices=8, pipeline_parallel=2, microbatches=2,
                  pipeline_hidden=16, batch_size=8, epochs=1, log_every=0,
                  checkpoint_dir=str(tmp_path / "harness_pipe"))
    first = run(ExperimentConfig(**common))
    assert first["engine"] == "pipeline_parallel"
    mgr = CheckpointManager(common["checkpoint_dir"])
    assert mgr.latest_step() == first["steps"]
    second = run(ExperimentConfig(**common, resume=True))
    assert np.isfinite(second["test_loss"])
    # the restored run continues the ORIGINAL step numbering (Trainer's
    # global step offset), so the final checkpoint lands at 2x — a silent
    # from-scratch restart would leave latest_step at first["steps"]
    assert mgr.latest_step() == 2 * first["steps"]
