"""Pallas paged decode-attention kernel (ISSUE 16): the fused
``ops/paged_attention.py`` kernel pinned against its pure-jnp oracle
``paged_attention_reference`` — MHA and GQA head layouts, the decode
(l_q=1) and speculative-verify (l_q=k+1) query widths, in-kernel int8
dequant, block-table aliasing, the per-slot length mask, and the GSPMD
mesh variant.  Everything runs in Pallas interpret mode on this
container's CPU devices (the kernel's off-TPU default).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.ops.paged_attention import (
    paged_attention, paged_attention_reference)


def _case(seed, *, s=4, l_q=1, h=4, kvh=None, d=8, blk=4, mb=4,
          n=None, int8=False):
    """Random pools + a PERMUTED block table (physical ids deliberately
    non-contiguous and out of order — the indirection under test) and
    in-range positions leaving every query row at least one valid key."""
    kvh = kvh if kvh is not None else h
    n = n if n is not None else s * mb + 2
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((s, l_q, h, d)), jnp.float32)
    if int8:
        k_pool = jnp.asarray(
            rng.integers(-127, 128, (n, blk, kvh, d)), jnp.int8)
        v_pool = jnp.asarray(
            rng.integers(-127, 128, (n, blk, kvh, d)), jnp.int8)
        k_scale = jnp.asarray(
            rng.uniform(0.5, 1.5, (n, blk, kvh)) / 127.0, jnp.float32)
        v_scale = jnp.asarray(
            rng.uniform(0.5, 1.5, (n, blk, kvh)) / 127.0, jnp.float32)
    else:
        k_pool = jnp.asarray(
            rng.standard_normal((n, blk, kvh, d)), jnp.float32)
        v_pool = jnp.asarray(
            rng.standard_normal((n, blk, kvh, d)), jnp.float32)
        k_scale = v_scale = None
    bt = jnp.asarray(
        rng.permutation(n)[:s * mb].reshape(s, mb), jnp.int32)
    pos = jnp.asarray(
        rng.integers(1, mb * blk - l_q + 1, s), jnp.int32)
    return q, k_pool, v_pool, bt, pos, k_scale, v_scale


def _both(q, k_pool, v_pool, bt, pos, k_scale=None, v_scale=None):
    out = paged_attention(q, k_pool, v_pool, bt, pos,
                          k_scale=k_scale, v_scale=v_scale)
    ref = paged_attention_reference(q, k_pool, v_pool, bt, pos,
                                    k_scale=k_scale, v_scale=v_scale)
    return np.asarray(out), np.asarray(ref)


def test_kernel_matches_reference_decode_mha():
    """l_q=1 MHA decode: the fused online-softmax accumulation matches
    the dense masked-softmax oracle to f32 reassociation tolerance."""
    out, ref = _both(*_case(0)[:5])
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=2e-5)


def test_kernel_matches_reference_gqa():
    """GQA (heads=4 over kv_heads=2): the kernel folds query groups into
    the kv-head grid axis; the oracle widens kv heads by repeat — same
    numbers either way."""
    q, k, v, bt, pos, _, _ = _case(1, h=4, kvh=2)
    out, ref = _both(q, k, v, bt, pos)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=2e-5)


def test_kernel_block_query_verify_width():
    """The (slots, k+1) speculative-verify variant: each query row r
    attends keys ``t <= pos + r`` — the staircase mask the verify
    program's correctness rests on."""
    q, k, v, bt, pos, _, _ = _case(2, l_q=3, h=4, kvh=2)
    out, ref = _both(q, k, v, bt, pos)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=2e-5)
    # the staircase is real: row 0 recomputed standalone (l_q=1 at the
    # same position) equals row 0 of the block-query call
    solo = np.asarray(paged_attention(q[:, :1], k, v, bt, pos))
    np.testing.assert_allclose(solo[:, 0], out[:, 0],
                               rtol=1e-5, atol=2e-5)


def test_kernel_int8_dequant_matches_reference():
    """int8 pools + per-vector f32 scales: the kernel dequantizes inside
    the block loop; the oracle dequantizes the whole gather — identical
    math, no materialized f32 pool in the fused path."""
    q, k, v, bt, pos, ks, vs = _case(3, h=4, kvh=2, int8=True)
    out, ref = _both(q, k, v, bt, pos, ks, vs)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=2e-5)


def test_kernel_reads_through_block_aliases():
    """Zero-copy semantics at the kernel level: two slots whose TABLES
    point at the same physical blocks compute identical outputs for
    identical queries — sharing is invisible to the read path."""
    q, k, v, bt, pos, _, _ = _case(4, s=2)
    bt = jnp.stack([bt[0], bt[0]])            # slot 1 aliases slot 0
    pos = jnp.stack([pos[0], pos[0]])
    q = jnp.stack([q[0], q[0]])
    out = np.asarray(paged_attention(q, k, v, bt, pos))
    np.testing.assert_array_equal(out[0], out[1])
    ref = np.asarray(paged_attention_reference(q, k, v, bt, pos))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=2e-5)


def test_kernel_masks_tail_and_unmapped_blocks():
    """The length mask is the ONLY thing protecting reads past a slot's
    position: corrupting pool contents beyond ``pos`` — including whole
    blocks the table maps but the slot never reached — must not change
    the output (the 'unmapped entries hold a valid index' contract)."""
    q, k, v, bt, pos, _, _ = _case(5, s=3, mb=4, blk=4)
    pos = jnp.asarray([2, 5, 9], jnp.int32)   # slots end inside block 0/1/2
    base = np.asarray(paged_attention(q, k, v, bt, pos))
    # poison every pool position strictly past each slot's own pos —
    # conservatively: rebuild pools with garbage in any block only
    # reachable as a DEAD region (per-slot tail blocks)
    k2, v2 = np.array(k), np.array(v)
    for s_i, p_i in enumerate([2, 5, 9]):
        first_dead = p_i // 4 + 1
        for j in range(first_dead, 4):
            bid = int(np.asarray(bt)[s_i, j])
            k2[bid] = 1e4
            v2[bid] = -1e4
    out = np.asarray(paged_attention(q, jnp.asarray(k2), jnp.asarray(v2),
                                     bt, pos))
    np.testing.assert_array_equal(base, out)


def test_kernel_under_gspmd_mesh(mesh8):
    """The serving layout under jit: queries/tables/positions sharded
    over slots on the 8-way data axis, pools replicated (any slot reads
    any block) — the partitioned program still matches the oracle."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu.parallel import mesh as meshlib

    q, k, v, bt, pos, _, _ = _case(6, s=8)
    repl = NamedSharding(mesh8, P())
    row = NamedSharding(mesh8, P(meshlib.DATA_AXIS))
    qd = jax.device_put(q, NamedSharding(
        mesh8, P(meshlib.DATA_AXIS, None, None, None)))
    btd = jax.device_put(bt, NamedSharding(mesh8, P(meshlib.DATA_AXIS,
                                                    None)))
    posd = jax.device_put(pos, row)
    kd, vd = jax.device_put(k, repl), jax.device_put(v, repl)
    out = np.asarray(jax.jit(paged_attention)(qd, kd, vd, btd, posd))
    ref = np.asarray(paged_attention_reference(q, k, v, bt, pos))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=2e-5)


def test_kernel_rejects_bad_head_and_scale_combos():
    q, k, v, bt, pos, ks, vs = _case(7, h=4, kvh=2, int8=True)
    with pytest.raises(ValueError, match="together"):
        paged_attention(q, k, v, bt, pos, k_scale=ks)
    with pytest.raises(ValueError, match="divisible"):
        paged_attention(q[:, :, :3], k, v, bt, pos,
                        k_scale=ks, v_scale=vs)
