"""Optimizer-side features: gradient accumulation and LR schedules.

The reference's optimizer story is a constructor-default Adam applied
forever (reference server.py:52-55); these are the TPU-native extensions
that transformer-scale training needs — both parity-tested, not just smoke-
tested.
"""

import jax
import numpy as np
import optax
import pytest

from distributed_tensorflow_tpu.data.loaders import load_dataset
from distributed_tensorflow_tpu.engines import SyncEngine
from distributed_tensorflow_tpu.models import create_model
from distributed_tensorflow_tpu.parallel import mesh as meshlib
from distributed_tensorflow_tpu.utils.harness import (
    ExperimentConfig, make_lr_schedule, run)


@pytest.fixture(scope="module")
def mnist():
    return load_dataset("mnist", split="train")


# ------------------------------------------------------ grad accumulation


def test_grad_accum_matches_plain(mnist):
    """K microbatches accumulated inside the step must equal the one-shot
    step on the same global batch (SGD + no dropout: exact math, no rng)."""
    x, y = mnist.x[:64], mnist.y[:64]
    model = create_model("mlp", hidden=32, dropout_rate=0.0)
    mesh = meshlib.create_mesh(8)

    def train(k):
        eng = SyncEngine(model, optimizer=optax.sgd(0.1), mesh=mesh,
                         grad_accum=k)
        s = eng.init_state(jax.random.key(0), x)
        for _ in range(2):
            xs, ys = eng.shard_batch(x, y)
            s, m = eng.step(s, xs, ys)
        return s, m

    s1, m1 = train(1)
    s4, m4 = train(4)
    for a, b in zip(jax.tree.leaves(jax.device_get(s1.params)),
                    jax.tree.leaves(jax.device_get(s4.params))):
        np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5)
    assert float(m1["loss"]) == pytest.approx(float(m4["loss"]), abs=1e-5)


def test_grad_accum_indivisible_batch_rejected(mnist):
    eng = SyncEngine(create_model("mlp", hidden=32),
                     mesh=meshlib.create_mesh(8), grad_accum=3)
    s = eng.init_state(jax.random.key(0), mnist.x[:8])
    xs, ys = eng.shard_batch(mnist.x[:32], mnist.y[:32])  # 4 per device
    with pytest.raises(ValueError, match="grad_accum"):
        eng.step(s, xs, ys)


def test_grad_accum_engine_support():
    """grad_accum composes with sync/allreduce/fsdp, tensor_parallel,
    seq_parallel and expert_parallel (round 5); the async/gossip engines
    and the pipeline modes still reject it loudly.  The seq/expert cases
    assert routing-to-the-engine via the cheap divisibility check (a full
    accumulated run is the parity tests' job)."""
    with pytest.raises(ValueError, match="grad_accum"):
        run(ExperimentConfig(engine="async", grad_accum=2, n_devices=8))
    with pytest.raises(ValueError, match="grad_accum"):
        run(ExperimentConfig(model="gpt", dataset="lm_synth",
                             pipeline_parallel=4, grad_accum=2, n_devices=8))
    # seq/expert: accepted (not rejected) — an indivisible K hits the
    # mode's divisibility validation, proving the flag reaches the engine
    # seq: dp=2, global batch 6 → per-shard 3, 3 % 2 != 0
    with pytest.raises(ValueError, match="not divisible by"):
        run(ExperimentConfig(model="bert_tiny", dataset="glue_synth",
                             seq_parallel=4, batch_size=6, grad_accum=2,
                             per_worker_batch=False, n_devices=8))
    # expert: 8 token shards, global batch 9 → 9 % 2 != 0
    with pytest.raises(ValueError, match="not divisible by"):
        run(ExperimentConfig(model="moe", expert_parallel=4, batch_size=9,
                             grad_accum=2, per_worker_batch=False,
                             n_devices=8))


# ------------------------------------------------------------ LR schedules


def test_lr_schedule_shapes():
    cfg = ExperimentConfig(learning_rate=1e-2, lr_schedule="cosine",
                           warmup_steps=10)
    s = make_lr_schedule(cfg, total_steps=100)
    assert float(s(0)) == pytest.approx(0.0, abs=1e-8)
    assert float(s(10)) == pytest.approx(1e-2, rel=1e-3)
    assert float(s(100)) < 1e-3  # decayed

    cfg = ExperimentConfig(learning_rate=1e-2, lr_schedule="linear",
                           warmup_steps=0)
    s = make_lr_schedule(cfg, total_steps=50)
    assert float(s(0)) == pytest.approx(1e-2, rel=1e-6)
    assert float(s(50)) == pytest.approx(0.0, abs=1e-8)

    # warmup + constant: ramps, then holds
    cfg = ExperimentConfig(learning_rate=1e-2, lr_schedule="constant",
                           warmup_steps=5)
    s = make_lr_schedule(cfg, total_steps=50)
    assert float(s(1)) < 1e-2
    assert float(s(40)) == pytest.approx(1e-2, rel=1e-6)

    # default: no schedule object at all (engines use stock adam)
    assert make_lr_schedule(ExperimentConfig(), 100) is None

    with pytest.raises(ValueError, match="lr_schedule"):
        make_lr_schedule(ExperimentConfig(lr_schedule="step"), 100)


def _tiny_mnist_fn(batch_size, type="train", **kw):
    return load_dataset("mnist", split=type, n_synthetic_train=256,
                        n_synthetic_test=128)


def test_harness_warmup_cosine_trains():
    summary = run(ExperimentConfig(
        engine="sync", model="mlp", n_devices=8, batch_size=4, epochs=1,
        lr_schedule="cosine", warmup_steps=3, grad_accum=2, log_every=0,
        dataset_fn=_tiny_mnist_fn))
    assert np.isfinite(summary["test_loss"])
    assert summary["test_accuracy"] > 0.5  # synthetic mnist learns fast


def test_cli_flags_reach_config():
    """--lr-schedule/--warmup-steps/--grad-accum parse and run end-to-end."""
    from distributed_tensorflow_tpu.cli import main

    summary = main(["-m", "t", "-n", "8", "-b", "4", "--lr-schedule",
                    "linear", "--warmup-steps", "2", "--grad-accum", "2",
                    "--log-every", "0"], dataset_fn=_tiny_mnist_fn)
    assert np.isfinite(summary["test_loss"])


# --------------------------------------------- weight decay / grad clipping


def test_weight_decay_shrinks_params(mnist):
    """AdamW vs Adam from identical states: on a zero-gradient direction
    (bias of an unused class would be cleaner, but simplest observable:
    with decay, param norms after a step are strictly smaller than the
    no-decay update from the same start)."""
    from distributed_tensorflow_tpu.utils.harness import _make_optimizer

    x, y = mnist.x[:32], mnist.y[:32]
    model = create_model("mlp", hidden=16, dropout_rate=0.0)
    mesh = meshlib.create_mesh(8)

    def one_step(wd):
        cfg = ExperimentConfig(weight_decay=wd)
        eng = SyncEngine(model, optimizer=_make_optimizer(cfg, mnist, 32),
                         mesh=mesh)
        s = eng.init_state(jax.random.key(0), x)
        for _ in range(3):
            xs, ys = eng.shard_batch(x, y)
            s, _ = eng.step(s, xs, ys)
        return np.sqrt(sum(
            float((np.asarray(jax.device_get(p)) ** 2).sum())
            for p in jax.tree.leaves(s.params)))

    assert one_step(0.5) < one_step(0.0)


def test_clip_norm_bounds_update():
    """Clipping must actually bound the update.  Adam is scale-invariant
    down to its ε floor, so the clip threshold is chosen far below ε
    (per-coordinate |g| ≈ clip/√n_params ≪ 1e-8): the first-step update is
    then ≈ lr·|g|/ε per coordinate — orders of magnitude below the
    unclipped ±lr — instead of merely rescaled."""
    from distributed_tensorflow_tpu.data.loaders import load_dataset
    from distributed_tensorflow_tpu.utils.harness import _make_optimizer

    ds = load_dataset("mnist", split="train")
    x, y = ds.x[:32], ds.y[:32]
    model = create_model("mlp", hidden=16, dropout_rate=0.0)
    mesh = meshlib.create_mesh(8)

    def delta(clip):
        cfg = ExperimentConfig(clip_norm=clip, lr_schedule="linear")
        eng = SyncEngine(model, optimizer=_make_optimizer(cfg, ds, 32),
                         mesh=mesh)
        s0 = eng.init_state(jax.random.key(0), x)
        p0 = jax.device_get(s0.params)
        xs, ys = eng.shard_batch(x, y)
        s1, _ = eng.step(s0, xs, ys)
        p1 = jax.device_get(s1.params)
        return np.sqrt(sum(
            float(((np.asarray(a) - np.asarray(b)) ** 2).sum())
            for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p0))))

    assert delta(1e-8) < delta(0.0) * 0.1


def test_cli_weight_decay_clip_norm():
    from distributed_tensorflow_tpu.cli import main

    summary = main(["-m", "t", "-n", "8", "-b", "4", "--weight-decay",
                    "0.01", "--clip-norm", "1.0", "--log-every", "0"],
                   dataset_fn=_tiny_mnist_fn)
    assert np.isfinite(summary["test_loss"])
