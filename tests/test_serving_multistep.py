"""Multi-step decode dispatch (ISSUE 20): the fused k-iteration decode
scan on the slot table (``advance_multi`` / ``dispatch_multi`` +
``drain_multi``), the scheduler's pipelined dispatch loop behind
``multi_step=k``, and the dispatch-accounting vocabulary
(``serve_dispatches`` / ``serve_host_gap_s``).

The non-negotiable pin: greedy token streams are BITWISE IDENTICAL at
every k (and to the flag-off engine) in STRICTLY FEWER host dispatches —
fusing iterations moves only the host round-trip, never the math.  EOS
and budget deactivation happen in-device mid-scan; admissions quantize
at round boundaries (staleness bounded by k iterations); ITL stays
per-token attribution under VirtualClock.  Everything runs on this
container — jit + lax.scan + host Python, no shard_map anywhere.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.models.gpt import GPTLM, generate
from distributed_tensorflow_tpu.serving import (
    ContinuousBatcher, Request, RequestQueue, SlotKVCache, VirtualClock)


def tiny_gpt(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("hidden", 32)
    kw.setdefault("layers", 2)
    kw.setdefault("heads", 2)
    kw.setdefault("ffn", 64)
    kw.setdefault("max_len", 32)
    kw.setdefault("dropout_rate", 0.0)
    return GPTLM(**kw)


@pytest.fixture(scope="module")
def model_params():
    model = tiny_gpt()
    x = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 8)),
                    jnp.int32)
    params = model.init(jax.random.key(0), x, train=False)["params"]
    return model, params


@pytest.fixture(scope="module")
def draft_params():
    model = tiny_gpt(hidden=16, layers=1, ffn=32)
    x = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 8)),
                    jnp.int32)
    params = model.init(jax.random.key(1), x, train=False)["params"]
    return model, params


def _requests(n=8, seed=7, spread=0.05):
    rng = np.random.default_rng(seed)
    return [Request(rid=i,
                    prompt=rng.integers(0, 64, 3 + (i % 5)).astype(
                        np.int32),
                    max_new_tokens=4 + (i % 6),
                    arrival_s=spread * i)
            for i in range(n)]


def _streams(summary):
    return {r.rid: r.tokens for r in summary["results"]}


def _run(model, params, multi_step, *, slots=3, kv_kw=None, b_kw=None,
         reqs=None):
    kv = SlotKVCache(model, params, slots=slots, **(kv_kw or {}))
    b = ContinuousBatcher(kv, clock=VirtualClock(), multi_step=multi_step,
                          **(b_kw or {}))
    s = b.run(RequestQueue(reqs if reqs is not None else _requests()))
    return kv, s


# ------------------------------------------------------- slot-table layer


def test_advance_multi_matches_k_single_steps(model_params):
    """The fused scan IS k calls of the single-step program: same tokens,
    same lengths, one dispatch.  The acts stack is a contiguous True
    prefix per column (active-at-entry per iteration)."""
    model, params = model_params
    single = SlotKVCache(model, params, slots=3)
    fused = SlotKVCache(model, params, slots=3)
    prompts = _requests(3, seed=2)
    for r in prompts:
        single.insert(r.prompt)
        fused.insert(r.prompt)
    want = np.stack([single.advance() for _ in range(4)])
    d0 = fused.dispatch_count
    toks, acts = fused.advance_multi(4)
    assert fused.dispatch_count == d0 + 1
    np.testing.assert_array_equal(toks, want)
    assert acts.shape == (4, 3) and acts.all()
    np.testing.assert_array_equal(single.lengths, fused.lengths)
    np.testing.assert_array_equal(single.tokens, fused.tokens)
    # program accounting: exactly one fused width compiled, and the
    # single-step table never compiled one
    assert fused.compiled_programs()["decode_multi_widths"] == 1
    assert single.compiled_programs()["decode_multi_widths"] == 0


def test_in_device_deactivation_eos_and_budget(model_params):
    """``set_decode_limits`` arms per-slot EOS/budget; the scan stops
    emitting for a slot the iteration AFTER its budget hits zero or it
    emits EOS — no host round-trip in between.  Deactivated slots land
    ``halted`` and are excluded from the next dispatch mask."""
    model, params = model_params
    kv = SlotKVCache(model, params, slots=2)
    prompts = _requests(2, seed=3)
    s0, _ = kv.insert(prompts[0].prompt)
    s1, _ = kv.insert(prompts[1].prompt)
    kv.set_decode_limits(s0, None, 2)      # budget: 2 more tokens
    toks, acts = kv.advance_multi(5)
    assert acts[:, s0].tolist() == [True, True, False, False, False]
    assert acts[:, s1].all()
    assert kv.halted[s0] and not kv.halted[s1]
    # the halted slot is excluded from the next fused round entirely
    toks2, acts2 = kv.advance_multi(2)
    assert not acts2[:, s0].any() and acts2[:, s1].all()
    # EOS: arm the slot's SECOND upcoming greedy token (oracle index 2;
    # index 0 is insert's first token) — the scan emits it at iteration
    # 1 and deactivates the same iteration, in-device
    for seed in range(20):     # untrained logits love to repeat — find a
        p = _requests(1, seed=seed)[0].prompt   # prompt whose stream moves
        nxt = _oracle(model, params, p, 4)
        if int(nxt[1]) != int(nxt[2]) and int(nxt[0]) != int(nxt[2]):
            break
    else:
        pytest.skip("no non-degenerate greedy stream in 20 seeds")
    kv2 = SlotKVCache(model, params, slots=1)
    kv2.insert(p)
    kv2.set_decode_limits(0, int(nxt[2]), 0)   # 0 budget = unlimited
    toks3, acts3 = kv2.advance_multi(4)
    assert acts3[:, 0].tolist() == [True, True, False, False]
    assert int(toks3[1, 0]) == int(nxt[2]) and kv2.halted[0]


def test_pipeline_discipline_guards(model_params):
    """The in-flight contract: single-step ``advance`` refuses while a
    fused round is outstanding, rounds drain strictly FIFO, and
    ``abandon_multi`` drops outstanding rounds so evict() can't race a
    half-drained round."""
    model, params = model_params
    kv = SlotKVCache(model, params, slots=2)
    kv.insert(_requests(1, seed=4)[0].prompt)
    h1 = kv.dispatch_multi(2)
    h2 = kv.dispatch_multi(2)
    assert kv.pending_multi == 2
    with pytest.raises(RuntimeError, match="in flight"):
        kv.advance()
    with pytest.raises(RuntimeError, match="dispatch order"):
        kv.drain_multi(h2)
    kv.drain_multi(h1)
    kv.drain_multi(h2)
    assert kv.pending_multi == 0
    # abandon: outstanding rounds vanish without touching host mirrors
    lens = kv.lengths.copy()
    kv.dispatch_multi(3)
    kv.abandon_multi()
    assert kv.pending_multi == 0
    np.testing.assert_array_equal(kv.lengths, lens)
    kv.evict(0)                      # must not raise after abandon
    # and the table still works: next fused round re-uploads from host
    kv.insert(_requests(1, seed=5)[0].prompt)
    toks, acts = kv.advance_multi(2)
    assert acts[:, 0].all()


def _oracle(model, params, prompt, n_new):
    return np.asarray(generate(model, params, prompt[None, :], n_new,
                               greedy=True))[0]


# ------------------------------------------------------- scheduler layer


def test_bitwise_parity_and_fewer_dispatches(model_params):
    """THE acceptance pin: greedy streams at k in {2, 4, 8} are bitwise
    identical to k=1 AND to the flag-off engine, in strictly fewer host
    dispatches; the flag-off summary key set is untouched."""
    model, params = model_params
    kv0, s0 = _run(model, params, None)
    kv1, s1 = _run(model, params, 1)
    oracle = _streams(s0)
    assert oracle == _streams(s1)
    # flag-off: no multi program compiled, no multi keys in the summary
    assert kv0.compiled_programs()["decode_multi_widths"] == 0
    assert "serve_dispatches" not in s0 and "serve_host_gap_s" not in s0
    assert "serve_multi_step" not in s0
    assert set(s0) == set(s1) - {"serve_multi_step", "serve_dispatches",
                                 "serve_host_gap_s"}
    prev = s1["serve_dispatches"]
    for k in (2, 4, 8):
        kvk, sk = _run(model, params, k)
        assert _streams(sk) == oracle, f"k={k} diverged"
        assert sk["serve_dispatches"] < s1["serve_dispatches"], k
        assert sk["serve_dispatches"] <= prev, k
        assert sk["serve_multi_step"] == k
        assert sk["serve_host_gap_s"] >= 0.0
        assert kvk.compiled_programs()["decode_multi_widths"] == 1
        prev = sk["serve_dispatches"]


def test_itl_is_per_token_under_virtual_clock(model_params):
    """Fused rounds must NOT lump k tokens into one ITL gap: delivery
    attributes each emitted token its own decode-iteration tick — every
    non-first gap is exactly 1.0 under VirtualClock at any k."""
    model, params = model_params
    for k in (1, 4, 8):
        _, s = _run(model, params, k)
        for r in s["results"]:
            assert all(g == 1.0 for g in r.itl_s[1:]), (k, r.rid, r.itl_s)


def test_admission_staleness_bounded_by_k(model_params):
    """Admissions interleave BETWEEN dispatches, so a request arriving
    mid-round waits at most k iterations beyond what it waits at k=1 —
    the bounded-staleness trade the flag documents.  A request arriving
    into an idle engine is admitted immediately at any k."""
    model, params = model_params
    k = 4
    _, s1 = _run(model, params, 1, slots=6,
                 reqs=_requests(6, seed=9, spread=0.6))
    _, sk = _run(model, params, k, slots=6,
                 reqs=_requests(6, seed=9, spread=0.6))
    w1 = {r.rid: r.queue_wait_s for r in s1["results"]}
    wk = {r.rid: r.queue_wait_s for r in sk["results"]}
    for rid in w1:
        assert wk[rid] <= w1[rid] + (k - 1) + 1e-9, (rid, wk[rid], w1[rid])
    # t=0 arrival, idle engine: admitted before the first dispatch
    assert wk[0] == w1[0] == 0.0


def test_multi_step_validation(model_params):
    model, params = model_params
    kv = SlotKVCache(model, params, slots=2)
    with pytest.raises(ValueError, match=">= 1"):
        kv.dispatch_multi(0)
    with pytest.raises(RuntimeError, match="no fused round"):
        kv.drain_multi()


# ------------------------------------------------- composition (slow lane)


SHARED = np.arange(16, dtype=np.int32) % 64


@pytest.mark.slow
@pytest.mark.parametrize("case", ["chunk", "prefix", "paged", "int8",
                                  "paged_int8"])
def test_parity_composes_with_serving_features(model_params, case):
    """Multi-step under chunked prefill, the prefix pool, the paged
    table, and int8 KV storage: same bitwise-parity + fewer-dispatches
    pin — the fused scan runs the SAME per-iteration step the feature
    already compiled, so composition is free by construction."""
    model, params = model_params
    cfg = {
        "chunk": (dict(), dict(prefill_chunk=4), False),
        "prefix": (dict(prefix_cache_blocks=8, prefix_block=8), dict(),
                   True),
        "paged": (dict(kv_layout="paged", paged_blocks=48, paged_block=4),
                  dict(prefill_chunk=4), False),
        "int8": (dict(kv_dtype="int8"), dict(), False),
        "paged_int8": (dict(kv_layout="paged", paged_blocks=48,
                            paged_block=4, kv_dtype="int8"),
                       dict(prefill_chunk=4), False),
    }[case]
    kv_kw, b_kw, prefix = cfg

    def reqs():
        out = _requests()
        if prefix:
            out = [Request(rid=r.rid,
                           prompt=np.concatenate([SHARED, r.prompt]),
                           max_new_tokens=r.max_new_tokens,
                           arrival_s=r.arrival_s) for r in out]
        return out

    _, s_off = _run(model, params, None, kv_kw=kv_kw, b_kw=b_kw,
                    reqs=reqs())
    _, s1 = _run(model, params, 1, kv_kw=kv_kw, b_kw=b_kw, reqs=reqs())
    _, s4 = _run(model, params, 4, kv_kw=kv_kw, b_kw=b_kw, reqs=reqs())
    assert _streams(s_off) == _streams(s1) == _streams(s4)
    assert s4["serve_dispatches"] < s1["serve_dispatches"]


@pytest.mark.slow
def test_spec_decode_reuses_fused_draft_loop(model_params, draft_params):
    """With a draft attached the pipelined loop steps aside (verify owns
    the cadence) but the draft's k-token proposal loop fuses into ONE
    ``advance_multi`` dispatch: tokens stay bitwise identical and total
    dispatches (target + draft) drop vs flag-off — identically at any
    k, because the win is the proposal fusion, not the pipeline."""
    model, params = model_params
    dmodel, dparams = draft_params

    def run(ms, chunk=0):
        kv = SlotKVCache(model, params, slots=3)
        dkv = SlotKVCache(dmodel, dparams, slots=3)
        b = ContinuousBatcher(kv, clock=VirtualClock(), multi_step=ms,
                              draft_kv=dkv, draft_k=3,
                              prefill_chunk=chunk)
        s = b.run(RequestQueue(_requests()))
        return s, kv.dispatch_count + dkv.dispatch_count

    for chunk in (0, 4):
        s_off, d_off = run(None, chunk)
        s1, _ = run(1, chunk)
        s4, _ = run(4, chunk)
        assert _streams(s_off) == _streams(s1) == _streams(s4)
        assert s4["serve_dispatches"] < d_off
        assert s4["serve_dispatches"] == s1["serve_dispatches"]


@pytest.mark.slow
def test_fleet_parity_and_dispatch_aggregation(model_params):
    """ReplicaSet threads ``multi_step`` to every batcher: homogeneous
    and disaggregated fleets keep bitwise parity, the fleet summary
    aggregates ``serve_dispatches``/``serve_host_gap_s`` across
    replicas, and the flag-off fleet summary key set is untouched."""
    from distributed_tensorflow_tpu.serving import (
        ReplicaSet, build_replica_kvs)

    model, params = model_params

    def fleet(ms, **kw):
        rs = ReplicaSet(build_replica_kvs(model, params, kw.pop("n", 2),
                                          2),
                        clock=VirtualClock(), threaded=False,
                        multi_step=ms, **kw)
        return rs.run(_requests(spread=0.5))

    s_off, s1, s4 = fleet(None), fleet(1), fleet(4)
    assert _streams(s_off) == _streams(s1) == _streams(s4)
    assert s4["serve_dispatches"] < s1["serve_dispatches"]
    assert s4["serve_host_gap_s"] >= 0.0
    assert "serve_dispatches" not in s_off
    assert set(s_off) == set(s4) - {"serve_multi_step",
                                    "serve_dispatches",
                                    "serve_host_gap_s"}
    d_off = fleet(None, n=3, roles=["prefill", "decode", "decode"],
                  handoff_s=0.01)
    d4 = fleet(4, n=3, roles=["prefill", "decode", "decode"],
               handoff_s=0.01)
    assert _streams(d_off) == _streams(d4) == _streams(s_off)
    assert d4["serve_dispatches"] > 0
