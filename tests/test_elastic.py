"""Elastic preemption-tolerant training (distributed_tensorflow_tpu/elastic/).

Covers the four pillars ISSUE 9 names, at every layer that container jax
can run:

* **Exactly-once data resume**: ``DataState`` round-trips, ``start_batch``
  stream-continuation parity on every loader path, the prefetch-drain
  no-drop/no-replay proof (``consumer_state``), and a killed-and-resumed
  Trainer whose metric stream is BITWISE the uninterrupted run's — at
  k=1 and k=8 (mirroring tests/test_steady_state.py).
* **Resharding restore**: an FSDP checkpoint restored onto a different
  device count AND a different mesh-axis layout, the (same/different
  mesh × same/different precision policy) cross-product, legacy
  (sidecar-less) checkpoints, and the named error on unbridgeable
  layouts.
* **Graceful lease drain**: LeaseManager units (budget, SIGTERM flag,
  install/uninstall), the Trainer ``should_stop`` drain at k=1 and k=8
  (final checkpoint carries the data state), and the harness/CLI e2e —
  ``--max-steps-per-lease`` drain, ``--elastic-restore`` resume onto a
  different ``-n``, the ``preempted``/``preemption_lost_s`` report
  sections, and the supervisor-protocol ``['preempted', reason, step]``
  message.
* **Straggler detection + accounting**: outlier flagging against the
  running median, median adaptation, the structured ``straggler`` trace
  event, and `analyze diff` gating of
  ``preemption_lost_s``/``resume_replay_steps``/``straggler_events``.

The GSPMD tests run on FSDPEngine (pure jit — every container); the
Trainer-level tests ride test_steady_state's JitEngine.
"""

import dataclasses
import json
import os
import signal
import threading

import jax
import numpy as np
import pytest

from distributed_tensorflow_tpu import elastic
from distributed_tensorflow_tpu.data.device_prefetch import DevicePrefetch
from distributed_tensorflow_tpu.data.loaders import (
    Dataset, load_dataset, synthetic_classification)
from distributed_tensorflow_tpu.data.pipeline import iter_batches
from distributed_tensorflow_tpu.elastic import (
    DataState, ElasticRestoreError, LeaseManager, ResumableBatches,
    StragglerDetector, consumer_state, elastic_restore, preemption_lost_s)
from distributed_tensorflow_tpu.engines.allreduce import Trainer
from distributed_tensorflow_tpu.utils.checkpoint import CheckpointManager
from distributed_tensorflow_tpu.utils.metrics import MetricsLogger

from test_steady_state import JitEngine, _tiny_ds  # noqa: E402


# ------------------------------------------------------------- DataState

def test_data_state_json_roundtrip():
    st = DataState(epoch=2, batch_index=7, seed=3, batch_size=32,
                   dataset_len=1024, dataset="mnist")
    back = DataState.from_json(st.to_json())
    assert back == st
    assert back.version == elastic.DATA_STATE_VERSION


@pytest.mark.parametrize("garbage", [
    None, [], "x", 42, {}, {"epoch": 1}, {"epoch": "a", "batch_index": 0,
                                          "seed": 0, "batch_size": 1,
                                          "dataset_len": 1},
])
def test_data_state_tolerant_decode(garbage):
    """A garbled/foreign sidecar must decode to None (replay accounting),
    never raise — old checkpoints stay restorable."""
    assert DataState.from_json(garbage) is None


def test_data_state_matching_guards_the_stream_identity():
    st = DataState(epoch=0, batch_index=3, seed=1, batch_size=16,
                   dataset_len=256, dataset="tiny")
    assert st.matches(seed=1, batch_size=16, dataset_len=256)
    assert st.matches(seed=1, batch_size=16, dataset_len=256,
                      dataset="tiny")
    # any identity-field mismatch describes a DIFFERENT batch sequence —
    # including the dataset NAME: two datasets can coincide in
    # seed/batch/length and still be different streams
    assert not st.matches(seed=2, batch_size=16, dataset_len=256)
    assert not st.matches(seed=1, batch_size=32, dataset_len=256)
    assert not st.matches(seed=1, batch_size=16, dataset_len=512)
    assert not st.matches(seed=1, batch_size=16, dataset_len=256,
                          dataset="other")


# ---------------------------------------------- start_batch stream parity

def test_iter_batches_start_batch_continues_exact_sequence():
    x, y = synthetic_classification((4,), 3, 100, seed=7)
    full = list(iter_batches(x, y, 16, shuffle=True, seed=5, epoch=2,
                             drop_remainder=True))
    resumed = list(iter_batches(x, y, 16, shuffle=True, seed=5, epoch=2,
                                drop_remainder=True, start_batch=3))
    assert len(resumed) == len(full) - 3
    for (ax, ay, am), (bx, by, bm) in zip(full[3:], resumed):
        np.testing.assert_array_equal(ax, bx)
        np.testing.assert_array_equal(ay, by)
        np.testing.assert_array_equal(am, bm)


def test_iter_batches_start_batch_validation():
    x, y = synthetic_classification((4,), 3, 32, seed=0)
    with pytest.raises(ValueError, match="start_batch"):
        list(iter_batches(x, y, 8, start_batch=-1))
    # skipping the whole epoch yields an empty stream, not an error
    assert list(iter_batches(x, y, 8, drop_remainder=True,
                             start_batch=99)) == []


@pytest.mark.parametrize("name", ["synthetic", "lm_synth", "mnist"])
def test_dataset_start_batch_parity_per_loader(name):
    """Satellite: the ``start_batch`` resume contract holds on every
    loader path — classification (C++-pipeline-eligible), LM ((B, L)
    labels force the Python path) and the mnist loader (real archive or
    its synthetic fallback, whichever this container has)."""
    ds = load_dataset(name, split="train")
    full = list(ds.batches(32, shuffle=True, seed=1, epoch=0,
                           drop_remainder=True, native=False))
    resumed = list(ds.batches(32, shuffle=True, seed=1, epoch=0,
                              drop_remainder=True, start_batch=2))
    assert len(resumed) == len(full) - 2
    for (ax, ay, _), (bx, by, _) in zip(full[2:], resumed):
        np.testing.assert_array_equal(ax, bx)
        np.testing.assert_array_equal(ay, by)


def test_dataset_start_batch_rejects_native_pipeline():
    ds = load_dataset("synthetic", split="train")
    with pytest.raises(RuntimeError, match="native"):
        ds.batches(32, start_batch=1, native=True)


# ------------------------------------- ResumableBatches + prefetch drain

def test_resumable_batches_state_restore_roundtrip():
    ds = _tiny_ds(192)
    rb = ResumableBatches(ds, 16, seed=4, epoch=1)
    consumed = [next(rb) for _ in range(5)]
    st = rb.state()
    assert (st.epoch, st.batch_index) == (1, 5)
    rest = list(ResumableBatches.restore(ds, st))
    uninterrupted = list(ResumableBatches(ds, 16, seed=4, epoch=1))
    assert len(consumed) + len(rest) == len(uninterrupted)
    for (ax, ay, _), (bx, by, _) in zip(uninterrupted[5:], rest):
        np.testing.assert_array_equal(ax, bx)
        np.testing.assert_array_equal(ay, by)
    rb.close()


def test_resumable_batches_restore_validates_dataset():
    ds = _tiny_ds(192)
    st = dataclasses.replace(ResumableBatches(ds, 16).state(),
                             dataset_len=7)
    with pytest.raises(ValueError, match="dataset"):
        ResumableBatches.restore(ds, st)
    # a name mismatch at coinciding length is still a different stream
    st = dataclasses.replace(ResumableBatches(ds, 16).state(),
                             dataset="other")
    with pytest.raises(ValueError, match="other"):
        ResumableBatches.restore(ds, st)


def test_prefetch_drain_no_drop_no_replay():
    """THE exactly-once discounting proof: with the prefetcher reading
    ``depth`` batches ahead, checkpointing the CONSUMER position
    (``consumer_state``) and resuming yields every staged-but-untrained
    batch exactly once and no trained batch twice."""
    ds = _tiny_ds(192)  # 12 batches of 16
    rb = ResumableBatches(ds, 16, seed=0, epoch=0)
    pf = DevicePrefetch(rb, lambda b: b, depth=3)
    trained = [pf.__next__() for _ in range(4)]
    # producer ran ahead: 4 consumed + 3 staged
    assert pf.consumed == 4
    assert rb.state().batch_index == 7
    st = consumer_state(rb, pf)
    assert st.batch_index == 4  # read-ahead discounted
    pf.close()  # the "kill": staged batches are dropped with the process
    resumed = list(ResumableBatches.restore(ds, st))
    full = list(ResumableBatches(ds, 16, seed=0, epoch=0))
    # no replay: resumed stream starts exactly after the trained batches
    # no drop: the 3 staged-but-untrained batches lead the resumed stream
    assert len(trained) + len(resumed) == len(full) == 12
    for (ax, ay, _), (bx, by, _) in zip(full[4:], resumed):
        np.testing.assert_array_equal(ax, bx)
        np.testing.assert_array_equal(ay, by)


def test_prefetch_consumed_gauge_in_stats():
    pf = DevicePrefetch(iter([(np.zeros(2), np.zeros(2), np.ones(2))] * 5),
                        lambda b: b, depth=2)
    next(pf)
    next(pf)
    assert pf.stats()["consumed"] == 2


# ------------------------------------------------------- LeaseManager

def test_lease_step_budget():
    lm = LeaseManager(max_steps_per_lease=5)
    assert lm.should_stop(4) is None
    assert lm.should_stop(5) == "max_steps_per_lease:5"
    assert lm.should_stop(9) == "max_steps_per_lease:5"
    assert LeaseManager(0).should_stop(10 ** 9) is None  # 0 disables
    with pytest.raises(ValueError, match="max_steps_per_lease"):
        LeaseManager(-1)


def test_lease_sigterm_sets_flag_and_drains():
    lm = LeaseManager().install()
    try:
        assert lm.installed
        assert lm.should_stop(1) is None
        os.kill(os.getpid(), signal.SIGTERM)  # the preemption notice
        # the handler ONLY set a flag; the drain decision happens here
        assert lm.should_stop(1) == "signal:SIGTERM"
        rep = lm.report()
        assert rep["signal_handler_installed"] is True
        assert rep["preempt_signal"] == "SIGTERM"
    finally:
        lm.uninstall()
    assert not lm.installed
    # sticky record: a report taken after teardown still says it was armed
    assert lm.report()["signal_handler_installed"] is True


def test_lease_uninstall_restores_previous_handler():
    seen = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
    try:
        with LeaseManager() as lm:
            os.kill(os.getpid(), signal.SIGTERM)
            assert lm.preempt_signal == signal.SIGTERM
            assert not seen  # the lease owned the signal
        os.kill(os.getpid(), signal.SIGTERM)
        assert seen == [signal.SIGTERM]  # previous disposition is back
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_lease_install_off_main_thread_degrades_to_budget():
    box = {}

    def t():
        box["lm"] = LeaseManager(max_steps_per_lease=2).install()

    th = threading.Thread(target=t)
    th.start()
    th.join()
    lm = box["lm"]
    assert not lm.installed  # signal.signal is main-thread-only
    assert lm.should_stop(2) == "max_steps_per_lease:2"  # budget survives


# ------------------------------------------------------ StragglerDetector

class _FakeTracer:
    def __init__(self):
        self.events = []

    def event(self, name, **attrs):
        self.events.append({"name": name, **attrs})


def test_straggler_flags_outlier_and_emits_event():
    tr = _FakeTracer()
    sd = StragglerDetector(tracer=tr, factor=3.0, min_samples=5)
    for i in range(6):
        assert not sd.observe(i, 0.1)
    assert sd.observe(6, 0.5)  # 5× the median
    assert sd.events == 1 and sd.last_straggler_step == 6
    assert sd.max_ratio == pytest.approx(5.0)
    (ev,) = tr.events
    assert ev["name"] == "straggler" and ev["step"] == 6
    assert ev["ratio"] == pytest.approx(5.0)
    rep = sd.report()
    assert rep["events"] == 1 and rep["observed"] == 7


def test_straggler_needs_min_samples_and_adapts_to_new_pace():
    sd = StragglerDetector(factor=3.0, min_samples=5, window=8)
    assert not sd.observe(0, 10.0)  # huge, but no baseline yet
    for i in range(8):
        sd.observe(i, 0.1)
    assert sd.observe(99, 1.0)  # outlier vs the 0.1 median
    # a SUSTAINED 1.0 pace becomes the new normal: flagging stops once
    # the bounded window's median catches up
    flags = [sd.observe(100 + i, 1.0) for i in range(12)]
    assert not any(flags[8:])
    assert sd.report()["max_ratio"] >= 3.0


def test_straggler_validation():
    with pytest.raises(ValueError, match="factor"):
        StragglerDetector(factor=1.0)
    with pytest.raises(ValueError, match="min_samples"):
        StragglerDetector(min_samples=1)


def test_straggler_quiet_report_has_no_ratio():
    sd = StragglerDetector()
    sd.observe(1, 0.1)
    rep = sd.report()
    assert rep["events"] == 0 and rep["max_ratio"] is None


# ------------------------------------- Trainer drain + exactly-once resume

def _fit(trainer, ds, k, **kw):
    ml = MetricsLogger(None, log_every=1)
    r = trainer.fit(ds, epochs=2, batch_size=16, log_every=0,
                    steps_per_call=k, metrics_logger=ml, **kw)
    return r, [(m["step"], m["loss"], m["accuracy"]) for m in ml.records]


@pytest.mark.parametrize("k", [1, 8])
def test_trainer_should_stop_drains_at_boundary(k, tmp_path):
    """The graceful drain at both drain shapes: fit stops at the first
    chunk boundary where should_stop fires, reports the reason, and the
    final checkpoint carries the boundary's data state."""
    mgr = CheckpointManager(tmp_path / "c")
    tr = Trainer(None, engine=JitEngine(), seed=0)
    lm = LeaseManager(max_steps_per_lease=5)
    r, traj = _fit(tr, _tiny_ds(), k, checkpoint_manager=mgr,
                   should_stop=lm.should_stop)
    assert r["preempted"] == "max_steps_per_lease:5"
    expected = 5 if k == 1 else 8  # first boundary at/after the budget
    assert r["steps"] == expected
    assert mgr.latest_step() == expected
    extra = mgr.load_extra()
    st = DataState.from_json(extra["data_state"])
    assert st is not None and st.batch_index == expected
    assert extra["step"] == expected and extra["wall_time"] > 0


def test_trainer_sigterm_mid_fit_drains_with_checkpoint(tmp_path):
    """A SIGTERM delivered DURING the fit (the scheduler's preemption
    notice) finishes the in-flight chunk and exits with the structured
    reason — no exception, no corpse."""
    mgr = CheckpointManager(tmp_path / "c")
    tr = Trainer(None, engine=JitEngine(), seed=0)
    with LeaseManager() as lm:
        fired = {}

        def stop_hook(steps_done):
            # deliver the signal from inside the loop (deterministic:
            # mid-fit, AFTER this boundary's decision) — the flag is
            # read at the NEXT boundary, exactly like an async delivery
            reason = lm.should_stop(steps_done)
            if steps_done == 3 and not fired:
                fired["at"] = steps_done
                os.kill(os.getpid(), signal.SIGTERM)
            return reason

        r, _ = _fit(tr, _tiny_ds(), 1, checkpoint_manager=mgr,
                    should_stop=stop_hook)
    assert r["preempted"] == "signal:SIGTERM"
    assert r["steps"] == 4  # the boundary after the notice
    assert mgr.latest_step() == 4


@pytest.mark.parametrize("k", [1, 8])
def test_kill_and_resume_bitwise_same_mesh(k, tmp_path):
    """THE acceptance property (same mesh): a run checkpointed at step 6,
    killed, and resumed with the checkpoint's data state produces the
    BITWISE identical metric stream and final params as the uninterrupted
    run — at k=1 AND k=8 (the resume-parity mirror of
    tests/test_steady_state.py)."""
    tru = Trainer(None, engine=JitEngine(), seed=0)
    ru, traj_u = _fit(tru, _tiny_ds(), k, max_steps=13)
    assert ru["steps"] == 13

    mgr = CheckpointManager(tmp_path / "c")
    tr1 = Trainer(None, engine=JitEngine(), seed=0)
    r1, traj1 = _fit(tr1, _tiny_ds(), k, checkpoint_manager=mgr,
                     checkpoint_every=6, max_steps=6)
    # "kill": fresh trainer restores state + sidecar, continues the stream
    tr2 = Trainer(None, engine=JitEngine(), seed=0)
    template = tr2.engine.init_state(jax.random.key(0), _tiny_ds().x[:1])
    tr2.state, extra = elastic_restore(mgr, tr2.engine, template)
    r2, traj2 = _fit(tr2, _tiny_ds(), k, max_steps=7,
                     data_state=extra["data_state"])
    assert r2["resume_replay_steps"] == 0
    assert r2["start_step"] == 6
    assert traj1 + traj2 == traj_u  # bitwise, steps 1..13
    for a, b in zip(jax.tree.leaves(jax.device_get(tru.state.params)),
                    jax.tree.leaves(jax.device_get(tr2.state.params))):
        np.testing.assert_array_equal(a, b)


def test_resume_without_data_state_reports_replay(tmp_path):
    """A pre-elastic checkpoint (no sidecar) still restores — the stream
    restarts from epoch 0 and the unrecoverable positions surface as
    resume_replay_steps, with a warning."""
    mgr = CheckpointManager(tmp_path / "c")
    tr1 = Trainer(None, engine=JitEngine(), seed=0)
    _fit(tr1, _tiny_ds(), 4, max_steps=6)
    mgr.save(tr1.state, step=6)  # direct save: no elastic sidecar
    assert mgr.load_extra() is None

    tr2 = Trainer(None, engine=JitEngine(), seed=0)
    template = tr2.engine.init_state(jax.random.key(0), _tiny_ds().x[:1])
    tr2.state, extra = elastic_restore(mgr, tr2.engine, template)
    assert extra is None
    logs = []
    r2 = tr2.fit(_tiny_ds(), epochs=1, batch_size=16, log_every=0,
                 steps_per_call=4, max_steps=4, data_state={},
                 log_fn=logs.append)
    assert r2["resume_replay_steps"] == 6
    assert any("resume_replay_steps=6" in line for line in logs)


def test_mid_epoch_and_cross_epoch_resume_positions(tmp_path):
    """The data state crosses epoch boundaries correctly: a checkpoint at
    a step past epoch 0's end records (epoch 1, offset), and the resumed
    fit continues there — only the FIRST resumed epoch starts offset."""
    ds = _tiny_ds(96)  # 6 batches of 16 per epoch
    mgr = CheckpointManager(tmp_path / "c")
    tr = Trainer(None, engine=JitEngine(), seed=0)
    r, _ = _fit(tr, ds, 4, checkpoint_manager=mgr, checkpoint_every=8,
                max_steps=8)
    st = DataState.from_json(mgr.load_extra()["data_state"])
    assert (st.epoch, st.batch_index) == (1, 2)  # 8 = 6 + 2


# ------------------------------------------------- resharding (FSDP/GSPMD)

def _fsdp_engine(n_devices=None, mesh=None, precision="f32", dtype=None):
    from distributed_tensorflow_tpu.engines.fsdp import FSDPEngine
    from distributed_tensorflow_tpu.models import create_model
    from distributed_tensorflow_tpu.parallel import mesh as meshlib

    if mesh is None:
        mesh = meshlib.create_mesh(n_devices)
    kw = {"dtype": dtype} if dtype else {}
    return FSDPEngine(create_model("mlp", num_classes=4, hidden=32, **kw),
                      mesh=mesh, learning_rate=5e-3, precision=precision)


def _fsdp_ds():
    x, y = synthetic_classification((8,), 4, 256, seed=3)
    return Dataset(x=x, y=y, num_classes=4, name="tiny", synthetic=True)


def _train_and_save(tmp_path, *, n_devices=8, precision="f32", dtype=None,
                    steps=6):
    ds = _fsdp_ds()
    eng = _fsdp_engine(n_devices, precision=precision, dtype=dtype)
    tr = Trainer(None, engine=eng, seed=0)
    mgr = CheckpointManager(tmp_path / "ck")
    tr.fit(ds, epochs=2, batch_size=32, log_every=0, steps_per_call=4,
           checkpoint_manager=mgr, checkpoint_every=steps, max_steps=steps)
    return mgr, tr, ds


def _assert_tree_equal(a, b, exact=True):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x, y = np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y))
        if exact:
            np.testing.assert_array_equal(x, y)
        else:
            np.testing.assert_allclose(x, y, rtol=1e-6)


@pytest.mark.parametrize("target", ["same8", "count4", "count2",
                                    "layout4x2"])
def test_reshard_restore_across_mesh_shapes(tmp_path, target):
    """Resharding restore: a checkpoint written on an 8-device ('data',)
    fsdp mesh restores bitwise onto the SAME mesh, onto smaller device
    counts, and onto a different axis LAYOUT (('data','model') 4×2) —
    every leaf re-placed under the target engine's spec map."""
    from distributed_tensorflow_tpu.parallel import mesh as meshlib

    mgr, tr_src, ds = _train_and_save(tmp_path)
    if target == "layout4x2":
        mesh = meshlib.create_mesh(8, shape=(4, 2),
                                   axis_names=("data", "model"))
        eng = _fsdp_engine(mesh=mesh)
    else:
        eng = _fsdp_engine({"same8": 8, "count4": 4, "count2": 2}[target])
    template = eng.init_state(jax.random.key(0), ds.x[: eng.n_devices])
    state, extra = elastic_restore(mgr, eng, template)
    assert int(np.asarray(jax.device_get(state.step))) == 6
    _assert_tree_equal(tr_src.state.params, state.params)
    _assert_tree_equal(tr_src.state.opt_state, state.opt_state)
    # the sidecar rides along, whatever the target mesh
    assert DataState.from_json(extra["data_state"]).batch_index == 6
    # every mesh-placed leaf landed under the TARGET engine's spec map
    from jax.sharding import NamedSharding

    specs = eng.state_partition_specs(template)
    checked = 0
    for leaf, spec in zip(jax.tree.leaves(state), jax.tree.leaves(specs)):
        if isinstance(leaf, jax.Array) and isinstance(
                getattr(leaf, "sharding", None), NamedSharding):
            assert dict(leaf.sharding.mesh.shape) == dict(eng.mesh.shape)
            assert leaf.sharding.spec == spec
            checked += 1
    assert checked > 0


# round 20 fast-lane repair: continuation e2e — the reshard roundtrip
# pins stay fast
@pytest.mark.slow
def test_reshard_restore_continues_training(tmp_path):
    """The restored-on-a-smaller-mesh state is a WORKING TrainState: a
    further fit with the sidecar's data state continues the loss
    trajectory of the uninterrupted source run within tolerance (the
    cross-mesh acceptance bound; same-mesh bitwise is proved above)."""
    ds = _fsdp_ds()
    # uninterrupted 10-step reference on the source mesh
    tru = Trainer(None, engine=_fsdp_engine(8), seed=0)
    mlu = MetricsLogger(None, log_every=1)
    tru.fit(ds, epochs=2, batch_size=32, log_every=0, steps_per_call=4,
            metrics_logger=mlu, max_steps=10)
    traj_u = [(m["step"], m["loss"]) for m in mlu.records]

    mgr, _, _ = _train_and_save(tmp_path, steps=6)
    eng4 = _fsdp_engine(4)
    template = eng4.init_state(jax.random.key(0), ds.x[:4])
    tr = Trainer(None, engine=eng4, seed=0)
    tr.state, extra = elastic_restore(mgr, eng4, template)
    ml = MetricsLogger(None, log_every=1)
    r = tr.fit(ds, epochs=2, batch_size=32, log_every=0, steps_per_call=4,
               metrics_logger=ml, data_state=extra["data_state"],
               max_steps=4)
    assert r["resume_replay_steps"] == 0
    traj_r = [(m["step"], m["loss"]) for m in ml.records]
    assert [s for s, _ in traj_r] == [s for s, _ in traj_u[6:]]
    np.testing.assert_allclose([l for _, l in traj_r],
                               [l for _, l in traj_u[6:]], rtol=1e-5)


# round 20 fast-lane repair: the n=4 arm pins the claim fast; the n=8
# arm rides the slow lane
@pytest.mark.parametrize("n_target", [
    pytest.param(8, marks=pytest.mark.slow), 4])
def test_reshard_f32_checkpoint_into_master_policy(tmp_path, n_target):
    """Satellite bug-sweep cross-product, policy-crossing half: an
    f32-era checkpoint restores into a bf16-f32master run on the same
    AND a different mesh — the restored f32 params become the master,
    their downcast the stored params."""
    import jax.numpy as jnp

    mgr, tr_src, ds = _train_and_save(tmp_path, precision="f32")
    eng = _fsdp_engine(n_target, precision="bf16-f32master",
                       dtype="bfloat16")
    template = eng.init_state(jax.random.key(0), ds.x[:n_target])
    state, _extra = elastic_restore(mgr, eng, template)
    from distributed_tensorflow_tpu.parallel import precision as plib

    masters = [n for n in jax.tree.leaves(
        state.opt_state,
        is_leaf=lambda x: isinstance(x, plib.MasterWeightsState))
        if isinstance(n, plib.MasterWeightsState)]
    assert masters, "no master node in the adopted optimizer state"
    _assert_tree_equal(tr_src.state.params, masters[0].master)
    for p, m in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(masters[0].master)):
        assert p.dtype == jnp.bfloat16
        np.testing.assert_array_equal(np.asarray(jax.device_get(p)),
                                      np.asarray(jax.device_get(m)).astype(
                                          jnp.bfloat16))


# round 20 fast-lane repair: the n=4 arm pins the claim fast; the n=8
# arm rides the slow lane
@pytest.mark.parametrize("n_target", [
    pytest.param(8, marks=pytest.mark.slow), 4])
def test_reshard_same_policy_roundtrip_bf16_master(tmp_path, n_target):
    """Cross-product, same-policy half: a bf16-f32master checkpoint
    restores bitwise into a bf16-f32master run on the same and a
    different mesh (master copies reshard with their params)."""
    mgr, tr_src, ds = _train_and_save(tmp_path, precision="bf16-f32master",
                                      dtype="bfloat16")
    eng = _fsdp_engine(n_target, precision="bf16-f32master",
                       dtype="bfloat16")
    template = eng.init_state(jax.random.key(0), ds.x[:n_target])
    state, _ = elastic_restore(mgr, eng, template)
    _assert_tree_equal(tr_src.state.params, state.params)
    _assert_tree_equal(tr_src.state.opt_state, state.opt_state)


def test_reshard_unbridgeable_layout_raises_named_error(tmp_path):
    """A structure the target cannot express (here: a master-policy
    checkpoint into an f32 run) raises ElasticRestoreError naming the
    GSPMD coverage and the precision rule, not a raw tree mismatch."""
    mgr, _, ds = _train_and_save(tmp_path, precision="bf16-f32master",
                                 dtype="bfloat16")
    eng = _fsdp_engine(4, precision="f32")
    template = eng.init_state(jax.random.key(0), ds.x[:4])
    with pytest.raises(ElasticRestoreError, match="GSPMD"):
        elastic_restore(mgr, eng, template)


def test_preemption_lost_s_accounting():
    assert preemption_lost_s(None) is None
    assert preemption_lost_s({}) is None
    assert preemption_lost_s({"wall_time": True}) is None  # bool guard
    lost = preemption_lost_s({"wall_time": 100.0}, now=130.0)
    assert lost == pytest.approx(30.0)
    # clock skew must not report negative lost time
    assert preemption_lost_s({"wall_time": 100.0}, now=90.0) == 0.0


def test_elastic_restore_pins_requested_step(tmp_path):
    mgr, _, ds = _train_and_save(tmp_path, steps=4)
    eng = _fsdp_engine(4)
    template = eng.init_state(jax.random.key(0), ds.x[:4])
    state, extra = elastic_restore(mgr, eng, template, step=4)
    assert int(np.asarray(jax.device_get(state.step))) == 4
    assert extra["step"] == 4


# -------------------------------------------------- harness / CLI / e2e

def _tiny_dataset_fn(batch_size, type="train"):  # noqa: A002 — harness API
    n = 256 if type == "train" else 64
    x, y = synthetic_classification((8,), 4, n, seed=3)
    return Dataset(x=x, y=y, num_classes=4, name="tiny", synthetic=True)


def _econfig(tmp_path, **kw):
    from distributed_tensorflow_tpu.utils.harness import ExperimentConfig

    base = dict(engine="fsdp", model="mlp", dataset="synthetic",
                dataset_fn=_tiny_dataset_fn, n_devices=4, batch_size=8,
                epochs=2, log_every=0, steps_per_call=4, eval_batch=64,
                checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=4)
    base.update(kw)
    return ExperimentConfig(**base)


def test_harness_lease_drain_and_elastic_resume_cross_count(tmp_path):
    """Kill-and-resume acceptance at the harness layer: a run drained by
    --max-steps-per-lease, then resumed with --elastic-restore onto a
    DIFFERENT device count (same global batch), continues the exact
    stream — `preempted`, `preemption_lost_s`, `resume_replay_steps` all
    in the report, and `analyze diff` self-compares the new keys."""
    from distributed_tensorflow_tpu.observability.analyze import (
        diff_reports, load_report)
    from distributed_tensorflow_tpu.utils.harness import run

    s1 = run(_econfig(tmp_path, max_steps_per_lease=6))
    assert s1["preempted"] == "max_steps_per_lease:6"
    assert s1["steps"] == 8  # first chunk boundary at/after the budget
    rep1 = s1["run_report"]
    assert rep1["preempted"] == s1["preempted"]
    assert rep1["lease"]["max_steps_per_lease"] == 6
    assert rep1["stragglers"]["observed"] > 0

    # resume on HALF the devices, same global batch (8×4 == 16×2)
    s2 = run(_econfig(tmp_path, n_devices=2, batch_size=16,
                      elastic_restore=True, max_steps_per_lease=4))
    rep2 = s2["run_report"]
    assert rep2["restored_step"] == 8
    assert rep2["resume_replay_steps"] == 0  # exact stream continuation
    assert rep2["preemption_lost_s"] is not None
    assert rep2["preemption_lost_s"] >= 0.0

    out = tmp_path / "summary.json"
    out.write_text(json.dumps(s2))
    d = diff_reports(load_report(out), load_report(out))
    assert not d["regressions"]
    compared = {r["metric"] for r in d["unchanged"]}
    assert {"preemption_lost_s", "resume_replay_steps",
            "straggler_events"} <= compared


def test_harness_elastic_restore_requires_checkpoint_dir(tmp_path):
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    with pytest.raises(ValueError, match="elastic-restore"):
        run(ExperimentConfig(elastic_restore=True))
    with pytest.raises(ValueError, match="max-steps-per-lease"):
        run(ExperimentConfig(max_steps_per_lease=5))
    with pytest.raises(ValueError, match="max-steps-per-lease"):
        run(_econfig(tmp_path, max_steps_per_lease=-1))


def test_harness_sigterm_preemption_notice_drains(tmp_path):
    """The in-process rendering of the CI smoke's kill -TERM: a SIGTERM
    delivered while the harness trains drains gracefully — structured
    `preempted` summary, final checkpoint on disk, process alive."""
    from distributed_tensorflow_tpu.utils.harness import run

    timer = threading.Timer(1.0, os.kill,
                            args=(os.getpid(), signal.SIGTERM))
    timer.daemon = True
    timer.start()
    try:
        s = run(_econfig(tmp_path, epochs=500))  # far longer than the timer
    finally:
        timer.cancel()
    assert s["preempted"] == "signal:SIGTERM"
    assert s["run_report"]["lease"]["preempt_signal"] == "SIGTERM"
    assert s["run_report"]["lease"]["signal_handler_installed"] is True
    # SIGTERM's default disposition is restored: we are alive to assert
    ck = tmp_path / "ck"
    assert any(p.name.startswith("step_") for p in ck.iterdir())


def test_supervisor_protocol_preempted_message(tmp_path):
    """Satellite (supervisor integration): an external reference-style
    listener sees ['preempted', reason, step] — a planned drain, not a
    dead socket — alongside the reference event triple."""
    from distributed_tensorflow_tpu.utils.harness import run
    from distributed_tensorflow_tpu.utils.supervisor import (
        SupervisorListener)

    listener = SupervisorListener()
    s = run(_econfig(tmp_path, max_steps_per_lease=4,
                     supervisor_address=f"127.0.0.1:{listener.port}"))
    listener.close()
    assert s["preempted"]
    assert listener.messages[0] == "start"
    preempt = [m for m in listener.messages
               if isinstance(m, list) and m[0] == "preempted"]
    assert preempt == [["preempted", s["preempted"], s["steps"]]]


# round 20 fast-lane repair: fault-injection e2e rides the slow lane;
# the lease/drain unit pins stay fast
@pytest.mark.slow
def test_run_with_recovery_fault_injection_continuity(tmp_path):
    """Satellite (failure integration): a worker killed mid-run recovers
    through the ELASTIC restore — run_with_recovery relaunches with
    elastic_restore=True, the resumed run continues the exact step/loss
    trajectory (bitwise vs the uninterrupted run's metric stream), and
    the report accounts the crash (resume_replay_steps == 0)."""
    from distributed_tensorflow_tpu.utils import harness
    from distributed_tensorflow_tpu.utils.failure import run_with_recovery

    m0 = tmp_path / "uninterrupted.jsonl"
    harness.run(_econfig(tmp_path / "u", metrics_path=str(m0)))
    traj_u = [(r["step"], r["loss"])
              for r in map(json.loads, m0.read_text().splitlines())]
    assert [s for s, _ in traj_u] == list(range(1, 17))

    m1, m2 = tmp_path / "crashed.jsonl", tmp_path / "resumed.jsonl"
    cfg = _econfig(tmp_path, metrics_path=str(m2), max_steps_per_lease=8)
    attempts = []

    def killed_mid_run(config):
        attempts.append((config.resume, config.elastic_restore))
        if len(attempts) == 1:
            # the injected death: train 8 steps (checkpoints at 4, 8),
            # then die like a preempted worker — no drain, no cleanup
            harness.run(dataclasses.replace(
                config, metrics_path=str(m1), max_steps_per_lease=8))
            raise RuntimeError("injected worker death mid-chunk")
        return harness.run(config)

    out = run_with_recovery(cfg, max_restarts=1, run_fn=killed_mid_run)
    # the restart went through the elastic path, not a cold restore
    assert attempts == [(False, False), (True, True)]
    assert out["restarts"] == 1
    assert out["run_report"]["restored_step"] == 8
    assert out["run_report"]["resume_replay_steps"] == 0
    traj_r = [(r["step"], r["loss"])
              for r in map(json.loads,
                           m1.read_text().splitlines()
                           + m2.read_text().splitlines())]
    assert traj_r == traj_u  # step AND loss continuity, bitwise


def test_cli_flags_wire_through(tmp_path):
    """--elastic-restore / --max-steps-per-lease reach the config."""
    from distributed_tensorflow_tpu.cli import build_parser

    args = build_parser().parse_args(
        ["--elastic-restore", "--max-steps-per-lease", "9"])
    assert args.elastic_restore is True
    assert args.max_steps_per_lease == 9
    args = build_parser().parse_args([])
    assert args.elastic_restore is False
    assert args.max_steps_per_lease == 0


# ------------------------------------------------------- analyze gating

def test_analyze_diff_gates_preemption_keys():
    from distributed_tensorflow_tpu.observability.analyze import (
        diff_reports)

    base = {"preemption_lost_s": 10.0, "resume_replay_steps": 0,
            "straggler_events": 1}
    worse = {"preemption_lost_s": 30.0, "resume_replay_steps": 8,
             "straggler_events": 5}
    d = diff_reports(base, worse)
    regressed = {r["metric"] for r in d["regressions"]}
    assert {"preemption_lost_s", "resume_replay_steps",
            "straggler_events"} <= regressed
    better = diff_reports(worse, base)
    assert not better["regressions"]


def test_analyze_flattens_straggler_events():
    from distributed_tensorflow_tpu.observability.analyze import (
        load_report)

    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump({"stragglers": {"events": 3, "observed": 10}}, f)
        path = f.name
    try:
        assert load_report(path)["straggler_events"] == 3
    finally:
        os.unlink(path)
