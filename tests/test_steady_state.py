"""Steady-state training-loop layer: device prefetch + multi-step drain.

Covers the contracts ISSUE 1 names: prefetch ordering/exhaustion/early
close, padded-final-batch mask correctness through a scanned drain,
``steps_per_call > 1`` bitwise parity with ``steps_per_call = 1`` on a
fixed seed, and the auto-downshift to 1 — which since ISSUE 2 applies
ONLY to ``target_accuracy`` runs: telemetry (metrics sink, watchdog)
rides the chunked drain with zero downshift, including the k=8 vs k=1
bitwise parity of the on-disk metrics stream and the watchdog's
chunk-rescaled stall budget (firing and non-firing).

The shard_map engines need a newer jax than some CI containers carry, so
the Trainer/Engine machinery is exercised through a minimal pure-jit
Engine (``JitEngine``) that runs everywhere; the acceptance-letter MNIST
CNN + SyncEngine parity variant is guarded by ``jax.shard_map``
availability and runs wherever the engine layer itself runs.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_tensorflow_tpu.data.device_prefetch import DevicePrefetch
from distributed_tensorflow_tpu.data.loaders import (
    Dataset, synthetic_classification)
from distributed_tensorflow_tpu.data.pipeline import iter_batches
from distributed_tensorflow_tpu.engines.allreduce import (
    DEFAULT_STEPS_PER_CALL, Trainer)
from distributed_tensorflow_tpu.engines.base import (
    Engine, cross_entropy)
from distributed_tensorflow_tpu.utils.metrics import MetricsLogger

needs_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="shard_map engine layer needs a newer jax than this container")


# --------------------------------------------------------------- prefetcher

def _host_batches(n, rows=4):
    return [(np.full((rows, 2), i, np.float32),
             np.full((rows,), i, np.int32),
             np.ones((rows,), np.float32)) for i in range(n)]


def test_prefetch_orders_and_reads_ahead():
    placed = []

    def place(b):
        placed.append(int(b[1][0]))
        return jax.device_put(b[0]), jax.device_put(b[1])

    pf = DevicePrefetch(iter(_host_batches(6)), place, depth=2)
    seen = []
    for _xs, ys in pf:
        seen.append(int(np.asarray(ys)[0]))
        # the transfer for the NEXT depth batches was already issued when
        # the consumer got this one — bounded read-ahead, source order kept
        assert placed == list(range(min(len(seen) + 2, 6)))
    assert seen == list(range(6))
    with pytest.raises(StopIteration):
        next(pf)
    assert pf.take(3) == []  # exhausted stays exhausted


class _CloseableSource:
    def __init__(self, items):
        self._it = iter(items)
        self.closed = False

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._it)

    def close(self):
        self.closed = True


def test_prefetch_close_releases_source_early():
    src = _CloseableSource(_host_batches(6))
    pf = DevicePrefetch(src, lambda b: b, depth=2)
    next(pf)
    pf.close()  # consumer stops early (max_steps / early-stop / exception)
    assert src.closed
    assert pf.take(3) == []


def test_prefetch_exhaustion_closes_source():
    src = _CloseableSource(_host_batches(2))
    pf = DevicePrefetch(src, lambda b: b, depth=4)  # deeper than the epoch
    assert len(list(pf)) == 2
    assert src.closed


def test_prefetch_depth_gauge_slow_consumer():
    """Satellite: a consumer slower than the source sees the queue-depth
    gauge pinned at the configured --prefetch depth (the buffer is
    refilled before every hand-off), and never counts starvation."""
    pf = DevicePrefetch(iter(_host_batches(8)), lambda b: b, depth=3)
    assert pf.depth == 3
    assert pf.queue_depth == 3  # staged eagerly at construction
    for _ in range(4):  # slow consumer: source always ahead
        next(pf)
        assert pf.queue_depth == 3
    assert pf.starvation == 0
    stats = pf.stats()
    assert stats["depth"] == stats["queue_depth"] == 3
    assert stats["fill_wait_s"] >= 0.0


def test_prefetch_starvation_counts_empty_readahead():
    """depth=1 leaves zero batches staged after every hand-off — each
    next() is a starvation event (the following transfer cannot overlap
    compute); at depth=2 the same traffic never starves."""
    pf1 = DevicePrefetch(iter(_host_batches(6)), lambda b: b, depth=1)
    for i in range(4):
        next(pf1)
    assert pf1.starvation == 4
    pf2 = DevicePrefetch(iter(_host_batches(6)), lambda b: b, depth=2)
    for i in range(4):
        next(pf2)
    assert pf2.starvation == 0


def test_prefetch_take_and_depth_validation():
    pf = DevicePrefetch(iter(_host_batches(5)), lambda b: b, depth=1)
    assert len(pf.take(0)) == 0
    assert len(pf.take(3)) == 3
    assert len(pf.take(8)) == 2  # remainder only
    with pytest.raises(ValueError):
        DevicePrefetch(iter(()), lambda b: b, depth=0)


def test_padded_final_batch_mask_through_scanned_drain(mesh8):
    """A padded final batch prefetched to device and consumed by a jitted
    lax.scan drain must contribute exactly its real rows: the mask rides
    the prefetcher with the batch and zeroes the padding inside the scan."""
    from distributed_tensorflow_tpu.parallel import mesh as meshlib

    x, y = synthetic_classification((4,), 3, 100, seed=1)
    n_batches = 3  # 48 + 48 + (4 real + 44 padded)

    def place(b):
        return tuple(
            jax.device_put(a, meshlib.data_sharding(mesh8, np.ndim(a)))
            for a in b)

    pf = DevicePrefetch(iter_batches(x, y, 48, shuffle=False), place, depth=2)
    chunk = pf.take(n_batches + 1)  # over-ask: epoch has exactly 3
    assert len(chunk) == n_batches
    xs = jnp.stack([c[0] for c in chunk])
    ys = jnp.stack([c[1] for c in chunk])
    ms = jnp.stack([c[2] for c in chunk])

    @jax.jit
    def drain(xs, ys, ms):
        def body(carry, batch):
            _bx, by, bm = batch
            count, label_sum = carry
            return (count + bm.sum(), label_sum + (bm * by).sum()), None

        init = (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
        (count, label_sum), _ = jax.lax.scan(body, init, (xs, ys, ms))
        return count, label_sum

    count, label_sum = drain(xs, ys, ms)
    assert float(count) == 100.0  # every real row once, no padding rows
    assert float(label_sum) == float(y.sum())


# ------------------------------------------------- minimal pure-jit engine

class JitEngine(Engine):
    """Smallest Engine whose step runs on any jax: one jitted SGD step of a
    linear softmax classifier (no shard_map) — lets every container verify
    the Trainer's steady-state machinery (prefetch consumption, chunked
    many_step drain, bookkeeping parity) independent of the engine layer."""

    def __init__(self, num_classes: int = 4, learning_rate: float = 0.1,
                 mesh=None):
        import flax.linen as nn

        class _Linear(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False):
                return nn.Dense(num_classes)(x.reshape((x.shape[0], -1)))

        super().__init__(_Linear(), optimizer=optax.sgd(learning_rate),
                         mesh=mesh)

    def _build_step(self):
        tx, apply_fn = self.tx, self.model.apply

        def train_step(state, x, y):
            def loss_fn(p):
                logits = apply_fn({"params": p}, x)
                loss = cross_entropy(logits, y).mean()
                return loss, (logits.argmax(-1) == y).mean()

            (loss, acc), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)
            updates, opt_state = tx.update(grads, state.opt_state,
                                           state.params)
            params = optax.apply_updates(state.params, updates)
            return state.replace(step=state.step + 1, params=params,
                                 opt_state=opt_state), \
                {"loss": loss, "accuracy": acc}

        return jax.jit(train_step, donate_argnums=0)

    def _build_eval(self):
        apply_fn = self.model.apply
        return self._build_eval_gspmd(
            lambda params, x: apply_fn({"params": params}, x))


def _tiny_ds(n=208):
    x, y = synthetic_classification((8,), 4, n, seed=3)
    return Dataset(x=x, y=y, num_classes=4, name="tiny", synthetic=True)


def test_many_step_matches_sequential_steps():
    ds = _tiny_ds()
    batches = None
    runs = {}
    for name in ("scan", "loop"):
        eng = JitEngine()
        state = eng.init_state(jax.random.key(0), ds.x[:8])
        if batches is None:
            batches = [eng.shard_batch(ds.x[i * 16:(i + 1) * 16],
                                       ds.y[i * 16:(i + 1) * 16])
                       for i in range(3)]
        if name == "scan":
            state, m = eng.many_step(state, [b[0] for b in batches],
                                     [b[1] for b in batches])
            assert m["loss"].shape == (3,)  # per-step trajectory, stacked
            runs[name] = (np.asarray(m["loss"]),
                          jax.device_get(state.params))
        else:
            losses = []
            for bx, by in batches:
                state, m = eng.step(state, bx, by)
                losses.append(np.asarray(m["loss"]))
            runs[name] = (np.asarray(losses), jax.device_get(state.params))
    np.testing.assert_array_equal(runs["scan"][0], runs["loop"][0])
    for a, b in zip(jax.tree.leaves(runs["scan"][1]),
                    jax.tree.leaves(runs["loop"][1])):
        np.testing.assert_array_equal(a, b)


def test_build_many_step_validates_k():
    with pytest.raises(ValueError, match="steps_per_call"):
        JitEngine().build_many_step(0)


# ------------------------------------------------------ Trainer drain/parity

def _run_fit(k, max_steps=13, n=208, **fit_kw):
    eng = JitEngine()
    tr = Trainer(None, engine=eng, seed=0)
    ml = MetricsLogger(None, log_every=1)  # records EVERY step's metrics
    r = tr.fit(_tiny_ds(n), epochs=2, batch_size=16, log_every=0,
               steps_per_call=k, metrics_logger=ml, max_steps=max_steps,
               **fit_kw)
    return r, ml.records, jax.device_get(tr.state.params)


def test_steps_per_call_parity_bitwise():
    """k=8 must produce the step-for-step identical loss/accuracy
    trajectory and final params as k=1 on the same seed — including a
    5-step tail chunk (13 = 8 + 5) and an epoch boundary."""
    r1, recs1, p1 = _run_fit(1)
    r8, recs8, p8 = _run_fit(8)
    assert r1["steps"] == r8["steps"] == 13
    traj1 = [(m["step"], m["loss"], m["accuracy"]) for m in recs1]
    traj8 = [(m["step"], m["loss"], m["accuracy"]) for m in recs8]
    assert traj1 == traj8
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p8)):
        np.testing.assert_array_equal(a, b)


def test_resolve_steps_per_call():
    resolve = Trainer.resolve_steps_per_call
    assert resolve(None) == DEFAULT_STEPS_PER_CALL
    # zero-downshift telemetry: metric records ride the scan's stacked
    # trajectory and the watchdog rescales its budget to the chunk, so
    # neither forces the host between every step any more
    assert resolve(None, metrics_logger=object()) == DEFAULT_STEPS_PER_CALL
    assert resolve(None, watchdog=object()) == DEFAULT_STEPS_PER_CALL
    assert resolve(None, target_accuracy=0.9) == 1
    # a sub-chunk checkpoint cadence caps auto's k (state only exists at
    # chunk boundaries; the requested crash-loss window is honored)
    assert resolve(None, checkpoint_every=4) == 4
    assert resolve(None, checkpoint_every=50) == DEFAULT_STEPS_PER_CALL
    assert resolve(3) == 3
    assert resolve(5, metrics_logger=object()) == 5  # explicit wins
    assert resolve(8, checkpoint_every=2) == 8       # explicit wins
    with pytest.raises(ValueError):
        resolve(0)


def test_fit_auto_chunks_and_reports_shape():
    eng = JitEngine()
    tr = Trainer(None, engine=eng, seed=0)
    r = tr.fit(_tiny_ds(), epochs=1, batch_size=16, log_every=0,
               max_steps=10)
    assert r["steps_per_call"] == DEFAULT_STEPS_PER_CALL
    assert r["prefetch_depth"] == 2
    assert r["steps"] == 10  # 8-chunk + truncated 2-chunk honors max_steps
    assert r["step_time"]["steps"] == 10  # per-step times, not per-chunk


def test_fit_auto_keeps_chunking_with_metrics_logger():
    """A metrics logger no longer downshifts auto mode: records are
    flushed per chunk from the scan's stacked trajectory, step-exact."""
    eng = JitEngine()
    tr = Trainer(None, engine=eng, seed=0)
    ml = MetricsLogger(None, log_every=1)
    r = tr.fit(_tiny_ds(64), epochs=1, batch_size=16, log_every=0,
               metrics_logger=ml, max_steps=3)
    assert r["steps_per_call"] == DEFAULT_STEPS_PER_CALL
    assert [rec["step"] for rec in ml.records] == [1, 2, 3]


def test_metrics_stream_parity_k8_vs_k1_on_disk(tmp_path):
    """Acceptance: with a file-backed metrics sink and steps_per_call=8,
    fit does NOT downshift, and the per-step loss/accuracy records in the
    JSONL stream are bitwise identical to k=1 on the same seed."""
    def run(k):
        eng = JitEngine()
        tr = Trainer(None, engine=eng, seed=0)
        path = tmp_path / f"metrics_k{k}.jsonl"
        ml = MetricsLogger(path, log_every=1)
        r = tr.fit(_tiny_ds(), epochs=2, batch_size=16, log_every=0,
                   steps_per_call=k, metrics_logger=ml, max_steps=13)
        ml.close()
        lines = [json.loads(line) for line in path.read_text().splitlines()]
        return r, lines

    r1, recs1 = run(1)
    r8, recs8 = run(8)
    assert r8["steps_per_call"] == 8  # no downshift under the sink
    assert r8["chunk_sizes"] == [5, 8]  # 13 = 8 + 5-step tail
    traj = lambda recs: [(m["step"], m["loss"], m["accuracy"])  # noqa: E731
                         for m in recs]
    assert len(recs8) == 13
    assert traj(recs1) == traj(recs8)
    assert all(m["schema_version"] == 1 for m in recs8)


def test_watchdog_rides_chunked_drain_without_firing():
    """Satellite: watchdog_timeout works with steps_per_call=8 — the stall
    budget rescales to k × per-step budget and chunk-boundary beats keep
    it fed, so a healthy run never fires."""
    from distributed_tensorflow_tpu.utils.failure import Watchdog

    eng = JitEngine()
    tr = Trainer(None, engine=eng, seed=0)
    stalls = []
    with Watchdog(timeout=5.0, on_stall=stalls.append,
                  poll_interval=0.01) as wd:
        r = tr.fit(_tiny_ds(), epochs=2, batch_size=16, log_every=0,
                   steps_per_call=8, watchdog=wd, max_steps=13)
    assert r["steps_per_call"] == 8      # no downshift under the watchdog
    assert wd.timeout == 40.0            # k × per-step budget
    assert r["watchdog_beats"] == wd.beats >= 2  # one per chunk flush
    assert r["watchdog_stalls"] == 0 and not stalls


def test_watchdog_fires_on_stalled_chunk():
    """Satellite: a chunk that exceeds k × per-step budget IS a stall —
    the on_stall callback fires from the monitor thread mid-chunk."""
    import time as _time

    from distributed_tensorflow_tpu.utils.failure import Watchdog

    class SlowEngine(JitEngine):
        def many_step(self, state, xs_seq, ys_seq):
            state, m = super().many_step(state, xs_seq, ys_seq)
            jax.block_until_ready(m)
            _time.sleep(0.6)  # well past the scaled 8 × 0.02 s budget
            return state, m

    eng = SlowEngine()
    tr = Trainer(None, engine=eng, seed=0)
    stalls = []
    # armed from construction: the stalled chunk is the FIRST dispatch,
    # before any beat exists to arm on
    with Watchdog(timeout=0.02, on_stall=stalls.append,
                  poll_interval=0.01, arm_on_first_beat=False) as wd:
        tr.fit(_tiny_ds(), epochs=1, batch_size=16, log_every=0,
               steps_per_call=8, watchdog=wd, max_steps=13)
        assert abs(wd.timeout - 0.16) < 1e-9
    assert wd.stall_episodes >= 1 and stalls


def test_fit_auto_downshifts_for_target_accuracy():
    eng = JitEngine()
    tr = Trainer(None, engine=eng, seed=0)
    r = tr.fit(_tiny_ds(), epochs=1, batch_size=16, log_every=0,
               eval_ds=_tiny_ds(64), target_accuracy=0.05, eval_every=2,
               max_steps=6)
    assert r["steps_per_call"] == 1
    assert r["reached_target"]  # 5% on a 4-class task: first eval crosses


def test_explicit_chunked_drain_with_target_evals_at_boundaries():
    eng = JitEngine()
    tr = Trainer(None, engine=eng, seed=0)
    r = tr.fit(_tiny_ds(), epochs=2, batch_size=16, log_every=0,
               steps_per_call=4, eval_ds=_tiny_ds(64),
               target_accuracy=0.05, eval_every=4, max_steps=20)
    assert r["steps_per_call"] == 4
    assert r["reached_target"]
    assert r["steps"] % 4 == 0  # early-stop landed on a chunk boundary


def test_auto_caps_chunk_at_checkpoint_cadence(tmp_path):
    from distributed_tensorflow_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(tmp_path / "c", max_to_keep=10)
    eng = JitEngine()
    tr = Trainer(None, engine=eng, seed=0)
    r = tr.fit(_tiny_ds(), epochs=2, batch_size=16, log_every=0,
               checkpoint_manager=mgr, checkpoint_every=4, max_steps=13)
    # auto caps k at checkpoint_every, so every due step IS a boundary —
    # the crash-loss window the user asked for is honored
    assert r["steps_per_call"] == 4
    assert {4, 8, 12} <= set(mgr.steps())
    assert mgr.latest_step() == 13  # final state always checkpointed


def test_explicit_chunk_checkpoints_at_boundaries(tmp_path):
    from distributed_tensorflow_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(tmp_path / "c", max_to_keep=10)
    eng = JitEngine()
    tr = Trainer(None, engine=eng, seed=0)
    r = tr.fit(_tiny_ds(), epochs=2, batch_size=16, log_every=0,
               steps_per_call=8, checkpoint_manager=mgr, checkpoint_every=4,
               max_steps=13)
    # explicit k wins: due steps 4/8/12 land on the first chunk boundary
    # at/after them (8, 13); the final state is always checkpointed
    assert r["steps_per_call"] == 8
    assert 8 in mgr.steps()
    assert mgr.latest_step() == 13


def test_chunked_heartbeat_logs_exact_steps():
    eng = JitEngine()
    tr = Trainer(None, engine=eng, seed=0)
    lines = []
    tr.fit(_tiny_ds(), epochs=1, batch_size=16, log_every=3,
           log_fn=lines.append, max_steps=8)  # one chunk of 8
    # per-step metrics come back stacked, so mid-chunk heartbeat steps
    # (3, 6) log their OWN step's values, not the chunk boundary's
    assert [int(line.split()[1]) for line in lines] == [3, 6]


def test_chunked_nan_guard_raises():
    import flax.linen as nn

    from distributed_tensorflow_tpu.utils.failure import TrainingDiverged

    class NaNEngine(JitEngine):
        def __init__(self):
            super().__init__()

            class _Bad(nn.Module):
                @nn.compact
                def __call__(self, x, train: bool = False):
                    return nn.Dense(4)(x.reshape((x.shape[0], -1))) / 0.0

            self.model = _Bad()

    tr = Trainer(None, engine=NaNEngine(), seed=0)
    with pytest.raises(TrainingDiverged):
        tr.fit(_tiny_ds(64), epochs=1, batch_size=16, log_every=1,
               log_fn=lambda s: None, steps_per_call=4)


# -------------------------------------- acceptance config (shard_map envs)

@needs_shard_map
def test_mnist_cnn_sync_parity_steps_per_call(mesh8):
    """The acceptance-letter configuration: MNIST CNN under SyncEngine,
    steps_per_call=8 vs 1, identical per-step loss/accuracy trajectory on
    the same seed."""
    from distributed_tensorflow_tpu.data.loaders import load_dataset
    from distributed_tensorflow_tpu.engines import SyncEngine
    from distributed_tensorflow_tpu.models import create_model

    ds = load_dataset("mnist", split="train")

    def run(k):
        eng = SyncEngine(create_model("cnn", num_classes=ds.num_classes),
                         mesh=mesh8)
        tr = Trainer(None, engine=eng, seed=0)
        ml = MetricsLogger(None, log_every=1)
        r = tr.fit(ds, epochs=1, batch_size=64, log_every=0,
                   steps_per_call=k, metrics_logger=ml, max_steps=12)
        return r, [(m["step"], m["loss"], m["accuracy"])
                   for m in ml.records]

    r1, traj1 = run(1)
    r8, traj8 = run(8)
    assert r1["steps"] == r8["steps"] == 12
    assert r8["steps_per_call"] == 8  # metrics sink never downshifts
    assert traj1 == traj8


# ------------------------------------------------------- bench harness smoke

# round 20 fast-lane repair: heaviest bench-subprocess smoke (~33s)
# rides the slow lane; test_serving's bench --serve smoke keeps the
# one fast bench-subprocess representative
@pytest.mark.slow
def test_bench_stream_smoke_emits_json():
    """`bench.py --stream` must emit ONE parsable JSON line whatever the
    backend state (a real measurement on capable hosts, a structured skip
    otherwise) — the bench harness cannot silently rot."""
    repo = Path(__file__).resolve().parents[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_PER_CHIP_BATCH="8")
    proc = subprocess.run(
        [sys.executable, str(repo / "bench.py"), "--stream", "--steps", "2",
         "--no-probe", "--health", "on", "--checkpoint-every", "1"],
        capture_output=True, text=True, timeout=540, env=env, cwd=str(repo))
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["metric"] == "mnist_cnn_stream_examples_per_sec"
    # off-TPU (or without the engine layer) a structured skip is valid:
    # the contract is the parsable line, not the number
    if payload.get("skipped"):
        assert payload["value"] is None
        assert payload["error"]
    else:
        # telemetry riders: steady-state step-time percentiles (compile
        # chunk excluded) and the prefetch starvation counter of the
        # shipped Trainer.fit path
        assert payload["step_time_p50"] > 0
        assert payload["step_time_p95"] >= payload["step_time_p50"]
        assert payload["prefetch_starvation"] >= 0
        assert payload["trainer_examples_per_sec"] > 0
        # --health on riders: the fit result's health summary surfaces on
        # the bench line (max update ratio + anomaly steps)
        assert payload["health_max_update_ratio"] > 0
        assert payload["health_anomaly_steps"] == []
        # --checkpoint-every riders: the blocked-vs-overlapped checkpoint
        # seconds split of the async-checkpointed Trainer window
        assert payload["checkpoint_every"] == 1
        assert payload["checkpoint_async"] is True
        assert payload["checkpoint_wait_s"] >= 0
        assert payload["checkpoint_overlapped_s"] >= 0
