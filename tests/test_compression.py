"""Gradient-compression layer (ISSUE 3): codecs, wire accounting, engine
wiring, and the satellite knobs that ride along.

Layout mirrors the suite's shard_map split: the codec math, the GSPMD
engines (FSDP is pure jit) and the Trainer/report/harness plumbing run on
ANY jax; the explicit-collective engine variants (sync/async/gossip, whose
codecs own a real shard_map collective) are ``needs_shard_map``-guarded
like the rest of the engine layer, so the fast lane stays green on
containers whose jax predates ``jax.shard_map``.
"""

import json
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.data.loaders import (
    Dataset, load_dataset, synthetic_classification)
from distributed_tensorflow_tpu.engines import Trainer
from distributed_tensorflow_tpu.engines.fsdp import FSDPEngine
from distributed_tensorflow_tpu.models import create_model
from distributed_tensorflow_tpu.parallel import compression
from distributed_tensorflow_tpu.parallel import mesh as meshlib

needs_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="shard_map engine layer needs a newer jax than this container")


def _vec(n=256, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=(n,)).astype(np.float32))


# ------------------------------------------------------------ codec units

def test_make_codec_resolution():
    assert compression.make_codec("none").name == "none"
    assert compression.make_codec(None).name == "none"
    assert compression.make_codec("bf16").name == "bf16"
    assert compression.make_codec("int8").name == "int8"
    codec = compression.Bf16Codec()
    assert compression.make_codec(codec) is codec  # instance passthrough
    with pytest.raises(ValueError, match="unknown grad_compression"):
        compression.make_codec("fp4")


def test_none_roundtrip_is_identity():
    x = _vec()
    tree = {"w": x, "b": jnp.ones((3,), jnp.int32)}
    out = compression.make_codec("none").roundtrip(tree, rng=jax.random.key(0))
    np.testing.assert_array_equal(out["w"], x)
    np.testing.assert_array_equal(out["b"], tree["b"])


def test_bf16_roundtrip_cast_bounds():
    x = _vec()
    out = compression.make_codec("bf16").roundtrip({"w": x})["w"]
    assert out.dtype == jnp.float32
    # bf16 keeps 8 mantissa bits: relative rounding error <= 2^-8
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1 / 256)
    # non-f32 leaves pass through untouched (already-narrow or integral)
    half = x.astype(jnp.bfloat16)
    ints = jnp.arange(5, dtype=jnp.int32)
    rt = compression.make_codec("bf16").roundtrip({"h": half, "i": ints})
    np.testing.assert_array_equal(rt["h"], half)
    np.testing.assert_array_equal(rt["i"], ints)


def test_int8_roundtrip_within_one_quantum():
    x = _vec()
    scale = float(jnp.max(jnp.abs(x))) / 127.0
    codec = compression.make_codec("int8")
    # deterministic (no rng): round-to-nearest, error <= scale/2
    det = codec.roundtrip({"w": x})["w"]
    assert float(jnp.abs(det - x).max()) <= scale / 2 + 1e-7
    # stochastic: error <= one quantum
    sto = codec.roundtrip({"w": x}, rng=jax.random.key(1))["w"]
    assert float(jnp.abs(sto - x).max()) <= scale + 1e-7


def test_int8_stochastic_rounding_unbiased_in_expectation():
    """E[decode(encode(x, rng))] == x for stochastic rounding — the
    property that keeps quantization noise from biasing the descent
    direction.  Deterministic given the fixed seed."""
    x = _vec(64, seed=2)
    scale = float(jnp.max(jnp.abs(x))) / 127.0
    keys = jax.random.split(jax.random.key(0), 2048)
    dec = jax.vmap(lambda k: compression._int8_decode(
        *compression._int8_encode(x, k), jnp.float32))(keys)
    mean_err = float(jnp.abs(dec.mean(0) - x).max())
    # per-sample error is <= 1 quantum with variance <= s^2/4; over 2048
    # draws the mean sits within a few percent of a quantum
    assert mean_err < 0.08 * scale
    # round-to-nearest (rng=None) is biased by construction; the stochastic
    # mean must beat a half-quantum systematically
    assert mean_err < scale / 2


def test_wire_bytes_accounting():
    f32 = jnp.zeros((100,), jnp.float32)
    i32 = jnp.zeros((10,), jnp.int32)
    bf = jnp.zeros((8,), jnp.bfloat16)
    assert compression.make_codec("none").wire_bytes([f32]) == 400
    assert compression.make_codec("bf16").wire_bytes([f32]) == 200
    assert compression.make_codec("int8").wire_bytes([f32]) == 104  # + scale
    # integral leaves keep their width under every codec; bf16 leaves are
    # already at (or below) the bf16 wire width
    for name in ("none", "bf16", "int8"):
        assert compression.make_codec(name).wire_bytes([i32]) == 40
    assert compression.make_codec("bf16").wire_bytes([bf]) == 16
    assert compression.make_codec("int8").wire_bytes([bf]) == 12  # 8 + scale


# ------------------------- compressed collectives under vmap emulation
# (jax.vmap with an axis_name implements the same collectives as
# shard_map, so the codec's reduce math is verified on EVERY container —
# including the two-phase int8 layout's padding/chunking — while the
# shard_map renderings below stay guarded)

@pytest.mark.parametrize("size", [64, 61])  # 61: pad-to-chunks tail
@pytest.mark.parametrize("mean", [False, True])
def test_codec_reduce_math_under_vmap(size, mean):
    n = 8
    vals = jnp.asarray(np.random.default_rng(4).normal(
        size=(n, size)).astype(np.float32))
    ref = np.asarray(vals.mean(0) if mean else vals.sum(0))
    op = "all_reduce_mean" if mean else "all_reduce_sum"

    def run(codec):
        def device(x, key):
            return getattr(codec, op)(x, "data", rng=key)

        keys = jax.random.split(jax.random.key(9), n)  # per-device rounding
        return np.asarray(jax.vmap(device, axis_name="data")(vals, keys)[0])

    np.testing.assert_allclose(run(compression.make_codec("none")), ref,
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(run(compression.make_codec("bf16")), ref,
                               rtol=0.05, atol=0.05)
    q = np.abs(np.asarray(vals)).max(axis=1) / 127.0
    tol = 2 * q.sum() / (n if mean else 1)
    assert np.abs(run(compression.make_codec("int8")) - ref).max() \
        <= tol + 1e-6


def test_int8_reduce_accepts_no_rng_under_vmap():
    """rng=None is the documented deterministic-rounding mode — both
    quantization phases must tolerate it (regression: phase 2 once
    fold_in'd the None key)."""
    vals = jnp.asarray(np.random.default_rng(6).normal(
        size=(8, 24)).astype(np.float32))
    codec = compression.make_codec("int8")
    out = jax.vmap(lambda x: codec.all_reduce_sum(x, "data"),
                   axis_name="data")(vals)[0]
    q = np.abs(np.asarray(vals)).max(axis=1) / 127.0
    assert np.abs(np.asarray(out) - np.asarray(vals.sum(0))).max() \
        <= 2 * q.sum() + 1e-6


def test_int8_reduce_unbiased_under_vmap():
    """The two-phase int8 reduce composes two unbiased stochastic
    roundings — averaging the reduced value over many key draws recovers
    the exact sum."""
    n = 8
    vals = jnp.asarray(np.random.default_rng(5).normal(
        size=(n, 32)).astype(np.float32))
    codec = compression.make_codec("int8")

    def one(seed):
        keys = jax.random.split(jax.random.key(seed), n)
        return jax.vmap(
            lambda x, k: codec.all_reduce_sum(x, "data", rng=k),
            axis_name="data")(vals, keys)[0]

    reduced = jax.vmap(one)(jnp.arange(512))
    err = np.abs(np.asarray(reduced.mean(0)) - np.asarray(vals.sum(0)))
    q = np.abs(np.asarray(vals)).max() / 127.0
    assert err.max() < 0.25 * q  # noise ~q/sample shrinks ~sqrt(512)


# ------------------------------------- compressed collectives (shard_map)

@needs_shard_map
@pytest.mark.parametrize("reduce_name", ["all_reduce_sum", "all_reduce_mean"])
def test_compressed_reduce_none_bitwise_and_lossy_close(mesh8, reduce_name):
    from jax.sharding import PartitionSpec as P

    vals = jnp.asarray(
        np.random.default_rng(0).normal(size=(8, 64)).astype(np.float32))

    def run(codec):
        def body(x):
            return getattr(codec, reduce_name)(
                x[0], "data", rng=jax.random.key(3))

        return jax.jit(jax.shard_map(
            body, mesh=mesh8, in_specs=(P("data"),), out_specs=P()))(vals)

    exact = run(compression.make_codec("none"))
    ref = vals.sum(0) if reduce_name == "all_reduce_sum" else vals.mean(0)
    np.testing.assert_allclose(np.asarray(exact), np.asarray(ref), rtol=1e-6)

    close = np.asarray(run(compression.make_codec("bf16")))
    np.testing.assert_allclose(close, np.asarray(ref), rtol=0.05, atol=0.05)

    # int8 two-phase reduce: one quantum per sender (phase 1) plus one
    # for the re-quantized sum (phase 2, scale <= sum of sender scales)
    q = np.abs(np.asarray(vals)).max(axis=1) / 127.0
    tol = 2 * q.sum()
    if reduce_name == "all_reduce_mean":
        tol /= vals.shape[0]
    int8 = np.asarray(run(compression.make_codec("int8")))
    assert np.abs(int8 - np.asarray(ref)).max() <= tol + 1e-6


@needs_shard_map
def test_compressed_neighbor_mean_close_to_exact(mesh8):
    from jax.sharding import PartitionSpec as P

    from distributed_tensorflow_tpu.parallel import collectives as coll

    vals = jnp.asarray(
        np.random.default_rng(1).normal(size=(8, 32)).astype(np.float32))

    def run(fn):
        return jax.jit(jax.shard_map(
            lambda x: fn(x), mesh=mesh8,
            in_specs=(P("data"),), out_specs=P("data")))(vals)

    exact = np.asarray(run(lambda x: coll.neighbor_mean(x, "data", 1)))
    none = np.asarray(run(lambda x: compression.make_codec("none")
                          .neighbor_mean(x, "data", 1)))
    np.testing.assert_array_equal(none, exact)
    for name, tol in (("bf16", 0.05), ("int8", 0.1)):
        mixed = np.asarray(run(
            lambda x, n=name: compression.make_codec(n).neighbor_mean(
                x, "data", 1, rng=jax.random.key(5))))
        np.testing.assert_allclose(mixed, exact, rtol=tol, atol=tol)


# --------------------------------------- GSPMD engines (run on any jax)

def _tiny_ds(n=512, split="train"):
    x, y = synthetic_classification((8, 8), 4, n, seed=3, split=split)
    return Dataset(x=x, y=y, num_classes=4, name="tiny", synthetic=True)


def _fsdp_engine(codec, mesh, lr=5e-3):
    return FSDPEngine(create_model("mlp", num_classes=4, hidden=32),
                      mesh=mesh, learning_rate=lr, grad_compression=codec)


def _run_steps(eng, ds, n_steps=3, k=1):
    state = eng.init_state(jax.random.key(0), ds.x[:8])
    batches = [eng.shard_batch(ds.x[i * 32:(i + 1) * 32],
                               ds.y[i * 32:(i + 1) * 32])
               for i in range(n_steps)]
    if k == 1:
        losses = []
        for bx, by in batches:
            state, m = eng.step(state, bx, by)
            losses.append(np.asarray(m["loss"]))
        return np.asarray(losses), jax.device_get(state.params)
    state, m = eng.many_step(state, [b[0] for b in batches],
                             [b[1] for b in batches])
    return np.asarray(m["loss"]), jax.device_get(state.params)


def test_fsdp_none_codec_bitwise_identical_at_k1_and_k8(mesh8):
    """Acceptance: --grad-compression none is bitwise identical to the
    pre-codec path, through both the single step and the scanned drain."""
    ds = _tiny_ds()
    base1, pbase1 = _run_steps(FSDPEngine(
        create_model("mlp", num_classes=4, hidden=32), mesh=mesh8,
        learning_rate=5e-3), ds)
    none1, pnone1 = _run_steps(_fsdp_engine("none", mesh8), ds)
    np.testing.assert_array_equal(base1, none1)
    for a, b in zip(jax.tree.leaves(pbase1), jax.tree.leaves(pnone1)):
        np.testing.assert_array_equal(a, b)
    base8, pbase8 = _run_steps(FSDPEngine(
        create_model("mlp", num_classes=4, hidden=32), mesh=mesh8,
        learning_rate=5e-3), ds, n_steps=8, k=8)
    none8, pnone8 = _run_steps(_fsdp_engine("none", mesh8), ds,
                               n_steps=8, k=8)
    np.testing.assert_array_equal(base8, none8)
    for a, b in zip(jax.tree.leaves(pbase8), jax.tree.leaves(pnone8)):
        np.testing.assert_array_equal(a, b)


def test_fsdp_wire_bytes_halved_and_quartered(mesh8):
    """Acceptance: bf16 halves the reported gradient wire bytes; int8
    quarters them plus one f32 scale per leaf."""
    ds = _tiny_ds(64)
    engines = {name: _fsdp_engine(name, mesh8)
               for name in ("none", "bf16", "int8")}
    states = {name: eng.init_state(jax.random.key(0), ds.x[:8])
              for name, eng in engines.items()}
    raw = engines["none"].grad_collective_bytes_raw(states["none"])
    assert raw > 0
    assert engines["none"].grad_collective_bytes(states["none"]) == raw
    assert engines["bf16"].grad_collective_bytes(states["bf16"]) == raw // 2
    n_leaves = len(jax.tree.leaves(states["int8"].params))
    assert engines["int8"].grad_collective_bytes(states["int8"]) == \
        raw // 4 + 4 * n_leaves
    # raw is codec-independent
    for name in ("bf16", "int8"):
        assert engines[name].grad_collective_bytes_raw(states[name]) == raw


# round 20 fast-lane repair: drain-parity variant —
# test_fsdp_none_codec_bitwise_identical_at_k1_and_k8 keeps the fast
# k-invariance representative
@pytest.mark.slow
def test_fsdp_compressed_drain_parity_k1_vs_k8(mesh8):
    """The multi-step scan drain is UNCHANGED by compression: with the
    SAME codec, k=8 reproduces k=1 step for step (the stochastic-rounding
    key is derived from state.step, so the trajectory is deterministic)."""
    ds = _tiny_ds()
    for name in ("bf16", "int8"):
        l1, p1 = _run_steps(_fsdp_engine(name, mesh8), ds, n_steps=8, k=1)
        l8, p8 = _run_steps(_fsdp_engine(name, mesh8), ds, n_steps=8, k=8)
        np.testing.assert_array_equal(l1, l8)
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p8)):
            np.testing.assert_array_equal(a, b)


# round 20 fast-lane repair: convergence variant of the codec paths
# pinned bitwise/unbiased by the fast unit tests
@pytest.mark.slow
def test_fsdp_bf16_and_int8_converge_close_to_f32(mesh8):
    """Convergence-tolerance: compressed-gradient training lands within a
    few points of uncompressed on the tiny classification task (the
    everywhere-runnable mirror of the guarded MNIST MLP variant below)."""
    train, test = _tiny_ds(), _tiny_ds(128, "test")
    accs = {}
    for name in ("none", "bf16", "int8"):
        tr = Trainer(None, engine=_fsdp_engine(name, mesh8), seed=0)
        tr.fit(train, epochs=6, batch_size=64, log_every=0)
        accs[name] = tr.evaluate(test)["accuracy"]
    assert accs["none"] > 0.9
    assert accs["bf16"] > accs["none"] - 0.08
    assert accs["int8"] > accs["none"] - 0.12


def test_async_wire_bytes_counted_on_one_destacked_copy(mesh8):
    """The async/gossip exchange moves ONE de-stacked param copy per
    device: the codec accounting must see those shapes — the int8 per-leaf
    scale overhead is 4 bytes per MODEL leaf, not 4/n (a stacked-total
    divided by n would truncate it away)."""
    from distributed_tensorflow_tpu.engines import AsyncLocalEngine

    ds = _tiny_ds(64)
    engines = {name: AsyncLocalEngine(
        create_model("mlp", num_classes=4, hidden=32), mesh=mesh8,
        sync_every=4, grad_compression=name)
        for name in ("none", "bf16", "int8")}
    states = {name: eng.init_state(jax.random.key(0), ds.x[:8])
              for name, eng in engines.items()}
    raw = engines["none"].grad_collective_bytes_raw(states["none"])
    assert raw > 0
    assert engines["none"].grad_collective_bytes(states["none"]) == raw
    assert engines["bf16"].grad_collective_bytes(states["bf16"]) == raw // 2
    n_leaves = len(jax.tree.leaves(states["int8"].params))
    assert engines["int8"].grad_collective_bytes(states["int8"]) == \
        raw // 4 + 4 * n_leaves


def test_resolve_steps_per_call_with_reason():
    """fit's clamp attribution comes from the resolver itself — same
    branch picks k AND names why."""
    resolve = Trainer.resolve_steps_per_call_with_reason
    assert resolve(None) == (8, None)
    assert resolve(None, target_accuracy=0.9) == (1, "target_accuracy")
    # the checkpoint clamp rule is shared, but the reason distinguishes
    # the blocking-save discipline from the overlapped one (ISSUE 5)
    assert resolve(None, checkpoint_every=3) == (3, "checkpoint_sync")
    assert resolve(None, checkpoint_every=3, checkpoint_async=True) == \
        (3, "checkpoint_async")
    assert resolve(None, checkpoint_every=50) == (8, None)
    assert resolve(4, checkpoint_every=3) == (4, None)  # explicit: no clamp
    with pytest.raises(ValueError):
        resolve(0)


# --------------------------------------- Trainer / report / harness wiring

def test_fit_reports_wire_raw_and_codec(mesh8, tmp_path):
    from distributed_tensorflow_tpu.observability import (
        Tracer, build_run_report)

    ds = _tiny_ds(128)
    eng = _fsdp_engine("bf16", mesh8)
    tr = Trainer(None, engine=eng, seed=0)
    trace = tmp_path / "trace.jsonl"
    tracer = Tracer(path=trace)
    r = tr.fit(ds, epochs=1, batch_size=32, log_every=0, max_steps=2,
               tracer=tracer)
    tracer.close()
    assert r["grad_compression"] == "bf16"
    assert r["grad_allreduce_bytes"] * 2 == r["grad_allreduce_bytes_raw"]
    report = build_run_report(r)
    assert report["grad_allreduce_bytes"] == r["grad_allreduce_bytes"]
    assert report["grad_allreduce_bytes_raw"] == r["grad_allreduce_bytes_raw"]
    assert report["grad_compression"] == "bf16"
    events = [json.loads(line) for line in
              trace.read_text().splitlines()]
    prof = [e for e in events if e.get("name") == "collective_profile"]
    assert prof and prof[0]["grad_allreduce_bytes"] * 2 == \
        prof[0]["grad_allreduce_bytes_raw"]
    assert prof[0]["grad_compression"] == "bf16"


def test_checkpoint_clamp_warns_and_lands_in_report(mesh8, tmp_path):
    """Satellite: auto steps_per_call silently capped by checkpoint_every
    now warns once and surfaces the clamp (reason included) in the fit
    result and run report."""
    from distributed_tensorflow_tpu.observability import build_run_report
    from distributed_tensorflow_tpu.utils.checkpoint import CheckpointManager

    ds = _tiny_ds(256)
    tr = Trainer(None, engine=_fsdp_engine("none", mesh8), seed=0)
    cm = CheckpointManager(tmp_path / "ck")
    with pytest.warns(UserWarning, match="checkpoint_every=3 caps"):
        r = tr.fit(ds, epochs=1, batch_size=32, log_every=0,
                   checkpoint_manager=cm, checkpoint_every=3, max_steps=6)
    assert r["steps_per_call"] == 3
    assert r["steps_per_call_clamp"] == {
        "requested": 8, "effective": 3, "reason": "checkpoint_sync"}
    assert build_run_report(r)["steps_per_call_clamp"]["reason"] == \
        "checkpoint_sync"


def test_explicit_steps_per_call_never_warns(mesh8, tmp_path):
    from distributed_tensorflow_tpu.utils.checkpoint import CheckpointManager

    ds = _tiny_ds(256)
    tr = Trainer(None, engine=_fsdp_engine("none", mesh8), seed=0)
    cm = CheckpointManager(tmp_path / "ck")
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any warning fails the test
        r = tr.fit(ds, epochs=1, batch_size=32, log_every=0,
                   steps_per_call=4, checkpoint_manager=cm,
                   checkpoint_every=3, max_steps=6)
    assert r["steps_per_call"] == 4
    assert "steps_per_call_clamp" not in r


def test_target_accuracy_downshift_surfaces_in_result(mesh8):
    ds = _tiny_ds(256)
    tr = Trainer(None, engine=_fsdp_engine("none", mesh8), seed=0)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # the target downshift must NOT warn
        r = tr.fit(ds, epochs=1, batch_size=32, log_every=0,
                   eval_ds=_tiny_ds(64, "test"), target_accuracy=0.05,
                   eval_every=2, max_steps=4)
    assert r["steps_per_call"] == 1
    assert r["steps_per_call_clamp"]["reason"] == "target_accuracy"


def test_cli_flags_parse():
    from distributed_tensorflow_tpu.cli import build_parser

    args = build_parser().parse_args([])
    assert args.grad_compression == "none" and args.compile_cache is None
    args = build_parser().parse_args(
        ["--grad-compression", "bf16", "--compile-cache", "/tmp/xc"])
    assert args.grad_compression == "bf16"
    assert args.compile_cache == "/tmp/xc"
    with pytest.raises(SystemExit):
        build_parser().parse_args(["--grad-compression", "fp4"])


def test_harness_rejects_pipeline_compression():
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, _setup)

    with pytest.raises(ValueError, match="pipeline"):
        _setup(ExperimentConfig(model="bert_tiny", dataset="glue_synth",
                                pipeline_parallel=2,
                                grad_compression="bf16"))
    with pytest.raises(ValueError, match="unknown grad_compression"):
        _setup(ExperimentConfig(grad_compression="fp4"))


def test_enable_compile_cache_sets_config(tmp_path):
    """Satellite: --compile-cache points jax's persistent compilation
    cache at the directory (created on demand) and drops the
    min-compile-time gate so even fast test compiles persist."""
    from distributed_tensorflow_tpu.utils.harness import enable_compile_cache

    target = tmp_path / "xla-cache" / "nested"
    resolved = enable_compile_cache(target)
    assert target.is_dir()
    assert jax.config.jax_compilation_cache_dir == resolved == str(target)
    # leave a clean slate for other tests' compiles
    jax.config.update("jax_compilation_cache_dir", None)


# round 20 fast-lane repair: compile-cache e2e (~8s, disk round-trip)
@pytest.mark.slow
def test_run_with_compile_cache_populates_dir(mesh8, tmp_path):
    """End-to-end: a harness run with compile_cache set leaves compiled
    executables in the directory (so the next run skips those compiles).
    Soft on the entry count — jax versions differ in what they persist —
    but the run itself must succeed with the cache enabled."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    cache = tmp_path / "cache"
    summary = run(ExperimentConfig(
        engine="fsdp", model="mlp", dataset="synthetic", batch_size=4,
        epochs=1, log_every=0, grad_compression="bf16",
        compile_cache=str(cache)))
    try:
        assert summary["steps"] > 0
        assert cache.is_dir()
        assert summary["run_report"]["grad_compression"] == "bf16"
    finally:
        jax.config.update("jax_compilation_cache_dir", None)


# ------------------------------ explicit-collective engines (shard_map)

@needs_shard_map
def test_sync_none_codec_bitwise_identical(mesh8):
    """Acceptance: SyncEngine with --grad-compression none keeps the
    implicit AD-transpose psum — bitwise identical trajectories and params
    at k=1 and through the k=8 drain."""
    from distributed_tensorflow_tpu.engines import SyncEngine

    ds = _tiny_ds()

    def run(codec_kw, k):
        eng = SyncEngine(create_model("mlp", num_classes=4, hidden=32),
                         mesh=mesh8, learning_rate=5e-3, **codec_kw)
        return _run_steps(eng, ds, n_steps=8, k=k)

    for k in (1, 8):
        base_l, base_p = run({}, k)
        none_l, none_p = run({"grad_compression": "none"}, k)
        np.testing.assert_array_equal(base_l, none_l)
        for a, b in zip(jax.tree.leaves(base_p), jax.tree.leaves(none_p)):
            np.testing.assert_array_equal(a, b)


@needs_shard_map
def test_sync_bf16_mnist_mlp_converges_close_to_f32(mesh8):
    """Acceptance (ISSUE 3): short MNIST MLP run with bf16-compressed
    gradient allreduce lands within tolerance of full-f32 grads, and the
    engine reports half the wire bytes."""
    from distributed_tensorflow_tpu.engines import SyncEngine

    train = load_dataset("mnist", split="train")
    test = load_dataset("mnist", split="test")
    accs, engines = {}, {}
    for name in ("none", "bf16"):
        eng = SyncEngine(create_model("mlp", num_classes=train.num_classes),
                         mesh=mesh8, grad_compression=name)
        tr = Trainer(None, engine=eng, seed=0)
        tr.fit(train, epochs=1, batch_size=256, log_every=0, max_steps=80)
        accs[name] = tr.evaluate(test, batch_size=500)["accuracy"]
        engines[name] = (eng, tr.state)
    assert accs["none"] > 0.8          # the task trains at all
    assert abs(accs["bf16"] - accs["none"]) < 0.05
    eng_n, st_n = engines["none"]
    eng_b, st_b = engines["bf16"]
    assert eng_b.grad_collective_bytes(st_b) * 2 == \
        eng_n.grad_collective_bytes(st_n)


@needs_shard_map
@pytest.mark.parametrize("codec", ["bf16", "int8"])
def test_sync_compressed_step_stays_close(mesh8, codec):
    """One compressed sync step tracks the uncompressed update within the
    codec's quantization tolerance at k=1 and k=2.  Compared on the param
    DELTAS (update = params_after − params_before), which a mis-scaled
    gradient — e.g. an accidental extra data-axis psum doubling/8×-ing the
    reduce — would blow far past the tolerance, while raw param values
    (identical init ± lr-sized steps) would hide it."""
    import optax

    from distributed_tensorflow_tpu.engines import SyncEngine

    ds = _tiny_ds(128)
    outs = {}
    for name in ("none", codec):
        for K in (1, 2):
            # SGD, not Adam: Adam's sqrt(v) normalization makes the first
            # update ~lr regardless of gradient SCALE, which would hide
            # exactly the mis-reduction this test exists to catch
            eng = SyncEngine(create_model("mlp", num_classes=4, hidden=32),
                             mesh=mesh8, optimizer=optax.sgd(0.1),
                             grad_accum=K, grad_compression=name)
            state = eng.init_state(jax.random.key(0), ds.x[:8])
            p0 = jax.device_get(state.params)
            bx, by = eng.shard_batch(ds.x[:64], ds.y[:64])
            state, m = eng.step(state, bx, by)
            delta = jax.tree.map(lambda a, b: np.asarray(a) - np.asarray(b),
                                 jax.device_get(state.params), p0)
            outs[(name, K)] = (float(m["loss"]), delta)
    for K in (1, 2):
        base_loss, base_d = outs[("none", K)]
        comp_loss, comp_d = outs[(codec, K)]
        assert np.isfinite(comp_loss)
        assert abs(comp_loss - base_loss) < 0.1
        scale = max(float(np.abs(l).max())
                    for l in jax.tree.leaves(base_d))
        for a, b in zip(jax.tree.leaves(base_d), jax.tree.leaves(comp_d)):
            assert np.all(np.isfinite(b))
            # within 30% of the exact update magnitude everywhere — a
            # double-counted reduce (2x/8x delta) fails by a wide margin
            np.testing.assert_allclose(a, b, atol=0.3 * scale)


@needs_shard_map
@pytest.mark.parametrize("engine_name", ["async", "gossip"])
def test_async_and_gossip_compressed_exchange(mesh8, engine_name):
    """The periodic parameter exchange (async pmean / gossip neighbor mix)
    goes through the codec: a bf16 round lands within cast tolerance of
    the exact round, and the wire figure halves."""
    from distributed_tensorflow_tpu.engines import create_engine

    ds = _tiny_ds(128)
    kw = ({"sync_every": 1} if engine_name == "async"
          else {"degree": 1, "mix_every": 1})
    results = {}
    for name in ("none", "bf16"):
        eng = create_engine(engine_name,
                            create_model("mlp", num_classes=4, hidden=32),
                            mesh=mesh8, learning_rate=1e-2,
                            grad_compression=name, **kw)
        state = eng.init_state(jax.random.key(0), ds.x[:8])
        bx, by = eng.shard_batch(ds.x[:64], ds.y[:64])
        state, _m = eng.step(state, bx, by)  # step 1: exchange fires
        results[name] = (eng, state, jax.device_get(state.params))
    _, st_n, p_none = results["none"]
    eng_b, st_b, p_bf16 = results["bf16"]
    for a, b in zip(jax.tree.leaves(p_none), jax.tree.leaves(p_bf16)):
        np.testing.assert_allclose(a, b, rtol=0.05, atol=0.02)
    assert eng_b.grad_collective_bytes(st_b) * 2 == \
        results["none"][0].grad_collective_bytes(st_n)
