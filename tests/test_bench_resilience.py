"""bench.py hardening against a wedged backend lease (ISSUE 6 satellite):
bounded retry-with-backoff around backend init, and the partial-results
mode that keeps whatever measurement windows completed — so the r03–r05
blackout (one mid-run failure → three rounds of null artifacts) cannot
repeat.  Pure host-level unit tests: the failures are faked, no backend
is touched."""

import pytest

import bench


class _Flaky:
    """Callable failing ``fail_n`` times before succeeding."""

    def __init__(self, fail_n, exc=RuntimeError("lease wedged")):
        self.fail_n = fail_n
        self.exc = exc
        self.calls = 0

    def __call__(self):
        self.calls += 1
        if self.calls <= self.fail_n:
            raise self.exc
        return "backend"


def test_backend_retry_succeeds_after_transient_failures():
    sleeps: list[float] = []
    logs: list[str] = []
    fn = _Flaky(fail_n=2)
    out = bench.with_backend_retry(fn, "fake init", retries=3,
                                   backoff_s=5.0, sleep=sleeps.append,
                                   log=logs.append)
    assert out == "backend" and fn.calls == 3
    # linear backoff: 5s, then 10s — bounded, never exponential blowup
    assert sleeps == [5.0, 10.0]
    assert len(logs) == 2 and "fake init" in logs[0]


def test_backend_retry_raises_last_error_when_exhausted():
    sleeps: list[float] = []
    fn = _Flaky(fail_n=10, exc=RuntimeError("still wedged"))
    with pytest.raises(RuntimeError, match="still wedged"):
        bench.with_backend_retry(fn, "fake init", retries=3,
                                 backoff_s=1.0, sleep=sleeps.append,
                                 log=lambda _m: None)
    assert fn.calls == 3
    assert sleeps == [1.0, 2.0]  # no sleep after the final attempt


def test_backend_retry_env_defaults_are_bounded():
    assert bench.INIT_RETRIES >= 1
    assert bench.INIT_BACKOFF_S > 0


def test_measure_windows_keeps_completed_values_on_failure():
    """Partial-results mode: the windows that completed before the
    failure are kept, and the error is recorded for the JSON line's
    ``partial`` section — never an all-or-nothing artifact."""
    def fn(rep):
        if rep == 2:
            raise RuntimeError("device lost mid-window")
        return 100.0 + rep

    errors: list[str] = []
    vals = bench.measure_windows(fn, 5, "scan", errors)
    assert vals == [100.0, 101.0]
    assert len(errors) == 1
    assert "scan window 3/5" in errors[0]
    assert "device lost mid-window" in errors[0]


def test_measure_windows_clean_run_records_no_errors():
    errors: list[str] = []
    vals = bench.measure_windows(lambda rep: float(rep), 3, "scan", errors)
    assert vals == [0.0, 1.0, 2.0]
    assert errors == []


def test_measure_windows_first_window_failure_yields_empty():
    """Zero completed windows: the caller raises into the structured-skip
    path (bench still emits ONE parsable line, never a bare traceback)."""
    errors: list[str] = []

    def fn(_rep):
        raise RuntimeError("wedged before any window")

    assert bench.measure_windows(fn, 3, "scan", errors) == []
    assert len(errors) == 1
