"""Pipeline parallelism (engines/pipeline.py) on the fake CPU mesh.

Oracle strategy: the pipelined step must compute exactly the math of the
un-pipelined sequential forward (``_sequential_logits``) — same loss, same
gradients — because GPipe microbatching is a schedule, not an approximation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_tensorflow_tpu.engines.base import cross_entropy
from distributed_tensorflow_tpu.engines.pipeline import PipelineEngine
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def _mesh(dp, pp):
    return meshlib.create_mesh(
        dp * pp, shape=(dp, pp),
        axis_names=(meshlib.DATA_AXIS, meshlib.PIPE_AXIS))


def _batch(n=16, seed=0):
    rnd = np.random.default_rng(seed)
    x = rnd.random((n, 28, 28, 1), np.float32)
    y = (np.arange(n) % 10).astype(np.int32)
    return x, y


@pytest.mark.slow
@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
@pytest.mark.parametrize("dp,pp,m", [(2, 4, 4), (1, 8, 2), (4, 2, 1)])
def test_loss_matches_sequential_forward(dp, pp, m, schedule):
    """Reported step loss == global-batch mean loss of the sequential model."""
    mesh = _mesh(dp, pp)
    eng = PipelineEngine(num_classes=10, hidden=24, microbatches=m, mesh=mesh,
                         optimizer=optax.sgd(0.0),  # lr=0: params unchanged
                         schedule=schedule)
    x, y = _batch()
    state = eng.init_state(jax.random.key(0), x)
    state, metrics = eng.step(state, *eng.shard_batch(x, y))
    params = jax.device_get(state.params)
    logits = eng._sequential_logits(params, x)
    ref = float(cross_entropy(logits, jnp.asarray(y)).mean())
    assert abs(float(metrics["loss"]) - ref) < 1e-5


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
@pytest.mark.slow
def test_gradients_match_sequential_model(schedule):
    """One SGD step through the pipeline == explicit jax.grad of the
    sequential forward (microbatching must not change the math; for 1f1b
    additionally: the hand-scheduled interleaved backward must produce the
    same grads AD produces for gpipe)."""
    mesh = _mesh(2, 4)
    lr = 0.1
    eng = PipelineEngine(num_classes=10, hidden=24, microbatches=4, mesh=mesh,
                         optimizer=optax.sgd(lr), schedule=schedule)
    x, y = _batch()
    state = eng.init_state(jax.random.key(0), x)
    before = jax.device_get(state.params)
    state, _ = eng.step(state, *eng.shard_batch(x, y))
    after = jax.device_get(state.params)

    def ref_loss(params):
        logits = eng._sequential_logits(params, x)
        return cross_entropy(logits, jnp.asarray(y)).mean()

    grads = jax.grad(ref_loss)(before)
    expected = jax.tree.map(lambda p, g: p - lr * g, before, grads)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(a, e, atol=2e-5, rtol=1e-4),
        after, expected)


def test_params_stay_sharded_over_pipe():
    mesh = _mesh(2, 4)
    eng = PipelineEngine(num_classes=10, hidden=24, microbatches=2, mesh=mesh)
    x, y = _batch(8)
    state = eng.init_state(jax.random.key(0), x)
    state, _ = eng.step(state, *eng.shard_batch(x, y))
    kernel = state.params["blocks"]["Dense_0"]["kernel"]
    spec = kernel.sharding.spec
    assert spec[0] == meshlib.PIPE_AXIS
    # replicated parts really replicated
    assert state.params["head"]["Dense_0"]["kernel"].sharding.is_fully_replicated


@pytest.mark.slow
def test_training_reduces_loss():
    mesh = _mesh(2, 2)
    eng = PipelineEngine(num_classes=4, hidden=32, microbatches=2, mesh=mesh,
                         learning_rate=5e-3)
    rnd = np.random.default_rng(1)
    # learnable synthetic task: class determined by which quadrant mean is max
    x = rnd.random((64, 28, 28, 1), np.float32)
    y = (np.arange(64) % 4).astype(np.int32)
    x[np.arange(64), y * 5, y * 5, 0] += 3.0  # plant a class signal
    state = eng.init_state(jax.random.key(0), x)
    xs, ys = eng.shard_batch(x, y)
    losses = []
    for _ in range(60):
        state, m = eng.step(state, xs, ys)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.2, losses[::10]


def test_evaluate_runs_on_pipe_sharded_params():
    mesh = _mesh(2, 2)
    eng = PipelineEngine(num_classes=10, hidden=16, microbatches=2, mesh=mesh)
    x, y = _batch(12)
    state = eng.init_state(jax.random.key(0), x)

    class DS:
        def batches(self, bs, shuffle=False):
            mask = np.ones(len(x), np.float32)
            yield x, y, mask

    out = eng.evaluate(state, DS(), batch_size=12)
    assert 0.0 <= out["accuracy"] <= 1.0
    assert out["count"] == 12


def test_requires_data_pipe_mesh():
    with pytest.raises(ValueError, match="data.*pipe|pipe"):
        PipelineEngine(mesh=meshlib.create_mesh(8))


def test_embed_head_execute_behind_conditionals():
    """The boundary work must be *gated*, not masked: embed/head sit inside
    HLO `conditional`s, which XLA executes one branch of at runtime — so
    non-boundary stages genuinely skip those FLOPs (VERDICT r2 weak #2:
    previously every stage paid embed+head every tick and multiplied the
    result by 0/1).  Cost analysis can't see this (it sums both branches of
    a conditional), so the assertion is structural."""
    mesh = _mesh(2, 4)
    eng = PipelineEngine(num_classes=10, hidden=24, microbatches=4, mesh=mesh)
    x, y = _batch()
    state = eng.init_state(jax.random.key(0), x)
    state, _ = eng.step(state, *eng.shard_batch(x, y))
    hlo = eng._jit_step.lower(
        state, *eng.shard_batch(x, y)).compile().as_text()
    # forward fill-gate + drain-gate (AD adds transposed conditionals too)
    assert hlo.count("conditional") >= 2, hlo[:2000]


# ----------------------------------------------------------- BERT stages


def _bert_engine(dp=2, pp=4, m=4, lr=0.1, schedule="gpipe"):
    from distributed_tensorflow_tpu.models.bert import bert_pipeline_stages

    return PipelineEngine(
        microbatches=m, mesh=_mesh(dp, pp), optimizer=optax.sgd(lr),
        schedule=schedule,
        stages=bert_pipeline_stages(num_classes=2, vocab_size=128, hidden=32,
                                    heads=2, ffn=64, max_len=16))


def _tokens(n=16, seed=0):
    rnd = np.random.default_rng(seed)
    x = rnd.integers(1, 128, (n, 16)).astype(np.int32)
    y = (np.arange(n) % 2).astype(np.int32)
    return x, y


@pytest.mark.slow
def test_bert_pipeline_matches_sequential_forward():
    """Pipelined BERT step loss == sequential-forward loss (VERDICT r1 #5:
    pipelining a real registered model, not the built-in MLP)."""
    eng = _bert_engine(lr=0.0)
    x, y = _tokens()
    state = eng.init_state(jax.random.key(0), x)
    state, metrics = eng.step(state, *eng.shard_batch(x, y))
    params = jax.device_get(state.params)
    logits = eng._sequential_logits(params, x)
    ref = float(cross_entropy(logits, jnp.asarray(y)).mean())
    assert abs(float(metrics["loss"]) - ref) < 1e-5


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
@pytest.mark.slow
def test_bert_pipeline_gradients_match_sequential_model(schedule):
    lr = 0.1
    eng = _bert_engine(lr=lr, schedule=schedule)
    x, y = _tokens()
    state = eng.init_state(jax.random.key(0), x)
    before = jax.device_get(state.params)
    state, _ = eng.step(state, *eng.shard_batch(x, y))
    after = jax.device_get(state.params)

    def ref_loss(params):
        logits = eng._sequential_logits(params, x)
        return cross_entropy(logits, jnp.asarray(y)).mean()

    grads = jax.grad(ref_loss)(before)
    expected = jax.tree.map(lambda p, g: p - lr * g, before, grads)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(a, e, atol=2e-5, rtol=1e-4),
        after, expected)


@pytest.mark.slow
def test_bert_pipeline_harness_run():
    """`-pp 4 --model bert_tiny` accepted end-to-end by the harness."""
    from distributed_tensorflow_tpu.data.loaders import load_text_dataset
    from distributed_tensorflow_tpu.utils.harness import ExperimentConfig, run

    def dataset_fn(batch_size, type="train", **kw):
        return load_text_dataset(seq_len=16, vocab_size=128, n_train=128,
                                 n_test=64, split=type)

    summary = run(ExperimentConfig(
        engine="sync", model="bert_tiny", dataset="glue_synth",
        n_devices=8, pipeline_parallel=4, microbatches=2, pipeline_hidden=32,
        batch_size=8, epochs=1, log_every=0, dataset_fn=dataset_fn))
    assert summary["engine"] == "pipeline_parallel"
    assert summary["pipeline_parallel"] == 4
    assert np.isfinite(summary["test_loss"])


# ------------------------------------------------------ pp × tp composition


def _mesh3(dp, pp, tp):
    return meshlib.create_mesh(
        dp * pp * tp, shape=(dp, pp, tp),
        axis_names=(meshlib.DATA_AXIS, meshlib.PIPE_AXIS, meshlib.MODEL_AXIS))


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
@pytest.mark.slow
def test_bert_pipeline_tp_matches_sequential(schedule):
    """dp×pp×tp: the pipeline schedule manual over (data, pipe) with
    Megatron TP as a GSPMD auto axis inside each stage must still equal the
    sequential-forward oracle, and the stacked stage kernels must shard
    over BOTH pipe and model (VERDICT r2 weak #6: composition previously
    stopped at dp×tp×sp)."""
    from distributed_tensorflow_tpu.models.bert import bert_pipeline_stages

    lr = 0.1
    eng = PipelineEngine(
        microbatches=2, mesh=_mesh3(2, 2, 2), optimizer=optax.sgd(lr),
        schedule=schedule,
        stages=bert_pipeline_stages(num_classes=2, vocab_size=64, hidden=32,
                                    heads=2, ffn=64, max_len=16,
                                    partition_model=True))
    rnd = np.random.default_rng(0)
    x = rnd.integers(1, 64, (8, 16)).astype(np.int32)
    y = (np.arange(8) % 2).astype(np.int32)
    state = eng.init_state(jax.random.key(0), x)
    ffn = state.params["blocks"]["TransformerLayer_0"]["Dense_0"]["kernel"]
    assert ffn.sharding.spec == (meshlib.PIPE_AXIS, None, meshlib.MODEL_AXIS)
    before = jax.device_get(state.params)
    state, m = eng.step(state, *eng.shard_batch(x, y))
    after = jax.device_get(state.params)

    def ref_loss(params):
        logits = eng._sequential_logits(params, x)
        return cross_entropy(logits, jnp.asarray(y)).mean()

    assert float(m["loss"]) == pytest.approx(float(ref_loss(before)),
                                             abs=1e-5)
    grads = jax.grad(ref_loss)(before)
    expected = jax.tree.map(lambda p, g: p - lr * g, before, grads)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(a, e, atol=2e-5, rtol=1e-4),
        after, expected)


@pytest.mark.slow
def test_pipeline_tp_harness_run():
    """`-pp 2 -tp 2 --model bert_tiny` accepted end-to-end by the harness."""
    from distributed_tensorflow_tpu.data.loaders import load_text_dataset
    from distributed_tensorflow_tpu.utils.harness import ExperimentConfig, run

    def dataset_fn(batch_size, type="train", **kw):
        return load_text_dataset(seq_len=16, vocab_size=128, n_train=128,
                                 n_test=64, split=type)

    summary = run(ExperimentConfig(
        engine="sync", model="bert_tiny", dataset="glue_synth",
        n_devices=8, pipeline_parallel=2, tensor_parallel=2, microbatches=2,
        pipeline_hidden=32, batch_size=4, epochs=1, log_every=0,
        dataset_fn=dataset_fn))
    assert summary["engine"].startswith("pipeline_tp")
    assert summary["n_devices"] == 8
    assert np.isfinite(summary["test_loss"])


# round 20 fast-lane repair: error-path variant that still pays a full
# pipeline+TP compile (~9s); rides the slow lane
@pytest.mark.slow
def test_pipeline_tp_rejects_unannotated_models():
    from distributed_tensorflow_tpu.utils.harness import ExperimentConfig, run

    with pytest.raises(ValueError, match="annot|bert"):
        run(ExperimentConfig(model="mlp", dataset="synthetic", n_devices=8,
                             pipeline_parallel=2, tensor_parallel=2))


# ------------------------------------------------------------- pp × sp


def _pp_sp_mesh(dp=2, pp=2, sp=2):
    return meshlib.create_mesh(dp * pp * sp, shape=(dp, pp, sp),
                               axis_names=("data", "pipe", "seq"))


def _gpt_sp_engine(attention_impl="ring", positional="learned", lr=0.1):
    from distributed_tensorflow_tpu.models.gpt import gpt_pipeline_stages

    return PipelineEngine(
        microbatches=2, mesh=_pp_sp_mesh(), optimizer=optax.sgd(lr),
        stages=gpt_pipeline_stages(vocab_size=64, hidden=32, heads=2,
                                   ffn=64, max_len=16,
                                   attention_impl=attention_impl,
                                   seq_axis="seq", positional=positional))


def _lm_tokens(n=8, seed=0):
    rnd = np.random.default_rng(seed)
    x = rnd.integers(0, 64, (n, 16)).astype(np.int32)
    return x, np.roll(x, -1, axis=1).astype(np.int32)


@pytest.mark.parametrize("impl,posn", [("ring", "learned"),
                                       ("ring_flash", "rope")])
@pytest.mark.slow
def test_pipeline_seq_parallel_matches_sequential(impl, posn):
    """dp×pp×sp GPT decoder: pipelined + seq-sharded training must equal
    the un-pipelined full-sequence oracle exactly (loss and one SGD step) —
    this holds the pipe schedule, the in-stage ring attention, AND the
    seq-offset positions to one oracle at once."""
    lr = 0.1
    eng = _gpt_sp_engine(impl, posn, lr=lr)
    x, y = _lm_tokens()
    state = eng.init_state(jax.random.key(0), x)
    before = jax.device_get(state.params)
    state, m = eng.step(state, *eng.shard_batch(x, y))
    after = jax.device_get(state.params)

    def ref_loss(params):
        logits = eng._sequential_logits(params, x)
        return cross_entropy(logits, jnp.asarray(y)).mean()

    assert abs(float(m["loss"]) - float(ref_loss(before))) < 1e-5
    grads = jax.grad(ref_loss)(before)
    expected = jax.tree.map(lambda p, g: p - lr * g, before, grads)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(a, e, atol=2e-5, rtol=1e-4),
        after, expected)


def test_pipeline_seq_parallel_rejects_1f1b():
    """Ring collectives cannot live inside 1F1B's conditionals (measured
    XLA thunk-executor abort) — the engine must say so up front."""
    from distributed_tensorflow_tpu.models.gpt import gpt_pipeline_stages

    with pytest.raises(ValueError, match="1f1b"):
        PipelineEngine(
            microbatches=2, mesh=_pp_sp_mesh(), schedule="1f1b",
            stages=gpt_pipeline_stages(vocab_size=64, hidden=32, heads=2,
                                       ffn=64, max_len=16,
                                       attention_impl="ring",
                                       seq_axis="seq"))


@pytest.mark.slow
def test_pipeline_seq_parallel_harness():
    from distributed_tensorflow_tpu.data.loaders import load_lm_dataset
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    def lm_fn(batch_size, type="train", **kw):
        return load_lm_dataset(seq_len=16, vocab_size=64, n_train=128,
                               n_test=64, split=type)

    summary = run(ExperimentConfig(
        engine="sync", model="gpt", dataset="lm_synth", n_devices=8,
        pipeline_parallel=2, seq_parallel=2, microbatches=2, batch_size=4,
        epochs=1, log_every=0, dataset_fn=lm_fn))
    assert summary["engine"] == "pipeline_sp[dp*pp*sp,ring]"
    assert np.isfinite(summary["test_loss"])


# ------------------------------------------------- dp x pp x tp x sp (4-D)


def _pp_tp_sp_mesh():
    return meshlib.create_mesh(
        8, shape=(1, 2, 2, 2),
        axis_names=(meshlib.DATA_AXIS, meshlib.PIPE_AXIS,
                    meshlib.MODEL_AXIS, meshlib.SEQ_AXIS))


@pytest.mark.slow
def test_pipeline_tp_sp_matches_sequential():
    """dp×pp×tp×sp on a 4-D mesh: the pipe schedule (manual), in-stage ring
    attention (manual seq), AND Megatron TP (GSPMD auto axis) must together
    reproduce the un-pipelined dense full-sequence oracle — loss and one
    SGD step."""
    from distributed_tensorflow_tpu.models.gpt import gpt_pipeline_stages

    lr = 0.1
    eng = PipelineEngine(
        microbatches=2, mesh=_pp_tp_sp_mesh(), optimizer=optax.sgd(lr),
        stages=gpt_pipeline_stages(vocab_size=64, hidden=32, heads=2,
                                   ffn=64, max_len=16, partition_model=True,
                                   attention_impl="ring", seq_axis="seq"))
    rnd = np.random.default_rng(11)
    x = rnd.integers(0, 64, (8, 16)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    state = eng.init_state(jax.random.key(0), x)
    before = jax.device_get(state.params)
    state, m = eng.step(state, *eng.shard_batch(x, y))
    after = jax.device_get(state.params)

    def ref_loss(params):
        logits = eng._sequential_logits(params, x)
        return cross_entropy(logits, jnp.asarray(y)).mean()

    assert abs(float(m["loss"]) - float(ref_loss(before))) < 1e-5
    grads = jax.grad(ref_loss)(before)
    expected = jax.tree.map(lambda p, g: p - lr * g, before, grads)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(a, e, atol=2e-5, rtol=1e-4),
        after, expected)


@pytest.mark.slow
def test_pipeline_tp_sp_harness():
    from distributed_tensorflow_tpu.data.loaders import load_lm_dataset
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    def lm_fn(batch_size, type="train", **kw):
        return load_lm_dataset(seq_len=16, vocab_size=64, n_train=128,
                               n_test=64, split=type)

    summary = run(ExperimentConfig(
        engine="sync", model="gpt", dataset="lm_synth", n_devices=8,
        pipeline_parallel=2, tensor_parallel=2, seq_parallel=2,
        microbatches=2, batch_size=8, epochs=1, log_every=0,
        pipeline_hidden=32, dataset_fn=lm_fn))
    assert summary["engine"] == "pipeline_tp_sp[dp*pp*tp*sp,ring]"
    assert np.isfinite(summary["test_loss"])


# ------------------------------------------------- --model-arg stage sizing


@pytest.mark.slow
def test_stage_model_args_size_the_stages():
    """--model-arg heads/ffn/layers_per_stage must reach the GPT/BERT stage
    factories (VERDICT r3 #6): layers_per_stage=2 doubles each stage's
    depth, visible in the stacked block param tree."""
    from distributed_tensorflow_tpu.data.loaders import load_lm_dataset
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, _setup)

    def lm_fn(batch_size, type="train", **kw):
        return load_lm_dataset(seq_len=16, vocab_size=64, n_train=64,
                               n_test=32, split=type)

    ex = _setup(ExperimentConfig(
        engine="sync", model="gpt", dataset="lm_synth", n_devices=8,
        pipeline_parallel=2, microbatches=2, batch_size=8, log_every=0,
        pipeline_hidden=32, dataset_fn=lm_fn,
        model_args={"heads": 4, "ffn": 48, "layers_per_stage": 2}))
    assert ex.engine.block.layers_per_stage == 2
    assert ex.engine.block.heads == 4
    assert ex.engine.block.ffn == 48
    # and it actually trains
    x = ex.train_ds.x[:8]
    y = ex.train_ds.y[:8]
    st = ex.engine.init_state(jax.random.key(0), x)
    st, m = ex.engine.step(st, *ex.engine.shard_batch(x, y))
    assert np.isfinite(float(m["loss"]))


def test_stage_model_args_unknown_key_rejected():
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    with pytest.raises(ValueError, match="layers_per_stage"):
        run(ExperimentConfig(
            engine="sync", model="gpt", dataset="lm_synth", n_devices=8,
            pipeline_parallel=2, microbatches=2, batch_size=8, epochs=1,
            log_every=0, model_args={"hidden": 64}))


# ------------------------------------------------------------------ remat


@pytest.mark.slow
def test_gpipe_remat_grad_parity_and_memory():
    """remat=True must change scheduling only — identical loss and SGD step
    to remat=False — while the compiled step's temp (activation) memory
    drops materially at M=8 (VERDICT r3 #5: gpipe stores one residual set
    per tick, M+S-1 of them, without it)."""
    from distributed_tensorflow_tpu.models.gpt import gpt_pipeline_stages

    mesh = _mesh(2, 4)
    rnd = np.random.default_rng(0)
    tok = rnd.integers(0, 64, (16, 32)).astype(np.int32)
    tgt = np.roll(tok, -1, axis=1).astype(np.int32)

    out = {}
    for remat in (False, True):
        eng = PipelineEngine(
            microbatches=8, mesh=mesh, optimizer=optax.sgd(0.1), remat=remat,
            stages=gpt_pipeline_stages(vocab_size=64, hidden=64, heads=2,
                                       ffn=256, max_len=32))
        st = eng.init_state(jax.random.key(0), tok)
        st, m = eng.step(st, *eng.shard_batch(tok, tgt))
        mem = eng._jit_step.lower(
            st, *eng.shard_batch(tok, tgt)).compile().memory_analysis()
        out[remat] = (float(m["loss"]), jax.device_get(st.params),
                      mem.temp_size_in_bytes)

    assert out[False][0] == pytest.approx(out[True][0], abs=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5),
        out[False][1], out[True][1])
    # measured on the 8-device CPU mesh: 4.6 MB -> 1.2 MB at M=8; assert a
    # conservative 2x so minor XLA layout drift doesn't flake the test
    assert out[True][2] < out[False][2] / 2, (out[True][2], out[False][2])


@pytest.mark.slow
def test_gpipe_remat_composes_with_seq_parallel():
    """pp×sp + remat: the ring's collectives replay symmetrically during
    recompute (block runs unconditionally each tick) — same oracle parity
    as the non-remat pp×sp test."""
    from distributed_tensorflow_tpu.models.gpt import gpt_pipeline_stages

    lr = 0.1
    eng = PipelineEngine(
        microbatches=2, mesh=_pp_sp_mesh(), optimizer=optax.sgd(lr),
        remat=True,
        stages=gpt_pipeline_stages(vocab_size=64, hidden=32, heads=2,
                                   ffn=64, max_len=16,
                                   attention_impl="ring", seq_axis="seq"))
    x, y = _lm_tokens()
    state = eng.init_state(jax.random.key(0), x)
    before = jax.device_get(state.params)
    state, m = eng.step(state, *eng.shard_batch(x, y))
    after = jax.device_get(state.params)

    def ref_loss(params):
        logits = eng._sequential_logits(params, x)
        return cross_entropy(logits, jnp.asarray(y)).mean()

    assert abs(float(m["loss"]) - float(ref_loss(before))) < 1e-5
    grads = jax.grad(ref_loss)(before)
    expected = jax.tree.map(lambda p, g: p - lr * g, before, grads)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(a, e, atol=2e-5, rtol=1e-4),
        after, expected)


# ------------------------------------------------------------- pp × ep (MoE)


def _pp_ep_mesh(dp=2, pp=2, ep=2):
    return meshlib.create_mesh(
        dp * pp * ep, shape=(dp, pp, ep),
        axis_names=(meshlib.DATA_AXIS, meshlib.PIPE_AXIS,
                    meshlib.EXPERT_AXIS))


def _chunked_moe_oracle(eng, x, y, dp):
    """Per-(data-shard, microbatch) sequential oracle for MoE pipelines.

    Routing is capacity-limited per CALL (models/moe.py: capacity and
    grouping derive from the tokens the layer sees), so the oracle must
    apply the stages to exactly the chunks the schedule feeds them — a
    full-batch forward would route with a different capacity and is NOT
    the same function.  Returns total_objective_fn, task_loss_fn closing
    over the chunk decomposition."""
    from distributed_tensorflow_tpu.engines.expert_parallel import (
        router_losses)

    M, S = eng.microbatches, eng.n_stages
    per, mb = x.shape[0] // dp, x.shape[0] // dp // M
    aux_w, z_w = eng.aux_weight, eng.router_z_weight

    def chunk_losses(params, xc, yc):
        h = eng.embed.apply({"params": params["embed"]}, xc)
        aux = z = 0.0
        for s in range(S):
            bp = jax.tree.map(lambda a: a[s], params["blocks"])
            h, col = eng.block.apply({"params": bp}, h,
                                     mutable=["intermediates"])
            a_s, z_s, _ = router_losses(col["intermediates"])
            aux, z = aux + a_s, z + z_s
        logits = eng.head.apply({"params": params["head"]}, h)
        return cross_entropy(logits, jnp.asarray(yc)).mean(), aux, z

    def ref_total(params):
        total = 0.0
        for d in range(dp):
            for m_i in range(M):
                sl = slice(d * per + m_i * mb, d * per + (m_i + 1) * mb)
                ce, aux, z = chunk_losses(params, x[sl], y[sl])
                total = total + ce + aux_w * aux + z_w * z
        return total / (dp * M)

    def ref_task(params):
        return sum(
            chunk_losses(params, x[d * per + m_i * mb:
                                   d * per + (m_i + 1) * mb],
                         y[d * per + m_i * mb: d * per + (m_i + 1) * mb])[0]
            for d in range(dp) for m_i in range(M)) / (dp * M)

    return ref_total, ref_task


@pytest.mark.slow
@pytest.mark.parametrize("remat", [False, True])
def test_pipeline_moe_matches_chunked_oracle(remat):
    """dp×pp×ep GPT decoder with MoE-FFN stages: the pipelined step must
    equal the per-chunk sequential oracle — task loss AND one SGD step of
    the full objective (task + aux_weight·aux + z·z_loss summed over every
    stage's routers, averaged over microbatch×shard applications).  Expert
    weights must actually shard ('pipe', 'expert', ...).  remat=True holds
    the jax.checkpoint'd MoE block_apply to the same oracle: the router
    diagnostics are explicit checkpoint OUTPUTS here (not re-sown state),
    so recompute-in-backward cannot double-count them — unlike the GSPMD
    model path, which rejects remat+MoE for exactly that sow reason
    (models/gpt.py GPTLM)."""
    from distributed_tensorflow_tpu.models.gpt import gpt_pipeline_stages

    lr, aux_w, z_w = 0.1, 0.01, 1e-3
    eng = PipelineEngine(
        microbatches=2, mesh=_pp_ep_mesh(), optimizer=optax.sgd(lr),
        aux_weight=aux_w, router_z_weight=z_w, remat=remat,
        stages=gpt_pipeline_stages(vocab_size=64, hidden=32, heads=2,
                                   ffn=64, max_len=16, moe_experts=4,
                                   partition_experts=True))
    x, y = _lm_tokens()
    state = eng.init_state(jax.random.key(0), x)
    w1 = state.params["blocks"]["GPTBlock_0"]["MoELayer_0"]["w1"]
    assert w1.sharding.spec == (meshlib.PIPE_AXIS, meshlib.EXPERT_AXIS,
                                None, None)
    before = jax.device_get(state.params)
    state, m = eng.step(state, *eng.shard_batch(x, y))
    after = jax.device_get(state.params)

    ref_total, ref_task = _chunked_moe_oracle(eng, x, y, dp=2)
    assert float(m["loss"]) == pytest.approx(float(ref_task(before)),
                                             abs=1e-5)
    assert 0.0 <= float(m["overflow"]) <= 1.0
    grads = jax.grad(ref_total)(before)
    expected = jax.tree.map(lambda p, g: p - lr * g, before, grads)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(a, e, atol=2e-5, rtol=1e-4),
        after, expected)


@pytest.mark.slow
def test_bert_pipeline_moe_matches_chunked_oracle():
    """Same pp×ep oracle parity for the BERT encoder family (the stage
    carry is (activations, pad_mask) and the head is the [CLS] pooler)."""
    from distributed_tensorflow_tpu.models.bert import bert_pipeline_stages

    lr, aux_w = 0.1, 0.01
    eng = PipelineEngine(
        microbatches=2, mesh=_pp_ep_mesh(), optimizer=optax.sgd(lr),
        aux_weight=aux_w,
        stages=bert_pipeline_stages(num_classes=2, vocab_size=64, hidden=32,
                                    heads=2, ffn=64, max_len=16,
                                    moe_experts=4, partition_experts=True))
    rnd = np.random.default_rng(3)
    x = rnd.integers(1, 64, (8, 16)).astype(np.int32)
    y = (np.arange(8) % 2).astype(np.int32)
    state = eng.init_state(jax.random.key(0), x)
    before = jax.device_get(state.params)
    state, m = eng.step(state, *eng.shard_batch(x, y))
    after = jax.device_get(state.params)

    ref_total, ref_task = _chunked_moe_oracle(eng, x, y, dp=2)
    assert float(m["loss"]) == pytest.approx(float(ref_task(before)),
                                             abs=1e-5)
    grads = jax.grad(ref_total)(before)
    expected = jax.tree.map(lambda p, g: p - lr * g, before, grads)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(a, e, atol=2e-5, rtol=1e-4),
        after, expected)


def test_pipeline_moe_rejects_1f1b():
    """1F1B's hand-scheduled backward carries only the task cotangent —
    router aux losses would silently drop; the engine must say so."""
    from distributed_tensorflow_tpu.models.gpt import gpt_pipeline_stages

    with pytest.raises(ValueError, match="1f1b.*MoE|MoE.*1f1b|gpipe"):
        PipelineEngine(
            microbatches=2, mesh=_pp_ep_mesh(), schedule="1f1b",
            stages=gpt_pipeline_stages(vocab_size=64, hidden=32, heads=2,
                                       ffn=64, max_len=16, moe_experts=4,
                                       partition_experts=True))


def test_pipeline_expert_axis_requires_moe_stages():
    """An 'expert' mesh axis with dense stages would silently replicate —
    loud rejection instead."""
    from distributed_tensorflow_tpu.models.gpt import gpt_pipeline_stages

    with pytest.raises(ValueError, match="expert"):
        PipelineEngine(
            microbatches=2, mesh=_pp_ep_mesh(),
            stages=gpt_pipeline_stages(vocab_size=64, hidden=32, heads=2,
                                       ffn=64, max_len=16))


@pytest.mark.slow
def test_pipeline_ep_harness():
    """`-pp 2 -ep 2 --model gpt --num-experts 4` end-to-end through the
    harness, including the overflow metric plumbing."""
    from distributed_tensorflow_tpu.data.loaders import load_lm_dataset
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    def lm_fn(batch_size, type="train", **kw):
        return load_lm_dataset(seq_len=16, vocab_size=64, n_train=128,
                               n_test=64, split=type)

    summary = run(ExperimentConfig(
        engine="sync", model="gpt", dataset="lm_synth", n_devices=8,
        pipeline_parallel=2, expert_parallel=2, num_experts=4,
        microbatches=2, batch_size=4, epochs=1, log_every=0,
        dataset_fn=lm_fn,
        # the overflow warning's advised remediation must be reachable:
        # moe_capacity_factor is a stage --model-arg on the pp x ep path
        model_args={"moe_capacity_factor": 2.0}))
    assert summary["engine"] == "pipeline_ep[dp*pp*ep,gpipe]"
    assert np.isfinite(summary["test_loss"])


# ---------------------------------------------- pp × ep × tp / pp × ep × sp


def _ep4_mesh(extra_axis):
    return meshlib.create_mesh(
        8, shape=(1, 2, 2, 2),
        axis_names=(meshlib.DATA_AXIS, meshlib.PIPE_AXIS,
                    meshlib.EXPERT_AXIS, extra_axis))


@pytest.mark.slow
def test_pipeline_ep_tp_matches_sequential():
    """dp×pp×ep×tp (4-D mesh): GShard's 2-D expert layout inside pipeline
    stages — expert FFNs sharded over BOTH 'expert' and 'model' as GSPMD
    auto axes while the pipe schedule stays manual.  Drop-free capacity +
    aux off makes routing grouping-invariant, so the un-pipelined
    sequential forward is the exact oracle (same construction as
    tests/test_composite.py test_ep_sp_matches_single_device)."""
    from distributed_tensorflow_tpu.models.gpt import gpt_pipeline_stages

    lr = 0.1
    eng = PipelineEngine(
        microbatches=2, mesh=_ep4_mesh(meshlib.MODEL_AXIS),
        optimizer=optax.sgd(lr), aux_weight=0.0,
        stages=gpt_pipeline_stages(vocab_size=64, hidden=32, heads=2,
                                   ffn=64, max_len=16, moe_experts=4,
                                   partition_experts=True,
                                   partition_model=True,
                                   moe_capacity_factor=4.0))
    x, y = _lm_tokens()
    state = eng.init_state(jax.random.key(0), x)
    w1 = state.params["blocks"]["GPTBlock_0"]["MoELayer_0"]["w1"]
    assert w1.sharding.spec == (meshlib.PIPE_AXIS, meshlib.EXPERT_AXIS,
                                None, meshlib.MODEL_AXIS)
    before = jax.device_get(state.params)
    state, m = eng.step(state, *eng.shard_batch(x, y))
    after = jax.device_get(state.params)
    assert float(m["overflow"]) == 0.0  # capacity covers everything

    def ref_loss(params):
        logits = eng._sequential_logits(params, x)
        return cross_entropy(logits, jnp.asarray(y)).mean()

    assert float(m["loss"]) == pytest.approx(float(ref_loss(before)),
                                             abs=1e-5)
    grads = jax.grad(ref_loss)(before)
    expected = jax.tree.map(lambda p, g: p - lr * g, before, grads)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(a, e, atol=2e-5, rtol=1e-4),
        after, expected)


@pytest.mark.slow
def test_pipeline_ep_sp_matches_sequential():
    """dp×pp×ep×sp (4-D mesh): the long-context MoE pipeline — ring
    attention manual over 'seq' inside each stage while each seq device's
    token block routes to the 'expert'-sharded experts via GSPMD.  Same
    drop-free oracle construction as the ep×tp variant."""
    from distributed_tensorflow_tpu.models.gpt import gpt_pipeline_stages

    lr = 0.1
    eng = PipelineEngine(
        microbatches=2, mesh=_ep4_mesh(meshlib.SEQ_AXIS),
        optimizer=optax.sgd(lr), aux_weight=0.0,
        stages=gpt_pipeline_stages(vocab_size=64, hidden=32, heads=2,
                                   ffn=64, max_len=16, moe_experts=4,
                                   partition_experts=True,
                                   attention_impl="ring", seq_axis="seq",
                                   moe_capacity_factor=4.0))
    x, y = _lm_tokens()
    state = eng.init_state(jax.random.key(0), x)
    before = jax.device_get(state.params)
    state, m = eng.step(state, *eng.shard_batch(x, y))
    after = jax.device_get(state.params)
    assert float(m["overflow"]) == 0.0

    def ref_loss(params):
        logits = eng._sequential_logits(params, x)
        return cross_entropy(logits, jnp.asarray(y)).mean()

    assert float(m["loss"]) == pytest.approx(float(ref_loss(before)),
                                             abs=1e-5)
    grads = jax.grad(ref_loss)(before)
    expected = jax.tree.map(lambda p, g: p - lr * g, before, grads)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(a, e, atol=2e-5, rtol=1e-4),
        after, expected)


@pytest.mark.slow
def test_pipeline_ep_composites_harness():
    """`-pp 2 -ep 2 -tp 2` and `-pp 2 -ep 2 -sp 2` resolve through the
    harness combo table to the 4-D pipeline engines and train."""
    from distributed_tensorflow_tpu.data.loaders import load_lm_dataset
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    def lm_fn(batch_size, type="train", **kw):
        return load_lm_dataset(seq_len=16, vocab_size=64, n_train=64,
                               n_test=32, split=type)

    for extra, tag in ((dict(tensor_parallel=2), "pipeline_ep_tp"),
                       (dict(seq_parallel=2), "pipeline_ep_sp")):
        summary = run(ExperimentConfig(
            engine="sync", model="gpt", dataset="lm_synth", n_devices=8,
            pipeline_parallel=2, expert_parallel=2, num_experts=4,
            microbatches=2, batch_size=8, epochs=1, log_every=0,
            dataset_fn=lm_fn, **extra))
        assert summary["engine"].startswith(tag), summary["engine"]
        assert np.isfinite(summary["test_loss"])


_FIVE_D_SCRIPT = r"""
import numpy as np, jax, optax
import jax.numpy as jnp
from distributed_tensorflow_tpu.parallel import mesh as meshlib
from distributed_tensorflow_tpu.engines.pipeline import PipelineEngine
from distributed_tensorflow_tpu.engines.base import cross_entropy
from distributed_tensorflow_tpu.models.gpt import gpt_pipeline_stages

jax.config.update("jax_platforms", "cpu")
assert jax.device_count() == 16, jax.device_count()
rnd = np.random.default_rng(0)
x = rnd.integers(0, 64, (8, 16)).astype(np.int32)
y = np.roll(x, -1, axis=1).astype(np.int32)
mesh = meshlib.create_mesh(16, shape=(1, 2, 2, 2, 2),
    axis_names=("data", "pipe", "model", "seq", "expert"))
lr = 0.1
eng = PipelineEngine(microbatches=2, mesh=mesh, optimizer=optax.sgd(lr),
    aux_weight=0.0,
    stages=gpt_pipeline_stages(vocab_size=64, hidden=32, heads=2, ffn=64,
        max_len=16, moe_experts=4, partition_experts=True,
        partition_model=True, attention_impl="ring", seq_axis="seq",
        moe_capacity_factor=4.0))
state = eng.init_state(jax.random.key(0), x)
before = jax.device_get(state.params)
state, m = eng.step(state, *eng.shard_batch(x, y))
after = jax.device_get(state.params)
assert float(m["overflow"]) == 0.0

def ref_loss(params):
    logits = eng._sequential_logits(params, x)
    return cross_entropy(logits, jnp.asarray(y)).mean()

assert abs(float(m["loss"]) - float(ref_loss(before))) < 1e-5
grads = jax.grad(ref_loss)(before)
expected = jax.tree.map(lambda p, g: p - lr * g, before, grads)
jax.tree.map(
    lambda a, e: np.testing.assert_allclose(a, e, atol=2e-5, rtol=1e-4),
    after, expected)

# harness spelling on the same 16-device mesh
from distributed_tensorflow_tpu.data.loaders import load_lm_dataset
from distributed_tensorflow_tpu.utils.harness import ExperimentConfig, run

def lm_fn(batch_size, type="train", **kw):
    return load_lm_dataset(seq_len=16, vocab_size=64, n_train=32,
                           n_test=32, split=type)

summary = run(ExperimentConfig(
    engine="sync", model="gpt", dataset="lm_synth", n_devices=16,
    pipeline_parallel=2, expert_parallel=2, tensor_parallel=2,
    seq_parallel=2, num_experts=4, microbatches=2, batch_size=8,
    epochs=1, log_every=0, dataset_fn=lm_fn))
assert summary["engine"].startswith("pipeline_ep_tp_sp"), summary["engine"]
print("FIVE_D_OK", summary["engine"])
"""


@pytest.mark.slow
def test_pipeline_five_d_mesh_subprocess():
    """dp×pp×ep×tp×sp — every model-parallel axis on one 5-D mesh (pipe +
    ring manual; Megatron + GShard-2-D experts GSPMD).  Needs 16 virtual
    devices, so it runs in a subprocess with its own XLA_FLAGS (the suite's
    interpreter is pinned to 8); asserts exact sequential-oracle parity
    (drop-free capacity construction) and the harness combo spelling."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORM_NAME": "cpu",
        "JAX_PLATFORMS": "",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=16",
        "PYTHONPATH": str(repo) + os.pathsep + env.get("PYTHONPATH", ""),
    })
    out = subprocess.run([sys.executable, "-c", _FIVE_D_SCRIPT], env=env,
                         capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stderr[-3000:]
    assert "FIVE_D_OK" in out.stdout, out.stdout


# ------------------------------------------------------------ pp sampling


@pytest.mark.slow
def test_pipeline_generate_matches_naive_rollout():
    """PipelineEngine.generate (one-compile fixed-length fori_loop decode)
    must emit exactly the tokens of the naive per-length rollout: repeated
    _sequential_logits on the growing prefix, argmax of the last position.
    Causal masking is what makes the zero padding invisible — this test is
    the proof."""
    from distributed_tensorflow_tpu.models.gpt import gpt_pipeline_stages

    eng = PipelineEngine(
        microbatches=2, mesh=_mesh(2, 2), optimizer=optax.sgd(0.1),
        stages=gpt_pipeline_stages(vocab_size=64, hidden=32, heads=2,
                                   ffn=64, max_len=24))
    x, y = _lm_tokens()
    state = eng.init_state(jax.random.key(0), x)
    state, _ = eng.step(state, *eng.shard_batch(x, y))  # non-init params

    prompt = x[:2, :6]
    n_new = 5
    out = eng.generate(state, prompt, n_new)
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(out[:, :6], prompt)

    params = jax.device_get(state.params)
    toks = np.array(prompt)
    for _ in range(n_new):
        logits = np.asarray(eng._sequential_logits(params, toks))
        nxt = logits[:, -1, :].argmax(-1).astype(np.int32)
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, toks)


def test_pipeline_generate_rejects_bert_stages():
    from distributed_tensorflow_tpu.models.bert import bert_pipeline_stages

    eng = PipelineEngine(
        microbatches=2, mesh=_mesh(2, 2),
        stages=bert_pipeline_stages(num_classes=2, vocab_size=64, hidden=16,
                                    heads=2, ffn=32, max_len=16))
    with pytest.raises(ValueError, match="GPT|vocab"):
        eng.generate(None, np.zeros((1, 4), np.int32), 4)


@pytest.mark.slow
def test_pipeline_sample_through_harness():
    """`-pp 2 --sample 4`: the run samples post-train via the pipeline
    decode and records prompts+continuations in the summary."""
    from distributed_tensorflow_tpu.data.loaders import load_lm_dataset
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    def lm_fn(batch_size, type="train", **kw):
        return load_lm_dataset(seq_len=16, vocab_size=64, n_train=64,
                               n_test=32, split=type)

    summary = run(ExperimentConfig(
        engine="sync", model="gpt", dataset="lm_synth", n_devices=8,
        pipeline_parallel=2, microbatches=2, batch_size=4, epochs=1,
        log_every=0, dataset_fn=lm_fn, sample_tokens=4,
        sample_prompt_len=6))
    assert summary["engine"] == "pipeline_parallel"
    samples = np.asarray(summary["samples"])
    # one schema across engines: (B, N) decoded continuations only
    assert samples.shape == (4, 4)
    prompts = np.asarray(summary["sample_prompts"])
    assert prompts.shape == (4, 6)
    assert samples.min() >= 0 and samples.max() < 64  # vocab-bounded


def test_pipeline_generate_rejects_moe_stages():
    """Capacity-limited routing sees the fixed-length buffer's zero
    padding, so the decode would not be the true greedy continuation —
    engine and harness both reject BEFORE any work."""
    from distributed_tensorflow_tpu.models.gpt import gpt_pipeline_stages
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    eng = PipelineEngine(
        microbatches=2, mesh=_pp_ep_mesh(),
        stages=gpt_pipeline_stages(vocab_size=64, hidden=16, heads=2,
                                   ffn=32, max_len=16, moe_experts=4,
                                   partition_experts=True))
    with pytest.raises(ValueError, match="MoE|capacity"):
        eng.generate(None, np.zeros((1, 4), np.int32), 4)
    # harness: rejected pre-train
    with pytest.raises(ValueError, match="MoE pipeline"):
        run(ExperimentConfig(
            engine="sync", model="gpt", dataset="lm_synth", n_devices=8,
            pipeline_parallel=2, expert_parallel=2, num_experts=4,
            microbatches=2, batch_size=4, sample_tokens=4,
            sample_prompt_len=4))
