"""Disaggregated prefill/decode fleet (ISSUE 18): serialized KV handoff
between heterogeneous replicas, prefix-affinity routing, queue-driven
autoscaling, per-role conservation, the handoff fault site, and the
analyze/harness/CLI surfaces.  Everything here runs on this container —
the fleet is host Python over the GSPMD slot tables, no shard_map
anywhere.  (File named to sort AFTER test_serving.py: the single-batcher
invariants must fail first when the shared substrate breaks.)
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.models.gpt import GPTLM
from distributed_tensorflow_tpu.serving import (
    AutoscalePolicy, ContinuousBatcher, FaultInjector, ReplicaSet,
    Request, SlotKVCache, VirtualClock, build_replica_kvs)
from distributed_tensorflow_tpu.serving.fleet import RequestJournal


def tiny_gpt(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("hidden", 32)
    kw.setdefault("layers", 1)
    kw.setdefault("heads", 2)
    kw.setdefault("ffn", 64)
    kw.setdefault("max_len", 48)
    kw.setdefault("dropout_rate", 0.0)
    return GPTLM(**kw)


@pytest.fixture(scope="module")
def model_params():
    model = tiny_gpt()
    x = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 8)),
                    jnp.int32)
    params = model.init(jax.random.key(0), x, train=False)["params"]
    return model, params


def _requests(n=6, seed=3, max_new=8, spread=0.5):
    rng = np.random.default_rng(seed)
    return [Request(rid=i,
                    prompt=rng.integers(0, 64, 6 + i % 4).astype(np.int32),
                    max_new_tokens=max_new, arrival_s=float(i) * spread)
            for i in range(n)]


def _oracle(model, params, requests):
    """Single-replica greedy streams — the bitwise reference every fleet
    schedule (homogeneous, disaggregated, autoscaled) must reproduce."""
    s = ContinuousBatcher(SlotKVCache(model, params, slots=2),
                          clock=VirtualClock()).run(list(requests))
    return {r.rid: r.tokens for r in s["results"]}


def _streams(summary):
    return {r.rid: r.tokens for r in summary["results"]}


def _assert_conservation(summary):
    assert (summary["admitted"] + summary["shed_requests"]
            + summary["unserved_requests"]) == summary["offered"]


@pytest.fixture(scope="module")
def default_oracle(model_params):
    model, params = model_params
    return _oracle(model, params, _requests())


# --------------------------------------------------- handoff roundtrip


def _roundtrip(model, params, steps=6, **kv_kwargs):
    """extract → restore into a SECOND table of the same config, decode
    BOTH on; returns (source stream, restored stream).  extract leaves
    the source slot live, so the source's own continuation is the
    reference the restored table must reproduce."""
    prompt = np.arange(1, 13, dtype=np.int32)
    src = SlotKVCache(model, params, 2, **kv_kwargs)
    s_src, f_src = src.insert(prompt)
    payload = src.extract_handoff(s_src)

    dst = SlotKVCache(model, params, 2, **kv_kwargs)
    slot, tok = dst.restore_handoff(payload)
    assert tok == int(f_src)
    ref_toks, got = [int(f_src)], [int(tok)]
    for _ in range(steps):
        ref_toks.append(int(src.advance()[s_src]))
        got.append(int(dst.advance()[slot]))
    src.evict(s_src)
    assert not src.active.any()
    return ref_toks, got


def test_handoff_roundtrip_f32_bitwise(model_params):
    """f32 storage: the serialized payload is byte-exact, so the greedy
    continuation after restore is bitwise the source table's."""
    model, params = model_params
    ref, got = _roundtrip(model, params)
    assert got == ref


def test_handoff_roundtrip_bf16_bitwise(model_params):
    model, params = model_params
    ref, got = _roundtrip(model, params, kv_dtype=jnp.bfloat16)
    assert got == ref


def test_handoff_roundtrip_int8_scales_ride_along(model_params):
    """int8 storage: the per-vector f32 scale leaves travel in the same
    block trees, so restore is byte-exact against the int8 source — the
    continuation agrees with the int8 reference (tolerance vs the f32
    oracle is the storage dtype's, not the handoff's)."""
    model, params = model_params
    ref, got = _roundtrip(model, params, kv_dtype="int8")
    assert got == ref


def test_handoff_roundtrip_paged(model_params):
    """Paged layout: physical blocks serialize (aliased prefix blocks
    included — the payload is self-contained) and restore allocates into
    the receiving pool; eviction returns every block."""
    model, params = model_params
    ref, got = _roundtrip(model, params, kv_layout="paged", paged_block=8)
    assert got == ref


def test_handoff_paged_restore_failure_leaks_no_blocks(model_params):
    """A restore that dies mid-allocation (pool exhausted) releases every
    block it claimed — the no-leak guard on the receiving side."""
    model, params = model_params
    src = SlotKVCache(model, params, 2, kv_layout="paged", paged_block=8)
    slot, _ = src.insert(np.arange(1, 20, dtype=np.int32))  # 3 blocks
    payload = src.extract_handoff(slot)
    # 8-block pool with 6 already pinned by a resident slot: the restore
    # needs 3, claims 2, fails on the third — and must give both back
    dst = SlotKVCache(model, params, 2, kv_layout="paged", paged_block=8,
                      paged_blocks=8)
    resident, _ = dst.insert(np.arange(1, 45, dtype=np.int32))  # 6 blocks
    held = dst.blocks_in_use
    assert held == 6
    with pytest.raises(Exception):
        dst.restore_handoff(payload)
    assert dst.blocks_in_use == held
    assert int(dst.active.sum()) == 1
    dst.evict(resident)
    assert dst.blocks_in_use == 0


# round 20 fast-lane repair: mesh variant — the monolithic and paged
# handoff roundtrips keep the fast representatives
@pytest.mark.slow
def test_handoff_roundtrip_mesh8_slot_sharded(model_params, mesh8):
    """The handoff works across slot-sharded tables: extract gathers
    through the mesh, restore scatters back — streams stay bitwise."""
    model, params = model_params
    prompt = np.arange(1, 13, dtype=np.int32)
    ref = SlotKVCache(model, params, 8, mesh=mesh8)
    s_ref, f_ref = ref.insert(prompt)
    ref_toks = [int(f_ref)] + [int(ref.advance()[s_ref])
                               for _ in range(5)]
    src = SlotKVCache(model, params, 8, mesh=mesh8)
    s_src, _ = src.insert(prompt)
    payload = src.extract_handoff(s_src)
    dst = SlotKVCache(model, params, 8, mesh=mesh8)
    slot, tok = dst.restore_handoff(payload)
    got = [int(tok)] + [int(dst.advance()[slot]) for _ in range(5)]
    assert got == ref_toks


# ------------------------------------------------- disaggregated fleet


def _mixed_requests(n=9, max_new=6):
    """Every third request carries a long prompt — the interference
    shape disaggregation exists to remove from decode iterations."""
    rng = np.random.default_rng(5)
    reqs = []
    for i in range(n):
        plen = 36 if i % 3 == 2 else 6
        reqs.append(Request(
            rid=i, prompt=rng.integers(0, 64, plen).astype(np.int32),
            max_new_tokens=max_new, arrival_s=float(i) * 0.5))
    return reqs


def test_disagg_parity_accounting_and_ttft(model_params, default_oracle):
    """1P+1D fleet on a virtual clock with a modeled 0.25 s transfer:
    greedy streams bitwise vs the single-batcher oracle (the transfer
    shifts time, never tokens), every request hands off exactly once,
    the per-role partitions sum to the fleet conservation identity, and
    TTFT is arrival → first token INCLUDING the handoff — every
    request's TTFT carries at least the 0.25 s."""
    model, params = model_params
    reqs = _requests()
    oracle = default_oracle
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                    clock=VirtualClock(), roles=["prefill", "decode"],
                    handoff_s=0.25)
    summary = rs.run(list(reqs))
    assert _streams(summary) == oracle
    _assert_conservation(summary)
    d = summary["serve_disagg"]
    assert d["prefill_replicas"] == 1 and d["decode_replicas"] == 1
    assert d["handoffs_initiated"] == d["handoffs_delivered"] == len(reqs)
    assert d["handoffs_dropped"] == 0
    assert d["handoff_s"] == 0.25
    per = d["per_role"]
    for key in ("done", "shed", "lost", "unserved", "pending"):
        assert per["prefill"][key] + per["decode"][key] == {
            "done": summary["completed"], "shed": summary["shed_requests"],
            "lost": 0, "unserved": summary["unserved_requests"],
            "pending": 0}[key]
    assert summary["serve_replica_seconds"] > 0
    for r in summary["results"]:
        assert r.ttft_s >= 0.25, (r.rid, r.ttft_s)


# round 20 fast-lane repair: the ITL-headline lane race rides the slow
# lane; test_affinity_beats_least_loaded_hit_rate keeps a fast
# perf-claim representative for the disagg suite
@pytest.mark.slow
def test_disagg_beats_homogeneous_itl_on_same_trace(model_params):
    """The acceptance comparison: same seeded trace, same total replica
    count, virtual time with per-token prefill cost — the disaggregated
    fleet's ITL p95 AND p99 are strictly lower (decode replicas never
    share an iteration with a 36-token prompt), greedy streams equal."""
    model, params = model_params
    reqs = _mixed_requests()
    oracle = _oracle(model, params, reqs)

    def run(roles):
        clock = VirtualClock(tick=1.0, prefill_token_tick=0.25)
        rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                        clock=clock, prefill_chunk=8, roles=roles,
                        parallel_lanes=True)
        return rs.run(_mixed_requests())

    homog = run(None)
    disagg = run(["prefill", "decode"])
    assert _streams(homog) == oracle
    assert _streams(disagg) == oracle
    assert disagg["serve_itl_p95_s"] < homog["serve_itl_p95_s"], (
        disagg["serve_itl_p95_s"], homog["serve_itl_p95_s"])
    assert disagg["serve_itl_p99_s"] < homog["serve_itl_p99_s"]
    assert disagg["serve_parallel_lanes"] is True


def test_roles_validation(model_params):
    model, params = model_params
    kvs = build_replica_kvs(model, params, 2, 2)
    with pytest.raises(ValueError, match="1:1"):
        ReplicaSet(kvs, clock=VirtualClock(), roles=["prefill"])
    with pytest.raises(ValueError, match="prefill"):
        ReplicaSet(kvs, clock=VirtualClock(), roles=["decode", "decode"])
    with pytest.raises(ValueError, match="role"):
        ReplicaSet(kvs, clock=VirtualClock(), roles=["prefill", "chef"])


# ------------------------------------------------- affinity routing


def _shared_requests(n=8, shared_len=8, tail=4, seed=11):
    rng = np.random.default_rng(seed)
    shared = rng.integers(0, 64, shared_len).astype(np.int32)
    return [Request(rid=i,
                    prompt=np.concatenate(
                        [shared,
                         rng.integers(0, 64, tail).astype(np.int32)]),
                    max_new_tokens=4, arrival_s=float(i) * 0.5)
            for i in range(n)]


def test_affinity_beats_least_loaded_hit_rate(model_params):
    """Same seeded shared-prefix trace, same 2-replica fleet: the
    affinity router lands repeats where the pool is warm, so its
    fleet-wide hit rate is STRICTLY higher than least-loaded's — and
    the gate key only exists under the non-default router."""
    model, params = model_params

    def run(routing):
        kvs = build_replica_kvs(model, params, 2, 2,
                                prefix_cache_blocks=8, prefix_block=4)
        rs = ReplicaSet(kvs, clock=VirtualClock(), routing=routing)
        return rs.run(_shared_requests())

    ll = run("least-loaded")
    aff = run("affinity")
    assert ll["completed"] == aff["completed"] == 8
    assert "serve_fleet_prefix_hit_rate" not in ll
    assert "serve_routing" not in ll
    assert aff["serve_routing"] == "affinity"
    assert aff["serve_fleet_prefix_hit_rate"] \
        > ll["serve_prefix_cache_hit_rate"]
    # same streams either way: routing changes placement, not tokens
    assert _streams(ll) == _streams(aff)


def test_routing_validation(model_params):
    model, params = model_params
    with pytest.raises(ValueError, match="routing"):
        ReplicaSet(build_replica_kvs(model, params, 2, 2),
                   clock=VirtualClock(), routing="round-robin")


# ------------------------------------------------- autoscaling


def _bursty_requests(n=26, max_new=4):
    """Quiet head, a burst in the middle, quiet tail — the diurnal shape
    the queue-watermark policy exists for."""
    rng = np.random.default_rng(9)
    reqs, t = [], 0.0
    for i in range(n):
        t += 0.1 if 8 <= i < 20 else 2.0
        reqs.append(Request(
            rid=i, prompt=rng.integers(0, 64, 6).astype(np.int32),
            max_new_tokens=max_new, arrival_s=t))
    return reqs


def test_autoscale_diurnal_scales_up_and_saves_replica_seconds(
        model_params):
    """The burst wakes dormant replicas (scale_ups >= 1), every request
    completes, conservation holds, and the replica-seconds actually paid
    stay under the static-fleet bill (3 × elapsed)."""
    model, params = model_params
    reqs = _bursty_requests()
    oracle = _oracle(model, params, reqs)
    rs = ReplicaSet(build_replica_kvs(model, params, 3, 2),
                    clock=VirtualClock(), autoscale="1:3")
    summary = rs.run(_bursty_requests())
    assert _streams(summary) == oracle
    _assert_conservation(summary)
    auto = summary["autoscale"]
    assert auto["min_replicas"] == 1 and auto["max_replicas"] == 3
    assert auto["scale_ups"] >= 1
    assert summary["serve_replica_seconds"] > 0
    assert summary["serve_replica_seconds"] \
        < 3 * summary["elapsed_s"], summary["serve_replica_seconds"]
    for ev in auto["events"]:
        assert ev["action"] in ("up", "down")


def test_autoscale_policy_grammar():
    pol = AutoscalePolicy.parse("2:5")
    assert pol.min_replicas == 2 and pol.max_replicas == 5
    with pytest.raises(ValueError, match="MIN:MAX"):
        AutoscalePolicy.parse("3")
    with pytest.raises(ValueError, match="MIN:MAX"):
        AutoscalePolicy.parse("a:b")
    with pytest.raises(ValueError, match="max_replicas"):
        AutoscalePolicy.parse("4:2")
    with pytest.raises(ValueError, match="min_replicas"):
        AutoscalePolicy(min_replicas=0)
    with pytest.raises(ValueError, match="high_watermark"):
        AutoscalePolicy(high_watermark=0)


def test_autoscale_validation(model_params):
    model, params = model_params
    kvs = build_replica_kvs(model, params, 2, 2)
    with pytest.raises(ValueError, match="must fit"):
        ReplicaSet(kvs, clock=VirtualClock(), autoscale="1:5")
    # round 20: roles + autoscale COMPOSE — the MIN:MAX range is clamped
    # per role pool, so a 1:2 policy over a 1P:1D split is legal (each
    # pool drives 1:1)
    rs = ReplicaSet(kvs, clock=VirtualClock(),
                    roles=["prefill", "decode"], autoscale="1:2")
    assert rs._role_range("prefill") == (1, 1)
    assert rs._role_range("decode") == (1, 1)


# ------------------------------------------------- handoff fault site


def test_fault_grammar_handoff_site():
    faults = FaultInjector.parse("crash:replica=0,handoff=2")
    assert faults[0].site == "handoff" and faults[0].at == 2
    with pytest.raises(ValueError, match="handoff"):
        FaultInjector.parse("crash:replica=0,banana=1")


def test_handoff_crash_requeues_no_leak_no_duplicates(model_params,
                                                      default_oracle):
    """A prefill replica killed between prefill completion and decode
    admission (the handoff site): its request requeues to the surviving
    prefill replica, streams stay bitwise, conservation holds per role,
    and — on paged tables — no pool block leaks anywhere."""
    model, params = model_params
    reqs = _requests()
    oracle = default_oracle
    kvs = build_replica_kvs(model, params, 3, 2, kv_layout="paged",
                            paged_block=8)
    inj = FaultInjector("crash:replica=0,handoff=1", seed=0)
    rs = ReplicaSet(kvs, clock=VirtualClock(),
                    roles=["prefill", "prefill", "decode"],
                    fault_injector=inj)
    summary = rs.run(list(reqs))
    assert summary["serve_fleet"]["failovers"] == 1
    assert summary["serve_fleet"]["faults_injected"]
    assert summary["serve_duplicate_emissions"] == 0
    assert _streams(summary) == oracle
    _assert_conservation(summary)
    per = summary["serve_disagg"]["per_role"]
    assert sum(per[r]["done"] for r in per) == summary["completed"] == 6
    for kv in kvs:
        assert kv.blocks_in_use == 0, kv.blocks_in_use


def test_handoff_with_no_decode_survivor_is_accounted(model_params):
    """Killing the ONLY decode replica: prefill-side work cannot be
    delivered — the window ends with every request accounted (done on a
    survivor is impossible, so they land in unserved), never hung."""
    model, params = model_params
    kvs = build_replica_kvs(model, params, 2, 2)
    inj = FaultInjector("crash:replica=1,iter=1", seed=0)
    rs = ReplicaSet(kvs, clock=VirtualClock(),
                    roles=["prefill", "decode"], fault_injector=inj,
                    retry_limit=1)
    summary = rs.run(_requests())
    _assert_conservation(summary)
    d = summary["serve_disagg"]
    assert d["handoffs_dropped"] >= 0
    assert summary["completed"] + summary["unserved_requests"] == 6


# ------------------------------------------------- journal semantics


def test_journal_transfer_assign_consumes_no_attempt():
    """A handoff is a transfer, not a retry: assign(transfer=True) moves
    ownership without touching the attempt budget or resetting phase."""
    reqs = [Request(rid=0, prompt=np.arange(4, dtype=np.int32),
                    max_new_tokens=2, arrival_s=0.0)]
    j = RequestJournal(reqs)
    j.assign(0, 0, 0.0)
    e = j.entries[0]
    assert e.attempts == 1 and e.phase == "prefill"
    j.set_phase(0, "decode")
    j.assign(0, 1, 1.0, transfer=True)
    assert e.attempts == 1          # no attempt consumed
    assert e.phase == "decode"      # phase preserved across transfer
    j.assign(0, 0, 2.0, retry=True)
    assert e.attempts == 2
    assert e.phase == "prefill"     # a real retry re-prefills
    counts = j.role_counts()
    assert set(counts) == {"prefill", "decode"}


# ------------------------------------------------- flag-off parity pins


def test_flag_off_fleet_summary_keys_unchanged(model_params):
    """Round-17 pin: a default ReplicaSet run carries NONE of the
    round-18 gated keys — flag-off summaries stay key-identical."""
    model, params = model_params
    rs = ReplicaSet(build_replica_kvs(model, params, 2, 2),
                    clock=VirtualClock())
    summary = rs.run(_requests(n=3, max_new=4))
    for key in ("serve_disagg", "autoscale", "serve_replica_seconds",
                "serve_routing", "serve_fleet_prefix_hit_rate",
                "serve_parallel_lanes"):
        assert key not in summary, key


def test_flag_off_batcher_summary_keys_unchanged(model_params):
    """The single batcher without a role carries no handoff keys."""
    model, params = model_params
    summary = ContinuousBatcher(SlotKVCache(model, params, 2),
                                clock=VirtualClock()).run(_requests(n=2))
    for key in ("serve_role", "handoffs_out", "handoffs_in"):
        assert key not in summary, key


def test_handoff_programs_gated_out_of_compiled_set(model_params):
    """compiled_programs() is a pinned exact dict (test_serving.py): the
    handoff program family only appears once a handoff actually built
    its ops — a never-handed-off table reports the round-17 set."""
    model, params = model_params
    kv = SlotKVCache(model, params, 2)
    kv.insert(np.arange(6, dtype=np.int32))
    assert "handoff_block_ops" not in kv.compiled_programs()
    kv.extract_handoff(0)
    assert kv.compiled_programs()["handoff_block_ops"] >= 1


# ------------------------------------------------- analyze gates


def test_round18_diff_gates_and_directions():
    from distributed_tensorflow_tpu.observability.analyze import (
        _DIFF_METRICS)

    directions = dict(_DIFF_METRICS)
    assert directions["serve_fleet_prefix_hit_rate"] == "higher"
    assert directions["serve_replica_seconds"] == "lower"
    assert directions["disagg_vs_homogeneous_itl_p95"] == "lower"


def test_value_direction_round18_pins():
    """_value_direction pins: the disagg bench headline is a latency
    RATIO (< 1 = disagg wins) — lower-is-better — while the rate-valued
    serving headlines stay higher-is-better."""
    from distributed_tensorflow_tpu.observability.analyze import (
        _value_direction)

    assert _value_direction(
        {"metric": "gpt_serve_disagg_itl_p95_ratio",
         "unit": "disagg/homogeneous itl_p95 ratio (< 1 = disagg "
                 "wins)"}) == "lower"
    assert _value_direction(
        {"metric": "gpt_serve_fleet_requests_per_sec_per_chip",
         "unit": "requests/sec/chip"}) == "higher"


def test_round18_keys_flatten_through_serve_section(model_params,
                                                    tmp_path):
    """The gated keys survive serve_section and flatten through
    load_report for `analyze diff` — and a self-diff is clean."""
    import json

    from distributed_tensorflow_tpu.observability import serve_section
    from distributed_tensorflow_tpu.observability.analyze import (
        diff_reports, load_report)

    model, params = model_params
    kvs = build_replica_kvs(model, params, 2, 2,
                            prefix_cache_blocks=8, prefix_block=4)
    rs = ReplicaSet(kvs, clock=VirtualClock(), routing="affinity",
                    roles=["prefill", "decode"])
    sec = serve_section(rs.run(_shared_requests()), 8)
    json.dumps(sec)
    path = tmp_path / "report.json"
    path.write_text(json.dumps({"serve": sec}))
    flat = load_report(path)
    assert "serve_fleet_prefix_hit_rate" in flat
    assert "serve_replica_seconds" in flat
    diff = diff_reports(flat, dict(flat))
    assert diff["regressions"] == []


# ------------------------------------------------- harness + CLI


def _lm_fn(batch_size, type="train", **kw):
    from distributed_tensorflow_tpu.data.loaders import load_lm_dataset

    return load_lm_dataset(seq_len=16, vocab_size=64, n_train=64,
                           n_test=32, split=type)


_HARNESS_BASE = dict(
    engine="fsdp", model="gpt", dataset="lm_synth", dataset_fn=_lm_fn,
    n_devices=8, batch_size=4, log_every=0,
    model_args={"hidden": 32, "layers": 1, "heads": 2, "ffn": 64,
                "max_len": 48},
    serve_requests=8, serve_slots=2, serve_max_new=4,
    serve_prompt_len=4)


@pytest.mark.slow
def test_harness_disagg_e2e_fsdp():
    """--serve-disaggregate 1:1 through the harness: fleet forced on,
    every request hands off and completes, per-role conservation sums to
    the fleet identity, replica-seconds lands in the section.  (slow:
    trains a model; the tier1.yml Disagg smoke drives the same surface
    through the CLI in CI.)"""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    summary = run(ExperimentConfig(**_HARNESS_BASE,
                                   serve_disaggregate="1:1"))
    sec = summary["serve"]
    assert sec["mode"] == "fleet"
    assert sec["replicas"] == 2
    assert sec["completed"] == 8
    d = sec["serve_disagg"]
    assert d["handoffs_delivered"] == 8
    per = d["per_role"]
    assert per["prefill"]["done"] + per["decode"]["done"] == 8
    assert sec["serve_replica_seconds"] > 0
    assert summary["serve_exit_policy"] == 0


def test_harness_round18_validation_pre_train():
    """Bad round-18 flags fail BEFORE training, like every other serve
    flag — including the disagg-aware fault-spec replica bound."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    cases = [
        (dict(serve_disaggregate="2"), "P:D"),
        (dict(serve_disaggregate="0:1"), "at least one"),
        (dict(serve_disaggregate="1:1", serve_draft_config="self"),
         "draft"),
        (dict(serve_disaggregate="1:1", serve_hot_swap=True),
         "hot-swap"),
        (dict(serve_routing="bogus"), "serve-routing"),
        (dict(serve_routing="affinity"), "prefix"),
        (dict(serve_autoscale="2:1"), "max_replicas"),
        (dict(serve_autoscale="1:4", serve_replicas=2), "exceeds"),
        # round 20: autoscale + disaggregate now COMPOSES (per-role
        # pools) — the old rejection is gone; bad k still rejected
        (dict(serve_multi_step=0), "multi-step"),
        (dict(serve_fault_spec="crash:replica=3,iter=1",
              serve_disaggregate="1:2"), "replica 3"),
    ]
    for kw, pattern in cases:
        with pytest.raises(ValueError, match=pattern):
            run(ExperimentConfig(**_HARNESS_BASE, **kw))


def test_parse_disaggregate_grammar():
    from distributed_tensorflow_tpu.utils.harness import (
        parse_disaggregate)

    assert parse_disaggregate("2:3") == (2, 3)
    for bad in ("3", "a:b", "1:", "0:2", "2:0"):
        with pytest.raises(ValueError):
            parse_disaggregate(bad)


def test_cli_round18_flags_parse():
    from distributed_tensorflow_tpu.cli import build_parser

    args = build_parser().parse_args(
        ["--serve", "8", "--serve-disaggregate", "1:2",
         "--serve-routing", "affinity", "--serve-autoscale", "1:3"])
    assert args.serve_disaggregate == "1:2"
    assert args.serve_routing == "affinity"
    assert args.serve_autoscale == "1:3"
    # defaults stay round-17: no disagg, least-loaded, no autoscale
    args = build_parser().parse_args(["--serve", "8"])
    assert args.serve_disaggregate is None
    assert args.serve_routing == "least-loaded"
    assert args.serve_autoscale is None
