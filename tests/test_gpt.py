"""GPT decoder-LM tests: causal-attention parity across impls, LM training
under DP/TP/FSDP/seq-parallel/pipeline, and the harness/CLI path.

The reference has no language models (SURVEY.md §2.2); these tests hold the
new family to the same oracle discipline as BERT: every parallel rendering
must reproduce single-device dense-attention training step-for-step.
"""

import jax
import numpy as np
import pytest

from distributed_tensorflow_tpu.data.loaders import load_lm_dataset
from distributed_tensorflow_tpu.engines import (
    FSDPEngine, SeqParallelEngine, SyncEngine, Trainer)
from distributed_tensorflow_tpu.models import create_model
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def tiny_gpt(attention_impl="dense", heads=2, partition_model=False,
             vocab_size=64, max_len=64):
    return create_model(
        "gpt", num_classes=vocab_size, hidden=32, layers=1, heads=heads,
        ffn=64, max_len=max_len, dropout_rate=0.0,
        attention_impl=attention_impl, partition_model=partition_model)


@pytest.fixture(scope="module")
def lm_data():
    tr = load_lm_dataset(seq_len=32, vocab_size=64, n_train=512, n_test=256)
    te = load_lm_dataset(seq_len=32, vocab_size=64, n_train=512, n_test=256,
                         split="test")
    return tr, te


# ---------------------------------------------------------------- dataset


def test_lm_synth_dataset(lm_data):
    tr, te = lm_data
    assert tr.x.shape == (512, 32) and tr.y.shape == (512, 32)
    assert tr.num_classes == 64
    # targets are the inputs shifted by one: x[t+1] == y[t]
    np.testing.assert_array_equal(tr.x[:, 1:], tr.y[:, :-1])
    # deterministic in (seed, split); splits disjoint draws of one chain
    tr2 = load_lm_dataset(seq_len=32, vocab_size=64, n_train=512, n_test=256)
    np.testing.assert_array_equal(tr.x, tr2.x)
    assert not np.array_equal(tr.x[:256], te.x)


# ------------------------------------------------- causal impl parity


@pytest.mark.slow
def test_flash_causal_matches_dense(lm_data):
    """Same params, same tokens: the Pallas flash path (interpret mode on
    CPU) must produce the dense-causal logits."""
    tr, _ = lm_data
    x = tr.x[:4]
    dense = tiny_gpt("dense")
    flash = dense.clone(attention_impl="flash")
    params = dense.init(jax.random.key(0), x, train=False)["params"]
    ld = dense.apply({"params": params}, x, train=False)
    lf = flash.apply({"params": params}, x, train=False)
    np.testing.assert_allclose(ld, lf, atol=2e-5, rtol=1e-4)


# ----------------------------------------------------------- DP training


def test_gpt_sync_trains(lm_data):
    tr, te = lm_data
    eng = SyncEngine(tiny_gpt(), mesh=meshlib.create_mesh(8),
                     learning_rate=3e-3)
    t = Trainer(None, engine=eng)
    t.fit(tr, epochs=4, batch_size=64, log_every=0)
    ev = t.evaluate(te, batch_size=64)
    # a learned Markov chain beats the 1/64 ≈ 0.016 uniform floor by a wide
    # margin (measured ~0.097 after 4 epochs of this tiny config; 0.06 keeps
    # seed headroom while still requiring ~4× above chance)
    assert ev["accuracy"] > 0.06, ev
    # eval counts TOKENS for LMs (token_weights broadcast): B × L of them
    assert ev["count"] == len(te) * te.x.shape[1]


def test_gpt_fsdp_step(lm_data):
    tr, _ = lm_data
    eng = FSDPEngine(tiny_gpt(), mesh=meshlib.create_mesh(8))
    state = eng.init_state(jax.random.key(0), tr.x[:8])
    xs, ys = eng.shard_batch(tr.x[:16], tr.y[:16])
    state, m = eng.step(state, xs, ys)
    assert np.isfinite(float(m["loss"]))
    per_dev, total = eng.state_bytes_per_device(state)
    assert per_dev < total


# ------------------------------------------------------- tensor parallel


def test_gpt_tensor_parallel_matches_single_device(lm_data):
    """Megatron-annotated GPT on (data=2, model=4) must reproduce
    single-device training (SGD so fp32 noise stays fp32 noise)."""
    import optax

    tr, _ = lm_data
    x, y = tr.x[:16], tr.y[:16]

    eng1 = SyncEngine(tiny_gpt(heads=4), optimizer=optax.sgd(0.1),
                      mesh=meshlib.create_mesh(1))
    s1 = eng1.init_state(jax.random.key(0), x)
    for _ in range(2):
        xs, ys = eng1.shard_batch(x, y)
        s1, m1 = eng1.step(s1, xs, ys)

    from distributed_tensorflow_tpu.engines.tensor_parallel import (
        TensorParallelEngine)

    tp_mesh = meshlib.create_mesh(8, shape=(2, 4),
                                  axis_names=("data", "model"))
    eng8 = TensorParallelEngine(
        tiny_gpt(heads=4, partition_model=True), optimizer=optax.sgd(0.1),
        mesh=tp_mesh)
    s8 = eng8.init_state(jax.random.key(0), x)
    for _ in range(2):
        xs, ys = eng8.shard_batch(x, y)
        s8, m8 = eng8.step(s8, xs, ys)

    for a, b in zip(jax.tree.leaves(jax.device_get(s1.params)),
                    jax.tree.leaves(jax.device_get(s8.params))):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3)
    assert float(m1["loss"]) == pytest.approx(float(m8["loss"]), abs=1e-4)


# ----------------------------------------------- sequence parallelism (LM)


@pytest.mark.slow
@pytest.mark.parametrize("impl", ["ring", "ring_flash", "ulysses",
                                  "ulysses_flash"])
def test_gpt_seq_parallel_matches_single_device(lm_data, impl):
    """Causal LM under (data=2, seq=4): per-token logits VARY over 'seq'
    (unlike BERT's [CLS] broadcast), exercising the engine's LM loss path —
    must still reproduce single-device dense training step-for-step."""
    import optax

    tr, _ = lm_data
    x, y = tr.x[:16], tr.y[:16]
    heads = 4 if impl.startswith("ulysses") else 2

    eng1 = SyncEngine(tiny_gpt("dense", heads=heads),
                      optimizer=optax.sgd(0.1), mesh=meshlib.create_mesh(1))
    s1 = eng1.init_state(jax.random.key(0), x)
    for _ in range(2):
        xs, ys = eng1.shard_batch(x, y)
        s1, m1 = eng1.step(s1, xs, ys)

    sp_mesh = meshlib.create_mesh(8, shape=(2, 4),
                                  axis_names=("data", "seq"))
    eng8 = SeqParallelEngine(tiny_gpt(impl, heads=heads),
                             optimizer=optax.sgd(0.1), mesh=sp_mesh)
    s8 = eng8.init_state(jax.random.key(0), x)
    for _ in range(2):
        xs, ys = eng8.shard_batch(x, y)
        s8, m8 = eng8.step(s8, xs, ys)

    for a, b in zip(jax.tree.leaves(jax.device_get(s1.params)),
                    jax.tree.leaves(jax.device_get(s8.params))):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3)
    assert float(m1["loss"]) == pytest.approx(float(m8["loss"]), abs=1e-4)


def test_gpt_seq_parallel_eval_counts_tokens(lm_data):
    _, te = lm_data
    sp_mesh = meshlib.create_mesh(8, shape=(2, 4),
                                  axis_names=("data", "seq"))
    eng = SeqParallelEngine(tiny_gpt("ring"), mesh=sp_mesh)
    state = eng.init_state(jax.random.key(0), te.x[:8])
    ev = eng.evaluate(state, te, batch_size=64)
    assert ev["count"] == len(te) * te.x.shape[1]


@pytest.mark.slow
def test_gpt_composite_tp_sp_matches_single_device(lm_data):
    """dp×tp×sp GPT: Megatron-sharded weights (GSPMD) + manual-seq causal
    ring, LM loss varying over 'seq' — must reproduce single-device dense
    training (the composite engine's LM path)."""
    import optax

    from distributed_tensorflow_tpu.engines.composite import CompositeEngine

    tr, _ = lm_data
    x, y = tr.x[:8], tr.y[:8]

    eng1 = SyncEngine(tiny_gpt("dense", heads=2),
                      optimizer=optax.sgd(0.1), mesh=meshlib.create_mesh(1))
    s1 = eng1.init_state(jax.random.key(0), x)
    for _ in range(2):
        xs, ys = eng1.shard_batch(x, y)
        s1, m1 = eng1.step(s1, xs, ys)

    c_mesh = meshlib.create_mesh(
        8, shape=(2, 2, 2), axis_names=("data", "model", "seq"))
    eng8 = CompositeEngine(
        tiny_gpt("ring", heads=2, partition_model=True),
        optimizer=optax.sgd(0.1), mesh=c_mesh)
    s8 = eng8.init_state(jax.random.key(0), x)
    for _ in range(2):
        xs, ys = eng8.shard_batch(x, y)
        s8, m8 = eng8.step(s8, xs, ys)

    for a, b in zip(jax.tree.leaves(jax.device_get(s1.params)),
                    jax.tree.leaves(jax.device_get(s8.params))):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3)
    assert float(m1["loss"]) == pytest.approx(float(m8["loss"]), abs=1e-4)


# ---------------------------------------------------------------- pipeline


@pytest.mark.slow
def test_gpt_pipeline_trains(lm_data):
    """GPT decoder over the pipe axis (embed → blocks → untied head)."""
    from distributed_tensorflow_tpu.engines.pipeline import PipelineEngine
    from distributed_tensorflow_tpu.models.gpt import gpt_pipeline_stages

    tr, _ = lm_data
    pp_mesh = meshlib.create_mesh(8, shape=(2, 4),
                                  axis_names=("data", "pipe"))
    eng = PipelineEngine(
        microbatches=2, mesh=pp_mesh, learning_rate=3e-3,
        stages=gpt_pipeline_stages(vocab_size=64, hidden=32, heads=2,
                                   ffn=64, max_len=32))
    state = eng.init_state(jax.random.key(0), tr.x[:8])
    losses = []
    for i in range(6):
        lo = (i * 16) % 256
        xs, ys = eng.shard_batch(tr.x[lo:lo + 16], tr.y[lo:lo + 16])
        state, m = eng.step(state, xs, ys)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


# --------------------------------------------------------------- generate


@pytest.mark.slow
def test_generate_greedy_matches_full_forward(lm_data):
    """KV-cache decode oracle: greedy generation must reproduce the
    teacher-forced rollout that re-runs the FULL forward each step — any
    cache/cursor/position bug shows up as a divergent token."""
    from distributed_tensorflow_tpu.models.gpt import generate

    tr, _ = lm_data
    model = tiny_gpt()
    x = tr.x[:2, :8]
    params = model.init(jax.random.key(0), x, train=False)["params"]

    out = np.asarray(generate(model, params, x, max_new_tokens=6,
                              greedy=True))

    cur = np.asarray(x)
    for _ in range(6):
        logits = model.apply({"params": params}, cur, train=False)
        nxt = np.asarray(logits[:, -1].argmax(-1)).astype(cur.dtype)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, cur[:, 8:])


def test_generate_sampling_shapes_and_bounds(lm_data):
    from distributed_tensorflow_tpu.models.gpt import generate

    tr, _ = lm_data
    model = tiny_gpt()
    x = tr.x[:3, :5]
    params = model.init(jax.random.key(0), x, train=False)["params"]
    out = np.asarray(generate(model, params, x, max_new_tokens=4,
                              temperature=0.8, rng=jax.random.key(7)))
    assert out.shape == (3, 4)
    assert out.dtype == x.dtype
    assert (out >= 0).all() and (out < 64).all()
    # capacity guard: prompt (32) + 40 new > max_len (64)
    with pytest.raises(ValueError, match="max_len"):
        generate(model, params, tr.x[:1], max_new_tokens=40)


# ------------------------------------------------------------ harness/CLI


def _lm_dataset_fn(batch_size, type="train", **kw):
    return load_lm_dataset(seq_len=32, vocab_size=64, n_train=256, n_test=64,
                           split=type)


@pytest.mark.slow
def test_gpt_harness_dp(lm_data):
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    summary = run(ExperimentConfig(
        engine="sync", model="gpt", dataset="lm_synth", n_devices=8,
        batch_size=4, epochs=1, log_every=0, dataset_fn=_lm_dataset_fn))
    assert summary["model"] == "gpt"
    assert np.isfinite(summary["test_loss"])


@pytest.mark.slow
def test_gpt_harness_seq_parallel():
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    summary = run(ExperimentConfig(
        engine="sync", model="gpt", dataset="lm_synth", n_devices=8,
        seq_parallel=4, attention_impl="ring", batch_size=4, epochs=1,
        log_every=0, dataset_fn=_lm_dataset_fn))
    assert summary["engine"] == "seq_parallel[ring]"
    assert np.isfinite(summary["test_loss"])


def test_gpt_rejects_non_token_dataset():
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    with pytest.raises(ValueError, match="lm_synth"):
        run(ExperimentConfig(engine="sync", model="gpt", dataset="mnist",
                             n_devices=8))


# -------------------------------------------------------------------- RoPE


@pytest.mark.slow
def test_rope_gpt_trains_and_beats_chance(lm_data):
    tr, te = lm_data
    model = create_model("gpt", num_classes=64, hidden=32, layers=1,
                         heads=2, ffn=64, max_len=64, dropout_rate=0.0,
                         positional="rope")
    # no learned position table in the param tree
    params = model.init(jax.random.key(0), tr.x[:2], train=False)["params"]
    assert "pos_embed" not in params
    eng = SyncEngine(model, mesh=meshlib.create_mesh(8), learning_rate=3e-3)
    t = Trainer(None, engine=eng)
    t.fit(tr, epochs=3, batch_size=64, log_every=0)
    assert t.evaluate(te, batch_size=64)["accuracy"] > 0.05


@pytest.mark.slow
def test_rope_seq_parallel_matches_single_device(lm_data):
    """RoPE under (data=2, seq=4) ring attention: each seq device must
    rotate its block at GLOBAL positions (offset = block index × local
    length) — an un-offset implementation diverges immediately."""
    import optax

    tr, _ = lm_data
    x, y = tr.x[:16], tr.y[:16]

    def rope_gpt(impl):
        return create_model("gpt", num_classes=64, hidden=32, layers=1,
                            heads=2, ffn=64, max_len=64, dropout_rate=0.0,
                            positional="rope", attention_impl=impl)

    eng1 = SyncEngine(rope_gpt("dense"), optimizer=optax.sgd(0.1),
                      mesh=meshlib.create_mesh(1))
    s1 = eng1.init_state(jax.random.key(0), x)
    for _ in range(2):
        xs, ys = eng1.shard_batch(x, y)
        s1, m1 = eng1.step(s1, xs, ys)

    sp_mesh = meshlib.create_mesh(8, shape=(2, 4),
                                  axis_names=("data", "seq"))
    eng8 = SeqParallelEngine(rope_gpt("ring"), optimizer=optax.sgd(0.1),
                             mesh=sp_mesh)
    s8 = eng8.init_state(jax.random.key(0), x)
    for _ in range(2):
        xs, ys = eng8.shard_batch(x, y)
        s8, m8 = eng8.step(s8, xs, ys)

    for a, b in zip(jax.tree.leaves(jax.device_get(s1.params)),
                    jax.tree.leaves(jax.device_get(s8.params))):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3)
    assert float(m1["loss"]) == pytest.approx(float(m8["loss"]), abs=1e-4)


@pytest.mark.slow
def test_rope_generate_matches_full_forward(lm_data):
    """KV-cache decode with RoPE: cached keys carry their own rotation;
    the cursor position rotates each new q — greedy generation must still
    equal the teacher-forced rollout."""
    from distributed_tensorflow_tpu.models.gpt import generate

    tr, _ = lm_data
    model = create_model("gpt", num_classes=64, hidden=32, layers=1,
                         heads=2, ffn=64, max_len=64, dropout_rate=0.0,
                         positional="rope")
    x = tr.x[:2, :8]
    params = model.init(jax.random.key(0), x, train=False)["params"]
    out = np.asarray(generate(model, params, x, max_new_tokens=5,
                              greedy=True))
    cur = np.asarray(x)
    for _ in range(5):
        logits = model.apply({"params": params}, cur, train=False)
        nxt = np.asarray(logits[:, -1].argmax(-1)).astype(cur.dtype)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, cur[:, 8:])


@pytest.mark.slow
def test_rope_pipeline_trains(lm_data):
    """RoPE threads through the pipeline stages (no position table in any
    stage's params; blocks rotate at arange(L))."""
    from distributed_tensorflow_tpu.engines.pipeline import PipelineEngine
    from distributed_tensorflow_tpu.models.gpt import gpt_pipeline_stages

    tr, _ = lm_data
    pp_mesh = meshlib.create_mesh(8, shape=(2, 4),
                                  axis_names=("data", "pipe"))
    eng = PipelineEngine(
        microbatches=2, mesh=pp_mesh, learning_rate=3e-3,
        stages=gpt_pipeline_stages(vocab_size=64, hidden=32, heads=2,
                                   ffn=64, max_len=32, positional="rope"))
    state = eng.init_state(jax.random.key(0), tr.x[:8])
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    names = {"/".join(str(getattr(k, "key", k)) for k in p)
             for p, _ in flat}
    assert not any("Embed_1" in n for n in names), names  # no pos table
    losses = []
    for i in range(4):
        lo = (i * 16) % 256
        xs, ys = eng.shard_batch(tr.x[lo:lo + 16], tr.y[lo:lo + 16])
        state, m = eng.step(state, xs, ys)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()


# --------------------------------------------------------------------- GQA


def test_gqa_param_shapes_and_training(lm_data):
    """kv_heads=2 under heads=4: K/V kernels emit half the heads, and the
    model still trains."""
    tr, _ = lm_data
    model = create_model("gpt", num_classes=64, hidden=32, layers=1,
                         heads=4, kv_heads=2, ffn=64, max_len=64,
                         dropout_rate=0.0)
    params = model.init(jax.random.key(0), tr.x[:2], train=False)["params"]
    attn = params["GPTBlock_0"]["CausalSelfAttention_0"]
    assert attn["query"]["kernel"].shape == (32, 32)
    assert attn["key"]["kernel"].shape == (32, 16)   # 2 kv heads × 8
    assert attn["value"]["kernel"].shape == (32, 16)
    eng = SyncEngine(model, mesh=meshlib.create_mesh(8), learning_rate=3e-3)
    s = eng.init_state(jax.random.key(0), tr.x[:8])
    xs, ys = eng.shard_batch(tr.x[:32], tr.y[:32])
    s, first = eng.step(s, xs, ys)
    for _ in range(20):
        s, m = eng.step(s, xs, ys)
    assert float(m["loss"]) < float(first["loss"])


@pytest.mark.parametrize("kvh", [1, 2])
@pytest.mark.slow
def test_gqa_generate_matches_full_forward(lm_data, kvh):
    """MQA/GQA decode: the cache holds kv_heads only; greedy generation
    must still equal the teacher-forced full-forward rollout."""
    from distributed_tensorflow_tpu.models.gpt import generate

    tr, _ = lm_data
    model = create_model("gpt", num_classes=64, hidden=32, layers=1,
                         heads=4, kv_heads=kvh, ffn=64, max_len=64,
                         dropout_rate=0.0)
    x = tr.x[:2, :8]
    params = model.init(jax.random.key(0), x, train=False)["params"]
    out = np.asarray(generate(model, params, x, max_new_tokens=5,
                              greedy=True))
    cur = np.asarray(x)
    for _ in range(5):
        logits = model.apply({"params": params}, cur, train=False)
        nxt = np.asarray(logits[:, -1].argmax(-1)).astype(cur.dtype)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, cur[:, 8:])


def test_gqa_invalid_heads_rejected(lm_data):
    tr, _ = lm_data
    model = create_model("gpt", num_classes=64, hidden=32, layers=1,
                         heads=4, kv_heads=3, ffn=64, max_len=64)
    with pytest.raises(ValueError, match="kv_heads"):
        model.init(jax.random.key(0), tr.x[:2], train=False)


# ---------------------------------------------------------- checkpointing


@pytest.mark.slow
def test_gpt_checkpoint_roundtrip_and_generate(tmp_path, lm_data):
    """Orbax save → restore of a trained LM state, then generation parity:
    the restored params must produce byte-identical greedy continuations."""
    from distributed_tensorflow_tpu.models.gpt import generate
    from distributed_tensorflow_tpu.utils.checkpoint import CheckpointManager

    tr, _ = lm_data
    model = tiny_gpt()
    eng = SyncEngine(model, mesh=meshlib.create_mesh(8), learning_rate=3e-3)
    state = eng.init_state(jax.random.key(0), tr.x[:8])
    for i in range(3):
        xs, ys = eng.shard_batch(tr.x[i * 32:(i + 1) * 32],
                                 tr.y[i * 32:(i + 1) * 32])
        state, _ = eng.step(state, xs, ys)

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    jax.block_until_ready(state)
    mgr.save(state)

    template = eng.init_state(jax.random.key(1), tr.x[:8])
    restored = mgr.restore(template)
    assert int(jax.device_get(restored.step)) == int(
        jax.device_get(state.step))

    p0 = jax.device_get(eng.eval_params(state))
    p1 = jax.device_get(eng.eval_params(restored))
    out0 = np.asarray(generate(model, p0, tr.x[:2, :8], max_new_tokens=6,
                               greedy=True))
    out1 = np.asarray(generate(model, p1, tr.x[:2, :8], max_new_tokens=6,
                               greedy=True))
    np.testing.assert_array_equal(out0, out1)


@pytest.mark.slow
def test_lm_summary_reports_perplexity():
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    summary = run(ExperimentConfig(
        engine="sync", model="gpt", dataset="lm_synth", n_devices=8,
        batch_size=4, epochs=1, log_every=0, dataset_fn=_lm_dataset_fn))
    assert summary["test_perplexity"] == pytest.approx(
        np.exp(summary["test_loss"]), rel=1e-6)


# ------------------------------------------------- engine-matrix breadth


@pytest.mark.slow
def test_gpt_bf16_trains_finite(lm_data):
    """Mixed precision (bf16 activations, f32 params) on the LM: loss
    stays finite and decreases."""
    import jax.numpy as jnp

    tr, _ = lm_data
    model = create_model("gpt", num_classes=64, hidden=32, layers=1,
                         heads=2, ffn=64, max_len=64, dropout_rate=0.0,
                         dtype=jnp.bfloat16)
    p = model.init(jax.random.key(0), tr.x[:2], train=False)["params"]
    assert jax.tree.leaves(p)[0].dtype == jnp.float32  # params stay f32
    eng = SyncEngine(model, mesh=meshlib.create_mesh(8), learning_rate=3e-3)
    s = eng.init_state(jax.random.key(0), tr.x[:8])
    xs, ys = eng.shard_batch(tr.x[:32], tr.y[:32])
    s, first = eng.step(s, xs, ys)
    for _ in range(20):
        s, m = eng.step(s, xs, ys)
    assert np.isfinite(float(m["loss"]))
    assert float(m["loss"]) < float(first["loss"])


@pytest.mark.parametrize("engine_name", ["async", "gossip"])
@pytest.mark.slow
def test_gpt_under_async_and_gossip(lm_data, engine_name):
    """The LM trains under the reference-parity DP engines too (local-SGD
    async, ppermute gossip) — (B, L) labels need no engine special-casing."""
    from distributed_tensorflow_tpu.engines import create_engine

    tr, te = lm_data
    kw = {"sync_every": 4} if engine_name == "async" else {"degree": 1}
    eng = create_engine(engine_name, tiny_gpt(),
                        mesh=meshlib.create_mesh(8), learning_rate=3e-3,
                        **kw)
    t = Trainer(None, engine=eng)
    t.fit(tr, epochs=2, batch_size=64, log_every=0)
    ev = t.evaluate(te, batch_size=64)
    assert np.isfinite(ev["loss"])
    assert ev["accuracy"] > 0.03  # above the 1/64 floor


@pytest.mark.slow
def test_decode_cache_overflow_flag():
    """Direct decode-API use past max_len cannot raise (the cursor is
    traced) but must not stay silent: the sticky cache['overflow'] flag
    flips once a token would land past capacity (ADVICE r3)."""
    import jax.numpy as jnp

    def overflowed(cache):
        leaves = [leaf for path, leaf
                  in jax.tree_util.tree_flatten_with_path(cache)[0]
                  if "overflow" in jax.tree_util.keystr(path)]
        assert leaves, "decode cache carries no overflow flag"
        return any(bool(x) for x in leaves)

    model = tiny_gpt(max_len=4).clone(decode=True)
    tok = np.zeros((1, 1), np.int32)
    variables = model.init(jax.random.key(0), jnp.asarray(tok), train=False)
    params, cache = variables["params"], variables["cache"]

    flags = []
    for _ in range(6):
        _, upd = model.apply({"params": params, "cache": cache},
                             jnp.asarray(tok), train=False,
                             mutable=["cache"])
        cache = upd["cache"]
        flags.append(overflowed(cache))
    # within capacity: clean; past it: sticky True
    assert flags == [False, False, False, False, True, True]


def test_remat_grad_parity_dp(lm_data):
    """Model-level remat (nn.remat per block) is a scheduling change only:
    identical loss and SGD step on the sync DP path."""
    import optax

    tr, _ = lm_data
    x, y = tr.x[:16], tr.y[:16]
    out = {}
    for remat in (False, True):
        model = create_model("gpt", num_classes=64, hidden=32, layers=2,
                             heads=2, ffn=64, max_len=64, dropout_rate=0.0,
                             remat=remat)
        eng = SyncEngine(model, optimizer=optax.sgd(0.1),
                         mesh=meshlib.create_mesh(8))
        st = eng.init_state(jax.random.key(0), x)
        st, m = eng.step(st, *eng.shard_batch(x, y))
        out[remat] = (float(m["loss"]), jax.device_get(st.params))
    assert out[False][0] == pytest.approx(out[True][0], abs=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5),
        out[False][1], out[True][1])


@pytest.mark.slow
def test_remat_composes_with_ring_seq_parallel(lm_data):
    """--remat under dp×sp: nn.remat'd blocks containing ring ppermutes
    replay symmetrically across seq devices; parity vs the non-remat run."""
    import optax

    tr, _ = lm_data
    x, y = tr.x[:8], tr.y[:8]
    mesh = meshlib.create_mesh(
        8, shape=(2, 4), axis_names=(meshlib.DATA_AXIS, meshlib.SEQ_AXIS))
    out = {}
    for remat in (False, True):
        model = create_model("gpt", num_classes=64, hidden=32, layers=2,
                             heads=2, ffn=64, max_len=64, dropout_rate=0.0,
                             attention_impl="ring", remat=remat)
        eng = SeqParallelEngine(model, optimizer=optax.sgd(0.1), mesh=mesh)
        st = eng.init_state(jax.random.key(0), x)
        st, m = eng.step(st, *eng.shard_batch(x, y))
        out[remat] = (float(m["loss"]), jax.device_get(st.params))
    assert out[False][0] == pytest.approx(out[True][0], abs=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5),
        out[False][1], out[True][1])


def test_remat_cli_rejects_non_transformer():
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    with pytest.raises(ValueError, match="remat"):
        run(ExperimentConfig(engine="sync", model="mlp", dataset="synthetic",
                             n_devices=8, remat=True))


# -------------------------------------------------- multi-device generate


def test_generate_batch_parallel_matches_single_device(lm_data):
    """generate(mesh=...) shards the prompt batch over 'data': tokens must
    be identical to the single-device sampler (same params, same rng)."""
    from distributed_tensorflow_tpu.models.gpt import generate

    tr, _ = lm_data
    model = tiny_gpt()
    x = tr.x[:8, :8]
    params = model.init(jax.random.key(0), x, train=False)["params"]

    ref = np.asarray(generate(model, params, x, max_new_tokens=5,
                              greedy=True))
    mesh = meshlib.create_mesh(8)
    out = np.asarray(generate(model, params, x, max_new_tokens=5,
                              greedy=True, mesh=mesh))
    np.testing.assert_array_equal(ref, out)


@pytest.mark.slow
def test_generate_tp_decode_matches_single_device(lm_data):
    """TP decode: a partition_model GPT generates under a ('data','model')
    mesh with params kept Megatron-sharded — tokens must match the
    single-device sampler on the same (replicated) params."""
    from distributed_tensorflow_tpu.models.gpt import generate

    tr, _ = lm_data
    tp_model = tiny_gpt(partition_model=True)
    plain = tiny_gpt(partition_model=False)
    x = tr.x[:4, :8]
    # init unsharded (annotations only box metadata at init under jit);
    # reference tokens from the plain clone on identical param values
    params = jax.tree.map(
        lambda l: getattr(l, "value", l),
        tp_model.init(jax.random.key(1), x, train=False)["params"])
    ref = np.asarray(generate(plain, params, x, max_new_tokens=5,
                              greedy=True))

    mesh = meshlib.create_mesh(
        8, shape=(2, 4),
        axis_names=(meshlib.DATA_AXIS, meshlib.MODEL_AXIS))
    out = np.asarray(generate(tp_model, params, x, max_new_tokens=5,
                              greedy=True, mesh=mesh))
    np.testing.assert_array_equal(ref, out)


def test_gpt_seq_parallel_grad_accum_parity(lm_data):
    """grad_accum under dp×sp with an LM: loss/acc vary over BOTH manual
    axes (per-token blocks), exercising the varying-carry scan path."""
    import optax

    tr, _ = lm_data
    x, y = tr.x[:8], tr.y[:8]
    mesh = meshlib.create_mesh(
        8, shape=(2, 4), axis_names=(meshlib.DATA_AXIS, meshlib.SEQ_AXIS))
    out = {}
    for K in (1, 2):
        model = tiny_gpt("ring")
        eng = SeqParallelEngine(model, optimizer=optax.sgd(0.1), mesh=mesh,
                                grad_accum=K)
        st = eng.init_state(jax.random.key(0), x)
        st, m = eng.step(st, *eng.shard_batch(x, y))
        out[K] = (float(m["loss"]), jax.device_get(st.params))
    assert out[1][0] == pytest.approx(out[2][0], abs=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5),
        out[1][1], out[2][1])


# ---------------------------------------------------------------- --sample


def test_harness_sample_after_training():
    """--sample N: the summary carries greedy continuations decoded from
    the trained params (deterministic per seed), shaped (data_shards, N),
    with token ids inside the vocab."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    out = run(ExperimentConfig(
        model="gpt", dataset="lm_synth", engine="sync", n_devices=8,
        batch_size=4, epochs=1, log_every=0, sample_tokens=6,
        sample_prompt_len=4,
        model_args={"hidden": 32, "layers": 1, "heads": 2, "ffn": 64}))
    samples = np.asarray(out["samples"])
    prompts = np.asarray(out["sample_prompts"])
    assert samples.shape == (8, 6) and prompts.shape == (8, 4)
    # lm_synth's default vocab is 128 (data/loaders.py load_lm_dataset)
    assert (samples >= 0).all() and (samples < 128).all()


def test_harness_sample_validation():
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    # --sample under --pipeline-parallel works since round 5 (sequential-
    # forward decode over pipe-stacked GPT stages, engines/pipeline.py
    # generate; oracle-tested in tests/test_pipeline.py) — the rejection
    # that remains is a pipeline whose stages END IN A CLASSIFIER
    with pytest.raises(ValueError, match="causal LM"):
        run(ExperimentConfig(model="bert_tiny", dataset="glue_synth",
                             pipeline_parallel=4, sample_tokens=4,
                             n_devices=8))
    with pytest.raises(ValueError, match="causal LM"):
        run(ExperimentConfig(model="mlp", dataset="synthetic",
                             sample_tokens=4, n_devices=8))
    # deterministically-knowable failures raise BEFORE training: a
    # post-train raise would waste the run (and loop under --max-restarts)
    base = dict(model="gpt", dataset="lm_synth", engine="sync", n_devices=8,
                model_args={"hidden": 32, "layers": 1, "heads": 2,
                            "ffn": 64})
    with pytest.raises(ValueError, match="positive"):
        run(ExperimentConfig(sample_tokens=-4, **base))
    with pytest.raises(ValueError, match="sample-prompt-len"):
        run(ExperimentConfig(sample_tokens=4, sample_prompt_len=500, **base))
    with pytest.raises(ValueError, match="capacity"):
        run(ExperimentConfig(sample_tokens=4, sample_prompt_len=128,
                             **{**base, "model_args": {
                                 "hidden": 32, "layers": 1, "heads": 2,
                                 "ffn": 64, "max_len": 128}}))
