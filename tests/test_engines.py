"""Engine tests on the fake 8-device mesh: convergence + semantics.

The reference's only oracle is end-to-end convergence (SURVEY.md §4); we keep
that as integration coverage (tiny synthetic task to high accuracy) and add
the unit-level semantic checks the reference never had.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.data.loaders import Dataset, synthetic_classification
from distributed_tensorflow_tpu.engines import (
    AsyncLocalEngine, GossipEngine, SyncEngine, Trainer, create_engine)
from distributed_tensorflow_tpu.models import create_model


def tiny_data(n=512, split="train"):
    x, y = synthetic_classification((8, 8), 4, n, seed=3, split=split)
    return Dataset(x=x, y=y, num_classes=4, name="tiny", synthetic=True)


def tiny_model():
    return create_model("mlp", num_classes=4, hidden=32)


@pytest.fixture(scope="module")
def data():
    return tiny_data(), tiny_data(128, "test")


@pytest.mark.parametrize("engine_name,kw", [
    ("sync", {}),
    ("async", {"sync_every": 4}),
    ("gossip", {"degree": 1}),
])
def test_engine_converges(mesh8, data, engine_name, kw):
    train, test = data
    eng = create_engine(engine_name, tiny_model(), mesh=mesh8,
                        learning_rate=5e-3, **kw)
    tr = Trainer(None, engine=eng, seed=0)
    tr.fit(train, epochs=6, batch_size=64, log_every=0)
    acc = tr.evaluate(test)["accuracy"]
    assert acc > 0.9, f"{engine_name} reached only {acc}"


def test_sync_params_stay_replicated(mesh8, data):
    train, _ = data
    eng = SyncEngine(tiny_model(), mesh=mesh8)
    state = eng.init_state(jax.random.key(0), train.x[:8])
    xs, ys = eng.shard_batch(train.x[:64], train.y[:64])
    state, _ = eng.step(state, xs, ys)
    # replicated sharding: every device holds identical full values
    leaf = jax.tree.leaves(state.params)[0]
    assert leaf.sharding.is_fully_replicated


def test_sync_matches_single_device_math(data):
    """8-device sync must equal 1-device training on the same global batch
    (the defining property of sync DP).  SGD optimizer: linear in the
    gradient, so a wrong grad SCALE fails the test — Adam's scale invariance
    would mask exactly the bug this guards against (per-device loss must be
    scaled 1/n because shard_map's AD transpose psums grads implicitly)."""
    import optax

    from distributed_tensorflow_tpu.parallel import mesh as meshlib

    train, _ = data
    x, y = train.x[:64], train.y[:64]

    results = {}
    for n in (1, 8):
        mesh = meshlib.create_mesh(n)
        model = create_model("mlp", num_classes=4, hidden=32, dropout_rate=0.0)
        eng = SyncEngine(model, optimizer=optax.sgd(0.5), mesh=mesh)
        state = eng.init_state(jax.random.key(0), x)
        for _ in range(3):
            xs, ys = eng.shard_batch(x, y)
            state, m = eng.step(state, xs, ys)
        results[n] = (jax.device_get(eng.eval_params(state)), float(m["loss"]))

    p1 = jax.tree.leaves(results[1][0])
    p8 = jax.tree.leaves(results[8][0])
    for a, b in zip(p1, p8):
        np.testing.assert_allclose(a, b, atol=1e-5)
    assert results[1][1] == pytest.approx(results[8][1], abs=1e-5)


def test_async_devices_diverge_then_sync(mesh8, data):
    """Between averaging points device params differ; at sync they agree —
    the semantic contract of the async/local-SGD rendering (SURVEY.md §7.4)."""
    train, _ = data
    eng = AsyncLocalEngine(tiny_model(), mesh=mesh8, sync_every=4)
    state = eng.init_state(jax.random.key(0), train.x[:8])

    def spread(params):
        leaves = jax.device_get(jax.tree.leaves(params))
        return max(np.abs(l - l.mean(axis=0, keepdims=True)).max() for l in leaves)

    rng = np.random.default_rng(0)
    for step in range(1, 9):
        idx = rng.integers(0, len(train.x), 64)
        xs, ys = eng.shard_batch(train.x[idx], train.y[idx])
        state, _ = eng.step(state, xs, ys)
        if step % 4 == 0:
            assert spread(state.params) < 1e-6, f"step {step}: not synced"
        else:
            assert spread(state.params) > 1e-6, f"step {step}: unexpectedly synced"


def test_async_state_sharded_one_copy_per_device(mesh8, data):
    """The stacked local-SGD state must be row-sharded over 'data': each
    device holds exactly ONE parameter/optimizer copy (aggregate O(n) is the
    algorithm; per-device O(1) is the implementation contract — VERDICT r1
    weak #7)."""
    train, _ = data
    eng = AsyncLocalEngine(tiny_model(), mesh=mesh8, sync_every=4)
    state = eng.init_state(jax.random.key(0), train.x[:8])
    n = eng.n_devices
    for leaf in jax.tree.leaves(state.params):
        assert leaf.sharding.spec[0] == "data", leaf.sharding
        assert leaf.shape[0] == n
        # every device's addressable shard is 1/n of the stack — one row
        for shard in leaf.addressable_shards:
            assert shard.data.shape[0] == 1, shard.data.shape


def test_gossip_mixes_toward_consensus(mesh8, data):
    train, _ = data
    eng = GossipEngine(tiny_model(), mesh=mesh8, degree=1)
    state = eng.init_state(jax.random.key(0), train.x[:8])
    rng = np.random.default_rng(0)
    for _ in range(6):
        idx = rng.integers(0, len(train.x), 64)
        xs, ys = eng.shard_batch(train.x[idx], train.y[idx])
        state, _ = eng.step(state, xs, ys)
    # devices differ (gossip is local), but not unboundedly (mixing works)
    leaves = jax.device_get(jax.tree.leaves(state.params))
    spread = max(np.abs(l - l.mean(axis=0, keepdims=True)).max() for l in leaves)
    assert 0 < spread < 1.0


def test_eval_counts_full_test_set(mesh8, data):
    # eval must consume every example exactly once despite padding
    _, test = data
    eng = SyncEngine(tiny_model(), mesh=mesh8)
    state = eng.init_state(jax.random.key(0), test.x[:8])
    ev = eng.evaluate(state, test, batch_size=48)  # 128 % 48 != 0 → padding path
    assert ev["count"] == len(test)


def test_trainer_history_and_metrics(mesh8, data):
    train, test = data
    tr = Trainer(tiny_model(), mesh=mesh8)
    logs = []
    r = tr.fit(train, epochs=1, batch_size=64, log_every=2,
               log_fn=logs.append)
    assert r["steps"] == len(train) // 64
    assert r["examples_per_sec"] > 0
    assert logs, "heartbeat logs missing (reference client.py:92-94 parity)"
    assert tr.history
