"""Paged KV serving (ISSUE 16): the aliased block pool behind
``SlotKVCache(..., kv_layout="paged")`` — dispatch and the flag-off
program-set pin, decode/verify parity against the monolithic oracle
(fused and gather paths, staggered + chunked + prefix + speculative +
int8 composed, mesh-sharded variant), the zero-copy prefix ledger
(pool stores each shared prefix exactly once), copy-on-write isolation,
block-exhaustion admission (``can_admit`` deferral + the scheduler's
``serve_kv_block_deferrals``), honest ``kv_bytes_per_slot``, the
round-16 ``analyze diff`` gates, and the harness/bench surface.
Everything runs on this container — Pallas interpret mode on CPU.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.models.gpt import GPTLM, generate
from distributed_tensorflow_tpu.serving import (
    BlockPoolExhausted, ContinuousBatcher, PagedSlotKVCache, Request,
    SlotKVCache, VirtualClock, build_replica_kvs)


def tiny_gpt(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("hidden", 32)
    kw.setdefault("layers", 2)
    kw.setdefault("heads", 2)
    kw.setdefault("ffn", 64)
    kw.setdefault("max_len", 32)
    kw.setdefault("dropout_rate", 0.0)
    return GPTLM(**kw)


@pytest.fixture(scope="module")
def model_params():
    model = tiny_gpt()
    x = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 8)),
                    jnp.int32)
    params = model.init(jax.random.key(0), x, train=False)["params"]
    return model, params


def _prompts(n, seed=0, lo=3, hi=9):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 64, int(rng.integers(lo, hi))).astype(np.int32)
            for _ in range(n)]


def _oracle(model, params, prompt, n_new):
    return np.asarray(generate(model, params, prompt[None, :], n_new,
                               greedy=True))[0]


def _shared_prefix_prompts(n, seed, shared_len=8, suffix_len=4):
    rng = np.random.default_rng(seed)
    shared = rng.integers(0, 64, shared_len).astype(np.int32)
    return [np.concatenate([shared,
                            rng.integers(0, 64, suffix_len)
                            .astype(np.int32)]) for _ in range(n)]


# ------------------------------------------------- dispatch + program pins


def test_kv_layout_dispatch_and_flag_off_identity(model_params):
    """kv_layout='paged' dispatches to the subclass; the default stays
    the EXACT monolithic class with the PR 7 compiled-program family
    (no paged key in its inventory — the flag-off byte-identity pin at
    the program-set level), and the paged knobs are rejected outside
    the paged layout."""
    model, params = model_params
    kv = SlotKVCache(model, params, slots=2, kv_layout="paged",
                     paged_block=4)
    assert isinstance(kv, PagedSlotKVCache)
    assert kv.kv_layout == "paged"
    mono = SlotKVCache(model, params, slots=2)
    assert type(mono) is SlotKVCache
    assert mono.kv_layout == "monolithic"
    assert "paged_block_copies" not in mono.compiled_programs()
    with pytest.raises(ValueError, match="only apply"):
        SlotKVCache(model, params, slots=2, paged_block=4)
    with pytest.raises(ValueError, match="kv_layout"):
        SlotKVCache(model, params, slots=2, kv_layout="blocked")
    # paged inventory: admission ALWAYS chunks (no slice-out monolithic
    # prefill over a shared pool), prefix hits are pointer writes (no
    # block-op programs, ever)
    kv.insert(np.arange(5, dtype=np.int32))
    kv.advance()
    progs = kv.compiled_programs()
    assert progs["prefill_buckets"] == 0
    assert progs["prefix_block_ops"] == 0
    assert progs["paged_block_copies"] == 0
    assert progs["decode_steps"] == 1


def test_paged_constructor_validation(model_params):
    model, params = model_params
    with pytest.raises(ValueError, match="divide"):
        SlotKVCache(model, params, slots=1, kv_layout="paged",
                    paged_block=5)                      # 32 % 5
    with pytest.raises(ValueError, match="equal prefix_block"):
        SlotKVCache(model, params, slots=1, kv_layout="paged",
                    paged_block=8, prefix_cache_blocks=4, prefix_block=4)
    with pytest.raises(ValueError, match="one full slot"):
        SlotKVCache(model, params, slots=1, kv_layout="paged",
                    paged_block=4, paged_blocks=3)      # < max_blocks


# ------------------------------------------------------------ decode parity


def test_paged_decode_matches_oracle_staggered(model_params):
    """Slots of different ages over ONE shared block pool, advanced by
    one fused (Pallas) step: token-for-token the sequential sampler —
    the paged twin of the monolithic staggered-age parity test."""
    model, params = model_params
    kv = SlotKVCache(model, params, slots=4, kv_layout="paged",
                     paged_block=4)
    prompts = _prompts(3, seed=2)
    firsts = {}

    def collect(toks):
        for _, (slot, got) in firsts.items():
            got.append(int(toks[slot]))

    for i, p in enumerate(prompts):
        slot, first = kv.insert(p)
        firsts[i] = (slot, [first])
        collect(kv.advance())
    for _ in range(3):
        collect(kv.advance())
    for i, p in enumerate(prompts):
        n = len(firsts[i][1])
        np.testing.assert_array_equal(_oracle(model, params, p, n),
                                      np.asarray(firsts[i][1]), str(i))


# round 20 fast-lane repair: internal-equivalence variant (fused is
# the production path and is oracle-pinned fast)
@pytest.mark.slow
def test_paged_gather_path_matches_fused(model_params):
    """paged_fused=False keeps decode on the gather+dense path (the
    bitwise-monolithic oracle in paged clothes): same greedy stream as
    the fused Pallas kernel on the same workload."""
    model, params = model_params

    def run(fused):
        kv = SlotKVCache(model, params, slots=2, kv_layout="paged",
                         paged_block=4, paged_fused=fused)
        p = _prompts(1, seed=7, lo=6, hi=7)[0]
        slot, first = kv.insert(p)
        return [first] + [int(kv.advance()[slot]) for _ in range(5)]

    fused, gather = run(True), run(False)
    assert fused == gather
    p = _prompts(1, seed=7, lo=6, hi=7)[0]
    np.testing.assert_array_equal(_oracle(model, params, p, 6), fused)


# round 20 fast-lane repair: spec-verify × paged composition variant
@pytest.mark.slow
def test_paged_verify_block_parity(model_params):
    """The speculative (slots, k+1) verify over the block pool: feeding
    the committed pending token + the oracle's own continuation returns
    exactly the oracle's next argmaxes, and committed drafts decode on
    correctly — the fused block-query kernel behind verify_block."""
    model, params = model_params
    kv = SlotKVCache(model, params, slots=2, kv_layout="paged",
                     paged_block=4)
    p = _prompts(1, seed=3, lo=5, hi=6)[0]
    orc = _oracle(model, params, p, 6)
    slot, first = kv.insert(p)
    assert first == orc[0]
    block = np.zeros((2, 4), np.int32)
    block[slot] = orc[:4]
    g = kv.verify_block(block)
    np.testing.assert_array_equal(g[slot], orc[1:5])
    kv.commit_block(slot, 4, int(g[slot, 3]))
    assert int(kv.advance()[slot]) == orc[5]


# round 20 fast-lane repair: int8 × paged composition variant
@pytest.mark.slow
def test_paged_int8_decode_matches_monolithic_int8(model_params):
    """int8 pools with in-kernel dequant: the paged fused stream equals
    the monolithic int8 stream (both quantize identically on write; the
    kernel dequantizes what the gather path dequantizes)."""
    model, params = model_params
    p = _prompts(1, seed=8, lo=7, hi=8)[0]

    def run(**kw):
        kv = SlotKVCache(model, params, slots=2, kv_dtype="int8", **kw)
        slot, first = kv.insert(p)
        return [first] + [int(kv.advance()[slot]) for _ in range(5)]

    np.testing.assert_array_equal(
        run(), run(kv_layout="paged", paged_block=4))


def test_paged_on_mesh(model_params, mesh8):
    """The paged layout under GSPMD: pool leaves REPLICATE (any slot
    may touch any block), slot vectors shard over 'data', and the
    sharded fused decode still matches the sequential oracle."""
    model, params = model_params
    kv = SlotKVCache(model, params, slots=8, mesh=mesh8,
                     kv_layout="paged", paged_block=4)
    for leaf in jax.tree.leaves(kv.cache):
        assert leaf.sharding.is_fully_replicated
    out = {}
    for p in _prompts(3, seed=9):
        slot, first = kv.insert(p)
        out[slot] = (p, [first])
    for _ in range(4):
        toks = kv.advance()
        for slot, (_, got) in out.items():
            got.append(int(toks[slot]))
    for slot, (p, got) in out.items():
        np.testing.assert_array_equal(_oracle(model, params, p, 5), got)


# -------------------------------------------- zero-copy prefix sharing + CoW


def test_zero_copy_prefix_counters_and_single_storage(model_params):
    """THE zero-copy pin: admissions 2 and 3 of a shared 8-token prefix
    alias the SAME two physical blocks by pointer — counters exact, the
    pool stores the prefix once (blocks_in_use arithmetic), block
    tables agree on the shared ids, and refcounts account every sharer
    plus the pool pin.  Greedy tokens stay oracle-exact throughout."""
    model, params = model_params
    prompts = _shared_prefix_prompts(3, seed=11)     # 8 shared + 4 own
    kv = SlotKVCache(model, params, slots=3, kv_layout="paged",
                     prefix_cache_blocks=8, prefix_block=4)
    out = {}
    for i, p in enumerate(prompts):
        slot, first = kv.insert(p)
        out[i] = (slot, p, [first])
    for _ in range(3):
        toks = kv.advance()
        for i, (slot, _, got) in out.items():
            got.append(int(toks[slot]))
    for i, (slot, p, got) in out.items():
        np.testing.assert_array_equal(_oracle(model, params, p, 4),
                                      got, str(i))
    stats = kv.paged_stats()
    # admissions 2+3 each matched the 2 shared blocks (8 tokens)
    assert stats["zero_copy_hits"] == 2
    assert stats["zero_copy_blocks"] == 4
    assert stats["zero_copy_tokens"] == 16
    # reuse boundary aligned mid-prompt: nothing wrote a shared block
    assert stats["cow_copies"] == 0
    # stored ONCE: 2 shared + 3 private suffix + 3 private decode blocks
    # (naive per-slot storage would be 12)
    assert stats["blocks_in_use"] == 8
    bt = kv.block_tables_np
    slots_live = [out[i][0] for i in range(3)]
    shared_ids = bt[slots_live[0], :2]
    for s in slots_live[1:]:
        np.testing.assert_array_equal(bt[s, :2], shared_ids)
    # each shared block: 3 slot references + the pool's pin
    for bid in shared_ids:
        assert kv._block_refs[int(bid)] == 4
    # the suffix blocks are private
    assert len({int(bt[s, 2]) for s in slots_live}) == 3


def test_cow_isolation_on_fully_aligned_hit(model_params):
    """Copy-on-write: a block-aligned prefix hit recomputes its final
    token INTO a shared block — the writer gets a private copy (one
    jitted block copy, counted), every other sharer and the pool keep
    the original, and BOTH streams stay oracle-exact (the isolation
    claim)."""
    model, params = model_params
    p = _prompts(1, seed=12, lo=8, hi=9)[0]          # exactly 2 blocks
    kv = SlotKVCache(model, params, slots=2, kv_layout="paged",
                     prefix_cache_blocks=8, prefix_block=4)
    slot_a, first_a = kv.insert(p)
    got_a = [first_a, int(kv.advance()[slot_a])]
    assert kv.paged_stats()["cow_copies"] == 0
    slot_b, first_b = kv.insert(p)                   # fully-aligned hit
    st = kv.paged_stats()
    assert st["zero_copy_hits"] == 1 and st["zero_copy_blocks"] == 2
    assert st["zero_copy_tokens"] == 7               # reuse capped at lp-1
    assert st["cow_copies"] == 1
    bt = kv.block_tables_np
    assert bt[slot_a, 0] == bt[slot_b, 0]            # still shared
    assert bt[slot_a, 1] != bt[slot_b, 1]            # B owns its copy
    got_b = [first_b]
    for _ in range(3):
        toks = kv.advance()
        got_a.append(int(toks[slot_a]))
        got_b.append(int(toks[slot_b]))
    orc = _oracle(model, params, p, 5)
    np.testing.assert_array_equal(orc, got_a)        # A uncorrupted
    np.testing.assert_array_equal(orc[:4], got_b)    # B's copy correct


def test_prefix_pool_pins_survive_evict_and_reset_releases(model_params):
    """Pool = pin: evicting the admitting slot releases ITS references
    but the pooled blocks stay resident (that is the cache); a warm
    re-admission still zero-copies; reset_prefix_cache drains the pins
    back to the free list."""
    model, params = model_params
    p = _shared_prefix_prompts(1, seed=13)[0]        # 12 tokens, 3 blocks
    kv = SlotKVCache(model, params, slots=1, kv_layout="paged",
                     prefix_cache_blocks=8, prefix_block=4)
    slot, _ = kv.insert(p)
    assert kv.blocks_in_use == 3
    kv.evict(slot)
    assert kv.blocks_in_use == 3                     # the pool's pins
    hits_before = kv.paged_stats()["zero_copy_hits"]
    slot, first = kv.insert(p)
    assert kv.paged_stats()["zero_copy_hits"] == hits_before + 1
    np.testing.assert_array_equal(_oracle(model, params, p, 1), [first])
    kv.evict(slot)
    kv.reset_prefix_cache()
    assert kv.blocks_in_use == 0
    assert kv.paged_stats()["zero_copy_hits"] == 0


# --------------------------------------------- capacity + exhaustion gates


def test_block_pool_exhausted_and_can_admit(model_params):
    """A pool sized below slots × max_blocks: can_admit accounts live
    slots' committed worst-case budgets (not just allocated blocks),
    and actually running dry raises BlockPoolExhausted instead of
    corrupting a shared block."""
    model, params = model_params
    kv = SlotKVCache(model, params, slots=2, kv_layout="paged",
                     paged_block=4, paged_blocks=8)
    assert kv.can_admit(16, 16)                      # 8 blocks, 8 free
    slot, _ = kv.insert(np.arange(16, dtype=np.int32) % 64)
    kv.note_admission(slot, 32)                      # worst case: 8 blocks
    # 4 free, but the live slot may still claim 4 more → nothing fits
    assert not kv.can_admit(4, 4)
    kv.evict(slot)
    assert kv.can_admit(16, 16)
    # two 4-block prompts fill the pool; the next decode write must fail
    kv.insert(np.arange(16, dtype=np.int32) % 64)
    kv.insert(np.arange(16, dtype=np.int32)[::-1].copy() % 64)
    assert kv.blocks_in_use == 8
    with pytest.raises(BlockPoolExhausted, match="exhausted"):
        kv.advance()


def test_scheduler_defers_admission_on_block_pressure(model_params):
    """The scheduler's block-exhaustion gate: a pool that fits one
    request at a time serializes admissions (serve_kv_block_deferrals
    counts the pushbacks) yet completes every request oracle-exact —
    and the summary carries the round-16 paged vocabulary."""
    model, params = model_params
    prompts = [np.asarray(np.arange(16) * (i + 1) % 64, np.int32)
               for i in range(3)]
    kv = SlotKVCache(model, params, slots=2, kv_layout="paged",
                     paged_block=4, paged_blocks=8)
    res = ContinuousBatcher(kv, clock=VirtualClock()).run(
        [Request(rid=i, prompt=p, max_new_tokens=4, arrival_s=0.0)
         for i, p in enumerate(prompts)])
    assert res["completed"] == 3
    assert res["serve_kv_block_deferrals"] > 0
    assert res["serve_kv_layout"] == "paged"
    assert res["serve_kv_blocks_in_use"] == 0        # all evicted at end
    assert res["serve_kv_block_utilization"] == 0.0
    assert res["paged"]["block_deferrals"] == res["serve_kv_block_deferrals"]
    for i, p in enumerate(prompts):
        np.testing.assert_array_equal(
            _oracle(model, params, p, 4),
            np.asarray(res["results"][i].tokens), str(i))
    # monolithic summaries carry the same keys as None/monolithic
    res_m = ContinuousBatcher(
        SlotKVCache(model, params, slots=2),
        clock=VirtualClock()).run(
        [Request(rid=0, prompt=prompts[0], max_new_tokens=2,
                 arrival_s=0.0)])
    assert res_m["serve_kv_layout"] == "monolithic"
    assert res_m["serve_kv_blocks_in_use"] is None
    assert res_m["serve_prefix_zero_copy_hit_rate"] is None
    assert res_m["serve_kv_block_deferrals"] == 0


def test_paged_kv_bytes_per_slot_honest(model_params):
    """Paged capacity reports bytes BACKING live sequences (allocated
    blocks + tables, amortized over live slots) — below the monolithic
    slots × max_len claim for short sequences, growing with allocation,
    shrinking back on evict."""
    model, params = model_params
    kv = SlotKVCache(model, params, slots=2, kv_layout="paged",
                     paged_block=4)
    mono = SlotKVCache(model, params, slots=2)
    assert kv.blocks_in_use == 0
    assert kv.kv_bytes_per_slot() == kv.block_tables_np.nbytes
    slot, _ = kv.insert(np.arange(6, dtype=np.int32))
    assert kv.blocks_in_use == 2
    short_bytes = kv.kv_bytes_per_slot()
    assert short_bytes < mono.kv_bytes_per_slot()
    for _ in range(3):
        kv.advance()                                 # crosses into block 2
    assert kv.blocks_in_use == 3
    assert kv.kv_bytes_per_slot() > short_bytes
    kv.evict(slot)
    assert kv.blocks_in_use == 0
    # freed blocks are immediately reusable
    slot, _ = kv.insert(np.arange(5, dtype=np.int32))
    assert kv.blocks_in_use == 2


# ------------------------------------------------------- composed workloads


@pytest.mark.slow    # round 20 fast-lane repair: the fast paged
# representative is test_harness_paged_e2e + the parity suites
def test_paged_composed_chunk_prefix_spec_int8(model_params):
    """THE parity acceptance: staggered arrivals + chunked prefill +
    prefix pool + speculative decode + int8, paged vs monolithic on the
    same seeded trace — identical greedy streams, and the paged run's
    summary shows zero-copy sharing actually happened."""
    model, params = model_params
    prompts = _shared_prefix_prompts(6, seed=14)
    arrivals = [0.0, 0.0, 1.0, 2.0, 3.0, 4.0]

    def run(**layout):
        kv = SlotKVCache(model, params, slots=2, kv_dtype="int8",
                         prefix_cache_blocks=16, prefix_block=4,
                         **layout)
        return ContinuousBatcher(
            kv, clock=VirtualClock(), prefill_chunk=3,
            draft_kv=SlotKVCache(model, params, slots=2),
            draft_k=2).run(
            [Request(rid=i, prompt=p, max_new_tokens=4,
                     arrival_s=arrivals[i])
             for i, p in enumerate(prompts)])

    paged = run(kv_layout="paged")
    mono = run()
    assert paged["completed"] == mono["completed"] == 6
    for i in range(6):
        np.testing.assert_array_equal(
            np.asarray(mono["results"][i].tokens),
            np.asarray(paged["results"][i].tokens), str(i))
    assert paged["paged"]["zero_copy_hits"] > 0
    assert paged["serve_prefix_zero_copy_hit_rate"] > 0
    assert paged["serve_prefix_cache_hit_rate"] > 0
    assert mono["serve_kv_blocks_in_use"] is None


# round 20 fast-lane repair: mesh composition variant —
# test_paged_on_mesh keeps the fast mesh representative
@pytest.mark.slow
def test_paged_composed_on_mesh(model_params, mesh8):
    """The composed workload's mesh-sharded variant: chunked + prefix +
    int8 over a slot-sharded paged table — streams match the monolithic
    mesh run on the same trace."""
    model, params = model_params
    prompts = _shared_prefix_prompts(4, seed=15)

    def run(**layout):
        kv = SlotKVCache(model, params, slots=8, mesh=mesh8,
                         kv_dtype="int8", prefix_cache_blocks=16,
                         prefix_block=4, **layout)
        return ContinuousBatcher(kv, clock=VirtualClock(),
                                 prefill_chunk=4).run(
            [Request(rid=i, prompt=p, max_new_tokens=3,
                     arrival_s=float(i)) for i, p in enumerate(prompts)])

    paged = run(kv_layout="paged")
    mono = run()
    for i in range(4):
        np.testing.assert_array_equal(
            np.asarray(mono["results"][i].tokens),
            np.asarray(paged["results"][i].tokens), str(i))
    assert paged["paged"]["zero_copy_hits"] > 0


def test_fleet_build_replica_kvs_forwards_layout(model_params):
    """The fleet constructs paged replicas through the same kv_kwargs
    pass-through as every other layout knob."""
    model, params = model_params
    kvs = build_replica_kvs(model, params, 2, 2, kv_layout="paged",
                            paged_block=4)
    assert all(isinstance(kv, PagedSlotKVCache) for kv in kvs)
    assert all(kv.num_blocks == kvs[0].num_blocks for kv in kvs)


# ----------------------------------------------------- observability / gates


def test_analyze_diff_round16_directions():
    """serve_kv_blocks_in_use gates lower-is-better (footprint), the
    zero-copy hit rate higher — more blocks or fewer pointer-hits at
    equal workload are regressions."""
    from distributed_tensorflow_tpu.observability.analyze import (
        diff_reports)

    base = {"serve_kv_blocks_in_use": 8,
            "serve_prefix_zero_copy_hit_rate": 0.8}
    worse = {"serve_kv_blocks_in_use": 16,
             "serve_prefix_zero_copy_hit_rate": 0.2}
    d = diff_reports(base, worse, threshold=0.1)
    assert {r["metric"] for r in d["regressions"]} == {
        "serve_kv_blocks_in_use", "serve_prefix_zero_copy_hit_rate"}
    better = diff_reports(worse, base, threshold=0.1)
    assert not better["regressions"]
    assert {r["metric"] for r in better["improvements"]} == {
        "serve_kv_blocks_in_use", "serve_prefix_zero_copy_hit_rate"}


def test_value_direction_round16_pins():
    """_value_direction pins (the `byte`/`sec_per` substring bug
    class): block/byte-valued footprint headlines gate lower, every
    rate — including the zero-copy hit rate and the per-chip serving
    rate whose name CONTAINS 'sec_per' — stays higher."""
    from distributed_tensorflow_tpu.observability.analyze import (
        _value_direction)

    assert _value_direction(
        {"metric": "serve_kv_block_bytes", "unit": "bytes/block"}) \
        == "lower"
    assert _value_direction(
        {"metric": "serve_kv_bytes_per_slot", "unit": "bytes/slot"}) \
        == "lower"
    assert _value_direction(
        {"metric": "serve_prefix_zero_copy_hit_rate",
         "unit": "fraction"}) == "higher"
    assert _value_direction(
        {"metric": "gpt_serve_requests_per_sec_per_chip",
         "unit": "requests/sec/chip"}) == "higher"


# ----------------------------------------------------------- harness + bench


def _lm_fn(batch_size, type="train", **kw):
    from distributed_tensorflow_tpu.data.loaders import load_lm_dataset

    return load_lm_dataset(seq_len=16, vocab_size=64, n_train=64,
                           n_test=32, split=type)


def test_harness_paged_e2e():
    """--serve-kv-layout paged through the harness, shared synthetic
    prefix + prefix pool on: the serve section carries the round-16
    keys, zero-copy sharing fires, and the run report mirrors it."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    summary = run(ExperimentConfig(
        engine="fsdp", model="gpt", dataset="lm_synth",
        dataset_fn=_lm_fn, n_devices=8, batch_size=4, log_every=0,
        model_args={"hidden": 32, "layers": 1, "heads": 2, "ffn": 64,
                    "max_len": 32},
        serve_requests=6, serve_slots=8, serve_max_new=4,
        serve_prompt_len=4, serve_shared_prefix=8,
        serve_prefix_cache=8, serve_prefix_block=4,
        serve_kv_layout="paged"))
    sec = summary["serve"]
    assert sec == summary["run_report"]["serve"]
    assert sec["completed"] == 6
    assert sec["serve_kv_layout"] == "paged"
    assert sec["serve_kv_blocks_in_use"] is not None
    assert sec["serve_kv_block_utilization"] is not None
    assert sec["paged"]["zero_copy_hits"] > 0
    assert sec["serve_prefix_zero_copy_hit_rate"] > 0
    assert sec["serve_kv_block_deferrals"] == 0      # default pool fits


def test_harness_round16_flag_validation():
    """Bad paged flags fail BEFORE training (the --serve contract)."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    base = dict(engine="fsdp", model="gpt", dataset="lm_synth",
                n_devices=8, serve_requests=2,
                model_args={"hidden": 32, "layers": 1, "heads": 2,
                            "ffn": 64, "max_len": 32})
    with pytest.raises(ValueError, match="serve-kv-layout"):
        run(ExperimentConfig(**base, serve_kv_layout="blocked"))
    with pytest.raises(ValueError, match="kv-layout paged"):
        run(ExperimentConfig(**base, serve_paged_block=4))
    with pytest.raises(ValueError, match="divide"):
        run(ExperimentConfig(**base, serve_kv_layout="paged",
                             serve_paged_block=5))
    with pytest.raises(ValueError, match="equal"):
        run(ExperimentConfig(**base, serve_kv_layout="paged",
                             serve_prefix_cache=8, serve_paged_block=8,
                             serve_prefix_block=4))


@pytest.mark.slow
def test_bench_serve_smoke_paged():
    """`bench.py --serve` with BENCH_SERVE_KV_LAYOUT=paged: one parsable
    JSON line carrying the paged-vs-monolithic same-trace ITL ratio,
    the paged pool keys, and the zero-copy ledger."""
    repo = Path(__file__).resolve().parents[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               BENCH_SERVE_HIDDEN="32", BENCH_SERVE_LAYERS="1",
               BENCH_SERVE_HEADS="2", BENCH_SERVE_FFN="64",
               BENCH_SERVE_VOCAB="64", BENCH_SERVE_PROMPT_LEN="6",
               BENCH_SERVE_MAX_NEW="6", BENCH_SERVE_SLOTS="2",
               BENCH_SERVE_REQUESTS="4", BENCH_SERVE_RATE="5",
               BENCH_SERVE_REPEATS="1",
               BENCH_SERVE_PREFILL_CHUNK="2",
               BENCH_SERVE_PREFIX_CACHE="8",
               BENCH_SERVE_PREFIX_BLOCK="2",
               BENCH_SERVE_SHARED_PREFIX="4",
               BENCH_SERVE_LONG_EVERY="2",
               BENCH_SERVE_KV_LAYOUT="paged")
    proc = subprocess.run(
        [sys.executable, str(repo / "bench.py"), "--serve", "--no-probe"],
        capture_output=True, text=True, timeout=540, env=env,
        cwd=str(repo))
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["metric"] == "gpt_serve_requests_per_sec_per_chip"
    if payload.get("skipped"):
        assert payload["value"] is None and payload["error"]
        return
    assert payload["serve_kv_layout"] == "paged"
    assert payload["config"]["kv_layout"] == "paged"
    assert payload["paged_vs_monolithic_itl_p95"] > 0
    assert payload["serve_kv_blocks_in_use"] is not None
    assert payload["serve_kv_block_utilization"] is not None
    assert payload["paged"]["zero_copy_hits"] >= 0
    assert payload["serve_prefix_zero_copy_hit_rate"] is not None
