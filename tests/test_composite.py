"""Composed dp×tp×sp engine tests (engines/composite.py): math equivalence
vs single-device dense training, convergence, and harness wiring.

Oracle pattern follows tests/test_seq_parallel.py: SGD (linear in the
gradient) so fp32 noise can't be amplified by Adam's normalization, and
dropout off so the rng-folding scheme can't differ between paths.
"""

import jax
import numpy as np
import optax
import pytest

from distributed_tensorflow_tpu.data.loaders import load_text_dataset
from distributed_tensorflow_tpu.engines import SyncEngine, Trainer
from distributed_tensorflow_tpu.engines.composite import CompositeEngine
from distributed_tensorflow_tpu.models import create_model
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def tiny_bert(attention_impl="ring", heads=2, partition_model=True):
    return create_model(
        "bert_tiny", num_classes=2, vocab_size=128, hidden=32, layers=1,
        heads=heads, ffn=64, max_len=64, dropout_rate=0.0,
        attention_impl=attention_impl, partition_model=partition_model)


@pytest.fixture(scope="module")
def text_data():
    tr = load_text_dataset(seq_len=32, vocab_size=128, n_train=512, n_test=256)
    te = load_text_dataset(seq_len=32, vocab_size=128, n_train=512, n_test=256,
                           split="test")
    return tr, te


def mesh3(dp=2, tp=2, sp=2):
    return meshlib.create_mesh(dp * tp * sp, shape=(dp, tp, sp),
                               axis_names=("data", "model", "seq"))


@pytest.mark.slow
def test_composite_matches_single_device(text_data):
    """(data=2, model=2, seq=2) ring+TP training must reproduce single-device
    dense-attention unsharded training step-for-step."""
    tr, _ = text_data
    x, y = tr.x[:32], tr.y[:32]

    eng1 = SyncEngine(tiny_bert("dense", partition_model=False),
                      optimizer=optax.sgd(0.1), mesh=meshlib.create_mesh(1))
    s1 = eng1.init_state(jax.random.key(0), x)
    for _ in range(2):
        s1, m1 = eng1.step(s1, *eng1.shard_batch(x, y))

    eng8 = CompositeEngine(tiny_bert("ring"), optimizer=optax.sgd(0.1),
                           mesh=mesh3())
    s8 = eng8.init_state(jax.random.key(0), x)
    for _ in range(2):
        s8, m8 = eng8.step(s8, *eng8.shard_batch(x, y))

    for a, b in zip(jax.tree.leaves(jax.device_get(s1.params)),
                    jax.tree.leaves(jax.device_get(s8.params))):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3)
    assert float(m1["loss"]) == pytest.approx(float(m8["loss"]), abs=1e-4)


@pytest.mark.slow
def test_composite_ulysses_matches_single_device(text_data):
    tr, _ = text_data
    x, y = tr.x[:16], tr.y[:16]

    eng1 = SyncEngine(tiny_bert("dense", heads=4, partition_model=False),
                      optimizer=optax.sgd(0.1), mesh=meshlib.create_mesh(1))
    s1 = eng1.init_state(jax.random.key(0), x)
    s1, m1 = eng1.step(s1, *eng1.shard_batch(x, y))

    eng8 = CompositeEngine(tiny_bert("ulysses", heads=4),
                           optimizer=optax.sgd(0.1), mesh=mesh3())
    s8 = eng8.init_state(jax.random.key(0), x)
    s8, m8 = eng8.step(s8, *eng8.shard_batch(x, y))

    for a, b in zip(jax.tree.leaves(jax.device_get(s1.params)),
                    jax.tree.leaves(jax.device_get(s8.params))):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3)
    assert float(m1["loss"]) == pytest.approx(float(m8["loss"]), abs=1e-4)


def test_composite_params_model_sharded(text_data):
    """TP annotations must actually shard params over 'model' on the 3-D mesh."""
    tr, _ = text_data
    eng = CompositeEngine(tiny_bert("ring"), mesh=mesh3())
    state = eng.init_state(jax.random.key(0), tr.x[:8])
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    sharded = [jax.tree_util.keystr(p) for p, l in flat
               if "model" in str(l.sharding.spec)]
    assert any("query" in n for n in sharded), sharded
    assert any("Dense_0" in n for n in sharded), sharded  # FFN expand
    assert any("Embed_0" in n for n in sharded), sharded  # vocab embedding


@pytest.mark.slow
def test_composite_converges_and_evaluates(text_data):
    tr, te = text_data
    eng = CompositeEngine(tiny_bert("ring"), mesh=mesh3(),
                          learning_rate=3e-3)
    t = Trainer(None, engine=eng)
    t.fit(tr, epochs=2, batch_size=32, log_every=0)
    ev = t.evaluate(te, batch_size=64)
    assert ev["count"] == len(te)
    assert ev["accuracy"] > 0.85, ev


@pytest.mark.slow
def test_composite_harness_run(tmp_path):
    """End-to-end: harness composes tensor_parallel × seq_parallel."""
    from distributed_tensorflow_tpu.utils.harness import ExperimentConfig, run

    def dataset_fn(batch_size, type="train", **kw):
        return load_text_dataset(seq_len=16, vocab_size=128, n_train=128,
                                 n_test=64, split=type)

    summary = run(ExperimentConfig(
        engine="sync", model="bert_tiny", dataset="glue_synth",
        n_devices=8, tensor_parallel=2, seq_parallel=2,
        batch_size=16, epochs=1, log_every=0,
        model_fn=lambda: tiny_bert("ring"),
        dataset_fn=dataset_fn))
    assert summary["engine"] == "composite[dp*tp*sp,ring]"
    assert summary["n_devices"] == 8
    assert summary["tensor_parallel"] == 2 and summary["seq_parallel"] == 2
    assert np.isfinite(summary["test_loss"])


def test_composite_validation(text_data):
    with pytest.raises(ValueError):  # no data axis
        CompositeEngine(tiny_bert("ring"),
                        mesh=meshlib.create_mesh(8, axis_names=("model",)))
    with pytest.raises(ValueError):  # dense attention with seq>1
        CompositeEngine(tiny_bert("dense"), mesh=mesh3())
    eng = CompositeEngine(tiny_bert("ring"), mesh=mesh3())
    tr, _ = text_data
    with pytest.raises(ValueError):  # seq length not divisible by seq axis
        eng.shard_batch(tr.x[:8, :31], tr.y[:8])


# ------------------------------------------------------------------ ep×sp


def _moe_gpt(attention_impl="ring", partition_experts=True, **kw):
    return create_model(
        "gpt", num_classes=64, hidden=32, layers=2, heads=2, ffn=64,
        max_len=64, dropout_rate=0.0, attention_impl=attention_impl,
        moe_experts=4, partition_experts=partition_experts, **kw)


def _ep_sp_mesh(dp=2, ep=2, sp=2):
    return meshlib.create_mesh(
        dp * ep * sp, shape=(dp, ep, sp),
        axis_names=(meshlib.DATA_AXIS, meshlib.EXPERT_AXIS, meshlib.SEQ_AXIS))


def test_ep_sp_matches_single_device():
    """dp×ep×sp (ring attention + GSPMD experts) must reproduce the
    single-device dense-MoE step.  aux_weight=0 and generous capacity
    (capacity_factor=num_experts → zero drops) make the objective linear
    in the token grouping, so parity is exact up to fp reassociation; the
    balance losses legitimately differ per grouping and get their own
    training test below."""
    rng = np.random.default_rng(0)
    x = rng.integers(0, 64, (8, 32)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)

    def build(attention_impl, mesh):
        m = _moe_gpt(attention_impl,
                     partition_experts=attention_impl == "ring",
                     moe_capacity_factor=4.0)
        return CompositeEngine(m, optimizer=optax.sgd(0.1), mesh=mesh,
                               aux_weight=0.0, router_z_weight=0.0)

    e1 = build("dense", meshlib.create_mesh(1))
    s1 = e1.init_state(jax.random.key(0), x)
    s1, m1 = e1.step(s1, *e1.shard_batch(x, y))

    e8 = build("ring", _ep_sp_mesh())
    s8 = e8.init_state(jax.random.key(0), x)
    s8, m8 = e8.step(s8, *e8.shard_batch(x, y))

    assert float(m8["overflow"]) == 0.0  # capacity covers everything
    assert float(m1["loss"]) == pytest.approx(float(m8["loss"]), abs=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=1e-5, rtol=1e-4),
        jax.device_get(s1.params), jax.device_get(s8.params))


def test_ep_sp_trains_with_balance_losses():
    """Full objective (aux + z losses on) under dp×ep×sp still learns, and
    the router diagnostics flow out as metrics."""
    rng = np.random.default_rng(1)
    x = rng.integers(0, 64, (8, 32)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    eng = CompositeEngine(_moe_gpt(), mesh=_ep_sp_mesh(), learning_rate=1e-2,
                          router_z_weight=1e-3)
    st = eng.init_state(jax.random.key(0), x)
    xs, ys = eng.shard_batch(x, y)
    st, first = eng.step(st, xs, ys)
    for _ in range(10):
        st, m = eng.step(st, xs, ys)
    assert float(m["loss"]) < float(first["loss"])
    assert {"loss", "accuracy", "total_loss", "overflow"} <= set(m)
    assert 0.0 <= float(m["overflow"]) <= 1.0


def test_ep_sp_validation():
    """Expert-axis misuse fails loudly: no MoE blocks, or annotations off,
    or indivisible expert count."""
    dense_gpt = create_model("gpt", num_classes=64, hidden=32, layers=1,
                             heads=2, ffn=64, max_len=64,
                             attention_impl="ring")
    with pytest.raises(ValueError, match="moe_experts"):
        CompositeEngine(dense_gpt, mesh=_ep_sp_mesh())
    with pytest.raises(ValueError, match="partition_experts"):
        CompositeEngine(_moe_gpt(partition_experts=False),
                        mesh=_ep_sp_mesh())
    with pytest.raises(ValueError, match="not divisible"):
        CompositeEngine(_moe_gpt(), mesh=meshlib.create_mesh(
            8, shape=(1, 8, 1),
            axis_names=(meshlib.DATA_AXIS, meshlib.EXPERT_AXIS,
                        meshlib.SEQ_AXIS)))


def test_ep_sp_harness_cli():
    """--expert-parallel × --seq-parallel through the harness: the combo
    resolves to the composite engine and reports perplexity."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    out = run(ExperimentConfig(
        model="gpt", dataset="lm_synth", engine="sync", n_devices=8,
        expert_parallel=2, seq_parallel=2, num_experts=4, batch_size=4,
        epochs=1, log_every=0,
        model_args={"hidden": 32, "layers": 1, "heads": 2, "ffn": 64}))
    assert out["expert_parallel"] == 2 and out["seq_parallel"] == 2
    assert out["engine"] == "expert_sp[dp*ep*sp,ring]"
    assert out["steps"] > 0 and out["test_perplexity"] > 0


# ------------------------------------------------------- grad accumulation


def test_composite_grad_accum_parity_classification(text_data):
    """grad_accum under dp×tp×sp (BERT, [CLS] head): scan carries are
    seq-INVARIANT here (the broadcast keeps per-chunk loss identical on
    every seq device) — parity vs K=1."""
    tr, _ = text_data
    x, y = tr.x[:8], tr.y[:8]
    mesh = meshlib.create_mesh(
        8, shape=(2, 2, 2),
        axis_names=(meshlib.DATA_AXIS, meshlib.MODEL_AXIS, meshlib.SEQ_AXIS))
    out = {}
    for K in (1, 2):
        eng = CompositeEngine(tiny_bert("ring"), optimizer=optax.sgd(0.1),
                              mesh=mesh, grad_accum=K)
        st = eng.init_state(jax.random.key(0), x)
        st, m = eng.step(st, *eng.shard_batch(x, y))
        out[K] = (float(m["loss"]), jax.device_get(st.params))
    assert out[1][0] == pytest.approx(out[2][0], abs=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5),
        out[1][1], out[2][1])


def test_composite_grad_accum_parity_lm():
    """grad_accum under dp×tp×sp with a GPT LM: per-chunk loss VARIES over
    'seq' (token blocks), exercising the varying-carry pcast path."""
    rng = np.random.default_rng(3)
    x = rng.integers(0, 64, (8, 32)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    mesh = meshlib.create_mesh(
        8, shape=(2, 2, 2),
        axis_names=(meshlib.DATA_AXIS, meshlib.MODEL_AXIS, meshlib.SEQ_AXIS))
    out = {}
    for K in (1, 2):
        model = create_model("gpt", num_classes=64, hidden=32, layers=1,
                             heads=2, ffn=64, max_len=64, dropout_rate=0.0,
                             attention_impl="ring", partition_model=True)
        eng = CompositeEngine(model, optimizer=optax.sgd(0.1), mesh=mesh,
                              grad_accum=K)
        st = eng.init_state(jax.random.key(0), x)
        st, m = eng.step(st, *eng.shard_batch(x, y))
        out[K] = (float(m["loss"]), jax.device_get(st.params))
    assert out[1][0] == pytest.approx(out[2][0], abs=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5),
        out[1][1], out[2][1])


def test_ep_sp_grad_accum_trains():
    """Accumulated ep×sp MoE training (aux losses on, K=2): learns and
    reports the router diagnostics.  (Bit-parity vs K=1 is not owed here —
    per-chunk routing statistics legitimately differ, same caveat as the
    expert engine's accumulation test.)"""
    rng = np.random.default_rng(4)
    x = rng.integers(0, 64, (8, 32)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    eng = CompositeEngine(_moe_gpt(), mesh=_ep_sp_mesh(), learning_rate=1e-2,
                          grad_accum=2)
    st = eng.init_state(jax.random.key(0), x)
    xs, ys = eng.shard_batch(x, y)
    st, first = eng.step(st, xs, ys)
    for _ in range(10):
        st, m = eng.step(st, xs, ys)
    assert float(m["loss"]) < float(first["loss"])
    assert 0.0 <= float(m["overflow"]) <= 1.0


# ------------------------------------------------------ BERT MoE (ep×sp)


def test_bert_moe_ep_sp_matches_single_device():
    """Classification ep×sp: BERT with MoE-FFN layers under
    ('data','expert','seq') must reproduce the single-device dense-MoE
    step (aux off, drop-free capacity — same construction as the GPT
    parity test; additionally exercises the seq-INVARIANT loss path with
    seq-VARYING router stats)."""
    tr = load_text_dataset(seq_len=32, vocab_size=128, n_train=64, n_test=32)
    x, y = tr.x[:8], tr.y[:8]

    def build(attention_impl, mesh):
        m = create_model(
            "bert_tiny", num_classes=2, vocab_size=128, hidden=32, layers=2,
            heads=2, ffn=64, max_len=64, dropout_rate=0.0,
            attention_impl=attention_impl, moe_experts=4,
            moe_capacity_factor=4.0,
            partition_experts=attention_impl == "ring")
        return CompositeEngine(m, optimizer=optax.sgd(0.1), mesh=mesh,
                               aux_weight=0.0, router_z_weight=0.0)

    e1 = build("dense", meshlib.create_mesh(1))
    s1 = e1.init_state(jax.random.key(0), x)
    s1, m1 = e1.step(s1, *e1.shard_batch(x, y))

    e8 = build("ring", _ep_sp_mesh())
    s8 = e8.init_state(jax.random.key(0), x)
    s8, m8 = e8.step(s8, *e8.shard_batch(x, y))

    assert float(m8["overflow"]) == 0.0
    assert float(m1["loss"]) == pytest.approx(float(m8["loss"]), abs=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            jax.device_get(a), jax.device_get(b), atol=1e-5, rtol=1e-4),
        jax.device_get(s1.params), jax.device_get(s8.params))


def test_bert_moe_harness_cli():
    """--model bert_tiny with -ep × -sp through the harness."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    out = run(ExperimentConfig(
        model="bert_tiny", dataset="glue_synth", engine="sync", n_devices=8,
        expert_parallel=2, seq_parallel=2, num_experts=4, batch_size=4,
        epochs=1, log_every=0,
        model_args={"hidden": 32, "layers": 1, "heads": 2, "ffn": 64}))
    assert out["expert_parallel"] == 2 and out["seq_parallel"] == 2
    assert out["steps"] > 0 and np.isfinite(out["test_loss"])


def test_ep_tp_sp_harness_cli():
    """4-D dp×ep×tp×sp through the harness — and the summary label comes
    from the setup that chose the engine (the re-derived label ladder
    mislabeled combos twice before _Experiment.name)."""
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    out = run(ExperimentConfig(
        model="gpt", dataset="lm_synth", engine="sync", n_devices=8,
        expert_parallel=2, tensor_parallel=2, seq_parallel=2,
        num_experts=4, batch_size=4, epochs=1, log_every=0,
        model_args={"hidden": 32, "layers": 1, "heads": 2, "ffn": 64}))
    assert out["engine"] == "expert_tp_sp[dp*ep*tp*sp,ring]"
    assert out["expert_parallel"] == 2 and out["tensor_parallel"] == 2
    assert out["seq_parallel"] == 2
    assert out["steps"] > 0 and out["test_perplexity"] > 0
