"""SLO-aware serving observability (ISSUE 13): log-bucketed histogram
exactness/merge semantics, SLOMonitor goodput accounting, per-request
phase attribution, bounded-admission overload mode (shed-with-429,
conservation), lease drain of a serving window, the `analyze serve`
waterfall, and the new `analyze diff` gates.  Everything here runs on
this container — the histogram/SLO layer is stdlib host code and the
batcher tests ride the same GSPMD jit paths as tests/test_serving.py.
"""

import json
import math
import os
import signal
import subprocess
import sys
import threading
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.models.gpt import GPTLM
from distributed_tensorflow_tpu.observability import Tracer
from distributed_tensorflow_tpu.observability.metrics import (
    LogHistogram, MetricsRegistry, exact_percentile)
from distributed_tensorflow_tpu.observability.slo import SLOMonitor
from distributed_tensorflow_tpu.serving import (
    ContinuousBatcher, Request, RequestQueue, SlotKVCache, VirtualClock)


def tiny_gpt(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("hidden", 32)
    kw.setdefault("layers", 2)
    kw.setdefault("heads", 2)
    kw.setdefault("ffn", 64)
    kw.setdefault("max_len", 32)
    kw.setdefault("dropout_rate", 0.0)
    return GPTLM(**kw)


@pytest.fixture(scope="module")
def model_params():
    model = tiny_gpt()
    x = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 8)),
                    jnp.int32)
    params = model.init(jax.random.key(0), x, train=False)["params"]
    return model, params


def _requests(n, seed=0, rate=None, max_new=4, lo=3, hi=9):
    rng = np.random.default_rng(seed)
    arrivals = (rng.exponential(1.0 / rate, n).cumsum()
                if rate else np.zeros(n))
    return [Request(rid=i,
                    prompt=rng.integers(0, 64, int(rng.integers(lo, hi)))
                    .astype(np.int32),
                    max_new_tokens=max_new,
                    arrival_s=float(arrivals[i]))
            for i in range(n)]


# ------------------------------------------------------- histogram exactness

@pytest.mark.parametrize("dist", ["uniform", "lognormal", "point_mass"])
def test_histogram_quantiles_within_one_bucket_width(dist):
    """THE exactness contract: every histogram quantile is within one
    bucket's relative width (growth − 1) of the exact stored-sample
    percentile, across distribution shapes — uniform (flat), lognormal
    (the latency shape), point-mass (ties)."""
    rng = np.random.default_rng(0)
    n = 5000
    if dist == "uniform":
        vals = rng.uniform(1e-4, 1.0, n)
    elif dist == "lognormal":
        vals = rng.lognormal(mean=-3.0, sigma=1.0, size=n)
    else:
        vals = np.full(n, 0.0421)
    h = LogHistogram()
    for v in vals:
        h.record(float(v))
    g = h.growth
    for q in (0.5, 0.9, 0.95, 0.99):
        exact = exact_percentile(vals.tolist(), q)
        approx = h.quantile(q)
        assert approx is not None
        # one bucket width each way (tiny epsilon for the interpolated
        # reference straddling a bucket edge)
        assert exact / g * 0.999 <= approx <= exact * g * 1.001, (
            dist, q, exact, approx)


def test_histogram_point_mass_is_exact():
    h = LogHistogram()
    for _ in range(100):
        h.record(0.25)
    # quantiles clamp into the tracked exact [min, max] — a point mass
    # reports its exact value, not a bucket edge
    assert h.quantile(0.5) == 0.25
    assert h.quantile(0.99) == 0.25
    assert h.vmin == h.vmax == 0.25


def test_histogram_underflow_overflow_and_extremes():
    h = LogHistogram(min_value=1e-3, max_value=10.0)
    for v in (1e-6, 5e-4, 0.5, 123.0):
        h.record(v)
    assert h.underflow == 2 and h.overflow == 1
    assert h.count == 4
    assert h.quantile(0.0) == pytest.approx(1e-6)   # underflow → exact min
    assert h.quantile(1.0) == pytest.approx(123.0)  # overflow → exact max


def test_histogram_merge_equals_record_all():
    rng = np.random.default_rng(1)
    a_vals = rng.lognormal(-2.0, 0.7, 400)
    b_vals = rng.uniform(1e-5, 2.0, 300)
    a, b, ref = LogHistogram(), LogHistogram(), LogHistogram()
    for v in a_vals:
        a.record(float(v))
        ref.record(float(v))
    for v in b_vals:
        b.record(float(v))
        ref.record(float(v))
    a.merge(b)
    # merged quantiles are EXACTLY record-all's (same fixed ladder)
    assert a.counts == ref.counts
    assert a.count == ref.count and a.underflow == ref.underflow
    assert a.sum == pytest.approx(ref.sum)
    for q in (0.5, 0.95, 0.99):
        assert a.quantile(q) == ref.quantile(q)


def test_histogram_merge_rejects_different_ladder():
    with pytest.raises(ValueError, match="ladder"):
        LogHistogram(growth=1.05).merge(LogHistogram(growth=1.1))


def test_histogram_serialization_roundtrip():
    h = LogHistogram()
    for v in (0.001, 0.01, 0.1, 1.0, 0.1):
        h.record(v)
    h2 = LogHistogram.from_dict(json.loads(json.dumps(h.to_dict())))
    assert h2.counts == h.counts
    assert h2.summary() == h.summary()


def test_registry_record_snapshot_merge():
    r1, r2 = MetricsRegistry(), MetricsRegistry()
    for v in (0.01, 0.02, 0.03):
        r1.record("ttft", v)
    r2.record("ttft", 0.04)
    r2.record("itl", 0.005)
    r1.merge(r2)
    snap = r1.snapshot()
    assert snap["ttft"]["count"] == 4
    assert snap["itl"]["count"] == 1
    assert r1.names() == ["itl", "ttft"]
    # merge left r2 untouched
    assert r2.snapshot()["ttft"]["count"] == 1


# ----------------------------------------------------------------- SLOMonitor

def test_slo_monitor_observe_and_misses():
    m = SLOMonitor(ttft_s=0.1, itl_s=0.01, quantile=0.99)
    assert m.observe(0.05, [0.005, 0.008]) is True
    assert m.observe(0.2, [0.005]) is False            # TTFT miss
    assert m.observe(0.05, [0.005, 0.5]) is False      # ITL p99 miss
    assert m.observe(0.05, []) is True                 # no gaps → ITL ok
    s = m.summary(elapsed_s=2.0)
    assert s["requests"] == 4 and s["good_requests"] == 2
    assert s["ttft_misses"] == 1 and s["itl_misses"] == 1
    assert s["slo_attainment"] == pytest.approx(0.5)
    assert s["goodput_requests_per_sec"] == pytest.approx(1.0)


def test_slo_monitor_zero_requests_window():
    m = SLOMonitor(ttft_s=1.0, itl_s=1.0)
    s = m.summary(elapsed_s=1.0)
    assert s["requests"] == 0
    assert s["slo_attainment"] is None     # no claim, not a perfect score
    assert s["goodput_requests_per_sec"] == 0.0
    assert m.summary(elapsed_s=None)["goodput_requests_per_sec"] is None


def test_slo_monitor_all_shed_window():
    m = SLOMonitor(ttft_s=1.0, itl_s=1.0)
    m.shed(5)
    s = m.summary(elapsed_s=2.0)
    assert s["shed_requests"] == 5
    assert s["good_requests"] == 0
    assert s["goodput_requests_per_sec"] == 0.0   # shed is never goodput
    assert s["slo_attainment"] is None


def test_slo_monitor_validates():
    with pytest.raises(ValueError, match="positive"):
        SLOMonitor(ttft_s=0, itl_s=1.0)
    with pytest.raises(ValueError, match="quantile"):
        SLOMonitor(ttft_s=1.0, itl_s=1.0, quantile=1.5)


# ------------------------------------------------------------- request queue

def test_request_queue_depth_and_high_watermark():
    q = RequestQueue(_requests(5, rate=1.0))
    assert q.depth() == 5                  # all queued
    d1 = q.depth(now=q.next_arrival())     # only the first has arrived
    assert d1 >= 1
    assert q.depth(now=1e9) == 5
    assert q.depth_high_watermark == 5


def test_request_queue_shed_ready_keeps_fifo_prefix():
    reqs = _requests(6)                    # all arrive at t=0
    q = RequestQueue(reqs)
    shed = q.shed_ready(now=0.0, keep=2)
    assert [r.rid for r in shed] == [2, 3, 4, 5]   # newest shed
    assert len(q) == 2
    assert q.pop_ready(0.0).rid == 0               # FIFO survivors
    assert q.shed_ready(now=0.0, keep=5) == []     # under the cap: no-op


# ------------------------------------------ batcher: attribution + overload

def test_batcher_phase_attribution_and_histograms(model_params):
    """Per-request phase attribution: queue_wait + prefill == TTFT per
    request, the summary carries p99 + queue-wait percentiles from the
    stored-sample path, and the histogram copies agree within one bucket
    width (the online-percentile contract end-to-end)."""
    model, params = model_params
    kv = SlotKVCache(model, params, 2)
    reqs = _requests(6, rate=0.5, max_new=3)
    clock = VirtualClock(tick=1.0, prefill_token_tick=0.1)
    b = ContinuousBatcher(kv, clock=clock,
                          slo=SLOMonitor(ttft_s=1e9, itl_s=1e9))
    s = b.run(reqs)
    assert s["completed"] == 6
    for r in s["results"]:
        assert r.queue_wait_s >= 0
        assert r.prefill_s >= 0
        assert r.queue_wait_s + r.prefill_s == pytest.approx(r.ttft_s)
        assert r.slo_met is True
    # stored-sample percentile keys (p50 ≤ p95 ≤ p99, same stdlib path)
    assert (s["serve_ttft_p50_s"] <= s["serve_ttft_p95_s"]
            <= s["serve_ttft_p99_s"])
    assert (s["serve_queue_wait_p50_s"] <= s["serve_queue_wait_p95_s"]
            <= s["serve_queue_wait_p99_s"])
    assert s["serve_itl_p99_s"] >= s["serve_itl_p95_s"] >= 0
    # histogram copies within one bucket's relative width of exact
    hist = s["histograms"]
    for name, exact in (("ttft", s["serve_ttft_p99_s"]),
                        ("queue_wait", s["serve_queue_wait_p99_s"]),
                        ("itl", s["serve_itl_p99_s"])):
        hq = hist[name]["p99"]
        g = 1.0 + hist[name]["relative_width"]
        if exact and exact > 0:
            assert exact / g * 0.999 <= hq <= exact * g * 1.001, (
                name, exact, hq)
    assert hist["ttft"]["count"] == 6
    # goodput under an unmissable SLO == throughput
    assert s["serve_goodput_under_slo"] == pytest.approx(
        s["serve_requests_per_sec"])
    assert s["slo"]["slo_attainment"] == 1.0
    # queue-pressure keys exist
    assert s["queue_depth_p95"] is not None
    assert s["queue_depth_high_watermark"] >= 1
    # device-phase split observed some host time in both programs
    assert s["device_phase_s"]["prefill_s"] > 0
    assert s["device_phase_s"]["decode_s"] > 0


def test_batcher_external_registry_merges_across_windows(model_params):
    model, params = model_params
    kv = SlotKVCache(model, params, 2)
    reg = MetricsRegistry()
    b = ContinuousBatcher(kv, metrics=reg)
    b.run(_requests(3, max_new=2))
    b.run(_requests(3, seed=1, max_new=2))
    # the external registry accumulated BOTH windows (merge semantics)
    assert reg.snapshot()["ttft"]["count"] == 6


def test_batcher_shed_accounting_conservation(model_params):
    """Exact conservation under the queue cap: admitted + shed +
    unserved == offered, every shed gets an overload event + counter,
    and the SLO monitor counts shed as offered-not-goodput."""
    model, params = model_params
    kv = SlotKVCache(model, params, 2)
    slo = SLOMonitor(ttft_s=1e9, itl_s=1e9)
    b = ContinuousBatcher(kv, clock=VirtualClock(), queue_cap=2, slo=slo)
    s = b.run(_requests(10, max_new=2))    # all arrive at t=0
    assert s["shed_requests"] > 0
    assert (s["admitted"] + s["shed_requests"] + s["unserved_requests"]
            == s["offered"] == 10)
    assert s["serve_shed_rate"] == pytest.approx(s["shed_requests"] / 10)
    assert s["slo"]["shed_requests"] == s["shed_requests"]
    assert len(s["shed_rids"]) == s["shed_requests"]
    # shed rids and completed rids partition the offered set
    done = {r.rid for r in s["results"]}
    assert done.isdisjoint(s["shed_rids"])
    assert len(done) + len(s["shed_rids"]) == 10


# round 20 fast-lane repair: overload acceptance race (~9s) rides the
# slow lane; the bounded-admission conservation pins stay fast
@pytest.mark.slow
def test_overload_bounded_queue_wait_acceptance(model_params):
    """THE overload acceptance (ISSUE 13): on the same seeded trace,
    deterministic in decode-iteration time (VirtualClock), the uncapped
    batcher's queue wait GROWS with offered load, while the queue-capped
    batcher at ~2× the knee keeps queue-wait p99 bounded (≤ 3× the
    at-knee value) and sheds the excess with exact accounting."""
    model, params = model_params

    def run(rate, cap):
        kv = SlotKVCache(model, params, 2)
        b = ContinuousBatcher(kv, clock=VirtualClock(tick=1.0),
                              queue_cap=cap,
                              slo=SLOMonitor(ttft_s=1e9, itl_s=1e9))
        return b.run(_requests(24, seed=3, rate=rate, max_new=4))

    # service capacity ≈ slots/(max_new iterations) = 0.5 req/tick: the
    # knee.  2× and 4× the knee are increasingly overloaded.
    knee, over, collapse = 0.5, 1.0, 2.0
    s_knee = run(knee, cap=0)
    s_over = run(over, cap=0)
    s_coll = run(collapse, cap=0)
    # uncapped: queue wait grows monotonically with offered load
    assert (s_knee["serve_queue_wait_p99_s"]
            < s_over["serve_queue_wait_p99_s"]
            < s_coll["serve_queue_wait_p99_s"])
    assert s_over["shed_requests"] == 0
    # capped at 2× the knee: bounded queue wait + exact shed accounting
    s_cap = run(over, cap=2)
    assert s_cap["shed_requests"] > 0
    assert (s_cap["admitted"] + s_cap["shed_requests"]
            + s_cap["unserved_requests"] == s_cap["offered"] == 24)
    assert (s_cap["serve_queue_wait_p99_s"]
            <= 3.0 * s_knee["serve_queue_wait_p99_s"])
    assert (s_cap["serve_queue_wait_p99_s"]
            < s_over["serve_queue_wait_p99_s"])
    # and the cap bounds the observed backlog itself
    assert s_cap["queue_depth_p95"] <= 2.0


def test_observability_off_parity_with_pr10(model_params):
    """Parity discipline: with SLO/overload observability OFF (and even
    ON, uncapped — it is all host-side), the compiled program set and
    the greedy tokens are byte-identical to the PR 10 batcher."""
    model, params = model_params
    reqs = lambda: _requests(5, seed=7, rate=1.0, max_new=3)  # noqa: E731

    kv_plain = SlotKVCache(model, params, 2)
    plain = ContinuousBatcher(kv_plain, clock=VirtualClock()).run(reqs())

    kv_obs = SlotKVCache(model, params, 2)
    obs = ContinuousBatcher(
        kv_obs, clock=VirtualClock(), metrics=MetricsRegistry(),
        slo=SLOMonitor(ttft_s=0.001, itl_s=0.001),
        queue_cap=0).run(reqs())

    assert [r.tokens for r in plain["results"]] == \
        [r.tokens for r in obs["results"]]
    # the compiled-programs pin, extended: observability adds NO programs
    assert kv_obs.compiled_programs() == kv_plain.compiled_programs()
    assert kv_plain.compiled_programs()["prefill_chunk_buckets"] == 0
    assert kv_plain.compiled_programs()["prefix_block_ops"] == 0


# ------------------------------------------------------------- lease drain

def test_batcher_should_stop_drains_gracefully(model_params, tmp_path):
    """The serving lease drain: should_stop firing mid-run stops
    admission, finishes in-flight requests, accounts the unserved tail,
    and closes every opened span — the partial summary is consistent."""
    model, params = model_params
    kv = SlotKVCache(model, params, 2)
    trace = tmp_path / "drain.jsonl"
    tracer = Tracer(path=trace)
    fired = {"n": 0}

    def stop(_iters):
        fired["n"] += 1
        return "signal:SIGTERM" if fired["n"] > 4 else None

    b = ContinuousBatcher(kv, clock=VirtualClock(), tracer=tracer,
                          should_stop=stop,
                          slo=SLOMonitor(ttft_s=1e9, itl_s=1e9))
    s = b.run(_requests(12, rate=0.2, max_new=4))   # slow arrivals
    tracer.close()
    assert s["preempted"] == "signal:SIGTERM"
    assert 0 < s["completed"] < 12
    assert s["unserved_requests"] == 12 - s["completed"]
    assert (s["admitted"] + s["shed_requests"] + s["unserved_requests"]
            == s["offered"])
    # every opened request span closed (count == completed) + the
    # structured drain event is in the trace
    recs = [json.loads(line) for line in trace.read_text().splitlines()]
    req_spans = [r for r in recs if r.get("event") == "span"
                 and r.get("name") == "request"]
    assert len(req_spans) == s["completed"]
    drains = [r for r in recs if r.get("event") == "event"
              and r.get("name") == "serve_preempted"]
    assert drains and drains[0]["reason"] == "signal:SIGTERM"
    # the table is clean: a later run on the same kv serves normally
    s2 = ContinuousBatcher(kv, clock=VirtualClock()).run(
        _requests(3, max_new=2))
    assert s2["completed"] == 3


# round 20 fast-lane repair: subprocess sigterm e2e rides the slow lane
@pytest.mark.slow
def test_harness_sigterm_with_serve_flushes_serve_section(tmp_path):
    """Satellite (PR 9 integration): the in-process SIGTERM harness from
    tests/test_elastic.py, now with --serve — a preempted run must still
    flush its serve section (drained, with exact accounting) into the
    summary AND run report before exit."""
    from distributed_tensorflow_tpu.data.loaders import load_lm_dataset
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    def lm_fn(batch_size, type="train", **kw):
        return load_lm_dataset(seq_len=16, vocab_size=64, n_train=64,
                               n_test=32, split=type)

    cfg = ExperimentConfig(
        engine="fsdp", model="gpt", dataset="lm_synth", dataset_fn=lm_fn,
        n_devices=8, batch_size=4, epochs=800, log_every=0,
        steps_per_call=4,
        model_args={"hidden": 32, "layers": 1, "heads": 2, "ffn": 64,
                    "max_len": 32},
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=4,
        serve_requests=5, serve_slots=2, serve_max_new=4,
        serve_prompt_len=4)
    timer = threading.Timer(2.0, os.kill,
                            args=(os.getpid(), signal.SIGTERM))
    timer.daemon = True
    timer.start()
    try:
        s = run(cfg)
    finally:
        timer.cancel()
    assert s["preempted"] == "signal:SIGTERM"
    sec = s["serve"]
    assert sec is not None
    assert sec == s["run_report"]["serve"]
    # the drained window's accounting is exact whether it served
    # nothing (signal before serve) or part of the queue (signal mid-
    # serve): admitted + shed + unserved == offered == 5
    assert (sec["admitted"] + sec["shed_requests"]
            + sec["unserved_requests"] == sec["offered"] == 5)
    assert sec["preempted"] == "signal:SIGTERM" or sec["completed"] == 5
    assert sec["serve_goodput_under_slo"] is not None \
        or sec["completed"] == 0


def test_should_stop_interrupts_idle_wait(model_params):
    """A preemption notice landing in a long idle gap drains within one
    poll slice — not after the next arrival (regression: the hook was
    only consulted at the loop top, so a wall-clock batcher idling 30s
    to the next arrival ignored SIGTERM for the whole gap)."""
    import time as timelib

    model, params = model_params
    kv = SlotKVCache(model, params, 2)
    flag = {"stop": False}

    def on_token(rid, tok):
        flag["stop"] = True    # preempt once the first request streams

    b = ContinuousBatcher(
        kv, should_stop=lambda _i: ("signal:SIGTERM" if flag["stop"]
                                    else None))
    reqs = [Request(rid=0, prompt=np.arange(4, dtype=np.int32),
                    max_new_tokens=2, arrival_s=0.0),
            Request(rid=1, prompt=np.arange(4, dtype=np.int32),
                    max_new_tokens=2, arrival_s=30.0)]   # far future
    t0 = timelib.monotonic()
    s = b.run(reqs, on_token=on_token)
    elapsed = timelib.monotonic() - t0
    assert s["preempted"] == "signal:SIGTERM"
    assert s["completed"] == 1 and s["unserved_requests"] == 1
    assert elapsed < 5.0     # drained within poll slices, not after 30s


# ------------------------------------------------------- analyze: waterfall

def test_analyze_serve_waterfall_from_trace(model_params, tmp_path):
    from distributed_tensorflow_tpu.observability.analyze import (
        read_jsonl, render_waterfall_text, serve_waterfall,
        trace_summary)

    model, params = model_params
    kv = SlotKVCache(model, params, 2)
    trace = tmp_path / "serve.jsonl"
    tracer = Tracer(path=trace)
    b = ContinuousBatcher(kv, tracer=tracer, clock=VirtualClock(),
                          queue_cap=2,
                          slo=SLOMonitor(ttft_s=1e9, itl_s=1e9))
    s = b.run(_requests(8, max_new=3))     # burst at t=0 → some shed
    tracer.close()
    recs = read_jsonl(trace)
    wf = serve_waterfall(recs)
    assert wf["requests_n"] == s["completed"]
    assert wf["shed_n"] == s["shed_requests"] > 0
    by_rid = {r.rid: r for r in s["results"]}
    for row in wf["requests"]:
        r = by_rid[row["rid"]]
        assert row["queue_wait_s"] == pytest.approx(r.queue_wait_s)
        assert row["prefill_s"] == pytest.approx(r.prefill_s)
        assert row["decode_s"] == pytest.approx(r.decode_s)
        assert row["ttft_s"] == pytest.approx(r.ttft_s)
        assert row["slo_met"] is True
        assert row["tokens"] == len(r.tokens)
    # overload events record the PRE-shed backlog that triggered them
    # (post-shed depth is always == cap — zero information)
    for shed_row in wf["shed"]:
        assert shed_row["queue_depth"] > 2
        assert shed_row["queue_cap"] == 2
    text = render_waterfall_text(wf)
    assert "shed (429)" in text and "legend" in text
    # `analyze spans` surfaces the overload engagement
    summ = trace_summary(recs)
    assert summ["stalls"]["overload_events"] == s["shed_requests"]
    assert summ["counters"]["shed_requests"] == s["shed_requests"]


def test_waterfall_multi_window_rid_reuse(model_params, tmp_path):
    """A bench-style trace holds several windows that all reuse rids
    0..n−1: every window's request span gets its OWN row, and each
    prefill_chunk attaches to the span whose interval contains it
    (regression: rid-keyed rows silently merged windows)."""
    from distributed_tensorflow_tpu.observability.analyze import (
        read_jsonl, serve_waterfall)

    model, params = model_params
    kv = SlotKVCache(model, params, 2)
    trace = tmp_path / "two_windows.jsonl"
    with Tracer(path=trace) as tracer:
        for _ in range(2):                 # two windows, same rids
            ContinuousBatcher(kv, tracer=tracer, clock=VirtualClock(),
                              prefill_chunk=2).run(
                _requests(3, max_new=2, lo=5, hi=6))
    recs = read_jsonl(trace)
    wf = serve_waterfall(recs)
    assert wf["requests_n"] == 6           # 3 rids × 2 windows
    n_chunk_spans = sum(1 for r in recs if r.get("event") == "span"
                        and r.get("name") == "prefill_chunk")
    attributed = sum(len(r["prefill_chunks"]) for r in wf["requests"])
    assert attributed == n_chunk_spans     # none lost, none duplicated
    assert all(len(r["prefill_chunks"]) >= 1 for r in wf["requests"])


def test_waterfall_text_shed_past_last_span_no_crash():
    """A partial trace can carry overload events later than every CLOSED
    request span (sheds are emitted immediately, spans only at exit):
    the text renderer clamps instead of crashing on a negative pad."""
    from distributed_tensorflow_tpu.observability.analyze import (
        render_waterfall_text)

    wf = {"requests": [{"rid": 0, "t": 100.0, "dur_s": 1.0,
                        "queue_wait_s": 0.1, "prefill_s": 0.2,
                        "decode_s": 0.7, "ttft_s": 0.3, "slo_met": None,
                        "prefill_chunks": []}],
          "shed": [{"rid": 1, "t": 5000.0, "queue_depth": 9,
                    "queue_cap": 2}],
          "requests_n": 1, "shed_n": 1, "slo_met_n": None}
    text = render_waterfall_text(wf, width=40)
    assert "shed (429) at depth 9" in text


def test_analyze_serve_cli_subcommand(model_params, tmp_path):
    from distributed_tensorflow_tpu.observability.analyze import main

    model, params = model_params
    kv = SlotKVCache(model, params, 2)
    trace = tmp_path / "serve.jsonl"
    with Tracer(path=trace) as tracer:
        ContinuousBatcher(kv, tracer=tracer, clock=VirtualClock()).run(
            _requests(3, max_new=2))
    assert main(["serve", str(trace)]) == 0
    assert main(["serve", str(trace), "--text"]) == 0


# ------------------------------------------------------------ analyze: diff

def test_diff_gates_slo_keys_directions():
    from distributed_tensorflow_tpu.observability.analyze import (
        diff_reports)

    base = {"serve_ttft_p99_s": 0.1, "serve_itl_p99_s": 0.01,
            "serve_queue_wait_p99_s": 0.05,
            "serve_goodput_under_slo": 10.0,
            "serve_max_goodput_under_slo": 20.0,
            "serve_knee_rate_per_s": 16.0,
            "serve_shed_rate": 0.1}
    worse = {"serve_ttft_p99_s": 0.2, "serve_itl_p99_s": 0.02,
             "serve_queue_wait_p99_s": 0.2,
             "serve_goodput_under_slo": 5.0,
             "serve_max_goodput_under_slo": 10.0,
             "serve_knee_rate_per_s": 8.0,
             "serve_shed_rate": 0.4}
    d = diff_reports(base, worse)
    assert {r["metric"] for r in d["regressions"]} == set(base)
    d2 = diff_reports(worse, base)
    assert not d2["regressions"]
    assert {r["metric"] for r in d2["improvements"]} == set(base)


def test_load_report_flattens_goodput_keys(tmp_path):
    from distributed_tensorflow_tpu.observability.analyze import (
        load_report)

    p = tmp_path / "summary.json"
    p.write_text(json.dumps({
        "serve": {"serve_goodput_under_slo": 4.2,
                  "serve_ttft_p99_s": 0.3,
                  "serve_queue_wait_p99_s": 0.1,
                  "serve_shed_rate": 0.0,
                  "shed_requests": 0}}))
    flat = load_report(p)
    assert flat["serve_goodput_under_slo"] == 4.2
    assert flat["serve_ttft_p99_s"] == 0.3
    assert flat["serve_queue_wait_p99_s"] == 0.1
    assert flat["serve_shed_rate"] == 0.0


# ------------------------------------------------------------- bench sweep

@pytest.mark.slow    # round 20 fast-lane repair: the sweep ladder is
# a multi-window subprocess; CI's overload smoke covers the surface
def test_bench_serve_sweep_smoke_emits_json(tmp_path):
    """bench --serve --sweep smoke: the arrival-rate ladder runs, the
    line carries serve_max_goodput_under_slo + the knee + the overload
    window's accounting, and the artifact self-diffs exit 0 with the new
    gates compared."""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               BENCH_SERVE_HIDDEN="32", BENCH_SERVE_LAYERS="1",
               BENCH_SERVE_HEADS="2", BENCH_SERVE_FFN="64",
               BENCH_SERVE_VOCAB="128", BENCH_SERVE_PROMPT_LEN="8",
               BENCH_SERVE_MAX_NEW="4", BENCH_SERVE_SLOTS="2",
               BENCH_SERVE_REQUESTS="6", BENCH_SERVE_RATE="20",
               BENCH_SERVE_SWEEP_POINTS="2",
               BENCH_SERVE_PREFILL_CHUNK="4",
               BENCH_SERVE_PREFIX_CACHE="16",
               BENCH_SERVE_PREFIX_BLOCK="4")
    root = Path(__file__).resolve().parents[1]
    r = subprocess.run(
        [sys.executable, str(root / "bench.py"), "--serve", "--sweep",
         "--no-probe"],
        capture_output=True, text=True, env=env, timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]
    line = json.loads(r.stdout.strip().splitlines()[-1])
    if line.get("skipped"):
        pytest.skip(f"bench skipped: {line['error'][:200]}")
    assert line["metric"] == "gpt_serve_max_goodput_under_slo"
    assert line["serve_max_goodput_under_slo"] > 0
    assert line["serve_knee_rate_per_s"] > 0
    assert len(line["sweep"]) >= 1
    ov = line["overload"]
    assert ov is not None
    assert (ov["admitted"] + ov["shed_requests"]
            + ov["unserved_requests"] == ov["offered"])
    assert line["serve_overload_queue_wait_p99_s"] is not None
    # self-diff exit 0 with the sweep gates among the compared metrics
    from distributed_tensorflow_tpu.observability.analyze import (
        diff_reports, load_report)

    art = tmp_path / "sweep.json"
    art.write_text(json.dumps(line))
    d = diff_reports(load_report(art), load_report(art))
    compared = {r["metric"] for r in d["unchanged"]}
    assert "serve_max_goodput_under_slo" in compared
    assert "serve_knee_rate_per_s" in compared


def test_exact_percentile_matches_scheduler_percentile():
    """The scheduler's stored-sample path and the histogram module share
    literally the same percentile function (no drift possible)."""
    from distributed_tensorflow_tpu.serving import scheduler

    assert scheduler._percentile is exact_percentile
    vals = [3.0, 1.0, 2.0]
    assert exact_percentile(vals, 0.5) == 2.0
    assert exact_percentile([], 0.5) is None
    assert exact_percentile([7.0], 0.99) == 7.0
    assert exact_percentile(vals, 1.0) == 3.0
    assert math.isclose(exact_percentile(vals, 0.25), 1.5)
