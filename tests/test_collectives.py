"""Unit tests for the L1 collectives layer on the fake 8-device CPU mesh."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.parallel import collectives as coll
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def smap(fn, mesh, in_specs, out_specs):
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )


def test_all_reduce_mean(mesh8):
    x = np.arange(8.0)
    f = smap(lambda v: coll.all_reduce_mean(v, "data"), mesh8, P("data"), P())
    assert f(x) == pytest.approx(3.5)


def test_all_reduce_sum_tree(mesh8):
    tree = {"a": np.ones((8, 2)), "b": np.arange(8.0)}
    f = smap(lambda t: coll.all_reduce_sum(t, "data"), mesh8,
             P("data"), P())
    out = f(tree)
    np.testing.assert_allclose(out["a"], np.full((1, 2), 8.0))
    assert out["b"] == pytest.approx(28.0)


def test_all_gather(mesh8):
    x = np.arange(8.0).reshape(8, 1)
    f = smap(lambda v: coll.all_gather(v, "data", tiled=True), mesh8,
             P("data"), P("data"))
    out = f(x)
    # each shard gathers the full vector; global result is 8 copies stacked
    assert out.shape == (64, 1)
    np.testing.assert_allclose(np.asarray(out)[:8, 0], np.arange(8.0))


def test_ring_shift(mesh8):
    x = np.arange(8.0)
    f = smap(lambda v: coll.ring_shift(v, "data", 1), mesh8, P("data"), P("data"))
    np.testing.assert_allclose(np.asarray(f(x)), np.roll(np.arange(8.0), 1))


def test_ring_shift_negative(mesh8):
    x = np.arange(8.0)
    f = smap(lambda v: coll.ring_shift(v, "data", -1), mesh8, P("data"), P("data"))
    np.testing.assert_allclose(np.asarray(f(x)), np.roll(np.arange(8.0), -1))


@pytest.mark.parametrize("degree", [0, 1, 2, 3])
def test_neighbor_mean(mesh8, degree):
    x = np.arange(8.0)
    f = smap(lambda v: coll.neighbor_mean(v, "data", degree), mesh8,
             P("data"), P("data"))
    out = np.asarray(f(x))
    expect = np.empty(8)
    for i in range(8):
        vals = [x[(i + d) % 8] for d in range(-degree, degree + 1)]
        expect[i] = np.mean(vals)
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_neighbor_mean_preserves_global_mean(mesh8):
    # gossip averaging must conserve the parameter mean (doubly-stochastic mix)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 3))
    f = smap(lambda v: coll.neighbor_mean(v, "data", 2), mesh8,
             P("data"), P("data"))
    out = np.asarray(f(x))
    np.testing.assert_allclose(out.mean(axis=0), x.mean(axis=0), rtol=1e-6)


def test_broadcast_from(mesh8):
    x = np.arange(8.0) + 1.0
    f = smap(lambda v: coll.broadcast_from(v, "data", src=3), mesh8,
             P("data"), P("data"))
    np.testing.assert_allclose(np.asarray(f(x)), np.full(8, 4.0))


def test_reduce_scatter(mesh8):
    x = np.tile(np.arange(8.0), (8, 1)).reshape(8, 8)  # every device holds 0..7
    f = smap(lambda v: coll.reduce_scatter_sum(v.reshape(8), "data"), mesh8,
             P("data", None), P("data"))
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.arange(8.0) * 8)


def test_all_to_all(mesh8):
    # 8 devices, each with (8, 2) block; a2a splits dim0, concats dim1
    x = np.arange(8 * 8 * 2, dtype=np.float32).reshape(64, 2)
    f = smap(lambda v: coll.all_to_all(v, "data", 0, 1), mesh8,
             P("data", None), P("data", None))
    out = f(x)
    assert out.shape == (8, 16)


def test_mesh_creation_errors():
    with pytest.raises(ValueError):
        meshlib.create_mesh(1024)
    m = meshlib.create_mesh(4, shape=(2, 2), axis_names=("data", "model"))
    assert m.shape == {"data": 2, "model": 2}


def test_neighbor_mean_small_mesh_full_average():
    # on a 2-device axis degree>=1 must fall back to full pmean, not a no-op
    m2 = meshlib.create_mesh(2)
    x = np.array([0.0, 4.0])
    f = jax.jit(jax.shard_map(lambda v: coll.neighbor_mean(v, "data", 1),
                              mesh=m2, in_specs=P("data"), out_specs=P("data")))
    np.testing.assert_allclose(np.asarray(f(x)), [2.0, 2.0])
