"""FSDP engine tests: math parity with sync DP, the 1/n memory claim, and
the CLI/harness wiring.

The reference has no FSDP (its optimizer simply lives whole on the server,
reference server.py:52-55); these tests pin the TPU-first contract instead:
identical training math to SyncEngine with ~1/n per-device state bytes.
"""

import jax
import numpy as np
import optax
import pytest

from distributed_tensorflow_tpu.data.loaders import (
    Dataset, synthetic_classification)
from distributed_tensorflow_tpu.engines import (
    FSDPEngine, SyncEngine, Trainer, create_engine)
from distributed_tensorflow_tpu.engines.fsdp import fsdp_spec
from distributed_tensorflow_tpu.models import create_model
from distributed_tensorflow_tpu.parallel import mesh as meshlib


def tiny_data(n=512, split="train"):
    x, y = synthetic_classification((8, 8), 4, n, seed=3, split=split)
    return Dataset(x=x, y=y, num_classes=4, name="tiny", synthetic=True)


def tiny_model(**kw):
    return create_model("mlp", num_classes=4, hidden=32, **kw)


@pytest.fixture(scope="module")
def data():
    return tiny_data(), tiny_data(128, "test")


def test_fsdp_spec_picks_largest_divisible_dim():
    assert fsdp_spec((64, 32), 8) == jax.sharding.PartitionSpec("data", None)
    assert fsdp_spec((8, 512), 8) == jax.sharding.PartitionSpec(None, "data")
    assert fsdp_spec((7, 9), 8) == jax.sharding.PartitionSpec()   # replicate
    assert fsdp_spec((), 8) == jax.sharding.PartitionSpec()       # scalar


def test_fsdp_matches_sync_math(data):
    """FSDP must be sync DP in different clothes: same global batch, same
    SGD updates (SGD is linear in the gradient, so a wrong grad scale or a
    dropped reduce-scatter fails loudly; Adam would mask scale bugs)."""
    train, _ = data
    x, y = train.x[:64], train.y[:64]

    results = {}
    for cls in (SyncEngine, FSDPEngine):
        mesh = meshlib.create_mesh(8)
        model = tiny_model(dropout_rate=0.0)
        eng = cls(model, optimizer=optax.sgd(0.5), mesh=mesh)
        state = eng.init_state(jax.random.key(0), x)
        for _ in range(3):
            xs, ys = eng.shard_batch(x, y)
            state, m = eng.step(state, xs, ys)
        results[cls.__name__] = (jax.device_get(eng.eval_params(state)),
                                 float(m["loss"]))

    for a, b in zip(jax.tree.leaves(results["SyncEngine"][0]),
                    jax.tree.leaves(results["FSDPEngine"][0])):
        np.testing.assert_allclose(a, b, atol=1e-5)
    assert results["SyncEngine"][1] == pytest.approx(
        results["FSDPEngine"][1], abs=1e-5)


def test_fsdp_state_is_sharded_one_nth(mesh8, data):
    """The FSDP memory claim: per-device param+opt bytes ≈ 1/n of the
    replicated total (adam: mu+nu mirror params, all sharded; the residue
    is odd-sized biases and scalar counts)."""
    train, _ = data
    eng = FSDPEngine(tiny_model(), optimizer=optax.adam(1e-3), mesh=mesh8)
    state = eng.init_state(jax.random.key(0), train.x[:8])
    per_dev, total = eng.state_bytes_per_device(state)
    n = eng.n_devices
    # the MLP's kernels ((64,32)/(32,4) at hidden=32... use real fractions):
    # everything with an 8-divisible dim shards; allow the small replicated
    # residue but require most bytes gone from each device
    assert per_dev < total / n * 2.0, (per_dev, total)
    assert per_dev < total * 0.3, (per_dev, total)

    # the update must PRESERVE the layout step over step (out_shardings pin)
    xs, ys = eng.shard_batch(train.x[:64], train.y[:64])
    new_state, _ = eng.step(state, xs, ys)
    expected = jax.tree.leaves(eng._state_shardings)
    actual = jax.tree.leaves(jax.tree.map(lambda l: l.sharding, new_state))
    for before, after in zip(expected, actual):
        assert before == after


def test_fsdp_converges_and_cli_selects(mesh8, data):
    """End-to-end: -m d -ds fsdp maps to the engine; training converges on
    the tiny task through the standard Trainer."""
    from distributed_tensorflow_tpu.cli import build_parser, select_engine

    args = build_parser().parse_args(["-m", "d", "-ds", "fsdp"])
    assert select_engine(args) == "fsdp"

    train, test = data
    eng = create_engine("fsdp", tiny_model(), mesh=mesh8, learning_rate=5e-3)
    tr = Trainer(None, engine=eng, seed=0)
    tr.fit(train, epochs=6, batch_size=64, log_every=0)
    acc = tr.evaluate(test)["accuracy"]
    assert acc > 0.9, f"fsdp reached only {acc}"


def test_fsdp_works_with_annotated_model(mesh8):
    """A model carrying with_partitioning boxes (the TP MLP) must still
    init/step under FSDP — the boxes are unboxed and the shape rule wins."""
    from distributed_tensorflow_tpu.engines import TPMLP

    eng = FSDPEngine(TPMLP(num_classes=4, hidden=64), mesh=mesh8)
    x = np.random.default_rng(0).random((16, 8, 8, 1), np.float32)
    y = (np.arange(16) % 4).astype(np.int32)
    state = eng.init_state(jax.random.key(0), x)
    xs, ys = eng.shard_batch(x, y)
    state, m = eng.step(state, xs, ys)
    assert np.isfinite(float(m["loss"]))


# ----------------------------------------------------------- fsdp x tp


def _tp_bert(partition_model=True):
    return create_model("bert_tiny", num_classes=2, vocab_size=64, hidden=32,
                        layers=1, heads=2, ffn=64, max_len=16,
                        dropout_rate=0.0, partition_model=partition_model)


def _fsdp_tp_mesh():
    return meshlib.create_mesh(
        8, shape=(4, 2), axis_names=(meshlib.DATA_AXIS, meshlib.MODEL_AXIS))


def _bert_tokens(n=8, seed=5):
    rnd = np.random.default_rng(seed)
    return (rnd.integers(1, 64, (n, 16)).astype(np.int32),
            (np.arange(n) % 2).astype(np.int32))


@pytest.mark.slow
def test_fsdp_tp_matches_sync_math():
    """fsdp×tp on a ('data','model') mesh must train identically to plain
    sync DP of the same (unannotated) model: the Megatron annotations and
    the data-dim storage sharding change layout, never math (SGD, so any
    wrong grad scale or dropped collective fails loudly)."""
    x, y = _bert_tokens()

    sync = SyncEngine(_tp_bert(partition_model=False),
                      optimizer=optax.sgd(0.5), mesh=meshlib.create_mesh(8))
    fsdp = FSDPEngine(_tp_bert(partition_model=True),
                      optimizer=optax.sgd(0.5), mesh=_fsdp_tp_mesh())
    results = {}
    for name, eng in (("sync", sync), ("fsdp_tp", fsdp)):
        state = eng.init_state(jax.random.key(0), x)
        for _ in range(3):
            state, m = eng.step(state, *eng.shard_batch(x, y))
        results[name] = (jax.device_get(eng.eval_params(state)),
                         float(m["loss"]))
    assert abs(results["sync"][1] - results["fsdp_tp"][1]) < 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5, rtol=1e-4),
        results["sync"][0], results["fsdp_tp"][0])


@pytest.mark.slow
def test_fsdp_tp_state_sharded_over_both_axes():
    """Per-device state bytes under fsdp×tp must undercut even a perfect
    1/dp data-only sharding: the model dims shard too."""
    x, y = _bert_tokens()
    eng = FSDPEngine(_tp_bert(), mesh=_fsdp_tp_mesh())
    state = eng.init_state(jax.random.key(1), x)
    per_dev, total = eng.state_bytes_per_device(state)
    assert per_dev < total / 4, (per_dev, total)


@pytest.mark.slow
def test_fsdp_grad_accum_matches_k1():
    """K-microbatch accumulation under FSDP: identical SGD math to K=1."""
    x, y = _bert_tokens(n=16)
    outs = []
    for K in (1, 4):
        eng = FSDPEngine(_tp_bert(partition_model=False),
                         optimizer=optax.sgd(0.5),
                         mesh=meshlib.create_mesh(8), grad_accum=K)
        state = eng.init_state(jax.random.key(2), x)
        state, m = eng.step(state, *eng.shard_batch(x, y))
        outs.append((float(m["loss"]),
                     jax.device_get(eng.eval_params(state))))
    assert abs(outs[0][0] - outs[1][0]) < 1e-6
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5, rtol=1e-4),
        outs[0][1], outs[1][1])


@pytest.mark.slow
def test_fsdp_tp_harness_run():
    from distributed_tensorflow_tpu.utils.harness import (
        ExperimentConfig, run)

    summary = run(ExperimentConfig(
        engine="fsdp", model="bert_tiny", dataset="glue_synth", n_devices=8,
        tensor_parallel=2, grad_accum=2, batch_size=4, epochs=1, log_every=0,
        model_args={"hidden": 32, "layers": 1, "heads": 2, "ffn": 64,
                    "vocab_size": 1024, "max_len": 128}))
    assert summary["engine"] == "fsdp_tp[fsdp*tp]"
    assert np.isfinite(summary["test_loss"])
